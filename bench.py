"""Headline benchmark: end-to-end FCMA voxel selection throughput on TPU.

Prints ONE JSON line:
    {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}

The metric is the BASELINE.json north star "FCMA voxels/sec/chip": complete
FCMA stage-1 voxel selection — per-epoch full-brain correlation, Fisher-z
within-subject normalization, per-voxel SVM Gram matrices, and stratified
k-fold kernel-SVM cross validation for every voxel — via
``brainiak_tpu.fcma.voxelselector.VoxelSelector.run('svm')``.

``vs_baseline`` is the speedup over the reference's compute path re-created
on this host's CPU (NumPy/BLAS correlation + normalization + Gram, sklearn
SVC precomputed-kernel CV per voxel), measured on a subset and scaled
per-voxel.

Wall-clock timing of ``run()`` is sound here because results are fetched to
host (which synchronizes) — unlike ``block_until_ready``, which is a no-op
on this tunneled TPU platform.

Tiers (the JSON line's ``tier`` field reports which one ran): on a
responsive chip the north-star whole-brain config is attempted first
(V=65536 correlation width, E=32 — the BASELINE.json scale), then the
V=8192 mid config, then a reduced CPU fallback.  Each chip tier runs in
its own subprocess under a timeout so a tunnel wedge mid-tier cannot
hang the driver's bench invocation.  Further tiers print their
own JSON lines after the FCMA record: ``serve`` (batched
SRM-transform serving), ``service`` (always-on continuous batching,
``brainiak_tpu.serve.service`` — steady-state requests/s AND p99
latency AND padding waste over two resident models, the latter two
stamped ``direction="lower_is_better"`` so ``obs regress`` fails a
doubled p99 the right way round), ``distla`` (pod-scale
SUMMA-sharded Gram, ``brainiak_tpu.ops.distla`` — voxels/s of a
[T, V] -> [V, V] correlation with the voxel axis ring-sharded), and
``encoding`` (voxel-wise ridge CV throughput,
``brainiak_tpu.encoding`` — voxels×lambdas/s of a full RidgeEncoder
fit), ``kernels`` (the roofline-guided fused kernels —
single-scan HMM forward-backward TRs/s and fused SUMMA ring step
GB/s, each record's ``vs_baseline`` being the measured fusion win
over the unfused reference on the same backend), and ``streaming``
(out-of-core subject-sharded SRM over an on-disk SubjectStore,
``brainiak_tpu.data`` — streamed subjects/s AND the prefetch stall
ratio, the latter ``direction="lower_is_better"`` so a collapsed
disk/compute overlap fails CI the right way round), and
``realtime`` (the closed-loop per-TR tier, ``brainiak_tpu.realtime``
— a full seeded fmrisim scan through ``RealtimeSession`` with online
ISC + incremental event segmentation + a warm low-latency
ServeService hop; per-TR p99 latency AND deadline-miss ratio, BOTH
``lower_is_better``: the first latency-bound tier), each split into
an on-chip and a ``*_cpu_fallback`` tier so ``obs regress`` never
compares host rounds against on-chip baselines.

Stage breakdown: every tier runs with :mod:`brainiak_tpu.obs` enabled
on an in-memory sink — ``bench.data_gen`` / ``bench.warm`` (upload +
compile) / ``bench.steady`` spans — and the JSON line carries the
aggregate as ``"stages": {"data_gen_s", "warm_s", "steady_s"}``, so
``BENCH_*.json`` attributes time instead of reporting one opaque
number.  The record shape is validated by
``brainiak_tpu.obs.validate_bench_record`` (tested in
``tests/obs/test_bench_schema.py``; drift fails CI).
"""

import json
import math
import time

import numpy as np

from brainiak_tpu import obs
from brainiak_tpu.obs.report import BENCH_SCHEMA_VERSION
from brainiak_tpu.obs.report import BENCH_STAGE_KEYS as STAGE_KEYS

N_VOXELS = 8192
N_TRS = 150
N_EPOCHS = 16
EPOCHS_PER_SUBJ = 4
NUM_FOLDS = 4

# North-star scale (BASELINE.json: whole-brain FCMA): full MNI-brain
# correlation width at E>=32.  The rate is measured on a 1024-voxel
# selection slice against the full width (the two-mask API) — each
# selected voxel costs exactly the whole-brain per-voxel work, so the
# steady-state voxels/sec is the whole-brain rate without waiting for
# all 64k voxels (~2.5 h on this chip; reference regime
# /root/reference/src/brainiak/fcma/voxelselector.py:89-238).
WB_VOXELS = 65536
WB_SELECTED = 1024
WB_EPOCHS = 32
SERVE_REQUESTS = 256  # serve-tier workload (BENCH_SERVE_REQUESTS overrides)
# service tier (always-on continuous batching): mixed SRM-transform +
# ridge_encoding-scoring requests against two resident models.
# BENCH_SERVICE_REQUESTS overrides.
SERVICE_REQUESTS = 128
# federation tier (pod-scale serving federation,
# brainiak_tpu.serve.federation): heavy-tailed fmrisim traffic
# routed across two warm replicas, then replayed at 2x measured
# capacity against bounded admission control — gated on routed
# requests/s, accepted-request p99 under overload (lower is
# better), and the shed ratio.  BENCH_FEDERATION_REQUESTS overrides.
FEDERATION_REQUESTS = 128

# elastic tier (fault-tolerant fleet, brainiak_tpu.serve.federation
# .fleet): the deterministic chaos soak — heavy-tailed traffic
# triples mid-run while a replica is stalled and killed under
# injected faults; the supervisor fails its work over and scales
# the fleet up off the shared AOT cache.  Gated on soak requests/s
# (``vs_baseline`` = the same mix on a STATIC 2-replica fleet, no
# faults — the price of surviving the chaos), post-failure p99
# (lower is better), and the lost-ticket count (lower is better —
# the committed fixtures hold it at ZERO, so any regression from
# "every ticket resolves" fails ``obs regress --only elastic``
# outright).  BENCH_ELASTIC_REQUESTS overrides.
ELASTIC_REQUESTS = 96

# distla tier (pod-scale SUMMA Gram, brainiak_tpu.ops.distla): the
# on-chip workload is a [T, V] -> [V, V] sharded correlation at a
# width whose replicated working set is already uncomfortable per
# device; the CPU fallback runs a reduced width so the round still
# records a number.  BENCH_DISTLA_VOXELS overrides either.
DISTLA_VOXELS = 16384
DISTLA_CPU_VOXELS = 2048

# kernels tier (roofline-guided fused kernels): fused-vs-unfused
# throughput of the single-scan HMM forward-backward (TRs/s) and the
# fused SUMMA ring step (GB/s of Gram bytes produced+consumed) — the
# vs_baseline of each record IS the fusion win, measured on the same
# backend in the same process.  BENCH_KERNELS_TRS /
# BENCH_KERNELS_VOXELS override the workload sizes.
KERNELS_FB_TRS = 512
KERNELS_FB_EVENTS = 32
KERNELS_FB_REPS = 25
KERNELS_RING_VOXELS = 8192
KERNELS_RING_CPU_VOXELS = 2048

# encoding tier (voxel-wise ridge, brainiak_tpu.encoding): the
# on-chip workload is the paper-scale CV sweep (V=8192 voxels,
# F=512 features, 10 lambdas, 5 folds); the CPU fallback runs a
# reduced problem so the round still records a number in under a
# minute.  BENCH_ENCODING_VOXELS overrides the width on either.
ENCODING_VOXELS = 8192
ENCODING_FEATURES = 512
ENCODING_CPU_VOXELS = 1024
ENCODING_CPU_FEATURES = 64
ENCODING_N_LAMBDAS = 10
ENCODING_FOLDS = 5
ENCODING_TRS = 200

# streaming tier (out-of-core subject-sharded SRM, brainiak_tpu.data):
# a streamed SRM fit over an on-disk SubjectStore at a working set
# deliberately larger than the per-shard budget the streamed path
# holds live (the stack the in-memory path would allocate is the
# stamped config.stack_bytes); subjects/s of the shard rounds plus
# the prefetch STALL ratio (consumer time blocked on the buffer /
# steady wall — 0 means disk+H2D fully overlapped compute; gated
# lower_is_better).  BENCH_STREAMING_SUBJECTS overrides either
# backend's subject count.
STREAMING_SUBJECTS = 64
STREAMING_CPU_SUBJECTS = 24

# realtime tier (closed-loop per-TR streaming, brainiak_tpu.realtime):
# a full simulated scan from the seeded fmrisim real-time source
# driven through RealtimeSession — online z-scoring + OnlineISC +
# incremental event segmentation + a warm low-latency ServeService
# SRM-scoring hop per TR, against a hard 1 s TR budget.  The gated
# numbers are the per-TR p99 latency and the deadline-miss ratio
# (both lower_is_better: this tier is latency-bound, the first such
# workload class — a throughput win that costs tail latency fails CI
# the right way round).  BENCH_REALTIME_TRS overrides the scan
# length.
REALTIME_TRS = 200
REALTIME_DEADLINE_S = 1.0
REALTIME_EVENTS = 12
REALTIME_REFS = 3
STREAMING_VOXELS = 4096
STREAMING_CPU_VOXELS = 1024
STREAMING_TRS = 150
STREAMING_CPU_TRS = 80
STREAMING_FEATURES = 8
STREAMING_ITERS = 2

# stats tier (resampling-statistics engine, brainiak_tpu.stats): a
# chunked NullEngine run of the sign-flip family over an ISC-scale
# [subjects, voxels] input — surrogates/s of the vmapped one-program
# path, with ``vs_baseline`` = the measured win over the pre-engine
# host-loop formulation (one numpy surrogate + statistic per
# resample, the legacy brainiak idiom), timed on the same backend in
# the same process.  BENCH_STATS_RESAMPLES overrides either
# backend's resample count.
STATS_RESAMPLES = 2048
STATS_CPU_RESAMPLES = 512
STATS_SUBJECTS = 16
STATS_VOXELS = 4096
STATS_CPU_VOXELS = 1024
STATS_BASELINE_RESAMPLES = 64

# jobs tier (fit-as-a-service scheduler, brainiak_tpu.jobs): a
# Zipf/Pareto fit workload from the TrafficGenerator's fit mode —
# two tenants, mixed priorities — driven through the Scheduler
# (2 slots, 3-chunk grants) while a warm ServeService answers
# co-scheduled transform waves.  Gated numbers: scheduled jobs/s
# with ``vs_baseline`` = the ratio vs running the same fits
# back-to-back solo (scheduling+parking overhead vs the slot
# parallelism win), the co-scheduled serving p99 (lower_is_better —
# throughput fits must not wreck the latency tier), and jobs_lost
# (lower_is_better, zero baseline: a lost job is a regression at
# any throughput).  BENCH_JOBS_COUNT overrides either backend's job
# count.
JOBS_COUNT = 8
JOBS_CPU_COUNT = 6
JOBS_N_ITER = 6
JOBS_VOXELS = 16
JOBS_SAMPLES = 20
JOBS_MAX_SLOTS = 2
JOBS_GRANT_CHUNKS = 3


def _serve_n_requests():
    """The serve tier's request count: one reader for the env
    override so the measured workload and the stamped
    ``config.n_requests`` cannot drift apart."""
    import os
    return int(os.environ.get("BENCH_SERVE_REQUESTS",
                              SERVE_REQUESTS))


def _service_n_requests():
    """The service tier's request count (``BENCH_SERVICE_REQUESTS``
    overrides) — one reader, same no-drift rule as the other
    tiers."""
    import os
    return int(os.environ.get("BENCH_SERVICE_REQUESTS",
                              SERVICE_REQUESTS))


def _federation_n_requests():
    """The federation tier's request count
    (``BENCH_FEDERATION_REQUESTS`` overrides) — one reader, same
    no-drift rule as the other tiers."""
    import os
    return int(os.environ.get("BENCH_FEDERATION_REQUESTS",
                              FEDERATION_REQUESTS))


def _elastic_n_requests():
    """The elastic tier's request count (``BENCH_ELASTIC_REQUESTS``
    overrides) — one reader, same no-drift rule as the other
    tiers."""
    import os
    return int(os.environ.get("BENCH_ELASTIC_REQUESTS",
                              ELASTIC_REQUESTS))


def _even_epochs_env(name, default):
    """Read an epoch-count env override, rounded UP to even.

    ``make_data`` alternates condition labels 0/1 per epoch, so an odd
    epoch count would build one more data epoch than labels and
    VoxelSelector would see a label/epoch mismatch (ADVICE round 5).
    """
    import os
    import sys
    n = int(os.environ.get(name, default))
    if n % 2:
        print(f"bench: {name}={n} is odd; rounding up to {n + 1} "
              "(labels alternate 0/1 per epoch)", file=sys.stderr)
        n += 1
    return n


def make_data(n_voxels=N_VOXELS, n_trs=N_TRS, n_epochs=N_EPOCHS):
    rng = np.random.RandomState(0)
    data = []
    for _ in range(n_epochs):
        mat = rng.randn(n_trs, n_voxels).astype(np.float32)
        mat = (mat - mat.mean(0)) / (mat.std(0) * math.sqrt(n_trs))
        data.append(mat)
    labels = [0, 1] * (n_epochs // 2)
    return data, labels


def tpu_voxels_per_sec(n_voxels=N_VOXELS, unit=512, warm=True):
    from brainiak_tpu.fcma.voxelselector import VoxelSelector

    with obs.span("bench.data_gen"):
        data, labels = make_data(n_voxels)
        vs = VoxelSelector(labels, EPOCHS_PER_SUBJ, NUM_FOLDS, data,
                           voxel_unit=min(unit, n_voxels))
    if warm:
        with obs.span("bench.warm"):
            vs.run('svm')  # warm compile caches
    t0 = time.perf_counter()
    with obs.span("bench.steady"):
        results = vs.run('svm')
    dt = time.perf_counter() - t0
    assert len(results) == n_voxels
    return n_voxels / dt


def whole_brain_voxels_per_sec(n_voxels=WB_VOXELS, selected=WB_SELECTED,
                               n_epochs=WB_EPOCHS):
    """Steady-state whole-brain-scale selection rate on the accelerator:
    1024 voxels scored against the full 65536-voxel correlation width
    through the production path (``run('svm')``, two-mask form).  The
    warm call pays the one-time upload (device stack is cached across
    runs) and compile; the timed call is compute-only."""
    from brainiak_tpu.fcma.voxelselector import VoxelSelector

    with obs.span("bench.data_gen"):
        data, labels = make_data(n_voxels, n_epochs=n_epochs)
        sel = [m[:, :selected] for m in data]
        vs = VoxelSelector(labels, EPOCHS_PER_SUBJ, NUM_FOLDS, sel,
                           raw_data2=data, voxel_unit=selected)
    with obs.span("bench.warm"):
        vs.run('svm')
    t0 = time.perf_counter()
    with obs.span("bench.steady"):
        results = vs.run('svm')
    dt = time.perf_counter() - t0
    assert len(results) == selected
    return selected / dt


def cpu_voxels_per_sec(n_voxels=N_VOXELS, block=64, n_epochs=N_EPOCHS):
    """Reference-path throughput on host BLAS, at the SAME voxel count as
    the jax path being compared (per-voxel cost scales with the full
    correlation width, so mismatched sizes would skew vs_baseline)."""
    from sklearn import model_selection, svm

    data, labels = make_data(n_voxels, n_epochs=n_epochs)
    stacked = np.stack(data)  # [E, T, V]
    t0 = time.perf_counter()
    blk = stacked[:, :, :block]
    corr = np.stack([blk[e].T @ stacked[e] for e in range(n_epochs)],
                    axis=1)  # [block, E, V]
    num = 1.0 + corr
    den = 1.0 - corr
    num[num <= 0] = 1e-4
    den[den <= 0] = 1e-4
    z = 0.5 * np.log(num / den)
    zr = z.reshape(block, n_epochs // EPOCHS_PER_SUBJ, EPOCHS_PER_SUBJ,
                   n_voxels)
    m = zr.mean(axis=2, keepdims=True)
    var = (zr ** 2).mean(axis=2, keepdims=True) - m ** 2
    inv = np.where(var <= 0, 0.0, 1.0 / np.sqrt(np.maximum(var, 1e-30)))
    normed = ((zr - m) * inv).reshape(block, n_epochs, n_voxels)
    clf = svm.SVC(kernel='precomputed', shrinking=False, C=1)
    skf = model_selection.StratifiedKFold(n_splits=NUM_FOLDS,
                                          shuffle=False)
    for v in range(block):
        k = normed[v] @ normed[v].T
        nd = len(str(int(k[0, 0])))
        if nd > 2:
            k *= 10 ** (2 - nd)
        model_selection.cross_val_score(clf, k, y=labels, cv=skf, n_jobs=1)
    dt = time.perf_counter() - t0
    return block / dt


def _distla_n_voxels():
    """The distla tier's Gram width: the env override, else a default
    scaled to the ambient backend (the reduced CPU width keeps the
    fallback round under a minute) — one reader so the measured
    workload and the stamped ``config.n_voxels`` cannot drift."""
    import os

    import jax
    default = DISTLA_VOXELS if jax.default_backend() == "tpu" \
        else DISTLA_CPU_VOXELS
    return int(os.environ.get("BENCH_DISTLA_VOXELS", default))


def distla_tier_metrics(n_voxels, n_trs=N_TRS, seed=0):
    """The ``distla`` tier: SUMMA-sharded whole-Gram throughput
    (voxels/s of [T, V] -> [V, V] Pearson correlation) through
    :func:`brainiak_tpu.ops.distla.summa_gram`, ring over every
    device the backend exposes.  The warm call pays placement and
    compile; the timed call is the steady-state ring."""
    import jax

    from brainiak_tpu.ops import distla
    from brainiak_tpu.parallel import make_mesh, max_divisible_shards

    with obs.span("bench.data_gen"):
        rng = np.random.RandomState(seed)
        data = rng.randn(n_trs, n_voxels).astype(np.float32)
        n_shards = max_divisible_shards(n_voxels)
        mesh = make_mesh(("voxel",), (n_shards,))
    with obs.span("bench.warm"):
        np.asarray(distla.summa_gram(data, mesh))
    t0 = time.perf_counter()
    with obs.span("bench.steady"):
        out = np.asarray(distla.summa_gram(data, mesh))
    dt = time.perf_counter() - t0
    assert out.shape == (n_voxels, n_voxels)
    return {"voxels_per_sec": n_voxels / dt,
            "n_voxels": n_voxels, "n_trs": n_trs,
            "n_shards": n_shards,
            "backend": jax.default_backend()}


def distla_cpu_voxels_per_sec(n_voxels, n_trs=N_TRS, seed=0):
    """Reference-path Gram throughput on host BLAS at the SAME width
    as the sharded run (z-score + ``z.T @ z``), for the distla
    record's ``vs_baseline``."""
    rng = np.random.RandomState(seed)
    data = rng.randn(n_trs, n_voxels).astype(np.float32)
    t0 = time.perf_counter()
    z = (data - data.mean(0)) / (data.std(0) * math.sqrt(n_trs))
    out = z.T @ z
    dt = time.perf_counter() - t0
    assert out.shape == (n_voxels, n_voxels)
    return n_voxels / dt


def _distla_result_record(out):
    """The distla tier's bench JSON line (schema:
    ``brainiak_tpu.obs.validate_bench_record``).  Tier separation
    mirrors the FCMA/serve tiers: a run whose backend is not a TPU
    is stamped ``tier="distla_cpu_fallback"`` so ``obs regress``
    never compares a host round against an on-chip SUMMA baseline
    (and ``obs regress --only distla`` gates both as one family)."""
    vps = float(out["voxels_per_sec"])
    baseline = distla_cpu_voxels_per_sec(out["n_voxels"],
                                         n_trs=out["n_trs"])
    tier = "distla" if out.get("backend") == "tpu" \
        else "distla_cpu_fallback"
    rec = {"schema_version": BENCH_SCHEMA_VERSION,
           "metric": "distla_summa_gram_voxels_per_sec",
           "value": round(vps, 2),
           "unit": "voxels/sec",
           "vs_baseline": round(vps / baseline, 2),
           "tier": tier,
           "config": {"n_voxels": out["n_voxels"],
                      "n_trs": out["n_trs"],
                      "n_shards": out["n_shards"]}}
    commit = _git_commit()
    if commit:
        rec["git_commit"] = commit
    if out.get("stages"):
        rec["stages"] = out["stages"]
    return rec


def _streaming_shape():
    """The streaming tier's workload: env override for the subject
    count, backend-scaled defaults for the rest (the reduced CPU
    sizes keep the fallback round under a minute) — one reader so
    the measured workload and the stamped config cannot drift."""
    import os

    import jax
    tpu = jax.default_backend() == "tpu"
    n_subjects = int(os.environ.get(
        "BENCH_STREAMING_SUBJECTS",
        STREAMING_SUBJECTS if tpu else STREAMING_CPU_SUBJECTS))
    if tpu:
        return n_subjects, STREAMING_VOXELS, STREAMING_TRS
    return n_subjects, STREAMING_CPU_VOXELS, STREAMING_CPU_TRS


def streaming_tier_metrics(n_subjects, n_voxels, n_trs, seed=0):
    """The ``streaming`` tier: out-of-core SRM fit throughput over a
    real on-disk :class:`~brainiak_tpu.data.store.SubjectStore`
    (``brainiak_tpu.data``) — subjects/s of the streamed shard
    rounds (``n_subjects × n_iter / steady wall``), never holding
    the stacked ``[S, V, T]`` tensor.  The second gated metric is
    the prefetch stall ratio: consumer seconds blocked on the
    double buffer over the steady wall (0 = the background loader
    fully overlapped disk + H2D with compute).  The in-memory
    stacked fit of the SAME data at the SAME iteration schedule is
    the ``vs_baseline`` comparator."""
    import os
    import shutil
    import tempfile

    import jax

    from brainiak_tpu.data import write_store
    from brainiak_tpu.funcalign.srm import SRM

    shard = max(2, n_subjects // 8)
    with obs.span("bench.data_gen"):
        rng = np.random.RandomState(seed)
        shared = rng.randn(STREAMING_FEATURES, n_trs)
        subjects = []
        for _ in range(n_subjects):
            w = np.linalg.qr(
                rng.randn(n_voxels, STREAMING_FEATURES))[0]
            subjects.append(
                (w @ shared
                 + 0.1 * rng.randn(n_voxels, n_trs)).astype(
                     np.float32))
        tmp = tempfile.mkdtemp(prefix="bench_streaming_")
        store = write_store(os.path.join(tmp, "store"), subjects,
                            dtype=np.float32)
    # register with the SAME unit/help the prefetcher uses: the
    # get-or-create registry keeps the first registration, and this
    # call site can run before any ShardPrefetcher exists
    stall_counter = obs.counter(
        "data_prefetch_stall_seconds_total", unit="s",
        help="consumer time spent waiting on the prefetch buffer")
    try:
        with obs.span("bench.warm"):
            SRM(n_iter=1, features=STREAMING_FEATURES,
                shard_subjects=shard).fit(store)
        stall0 = float(stall_counter.value() or 0.0)
        t0 = time.perf_counter()
        with obs.span("bench.steady"):
            SRM(n_iter=STREAMING_ITERS, features=STREAMING_FEATURES,
                shard_subjects=shard).fit(store)
        dt = time.perf_counter() - t0
        stall = float(stall_counter.value() or 0.0) - stall0
        # warm the stacked program first (n_iter is a static arg, so
        # the warm fit must use the measured schedule) — the streamed
        # side was warmed above, and a cold XLA compile in the
        # baseline would flatter the streamed rate
        SRM(n_iter=STREAMING_ITERS,
            features=STREAMING_FEATURES).fit(subjects)
        t1 = time.perf_counter()
        SRM(n_iter=STREAMING_ITERS,
            features=STREAMING_FEATURES).fit(subjects)
        baseline_dt = time.perf_counter() - t1
    finally:
        shutil.rmtree(tmp, ignore_errors=True)
    visits = n_subjects * STREAMING_ITERS
    return {"subjects_per_sec": visits / dt,
            "inmem_subjects_per_sec": visits / baseline_dt,
            "stall_ratio": stall / dt,
            "n_subjects": n_subjects, "n_voxels": n_voxels,
            "n_trs": n_trs, "shard_subjects": shard,
            "stack_bytes": store.stack_nbytes,
            "backend": jax.default_backend()}


def _streaming_result_records(out):
    """The streaming tier's bench JSON lines — TWO records per
    round: streamed subjects/s (``vs_baseline`` = streamed rate over
    the in-memory stacked fit's rate on the same data) and the
    prefetch stall ratio, stamped ``direction="lower_is_better"`` so
    ``obs regress`` fails a collapsed overlap the right way round.
    Tier split mirrors every other tier (``streaming`` on TPU,
    ``streaming_cpu_fallback`` otherwise)."""
    tier = "streaming" if out.get("backend") == "tpu" \
        else "streaming_cpu_fallback"
    config = {"n_subjects": out["n_subjects"],
              "n_voxels": out["n_voxels"],
              "n_trs": out["n_trs"],
              "shard_subjects": out["shard_subjects"],
              "stack_bytes": out["stack_bytes"]}
    commit = _git_commit()

    def rec(metric, value, unit, vs, direction=None, stages=None):
        r = {"schema_version": BENCH_SCHEMA_VERSION,
             "metric": metric, "value": round(value, 4),
             "unit": unit, "vs_baseline": round(vs, 3),
             "tier": tier, "config": config}
        if direction:
            r["direction"] = direction
        if commit:
            r["git_commit"] = commit
        if stages:
            r["stages"] = stages
        return r

    return [
        rec("streaming_srm_subjects_per_sec",
            float(out["subjects_per_sec"]), "subjects/sec",
            float(out["subjects_per_sec"])
            / max(float(out["inmem_subjects_per_sec"]), 1e-9),
            stages=out.get("stages")),
        rec("streaming_prefetch_stall_ratio",
            float(out["stall_ratio"]), "ratio", 0.0,
            direction="lower_is_better"),
    ]


def _realtime_n_trs():
    """The realtime tier's scan length (``BENCH_REALTIME_TRS``
    overrides) — one reader, same no-drift rule as the other
    tiers."""
    import os
    return int(os.environ.get("BENCH_REALTIME_TRS", REALTIME_TRS))


def realtime_tier_metrics(n_trs, seed=0):
    """The ``realtime`` tier: a full closed-loop scan off the seeded
    fmrisim real-time source through
    :class:`brainiak_tpu.realtime.RealtimeSession` — per TR: online
    z-scoring, cumulative OnlineISC against a 3-subject reference
    group, the forward-only incremental event segmentation, and a
    warm SRM scoring hop through ``ServeService.submit(...,
    low_latency=True)`` — under the hard ``REALTIME_DEADLINE_S``
    per-TR budget.  A short warm scan pays every compile first, so
    the measured scan is the steady state the deadline SLO is about
    (and runs at zero retraces — asserted, not assumed)."""
    import jax

    from brainiak_tpu.realtime import (IncrementalEventSegment,
                                       MemoryFeed, OnlineISC,
                                       OnlineZScore,
                                       RealtimeSession)
    from brainiak_tpu.eventseg.event import EventSegment
    from brainiak_tpu.serve import BucketPolicy, ModelResidency
    from brainiak_tpu.serve.__main__ import build_demo_model
    from brainiak_tpu.serve.service import ServeService
    from brainiak_tpu.utils.fmrisim_real_time_generator import \
        generate_stream

    with obs.span("bench.data_gen"):
        rng = np.random.RandomState(seed)
        stream = generate_stream({"numTRs": n_trs}, rng=seed)
        # mask-flattened [T, V] rows via the library's own ingest
        # path (one flattening convention, not a bench re-implementation)
        rows = MemoryFeed(stream).rows.astype(np.float32)
        n_voxels = rows.shape[1]
        refs = rng.randn(n_trs, n_voxels,
                         REALTIME_REFS).astype(np.float32)
        seg_model = EventSegment(n_events=REALTIME_EVENTS)
        seg_model.set_event_patterns(
            rng.randn(n_voxels, REALTIME_EVENTS))
        srm = build_demo_model(n_subjects=2, voxels=n_voxels,
                               samples=48, features=8, n_iter=2,
                               seed=seed)
        residency = ModelResidency(
            budget_bytes=1 << 30,
            policy=BucketPolicy(max_batch=16, max_wait_s=2.0))
        residency.register("m", model=srm)

    def run_scan(trs):
        session = RealtimeSession(
            MemoryFeed(rows[:trs]),
            {"isc": OnlineISC(refs[:trs], dtype=np.float32),
             "evseg": IncrementalEventSegment(
                 seg_model, n_trs=trs, var=4.0,
                 dtype=np.float32)},
            preprocess=OnlineZScore(n_voxels, dtype=np.float32),
            deadline_s=REALTIME_DEADLINE_S, service=service,
            service_model="m", name="bench-realtime")
        return session.run()

    with ServeService(residency, default_model="m") as service:
        with obs.span("bench.warm"):
            # pays every compile; the event chain needs T > K-1
            run_scan(min(n_trs, 2 * REALTIME_EVENTS))
        with obs.span("bench.steady"):
            summary = run_scan(n_trs)
    retraces = summary["retraces"]
    if any(count > 1.0 for count in retraces.values()):
        raise RuntimeError(
            "realtime bench scan rebuilt step programs "
            f"({retraces}); refusing to emit a latency number for "
            "a retracing loop")
    return {"p99_latency_s": summary["p99_latency_s"],
            "miss_ratio": summary["deadline_miss_ratio"],
            "n_misses": summary["n_deadline_misses"],
            "n_trs": summary["n_trs"],
            "n_voxels": n_voxels,
            "deadline_s": REALTIME_DEADLINE_S,
            "backend": jax.default_backend()}


def _realtime_result_records(out):
    """The realtime tier's bench JSON lines — two records, BOTH
    ``direction="lower_is_better"`` (the tier is latency-bound):
    per-TR p99 latency and the deadline-miss ratio.  Tier split
    mirrors every other tier (``realtime`` on TPU,
    ``realtime_cpu_fallback`` otherwise)."""
    tier = "realtime" if out.get("backend") == "tpu" \
        else "realtime_cpu_fallback"
    config = {"n_trs": out["n_trs"],
              "n_voxels": out["n_voxels"],
              "deadline_s": out["deadline_s"],
              "n_refs": REALTIME_REFS,
              "n_events": REALTIME_EVENTS,
              "backend": out.get("backend")}
    commit = _git_commit()

    def rec(metric, value, unit, stages=None):
        r = {"schema_version": BENCH_SCHEMA_VERSION,
             "metric": metric, "value": round(float(value), 6),
             "unit": unit, "vs_baseline": 0.0, "tier": tier,
             "config": config, "direction": "lower_is_better"}
        if commit:
            r["git_commit"] = commit
        if stages:
            r["stages"] = stages
        return r

    return [
        rec("realtime_tr_p99_latency_seconds",
            out["p99_latency_s"], "s", stages=out.get("stages")),
        rec("realtime_deadline_miss_ratio", out["miss_ratio"],
            "ratio"),
    ]


def _stats_shape():
    """The stats tier's workload (env override, else backend-scaled
    defaults) — one reader so the measured workload and the stamped
    config cannot drift."""
    import os

    import jax
    on_tpu = jax.default_backend() == "tpu"
    n_resamples = int(os.environ.get(
        "BENCH_STATS_RESAMPLES",
        STATS_RESAMPLES if on_tpu else STATS_CPU_RESAMPLES))
    n_voxels = STATS_VOXELS if on_tpu else STATS_CPU_VOXELS
    return n_resamples, n_voxels


def stats_tier_metrics(n_resamples, n_voxels, seed=0):
    """The ``stats`` tier: resampling-null throughput of the
    :class:`brainiak_tpu.stats.engine.NullEngine` sign-flip family
    over an ISC-scale ``[subjects, voxels]`` input, on whatever
    backend is ambient.

    A short warm run pays the (single) surrogate-program compile, so
    the measured run is the steady chunked state.  The baseline is
    the pre-engine host-loop formulation — one numpy sign-flip
    surrogate + median statistic per resample, the legacy brainiak
    ``permutation_isc`` inner loop — capped at
    ``STATS_BASELINE_RESAMPLES`` iterations (the rate extrapolates;
    a full host run at the engine's resample count would dominate
    the bench round)."""
    import jax

    from brainiak_tpu.stats.engine import NullEngine

    with obs.span("bench.data_gen"):
        rng = np.random.RandomState(seed)
        iscs = 0.2 + 0.1 * rng.randn(STATS_SUBJECTS, n_voxels)
    engine = NullEngine()
    run_kwargs = dict(statistic="median", side="two-sided",
                      seed=seed)
    with obs.span("bench.warm"):
        engine.run(iscs, "sign_flip", 64, **run_kwargs)
    with obs.span("bench.steady"):
        t0 = time.perf_counter()
        result = engine.run(iscs, "sign_flip", n_resamples,
                            **run_kwargs)
        rate = n_resamples / (time.perf_counter() - t0)
    p = result.p_values()
    assert np.all((p > 0.0) & (p <= 1.0))
    with obs.span("bench.baseline"):
        reps = min(n_resamples, STATS_BASELINE_RESAMPLES)
        host_rng = np.random.RandomState(seed)
        t0 = time.perf_counter()
        for _ in range(reps):
            signs = host_rng.choice((-1.0, 1.0),
                                    size=(iscs.shape[0], 1))
            np.median(signs * iscs, axis=0)
        host_rate = reps / (time.perf_counter() - t0)
    return {"surrogates_per_sec": rate,
            "host_surrogates_per_sec": host_rate,
            "n_resamples": n_resamples,
            "n_subjects": STATS_SUBJECTS, "n_voxels": n_voxels,
            "backend": jax.default_backend()}


def _stats_result_record(out):
    """The stats tier's bench JSON line: engine surrogates/s, with
    ``vs_baseline`` = the measured win over the host-loop
    formulation on the same backend.  Tier split mirrors every
    other tier (``stats`` on TPU, ``stats_cpu_fallback`` otherwise)
    so ``obs regress --only stats`` never compares host rounds
    against on-chip ones."""
    tier = "stats" if out.get("backend") == "tpu" \
        else "stats_cpu_fallback"
    host = out.get("host_surrogates_per_sec") or 0.0
    rec = {"schema_version": BENCH_SCHEMA_VERSION,
           "metric": "stats_surrogates_per_sec",
           "value": round(float(out["surrogates_per_sec"]), 3),
           "unit": "surrogates/sec",
           "vs_baseline": round(out["surrogates_per_sec"] / host, 3)
           if host else 0.0,
           "tier": tier,
           "config": {"n_resamples": out["n_resamples"],
                      "n_subjects": out["n_subjects"],
                      "n_voxels": out["n_voxels"],
                      "family": "sign_flip",
                      "backend": out.get("backend")}}
    commit = _git_commit()
    if commit:
        rec["git_commit"] = commit
    if out.get("stages"):
        rec["stages"] = out["stages"]
    return rec


def _jobs_count():
    """The jobs tier's job count (``BENCH_JOBS_COUNT`` overrides) —
    one reader, same no-drift rule as the other tiers."""
    import os

    import jax
    on_tpu = jax.default_backend() == "tpu"
    return int(os.environ.get(
        "BENCH_JOBS_COUNT",
        JOBS_COUNT if on_tpu else JOBS_CPU_COUNT))


def jobs_tier_metrics(n_jobs, seed=0):
    """The ``jobs`` tier: a two-tenant mixed-priority fit workload
    (the :class:`~brainiak_tpu.serve.federation.traffic.
    TrafficGenerator` fit mode — Zipf tenant mix, the same stream
    the soak test replays) through one
    :class:`~brainiak_tpu.jobs.scheduler.Scheduler`, co-scheduled
    with a warm :class:`~brainiak_tpu.serve.service.ServeService`
    answering fixed-shape transform waves the whole time.

    The solo baseline runs the identical specs back-to-back through
    :func:`~brainiak_tpu.jobs.runners.run_job` (no scheduler, no
    parking) — ``vs_baseline`` on the throughput record is the
    scheduled/solo rate ratio."""
    import os
    import shutil
    import tempfile

    import jax

    from brainiak_tpu.jobs.runners import run_job
    from brainiak_tpu.jobs.scheduler import Scheduler
    from brainiak_tpu.serve import BucketPolicy, ModelResidency
    from brainiak_tpu.serve.__main__ import build_demo_model
    from brainiak_tpu.serve.batching import Request
    from brainiak_tpu.serve.federation.traffic import \
        TrafficGenerator
    from brainiak_tpu.serve.service import ServeService

    with obs.span("bench.data_gen"):
        gen = TrafficGenerator(seed=seed)
        specs = gen.fit_jobs(
            n_jobs, tenants=("hospital-a", "hospital-b"),
            kinds=("srm",), priorities=(0, 1),
            n_iter=JOBS_N_ITER, features=3,
            voxels=JOBS_VOXELS, samples=JOBS_SAMPLES)
        srm = build_demo_model(n_subjects=2, voxels=32, samples=32,
                               features=4, n_iter=2, seed=seed)
        counts = [w.shape[0] for w in srm.w_]
        residency = ModelResidency(
            budget_bytes=1 << 30,
            policy=BucketPolicy(max_batch=8, max_wait_s=0.05))
        residency.register("m", model=srm)
        rng = np.random.RandomState(seed)
        payloads = [rng.randn(counts[i % 2], 16).astype(np.float32)
                    for i in range(4)]

    latencies = []
    tmp = tempfile.mkdtemp(prefix="bench-jobs-")
    try:
        with ServeService(residency, default_model="m") as service:

            def wave(prefix):
                reqs = [Request(request_id=f"{prefix}-{i}",
                                x=payloads[i], subject=i % 2,
                                model="m")
                        for i in range(len(payloads))]
                for ticket in service.submit_many(reqs):
                    rec = ticket.result(timeout=60.0)
                    if rec.ok and rec.latency_s is not None:
                        latencies.append(rec.latency_s)

            with obs.span("bench.warm"):
                # pays every compile: the serving buckets AND the
                # fit programs (an unmeasured solo pass), then times
                # the WARM solo baseline — the vs_baseline ratio
                # compares steady state to steady state, not a
                # compile-paying run to a warm one
                wave("warm")
                for spec in specs:
                    run_job(spec, os.path.join(tmp, "solo-warm"))
                t0 = time.perf_counter()
                for spec in specs:
                    run_job(spec, os.path.join(tmp, "solo"))
                solo_rate = n_jobs / (time.perf_counter() - t0)
            latencies.clear()  # warm latencies are not the number

            with obs.span("bench.steady"):
                sched = Scheduler(
                    os.path.join(tmp, "jobs"),
                    max_slots=JOBS_MAX_SLOTS,
                    grant_chunks=JOBS_GRANT_CHUNKS,
                    serve_pressure_depth=1 << 20,
                    tick_interval_s=0.01)
                try:
                    t0 = time.perf_counter()
                    tickets = sched.submit_many(specs)
                    k = 0
                    while not all(t.done() for t in tickets):
                        wave(f"co{k}")
                        k += 1
                        time.sleep(0.01)
                    records = [t.result(timeout=600.0)
                               for t in tickets]
                    sched_rate = n_jobs \
                        / (time.perf_counter() - t0)
                finally:
                    sched.close()
        lost = [r["job_id"] for r in records
                if r["state"] != "done"]
        p99 = float(np.percentile(latencies, 99)) \
            if latencies else 0.0
    finally:
        shutil.rmtree(tmp, ignore_errors=True)
    return {"jobs_per_sec": sched_rate,
            "solo_jobs_per_sec": solo_rate,
            "coserve_p99_s": p99,
            "n_serve_requests": len(latencies),
            "jobs_lost": len(lost), "lost": lost,
            "n_jobs": n_jobs, "n_iter": JOBS_N_ITER,
            "backend": jax.default_backend()}


def _jobs_result_records(out):
    """The jobs tier's bench JSON lines — three records: scheduled
    jobs/s (``vs_baseline`` = the scheduled/solo rate ratio),
    co-scheduled serving p99 (``lower_is_better``), and jobs_lost
    (``lower_is_better``, zero baseline).  Tier split mirrors every
    other tier (``jobs`` on TPU, ``jobs_cpu_fallback``
    otherwise)."""
    tier = "jobs" if out.get("backend") == "tpu" \
        else "jobs_cpu_fallback"
    config = {"n_jobs": out["n_jobs"], "n_iter": out["n_iter"],
              "n_tenants": 2, "kinds": ["srm"],
              "max_slots": JOBS_MAX_SLOTS,
              "grant_chunks": JOBS_GRANT_CHUNKS,
              "backend": out.get("backend")}
    commit = _git_commit()

    def rec(metric, value, unit, vs=0.0, direction=None,
            stages=None):
        r = {"schema_version": BENCH_SCHEMA_VERSION,
             "metric": metric, "value": round(float(value), 6),
             "unit": unit, "vs_baseline": round(float(vs), 3),
             "tier": tier, "config": config}
        if direction:
            r["direction"] = direction
        if commit:
            r["git_commit"] = commit
        if stages:
            r["stages"] = stages
        return r

    solo = out.get("solo_jobs_per_sec") or 0.0
    return [
        rec("jobs_scheduled_jobs_per_sec", out["jobs_per_sec"],
            "jobs/sec",
            vs=out["jobs_per_sec"] / solo if solo else 0.0,
            stages=out.get("stages")),
        rec("jobs_coserve_p99_latency_seconds",
            out["coserve_p99_s"], "s",
            direction="lower_is_better"),
        rec("jobs_lost", out["jobs_lost"], "jobs",
            direction="lower_is_better"),
    ]


def _kernels_shape():
    """The kernels tier's workload sizes (env overrides, else
    backend-scaled defaults) — one reader so the measured workload
    and the stamped config cannot drift."""
    import os

    import jax
    on_tpu = jax.default_backend() == "tpu"
    n_trs = int(os.environ.get("BENCH_KERNELS_TRS", KERNELS_FB_TRS))
    voxels = int(os.environ.get(
        "BENCH_KERNELS_VOXELS",
        KERNELS_RING_VOXELS if on_tpu else KERNELS_RING_CPU_VOXELS))
    return n_trs, voxels


def kernels_tier_metrics(n_trs, ring_voxels, n_events=KERNELS_FB_EVENTS,
                         reps=KERNELS_FB_REPS, seed=0):
    """The ``kernels`` tier: fused-vs-unfused throughput of two of
    the PR's fused sites, on whatever backend is ambient.

    - eventseg forward-backward TRs/s: the single-scan fused program
      (betas never round-trip HBM) vs the two-scan reference, same
      [T, K] workload, ``reps`` timed dispatches each (every result
      fetched — fetching synchronizes on this platform).
    - SUMMA ring step GB/s: the fused rotate-multiply-accumulate
      ring program vs the unfused stack/transpose/scatter
      formulation, timed as the DEVICE DISPATCH ALONE — operands are
      pre-placed and pre-normalized on the mesh, and a scalar fetch
      synchronizes — so the gated metric tracks the kernel, not the
      host round-trip the two modes share.  Bytes = the [V, V]
      output plus both [T, V] operands at fp32.
    """
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec

    from brainiak_tpu.eventseg import event as ev
    from brainiak_tpu.ops import distla
    from brainiak_tpu.ops.correlation import resolve_precision
    from brainiak_tpu.parallel import make_mesh, max_divisible_shards
    from brainiak_tpu.parallel.mesh import place_on_mesh

    with obs.span("bench.data_gen"):
        rng = np.random.RandomState(seed)
        es = ev.EventSegment(n_events)
        log_P, log_p_start, log_p_end = es._build_transitions(n_trs)
        lp = np.hstack([rng.randn(n_trs, n_events),
                        np.full((n_trs, 1), -np.inf)])
        fb_args = (jnp.asarray(lp), jnp.asarray(log_P),
                   jnp.asarray(log_p_start), jnp.asarray(log_p_end))
        ring_data = rng.randn(N_TRS, ring_voxels).astype(np.float32)
        n_shards = max_divisible_shards(ring_voxels)
        mesh = make_mesh(("voxel",), (n_shards,))
        # place + z-score ONCE; both ring modes time the same
        # device-resident operands
        padded, _ = distla._pad_cols(ring_data, n_shards)
        z = distla._zscore_cols(place_on_mesh(
            padded, NamedSharding(mesh,
                                  PartitionSpec(None, "voxel"))))
        auto_mode = distla._ring_step_for(N_TRS, padded.shape[1],
                                          n_shards)

    def ring_program(mode):
        return distla._summa_program(
            mesh, ("voxel",), resolve_precision(None),
            ring_step=mode)

    def time_fb(program):
        t0 = time.perf_counter()
        for _ in range(reps):
            np.asarray(program(*fb_args)[0])
        return n_trs * reps / (time.perf_counter() - t0)

    def time_ring(mode):
        program = ring_program(mode)
        t0 = time.perf_counter()
        out = program(z, z)
        sync = float(out[0, 0])  # scalar fetch syncs the dispatch
        dt = time.perf_counter() - t0
        assert np.isfinite(sync)
        gbytes = 4.0 * (ring_voxels * ring_voxels
                        + 2 * N_TRS * ring_voxels) / 1e9
        return gbytes / dt

    with obs.span("bench.warm"):  # upload + compile, per program
        for program in (ev._fb_program(), ev._fb_reference_program()):
            np.asarray(program(*fb_args)[0])
        for mode in (auto_mode, "unfused"):
            float(ring_program(mode)(z, z)[0, 0])
    with obs.span("bench.steady"):
        fb_fused = time_fb(ev._fb_program())
        fb_ref = time_fb(ev._fb_reference_program())
        ring_fused = time_ring(auto_mode)
        ring_unfused = time_ring("unfused")
    return {"fb_trs_per_sec": fb_fused,
            "fb_reference_trs_per_sec": fb_ref,
            "ring_gb_per_sec": ring_fused,
            "ring_unfused_gb_per_sec": ring_unfused,
            "n_trs": n_trs, "n_events": n_events, "reps": reps,
            "ring_voxels": ring_voxels, "n_shards": n_shards,
            "backend": jax.default_backend()}


def _kernels_result_records(out):
    """The kernels tier's bench JSON lines — one record per fused
    site, where ``vs_baseline`` is the measured fusion win
    (fused rate / unfused-reference rate on the same backend).
    Tier split mirrors the other tiers (``kernels`` on TPU,
    ``kernels_cpu_fallback`` otherwise) so ``obs regress --only
    kernels`` never compares host rounds against on-chip ones."""
    tier = "kernels" if out.get("backend") == "tpu" \
        else "kernels_cpu_fallback"
    commit = _git_commit()

    def rec(metric, value, baseline, unit, config):
        r = {"schema_version": BENCH_SCHEMA_VERSION,
             "metric": metric, "value": round(float(value), 3),
             "unit": unit,
             "vs_baseline": round(float(value) / baseline, 3)
             if baseline else 0.0,
             "tier": tier, "config": config}
        if commit:
            r["git_commit"] = commit
        if out.get("stages"):
            r["stages"] = out["stages"]
        return r

    return [
        rec("kernels_eventseg_fb_trs_per_sec",
            out["fb_trs_per_sec"], out["fb_reference_trs_per_sec"],
            "TRs/sec",
            {"n_trs": out["n_trs"], "n_events": out["n_events"],
             "reps": out["reps"]}),
        rec("kernels_summa_ring_gb_per_sec",
            out["ring_gb_per_sec"], out["ring_unfused_gb_per_sec"],
            "GB/sec",
            {"n_voxels": out["ring_voxels"], "n_trs": N_TRS,
             "n_shards": out["n_shards"]}),
    ]


def _encoding_shape():
    """The encoding tier's problem size: the env override for the
    voxel width, else backend-scaled defaults — one reader so the
    measured workload and the stamped config cannot drift."""
    import os

    import jax
    on_tpu = jax.default_backend() == "tpu"
    voxels = int(os.environ.get(
        "BENCH_ENCODING_VOXELS",
        ENCODING_VOXELS if on_tpu else ENCODING_CPU_VOXELS))
    features = ENCODING_FEATURES if on_tpu else ENCODING_CPU_FEATURES
    return voxels, features


def _encoding_lambdas():
    return np.logspace(0.0, 3.0, ENCODING_N_LAMBDAS)


def encoding_tier_metrics(n_voxels, n_features, n_trs=ENCODING_TRS,
                          seed=0):
    """The ``encoding`` tier: voxel-wise ridge CV throughput
    (voxels×lambdas/s of a full :class:`RidgeEncoder` fit — Gram,
    batched fold eigendecompositions, the one-program lambda sweep,
    per-voxel selection, refit) on synthetic ``Y = X W + noise``
    data.  The warm fit pays the compiles; the timed fit is the
    steady-state sweep."""
    import jax

    from brainiak_tpu.encoding import RidgeEncoder

    lambdas = _encoding_lambdas()
    with obs.span("bench.data_gen"):
        rng = np.random.RandomState(seed)
        x = rng.randn(n_trs, n_features).astype(np.float32)
        w = rng.randn(n_features, n_voxels).astype(np.float32)
        y = (x @ w + 0.5 * rng.randn(n_trs, n_voxels)).astype(
            np.float32)
    with obs.span("bench.warm"):
        RidgeEncoder(lambdas=lambdas,
                     n_folds=ENCODING_FOLDS).fit(x, y)
    t0 = time.perf_counter()
    with obs.span("bench.steady"):
        enc = RidgeEncoder(lambdas=lambdas,
                           n_folds=ENCODING_FOLDS).fit(x, y)
    dt = time.perf_counter() - t0
    assert enc.W_.shape == (n_features, n_voxels)
    return {"voxels_lambdas_per_sec": n_voxels * len(lambdas) / dt,
            "n_voxels": n_voxels, "n_features": n_features,
            "n_lambdas": len(lambdas), "n_folds": ENCODING_FOLDS,
            "n_trs": n_trs, "backend": jax.default_backend()}


def encoding_cpu_voxels_lambdas_per_sec(n_voxels, n_features,
                                        n_trs=ENCODING_TRS, seed=0):
    """Reference-path encoding throughput on host NumPy/BLAS at the
    SAME problem size: the identical eigendecomposition CV sweep +
    per-voxel refit, for the encoding record's ``vs_baseline``."""
    lambdas = _encoding_lambdas()
    k = ENCODING_FOLDS
    rng = np.random.RandomState(seed)
    x = rng.randn(n_trs, n_features).astype(np.float32)
    w = rng.randn(n_features, n_voxels).astype(np.float32)
    y = (x @ w + 0.5 * rng.randn(n_trs, n_voxels)).astype(np.float32)
    t0 = time.perf_counter()
    xc = x - x.mean(0)
    yc = y - y.mean(0)
    g = xc.T @ xc
    b = xc.T @ yc
    t_f = n_trs // k
    scores = np.zeros((len(lambdas), n_voxels), np.float32)
    for fold in range(k):
        sl = slice(fold * t_f, (fold + 1) * t_f)
        xf, yf = xc[sl], yc[sl]
        ev, q = np.linalg.eigh(g - xf.T @ xf)
        ev = np.maximum(ev, 0.0)
        a = q.T @ (b - xf.T @ yf)
        p = xf @ q
        yf_c = yf - yf.mean(0)
        yf_ss = (yf_c * yf_c).sum(0)
        for i, lam in enumerate(lambdas):
            pred = p @ (a / (ev[:, None] + lam))
            pc = pred - pred.mean(0)
            den = np.sqrt((pc * pc).sum(0) * yf_ss)
            scores[i] += np.where(
                den > 0, (pc * yf_c).sum(0) / np.where(den > 0, den,
                                                       1.0), 0.0)
    sel = lambdas[np.argmax(scores, axis=0)]
    ev, q = np.linalg.eigh(g)
    ev = np.maximum(ev, 0.0)
    a = q.T @ b
    out = q @ (a / (ev[:, None] + sel[None, :]))
    dt = time.perf_counter() - t0
    assert out.shape == (n_features, n_voxels)
    return n_voxels * len(lambdas) / dt


def _encoding_result_record(out):
    """The encoding tier's bench JSON line (schema:
    ``brainiak_tpu.obs.validate_bench_record``).  Tier separation
    mirrors the other tiers: a run whose backend is not a TPU is
    stamped ``tier="encoding_cpu_fallback"`` so ``obs regress
    --only encoding`` gates both backends as one family without
    ever comparing them against each other."""
    vls = float(out["voxels_lambdas_per_sec"])
    baseline = encoding_cpu_voxels_lambdas_per_sec(
        out["n_voxels"], out["n_features"], n_trs=out["n_trs"])
    tier = "encoding" if out.get("backend") == "tpu" \
        else "encoding_cpu_fallback"
    rec = {"schema_version": BENCH_SCHEMA_VERSION,
           "metric": "encoding_ridge_cv_voxels_lambdas_per_sec",
           "value": round(vls, 2),
           "unit": "voxels*lambdas/sec",
           "vs_baseline": round(vls / baseline, 2),
           "tier": tier,
           "config": {"n_voxels": out["n_voxels"],
                      "n_features": out["n_features"],
                      "n_lambdas": out["n_lambdas"],
                      "n_folds": out["n_folds"],
                      "n_trs": out["n_trs"]}}
    commit = _git_commit()
    if commit:
        rec["git_commit"] = commit
    if out.get("stages"):
        rec["stages"] = out["stages"]
    return rec


def serve_tier_metrics(n_requests=SERVE_REQUESTS, seed=0):
    """The ``serve`` tier: batched SRM-transform serving throughput
    through ``brainiak_tpu.serve`` (requests/s, latency percentiles,
    padding waste) against a tiny model fitted in-process, with
    ``vs_baseline`` the unbatched per-request host-BLAS loop.  The
    engine run goes through a save/load round trip so the measured
    path is the production one (artifact -> engine), and the obs
    spans around the phases feed the ``stages`` breakdown."""
    import io as _io

    from brainiak_tpu import serve
    from brainiak_tpu.serve.__main__ import (build_demo_model,
                                             build_mixed_requests,
                                             measure,
                                             naive_requests_per_sec,
                                             summary_to_out)

    with obs.span("bench.data_gen"):
        model = build_demo_model(n_subjects=4, voxels=256,
                                 samples=64, features=16, n_iter=3,
                                 seed=seed)
        buf = _io.BytesIO(serve.save_model_bytes(model))
        model = serve.load_model(buf)
        requests = build_mixed_requests(model, n_requests,
                                        seed=seed)
    with obs.span("bench.warm"):
        measure(model, requests, warm=False)  # compile pass
    with obs.span("bench.steady"):
        summary = measure(model, requests, warm=False)
    return summary_to_out(
        summary,
        baseline_rps=naive_requests_per_sec(model, requests))


def _serve_result_record(out, n_requests):
    """The serve tier's bench JSON line — delegated to the shared
    builder in ``brainiak_tpu.serve.__main__`` so the CLI ``bench``
    subcommand and this tier cannot drift (``obs regress`` gates the
    serve tier separately from the FCMA tiers)."""
    from brainiak_tpu.serve.__main__ import bench_record

    return bench_record(out, n_requests,
                        stages=out.get("stages"))


def service_tier_metrics(n_requests=SERVICE_REQUESTS, seed=0):
    """The ``service`` tier: always-on continuous-batching serving
    through :class:`brainiak_tpu.serve.ServeService` — two resident
    models (an SRM transform tier and a ridge_encoding scoring
    tier) under one residency, mixed-shape requests submitted in
    staggered waves, results delivered by ticket.  The warm drive
    pays the compiles; the timed drive is the steady-state loop.
    ``vs_baseline`` is the unbatched per-request host loop over the
    same mixed workload."""
    import itertools

    import jax

    from brainiak_tpu.serve import BucketPolicy, ModelResidency
    from brainiak_tpu.serve.__main__ import (build_demo_model,
                                             build_encoding_model,
                                             build_encoding_requests,
                                             build_mixed_requests,
                                             drive_service,
                                             naive_requests_per_sec)

    with obs.span("bench.data_gen"):
        srm = build_demo_model(n_subjects=4, voxels=256,
                               samples=64, features=16, n_iter=3,
                               seed=seed)
        enc = build_encoding_model(voxels=256, features=32,
                                   samples=80, n_folds=4, seed=seed)
        n_srm = n_requests // 2
        n_enc = n_requests - n_srm
        sreqs = build_mixed_requests(srm, n_srm, seed=seed)
        ereqs = build_encoding_requests(enc, n_enc, seed=seed + 1)
        for req in sreqs:
            req.model = "srm"
        for req in ereqs:
            req.model = "enc"
        requests = [req for pair in itertools.zip_longest(
            sreqs, ereqs) for req in pair if req is not None]
        policy = BucketPolicy(max_batch=32, max_wait_s=0.02)

    def _drive(**kwargs):
        residency = ModelResidency(budget_bytes=1 << 30,
                                   policy=policy)
        residency.register("srm", model=srm)
        residency.register("enc", model=enc)
        for req in requests:  # fresh stamps/traces per drive
            req.submitted = None
            req.trace_id = None
            req.parent_id = None
        return drive_service(residency, requests,
                             default_model="srm", waves=4,
                             **kwargs)

    with obs.span("bench.warm"):
        _drive()
    with obs.span("bench.steady"):
        summary, _, wall = _drive()
    # telemetry overhead: the SAME steady drive with obs fully
    # suspended (no sinks, no tracing — the disabled fast path) vs
    # the full live plane (sink + request tracing + SLO burn
    # tracking + /metrics exposition live on an ephemeral port).
    # Three reps per lane, min wall each: max-wait-vs-max-batch
    # flush timing makes partial-batch extents (and therefore a
    # stray compile) drive-dependent, and one 0.5 s compile would
    # swamp a 0.1 s steady wall — the min is the steady-state
    # estimate.  The ratio gates telemetry cost from day one
    # (lower_is_better in obs regress).
    from brainiak_tpu.obs import sink as obs_sink
    from brainiak_tpu.obs.http import TelemetryServer
    from brainiak_tpu.obs.slo import Objective
    walls_off = []
    with obs_sink.suspended():
        for _ in range(3):
            walls_off.append(_drive()[2])
    # the exposition server runs for the whole on-lane but is
    # started/stopped OUTSIDE the timed drives: drive_service's
    # wall includes shutdown, and charging the listener's stop
    # (poll interval + thread join) to telemetry overhead would be
    # phantom cost the off-lane never pays
    with TelemetryServer(port=0):
        walls_on = [
            _drive(slos=[Objective.latency(
                "bench_p99", quantile=0.99, threshold_s=30.0)])[2]
            for _ in range(3)]
    wall_off = min(walls_off)
    obs_overhead = (min(walls_on) / wall_off) if wall_off > 0 \
        else 0.0
    if summary["n_errors"]:
        # error records resolve in microseconds: rating them would
        # report record "throughput" (and a zero p99) for a broken
        # serving path, and the regress gate would stay green
        raise RuntimeError(
            f"service bench round produced {summary['n_errors']} "
            f"error record(s) ({summary['errors_by_code']}); "
            "refusing to emit a throughput number for a failing "
            "serving path")
    srm_rps = naive_requests_per_sec(srm, sreqs)
    enc_rps = naive_requests_per_sec(enc, ereqs)
    baseline = n_requests / (n_srm / srm_rps + n_enc / enc_rps)
    return {"requests_per_sec": n_requests / wall,
            "p50_latency_s": summary["p50_latency_s"],
            "p99_latency_s": summary["p99_latency_s"],
            "padding_waste": summary["padding_waste"],
            "retrace_total": summary["retrace_total"],
            "evictions": summary["residency"]["evictions"],
            "obs_overhead_ratio": obs_overhead,
            "n_requests": n_requests,
            "baseline_rps": baseline,
            "backend": jax.default_backend()}


def _service_result_records(out, n_requests):
    """The service tier's bench JSON lines — one record per gated
    metric: steady-state requests/s (higher is better), p99 latency
    and padding waste (both stamped ``direction="lower_is_better"``
    so ``obs regress --only service`` fails a doubled p99 or a
    padding blow-up the right way round), and the telemetry
    overhead ratio (steady-state wall with full tracing + SLO +
    /metrics exposition live vs obs suspended — also
    ``lower_is_better``, so a telemetry change that taxes the
    serving hot path fails CI from day one).  Tier split mirrors
    the other tiers (``service`` on TPU, ``service_cpu_fallback``
    otherwise)."""
    tier = "service" if out.get("backend") == "tpu" \
        else "service_cpu_fallback"
    config = {"n_requests": n_requests,
              "n_models": 2,
              "backend": out.get("backend"),
              "evictions": out.get("evictions", 0),
              "retrace_total": out.get("retrace_total", 0)}
    commit = _git_commit()

    def rec(metric, value, unit, vs=0.0, direction=None,
            stages=None):
        r = {"schema_version": BENCH_SCHEMA_VERSION,
             "metric": metric, "value": round(float(value), 6),
             "unit": unit, "vs_baseline": vs, "tier": tier,
             "config": config}
        if direction:
            r["direction"] = direction
        if commit:
            r["git_commit"] = commit
        if stages:
            r["stages"] = stages
        return r

    rps = float(out["requests_per_sec"])
    baseline = float(out.get("baseline_rps") or 0.0)
    vs = round(rps / baseline, 3) if baseline > 0 else 0.0
    return [
        rec("service_mixed_requests_per_sec", rps, "requests/sec",
            vs=vs, stages=out.get("stages")),
        rec("service_p99_latency_seconds",
            out["p99_latency_s"], "s",
            direction="lower_is_better"),
        rec("service_padding_waste_ratio", out["padding_waste"],
            "ratio", direction="lower_is_better"),
        rec("service_obs_overhead_ratio",
            out.get("obs_overhead_ratio", 0.0), "ratio",
            direction="lower_is_better"),
    ]


def federation_tier_metrics(n_requests=FEDERATION_REQUESTS, seed=0):
    """The ``federation`` tier: pod-scale serving federation
    (:mod:`brainiak_tpu.serve.federation`) — heavy-tailed
    fmrisim-driven SRM traffic routed across TWO warm in-process
    replicas behind the residency/depth router, with
    ``vs_baseline`` the same workload through ONE replica (the
    federation win).  A second phase replays fresh traffic at 2x
    the measured routed capacity against depth-bounded admission
    control: the gated numbers are the ACCEPTED requests' p99 (the
    bounded-queue promise — without shedding it would be the
    backlog) and the shed ratio."""
    import jax

    from brainiak_tpu.serve import BucketPolicy, ModelResidency
    from brainiak_tpu.serve.__main__ import build_demo_model
    from brainiak_tpu.serve.federation import (AdmissionController,
                                               LocalReplica,
                                               Router,
                                               TrafficGenerator,
                                               replay)
    from brainiak_tpu.serve.service import ServeService

    with obs.span("bench.data_gen"):
        model = build_demo_model(n_subjects=4, voxels=256,
                                 samples=64, features=16, n_iter=3,
                                 seed=seed)
        gen = TrafficGenerator(model, model_name="m", seed=seed)
        requests = gen.requests(n_requests)
        policy = BucketPolicy(max_batch=32, max_wait_s=0.02)

    def replicas(n, tag):
        out = []
        for i in range(n):
            res = ModelResidency(budget_bytes=1 << 30,
                                 policy=policy)
            res.register("m", model=model)
            out.append(LocalReplica(ServeService(
                res, default_model="m",
                name=f"{tag}{i + 1}").start()))
        return out

    def drive(reps, reqs, admission=None, schedule=None):
        router = Router(reps, admission=admission)
        try:
            t0 = time.perf_counter()
            if schedule is not None:
                tickets = replay(schedule, router.submit_many)
            else:
                for req in reqs:  # fresh stamps/traces per drive
                    req.submitted = None
                    req.trace_id = None
                    req.parent_id = None
                tickets = router.submit_many(reqs)
            records = [t.result(timeout=600.0) for t in tickets]
            wall = time.perf_counter() - t0
        finally:
            for rep in reps:
                rep.service.shutdown()
        return router, records, wall

    with obs.span("bench.warm"):  # compiles (program caches are
        drive(replicas(1, "w"), requests)  # process-global)
    with obs.span("bench.steady"):
        _, records, single_wall = drive(replicas(1, "s"), requests)
        if not all(r.ok for r in records):
            raise RuntimeError(
                "federation bench single-replica drive produced "
                "error records; refusing to emit numbers")
        router, records, wall = drive(replicas(2, "f"), requests)
        if not all(r.ok for r in records):
            raise RuntimeError(
                "federation bench routed drive produced error "
                "records; refusing to emit numbers")
        routed_rps = n_requests / wall
        single_rps = n_requests / single_wall
        # overload: a fresh heavy-tailed mix arriving as one
        # atomic burst of 2x the fleet's admission capacity
        # (2 replicas x depth bound) — the router's in-flight-
        # corrected placement admits exactly the bound per replica
        # and sheds the deterministic rest, so the gated shed
        # ratio is burst structure, not scheduler jitter; the
        # accepted requests' p99 is then capped by bound/rate (the
        # bounded-queue promise) instead of the backlog.  The
        # wall-paced heavy-tailed replay (federation.replay) is
        # soak coverage, exercised by the SRV003 selfcheck and the
        # federation tests.
        bound = max(8, n_requests // 4)
        over_router, over_records, _ = drive(
            replicas(2, "o"),
            gen.requests(2 * bound * 2, prefix="o"),
            admission=AdmissionController(max_depth=bound))
    ok_latencies = sorted(r.latency_s for r in over_records
                          if r.ok and r.latency_s is not None)
    n_shed = sum(1 for r in over_records
                 if r.error == "shed_overload")
    n_failed = len(over_records) - n_shed \
        - sum(1 for r in over_records if r.ok)
    if n_failed or not ok_latencies:
        raise RuntimeError(
            f"federation overload drive produced {n_failed} "
            "non-shed error record(s) "
            f"({len(ok_latencies)} served); refusing to emit "
            "numbers")
    idx = min(len(ok_latencies) - 1,
              int(round(0.99 * (len(ok_latencies) - 1))))
    return {"routed_requests_per_sec": routed_rps,
            "single_replica_rps": single_rps,
            "overload_p99_s": ok_latencies[idx],
            "shed_ratio": n_shed / len(over_records),
            "shed_bound": bound,
            "overload_burst": len(over_records),
            "routed": router.summary()["routed"],
            "n_requests": n_requests,
            "n_replicas": 2,
            "backend": jax.default_backend()}


def _federation_result_records(out):
    """The federation tier's bench JSON lines — three records:
    routed requests/s across 2 replicas (``vs_baseline`` = the
    federation win over one replica on the same workload),
    accepted-request p99 under 2x-capacity overload and the shed
    ratio (both ``direction="lower_is_better"`` so a melted queue
    or an over-eager shedder fails CI the right way round).  Tier
    split mirrors every other tier (``federation`` on TPU,
    ``federation_cpu_fallback`` otherwise)."""
    tier = "federation" if out.get("backend") == "tpu" \
        else "federation_cpu_fallback"
    config = {"n_requests": out["n_requests"],
              "n_replicas": out["n_replicas"],
              "backend": out.get("backend"),
              "shed_bound": out["shed_bound"],
              "overload_burst": out["overload_burst"]}
    commit = _git_commit()

    def rec(metric, value, unit, vs=0.0, direction=None,
            stages=None):
        r = {"schema_version": BENCH_SCHEMA_VERSION,
             "metric": metric, "value": round(float(value), 6),
             "unit": unit, "vs_baseline": vs, "tier": tier,
             "config": config}
        if direction:
            r["direction"] = direction
        if commit:
            r["git_commit"] = commit
        if stages:
            r["stages"] = stages
        return r

    rps = float(out["routed_requests_per_sec"])
    single = float(out.get("single_replica_rps") or 0.0)
    vs = round(rps / single, 3) if single > 0 else 0.0
    return [
        rec("federation_routed_requests_per_sec", rps,
            "requests/sec", vs=vs, stages=out.get("stages")),
        rec("federation_overload_p99_seconds",
            out["overload_p99_s"], "s",
            direction="lower_is_better"),
        rec("federation_shed_ratio", out["shed_ratio"], "ratio",
            direction="lower_is_better"),
    ]


def elastic_tier_metrics(n_requests=ELASTIC_REQUESTS, seed=0):
    """Elastic-fleet chaos soak throughput (ISSUE 16 satellite):
    one :func:`~brainiak_tpu.serve.federation.fleet.chaos_soak`
    (replica stalled, killed, failed over; traffic tripled;
    fleet scaled up off the shared AOT cache), with the SAME
    request mix on a static no-fault 2-replica fleet as the
    baseline — ``vs_baseline`` is the survival tax.  A soak whose
    non-shed/non-replica_lost error count is nonzero refuses to
    emit numbers (same rule as the service/federation tiers);
    unresolved tickets and replica_lost records are NOT refusals —
    they are the gated lost-ticket metric itself."""
    import jax

    from brainiak_tpu.serve.federation.fleet import chaos_soak

    with obs.span("bench.baseline"):
        static = chaos_soak(n_requests=n_requests, seed=seed,
                            chaos=False)
    with obs.span("bench.soak"):
        soak = chaos_soak(n_requests=n_requests, seed=seed,
                          chaos=True)
    for name, facts in (("static", static), ("soak", soak)):
        other = {code: n for code, n in facts["by_code"].items()
                 if code not in ("delivered", "shed_overload",
                                 "replica_lost")}
        if other:
            raise RuntimeError(
                f"elastic bench {name} round produced unexpected "
                f"error records {other}; refusing to emit numbers")
    lost = soak["n_unresolved"] + soak["n_replica_lost"]
    return {"soak_requests_per_sec": soak["requests_per_sec"],
            "static_requests_per_sec":
                static["requests_per_sec"],
            "post_failure_p99_s": soak.get("post_failure_p99_s",
                                           0.0),
            "lost_tickets": lost,
            "n_unresolved": soak["n_unresolved"],
            "n_replica_lost": soak["n_replica_lost"],
            "n_shed": soak["n_shed"],
            "failover": soak.get("failover"),
            "scaled_replicas": soak.get("scaled_replicas", []),
            "warm_retraces": soak.get("warm_retraces"),
            "final_retraces": soak.get("final_retraces"),
            "n_requests": soak["n_requests"],
            "n_replicas": 2,
            "backend": jax.default_backend()}


def _elastic_result_records(out):
    """The elastic tier's bench JSON lines — three records: soak
    requests/s under chaos (``vs_baseline`` = soak rate over the
    static-fleet rate on the same mix), post-failure p99
    (``lower_is_better``: failover + scale-up must not melt the
    tail), and the lost-ticket count (``lower_is_better`` with the
    committed fixtures at ZERO: the first unresolved or
    replica_lost ticket is an infinite-ratio regression).  Tier
    split mirrors every other tier (``elastic`` on TPU,
    ``elastic_cpu_fallback`` otherwise)."""
    tier = "elastic" if out.get("backend") == "tpu" \
        else "elastic_cpu_fallback"
    config = {"n_requests": out["n_requests"],
              "n_replicas": out["n_replicas"],
              "backend": out.get("backend"),
              "scaled_replicas": out.get("scaled_replicas")}
    commit = _git_commit()

    def rec(metric, value, unit, vs=0.0, direction=None,
            stages=None):
        r = {"schema_version": BENCH_SCHEMA_VERSION,
             "metric": metric, "value": round(float(value), 6),
             "unit": unit, "vs_baseline": vs, "tier": tier,
             "config": config}
        if direction:
            r["direction"] = direction
        if commit:
            r["git_commit"] = commit
        if stages:
            r["stages"] = stages
        return r

    rps = float(out["soak_requests_per_sec"])
    static = float(out.get("static_requests_per_sec") or 0.0)
    vs = round(rps / static, 3) if static > 0 else 0.0
    return [
        rec("elastic_soak_requests_per_sec", rps, "requests/sec",
            vs=vs, stages=out.get("stages")),
        rec("elastic_post_failure_p99_seconds",
            out["post_failure_p99_s"], "s",
            direction="lower_is_better"),
        rec("elastic_lost_tickets", out["lost_tickets"],
            "requests", direction="lower_is_better"),
    ]


def _ts_key(ts):
    """Chronological sort key for possibly-absent ISO timestamps with
    heterogeneous UTC offsets (lexicographic comparison is wrong across
    offsets)."""
    if not ts:
        return float("-inf")
    import datetime
    if ts.endswith("Z"):
        # fromisoformat rejects a 'Z' suffix before Python 3.11
        ts = ts[:-1] + "+00:00"
    try:
        return datetime.datetime.fromisoformat(ts).timestamp()
    except ValueError:
        return float("-inf")


def _last_onchip():
    """Most recent real-chip evidence in the repo, for transport inside
    the bench JSON line even when this run itself falls back to CPU
    (the tunnel wedges for whole rounds; see docs/performance.md).

    Sources, newest wins: ``benchmarks/TPU_MFU.json`` and
    ``benchmarks/TPU_VALIDATION.json``, both written only by scripts
    that ran on a live chip (``backend == "tpu"`` recorded inside).
    Timestamp comes from the artifact's own ``ts`` stamp when present,
    else the file's last git commit date (checkout mtime is
    meaningless).
    """
    import os
    import subprocess
    here = os.path.dirname(os.path.abspath(__file__))
    best = None
    for name, extract in (
            ("benchmarks/TPU_MFU.json",
             lambda d: d.get("end_to_end_32k", {}).get("voxels_per_s")),
            ("benchmarks/TPU_VALIDATION.json",
             lambda d: max((v.get("voxels_per_s", 0)
                            for v in d.get("end_to_end", {}).values()),
                           default=None)),
    ):
        path = os.path.join(here, name)
        if not os.path.exists(path):
            continue
        try:
            with open(path) as f:
                doc = json.load(f)
        except (OSError, ValueError):
            continue
        if doc.get("backend") != "tpu":
            continue
        vps = extract(doc)
        if not vps:
            continue
        ts = doc.get("ts")
        if ts is None:
            try:
                ts = subprocess.run(
                    ["git", "log", "-1", "--format=%cI", "--", name],
                    cwd=here, capture_output=True, text=True,
                    timeout=10).stdout.strip() or None
            except (OSError, subprocess.TimeoutExpired):
                ts = None
        if best is None or _ts_key(ts) > _ts_key(best[2]):
            best = (name, float(vps), ts)
    if best is None:
        return {}
    return {"last_onchip_voxels_per_sec": round(best[1], 1),
            "last_onchip_ts": best[2],
            "last_onchip_source": best[0]}


def _device_responsive(timeout=150):
    """Probe the accelerator in a subprocess: a wedged TPU tunnel hangs
    forever on the first dispatch (even block_until_ready is a no-op), so
    never touch the device in-process before knowing it answers."""
    import subprocess
    import sys
    code = ("import jax, jax.numpy as jnp;"
            "print(float((jnp.ones((64,64))@jnp.ones((64,64)))[0,0]))")
    try:
        r = subprocess.run([sys.executable, "-c", code], timeout=timeout,
                           capture_output=True)
        return r.returncode == 0
    except subprocess.TimeoutExpired:
        return False


def _run_tier_subprocess(tier, timeout):
    """Run one accelerator tier in a fresh subprocess (one chip process
    at a time; a wedge mid-tier must not hang THIS process past the
    driver's patience) and return its parsed JSON result, or None."""
    import subprocess
    import sys
    try:
        r = subprocess.run([sys.executable, __file__, "--tier", tier],
                           timeout=timeout, capture_output=True,
                           text=True)
    except subprocess.TimeoutExpired:
        print(f"tier {tier}: timed out after {timeout}s",
              file=sys.stderr)
        return None
    if r.returncode != 0:
        # keep the child's traceback: a failed whole-brain attempt in
        # the rare healthy-chip window must leave a diagnostic behind
        tail = "\n".join((r.stderr or "").strip().splitlines()[-15:])
        print(f"tier {tier}: rc={r.returncode}\n{tail}",
              file=sys.stderr)
        return None
    for line in reversed(r.stdout.strip().splitlines()):
        try:
            return json.loads(line)
        except ValueError:
            continue
    return None


def _stage_seconds(records):
    """Aggregate ``bench.*`` span records into the per-stage
    breakdown dict (missing stages report 0.0 so the emitted schema
    is stable)."""
    totals = {}
    for rec in records:
        if rec.get("kind") != "span":
            continue
        name = rec.get("name", "")
        if not name.startswith("bench."):
            continue
        key = name.split(".", 1)[1] + "_s"
        totals[key] = totals.get(key, 0.0) + float(rec["dur_s"])
    return {key: round(totals.get(key, 0.0), 4)
            for key in STAGE_KEYS}


def measure_tier(tier):
    """Run one tier with obs collecting on an in-memory sink; returns
    ``{"voxels_per_sec": vps, "stages": {...}}`` (the child-process
    JSON contract, also used in-process by the CPU fallback and the
    bench schema test)."""
    import os
    import jax  # noqa: F401  (monitoring hook needs jax imported;
    # plain import does not initialize a backend)
    obs.install_compile_listener()
    mem = obs.add_sink(obs.MemorySink())
    try:
        if tier == "distla":
            out = distla_tier_metrics(_distla_n_voxels())
            # the record's tier is split by backend (an on-chip SUMMA
            # rate must never share a regress baseline with a
            # CPU-fallback one — same rule as the fcma/serve tiers)
            obs.gauge("bench_distla_voxels_per_sec",
                      unit="voxels/sec").set(
                          out["voxels_per_sec"],
                          tier="distla" if out["backend"] == "tpu"
                          else "distla_cpu_fallback")
            out["stages"] = _stage_seconds(mem.records)
            return out
        if tier == "kernels":
            out = kernels_tier_metrics(*_kernels_shape())
            # tier split by backend, same rule as every other tier
            obs.gauge("bench_kernels_fb_trs_per_sec",
                      unit="TRs/sec").set(
                          out["fb_trs_per_sec"],
                          tier="kernels" if out["backend"] == "tpu"
                          else "kernels_cpu_fallback")
            out["stages"] = _stage_seconds(mem.records)
            return out
        if tier == "realtime":
            out = realtime_tier_metrics(_realtime_n_trs())
            # tier split by backend, same rule as every other tier
            obs.gauge("bench_realtime_tr_p99_seconds",
                      unit="s").set(
                          out["p99_latency_s"],
                          tier="realtime" if out["backend"] == "tpu"
                          else "realtime_cpu_fallback")
            out["stages"] = _stage_seconds(mem.records)
            return out
        if tier == "stats":
            out = stats_tier_metrics(*_stats_shape())
            # tier split by backend, same rule as every other tier
            obs.gauge("bench_stats_surrogates_per_sec",
                      unit="surrogates/sec").set(
                          out["surrogates_per_sec"],
                          tier="stats" if out["backend"] == "tpu"
                          else "stats_cpu_fallback")
            out["stages"] = _stage_seconds(mem.records)
            return out
        if tier == "jobs":
            out = jobs_tier_metrics(_jobs_count())
            # tier split by backend, same rule as every other tier
            obs.gauge("bench_jobs_scheduled_jobs_per_sec",
                      unit="jobs/sec").set(
                          out["jobs_per_sec"],
                          tier="jobs" if out["backend"] == "tpu"
                          else "jobs_cpu_fallback")
            out["stages"] = _stage_seconds(mem.records)
            return out
        if tier == "streaming":
            out = streaming_tier_metrics(*_streaming_shape())
            # tier split by backend, same rule as every other tier
            obs.gauge("bench_streaming_subjects_per_sec",
                      unit="subjects/sec").set(
                          out["subjects_per_sec"],
                          tier="streaming" if out["backend"] == "tpu"
                          else "streaming_cpu_fallback")
            out["stages"] = _stage_seconds(mem.records)
            return out
        if tier == "encoding":
            out = encoding_tier_metrics(*_encoding_shape())
            # the record's tier is split by backend (an on-chip
            # sweep rate must never share a regress baseline with a
            # CPU-fallback one — same rule as the other tiers)
            obs.gauge("bench_encoding_voxels_lambdas_per_sec",
                      unit="voxels*lambdas/sec").set(
                          out["voxels_lambdas_per_sec"],
                          tier="encoding" if out["backend"] == "tpu"
                          else "encoding_cpu_fallback")
            out["stages"] = _stage_seconds(mem.records)
            return out
        if tier == "serve":
            out = serve_tier_metrics(n_requests=_serve_n_requests())
            # the record's tier is split by backend (an on-chip
            # serve rate must never share a regress baseline with
            # a CPU-fallback one — same rule as the fcma tiers)
            out["backend"] = jax.default_backend()
            obs.gauge("bench_serve_requests_per_sec",
                      unit="requests/sec").set(
                          out["requests_per_sec"], tier="serve")
            out["stages"] = _stage_seconds(mem.records)
            return out
        if tier == "service":
            out = service_tier_metrics(
                n_requests=_service_n_requests())
            # tier split by backend, same rule as every other tier
            svc_tier = "service" if out["backend"] == "tpu" \
                else "service_cpu_fallback"
            obs.gauge("bench_service_requests_per_sec",
                      unit="requests/sec").set(
                          out["requests_per_sec"], tier=svc_tier)
            out["stages"] = _stage_seconds(mem.records)
            return out
        if tier == "federation":
            out = federation_tier_metrics(
                n_requests=_federation_n_requests())
            # tier split by backend, same rule as every other tier
            fed_tier = "federation" if out["backend"] == "tpu" \
                else "federation_cpu_fallback"
            obs.gauge("bench_federation_requests_per_sec",
                      unit="requests/sec").set(
                          out["routed_requests_per_sec"],
                          tier=fed_tier)
            out["stages"] = _stage_seconds(mem.records)
            return out
        if tier == "elastic":
            out = elastic_tier_metrics(
                n_requests=_elastic_n_requests())
            # tier split by backend, same rule as every other tier
            ela_tier = "elastic" if out["backend"] == "tpu" \
                else "elastic_cpu_fallback"
            obs.gauge("bench_elastic_requests_per_sec",
                      unit="requests/sec").set(
                          out["soak_requests_per_sec"],
                          tier=ela_tier)
            out["stages"] = _stage_seconds(mem.records)
            return out
        if tier == "wb":
            vps = whole_brain_voxels_per_sec(
                n_voxels=int(os.environ.get("BENCH_WB_VOXELS",
                                            WB_VOXELS)),
                selected=int(os.environ.get("BENCH_WB_SELECTED",
                                            WB_SELECTED)),
                n_epochs=_even_epochs_env("BENCH_WB_EPOCHS",
                                          WB_EPOCHS))
        elif tier == "mid":
            vps = tpu_voxels_per_sec(
                n_voxels=int(os.environ.get("BENCH_MID_VOXELS",
                                            N_VOXELS)))
        else:  # reduced CPU fallback
            vps = tpu_voxels_per_sec(n_voxels=2048, unit=256)
        # label with the PUBLISHED tier vocabulary (the bench JSON
        # line's "tier" field), not the internal child-process name
        obs.gauge("bench_voxels_per_sec", unit="voxels/sec").set(
            vps, tier={"wb": "whole_brain",
                       "mid": "mid_V8192"}.get(tier, tier))
        stages = _stage_seconds(mem.records)
    finally:
        obs.remove_sink(mem)
    return {"voxels_per_sec": vps, "stages": stages}


def _git_commit():
    """Short commit hash of the tree this bench ran from, or None
    (regress.py pins a record to the code that produced it)."""
    from brainiak_tpu.obs.report import git_commit_stamp
    return git_commit_stamp()


def _result_record(tier, vps, cpu_vps, config=None, stages=None):
    """The bench JSON line (schema:
    ``brainiak_tpu.obs.validate_bench_record``)."""
    metric = "fcma_voxel_selection_voxels_per_sec_chip"
    if tier == "cpu_fallback":
        metric += "_CPU_FALLBACK_tpu_unresponsive"
    rec = {"schema_version": BENCH_SCHEMA_VERSION,
           "metric": metric,
           "value": round(vps, 2),
           "unit": "voxels/sec",
           "vs_baseline": round(vps / cpu_vps, 2),
           "tier": tier}
    commit = _git_commit()
    if commit:
        rec["git_commit"] = commit
    if config:
        rec["config"] = config
    if stages:
        rec["stages"] = stages
    rec.update(_last_onchip())
    return rec


def _tier_main(tier):
    """Child-process entry: run one tier on the ambient (TPU) backend
    and print its rate (+ stage breakdown) as a JSON line.  Env
    overrides exist so the orchestration can be smoke-tested at toy
    sizes on CPU — set ``BENCH_FORCE_CPU=1`` for that (the
    JAX_PLATFORMS env var alone HANGS once the tunnel PJRT plugin is
    registered; the platform must be pinned in-process before backend
    init, docs/performance.md operational rule 4)."""
    import os
    if os.environ.get("BENCH_FORCE_CPU"):
        import jax
        jax.config.update("jax_platforms", "cpu")
    print(json.dumps(measure_tier(tier)))


def main():
    """One bench invocation prints one JSON line per tier: the FCMA
    fit-path record (whole-brain / mid / cpu_fallback), the serve
    tier record, the service tier's three records (requests/s, p99,
    padding waste), and the distla/encoding records — ``obs
    regress`` gates each tier against its own history."""
    responsive = _fcma_main()
    _serve_main(responsive)
    _service_main(responsive)
    _federation_main(responsive)
    _elastic_main(responsive)
    _distla_main(responsive)
    _encoding_main(responsive)
    _kernels_main(responsive)
    _streaming_main(responsive)
    _realtime_main(responsive)
    _stats_main(responsive)
    _jobs_main(responsive)


def _aux_tier_main(responsive, tier, record_fn, timeout=420):
    """Shared auxiliary-tier driver (serve/distla/encoding):
    subprocess first (one chip process at a time — a wedge must not
    hang the driver), in-process CPU fallback otherwise.
    ``responsive`` is an earlier tier's probe verdict; a prior
    subprocess may have wedged the tunnel since, so a True verdict
    is re-probed cheaply before committing the chip, while a False
    one is trusted as-is (straight to the CPU fallback)."""
    if responsive:
        responsive = _device_responsive(timeout=90)
    out = _run_tier_subprocess(tier, timeout=timeout) \
        if responsive else None
    if out is None:
        import jax
        jax.config.update("jax_platforms", "cpu")
        out = measure_tier(tier)
    recs = record_fn(out)
    # multi-metric tiers (service) return one record per gated
    # metric; each is its own bench JSON line
    for rec in recs if isinstance(recs, list) else [recs]:
        print(json.dumps(rec))


def _encoding_main(responsive):
    """Encoding tier: voxel-wise ridge CV throughput."""
    _aux_tier_main(responsive, "encoding", _encoding_result_record)


def _kernels_main(responsive):
    """Kernels tier: fused-vs-unfused throughput — two records
    (eventseg forward-backward TRs/s, SUMMA ring step GB/s), each
    with the measured fusion win as ``vs_baseline``."""
    _aux_tier_main(responsive, "kernels", _kernels_result_records)


def _federation_main(responsive):
    """Federation tier: routed requests/s across 2 replicas, p99
    under 2x-capacity overload, shed ratio.  Like the service
    tier, a failing round (non-shed error records) refuses to emit
    numbers without aborting the driver."""
    import sys
    try:
        _aux_tier_main(responsive, "federation",
                       _federation_result_records)
    except RuntimeError as exc:
        print(f"tier federation: {exc}", file=sys.stderr)


def _elastic_main(responsive):
    """Elastic tier: chaos-soak requests/s vs a static 2-replica
    fleet, post-failure p99, lost-ticket count.  Like the
    federation tier, a failing round (unexpected error records)
    refuses to emit numbers without aborting the driver."""
    import sys
    try:
        _aux_tier_main(responsive, "elastic",
                       _elastic_result_records)
    except RuntimeError as exc:
        print(f"tier elastic: {exc}", file=sys.stderr)


def _distla_main(responsive):
    """Distla tier: SUMMA-sharded Gram throughput."""
    _aux_tier_main(responsive, "distla", _distla_result_record)


def _streaming_main(responsive):
    """Streaming tier: out-of-core subject-sharded SRM — two
    records (streamed subjects/s, prefetch stall ratio)."""
    _aux_tier_main(responsive, "streaming", _streaming_result_records)


def _stats_main(responsive):
    """Stats tier: resampling-null surrogates/s through the chunked
    NullEngine, with the host-loop formulation as ``vs_baseline``."""
    _aux_tier_main(responsive, "stats", _stats_result_record)


def _jobs_main(responsive):
    """Jobs tier: the fit scheduler co-scheduled with warm serving
    — three records (scheduled jobs/s vs the solo baseline,
    co-scheduled serving p99, jobs lost; the latter two
    lower-is-better)."""
    _aux_tier_main(responsive, "jobs", _jobs_result_records)


def _realtime_main(responsive):
    """Realtime tier: closed-loop per-TR scan — two records (per-TR
    p99 latency, deadline-miss ratio; both lower-is-better).  A
    retracing scan refuses to emit numbers without aborting the
    driver (same rule as the service tier)."""
    import sys
    try:
        _aux_tier_main(responsive, "realtime",
                       _realtime_result_records)
    except RuntimeError as exc:
        print(f"tier realtime: {exc}", file=sys.stderr)


def _serve_main(responsive):
    """Serve tier: batched SRM-transform serving throughput."""
    n_requests = _serve_n_requests()
    _aux_tier_main(
        responsive, "serve",
        lambda out: _serve_result_record(out, n_requests))


def _service_main(responsive):
    """Service tier: continuous-batching steady state — three
    records (requests/s, p99 latency, padding waste).  A failing
    service round (error records -> the tier refuses to emit fake
    numbers) must not abort the driver: the remaining tiers still
    record their history."""
    import sys
    n_requests = _service_n_requests()
    try:
        _aux_tier_main(
            responsive, "service",
            lambda out: _service_result_records(out, n_requests))
    except RuntimeError as exc:
        print(f"tier service: {exc}", file=sys.stderr)


def _fcma_main():
    # Probe BEFORE any in-process jax backend touch: on a wedged TPU
    # tunnel even backend initialization (jax.default_backend()) hangs.
    # The tunnel sometimes un-wedges after an idle period, so a failed
    # probe is retried twice on a short schedule (fresh subprocess each
    # time per the one-process rule) before conceding the CPU fallback
    # — one wedge at the exact probe instant should not forfeit the
    # round's only driver-run perf measurement.
    responsive = _device_responsive()
    for _ in range(2):
        if responsive:
            break
        time.sleep(90)
        responsive = _device_responsive()

    # the same env overrides the tier children read (_tier_main), so
    # the emitted config and the CPU-baseline scale always match what
    # the child actually measured — even under the smoke-test sizes
    import os
    wb_voxels = int(os.environ.get("BENCH_WB_VOXELS", WB_VOXELS))
    wb_selected = int(os.environ.get("BENCH_WB_SELECTED", WB_SELECTED))
    wb_epochs = _even_epochs_env("BENCH_WB_EPOCHS", WB_EPOCHS)
    mid_voxels = int(os.environ.get("BENCH_MID_VOXELS", N_VOXELS))

    if responsive:
        # North-star tier first (BASELINE.json scale: whole-brain
        # width, E>=32); each tier in its own subprocess so a mid-run
        # wedge cannot hang the bench.  The timeout is a last-resort
        # tradeoff: killing mid-dispatch can deepen a wedge, but an
        # unbounded child would hang the driver's bench invocation
        # outright — so the ceiling is sized at ~2.5x the expected
        # healthy-chip wall time (upload + compile + 2 runs ~ 8 min)
        # and a probe runs before committing the next tier.
        out = _run_tier_subprocess("wb", timeout=1200)
        if out:
            cpu_vps = cpu_voxels_per_sec(n_voxels=wb_voxels, block=8,
                                         n_epochs=wb_epochs)
            print(json.dumps(_result_record(
                "whole_brain", out["voxels_per_sec"], cpu_vps,
                config={"n_voxels": wb_voxels,
                        "selected": wb_selected,
                        "n_epochs": wb_epochs, "n_trs": N_TRS},
                stages=out.get("stages"))))
            return responsive
        # the wb attempt may have wedged the tunnel — re-probe
        # cheaply before committing the mid tier to the chip, and
        # keep the FRESHER verdict (the serve tier reads it too)
        responsive = _device_responsive(timeout=90)
        if responsive:
            out = _run_tier_subprocess("mid", timeout=420)
            if out:
                cpu_vps = cpu_voxels_per_sec(n_voxels=mid_voxels)
                print(json.dumps(_result_record(
                    "mid_V8192", out["voxels_per_sec"], cpu_vps,
                    config={"n_voxels": mid_voxels,
                            "n_epochs": N_EPOCHS, "n_trs": N_TRS},
                    stages=out.get("stages"))))
                return responsive

    # fall back to CPU so the driver records a number instead of a
    # hung process (reduced size: the full problem takes tens of
    # minutes on CPU)
    import jax
    jax.config.update("jax_platforms", "cpu")
    out = measure_tier("cpu_fallback")
    cpu_vps = cpu_voxels_per_sec(n_voxels=2048, block=32)
    print(json.dumps(_result_record(
        "cpu_fallback", out["voxels_per_sec"], cpu_vps,
        stages=out["stages"])))
    return responsive


if __name__ == "__main__":
    import sys
    if len(sys.argv) >= 3 and sys.argv[1] == "--tier":
        _tier_main(sys.argv[2])
    else:
        main()
