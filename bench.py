"""Headline benchmark: FCMA voxel-selection kernel throughput on TPU.

Prints ONE JSON line:
    {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}

The metric is the BASELINE.json north star "FCMA voxels/sec/chip": how many
selected voxels per second one chip pushes through FCMA stage 1+2
(per-epoch full-brain correlation + Fisher-z within-subject normalization,
reference voxelselector.py:284-328 + fcma_extension.cc).  ``vs_baseline``
is the speedup over the same pipeline run with NumPy/BLAS on this host's
CPU — the reference implementation's compute path without MPI.

Timing notes: on the tunneled TPU platform ``block_until_ready`` does not
synchronize and host<->device transfers are slow, so the benchmark
generates data on-device, chains k pipeline repetitions in a fori_loop,
synchronizes by fetching a scalar, and subtracts the k=1 dispatch overhead.
"""

import json
import time
from functools import partial

import numpy as np

N_VOXELS = 16384
N_TRS = 150
N_EPOCHS = 16
BLOCK = 256
EPOCHS_PER_SUBJ = 4


def _tpu_voxels_per_sec():
    import jax
    import jax.numpy as jnp

    from brainiak_tpu.ops.correlation import correlate_epochs
    from brainiak_tpu.ops.fisherz import within_subject_normalization

    n_blocks = N_VOXELS // BLOCK

    @partial(jax.jit, static_argnames="k")
    def run(key, k):
        data = jax.random.normal(key, (N_EPOCHS, N_VOXELS, N_TRS),
                                 jnp.float32)
        mean = jnp.mean(data, axis=2, keepdims=True)
        std = jnp.std(data, axis=2, keepdims=True)
        norm = (data - mean) / (std * np.sqrt(N_TRS))

        def body(i, acc):
            blk = jax.lax.dynamic_slice_in_dim(
                norm, (i % n_blocks) * BLOCK, BLOCK, axis=1)
            corr = correlate_epochs(blk, norm)
            out = within_subject_normalization(corr, EPOCHS_PER_SUBJ)
            return acc + jnp.sum(out[:, 0, ::1024])

        return jax.lax.fori_loop(0, k, body, 0.0)

    key = jax.random.PRNGKey(0)
    k_lo, k_hi = 1, 17
    for k in (k_lo, k_hi):
        float(run(key, k))  # warm compile caches
    t0 = time.perf_counter()
    float(run(key, k_lo))
    d_lo = time.perf_counter() - t0
    t0 = time.perf_counter()
    float(run(key, k_hi))
    d_hi = time.perf_counter() - t0
    voxels = (k_hi - k_lo) * BLOCK
    return voxels / (d_hi - d_lo)


def _cpu_voxels_per_sec():
    rng = np.random.RandomState(0)
    data = rng.randn(N_EPOCHS, N_VOXELS, N_TRS).astype(np.float32)
    mean = data.mean(axis=2, keepdims=True)
    std = data.std(axis=2, keepdims=True)
    norm = (data - mean) / (std * np.sqrt(N_TRS))

    block = 64  # smaller block: CPU throughput is per-voxel linear
    t0 = time.perf_counter()
    blk = norm[:, :block]
    # BLAS per-epoch GEMM (the reference's cython sgemm path)
    corr = np.stack([blk[e] @ norm[e].T for e in range(N_EPOCHS)], axis=1)
    num = 1.0 + corr
    den = 1.0 - corr
    num[num <= 0] = 1e-4
    den[den <= 0] = 1e-4
    z = 0.5 * np.log(num / den)
    zr = z.reshape(block, N_EPOCHS // EPOCHS_PER_SUBJ, EPOCHS_PER_SUBJ,
                   N_VOXELS)
    m = zr.mean(axis=2, keepdims=True)
    var = (zr ** 2).mean(axis=2, keepdims=True) - m ** 2
    inv = np.where(var <= 0, 0.0, 1.0 / np.sqrt(np.maximum(var, 1e-30)))
    _ = ((zr - m) * inv).reshape(block, N_EPOCHS, N_VOXELS)
    dt = time.perf_counter() - t0
    return block / dt


def main():
    tpu_vps = _tpu_voxels_per_sec()
    cpu_vps = _cpu_voxels_per_sec()
    print(json.dumps({
        "metric": "fcma_voxel_selection_corrnorm_voxels_per_sec_chip",
        "value": round(tpu_vps, 2),
        "unit": "voxels/sec",
        "vs_baseline": round(tpu_vps / cpu_vps, 2),
    }))


if __name__ == "__main__":
    main()
