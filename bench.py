"""Headline benchmark: end-to-end FCMA voxel selection throughput on TPU.

Prints ONE JSON line:
    {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}

The metric is the BASELINE.json north star "FCMA voxels/sec/chip": complete
FCMA stage-1 voxel selection — per-epoch full-brain correlation, Fisher-z
within-subject normalization, per-voxel SVM Gram matrices, and stratified
k-fold kernel-SVM cross validation for every voxel — via
``brainiak_tpu.fcma.voxelselector.VoxelSelector.run('svm')``.

``vs_baseline`` is the speedup over the reference's compute path re-created
on this host's CPU (NumPy/BLAS correlation + normalization + Gram, sklearn
SVC precomputed-kernel CV per voxel), measured on a subset and scaled
per-voxel.

Wall-clock timing of ``run()`` is sound here because results are fetched to
host (which synchronizes) — unlike ``block_until_ready``, which is a no-op
on this tunneled TPU platform.
"""

import json
import math
import time

import numpy as np

N_VOXELS = 8192
N_TRS = 150
N_EPOCHS = 16
EPOCHS_PER_SUBJ = 4
NUM_FOLDS = 4


def make_data(n_voxels=N_VOXELS):
    rng = np.random.RandomState(0)
    data = []
    for _ in range(N_EPOCHS):
        mat = rng.randn(N_TRS, n_voxels).astype(np.float32)
        mat = (mat - mat.mean(0)) / (mat.std(0) * math.sqrt(N_TRS))
        data.append(mat)
    labels = [0, 1] * (N_EPOCHS // 2)
    return data, labels


def tpu_voxels_per_sec(n_voxels=N_VOXELS, unit=512, warm=True):
    from brainiak_tpu.fcma.voxelselector import VoxelSelector

    data, labels = make_data(n_voxels)
    vs = VoxelSelector(labels, EPOCHS_PER_SUBJ, NUM_FOLDS, data,
                       voxel_unit=unit)
    if warm:
        vs.run('svm')  # warm compile caches
    t0 = time.perf_counter()
    results = vs.run('svm')
    dt = time.perf_counter() - t0
    assert len(results) == n_voxels
    return n_voxels / dt


def cpu_voxels_per_sec(n_voxels=N_VOXELS, block=64):
    """Reference-path throughput on host BLAS, at the SAME voxel count as
    the jax path being compared (per-voxel cost scales with the full
    correlation width, so mismatched sizes would skew vs_baseline)."""
    from sklearn import model_selection, svm

    data, labels = make_data(n_voxels)
    stacked = np.stack(data)  # [E, T, V]
    t0 = time.perf_counter()
    blk = stacked[:, :, :block]
    corr = np.stack([blk[e].T @ stacked[e] for e in range(N_EPOCHS)],
                    axis=1)  # [block, E, V]
    num = 1.0 + corr
    den = 1.0 - corr
    num[num <= 0] = 1e-4
    den[den <= 0] = 1e-4
    z = 0.5 * np.log(num / den)
    zr = z.reshape(block, N_EPOCHS // EPOCHS_PER_SUBJ, EPOCHS_PER_SUBJ,
                   n_voxels)
    m = zr.mean(axis=2, keepdims=True)
    var = (zr ** 2).mean(axis=2, keepdims=True) - m ** 2
    inv = np.where(var <= 0, 0.0, 1.0 / np.sqrt(np.maximum(var, 1e-30)))
    normed = ((zr - m) * inv).reshape(block, N_EPOCHS, n_voxels)
    clf = svm.SVC(kernel='precomputed', shrinking=False, C=1)
    skf = model_selection.StratifiedKFold(n_splits=NUM_FOLDS,
                                          shuffle=False)
    for v in range(block):
        k = normed[v] @ normed[v].T
        nd = len(str(int(k[0, 0])))
        if nd > 2:
            k *= 10 ** (2 - nd)
        model_selection.cross_val_score(clf, k, y=labels, cv=skf, n_jobs=1)
    dt = time.perf_counter() - t0
    return block / dt


def _ts_key(ts):
    """Chronological sort key for possibly-absent ISO timestamps with
    heterogeneous UTC offsets (lexicographic comparison is wrong across
    offsets)."""
    if not ts:
        return float("-inf")
    import datetime
    if ts.endswith("Z"):
        # fromisoformat rejects a 'Z' suffix before Python 3.11
        ts = ts[:-1] + "+00:00"
    try:
        return datetime.datetime.fromisoformat(ts).timestamp()
    except ValueError:
        return float("-inf")


def _last_onchip():
    """Most recent real-chip evidence in the repo, for transport inside
    the bench JSON line even when this run itself falls back to CPU
    (the tunnel wedges for whole rounds; see docs/performance.md).

    Sources, newest wins: ``benchmarks/TPU_MFU.json`` and
    ``benchmarks/TPU_VALIDATION.json``, both written only by scripts
    that ran on a live chip (``backend == "tpu"`` recorded inside).
    Timestamp comes from the artifact's own ``ts`` stamp when present,
    else the file's last git commit date (checkout mtime is
    meaningless).
    """
    import os
    import subprocess
    here = os.path.dirname(os.path.abspath(__file__))
    best = None
    for name, extract in (
            ("benchmarks/TPU_MFU.json",
             lambda d: d.get("end_to_end_32k", {}).get("voxels_per_s")),
            ("benchmarks/TPU_VALIDATION.json",
             lambda d: max((v.get("voxels_per_s", 0)
                            for v in d.get("end_to_end", {}).values()),
                           default=None)),
    ):
        path = os.path.join(here, name)
        if not os.path.exists(path):
            continue
        try:
            with open(path) as f:
                doc = json.load(f)
        except (OSError, ValueError):
            continue
        if doc.get("backend") != "tpu":
            continue
        vps = extract(doc)
        if not vps:
            continue
        ts = doc.get("ts")
        if ts is None:
            try:
                ts = subprocess.run(
                    ["git", "log", "-1", "--format=%cI", "--", name],
                    cwd=here, capture_output=True, text=True,
                    timeout=10).stdout.strip() or None
            except (OSError, subprocess.TimeoutExpired):
                ts = None
        if best is None or _ts_key(ts) > _ts_key(best[2]):
            best = (name, float(vps), ts)
    if best is None:
        return {}
    return {"last_onchip_voxels_per_sec": round(best[1], 1),
            "last_onchip_ts": best[2],
            "last_onchip_source": best[0]}


def _device_responsive(timeout=150):
    """Probe the accelerator in a subprocess: a wedged TPU tunnel hangs
    forever on the first dispatch (even block_until_ready is a no-op), so
    never touch the device in-process before knowing it answers."""
    import subprocess
    import sys
    code = ("import jax, jax.numpy as jnp;"
            "print(float((jnp.ones((64,64))@jnp.ones((64,64)))[0,0]))")
    try:
        r = subprocess.run([sys.executable, "-c", code], timeout=timeout,
                           capture_output=True)
        return r.returncode == 0
    except subprocess.TimeoutExpired:
        return False


def main():
    # Probe BEFORE any in-process jax backend touch: on a wedged TPU
    # tunnel even backend initialization (jax.default_backend()) hangs.
    # The tunnel sometimes un-wedges after an idle period, so a failed
    # probe is retried twice on a short schedule (fresh subprocess each
    # time per the one-process rule) before conceding the CPU fallback
    # — one wedge at the exact probe instant should not forfeit the
    # round's only driver-run perf measurement.
    responsive = _device_responsive()
    for _ in range(2):
        if responsive:
            break
        time.sleep(90)
        responsive = _device_responsive()
    import jax

    if not responsive:
        # fall back to CPU so the driver records a number instead of a
        # hung process (reduced size: the full problem takes tens of
        # minutes on CPU)
        jax.config.update("jax_platforms", "cpu")
        vps = tpu_voxels_per_sec(n_voxels=2048, unit=256)
        cpu_vps = cpu_voxels_per_sec(n_voxels=2048, block=32)
        print(json.dumps({
            "metric": "fcma_voxel_selection_voxels_per_sec_chip"
                      "_CPU_FALLBACK_tpu_unresponsive",
            "value": round(vps, 2),
            "unit": "voxels/sec",
            "vs_baseline": round(vps / cpu_vps, 2),
            **_last_onchip(),
        }))
        return
    tpu_vps = tpu_voxels_per_sec()
    cpu_vps = cpu_voxels_per_sec()
    print(json.dumps({
        "metric": "fcma_voxel_selection_voxels_per_sec_chip",
        "value": round(tpu_vps, 2),
        "unit": "voxels/sec",
        "vs_baseline": round(tpu_vps / cpu_vps, 2),
        **_last_onchip(),
    }))


if __name__ == "__main__":
    main()
