"""Benchmark harness for the five BASELINE.json configs.

Runs each config end-to-end through the public API on the current JAX
backend and prints one JSON line per config:

    {"config": ..., "seconds": ..., "detail": {...}}

Usage:
    python benchmarks/run_baselines.py [--scale small|full] [--config NAME]

``small`` (default) finishes in ~a minute on CPU for smoke-testing the
harness; ``full`` is the TPU-scale measurement.  Timing includes a final
host fetch of (small) outputs, which synchronizes device work — see
.claude/skills/verify/SKILL.md for why block_until_ready is not used.
"""

import argparse
import json
import math
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

SCALES = {
    "small": dict(srm=dict(S=4, V=2000, T=100, K=10, iters=10),
                  eventseg=dict(V=50, T=200, K=10),
                  isc=dict(S=10, T=150, V=100, boots=200, perms=200),
                  searchlight=dict(dim=16, S=4, T=20, rad=1),
                  fcma=dict(V=2048, T=100, E=8, unit=256)),
    "full": dict(srm=dict(S=20, V=40000, T=300, K=50, iters=10),
                 eventseg=dict(V=100, T=500, K=40),
                 isc=dict(S=20, T=300, V=500, boots=1000, perms=1000),
                 searchlight=dict(dim=32, S=8, T=40, rad=2),
                 fcma=dict(V=16384, T=150, E=16, unit=512)),
}


def bench_srm(S, V, T, K, iters):
    import jax
    import jax.numpy as jnp

    from brainiak_tpu.funcalign.srm import (SRM, _fit_prob_srm_jit,
                                            _stack_and_pad)

    rng = np.random.RandomState(0)
    shared = rng.randn(K, T)
    X = []
    for _ in range(S):
        q, _ = np.linalg.qr(rng.randn(V, K))
        X.append((q @ shared
                  + 0.1 * rng.randn(V, T)).astype(np.float32))
    SRM(n_iter=iters, features=K).fit(X)  # warm: identical statics
    t0 = time.perf_counter()
    model = SRM(n_iter=iters, features=K).fit(X)
    dt = time.perf_counter() - t0

    # Compute-only: the full fit re-uploads [S, V, T] and pulls the
    # [S, V, K] bases back per call — negligible on a real TPU host
    # (PCIe/ICI), dominant through a slow dev tunnel.  Pre-stage the
    # stack once and sync on the scalar log-likelihood to time the EM
    # program itself.
    dtype = np.float32
    stacked, voxel_counts, _, trace_xtx = _stack_and_pad(X, dtype)
    stacked_j = jnp.asarray(stacked)
    trace_j = jnp.asarray(trace_xtx)
    counts_j = jnp.asarray(voxel_counts).astype(dtype)
    key = jax.random.PRNGKey(0)
    out = _fit_prob_srm_jit(stacked_j, trace_j, counts_j, key,
                            features=K, n_iter=iters)
    float(out[4])  # warm + sync
    t0 = time.perf_counter()
    out = _fit_prob_srm_jit(stacked_j, trace_j, counts_j, key,
                            features=K, n_iter=iters)
    float(out[4])
    dt_compute = time.perf_counter() - t0
    return dt, {"logprob": model.logprob_, "subjects": S, "voxels": V,
                "iters": iters, "compute_only_s": round(dt_compute, 3)}


def bench_eventseg(V, T, K):
    from brainiak_tpu.eventseg.event import EventSegment

    rng = np.random.RandomState(0)
    bounds = np.sort(rng.choice(np.arange(1, T), K - 1, replace=False))
    labels = np.searchsorted(bounds, np.arange(T), side='right')
    pat = rng.randn(K, V)
    D = pat[labels] + 0.5 * rng.randn(T, V)
    EventSegment(K).fit(D)  # warm: identical shapes
    t0 = time.perf_counter()
    es = EventSegment(K).fit(D)
    dt = time.perf_counter() - t0
    found = np.argmax(es.segments_[0], axis=1)
    acc = np.mean(found == labels)
    return dt, {"boundary_accuracy": float(acc),
                "n_iters_run": int(es.ll_.shape[0])}


def bench_isc(S, T, V, boots, perms):
    from brainiak_tpu.isc import bootstrap_isc, isc, permutation_isc, \
        phaseshift_isc

    rng = np.random.RandomState(0)
    signal = rng.randn(T, V)
    data = np.dstack([signal + rng.randn(T, V) for _ in range(S)]) \
        .astype(np.float32)
    iscs = isc(data)
    # warm with identical shapes/statics so the timed region excludes
    # compilation
    bootstrap_isc(iscs, n_bootstraps=boots, random_state=0)
    permutation_isc(iscs, n_permutations=perms, random_state=0)
    phaseshift_isc(data, n_shifts=min(200, boots), random_state=0)
    t0 = time.perf_counter()
    _, _, p_b, _ = bootstrap_isc(iscs, n_bootstraps=boots,
                                 random_state=0)
    _, p_p, _ = permutation_isc(iscs, n_permutations=perms,
                                random_state=0)
    _, p_s, _ = phaseshift_isc(data, n_shifts=min(200, boots),
                               random_state=0)
    dt = time.perf_counter() - t0
    return dt, {"voxels": V, "bootstraps": boots, "permutations": perms,
                "median_p_boot": float(np.median(p_b))}


def bench_searchlight(dim, S, T, rad):
    import jax.numpy as jnp

    from brainiak_tpu.searchlight import Ball, Searchlight

    rng = np.random.RandomState(0)
    subjects = [rng.randn(dim, dim, dim, T).astype(np.float32)
                for _ in range(S)]
    mask = np.ones((dim, dim, dim), dtype=bool)
    # RSA voxel function: correlation between the neighborhood RDM of the
    # first subject and the mean RDM of the others
    half = T // 2

    def voxel_fn(patches, mpatch, myrad, bcast):
        def rdm(p):
            a = p[:, :half].mean(axis=1)
            b = p[:, half:].mean(axis=1)
            return a - b

        d0 = rdm(patches[0])
        rest = jnp.mean(jnp.stack([rdm(patches[i])
                                   for i in range(1, S)]), axis=0)
        d0 = jnp.where(mpatch, d0, 0.0)
        rest = jnp.where(mpatch, rest, 0.0)
        num = jnp.sum(d0 * rest)
        den = jnp.sqrt(jnp.sum(d0 ** 2) * jnp.sum(rest ** 2)) + 1e-12
        return num / den

    sl = Searchlight(sl_rad=rad, shape=Ball)
    sl.distribute(subjects, mask)
    sl.run_searchlight_jax(voxel_fn, batch_size=256)  # warm
    t0 = time.perf_counter()
    out = sl.run_searchlight_jax(voxel_fn, batch_size=256)
    dt = time.perf_counter() - t0
    n_centers = int(np.isfinite(out).sum())
    return dt, {"centers": n_centers,
                "centers_per_sec": n_centers / dt}


def bench_fcma(V, T, E, unit):
    from brainiak_tpu.fcma.voxelselector import VoxelSelector

    rng = np.random.RandomState(0)
    data = []
    for _ in range(E):
        mat = rng.randn(T, V).astype(np.float32)
        mat = (mat - mat.mean(0)) / (mat.std(0) * math.sqrt(T))
        data.append(mat)
    labels = [0, 1] * (E // 2)
    vs = VoxelSelector(labels, max(E // 4, 2), 2, data, voxel_unit=unit)
    vs.run('svm')  # warm compile
    t0 = time.perf_counter()
    results = vs.run('svm')
    dt = time.perf_counter() - t0
    return dt, {"voxels": V, "voxels_per_sec": V / dt,
                "top_acc": results[0][1]}


CONFIGS = {
    "srm_synthetic_fit": bench_srm,
    "eventseg_hmm_fit": bench_eventseg,
    "isc_with_nulls": bench_isc,
    "searchlight_rsa": bench_searchlight,
    "fcma_voxel_selection": bench_fcma,
}
_PARAM_KEY = {"srm_synthetic_fit": "srm", "eventseg_hmm_fit": "eventseg",
              "isc_with_nulls": "isc", "searchlight_rsa": "searchlight",
              "fcma_voxel_selection": "fcma"}


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--scale", choices=list(SCALES), default="small")
    ap.add_argument("--config", choices=list(CONFIGS), default=None)
    ap.add_argument("--backend", default=None,
                    help="force a JAX platform (e.g. cpu) — more reliable "
                         "than the env var when a sitecustomize has "
                         "already registered a TPU plugin")
    args = ap.parse_args()
    import jax
    if args.backend:
        jax.config.update("jax_platforms", args.backend)
    params = SCALES[args.scale]
    names = [args.config] if args.config else list(CONFIGS)
    backend = jax.default_backend()
    for name in names:
        seconds, detail = CONFIGS[name](**params[_PARAM_KEY[name]])
        print(json.dumps({"config": name, "backend": backend,
                          "scale": args.scale,
                          "seconds": round(seconds, 3),
                          "detail": detail}))


if __name__ == "__main__":
    main()
