"""Stage-level timing of the whole-brain SRM EM iteration on the live
accelerator: which of (big einsums | batched eigh polar | K x K
cholesky solves | full iteration) dominates wall time.

The full-scale SRM fit measured 37.3 s for S=20, V=40k, T=300, K=50,
10 iters — ~100x above both the compute and HBM rooflines measured on
the same chip (BASELINE.md), so one stage must be pathological; the
prime suspect is the [S, K, K] batched eigh (TPU lowers eigh as many
small sequential ops).  Run when a healthy chip is available:

    python benchmarks/srm_stage_timing.py [--subjects 20 --voxels 40000]
"""

import argparse
import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--subjects", type=int, default=20)
    ap.add_argument("--voxels", type=int, default=40000)
    ap.add_argument("--trs", type=int, default=300)
    ap.add_argument("--features", type=int, default=50)
    ap.add_argument("--backend", default=None)
    args = ap.parse_args()
    if args.backend:
        import jax
        jax.config.update("jax_platforms", args.backend)
    import jax
    import jax.numpy as jnp

    from brainiak_tpu.funcalign.srm import _em_iteration, _procrustes

    s, v, t, k = args.subjects, args.voxels, args.trs, args.features
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(s, v, t), jnp.float32)
    w = jnp.tile(jnp.eye(v, k, dtype=jnp.float32)[None], (s, 1, 1))
    rho2 = jnp.ones(s, jnp.float32)
    sigma_s = jnp.eye(k, dtype=jnp.float32)
    trace_xtx = jnp.sum(x * x, axis=(1, 2))
    voxel_counts = jnp.full((s,), v, jnp.float32)
    shared = jnp.asarray(rng.randn(k, t), jnp.float32)
    a_stack = jnp.einsum('svt,kt->svk', x, shared)
    gram = jnp.einsum('svi,svj->sij', a_stack, a_stack)

    def timeit(fn, *fargs, n=3):
        out = fn(*fargs)
        jax.tree_util.tree_map(
            lambda l: float(jnp.sum(l)), out)  # sync (scalar fetch)
        t0 = time.perf_counter()
        for _ in range(n):
            out = fn(*fargs)
        jax.tree_util.tree_map(lambda l: float(jnp.sum(l)), out)
        return (time.perf_counter() - t0) / n

    hp = jax.lax.Precision.HIGHEST
    stages = {}
    stages["einsum_wtx [S,V,K]x[S,V,T]->KT"] = timeit(
        jax.jit(lambda w_, x_: jnp.einsum('svk,svt->kt', w_, x_,
                                          precision=hp)), w, x)
    stages["einsum_a [S,V,T]x[K,T]->SVK"] = timeit(
        jax.jit(lambda x_, sh: jnp.einsum('svt,kt->svk', x_, sh,
                                          precision=hp)), x, shared)
    stages["batched_eigh [S,K,K]"] = timeit(
        jax.jit(lambda g: jnp.linalg.eigh(g)[1]), gram)
    stages["batched_procrustes (eigh+NS)"] = timeit(
        jax.jit(jax.vmap(_procrustes)), a_stack)
    from brainiak_tpu.funcalign.srm import _polar_ns
    stages["batched_polar_ns (matmul-only)"] = timeit(
        jax.jit(jax.vmap(_polar_ns)), a_stack)
    stages["cho_factor+solve KxK"] = timeit(
        jax.jit(lambda m: jax.scipy.linalg.cho_solve(
            jax.scipy.linalg.cho_factor(m + jnp.eye(k)),
            jnp.eye(k))), sigma_s)
    stages["full_em_iteration"] = timeit(
        jax.jit(lambda *a_: _em_iteration(*a_, t),
                static_argnums=()), x, w, rho2, sigma_s, trace_xtx,
        voxel_counts)

    for name, dt in stages.items():
        print(f"{name:42s} {dt * 1e3:9.1f} ms")
    print(json.dumps({"metric": "srm_stage_timing",
                      "stages_ms": {n: round(dt * 1e3, 1)
                                    for n, dt in stages.items()}}))


if __name__ == "__main__":
    main()
