"""One-command on-chip evidence capture for a round.

Runs, in order and each in its OWN subprocess (one chip process at a
time, sized well inside its timeout — docs/performance.md operational
rules):

1. probe          — 64x64 matmul in a subprocess; abort if wedged
2. tpu_validation — kernel parity + end-to-end VoxelSelector
                    (refreshes benchmarks/TPU_VALIDATION.json with ts)
3. tpu_mfu        — whole-brain MFU sweep (V>=32k, E>=32, fp32/bf16,
                    XLA-vs-Pallas production stage)
                    (writes benchmarks/TPU_MFU.json)
4. bench.py       — the driver's headline metric
5. srm timing     — benchmarks/srm_stage_timing.py compute-only split

A probe runs BETWEEN steps; the first wedge stops the sequence (later
steps would hang and the timeout kill could deepen the wedge).  Exit
code 0 iff at least steps 1-4 completed.
"""

import os
import subprocess
import sys
import time

HERE = os.path.dirname(os.path.abspath(__file__))
REPO = os.path.dirname(HERE)
sys.path.insert(0, REPO)

from bench import _device_responsive as probe  # noqa: E402

STEPS = [
    ("tpu_validation", [sys.executable,
                        os.path.join(HERE, "tpu_validation.py")], 900),
    ("tpu_mfu", [sys.executable, os.path.join(HERE, "tpu_mfu.py")],
     1500),
    # generous ceiling: bench.py manages its own chip-tier subprocess
    # timeouts internally (whole-brain ~8 min healthy + mid tier +
    # probes + a minutes-long CPU fallback); this outer timeout only
    # guards against bench.py's own orchestration hanging, and killing
    # at this level never lands mid-dispatch because the chip work all
    # happens in bench.py's children, which it reaps itself
    ("bench", [sys.executable, os.path.join(REPO, "bench.py")], 3000),
    ("srm_stage_timing", [sys.executable,
                          os.path.join(HERE, "srm_stage_timing.py")],
     900),
]


def main():
    if not probe():
        print("chip unresponsive at start; aborting", file=sys.stderr)
        return 1
    done = 0
    for name, cmd, step_timeout in STEPS:
        t0 = time.time()
        print(f"== {name} ==", file=sys.stderr)
        try:
            r = subprocess.run(cmd, timeout=step_timeout)
        except subprocess.TimeoutExpired:
            print(f"{name}: TIMED OUT after {step_timeout}s — chip "
                  "likely wedged; stopping", file=sys.stderr)
            break
        print(f"{name}: rc={r.returncode} in {time.time() - t0:.0f}s",
              file=sys.stderr)
        if r.returncode != 0:
            break
        done += 1
        if not probe():
            print(f"chip wedged after {name}; stopping", file=sys.stderr)
            break
    print(f"{done}/{len(STEPS)} steps completed", file=sys.stderr)
    return 0 if done >= 3 else 1


if __name__ == "__main__":
    sys.exit(main())
