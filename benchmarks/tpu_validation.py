"""Real-TPU validation of the fused Pallas FCMA kernels + precision knob.

Round-1 verdict items 3 and 4: every Pallas run to date was interpreter
mode on CPU, and the ``precision='high'`` knob was implemented but never
measured.  This script runs on a real TPU chip and records:

1. **Compile-mode parity**: ``fcma_corr_normalize`` / ``fcma_gram`` /
   ``fcma_sample_gram`` compiled (interpret=False) vs the XLA einsum path,
   max |delta| at fp32 tolerance.  Target semantics: reference
   ``fcma/src/fcma_extension.cc:29-92`` + ``fcma/cython_blas.pyx:20-115``.
2. **Throughput**: compiled-Pallas vs XLA-path voxels/sec on the same
   block shapes, plus end-to-end ``VoxelSelector(use_pallas=True/False)``.
3. **Precision sweep**: ``precision='highest'`` vs ``'high'`` — throughput
   and per-voxel CV-accuracy deltas against the 'highest' accuracies
   (the reference accuracy band check lives in
   tests/fcma/test_voxel_selection.py).

Each dispatch stays at a few hundred ms (wedge-safe).  Writes one JSON
artifact to ``benchmarks/TPU_VALIDATION.json`` and prints a summary.
"""

import json
import math
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

N_VOXELS = 8192
N_BLOCK = 256
N_TRS = 150
N_EPOCHS = 16
EPOCHS_PER_SUBJ = 4
NUM_FOLDS = 4

# --smoke: interpret-mode Pallas at toy shapes on CPU — validates the
# harness end to end (imports, call signatures, JSON assembly) without
# a chip, so the one healthy-chip window is never spent debugging this
# script.  Writes no artifact.
INTERPRET = False


def _fetch(x):
    """Host fetch: synchronizes on the tunneled TPU platform (where
    block_until_ready is a no-op)."""
    import jax
    return jax.tree.map(np.asarray, x)


def make_epoch_data(n_voxels, n_trs=None, n_epochs=None, seed=0):
    # None -> module globals at CALL time (def-time defaults would pin
    # the pre---smoke sizes)
    n_trs = N_TRS if n_trs is None else n_trs
    n_epochs = N_EPOCHS if n_epochs is None else n_epochs
    rng = np.random.RandomState(seed)
    data = []
    for _ in range(n_epochs):
        mat = rng.randn(n_trs, n_voxels).astype(np.float32)
        mat = (mat - mat.mean(0)) / (mat.std(0) * math.sqrt(n_trs))
        data.append(mat)
    return np.stack(data)  # [E, T, V]


def time_call(fn, *args, repeats=5, **kw):
    """Amortized timing: one warm (compile) fetch, then ``repeats``
    dispatches with a single trailing fetch — the tunnel round-trip is
    paid once, not per repeat (block_until_ready is a no-op here)."""
    out = fn(*args, **kw)
    _fetch(out)  # warm: compile + first run
    t0 = time.perf_counter()
    for _ in range(repeats):
        out = fn(*args, **kw)
    _fetch(out)
    return out, (time.perf_counter() - t0) / repeats


def kernel_parity_and_throughput():
    """Compiled-Pallas vs XLA on the exact production block helpers
    (tile picking + padding included)."""
    import jax.numpy as jnp

    from brainiak_tpu.fcma.voxelselector import (
        _block_gram_pallas, _block_gram_xla, _block_kernel_matrices,
        _block_kernel_matrices_pallas)
    from brainiak_tpu.ops.pallas_kernels import fcma_sample_gram

    data = jnp.asarray(make_epoch_data(N_VOXELS))   # [E, T, V]
    blk = data[:, :, :N_BLOCK]
    res = {}

    # --- corr + normalize (+ per-voxel Gram): full kernel-matrices path
    (ref_k, ref_c), t_xla = time_call(_block_kernel_matrices, blk, data,
                                      EPOCHS_PER_SUBJ)
    (out_k, out_c), t_pal = time_call(_block_kernel_matrices_pallas,
                                      blk, data, EPOCHS_PER_SUBJ,
                                      interpret=INTERPRET)
    delta = float(jnp.max(jnp.abs(out_c - ref_c)))
    res["corr_normalize"] = {
        "max_abs_delta_corr": delta,
        "max_abs_delta_gram": float(jnp.max(jnp.abs(out_k - ref_k))),
        "xla_s": round(t_xla, 4), "pallas_s": round(t_pal, 4),
        "pallas_speedup": round(t_xla / t_pal, 2),
        "voxel_pairs_per_s_pallas": round(N_BLOCK * N_VOXELS / t_pal),
    }

    # --- fused Gram-only reduction (corr tensor never reaches HBM) ---
    ref_g, t_xla_g = time_call(_block_gram_xla, blk, data,
                               EPOCHS_PER_SUBJ)
    out_g, t_pal_g = time_call(_block_gram_pallas, blk, data,
                               EPOCHS_PER_SUBJ, interpret=INTERPRET)
    scale = float(jnp.max(jnp.abs(ref_g)))
    delta_g = float(jnp.max(jnp.abs(out_g - ref_g))) / scale
    res["gram"] = {
        "max_rel_delta": delta_g,
        "xla_s": round(t_xla_g, 4), "pallas_s": round(t_pal_g, 4),
        "pallas_speedup": round(t_xla_g / t_pal_g, 2),
    }

    # --- fcma_sample_gram (classifier feature Gram) ---
    n_samples, v1, v2 = 16, min(1024, N_VOXELS), N_VOXELS
    x1 = jnp.asarray(make_epoch_data(v1, n_epochs=n_samples, seed=1))
    x2 = jnp.asarray(make_epoch_data(v2, n_epochs=n_samples, seed=2))

    import jax

    from brainiak_tpu.ops.fisherz import within_subject_normalization

    @jax.jit
    def xla_sample_gram(x1, x2):
        corr = jnp.einsum("ntb,ntv->bnv", x1, x2,
                          preferred_element_type=jnp.float32,
                          precision=jax.lax.Precision.HIGHEST)
        z = within_subject_normalization(corr, EPOCHS_PER_SUBJ)
        zt = jnp.swapaxes(z, 0, 1).reshape(n_samples, -1)
        return zt @ zt.T

    ref_s, t_xla_s = time_call(xla_sample_gram, x1, x2)
    out_s, t_pal_s = time_call(fcma_sample_gram, x1, x2,
                               EPOCHS_PER_SUBJ, interpret=INTERPRET)
    scale_s = float(jnp.max(jnp.abs(ref_s)))
    delta_s = float(jnp.max(jnp.abs(out_s - ref_s))) / scale_s
    res["sample_gram"] = {
        "max_rel_delta": delta_s,
        "xla_s": round(t_xla_s, 4), "pallas_s": round(t_pal_s, 4),
        "pallas_speedup": round(t_xla_s / t_pal_s, 2),
    }
    return res


def end_to_end(n_voxels=None, unit=512):
    """VoxelSelector end-to-end: pallas vs xla, precision sweep."""
    from brainiak_tpu.fcma.voxelselector import VoxelSelector

    n_voxels = N_VOXELS if n_voxels is None else n_voxels
    unit = min(unit, n_voxels)
    data = list(make_epoch_data(n_voxels))
    labels = [0, 1] * (N_EPOCHS // 2)
    res = {}
    accs = {}
    for name, kw in [
            ("xla_highest", dict(use_pallas=False, precision="highest")),
            ("pallas_highest", dict(use_pallas=True, precision="highest")),
            ("xla_high", dict(use_pallas=False, precision="high")),
            ("pallas_high", dict(use_pallas=True, precision="high"))]:
        vs = VoxelSelector(labels, EPOCHS_PER_SUBJ, NUM_FOLDS, data,
                           voxel_unit=unit, **kw)
        vs.run("svm")  # warm compile caches
        t0 = time.perf_counter()
        results = vs.run("svm")
        dt = time.perf_counter() - t0
        accs[name] = dict(results)
        res[name] = {"voxels_per_s": round(n_voxels / dt, 1),
                     "seconds": round(dt, 2)}

    base = accs["xla_highest"]
    for name in ("pallas_highest", "xla_high", "pallas_high"):
        deltas = [abs(accs[name][v] - base[v]) for v in base]
        res[name]["max_acc_delta_vs_xla_highest"] = round(max(deltas), 4)
        res[name]["mean_acc_delta"] = round(float(np.mean(deltas)), 5)
    return res


def main():
    import argparse
    import datetime

    ap = argparse.ArgumentParser()
    ap.add_argument("--smoke", action="store_true",
                    help="tiny shapes, interpret-mode Pallas, CPU: "
                         "validates the harness without a chip; "
                         "writes no artifact")
    args = ap.parse_args()

    import jax
    if args.smoke:
        global N_VOXELS, N_BLOCK, N_TRS, N_EPOCHS, INTERPRET
        jax.config.update("jax_platforms", "cpu")
        N_VOXELS, N_BLOCK, N_TRS, N_EPOCHS = 512, 64, 40, 8
        INTERPRET = True

    backend = jax.default_backend()
    out = {"backend": backend,
           "ts": datetime.datetime.now(datetime.timezone.utc)
                 .isoformat(timespec="seconds"),
           "n_voxels": N_VOXELS, "n_trs": N_TRS,
           "n_epochs": N_EPOCHS}
    print(f"backend: {backend}", file=sys.stderr)
    out["kernels"] = kernel_parity_and_throughput()
    print(json.dumps(out["kernels"], indent=2), file=sys.stderr)
    out["end_to_end"] = end_to_end()
    if args.smoke:
        print(json.dumps(out, indent=2))
        return
    path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "TPU_VALIDATION.json")
    with open(path, "w") as f:
        json.dump(out, f, indent=2)
    print(json.dumps(out, indent=2))


if __name__ == "__main__":
    main()
