"""Whole-brain FCMA MFU measurement on a real TPU chip.

Round-2 verdict item 2: the 8192-voxel bench runs the chip at ~1% MFU
end-to-end and no MFU number exists anywhere.  This script measures, at
whole-brain scale (V up to 32-64k, E >= 32):

1. the raw epoch-batched correlation einsum (the FLOP carrier,
   reference hot kernel ``fcma/cython_blas.pyx:115-116``) in fp32
   HIGHEST, fp32 'default' (bf16 MXU passes), and at higher
   arithmetic intensity (longer T);
2. the full production block stage (corr + Fisher-z normalize +
   per-voxel Gram), XLA vs compiled Pallas — the first large-V test of
   the fused kernel's HBM-intermediate argument;
3. end-to-end ``VoxelSelector.run('svm')`` with the deferred batched
   CV, reporting voxels/s and effective TFLOP/s.

Every timed dispatch is sized to finish in at most a few seconds
(wedge-safe: docs/performance.md operational rules), inputs are
GENERATED ON DEVICE (no 600 MB crawl through the ~15 MB/s tunnel), and
timing fetches a scalar to synchronize (block_until_ready is a no-op on
the tunneled platform).

MFU is reported against two cielings:
- ``peak_bf16`` = 197 TFLOP/s (TPU v5e MXU nominal);
- ``peak_fp32_highest`` = 197/6 TFLOP/s (each fp32 HIGHEST dot runs ~6
  bf16 passes — 3 products x fp32 accumulate splitting).

Writes ``benchmarks/TPU_MFU.json``.
"""

import json
import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

PEAK_BF16 = 197e12
PEAK_FP32_HIGHEST = PEAK_BF16 / 6.0

N_TRS = 150
EPOCHS_PER_SUBJ = 4
NUM_FOLDS = 4


def _sync(x):
    """Fetch one scalar per output leaf to synchronize (tunnel-safe)."""
    import jax
    import jax.numpy as jnp
    return [float(jnp.sum(leaf).astype(jnp.float32))
            for leaf in jax.tree.leaves(x)]


def device_epoch_data(n_voxels, n_trs, n_epochs, seed=0):
    """[E, T, V] epoch-normalized data generated ON DEVICE."""
    import jax
    import jax.numpy as jnp

    @jax.jit
    def make(key):
        x = jax.random.normal(key, (n_epochs, n_trs, n_voxels),
                              jnp.float32)
        x = (x - x.mean(1, keepdims=True)) / (
            x.std(1, keepdims=True) * jnp.sqrt(float(n_trs)))
        return x

    data = make(jax.random.PRNGKey(seed))
    _sync(data)
    return data


def time_dispatch(fn, *args, repeats=3):
    """Warm once (compile), then average ``repeats`` dispatches with one
    trailing scalar fetch."""
    out = fn(*args)
    _sync(out)
    t0 = time.perf_counter()
    for _ in range(repeats):
        out = fn(*args)
    _sync(out)
    return (time.perf_counter() - t0) / repeats


CORR_CONFIGS = [
    # name, V, T, E, B, precision
    ("bench_parity_fp32_highest", 8192, 150, 16, 512, "highest"),
    ("wholebrain_fp32_highest", 32768, 150, 32, 512, "highest"),
    ("wholebrain_bf16_default", 32768, 150, 32, 512, "default"),
    ("wholebrain_long_t_fp32", 32768, 450, 32, 512, "highest"),
    ("wholebrain_long_t_bf16", 32768, 450, 32, 512, "default"),
    ("wholebrain64k_bf16", 65536, 150, 32, 256, "default"),
]

SMOKE_CONFIGS = [
    ("smoke_fp32_highest", 1024, 50, 8, 128, "highest"),
    ("smoke_bf16_default", 1024, 50, 8, 128, "default"),
]


def corr_stage_configs():
    """Raw correlation einsum across scale/precision/intensity."""
    import jax
    import jax.numpy as jnp

    res = []
    for name, v, t, e, b, prec in CORR_CONFIGS:
        data = device_epoch_data(v, t, e, seed=1)
        blk = data[:, :, :b]
        precision = (jax.lax.Precision.HIGHEST if prec == "highest"
                     else jax.lax.Precision.DEFAULT)

        @jax.jit
        def corr(blk, data):
            return jnp.einsum("etb,etv->bev", blk, data,
                              precision=precision,
                              preferred_element_type=jnp.float32)

        dt = time_dispatch(corr, blk, data)
        flops = 2.0 * b * v * t * e
        tflops = flops / dt / 1e12
        peak = PEAK_BF16 if prec == "default" else PEAK_FP32_HIGHEST
        res.append({
            "config": name, "V": v, "T": t, "E": e, "block": b,
            "precision": prec, "seconds_per_block": round(dt, 4),
            "effective_tflops": round(tflops, 2),
            "mfu_vs_bf16_peak_pct": round(100 * flops / dt / PEAK_BF16,
                                          2),
            "mfu_vs_precision_peak_pct": round(
                100 * flops / dt / peak, 2),
            "extrapolated_wholebrain_corr_s": round(
                dt * (v / b), 2),
        })
        print(f"  corr {name}: {tflops:.2f} TFLOP/s "
              f"({res[-1]['mfu_vs_precision_peak_pct']}% of "
              f"precision peak)", file=sys.stderr)
        del data, blk
    return res


def production_stage_large_v(v=32768, e=32, b=512, with_pallas=True):
    """Full block stage (corr+normalize+Gram): XLA vs Pallas at large V
    — the regime the fused kernel's HBM argument targets."""
    from brainiak_tpu.fcma.voxelselector import (
        _block_kernel_matrices, _block_kernel_matrices_pallas)

    data = device_epoch_data(v, N_TRS, e, seed=2)
    blk = data[:, :, :b]
    res = {}
    t_xla = time_dispatch(
        lambda bk, d: _block_kernel_matrices(bk, d, EPOCHS_PER_SUBJ),
        blk, data)
    flops = 2.0 * b * v * N_TRS * e
    res["V"] = v
    res["E"] = e
    res["block"] = b
    res["xla_s_per_block"] = round(t_xla, 4)
    res["xla_corr_stage_tflops"] = round(flops / t_xla / 1e12, 2)
    res["xla_mfu_vs_fp32_highest_peak_pct"] = round(
        100 * flops / t_xla / PEAK_FP32_HIGHEST, 2)
    if with_pallas:  # compiled Pallas needs a real TPU backend
        t_pal = time_dispatch(
            lambda bk, d: _block_kernel_matrices_pallas(
                bk, d, EPOCHS_PER_SUBJ),
            blk, data)
        res["pallas_s_per_block"] = round(t_pal, 4)
        res["pallas_speedup"] = round(t_xla / t_pal, 3)
        res["pallas_corr_stage_tflops"] = round(flops / t_pal / 1e12,
                                                2)
        res["pallas_mfu_vs_fp32_highest_peak_pct"] = round(
            100 * flops / t_pal / PEAK_FP32_HIGHEST, 2)
        print(f"  stage V={v}: xla {t_xla:.3f}s  pallas {t_pal:.3f}s "
              f"({res['pallas_speedup']}x)", file=sys.stderr)
    else:
        print(f"  stage V={v}: xla {t_xla:.3f}s (pallas skipped)",
              file=sys.stderr)
    return res


def end_to_end_wholebrain(v=32768, e=32, unit=1024):
    """VoxelSelector.run('svm') at whole-brain V: voxels/s, effective
    TFLOP/s (correlation FLOPs / end-to-end time), and MFU."""
    import math

    from brainiak_tpu.fcma.voxelselector import VoxelSelector

    rng = np.random.RandomState(0)
    data = []
    for _ in range(e):
        mat = rng.randn(N_TRS, v).astype(np.float32)
        mat = (mat - mat.mean(0)) / (mat.std(0) * math.sqrt(N_TRS))
        data.append(mat)
    labels = [0, 1] * (e // 2)
    vs = VoxelSelector(labels, EPOCHS_PER_SUBJ, NUM_FOLDS, data,
                       voxel_unit=unit)
    t_up0 = time.perf_counter()
    results = vs.run("svm")  # warm: upload + compile + first run
    warm_s = time.perf_counter() - t_up0
    assert len(results) == v
    t0 = time.perf_counter()
    results = vs.run("svm")
    dt = time.perf_counter() - t0
    flops = 2.0 * float(v) * v * N_TRS * e
    return {
        "V": v, "E": e, "voxel_unit": unit,
        "warm_first_run_s": round(warm_s, 2),
        "seconds": round(dt, 2),
        "voxels_per_s": round(v / dt, 1),
        "corr_flops": flops,
        "effective_tflops_end_to_end": round(flops / dt / 1e12, 2),
        "mfu_end_to_end_vs_fp32_highest_peak_pct": round(
            100 * flops / dt / PEAK_FP32_HIGHEST, 2),
    }


def main():
    import argparse

    ap = argparse.ArgumentParser()
    ap.add_argument("--smoke", action="store_true",
                    help="tiny shapes on CPU: validates the harness "
                         "without a chip; writes no artifact")
    args = ap.parse_args()

    import jax
    if args.smoke:
        jax.config.update("jax_platforms", "cpu")
        CORR_CONFIGS[:] = SMOKE_CONFIGS

    backend = jax.default_backend()
    print(f"backend: {backend}", file=sys.stderr)
    import datetime
    out = {"backend": backend,
           "ts": datetime.datetime.now(datetime.timezone.utc)
                 .isoformat(timespec="seconds"),
           "peak_bf16_tflops": PEAK_BF16 / 1e12,
           "peak_fp32_highest_tflops": round(PEAK_FP32_HIGHEST / 1e12,
                                             1)}
    out["corr_stage"] = corr_stage_configs()
    if args.smoke:
        out["production_stage_32k"] = production_stage_large_v(
            v=1024, e=8, b=128, with_pallas=False)
        out["end_to_end_32k"] = end_to_end_wholebrain(v=1024, e=8,
                                                      unit=256)
        print(json.dumps(out, indent=1))
        return
    out["production_stage_32k"] = production_stage_large_v()
    out["end_to_end_32k"] = end_to_end_wholebrain()
    path = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                        "TPU_MFU.json")
    with open(path, "w", encoding="utf-8") as f:
        json.dump(out, f, indent=1)
    print(json.dumps(out, indent=1))


if __name__ == "__main__":
    main()
