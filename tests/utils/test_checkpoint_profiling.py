import time

import numpy as np

from brainiak_tpu.obs import (
    reset_stage_times,
    stage_timer,
    stage_times,
)
from brainiak_tpu.utils.checkpoint import CheckpointManager


def test_checkpoint_roundtrip(tmp_path):
    mngr = CheckpointManager(str(tmp_path / "ckpts"))
    assert mngr.latest_step() is None
    state = {"a": np.arange(6.0).reshape(2, 3), "b": np.float64(3.5)}
    mngr.save(2, state)
    mngr.save(5, {"a": state["a"] * 2, "b": np.float64(7.0)})
    assert mngr.latest_step() == 5
    step, restored = mngr.restore(template=state)
    assert step == 5
    assert np.allclose(np.asarray(restored["a"]), state["a"] * 2)
    step2, restored2 = mngr.restore(step=2, template=state)
    assert step2 == 2
    assert np.allclose(np.asarray(restored2["a"]), state["a"])


def _npz_fallback_manager(path, **kwargs):
    """Build a CheckpointManager forced onto the npz fallback path."""
    mngr = CheckpointManager(str(path), **kwargs)
    mngr._ocp = None
    mngr._mngr = None
    return mngr


def test_npz_fallback_roundtrip_and_pruning(tmp_path):
    mngr = _npz_fallback_manager(tmp_path / "ckpts", max_to_keep=2)
    state = {"a": np.arange(6.0).reshape(2, 3), "b": np.float64(3.5)}
    for step in (1, 2, 3):
        mngr.save(step, {"a": state["a"] * step, "b": state["b"]})
    # max_to_keep=2: step 1 pruned, 2 and 3 survive.
    kept = sorted(f for f in (tmp_path / "ckpts").iterdir())
    assert [f.name for f in kept] == ["ckpt_2.npz", "ckpt_3.npz"]
    assert mngr.latest_step() == 3
    step, restored = mngr.restore()
    assert step == 3
    assert np.allclose(restored["a"], state["a"] * 3)
    step2, restored2 = mngr.restore(step=2)
    assert np.allclose(restored2["a"], state["a"] * 2)


def test_npz_fallback_edges(tmp_path):
    # keep-everything (max_to_keep=None, orbax convention)
    mngr = _npz_fallback_manager(tmp_path / "all", max_to_keep=None)
    for step in (1, 2, 3):
        mngr.save(step, {"x": np.ones(2) * step})
    assert len(list((tmp_path / "all").iterdir())) == 3
    # empty directory: restore reports nothing rather than raising
    empty = _npz_fallback_manager(tmp_path / "none")
    assert empty.latest_step() is None
    assert empty.restore() == (None, None)
    # stray files that look almost like checkpoints are ignored
    (tmp_path / "none" / "ckpt_abc.npz").write_bytes(b"junk")
    (tmp_path / "none" / "notes.txt").write_text("hi")
    assert empty.latest_step() is None


def test_stage_timer():
    reset_stage_times()
    with stage_timer("stage_a"):
        time.sleep(0.01)
    with stage_timer("stage_a"):
        time.sleep(0.01)
    times = stage_times()
    assert len(times["stage_a"]) == 2
    assert all(t >= 0.01 for t in times["stage_a"])
    reset_stage_times()
    assert stage_times() == {}


def test_stage_timer_sync_target():
    """The sync branch blocks on the device value before stopping the
    clock — both via the ``sync=`` argument and via a holder assigned
    inside the block (the pattern for values created mid-stage)."""
    import jax.numpy as jnp

    reset_stage_times()
    x = jnp.ones((64, 64))
    with stage_timer("stage_sync", sync=x):
        y = x @ x
    with stage_timer("stage_sync") as holder:
        holder["sync"] = {"out": x @ x}  # pytree target
    times = stage_times()
    assert len(times["stage_sync"]) == 2
    assert all(t > 0 for t in times["stage_sync"])
    assert float(y[0, 0]) == 64.0
    reset_stage_times()


def test_profiling_shim_warns_and_still_works():
    """The utils.profiling shim emits a DeprecationWarning pointing
    at brainiak_tpu.obs on import, and keeps re-exporting the legacy
    names (PR 5 satellite)."""
    import importlib
    import sys

    import pytest

    sys.modules.pop("brainiak_tpu.utils.profiling", None)
    with pytest.warns(DeprecationWarning, match="brainiak_tpu.obs"):
        shim = importlib.import_module(
            "brainiak_tpu.utils.profiling")
    assert shim.stage_timer is stage_timer
    assert shim.stage_times is stage_times
    assert shim.reset_stage_times is reset_stage_times
