import math

import numpy as np
import pytest

import brainiak_tpu.utils.fmrisim as sim


def test_generate_signal():
    dimensions = np.array([10, 10, 10])
    volume = sim.generate_signal(dimensions=dimensions,
                                 feature_coordinates=np.array([[5, 5, 5]]),
                                 feature_type=['cube'],
                                 feature_size=[3],
                                 signal_magnitude=[30])
    assert np.all(volume.shape == dimensions)
    assert np.max(volume) == 30
    assert np.sum(volume > 0) == math.pow(3, 3)
    assert volume[5, 5, 5] == 30
    assert volume[5, 5, 1] == 0

    coords = np.array([[5, 5, 5], [3, 3, 3], [7, 7, 7]])
    volume = sim.generate_signal(dimensions=dimensions,
                                 feature_coordinates=coords,
                                 feature_type=['loop', 'cavity', 'sphere'],
                                 feature_size=[3],
                                 signal_magnitude=[30])
    assert volume[5, 5, 5] == 0, "Loop is empty"
    assert volume[3, 3, 3] == 0, "Cavity is empty"
    assert volume[7, 7, 7] != 0, "Sphere is not empty"

    # out-of-bounds corrections
    x, y, z = sim._insert_idxs(np.array([0, 2, 10]), 3, dimensions)
    assert x[1] - x[0] == 2
    assert y[1] - y[0] == 3
    assert z[1] - z[0] == 1

    # random patterns
    volume = sim.generate_signal(dimensions=dimensions,
                                 feature_coordinates=np.array([[5, 5, 5]]),
                                 feature_type=['cube'],
                                 feature_size=[3],
                                 signal_magnitude=[30],
                                 signal_constant=0)
    assert volume[4:7, 4:7, 4:7].std() > 0


def test_generate_stimfunction_and_convolve(tmp_path):
    onsets = [10, 30, 50, 70, 90]
    stimfunction = sim.generate_stimfunction(onsets=onsets,
                                             event_durations=[6],
                                             total_time=100)
    assert stimfunction.shape[0] == 100 * 100
    assert np.sum(stimfunction) == 6 * len(onsets) * 100

    signal_function = sim.convolve_hrf(stimfunction=stimfunction,
                                       tr_duration=2)
    assert signal_function.shape[0] == 50

    # HRF has ~30 s support and an undershoot
    stimfunction1 = sim.generate_stimfunction(onsets=[0],
                                              event_durations=[1],
                                              total_time=100)
    sf = sim.convolve_hrf(stimfunction=stimfunction1, tr_duration=1)
    max_response = np.where(sf != 0)[0].max()
    assert 25 < max_response <= 30
    assert np.sum(sf < 0) > 0

    # export / import round trip
    path = str(tmp_path / "timing.txt")
    sim.export_3_column(stimfunction, path)
    stimfunc_new = sim.generate_stimfunction(onsets=None,
                                             event_durations=None,
                                             total_time=100,
                                             timing_file=path)
    assert np.all(stimfunc_new == stimfunction)

    with pytest.raises(ValueError):
        sim.generate_stimfunction(onsets=onsets, event_durations=[5],
                                  total_time=89)

    # epoch-file export
    cond_a = sim.generate_stimfunction(onsets=onsets, event_durations=[5],
                                       total_time=110)
    cond_b = sim.generate_stimfunction(onsets=[x + 5 for x in onsets],
                                       event_durations=[5],
                                       total_time=110)
    group = [np.hstack((cond_a, cond_b))] * 2
    epoch_path = str(tmp_path / "epochs.npy")
    sim.export_epoch_file(group, epoch_path, 2)
    epochs = np.load(epoch_path, allow_pickle=True)
    assert len(epochs) == 2
    assert epochs[0].shape[0] == 2  # conditions
    assert epochs[0].shape[1] >= 5  # epochs

    # same-shaped subjects must save as a PLAIN array readable by
    # io.load_labels (allow_pickle=False, as in the reference io.py:148)
    # — regression: dtype=object was forced unconditionally once
    from brainiak_tpu.io import load_labels
    specs = load_labels(epoch_path)
    assert len(specs) == 2
    assert specs[0].shape == epochs[0].shape

    # genuinely ragged subjects still export (pickled object form)
    ragged = [np.hstack((cond_a, cond_b)),
              np.hstack((cond_a[:5500], cond_b[:5500]))]
    ragged_path = str(tmp_path / "epochs_ragged.npy")
    sim.export_epoch_file(ragged, ragged_path, 2)
    loaded = np.load(ragged_path, allow_pickle=True)
    assert len(loaded) == 2 and loaded[0].shape != loaded[1].shape


def test_apply_signal_and_compute_signal_change():
    np.random.seed(0)
    dimensions = np.array([10, 10, 10])
    volume = sim.generate_signal(dimensions=dimensions,
                                 feature_coordinates=np.array([[5, 5, 5]]),
                                 feature_type=['cube'],
                                 feature_size=[2],
                                 signal_magnitude=[30])
    stimfunction = sim.generate_stimfunction(onsets=[10, 30, 50, 70, 90],
                                             event_durations=[6],
                                             total_time=100)
    signal_function = sim.convolve_hrf(stimfunction=stimfunction,
                                       tr_duration=2)
    stimfunction_tr = stimfunction[::200]
    mask, template = sim.mask_brain(dimensions, mask_self=False)
    noise_dict = sim._noise_dict_update({})
    noise = sim.generate_noise(dimensions=dimensions,
                               stimfunction_tr=stimfunction_tr,
                               tr_duration=2,
                               template=template,
                               mask=mask,
                               noise_dict=noise_dict,
                               iterations=[0, 0])
    nf = noise[5, 5, 5, :].reshape(50, 1)

    with pytest.raises(ValueError):
        sim.compute_signal_change(signal_function, nf.T, noise_dict,
                                  [0.5], 'PSC')

    # all methods scale linearly in magnitude
    for method in ['PSC', 'SFNR', 'CNR_Amp/Noise-SD',
                   'CNR_Signal-SD/Noise-SD']:
        sig_a = sim.compute_signal_change(signal_function, nf, noise_dict,
                                          [0.5], method)
        sig_b = sim.compute_signal_change(signal_function, nf, noise_dict,
                                          [1.0], method)
        assert np.isclose(sig_b.max() / sig_a.max(), 2), method

    # every method against its hand-computed formula (reference
    # fmrisim.py:3185-3270): the dB methods' 10^(mag/20) exponent and
    # the SD-ratio normalizations are easy to drift silently
    sig = np.asarray(signal_function, dtype=float)
    sig_n = sig / np.max(np.abs(sig))
    noise_col = nf[:, 0]
    max_amp = np.max(np.abs(sig_n[:, 0]))
    mag = 0.7
    expectations = {
        'SFNR': sig_n * (noise_col.mean() / noise_dict['sfnr']) * mag,
        'CNR_Amp/Noise-SD': sig_n * mag * np.std(noise_col),
        'CNR_Amp2/Noise-Var_dB':
            sig_n * (10 ** (mag / 20)) * np.std(noise_col) / max_amp,
        'CNR_Signal-SD/Noise-SD':
            sig_n * (mag / max_amp) * np.std(noise_col)
            / np.std(sig_n[:, 0]),
        'CNR_Signal-Var/Noise-Var_dB':
            sig_n * (10 ** (mag / 20)) * np.std(noise_col)
            / (max_amp * np.std(sig_n[:, 0])),
        'PSC': sig_n * (noise_col.mean() / 100) * mag,
    }
    for method, want in expectations.items():
        got = sim.compute_signal_change(signal_function, nf, noise_dict,
                                        [mag], method)
        np.testing.assert_allclose(got, want, rtol=1e-12,
                                   err_msg=method)
    with pytest.raises(ValueError, match="method"):
        sim.compute_signal_change(signal_function, nf, noise_dict,
                                  [mag], 'Z-score')

    signal = sim.apply_signal(signal_function=signal_function,
                              volume_signal=volume)
    assert signal.shape == (10, 10, 10, 50)
    signal = sim.apply_signal(signal_function=stimfunction,
                              volume_signal=volume)
    assert np.any(signal == 30)

    with pytest.raises(IndexError):
        sig_vox = (volume > 0).sum()
        vox_pattern = np.tile(stimfunction, (1, sig_vox - 1))
        sim.apply_signal(signal_function=vox_pattern, volume_signal=volume)


def test_generate_noise_properties():
    np.random.seed(1)
    dimensions = np.array([10, 10, 10])
    stimfunction = sim.generate_stimfunction(onsets=[10, 30, 50, 70, 90],
                                             event_durations=[6],
                                             total_time=200)
    stimfunction_tr = stimfunction[::200]
    mask, template = sim.mask_brain(dimensions, mask_self=False)
    noise_dict = sim._noise_dict_update({'sfnr': 90, 'snr': 50})
    noise = sim.generate_noise(dimensions=dimensions,
                               stimfunction_tr=stimfunction_tr,
                               tr_duration=2,
                               template=template,
                               mask=mask,
                               noise_dict=noise_dict,
                               iterations=[3, 0])
    assert noise.shape == (10, 10, 10, 100)
    assert np.all(noise >= 0)
    # noise in brain >> noise outside
    assert noise[mask > 0].mean() > 10 * noise[mask == 0].mean()
    # the fitted SNR is in the right ballpark
    est_snr = sim._calc_snr(noise, mask)
    assert 0.3 * noise_dict['snr'] < est_snr < 3 * noise_dict['snr']


def test_packaged_brain_template(tmp_path):
    """mask_brain(mask_self=False) must load the PACKAGED template (the
    analog of the reference's grey-matter atlas, reference
    fmrisim.py:2288-2292) rather than regenerating one per call, and
    the packaged file must be bit-reproducible from the procedural
    generator (provenance pin for tools/gen_brain_template.py)."""
    import os

    path = os.path.join(os.path.dirname(sim.__file__),
                        "sim_parameters", "brain_template.npz")
    with np.load(path) as payload:
        stored = payload["template"]
    assert stored.shape == (91, 109, 91)
    assert stored.dtype == np.uint8
    regen = np.round(sim._synthetic_brain_template((91, 109, 91))
                     * 255.0).astype(np.uint8)
    # one quantization step of slack: bit-exactness would couple the
    # suite to scipy/numpy rounding staying identical across versions
    # (a 0.5-ulp flip at a quantization boundary is legitimate)
    assert np.abs(stored.astype(int) - regen.astype(int)).max() <= 1

    # the packaged template drives mask_brain and zooms to any 3-D shape
    mask, template = sim.mask_brain(np.array([12, 14, 12]),
                                    mask_self=False)
    assert mask.shape == (12, 14, 12) and template.shape == (12, 14, 12)
    # normalization happens BEFORE the zoom (as in the reference), so
    # the interpolated peak can land slightly under 1
    assert 0.0 <= template.min() and 0.9 < template.max() <= 1.0
    assert 0.05 < mask.mean() < 0.7
    # deterministic: two calls agree exactly (no per-call regeneration)
    mask2, template2 = sim.mask_brain(np.array([12, 14, 12]),
                                      mask_self=False)
    np.testing.assert_array_equal(template, template2)

    # template_name= loads a user-supplied .npy (reference
    # fmrisim.py:2292-2294), previously accepted but ignored
    custom = np.zeros((10, 10, 10))
    custom[3:7, 3:7, 3:7] = 1.0
    custom_path = tmp_path / "custom_template.npy"
    np.save(custom_path, custom)
    cmask, ctemplate = sim.mask_brain(np.ones((10, 10, 10)),
                                      template_name=str(custom_path),
                                      mask_threshold=0.5,
                                      mask_self=False)
    np.testing.assert_array_equal(ctemplate, custom)
    assert cmask.sum() == 4 ** 3


def test_calc_noise_roundtrip():
    np.random.seed(2)
    dimensions = np.array([12, 12, 12])
    stimfunction = sim.generate_stimfunction(onsets=[], event_durations=[1],
                                             total_time=150)
    stimfunction_tr = stimfunction[::100]
    mask, template = sim.mask_brain(dimensions, mask_self=False)
    gen_dict = sim._noise_dict_update({'sfnr': 60, 'snr': 40,
                                       'matched': 0})
    noise = sim.generate_noise(dimensions=dimensions,
                               stimfunction_tr=stimfunction_tr,
                               tr_duration=1.5,
                               template=template,
                               mask=mask,
                               noise_dict=gen_dict,
                               iterations=[5, 5])
    est = sim.calc_noise(noise, mask, template)
    assert 0.4 * gen_dict['sfnr'] < est['sfnr'] < 2.5 * gen_dict['sfnr']
    assert 0.4 * gen_dict['snr'] < est['snr'] < 2.5 * gen_dict['snr']
    assert -1 < est['auto_reg_rho'][0] < 1
    assert est['fwhm'] > 0


def test_spatial_noise_fwhm_calibration():
    """The spectral field sampler must realize the requested smoothness:
    measured FWHM tracks the request across the usual range (the
    reference's empirical FWHM→sigma map contract,
    fmrisim.py:1917-1934)."""
    np.random.seed(5)
    for n in (16, 32):  # calibration must be grid-size independent
        dims = (n, n, n)
        mask = np.ones(dims)
        est = {}
        for f in (2.0, 4.0, 6.0):
            est[f] = np.mean([
                sim._calc_fwhm(sim._generate_noise_spatial(dims, fwhm=f),
                               mask) for _ in range(8)])
        assert est[2.0] < est[4.0] < est[6.0]
        for f, e in est.items():
            assert abs(e - f) / f < 0.35, (n, f, e)
    # non-cubic grids: isotropic in voxel units, still calibrated
    dims = (32, 32, 12)
    diffs = {ax: [] for ax in range(3)}
    fwhms = []
    for _ in range(8):
        f = sim._generate_noise_spatial(dims, fwhm=4.0)
        for ax in range(3):
            diffs[ax].append(np.std(np.diff(f, axis=ax)))
        fwhms.append(sim._calc_fwhm(f, np.ones(dims)))
    per_axis = [np.mean(diffs[ax]) for ax in range(3)]
    assert max(per_axis) / min(per_axis) < 1.3, per_axis
    assert abs(np.mean(fwhms) - 4.0) / 4.0 < 0.35


def test_drift_power_drop_spectrum():
    """cos_power_drop concentrates drift power below the requested
    period and suppresses the high-frequency tail (the reference's
    99%-power DCT criterion, fmrisim.py:1634-1680)."""
    np.random.seed(6)
    trs, tr, period = 300, 2.0, 150
    drift = sim._generate_noise_temporal_drift(
        trs, tr, basis="cos_power_drop", period=period)
    p = np.abs(np.fft.rfft(drift)) ** 2
    freqs = np.fft.rfftfreq(trs, d=tr)
    assert p[freqs <= 1.0 / period].sum() / p.sum() > 0.7
    assert p[freqs > 10.0 / period].sum() / p.sum() < 0.05
    with pytest.raises(ValueError):
        sim._generate_noise_temporal_drift(100, 2.0, period=1.0)


def test_mask_brain():
    mask, template = sim.mask_brain(np.array([10, 10, 10]),
                                    mask_self=False)
    assert mask.shape == (10, 10, 10)
    assert template.max() <= 1.0
    assert 0 < mask.sum() < mask.size
    # center in brain, corner not
    assert mask[5, 5, 5] == 1
    assert mask[0, 0, 0] == 0
    # self-masking from a 4D volume
    vol = np.zeros((8, 8, 8, 3))
    vol[2:6, 2:6, 2:6, :] = 100
    mask2, template2 = sim.mask_brain(vol, mask_self=True)
    assert mask2[3, 3, 3] == 1
    assert mask2[0, 0, 0] == 0


def test_synthetic_template_structure():
    """The procedural template must carry the atlas's gross structure:
    values in [0, 1], bright shell vs darker ventricle interior, rough
    left/right symmetry, and a bimodal histogram so the automatic mask
    threshold works."""
    dims = (24, 24, 24)
    t = sim._synthetic_brain_template(dims)
    assert t.shape == dims
    assert t.min() >= 0 and np.isclose(t.max(), 1.0)
    # interior brighter than the background corners, ventricle darker
    # than the brain average
    background = np.mean([t[0, 0, 0], t[-1, 0, 0], t[0, -1, -1],
                          t[-1, -1, -1]])
    center = t[10:14, 10:14, 10:14].mean()     # ventricle region
    brain = t[t > 0.5].mean()
    assert background < 0.1
    assert background < center < brain
    # 2-D volumes keep working (dims-agnostic fallback)
    t2 = sim._synthetic_brain_template((12, 12))
    assert t2.shape == (12, 12) and np.isclose(t2.max(), 1.0)
    # rough left/right symmetry
    assert np.abs(t - t[::-1]).mean() < 0.05
    # the automatic threshold must find a sensible brain fraction
    mask, template = sim.mask_brain(np.ones(np.array(dims)),
                                    mask_self=False)
    frac = mask.mean()
    assert 0.1 < frac < 0.7


def test_drift_and_phys_components():
    np.random.seed(3)
    drift = sim._generate_noise_temporal_drift(200, 2.0)
    assert drift.shape == (200,)
    assert np.isclose(drift.std(), 1.0, atol=0.01)
    drift_sine = sim._generate_noise_temporal_drift(100, 2.0, basis="sine")
    assert np.isclose(drift_sine.std(), 1.0, atol=0.01)
    phys = sim._generate_noise_temporal_phys(list(np.arange(0, 100, 2.0)))
    assert phys.shape == (50,)
    task = sim._generate_noise_temporal_task(
        np.array([0, 1, 0, 1, 1, 0] * 10))
    assert task.shape == (60,)
    # option variants (reference fmrisim.py:1502-1693): rician
    # task-locked noise, discrete_cos harmonic drift, error contracts
    task_r = sim._generate_noise_temporal_task(
        np.array([0, 1, 0, 1, 1, 0] * 10), motion_noise='rician')
    assert task_r.shape == (60,) and np.isfinite(task_r).all()
    import pytest
    with pytest.raises(ValueError, match="gaussian or rician"):
        sim._generate_noise_temporal_task(np.ones(10),
                                          motion_noise='poisson')
    drift_dc = sim._generate_noise_temporal_drift(
        200, 2.0, basis="discrete_cos")
    assert np.isclose(drift_dc.std(), 1.0, atol=0.01)
    with pytest.raises(ValueError, match="drift basis"):
        sim._generate_noise_temporal_drift(100, 2.0, basis="spline")


def test_signal_feature_shapes_and_stim_file(tmp_path):
    """Feature geometry variants (loop/cavity, unknown-type error), the
    3-column timing-file input, a custom HRF array, and 1-D
    apply_signal input (reference fmrisim.py:310-966)."""
    dims = np.array([11, 11, 11])
    center = np.array([[5, 5, 5]])
    vols = {}
    for ft in ('loop', 'sphere', 'cavity'):
        vols[ft] = sim.generate_signal(dimensions=dims,
                                       feature_coordinates=center,
                                       feature_type=[ft],
                                       feature_size=[5],
                                       signal_magnitude=[1])
        assert vols[ft].shape == tuple(dims) and vols[ft].max() == 1.0
    # a cavity is a sphere with the interior removed
    assert vols['cavity'].sum() < vols['sphere'].sum()
    # a loop is planar: exactly one slice along the loop axis is active
    active_slices = (vols['loop'].sum(axis=(0, 1)) > 0).sum()
    assert active_slices == 1
    with pytest.raises(ValueError, match="feature type"):
        sim.generate_signal(dimensions=dims, feature_coordinates=center,
                            feature_type=['pyramid'], feature_size=[3],
                            signal_magnitude=[1])

    # FSL-style 3-column timing file == the equivalent explicit args
    tfile = tmp_path / "events.txt"
    tfile.write_text("10.0 6.0 1.0\n30.0 6.0 1.0\n")
    from_file = sim.generate_stimfunction(onsets=None,
                                          event_durations=None,
                                          total_time=60,
                                          timing_file=str(tfile))
    explicit = sim.generate_stimfunction(onsets=[10.0, 30.0],
                                         event_durations=[6.0],
                                         total_time=60)
    np.testing.assert_array_equal(from_file, explicit)

    # custom HRF array short-circuits the double-gamma
    box = sim.generate_stimfunction(onsets=[2], event_durations=[2],
                                    total_time=20)
    delta = np.zeros(100)
    delta[0] = 1.0
    conv = sim.convolve_hrf(stimfunction=box, tr_duration=2,
                            hrf_type=delta, scale_function=False)
    assert conv.shape[0] == 10
    # identity HRF: the convolved course is the mid-TR boxcar sample
    stride = 200
    np.testing.assert_allclose(conv[:, 0],
                               box[stride // 2::stride, 0][:10])

    # 1-D signal function is promoted to a column
    vol = vols['sphere']
    sig4d = sim.apply_signal(signal_function=np.ones(5), volume_signal=vol)
    assert sig4d.shape == tuple(dims) + (5,)


def test_system_noise_distribution_variants():
    """Scanner-noise spatial/temporal distributions beyond the default
    gaussian (reference fmrisim.py:1397-1482): the temporal component
    is demeaned per voxel over time regardless of distribution, while
    the spatial pattern keeps its raw location (a rician/exponential
    spatial mean is part of the scanner's stable pattern)."""
    np.random.seed(11)
    dims = (6, 6, 6, 30)
    for s_type, t_type in [("rician", "rician"),
                           ("exponential", "exponential"),
                           ("gaussian", "rician")]:
        noise = sim._generate_noise_system(
            dims, spatial_sd=1.0, temporal_sd=1.0,
            spatial_noise_type=s_type, temporal_noise_type=t_type)
        assert noise.shape == dims
        assert np.isfinite(noise).all()
        # per-voxel time mean == the voxel's stable spatial offset
        spatial_part = noise.mean(axis=3)
        temporal_part = noise - spatial_part[..., None]
        np.testing.assert_allclose(temporal_part.mean(axis=3), 0.0,
                                   atol=1e-12)
        if s_type == "gaussian":
            assert abs(spatial_part.mean()) < 0.5
        else:
            # unshifted rician/exponential spatial means are positive
            assert spatial_part.mean() > 0.5


def test_arma_mle_recovery():
    """The Kalman-filter ARMA(1,1) MLE must recover known coefficients
    (the contract of the reference's statsmodels-based estimator,
    fmrisim.py:1205-1289) — including the MA term that a Yule-Walker
    moment estimate gets badly biased."""
    rng = np.random.RandomState(7)
    n_vox, n_tr, burn = 40, 300, 50
    rho, theta = 0.5, 0.3
    e = rng.randn(n_vox, n_tr + burn)
    x = np.zeros((n_vox, n_tr + burn))
    for t in range(1, n_tr + burn):
        x[:, t] = rho * x[:, t - 1] + e[:, t] + theta * e[:, t - 1]
    x = x[:, burn:]
    np.random.seed(8)
    ar, ma = sim._calc_ARMA_noise(x, np.ones(n_vox), sample_num=40)
    assert abs(ar[0] - rho) < 0.1
    assert abs(ma[0] - theta) < 0.12


def test_arma_mle_golden_values():
    """Pin exact _arma11_mle outputs on a fixed ARMA(0.45, 0.25)
    series.  The parity suite's statsmodels stand-in delegates to this
    estimator (tests/parity/conftest.py), so the cross-oracle fmrisim
    test cannot catch drift in it; this golden pin can — any change to
    the grid recipe or the Kalman likelihood shows up here even inside
    the recovery tests' tolerance bands."""
    rng = np.random.RandomState(31)
    n_tr, burn = 250, 50
    e = rng.randn(3, n_tr + burn)
    x = np.zeros((3, n_tr + burn))
    for t in range(1, n_tr + burn):
        x[:, t] = 0.45 * x[:, t - 1] + e[:, t] + 0.25 * e[:, t - 1]
    x = x[:, burn:]
    x = (x - x.mean(1, keepdims=True)) / x.std(1, keepdims=True)
    rho, theta, ll = sim._arma11_mle(x)
    np.testing.assert_allclose(
        rho, [0.45694444, 0.34814815, 0.4612963], atol=1e-6)
    np.testing.assert_allclose(
        theta, [0.20453704, 0.27851852, 0.19148148], atol=1e-6)
    np.testing.assert_allclose(
        ll, [-300.24724723, -309.17816001, -301.072746], atol=1e-4)


def test_arma_mle_white_noise_is_zero():
    """On white data the likelihood is flat along the rho = -theta
    cancellation ridge; the near-tie break must keep the estimate at
    ~(0, 0) rather than an arbitrary ridge point."""
    rng = np.random.RandomState(12)
    w = rng.randn(40, 400)
    np.random.seed(13)
    ar, ma = sim._calc_ARMA_noise(w, np.ones(40), sample_num=40)
    assert abs(ar[0]) < 0.1
    assert abs(ma[0]) < 0.1


def test_arma_mle_weak_signal_not_shrunk():
    """Weak-but-identified autocorrelation (AR(1) rho=0.3 at T=100)
    must survive the white-noise likelihood-ratio gate — a regression
    guard against near-tie heuristics that collapse the whole
    confidence region toward zero."""
    rng = np.random.RandomState(21)
    n_vox, n_tr, burn, rho = 50, 100, 50, 0.3
    e = rng.randn(n_vox, n_tr + burn)
    x = np.zeros((n_vox, n_tr + burn))
    for t in range(1, n_tr + burn):
        x[:, t] = rho * x[:, t - 1] + e[:, t]
    x = x[:, burn:]
    np.random.seed(22)
    ar, ma = sim._calc_ARMA_noise(x, np.ones(n_vox), sample_num=n_vox)
    # the effective lag-1 dependence (ar + ma for AR-dominated data)
    assert 0.15 < ar[0] + ma[0] < 0.45


def test_arma_loglik_prefers_truth():
    """The concentrated exact likelihood must rank the generating
    parameters above clearly wrong ones."""
    rng = np.random.RandomState(9)
    n_tr, burn = 400, 50
    e = rng.randn(1, n_tr + burn)
    x = np.zeros((1, n_tr + burn))
    for t in range(1, n_tr + burn):
        x[:, t] = 0.6 * x[:, t - 1] + e[:, t] - 0.2 * e[:, t - 1]
    x = x[:, burn:]
    x = (x - x.mean()) / x.std()
    cand_r = np.array([[0.6, 0.0, -0.6]])
    cand_t = np.array([[-0.2, 0.0, 0.5]])
    ll = sim._arma11_loglik_grid(x, cand_r, cand_t)
    assert np.argmax(ll[0]) == 0


def test_gen_1d_gaussian_rfs():
    np.random.seed(4)
    rfs, tuning = sim.generate_1d_gaussian_rfs(
        20, 360, (0, 359), rf_size=15, random_tuning=True)
    assert rfs.shape == (20, 360)
    assert np.allclose(rfs.max(axis=1), 1.0)
    assert np.all((tuning >= 0) & (tuning < 360))
    # even spacing
    rfs2, tuning2 = sim.generate_1d_gaussian_rfs(
        10, 360, (0, 360), random_tuning=False)
    spacing = np.diff(tuning2)
    assert len(np.unique(spacing)) == 1
    # responses peak near the presented feature
    trials = np.array([45, 180, 300])
    data = sim.generate_1d_rf_responses(rfs2, trials, 360, (0, 360),
                                        trial_noise=0.01)
    assert data.shape == (10, 3)


def test_convolve_hrf_rejects_unknown_string_hrf_type():
    """A typo'd hrf_type string must raise a clear ValueError instead
    of coercing to a 0-d string array and failing in np.convolve."""
    box = sim.generate_stimfunction(onsets=[2], event_durations=[2],
                                    total_time=20)
    with pytest.raises(ValueError, match="double-gamma"):
        sim.convolve_hrf(stimfunction=box, tr_duration=2,
                         hrf_type='double-gamma')
    # the canonical spelling still works
    out = sim.convolve_hrf(stimfunction=box, tr_duration=2,
                           hrf_type='double_gamma')
    assert out.shape[0] == 10
