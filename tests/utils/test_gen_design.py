import os

import numpy as np
import pytest

from brainiak_tpu.utils.utils import ReadDesign, gen_design

# Committed AFNI 3dDeconvolve-style design fixture (186 TRs, 27
# columns: 4 polynomial-drift + 6 orth/motion + 17 stimulus).
DESIGN_1D = os.path.join(os.path.dirname(__file__),
                         "example_design.1D")

# Stimulus timing fixtures (FSL 3-column and equivalent AFNI married format).
FSL_1 = "5.2 2.0 2.0\n40.0 1.5 4.0\n50.0 1.0 2.0\n"
FSL_HALF = "5.2 2.0 1.0\n40.0 1.5 2.0\n50.0 1.0 1.0\n"
AFNI_1 = "5.2*2.0:2.0 40.0*4.0:1.5\n2.0*2.0:1.0\n"
AFNI_NEG = "-1.0\n"


@pytest.fixture
def stim_files(tmp_path):
    paths = {}
    for name, content in [("fsl1", FSL_1), ("fsl_half", FSL_HALF),
                          ("afni1", AFNI_1), ("afni_neg", AFNI_NEG)]:
        p = tmp_path / f"{name}.txt"
        p.write_text(content)
        paths[name] = str(p)
    return paths


def test_gen_design_fsl(stim_files):
    d1 = gen_design([stim_files["fsl1"]], scan_duration=[48, 20], TR=2,
                    style='FSL')
    assert d1.shape == (34, 1)
    # runs are separate timelines: first TR of run 2 precedes any response
    assert d1[24] == 0
    # single long run: 8 s after the 40 s onset there is a response
    d3 = gen_design([stim_files["fsl1"]], scan_duration=68, TR=2, style='FSL')
    assert d3[24] != 0
    # weights scale the response linearly
    d4 = gen_design([stim_files["fsl_half"]], scan_duration=[48, 20], TR=2,
                    style='FSL')
    assert np.allclose(d1 * 0.5, d4)
    # TR=1 sampling agrees with TR=2 at shared time points
    d5 = gen_design([stim_files["fsl_half"]], scan_duration=[48, 20], TR=1,
                    style='FSL')
    assert np.abs(d4 - d5[::2]).mean() < 0.1
    # multiple conditions stack as columns
    d2 = gen_design([stim_files["fsl1"], stim_files["fsl_half"]],
                    scan_duration=[48, 20], TR=2, style='FSL')
    assert d2.shape == (34, 2)


def test_gen_design_afni_equals_fsl(stim_files):
    # AFNI events: run 1 has (5.2, w2, d2) and (40, w4, d1.5); run 2 has
    # (2.0+48=50 globally, w2, d1) -> same events as the FSL file.
    d_fsl = gen_design([stim_files["fsl1"]], scan_duration=[48, 20], TR=2,
                       style='FSL')
    d_afni = gen_design([stim_files["afni1"]], scan_duration=[48, 20], TR=2,
                        style='AFNI')
    assert np.allclose(d_fsl, d_afni)


def test_gen_design_afni_negative_onset(stim_files):
    d = gen_design([stim_files["afni_neg"]], scan_duration=[48], TR=2,
                   style='AFNI')
    assert np.all(d == 0.0)


def test_gen_design_bad_style(stim_files):
    with pytest.raises(ValueError):
        gen_design([stim_files["fsl1"]], scan_duration=[48], TR=2,
                   style='SPM')
    with pytest.raises(ValueError):
        # AFNI line count must match run count
        gen_design([stim_files["afni1"]], scan_duration=[48], TR=2,
                   style='AFNI')


def test_gen_design_arg_validation(stim_files):
    with pytest.raises(ValueError, match="TR"):
        gen_design([stim_files["fsl1"]], scan_duration=[48], TR=0)
    with pytest.raises(ValueError, match="scan_duration"):
        gen_design([stim_files["fsl1"]], scan_duration=[1], TR=2)
    # a single path is promoted to a one-element list
    single = gen_design(stim_files["fsl1"], scan_duration=[48], TR=2)
    listed = gen_design([stim_files["fsl1"]], scan_duration=[48], TR=2)
    np.testing.assert_array_equal(single, listed)


def test_gen_design_fsl_short_columns(tmp_path):
    """FSL rows may omit duration and weight (default 1.0) — reference
    utils.py gen_design accepts 1-3 column rows."""
    full = tmp_path / "full.txt"
    full.write_text("5.0 1.0 1.0\n20.0 1.0 1.0\n")
    short = tmp_path / "short.txt"
    short.write_text("5.0\n20.0\n")
    d_full = gen_design([str(full)], scan_duration=[48], TR=2)
    d_short = gen_design([str(short)], scan_duration=[48], TR=2)
    np.testing.assert_allclose(d_short, d_full)


def test_read_design_header_mismatch_warns(tmp_path):
    """A header ncol that disagrees with the matrix falls back to the
    matrix's column count with a warning (reference utils.py
    ReadDesign semantics)."""
    import warnings

    ref = ReadDesign(DESIGN_1D)
    text = open(DESIGN_1D).read()
    bad = text.replace(f'ni_type = "{ref.n_col}*double"',
                       f'ni_type = "{ref.n_col + 3}*double"')
    assert bad != text
    p = tmp_path / "bad.1D"
    p.write_text(bad)
    with warnings.catch_warnings(record=True) as caught:
        warnings.simplefilter("always")
        d = ReadDesign(str(p))
    assert d.n_col == ref.n_col
    assert any("columns" in str(w.message) for w in caught)


def test_read_design_afni_fixture():
    # Committed AFNI 3dDeconvolve-style design fixture.
    d = ReadDesign(DESIGN_1D)
    assert d.n_TR == 186
    assert d.n_col == 27
    assert d.n_basis == 4
    assert d.n_stim > 0
    assert d.design_task.shape[0] == 186
    assert d.reg_nuisance is not None
    # excluding nuisance terms
    d2 = ReadDesign(DESIGN_1D,
                    include_orth=False, include_pols=False)
    assert d2.reg_nuisance is None
