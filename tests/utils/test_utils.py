import numpy as np
import pytest

from brainiak_tpu.utils.utils import (
    array_correlation,
    center_mass_exp,
    circ_dist,
    concatenate_not_none,
    cov2corr,
    from_sym_2_tri,
    from_tri_2_sym,
    p_from_null,
    phase_randomize,
    sumexp_stable,
    usable_cpu_count,
    _check_timeseries_input,
)


def test_tri_sym_roundtrip():
    rng = np.random.RandomState(0)
    dim = 5
    sym = rng.rand(dim, dim)
    sym = sym + sym.T
    tri = from_sym_2_tri(sym)
    assert tri.shape == (dim * (dim + 1) // 2,)
    back = from_tri_2_sym(tri, dim)
    assert np.allclose(np.triu(back), np.triu(sym))


def test_sumexp_stable():
    rng = np.random.RandomState(1)
    data = rng.randn(4, 3) * 50
    s, m, e = sumexp_stable(data)
    assert np.allclose(m, data.max(axis=0))
    assert np.all(np.isfinite(s))
    # softmax reconstruction
    soft = e / s
    assert np.allclose(soft.sum(axis=0), 1.0)


def test_concatenate_not_none():
    a = np.ones((2, 3))
    out = concatenate_not_none([None, a, None, 2 * a], axis=0)
    assert out.shape == (4, 3)
    assert np.allclose(out[2:], 2.0)


def test_cov2corr():
    rng = np.random.RandomState(2)
    x = rng.randn(100, 4)
    cov = np.cov(x.T)
    corr = cov2corr(cov)
    assert np.allclose(np.diag(corr), 1.0)
    assert np.allclose(corr, np.corrcoef(x.T))


def test_circ_dist():
    x = np.array([0.0, np.pi / 2])
    y = np.array([np.pi / 2, 0.0])
    d = circ_dist(x, y)
    assert np.allclose(d, [-np.pi / 2, np.pi / 2])
    with pytest.raises(ValueError):
        circ_dist(np.zeros(2), np.zeros(3))


def test_center_mass_exp():
    # whole support: mean of exponential = scale
    assert np.isclose(center_mass_exp((0, np.inf), scale=2.0), 2.0)
    m = center_mass_exp((0.0, 1.0), scale=1.0)
    assert 0 < m < 0.5
    with pytest.raises(AssertionError):
        center_mass_exp((1.0, 0.5))


def test_array_correlation():
    rng = np.random.RandomState(3)
    x = rng.randn(50, 7)
    y = rng.randn(50, 7)
    r = array_correlation(x, y)
    expected = [np.corrcoef(x[:, i], y[:, i])[0, 1] for i in range(7)]
    assert np.allclose(r, expected)
    # axis=1 equals transposed computation
    assert np.allclose(array_correlation(x, y, axis=1),
                       array_correlation(x.T, y.T, axis=0))
    with pytest.raises(ValueError):
        array_correlation(x, y[:, :3])


def test_p_from_null():
    null = np.array([-2.0, -1.0, 0.0, 1.0, 2.0])
    assert p_from_null(3.0, null, side='right', exact=True) == 0.0
    assert p_from_null(3.0, null, side='right') == pytest.approx(1 / 6)
    assert p_from_null(0.0, null, side='two-sided', exact=True) == 1.0
    assert p_from_null(-3.0, null, side='left', exact=True) == 0.0
    with pytest.raises(ValueError):
        p_from_null(0.0, null, side='up')


def test_phase_randomize_preserves_spectrum():
    rng = np.random.RandomState(4)
    data = rng.randn(60, 3, 2)
    shifted = phase_randomize(data, random_state=0)
    assert shifted.shape == data.shape
    assert not np.allclose(shifted, data)
    # power spectrum preserved per voxel/subject
    p0 = np.abs(np.fft.fft(data, axis=0))
    p1 = np.abs(np.fft.fft(shifted, axis=0))
    assert np.allclose(p0, p1, atol=1e-8)
    # odd-length series too
    shifted_odd = phase_randomize(data[:59], random_state=0)
    assert np.allclose(np.abs(np.fft.fft(data[:59], axis=0)),
                       np.abs(np.fft.fft(shifted_odd, axis=0)), atol=1e-8)
    # 2-D input keeps its shape
    d2 = rng.randn(40, 3)
    with pytest.warns(DeprecationWarning):
        assert phase_randomize(d2, random_state=1).shape == d2.shape


def test_phase_randomize_shim_delegates_to_jax_path():
    """The host-NumPy twin is now a deprecation shim over the single
    jax implementation (ISSUE 18 satellite): it must warn, seed
    deterministically from either an int or a RandomState, and draw
    phases that are distribution-identical to the legacy chain
    (uniform on the circle, DC component preserved exactly)."""
    rng = np.random.RandomState(6)
    data = rng.randn(48, 2, 3)
    with pytest.warns(DeprecationWarning):
        a = phase_randomize(data, random_state=7)
    b = phase_randomize(data, random_state=7)
    c = phase_randomize(data, random_state=8)
    assert np.array_equal(a, b)
    assert not np.allclose(a, c)
    # a RandomState seeds the key from its own chain: same state in,
    # same surrogate out
    d = phase_randomize(data, random_state=np.random.RandomState(9))
    e = phase_randomize(data, random_state=np.random.RandomState(9))
    assert np.array_equal(d, e)
    # the DC component is never scrambled, so every surrogate keeps
    # the original per-series time-mean
    assert np.allclose(np.mean(a, axis=0), np.mean(data, axis=0),
                       atol=1e-8)
    # distribution-level parity with the legacy uniform-phase draw:
    # across seeds, the surrogate phase at one frequency bin is
    # uniform on the circle (resultant of n unit vectors ~ sqrt(n))
    series = rng.randn(32, 1, 1)
    n_draws = 128
    angles = np.empty(n_draws)
    for seed in range(n_draws):
        surrogate = phase_randomize(series, random_state=seed)
        angles[seed] = np.angle(np.fft.fft(surrogate[:, 0, 0])[3])
    resultant = np.abs(np.mean(np.exp(1j * angles)))
    assert resultant < 4.0 / np.sqrt(n_draws)
    assert angles.min() < -2.0 and angles.max() > 2.0


def test_check_timeseries_input():
    rng = np.random.RandomState(5)
    arrays = [rng.randn(10, 4) for _ in range(3)]
    data, n_TRs, n_voxels, n_subjects = _check_timeseries_input(arrays)
    assert data.shape == (10, 4, 3)
    assert (n_TRs, n_voxels, n_subjects) == (10, 4, 3)
    data2, *_ = _check_timeseries_input(rng.randn(10, 3))
    assert data2.shape == (10, 1, 3)
    with pytest.raises(ValueError):
        _check_timeseries_input(rng.randn(10))
    with pytest.raises(ValueError):
        _check_timeseries_input([rng.randn(10, 4), rng.randn(10, 5)])


def test_usable_cpu_count():
    assert usable_cpu_count() >= 1
