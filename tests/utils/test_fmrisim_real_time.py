import os

import numpy as np
import pytest

from brainiak_tpu.utils.fmrisim_real_time_generator import (
    default_settings,
    generate_data,
)


def test_generate_realtime_data(tmp_path):
    np.random.seed(0)
    out = str(tmp_path / "rt")
    settings = dict(default_settings)
    settings.update({'numTRs': 20, 'save_dicom': False,
                     'save_realtime': False})
    generate_data(out, settings)
    files = sorted(os.listdir(out))
    assert 'mask.npy' in files and 'labels.npy' in files
    vols = [f for f in files if f.startswith('rt_')]
    assert len(vols) == 20
    vol = np.load(os.path.join(out, vols[0]))
    assert vol.ndim == 3
    mask = np.load(os.path.join(out, 'mask.npy'))
    assert vol[mask > 0].mean() > vol[mask == 0].mean()
    labels = np.load(os.path.join(out, 'labels.npy'))
    assert set(np.unique(labels)).issubset({0.0, 1.0, 2.0})


def test_generate_realtime_multivariate(tmp_path):
    np.random.seed(1)
    out = str(tmp_path / "rt_mv")
    settings = dict(default_settings)
    settings.update({'numTRs': 12, 'multivariate_pattern': True})
    generate_data(out, settings)
    assert len([f for f in os.listdir(out)
                if f.startswith('rt_')]) == 12


def test_generate_realtime_custom_inputs(tmp_path, monkeypatch):
    """User-supplied template/ROI/noise-dict files and the
    different_ROIs + save_realtime branches (reference
    fmrisim_real_time_generator.py:117-265)."""
    import brainiak_tpu.utils.fmrisim_real_time_generator as rtg

    np.random.seed(3)
    dims = (20, 20, 12)
    template = np.ones(dims) * 800
    template_path = tmp_path / "template.npy"
    np.save(template_path, template)
    roi_a = np.zeros(dims)
    roi_a[4:8, 4:8, 4:8] = 1
    roi_b = np.zeros(dims)
    roi_b[12:16, 12:16, 4:8] = 1
    roi_a_path = tmp_path / "roi_a.npy"
    roi_b_path = tmp_path / "roi_b.npy"
    np.save(roi_a_path, roi_a)
    np.save(roi_b_path, roi_b)
    nd_path = tmp_path / "noise.txt"
    nd_path.write_text("{'snr': 25, 'sfnr': 60, 'max_activity': 800,"
                       " 'matched': 0}")

    out = str(tmp_path / "rt_custom")
    settings = dict(default_settings)
    settings.update({'numTRs': 14, 'trDuration': 1,
                     'event_duration': 2, 'isi': 1, 'burn_in': 1,
                     'template_path': str(template_path),
                     'ROI_A_file': str(roi_a_path),
                     'ROI_B_file': str(roi_b_path),
                     'noise_dict_file': str(nd_path),
                     'different_ROIs': True,
                     'save_realtime': True})
    # record the pacing instead of paying ~14 s of real sleep
    sleeps = []
    monkeypatch.setattr(rtg.time, "sleep", sleeps.append)
    generate_data(out, settings)
    vols = [f for f in sorted(os.listdir(out)) if f.startswith('rt_')]
    assert len(vols) == 14
    vol = np.load(os.path.join(out, vols[0]))
    assert vol.shape == dims
    # save_realtime paces output at ~trDuration per volume
    assert len(sleeps) == 14
    assert all(0.0 <= s <= 1.0 for s in sleeps)
    assert sum(sleeps) > 10


def test_dicom_gated(tmp_path):
    np.random.seed(2)
    settings = dict(default_settings)
    settings.update({'numTRs': 3, 'save_dicom': True})
    with pytest.raises(ImportError):
        generate_data(str(tmp_path / "rt_dcm"), settings)


def test_dicom_save_path(tmp_path):
    """The .dcm writer round-trips volumes when pydicom is
    available (ISSUE 15 satellite)."""
    pydicom = pytest.importorskip("pydicom")
    settings = dict(default_settings)
    settings.update({'numTRs': 2, 'save_dicom': True})
    out = str(tmp_path / "rt_dcm")
    generate_data(out, settings, rng=0)
    vols = sorted(f for f in os.listdir(out) if f.endswith(".dcm"))
    assert len(vols) == 2
    ds = pydicom.dcmread(os.path.join(out, vols[0]))
    assert int(ds.NumberOfFrames) == 16
    assert (int(ds.Rows), int(ds.Columns)) == (24, 24)


def test_seeded_generate_data_is_byte_deterministic(tmp_path):
    """A fixed seed makes the on-disk CLI path byte-compatible
    across runs — and a different seed produces different data
    (ISSUE 15 satellite: seedable rng threading)."""
    settings = dict(default_settings)
    settings.update({'numTRs': 6})
    a, b, c = (str(tmp_path / name) for name in "abc")
    generate_data(a, settings, rng=11)
    generate_data(b, settings, rng=11)
    generate_data(c, settings, rng=12)
    files = sorted(os.listdir(a))
    assert sorted(os.listdir(b)) == files
    for name in files:
        with open(os.path.join(a, name), "rb") as fa, \
                open(os.path.join(b, name), "rb") as fb:
            assert fa.read() == fb.read(), name
    vol_a = np.load(os.path.join(a, "rt_000.npy"))
    vol_c = np.load(os.path.join(c, "rt_000.npy"))
    assert not np.array_equal(vol_a, vol_c)


def test_generate_stream_matches_on_disk_volumes(tmp_path):
    """The in-memory generator mode yields the same volumes the
    on-disk path writes under the same seed — no disk round-trip
    needed to consume the stream (ISSUE 15 satellite)."""
    from brainiak_tpu.utils.fmrisim_real_time_generator import \
        generate_stream

    settings = dict(default_settings)
    settings.update({'numTRs': 5})
    out = str(tmp_path / "rt")
    generate_data(out, settings, rng=21)
    stream = generate_stream({'numTRs': 5}, rng=21)
    assert stream.n_trs == 5 and len(stream) == 5
    assert stream.brain.shape[3] == 5
    mask = np.load(os.path.join(out, "mask.npy"))
    assert np.array_equal(stream.mask, mask)
    assert np.array_equal(
        stream.labels, np.load(os.path.join(out, "labels.npy")))
    for tr, vol in enumerate(stream):
        on_disk = np.load(os.path.join(out, f"rt_{tr:0>3}.npy"))
        assert np.array_equal(vol.astype(np.int16), on_disk)
        assert np.array_equal(vol, stream.volume(tr))


def test_generate_stream_accepts_generator_instances():
    """rng= threads an explicit numpy Generator (not just a seed)
    through the simulation."""
    from brainiak_tpu.utils.fmrisim_real_time_generator import \
        generate_stream

    s1 = generate_stream({'numTRs': 3},
                         rng=np.random.default_rng(5))
    s2 = generate_stream({'numTRs': 3},
                         rng=np.random.default_rng(5))
    assert np.array_equal(s1.brain, s2.brain)


def test_paced_stream_follows_absolute_schedule(monkeypatch):
    """paced=True delivers TR t at start + t*trDuration — an
    absolute schedule, so consumer time between pulls counts
    against the period instead of stretching it (the save_realtime
    analog for the in-memory mode)."""
    import brainiak_tpu.utils.fmrisim_real_time_generator as rtg

    sleeps = []
    monkeypatch.setattr(rtg.time, "sleep", sleeps.append)
    # frozen clock + no-op sleep: the requested delays expose the
    # schedule itself — TR 0 is due immediately, TR t waits t TRs
    monkeypatch.setattr(rtg.time, "monotonic", lambda: 100.0)
    stream = rtg.generate_stream({'numTRs': 4, 'trDuration': 1},
                                 rng=0, paced=True)
    assert len(list(stream)) == 4
    assert sleeps == pytest.approx([1.0, 2.0, 3.0])


def test_seeded_simulation_restores_global_rng_stream():
    """A seeded run pins global NumPy state only for the duration
    of the simulation — the caller's stream continues as if the
    call never happened."""
    from brainiak_tpu.utils.fmrisim_real_time_generator import \
        generate_stream

    np.random.seed(123)
    expected = np.random.rand(3)
    np.random.seed(123)
    generate_stream({'numTRs': 3}, rng=5)
    assert np.array_equal(np.random.rand(3), expected)
