import os

import numpy as np
import pytest

from brainiak_tpu.utils.fmrisim_real_time_generator import (
    default_settings,
    generate_data,
)


def test_generate_realtime_data(tmp_path):
    np.random.seed(0)
    out = str(tmp_path / "rt")
    settings = dict(default_settings)
    settings.update({'numTRs': 20, 'save_dicom': False,
                     'save_realtime': False})
    generate_data(out, settings)
    files = sorted(os.listdir(out))
    assert 'mask.npy' in files and 'labels.npy' in files
    vols = [f for f in files if f.startswith('rt_')]
    assert len(vols) == 20
    vol = np.load(os.path.join(out, vols[0]))
    assert vol.ndim == 3
    mask = np.load(os.path.join(out, 'mask.npy'))
    assert vol[mask > 0].mean() > vol[mask == 0].mean()
    labels = np.load(os.path.join(out, 'labels.npy'))
    assert set(np.unique(labels)).issubset({0.0, 1.0, 2.0})


def test_generate_realtime_multivariate(tmp_path):
    np.random.seed(1)
    out = str(tmp_path / "rt_mv")
    settings = dict(default_settings)
    settings.update({'numTRs': 12, 'multivariate_pattern': True})
    generate_data(out, settings)
    assert len([f for f in os.listdir(out)
                if f.startswith('rt_')]) == 12


def test_dicom_gated(tmp_path):
    np.random.seed(2)
    settings = dict(default_settings)
    settings.update({'numTRs': 3, 'save_dicom': True})
    with pytest.raises(ImportError):
        generate_data(str(tmp_path / "rt_dcm"), settings)
