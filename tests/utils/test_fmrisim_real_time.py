import os

import numpy as np
import pytest

from brainiak_tpu.utils.fmrisim_real_time_generator import (
    default_settings,
    generate_data,
)


def test_generate_realtime_data(tmp_path):
    np.random.seed(0)
    out = str(tmp_path / "rt")
    settings = dict(default_settings)
    settings.update({'numTRs': 20, 'save_dicom': False,
                     'save_realtime': False})
    generate_data(out, settings)
    files = sorted(os.listdir(out))
    assert 'mask.npy' in files and 'labels.npy' in files
    vols = [f for f in files if f.startswith('rt_')]
    assert len(vols) == 20
    vol = np.load(os.path.join(out, vols[0]))
    assert vol.ndim == 3
    mask = np.load(os.path.join(out, 'mask.npy'))
    assert vol[mask > 0].mean() > vol[mask == 0].mean()
    labels = np.load(os.path.join(out, 'labels.npy'))
    assert set(np.unique(labels)).issubset({0.0, 1.0, 2.0})


def test_generate_realtime_multivariate(tmp_path):
    np.random.seed(1)
    out = str(tmp_path / "rt_mv")
    settings = dict(default_settings)
    settings.update({'numTRs': 12, 'multivariate_pattern': True})
    generate_data(out, settings)
    assert len([f for f in os.listdir(out)
                if f.startswith('rt_')]) == 12


def test_generate_realtime_custom_inputs(tmp_path, monkeypatch):
    """User-supplied template/ROI/noise-dict files and the
    different_ROIs + save_realtime branches (reference
    fmrisim_real_time_generator.py:117-265)."""
    import brainiak_tpu.utils.fmrisim_real_time_generator as rtg

    np.random.seed(3)
    dims = (20, 20, 12)
    template = np.ones(dims) * 800
    template_path = tmp_path / "template.npy"
    np.save(template_path, template)
    roi_a = np.zeros(dims)
    roi_a[4:8, 4:8, 4:8] = 1
    roi_b = np.zeros(dims)
    roi_b[12:16, 12:16, 4:8] = 1
    roi_a_path = tmp_path / "roi_a.npy"
    roi_b_path = tmp_path / "roi_b.npy"
    np.save(roi_a_path, roi_a)
    np.save(roi_b_path, roi_b)
    nd_path = tmp_path / "noise.txt"
    nd_path.write_text("{'snr': 25, 'sfnr': 60, 'max_activity': 800,"
                       " 'matched': 0}")

    out = str(tmp_path / "rt_custom")
    settings = dict(default_settings)
    settings.update({'numTRs': 14, 'trDuration': 1,
                     'event_duration': 2, 'isi': 1, 'burn_in': 1,
                     'template_path': str(template_path),
                     'ROI_A_file': str(roi_a_path),
                     'ROI_B_file': str(roi_b_path),
                     'noise_dict_file': str(nd_path),
                     'different_ROIs': True,
                     'save_realtime': True})
    # record the pacing instead of paying ~14 s of real sleep
    sleeps = []
    monkeypatch.setattr(rtg.time, "sleep", sleeps.append)
    generate_data(out, settings)
    vols = [f for f in sorted(os.listdir(out)) if f.startswith('rt_')]
    assert len(vols) == 14
    vol = np.load(os.path.join(out, vols[0]))
    assert vol.shape == dims
    # save_realtime paces output at ~trDuration per volume
    assert len(sleeps) == 14
    assert all(0.0 <= s <= 1.0 for s in sleeps)
    assert sum(sleeps) > 10


def test_dicom_gated(tmp_path):
    np.random.seed(2)
    settings = dict(default_settings)
    settings.update({'numTRs': 3, 'save_dicom': True})
    with pytest.raises(ImportError):
        generate_data(str(tmp_path / "rt_dcm"), settings)
