import numpy as np

from brainiak_tpu.native import column_mean, epoch_zscore, native_available


def test_native_builds():
    # the toolchain is present in this environment, so the native path
    # should actually build and load
    assert native_available()


def test_epoch_zscore_matches_numpy():
    rng = np.random.RandomState(0)
    mat = rng.randn(50, 37).astype(np.float32)
    mat[:, 5] = 2.5  # constant column -> zeros
    expected = np.nan_to_num(
        (mat - mat.mean(0)) / (mat.std(0) * np.sqrt(50)))
    got = epoch_zscore(mat.copy())
    assert np.allclose(got, expected, atol=1e-5)
    assert np.allclose(got[:, 5], 0.0)


def test_column_mean_matches_numpy():
    rng = np.random.RandomState(1)
    mat = rng.randn(40, 23).astype(np.float32)
    assert np.allclose(column_mean(mat), mat.mean(0), atol=1e-5)


def test_preprocessing_uses_native_and_stays_golden():
    """The golden-fixture preprocessing test must still pass with the
    native kernel in the loop (covered by test_preprocessing), but also
    check directly on synthetic data."""
    from brainiak_tpu.fcma.preprocessing import _separate_epochs

    rng = np.random.RandomState(2)
    activity = [rng.randn(10, 30).astype(np.float32)]
    epochs = np.zeros((1, 2, 30))
    epochs[0, 0, 3:9] = 1
    epochs[0, 1, 15:23] = 1
    raw, labels = _separate_epochs(activity, [epochs])
    assert len(raw) == 2 and labels == [0, 0]
    assert raw[0].shape == (6, 10)
    # z-scored over time and scaled by 1/sqrt(len)
    assert np.allclose(raw[0].std(axis=0) * np.sqrt(6), 1.0, atol=1e-5)
