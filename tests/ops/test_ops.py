import numpy as np
import pytest

from brainiak_tpu.fcma.util import compute_correlation
from brainiak_tpu.ops.correlation import (
    correlate_epochs,
    normalize_for_correlation,
)
from brainiak_tpu.ops.fisherz import fisher_z, within_subject_normalization
from brainiak_tpu.ops.masked import masked_log
from brainiak_tpu.ops import stats as jstats


def _np_reference_normalization(corr, epochs_per_subj):
    """Independent NumPy oracle for the reference C++ normalization
    (fcma_extension.cc:29-92)."""
    out = np.array(corr, dtype=np.float32, copy=True)
    b, e, v = out.shape
    n_subjs = e // epochs_per_subj
    num = 1.0 + out
    den = 1.0 - out
    num[num <= 0] = 1e-4
    den[den <= 0] = 1e-4
    out = 0.5 * np.log(num / den)
    for s in range(n_subjs):
        sl = slice(s * epochs_per_subj, (s + 1) * epochs_per_subj)
        blockv = out[:, sl, :]
        mean = blockv.mean(axis=1, keepdims=True)
        var = (blockv ** 2).mean(axis=1, keepdims=True) - mean ** 2
        inv = np.where(var <= 0, 0.0, 1.0 / np.sqrt(np.maximum(var, 1e-30)))
        out[:, sl, :] = (blockv - mean) * inv
    return out


def test_compute_correlation_matches_corrcoef():
    rng = np.random.RandomState(0)
    m1 = rng.randn(10, 40).astype(np.float32)
    m2 = rng.randn(7, 40).astype(np.float32)
    corr = compute_correlation(m1, m2)
    assert corr.shape == (10, 7)
    expected = np.corrcoef(np.vstack([m1, m2]))[:10, 10:]
    assert np.allclose(corr, expected, atol=1e-5)


def test_compute_correlation_zero_variance():
    rng = np.random.RandomState(1)
    m1 = rng.randn(3, 20).astype(np.float32)
    m1[1] = 5.0  # constant row
    corr = compute_correlation(m1, m1)
    assert np.allclose(corr[1], 0.0)
    corr_nan = compute_correlation(m1, m1, return_nans=True)
    assert np.all(np.isnan(corr_nan[1]))
    with pytest.raises(ValueError):
        compute_correlation(m1, rng.randn(3, 21))


def test_correlate_epochs_layout():
    rng = np.random.RandomState(2)
    E, V, T, B = 4, 12, 30, 5
    data = rng.randn(E, V, T).astype(np.float32)
    norm = np.asarray(normalize_for_correlation(data, 2))
    corr = np.asarray(correlate_epochs(norm[:, :B], norm))
    assert corr.shape == (B, E, V)
    # spot-check against per-epoch corrcoef
    for e in range(E):
        expected = np.corrcoef(data[e])[:B, :]
        assert np.allclose(corr[:, e, :], expected, atol=1e-5)


def test_fisher_z_clamps():
    r = np.array([0.0, 0.5, 1.0, -1.0], dtype=np.float32)
    z = np.asarray(fisher_z(r))
    assert z[0] == 0.0
    assert np.isclose(z[1], np.arctanh(0.5), atol=1e-6)
    assert np.isfinite(z[2]) and np.isfinite(z[3])


def test_within_subject_normalization_matches_oracle():
    rng = np.random.RandomState(3)
    corr = (rng.rand(6, 8, 10).astype(np.float32) * 1.8 - 0.9)
    got = np.asarray(within_subject_normalization(corr, epochs_per_subj=4))
    expected = _np_reference_normalization(corr, 4)
    assert np.allclose(got, expected, atol=1e-4)
    # each subject-block now has ~zero mean, unit variance per (voxel, col)
    assert np.allclose(got[:, :4].mean(axis=1), 0.0, atol=1e-5)


def test_masked_log():
    x = np.array([-1.0, 0.0, 1.0, np.e], dtype=np.float32)
    out = np.asarray(masked_log(x))
    assert out[0] == -np.inf and out[1] == -np.inf
    assert np.isclose(out[2], 0.0) and np.isclose(out[3], 1.0, atol=1e-6)


def test_jax_phase_randomize_preserves_spectrum():
    import jax
    rng = np.random.RandomState(4)
    data = rng.randn(40, 3, 2).astype(np.float32)
    out = np.asarray(jstats.phase_randomize(jax.random.PRNGKey(0), data))
    assert out.shape == data.shape
    assert not np.allclose(out, data)
    assert np.allclose(np.abs(np.fft.fft(data, axis=0)),
                       np.abs(np.fft.fft(out, axis=0)), atol=1e-3)
    # odd length
    out_odd = np.asarray(
        jstats.phase_randomize(jax.random.PRNGKey(1), data[:39]))
    assert np.allclose(np.abs(np.fft.fft(data[:39], axis=0)),
                       np.abs(np.fft.fft(out_odd, axis=0)), atol=1e-3)


def test_jax_p_from_null():
    null = np.array([-2.0, -1.0, 0.0, 1.0, 2.0])
    assert np.isclose(
        np.asarray(jstats.p_from_null(3.0, null, side="right", exact=True)),
        0.0)
    assert np.isclose(
        np.asarray(jstats.p_from_null(3.0, null, side="right")), 1 / 6)
    # left: {-2,-1,0} <= 0.5 -> (3+1)/(5+1)
    assert np.isclose(
        np.asarray(jstats.p_from_null(0.5, null, side="left")), 4 / 6)
    # two-sided exact: |{-2,2}| >= 1.5 -> 2/5
    assert np.isclose(
        np.asarray(jstats.p_from_null(1.5, null, side="two-sided",
                                      exact=True)), 2 / 5)
    with pytest.raises(ValueError, match="side"):
        jstats.p_from_null(0.0, null, side="middle")


def test_jax_phase_randomize_2d_squeeze():
    """A [T, subjects] input takes the 2-D squeeze path and returns the
    same shape with the spectrum preserved (reference
    utils/utils.py:720-801 accepts both layouts)."""
    import jax
    rng = np.random.RandomState(6)
    data = rng.randn(40, 4).astype(np.float32)
    out = np.asarray(jstats.phase_randomize(jax.random.PRNGKey(2), data))
    assert out.shape == data.shape
    assert np.allclose(np.abs(np.fft.fft(data, axis=0)),
                       np.abs(np.fft.fft(out, axis=0)), atol=1e-3)


def test_pallas_fcma_kernel_matches_xla_path():
    """The fused Pallas kernel (interpreter mode on CPU) reproduces the
    XLA correlate+normalize pipeline."""
    import jax.numpy as jnp

    from brainiak_tpu.ops.pallas_kernels import fcma_corr_normalize

    rng = np.random.RandomState(0)
    E, T, B, V = 8, 40, 16, 32
    data = rng.randn(E, T, V).astype(np.float32)
    norm = np.asarray(normalize_for_correlation(
        jnp.asarray(data).transpose(0, 2, 1), 2)).transpose(0, 2, 1)
    blk = norm[:, :, :B]

    expected = np.asarray(within_subject_normalization(
        np.asarray(correlate_epochs(
            jnp.asarray(blk.transpose(0, 2, 1)),
            jnp.asarray(norm.transpose(0, 2, 1)))), 4))
    got = np.asarray(fcma_corr_normalize(
        jnp.asarray(blk), jnp.asarray(norm), 4, tile_b=8, tile_v=16,
        interpret=True))
    assert got.shape == expected.shape == (B, E, V)
    # self-correlation entries (voxel b with itself) sit exactly at the
    # clamped Fisher-z / zero-variance threshold, where fp-order
    # differences between implementations are amplified; the reference's
    # own normalization has the same knife edge.  Compare all other
    # entries tightly.
    mask = np.ones_like(got, dtype=bool)
    for b in range(B):
        mask[b, :, b] = False
    assert np.allclose(got[mask], expected[mask], atol=1e-4)


def test_pallas_gram_kernel_matches_unfused():
    """The Gram-accumulating kernel (voxel grid axis as an in-VMEM
    reduction) equals corr-normalize followed by the Gram einsum."""
    import jax.numpy as jnp

    from brainiak_tpu.ops.pallas_kernels import (
        fcma_corr_normalize,
        fcma_gram,
    )

    rng = np.random.RandomState(1)
    E, T, B, V = 8, 40, 16, 48
    data = rng.randn(E, T, V).astype(np.float32)
    norm = np.asarray(normalize_for_correlation(
        jnp.asarray(data).transpose(0, 2, 1), 2)).transpose(0, 2, 1)
    blk = norm[:, :, :B]

    corr = np.asarray(fcma_corr_normalize(
        jnp.asarray(blk), jnp.asarray(norm), 4, tile_b=8, tile_v=16,
        interpret=True))
    expected = np.einsum('bev,bfv->bef', corr, corr)
    got = np.asarray(fcma_gram(
        jnp.asarray(blk), jnp.asarray(norm), 4, tile_b=8, tile_v=16,
        interpret=True))
    assert got.shape == (B, E, E)
    assert np.allclose(got, expected, atol=1e-3)
    # zero-padded voxel columns must contribute exactly nothing
    norm_pad = np.concatenate(
        [norm, np.zeros((E, T, 16), np.float32)], axis=2)
    got_pad = np.asarray(fcma_gram(
        jnp.asarray(blk), jnp.asarray(norm_pad), 4, tile_b=8, tile_v=16,
        interpret=True))
    assert np.allclose(got_pad, got, atol=1e-5)


def test_pallas_production_tiles_multistep():
    """The fused kernels at REAL production tile sizes — the tiles
    ``pick_tiles`` returns on a TPU for whole-brain extents — with a
    multi-step voxel reduction and nonzero padding on both voxel axes.

    The tiny-tile tests above (tile_b=8/tile_v=16) cannot catch a
    layout or padding bug that only appears at the (128, 512) tiles the
    chip actually runs; this bounded interpret-mode case executes that
    grid: 2 block tiles x 2 voxel tiles, 56 pad lanes on B and 24 on V.
    """
    import jax.numpy as jnp

    from brainiak_tpu.ops.pallas_kernels import (
        fcma_corr_normalize,
        fcma_gram,
        pad_to_tiles,
        pick_tiles,
    )

    E, T, B, V, eps = 8, 16, 200, 1000, 4
    assert pick_tiles(E, T, B, V) == (128, 512, True)

    # DISJOINT selected/all voxel sets (the two-mask form): no
    # self-correlation knife edges, so Pallas and XLA must agree
    # tightly at EVERY entry and the Gram oracle can come from the
    # independent XLA pipeline (clamp semantics have their own test
    # below)
    rng = np.random.RandomState(7)
    data = rng.randn(E, T, V + B).astype(np.float32)
    norm = np.asarray(normalize_for_correlation(
        jnp.asarray(data).transpose(0, 2, 1), 2)).transpose(0, 2, 1)
    blk, norm = norm[:, :, V:], norm[:, :, :V]

    blk_p, data_p, tile_b, tile_v, fits = pad_to_tiles(
        jnp.asarray(blk), jnp.asarray(norm))
    assert fits and (tile_b, tile_v) == (128, 512)
    assert blk_p.shape == (E, T, 256) and data_p.shape == (E, T, 1024)

    got = np.asarray(fcma_corr_normalize(
        blk_p, data_p, eps, tile_b=tile_b, tile_v=tile_v,
        interpret=True))[:B, :, :V]
    expected = np.asarray(within_subject_normalization(
        np.asarray(correlate_epochs(
            jnp.asarray(blk.transpose(0, 2, 1)),
            jnp.asarray(norm.transpose(0, 2, 1)))), eps))
    # 5e-4: the per-subject z-score divides by an across-4-epochs std
    # that can be small, amplifying fp32 summation-order noise; layout
    # or padding bugs produce O(1) errors, far above this
    assert np.allclose(got, expected, atol=5e-4)

    # the Gram's voxel grid axis takes TWO accumulation steps here, and
    # the 24 zero pad lanes must contribute exactly nothing — the
    # oracle is the XLA path's UNPADDED normalized correlation, so a
    # pad-lane leak shared by both Pallas outputs cannot cancel
    got_gram = np.asarray(fcma_gram(
        blk_p, data_p, eps, tile_b=tile_b, tile_v=tile_v,
        interpret=True))[:B]
    expected_gram = np.einsum('bev,bfv->bef', expected, expected)
    assert np.allclose(got_gram, expected_gram, rtol=1e-4, atol=1e-2)


def test_pick_tiles_budget_edges():
    """The VMEM tile chooser across its regimes: shrink-to-fit on the
    voxel then block axis, the doesn't-fit signal, and the callers'
    fallback contract (ValueError pointing at the XLA path)."""
    import jax.numpy as jnp
    import pytest

    from brainiak_tpu.ops.pallas_kernels import (
        _VMEM_BUDGET_FLOATS,
        fcma_corr_normalize,
        fcma_gram,
        fcma_sample_gram,
        pad_to_tiles,
        pick_tiles,
    )

    def used(e, t, tb, tv):
        return 2 * e * t * (tb + tv) + 5 * e * tb * tv

    # whole-brain E=32: (128, 512) blows the budget, the chooser must
    # shrink and what it returns must actually fit
    tb, tv, fits = pick_tiles(32, 150, 1024, 65536)
    assert fits and used(32, 150, tb, tv) <= _VMEM_BUDGET_FLOATS
    assert tb in (8, 16, 32, 64, 128) and tv % 128 == 0

    # epoch x TR extent so large even (8, 128) tiles exceed the budget
    big_e, big_t = 64, 4096  # 2*64*4096*(8+128) ~ 71M floats
    tb, tv, fits = pick_tiles(big_e, big_t, 256, 1024)
    assert not fits

    # callers refuse with a pointer to the XLA fallback...
    blk = jnp.zeros((big_e, big_t, 8), jnp.float32)
    data = jnp.zeros((big_e, big_t, 128), jnp.float32)
    with pytest.raises(ValueError, match="XLA path"):
        fcma_corr_normalize(blk, data, 4, interpret=True)
    with pytest.raises(ValueError, match="XLA path"):
        fcma_gram(blk, data, 4, interpret=True)
    with pytest.raises(ValueError, match="XLA path"):
        fcma_sample_gram(blk, data, 4, interpret=True)
    # ...and pad_to_tiles reports the no-fit without padding anything
    blk_p, data_p, _, _, fits = pad_to_tiles(blk, data)
    assert not fits and blk_p is blk and data_p is data

    # volumes smaller than one tile clamp to the full extent
    assert pick_tiles(8, 40, 4, 60) == (4, 60, True)


def test_pallas_clamp_confinement():
    """Pallas-vs-XLA normalized correlation agrees to fp32 tolerance
    everywhere EXCEPT entries whose subject-epoch group contains a
    clamped |r| -> 1 correlation.

    At |r| -> 1 the Fisher z derivative diverges, so last-ulp
    correlation differences between the two matmul pipelines legally
    explode there — and the per-subject z-score then spreads that
    entry's delta across its whole (voxel-pair, subject) group.  This
    test pins that the large deltas are CONFINED to those groups: a
    regression leaking error into mid-range r fails the tight branch.
    """
    import jax.numpy as jnp

    from brainiak_tpu.ops.pallas_kernels import fcma_corr_normalize

    E, T, B, V, eps = 8, 20, 16, 32, 4
    rng = np.random.RandomState(3)
    data = rng.randn(E, T, V).astype(np.float32)
    data[:, :, 21] = data[:, :, 5]    # r = +1 against block voxel 5
    data[:, :, 27] = -data[:, :, 11]  # r = -1 against block voxel 11
    norm = np.asarray(normalize_for_correlation(
        jnp.asarray(data).transpose(0, 2, 1), 2)).transpose(0, 2, 1)
    blk = norm[:, :, :B]

    corr = np.asarray(correlate_epochs(
        jnp.asarray(blk.transpose(0, 2, 1)),
        jnp.asarray(norm.transpose(0, 2, 1))))  # [B, E, V]
    expected = np.asarray(within_subject_normalization(corr, eps))
    got = np.asarray(fcma_corr_normalize(
        jnp.asarray(blk), jnp.asarray(norm), eps, tile_b=8, tile_v=16,
        interpret=True))

    # a near-clamp r anywhere in a subject's epochs poisons that whole
    # (voxel-pair, subject) z-score group
    near = (np.abs(corr) > 0.999).reshape(B, E // eps, eps, V)
    poisoned = np.broadcast_to(
        near.any(axis=2, keepdims=True), near.shape).reshape(B, E, V)
    # the planted duplicates (and self-correlations) must actually be
    # exercising the clamp, and must not drown the clean set
    assert poisoned.any() and poisoned[5, :, 21].all() \
        and poisoned[11, :, 27].all()
    assert (~poisoned).mean() > 0.9
    assert np.allclose(got[~poisoned], expected[~poisoned], atol=1e-4)


def test_ring_correlation_matches_dense():
    """Ring-sharded V x V correlation over an 8-way voxel mesh equals the
    dense corrcoef, with only shard-resident data per device."""
    from brainiak_tpu.ops.ring import ring_correlation
    from brainiak_tpu.parallel import make_mesh
    from tests.conftest import mesh_atol

    rng = np.random.RandomState(0)
    T, V = 50, 64
    data = rng.randn(T, V)
    mesh = make_mesh(("voxel",), (8,))
    corr = np.asarray(ring_correlation(data, mesh))
    dense = np.corrcoef(data.T)
    assert corr.shape == (V, V)
    assert np.allclose(corr, dense, atol=mesh_atol())
    # constant column -> zero row/col (matching compute_correlation)
    data2 = data.copy()
    data2[:, 5] = 3.0
    corr2 = np.asarray(ring_correlation(data2, mesh))
    assert np.allclose(corr2[5], 0.0) and np.allclose(corr2[:, 5], 0.0)
    # cross-correlation against a second array (the LOO-ISFC pattern)
    other = rng.randn(T, V)
    cross = np.asarray(ring_correlation(data, mesh, data_b=other))
    dense_cross = np.corrcoef(data.T, other.T)[:V, V:]
    assert np.allclose(cross, dense_cross, atol=mesh_atol())
    # precondition guards: voxel count must divide the ring, and
    # data_b must match shape
    with pytest.raises(AssertionError, match="divisible"):
        ring_correlation(data[:, :63], mesh)
    with pytest.raises(AssertionError, match="same shape"):
        ring_correlation(data, mesh, data_b=other[:, :32])


def test_compute_correlation_validation_and_recon_residual():
    """compute_correlation's input contract and the TFA recon kernel
    (reference fcma/util.py + tfa_extension.cpp:169-239)."""
    with pytest.raises(ValueError, match="2D"):
        compute_correlation(np.ones(5), np.ones((2, 5)))
    with pytest.raises(ValueError, match="discrepancy"):
        compute_correlation(np.ones((2, 4)), np.ones((2, 5)))

    from brainiak_tpu.ops.rbf import reconstruction_residual
    from tests.conftest import mesh_atol
    rng = np.random.RandomState(1)
    X = rng.randn(10, 6).astype(np.float32)
    F = rng.randn(10, 3).astype(np.float32)
    W = rng.randn(3, 6).astype(np.float32)
    got = np.asarray(reconstruction_residual(X, F, W, 0.5))
    # mesh_atol: the kernel's matmul runs at default precision, which
    # on TPU means bf16 passes
    np.testing.assert_allclose(got, 0.5 * (X - F @ W),
                               atol=max(mesh_atol(), 1e-5))
