"""The interior-point C-SVC dual solver must match libsvm exactly.

The IPM (`ops.svm.svm_fit_dual_ipm`) is the independent cross-check for
the SMO budget: same dual problem, different algorithm, so agreement
with both the SMO path and sklearn's SVC is strong evidence either
solver is converged (reference semantics:
tests/fcma/test_voxel_selection.py + sklearn SVC precomputed).
"""

import numpy as np
import jax.numpy as jnp
from sklearn import model_selection
from sklearn.svm import SVC

from brainiak_tpu.ops.svm import svm_cv_accuracy, svm_fit_dual_ipm


def test_ipm_matches_sklearn_duals():
    rng = np.random.RandomState(0)
    checked = 0
    for _ in range(12):
        n = int(rng.choice([8, 12, 16, 24]))
        feat = rng.randn(n, 40)
        kernel = feat @ feat.T
        y = np.where(rng.rand(n) > 0.5, 1.0, -1.0)
        if np.abs(y.sum()) == n:
            y[0] = -y[0]
        box = np.ones(n)
        box[rng.rand(n) < 0.3] = 0.0  # random fold/pair exclusions
        act = box > 0
        if not ((y[act] > 0).any() and (y[act] < 0).any()):
            continue
        alpha, bias, gap = svm_fit_dual_ipm(
            jnp.asarray(kernel), jnp.asarray(y), jnp.asarray(box),
            n_iters=40)
        ref = SVC(kernel='precomputed', C=1.0).fit(
            kernel[np.ix_(act, act)], y[act])
        a_ref = np.zeros(n)
        a_ref[np.where(act)[0][ref.support_]] = np.abs(ref.dual_coef_[0])
        assert np.max(np.abs(np.asarray(alpha) - a_ref)) < 1e-3
        # the violating-pair gap is a gradient-space sup-norm: a dual
        # error eps moves it by up to eps * n * max|K|, so scale the
        # tolerance accordingly (the dual parity above is the contract)
        assert float(gap) < 1e-3 * n * (1.0 + np.abs(kernel).max())
        checked += 1
    assert checked >= 8


def test_cv_chunked_path_matches_unchunked():
    """Voxel batches beyond the VMEM chunk budget split into multiple
    _cv_batch dispatches; the split must be invisible in the results."""
    import brainiak_tpu.ops.svm as svm_mod

    rng = np.random.RandomState(5)
    n_epochs = 8
    labels = np.array([0, 1] * 4)
    feats = rng.randn(6, n_epochs, 16).astype(np.float32)
    kernels = np.einsum('vef,vgf->veg', feats, feats)
    whole, whole_gap = svm_cv_accuracy(kernels, labels, 2, n_iters=30,
                                       return_gap=True)
    budget = svm_mod._CV_CHUNK_BUDGET_FLOATS
    svm_mod._CV_CHUNK_BUDGET_FLOATS = 1  # force chunk=1: 6 dispatches
    try:
        parts, parts_gap = svm_cv_accuracy(kernels, labels, 2,
                                           n_iters=30, return_gap=True)
    finally:
        svm_mod._CV_CHUNK_BUDGET_FLOATS = budget
    np.testing.assert_allclose(np.asarray(parts), np.asarray(whole),
                               atol=0)
    np.testing.assert_allclose(np.asarray(parts_gap),
                               np.asarray(whole_gap), rtol=1e-6)


def test_cv_rejects_single_class():
    rng = np.random.RandomState(6)
    kernels = rng.randn(2, 8, 8).astype(np.float32)
    import pytest
    with pytest.raises(ValueError, match="two classes"):
        svm_cv_accuracy(kernels, np.zeros(8, dtype=int), 2)


def test_ipm_cv_float32():
    """fp32 regression: as the interior path converges, ``ub - a``
    underflows at fp32 ulp and the barrier divisions NaN without the
    boundary floor — the f64 suite cannot catch that."""
    rng = np.random.RandomState(3)
    n_epochs = 16
    labels = np.array([0, 1] * 8)
    kernels = []
    for _ in range(32):
        feat = rng.randn(n_epochs, 64).astype(np.float32)
        feat += 0.5 * labels[:, None].astype(np.float32) \
            * rng.randn(1, 64).astype(np.float32)
        kernels.append(feat @ feat.T / 64)
    kernels = np.stack(kernels).astype(np.float32)
    acc_ipm = svm_cv_accuracy(kernels, labels, 4, n_iters=30,
                              solver='ipm')
    acc_smo = svm_cv_accuracy(kernels, labels, 4, n_iters=50,
                              solver='smo')
    assert np.all(np.isfinite(acc_ipm))
    # identical up to single near-boundary test samples (1/16 epochs)
    assert np.abs(acc_ipm - acc_smo).max() <= 1.0 / n_epochs + 1e-9
    assert abs(float(acc_ipm.mean() - acc_smo.mean())) < 0.01


def test_ipm_cv_matches_smo_and_sklearn():
    rng = np.random.RandomState(1)
    for n_classes, n_epochs in [(2, 16), (3, 18)]:
        labels = np.tile(np.arange(n_classes), n_epochs // n_classes)
        kernels = []
        for _ in range(20):
            feat = rng.randn(n_epochs, 30) \
                + 0.8 * np.eye(n_classes)[labels] @ rng.randn(n_classes,
                                                              30)
            kernels.append(feat @ feat.T)
        kernels = np.stack(kernels)
        acc_ipm = svm_cv_accuracy(kernels, labels, 4, n_iters=40,
                                  solver='ipm')
        acc_smo = svm_cv_accuracy(kernels, labels, 4, n_iters=50,
                                  solver='smo')
        np.testing.assert_allclose(acc_ipm, acc_smo, atol=1e-9)
        skf = model_selection.StratifiedKFold(n_splits=4, shuffle=False)
        acc_ref = np.array([
            model_selection.cross_val_score(
                SVC(kernel='precomputed', C=1.0), k, labels,
                cv=skf).mean()
            for k in kernels])
        np.testing.assert_allclose(acc_ipm, acc_ref, atol=1e-9)
