"""Fused-kernel parity (ISSUE 11): the fused rotate-multiply-
accumulate SUMMA ring step, the device-side epoch norm, and the
MTTKRP-style RBF factor contractions, each against its unfused
reference on the CPU/interpreter backends."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from brainiak_tpu.obs import metrics as obs_metrics
from brainiak_tpu.ops import distla, rbf
from brainiak_tpu.ops.kernels import epoch_norm as en
from brainiak_tpu.ops.kernels import ring
from brainiak_tpu.parallel import make_mesh


# -- fused ring step --------------------------------------------------

def test_ring_mma_pallas_matches_xla_update():
    """The Pallas step body (interpreter mode) and the XLA
    dynamic-update-slice step place identical blocks and leave every
    other block untouched."""
    rng = np.random.RandomState(0)
    t, vl, b, shards = 16, 32, 8, 4
    z = jnp.asarray(rng.randn(t, vl).astype(np.float32))
    rot = jnp.asarray(rng.randn(t, b).astype(np.float32))
    out0 = jnp.asarray(np.full((vl, shards * b), -1.0, np.float32))
    got = np.asarray(ring.ring_mma(out0, z, rot, 2, n_shards=shards,
                                   tile_r=16, interpret=True))
    ref = np.asarray(ring.mma_update(out0, z, rot, 2 * b))
    assert np.allclose(got, ref, atol=1e-5)
    # untouched blocks alias straight through
    assert np.allclose(got[:, :2 * b], -1.0)
    assert np.allclose(got[:, 3 * b:], -1.0)


def test_ring_mma_under_scan_with_traced_owner():
    """The Pallas step composes under lax.scan with a traced owner
    index (the real SUMMA use) — all blocks land correctly."""
    rng = np.random.RandomState(1)
    t, vl, b, shards = 8, 16, 8, 4
    z = jnp.asarray(rng.randn(t, vl).astype(np.float32))
    rot = jnp.asarray(rng.randn(t, b).astype(np.float32))

    def step(out, s):
        return ring.ring_mma(out, z, rot, s, n_shards=shards,
                             tile_r=8, interpret=True), None

    out, _ = jax.lax.scan(step, jnp.zeros((vl, shards * b),
                                          jnp.float32),
                          jnp.arange(shards, dtype=jnp.int32))
    ref = np.asarray(z).T @ np.asarray(rot)
    assert np.allclose(np.asarray(out), np.tile(ref, (1, shards)),
                       atol=1e-5)


def test_summa_gram_fused_matches_unfused_and_dense():
    """The fused ring step reproduces the unfused three-stage
    formulation (and the dense Gram) bit-for-tolerance, on even and
    uneven splits."""
    rng = np.random.RandomState(2)
    t, v = 20, 64
    data = rng.randn(t, v).astype(np.float32)
    z = (data - data.mean(0)) / (data.std(0) * np.sqrt(t))
    dense = z.T @ z
    mesh = make_mesh(("voxel",), (8,))
    for cols in (v, v - 7):
        fused = np.asarray(distla.summa_gram(
            data[:, :cols], mesh, ring_step="fused"))
        unfused = np.asarray(distla.summa_gram(
            data[:, :cols], mesh, ring_step="unfused"))
        assert np.allclose(fused, dense[:cols, :cols], atol=5e-4)
        assert np.allclose(fused, unfused, atol=1e-6)


def test_summa_gram_fused_nan_columns_propagate():
    """NaN voxels propagate whole NaN rows/columns through the fused
    step, exactly like the unfused reference."""
    rng = np.random.RandomState(3)
    data = rng.randn(16, 32).astype(np.float32)
    data[:, 5] = np.nan
    mesh = make_mesh(("voxel",), (8,))
    got = np.asarray(distla.summa_gram(data, mesh,
                                       ring_step="fused"))
    assert np.all(np.isnan(got[5]))
    assert np.all(np.isnan(got[:, 5]))
    assert np.isnan(got).sum() == 2 * 32 - 1


def test_ring_step_mode_selection(monkeypatch):
    """Auto mode: Pallas only on TPU with tileable extents; the env
    override wins; unfused is never auto-selected."""
    assert ring.ring_step_mode(150, 1024, 1024,
                               backend="cpu") == "fused"
    assert ring.ring_step_mode(152, 1024, 1024,
                               backend="tpu") == "pallas"
    # non-tileable extents fall back to the XLA fused step
    assert ring.ring_step_mode(152, 100, 100,
                               backend="tpu") == "fused"
    monkeypatch.setenv(ring.RING_STEP_ENV, "unfused")
    assert ring.ring_step_mode(152, 1024, 1024,
                               backend="tpu") == "unfused"


def test_pick_ring_tiles_respects_budget():
    tile_r, fits = ring.pick_ring_tiles(152, 4096, 1024)
    assert fits and 4096 % tile_r == 0
    used = 2 * 152 * (1024 + tile_r) + 2 * tile_r * 1024
    assert used <= ring._VMEM_BUDGET_FLOATS
    # an epoch x TR extent too large for any tile reports not-fits
    assert not ring.pick_ring_tiles(200_000, 4096, 4096)[1]


# -- device epoch norm ------------------------------------------------

def _np_ref(mat):
    rows = mat.shape[0]
    mean = mat.mean(axis=0)
    std = mat.std(axis=0)
    with np.errstate(divide="ignore", invalid="ignore"):
        out = (mat - mean) / (std * np.sqrt(rows))
    return np.nan_to_num(out, nan=0.0, posinf=0.0, neginf=0.0)


def test_epoch_zscore_device_matches_numpy(monkeypatch):
    monkeypatch.setenv(en.EPOCH_NORM_ENV, "device")
    rng = np.random.RandomState(0)
    mat = rng.randn(50, 37).astype(np.float32)
    mat[:, 5] = 2.5  # constant column -> exact zeros
    mat[3, 7] = np.nan  # NaN input -> zeroed column, not poison
    got = en.epoch_zscore(mat)
    assert np.allclose(got, _np_ref(mat), atol=1e-5)
    assert np.all(got[:, 5] == 0.0)
    assert np.all(np.isfinite(got))


def test_normalize_epochs_groups_shapes_and_preserves_order(
        monkeypatch):
    """Mixed epoch lengths batch by shape (one dispatch per group)
    and the output order matches the input order."""
    monkeypatch.setenv(en.EPOCH_NORM_ENV, "device")
    rng = np.random.RandomState(1)
    mats = [rng.randn(12, 9).astype(np.float32),
            rng.randn(20, 9).astype(np.float32),
            rng.randn(12, 9).astype(np.float32)]
    out = en.normalize_epochs(mats)
    for mat, got in zip(mats, out):
        assert got.shape == mat.shape
        assert np.allclose(got, _np_ref(mat), atol=1e-5)


def test_normalize_epochs_numpy_fallback_forced(monkeypatch):
    monkeypatch.setenv(en.EPOCH_NORM_ENV, "numpy")
    rng = np.random.RandomState(2)
    mats = [rng.randn(10, 6).astype(np.float32)]
    out = en.normalize_epochs(mats)
    assert np.allclose(out[0], _np_ref(mats[0]), atol=1e-6)


def test_epoch_norm_pallas_tile_path_matches(monkeypatch):
    """The Pallas voxel-tile kernel (interpreter mode) matches the
    fused-XLA program on a tile-aligned batch."""
    rng = np.random.RandomState(3)
    batch = rng.randn(2, 16, 256).astype(np.float32)
    got = np.asarray(en._pallas_batch_zscore(
        jnp.asarray(batch), tile_v=128, interpret=True))
    ref = np.stack([_np_ref(batch[i]) for i in range(2)])
    assert np.allclose(got, ref, atol=1e-5)


def test_preprocessing_epoch_separation_still_normalizes():
    """The ingest path (_separate_epochs) keeps its output contract
    through the device-side normalization."""
    from brainiak_tpu.fcma.preprocessing import _separate_epochs

    rng = np.random.RandomState(2)
    activity = [rng.randn(10, 30).astype(np.float32)]
    epochs = np.zeros((1, 2, 30))
    epochs[0, 0, 3:9] = 1
    epochs[0, 1, 15:23] = 1
    raw, labels = _separate_epochs(activity, [epochs])
    assert len(raw) == 2 and labels == [0, 0]
    assert raw[0].shape == (6, 10)
    assert np.allclose(raw[0].std(axis=0) * np.sqrt(6), 1.0,
                       atol=1e-5)


def test_fcma_preprocessing_no_native_import():
    """Acceptance: the FCMA ingest path no longer imports
    brainiak_tpu.native."""
    import ast
    import inspect

    from brainiak_tpu.fcma import preprocessing

    tree = ast.parse(inspect.getsource(preprocessing))
    for node in ast.walk(tree):
        if isinstance(node, ast.ImportFrom):
            assert "native" not in (node.module or "")
        if isinstance(node, ast.Import):
            assert all("native" not in a.name for a in node.names)


def test_native_shim_emits_deprecation_warning():
    import importlib
    import sys
    import warnings

    sys.modules.pop("brainiak_tpu.native", None)
    with warnings.catch_warnings(record=True) as caught:
        warnings.simplefilter("always")
        importlib.import_module("brainiak_tpu.native")
    assert any(issubclass(w.category, DeprecationWarning)
               and "epoch_norm" in str(w.message) for w in caught)


# -- MTTKRP factor reconstruction -------------------------------------

def test_rbf_factors_matches_naive_broadcast():
    rng = np.random.RandomState(0)
    R = rng.randn(200, 3)
    C = rng.randn(7, 3)
    W = np.abs(rng.rand(7, 1)) + 0.5
    naive = np.exp(-((R[:, None, :] - C[None]) ** 2).sum(-1) / W.T)
    got = np.asarray(rbf.rbf_factors(jnp.asarray(R), jnp.asarray(C),
                                     jnp.asarray(W)))
    assert np.allclose(got, naive, atol=1e-5)


def test_rbf_weight_products_match_materialized_einsum():
    rng = np.random.RandomState(1)
    R = rng.randn(300, 3)
    C = rng.randn(5, 3)
    W = np.abs(rng.rand(5)) + 1.0
    X = rng.randn(300, 40)
    F = np.exp(-((R[:, None, :] - C[None]) ** 2).sum(-1) / W[None])
    g, b = rbf.rbf_weight_products(jnp.asarray(R), jnp.asarray(C),
                                   jnp.asarray(W), jnp.asarray(X),
                                   chunk=128)
    assert np.allclose(np.asarray(g), np.einsum('vk,vl->kl', F, F),
                       atol=1e-4)
    assert np.allclose(np.asarray(b), np.einsum('vk,vt->kt', F, X),
                       atol=1e-4)


@pytest.mark.parametrize("loss", ["linear", "soft_l1"])
def test_rbf_residual_sum_matches_naive(loss):
    rng = np.random.RandomState(2)
    R = rng.randn(250, 3)
    C = rng.randn(4, 3)
    W = np.abs(rng.rand(4)) + 1.0
    X = rng.randn(250, 30)
    Wt = rng.randn(4, 30)
    sigma = 0.7
    F = np.exp(-((R[:, None, :] - C[None]) ** 2).sum(-1) / W[None])
    sq = (sigma * (X - F @ Wt)) ** 2
    ref = np.sum(2.0 * (np.sqrt(1.0 + sq) - 1.0)) \
        if loss == "soft_l1" else np.sum(sq)
    got = float(rbf.rbf_residual_sum(
        jnp.asarray(R), jnp.asarray(C), jnp.asarray(W),
        jnp.asarray(X), jnp.asarray(Wt), sigma, nlss_loss=loss,
        chunk=64))
    assert np.isclose(got, ref, rtol=1e-5)


def test_rbf_residual_sum_masks_match_htfa_convention():
    """vmask/tmask zero pad voxels and TRs exactly as the
    materialized masked residual did."""
    rng = np.random.RandomState(3)
    R = rng.randn(100, 3)
    C = rng.randn(3, 3)
    W = np.abs(rng.rand(3)) + 1.0
    X = rng.randn(100, 20)
    Wt = rng.randn(3, 20)
    vm = (rng.rand(100) > 0.4).astype(float)
    tm = (rng.rand(20) > 0.3).astype(float)
    F = np.exp(-((R[:, None, :] - C[None]) ** 2).sum(-1) / W[None])
    Fm = F * vm[:, None]
    xm = X * vm[:, None] * tm[None, :]
    ref = np.sum(((0.5 * (xm - Fm @ Wt))
                  * (vm[:, None] * tm[None, :])) ** 2)
    got = float(rbf.rbf_residual_sum(
        jnp.asarray(R), jnp.asarray(C), jnp.asarray(W),
        jnp.asarray(xm), jnp.asarray(Wt), 0.5,
        vmask=jnp.asarray(vm), tmask=jnp.asarray(tm), chunk=32))
    assert np.isclose(got, ref, rtol=1e-5)


# -- retrace stability ------------------------------------------------

def test_fused_sites_do_not_retrace_on_repeat_calls():
    """Repeat calls at one configuration add zero program-builder
    cache misses on the fused sites (retrace_total{site=...} <= 1
    per fused site — ISSUE 11 acceptance)."""
    rng = np.random.RandomState(4)
    mesh = make_mesh(("voxel",), (8,))
    data = rng.randn(16, 32).astype(np.float32)
    mats = [rng.randn(64, 1024).astype(np.float32)]
    retrace = obs_metrics.counter("retrace_total")

    import os
    os.environ[en.EPOCH_NORM_ENV] = "device"
    try:
        for _ in range(2):
            distla.summa_gram(data, mesh, ring_step="fused")
            en.normalize_epochs(mats)
    finally:
        os.environ.pop(en.EPOCH_NORM_ENV, None)
    before = {site: retrace.value(site=site)
              for site in ("distla.summa", "fcma.epoch_norm")}
    distla.summa_gram(data, mesh, ring_step="fused")
    en.normalize_epochs([rng.randn(64, 1024).astype(np.float32)])
    for site, count in before.items():
        assert retrace.value(site=site) == count, site


def test_rbf_factors_accurate_at_offset_coordinates():
    """Review fix: real scanner coordinates (~200 mm offsets) must
    not lose accuracy to ||R||² − 2R·c cancellation — operands are
    centered before the matmul decomposition, and factors never
    exceed 1 (sq clamped at 0)."""
    rng = np.random.RandomState(0)
    R = (rng.randn(400, 3) * 5 + 200.0).astype(np.float32)
    C = (rng.randn(4, 3) * 5 + 200.0).astype(np.float32)
    W = (np.abs(rng.rand(4)) + 1.0).astype(np.float32)
    ref = np.exp(-((R[:, None, :].astype(np.float64)
                    - C[None].astype(np.float64)) ** 2).sum(-1)
                 / W[None])
    got = np.asarray(rbf.rbf_factors(
        jnp.asarray(R), jnp.asarray(C), jnp.asarray(W)))
    assert np.max(np.abs(got - ref)) < 5e-6
    assert got.max() <= 1.0


def test_epoch_norm_tile_picker_keeps_lane_alignment():
    """Review fix: the Pallas voxel tile must keep the lane (last)
    dimension 128-aligned or Mosaic rejects the block — unaligned
    widths fall back to the fused-XLA path instead."""
    assert en._pick_tile_v(16, 320) == 0    # 320 % 128 != 0
    assert en._pick_tile_v(16, 768) == 256  # halves to aligned
    assert en._pick_tile_v(16, 512) == 512
    assert en._pick_tile_v(7, 512) == 0     # sublane-unaligned T


def test_normalize_epochs_preserves_float64_dtype(monkeypatch):
    """Review fix: float64 epochs above the device threshold must
    not be silently downcast — when the backend would narrow the
    dtype, the group takes the exact host path instead."""
    monkeypatch.setenv(en.EPOCH_NORM_ENV, "device")
    x64 = jax.config.jax_enable_x64
    rng = np.random.RandomState(5)
    mats = [rng.randn(300, 300)]  # float64, > _MIN_DEVICE_ELEMS
    out = en.normalize_epochs(mats)
    assert out[0].dtype == np.float64
    tol = 1e-12 if not x64 else 1e-8  # host path is exact
    assert np.allclose(out[0], _np_ref(mats[0]), atol=tol)


def test_summa_gram_rejects_unknown_ring_step():
    """Review fix: a typo'd ring_step override raises instead of
    silently running a different kernel."""
    rng = np.random.RandomState(6)
    mesh = make_mesh(("voxel",), (8,))
    data = rng.randn(8, 16).astype(np.float32)
    with pytest.raises(ValueError, match="ring_step"):
        distla.summa_gram(data, mesh, ring_step="Pallas")


def test_vmem_budget_shared_across_kernel_modules():
    """Review fix: one budget constant — the ring and epoch-norm
    tile pickers read pallas_kernels' value, so a retune lands
    everywhere."""
    from brainiak_tpu.ops import pallas_kernels

    assert ring._VMEM_BUDGET_FLOATS \
        == pallas_kernels._VMEM_BUDGET_FLOATS
    assert en._vmem_budget_floats() \
        == pallas_kernels._VMEM_BUDGET_FLOATS
