"""Pod-scale distributed linear algebra (ops/distla) — ISSUE 6.

Numerics parity of the SUMMA ring against the replicated einsum
(:func:`brainiak_tpu.ops.correlation.correlate_epochs`) on the
8-device CPU mesh for even and uneven panel splits and
NaN-propagating columns; the checkpointable panel loop's mid-Gram
preemption resume; the budget dispatcher (a Gram whose replicated
working set exceeds the per-device budget completes via SUMMA
panels); the sharded batched eigh/Cholesky helpers; the SRM
fit-parity of the sharded-batched E-step solves; and the
``distla.*`` cost-record/span join for achieved-FLOP/s.
"""

import numpy as np
import pytest

import jax.numpy as jnp

from brainiak_tpu import obs
from brainiak_tpu.ops import distla
from brainiak_tpu.parallel import make_mesh
from tests.conftest import mesh_atol


def _dense_corr(data):
    """NumPy reference Pearson Gram with the layer's z-score
    semantics (constant columns -> 0, NaN columns -> NaN)."""
    data = np.asarray(data, dtype=float)
    t = data.shape[0]
    mean = data.mean(axis=0, keepdims=True)
    std = data.std(axis=0, keepdims=True)
    with np.errstate(invalid="ignore"):
        z = np.where(std > 0, (data - mean)
                     / (np.where(std > 0, std, 1.0) * np.sqrt(t)), 0.0)
    z = np.where(np.isnan(std), np.nan, z)
    return z.T @ z


def test_summa_gram_matches_replicated_einsum_even_split():
    """[V, V] SUMMA Gram == the replicated correlate_epochs einsum
    at an even panel split (64 voxels over the 8-way ring)."""
    from brainiak_tpu.ops.correlation import (correlate_epochs,
                                              normalize_for_correlation)

    rng = np.random.RandomState(0)
    data = rng.randn(20, 64)
    mesh = make_mesh(("voxel",), (8,))
    got = np.asarray(distla.summa_gram(data, mesh))
    z = np.asarray(normalize_for_correlation(jnp.asarray(data), 0))
    dense = np.asarray(
        correlate_epochs(z.T[None], z.T[None]))[:, 0, :]
    assert got.shape == (64, 64)
    assert np.allclose(got, dense, atol=max(mesh_atol(), 1e-5))


def test_summa_gram_uneven_split_and_cross():
    """Voxel counts that do NOT divide the ring are zero-padded and
    sliced (uneven panel split), for both the Gram and the
    cross-correlation (data_b) form."""
    rng = np.random.RandomState(1)
    data = rng.randn(16, 53)  # 53 % 8 != 0
    other = rng.randn(16, 53)
    mesh = make_mesh(("voxel",), (8,))
    got = np.asarray(distla.summa_gram(data, mesh))
    assert got.shape == (53, 53)
    assert np.allclose(got, _dense_corr(data), atol=1e-8)

    cross = np.asarray(distla.summa_gram(data, mesh, data_b=other))
    t = data.shape[0]

    def _z(d):
        return (d - d.mean(0)) / (d.std(0) * np.sqrt(t))

    assert np.allclose(cross, _z(data).T @ _z(other), atol=1e-8)
    with pytest.raises(ValueError, match="shape"):
        distla.summa_gram(data, mesh, data_b=other[:, :20])


def test_summa_gram_two_dimensional_mesh_ring():
    """A 2-D ('subject', 'voxel') mesh flattens into one SUMMA ring:
    the full 8-device grid participates and the result matches the
    single-axis ring and the dense reference."""
    rng = np.random.RandomState(2)
    data = rng.randn(12, 48)
    mesh2d = make_mesh(("subject", "voxel"), (2, 4))
    got = np.asarray(distla.summa_gram(data, mesh2d))
    assert np.allclose(got, _dense_corr(data), atol=1e-8)
    # explicit axis subset: ring over just the voxel axis of the 2-D
    # mesh (4 shards) gives the same numbers
    sub = np.asarray(distla.summa_gram(data, mesh2d,
                                       axis_names=("voxel",)))
    assert np.allclose(sub, got, atol=1e-8)
    with pytest.raises(ValueError, match="ring axes"):
        distla.summa_gram(data, mesh2d, axis_names=("nope",))


def test_summa_gram_nan_columns_propagate():
    """A NaN voxel column yields NaN across its row/column instead of
    fabricated finite correlations; finite entries are untouched."""
    rng = np.random.RandomState(3)
    data = rng.randn(16, 32)
    data[3, 5] = np.nan
    mesh = make_mesh(("voxel",), (8,))
    got = np.asarray(distla.summa_gram(data, mesh))
    assert np.all(np.isnan(got[5, :])) and np.all(np.isnan(got[:, 5]))
    keep = np.arange(32) != 5
    dense = _dense_corr(data)
    assert np.allclose(got[np.ix_(keep, keep)],
                       dense[np.ix_(keep, keep)], atol=1e-8)


def test_panel_gram_matches_and_checkpoints(tmp_path):
    """The host-driven panel loop reproduces the fused ring and a
    preemption mid-Gram resumes at the last completed panel (panels
    already computed are NOT redone)."""
    from brainiak_tpu.resilience.faults import PreemptionError, inject

    rng = np.random.RandomState(4)
    data = rng.randn(16, 64)
    mesh = make_mesh(("voxel",), (8,))
    dense = _dense_corr(data)

    plain = distla.panel_gram(data, mesh)
    assert np.allclose(plain, dense, atol=1e-8)

    ckpt = str(tmp_path / "panels")
    with inject("preempt", at_step=2) as fault:
        with pytest.raises(PreemptionError):
            distla.panel_gram(data, mesh, checkpoint_dir=ckpt,
                              checkpoint_every=1)
    assert fault.fired

    mem = obs.add_sink(obs.MemorySink())
    try:
        resumed = distla.panel_gram(data, mesh, checkpoint_dir=ckpt,
                                    checkpoint_every=1)
    finally:
        obs.remove_sink(mem)
    assert np.allclose(resumed, dense, atol=1e-8)
    chunks = [r for r in mem.records if r["kind"] == "span"
              and r["name"] == "distla.panel_chunk"]
    resumes = [r for r in mem.records if r["kind"] == "event"
               and r["name"] == "resume"]
    assert len(resumes) == 1
    # 8 panels total, 2 completed before the preemption
    assert len(chunks) == 6


def test_panel_gram_fingerprint_covers_data_b(tmp_path):
    """A resume against the same data but a DIFFERENT
    cross-correlation target must refuse (fresh checkpoint_dir), not
    mix rows of corr(data, X) with rows of corr(data, Y)."""
    from brainiak_tpu.resilience.faults import PreemptionError, inject

    rng = np.random.RandomState(11)
    data = rng.randn(16, 64)
    x = rng.randn(16, 64)
    y = rng.randn(16, 64)
    mesh = make_mesh(("voxel",), (8,))
    ckpt = str(tmp_path / "cross")
    with inject("preempt", at_step=2):
        with pytest.raises(PreemptionError):
            distla.panel_gram(data, mesh, data_b=x,
                              checkpoint_dir=ckpt, checkpoint_every=1)
    with pytest.raises(ValueError, match="different data"):
        distla.panel_gram(data, mesh, data_b=y,
                          checkpoint_dir=ckpt, checkpoint_every=1)


def test_gram_rejects_mismatched_data_b_on_every_branch():
    """The cross-Gram shape contract holds on the replicated branch
    too — not only once the data grows past the budget."""
    rng = np.random.RandomState(12)
    data = rng.randn(16, 32)
    with pytest.raises(ValueError, match="shape"):
        distla.gram(data, data_b=rng.randn(16, 20))


def test_gram_budget_dispatch_replicated_would_oom():
    """A voxel count whose replicated working set exceeds the
    per-device budget completes via SUMMA panels (the whole-brain
    acceptance shape, scaled to the CPU mesh): forcing the
    replicated einsum under the same budget refuses."""
    rng = np.random.RandomState(5)
    data = rng.randn(16, 128)
    mesh = make_mesh(("voxel",), (8,))
    budget = 64 << 10  # 64 KiB: the [V, V] output alone exceeds it
    with pytest.raises(ValueError, match="budget"):
        distla.gram(data, mesh=mesh, budget_bytes=budget,
                    force="replicated")
    out = np.asarray(distla.gram(data, mesh=mesh,
                                 budget_bytes=budget))
    assert np.allclose(out, _dense_corr(data), atol=1e-8)
    # under-budget problems keep the replicated einsum (no mesh
    # required) and agree with the ring
    small = np.asarray(distla.gram(data))
    assert np.allclose(small, out, atol=1e-8)
    with pytest.raises(ValueError, match="force"):
        distla.gram(data, force="both")


def test_batched_solves_sharded_over_subject_axis():
    """batched_eigh / batched_cholesky_solve lay the batch along the
    mesh's subject axis and match the NumPy per-subject solves."""
    rng = np.random.RandomState(6)
    s, k = 8, 5
    base = rng.randn(s, k, k)
    spd = base @ np.transpose(base, (0, 2, 1)) + 3 * np.eye(k)
    rhs = rng.randn(s, k, 2)
    mesh = make_mesh(("subject",), (8,))

    solved = np.asarray(distla.batched_cholesky_solve(
        jnp.asarray(spd), jnp.asarray(rhs), mesh=mesh))
    assert np.allclose(solved, np.linalg.solve(spd, rhs), atol=1e-8)

    w, q = distla.batched_eigh(jnp.asarray(spd), mesh=mesh)
    recon = np.asarray(jnp.einsum('sik,sk,sjk->sij', q, w, q))
    assert np.allclose(recon, spd, atol=1e-8)

    # non-divisible batch falls back to the plain vmap, same numbers
    solved5 = np.asarray(distla.batched_cholesky_solve(
        jnp.asarray(spd[:5]), jnp.asarray(rhs[:5]), mesh=mesh))
    assert np.allclose(solved5, np.linalg.solve(spd[:5], rhs[:5]),
                       atol=1e-8)


def test_srm_fit_parity_sharded_solves():
    """SRM/DetSRM with the subject-sharded E-step solves reproduce
    the unsharded fit from the same seed (allclose factors)."""
    from brainiak_tpu.funcalign.srm import SRM, DetSRM

    rng = np.random.RandomState(7)
    X = [rng.randn(30, 40).astype(np.float64) for _ in range(4)]
    mesh = make_mesh(("subject",), (4,))
    atol = mesh_atol()

    plain = SRM(n_iter=5, features=3, rand_seed=0).fit(X)
    sharded = SRM(n_iter=5, features=3, rand_seed=0, mesh=mesh).fit(X)
    assert np.allclose(plain.s_, sharded.s_, atol=atol)
    assert np.allclose(plain.sigma_s_, sharded.sigma_s_, atol=atol)
    for w0, w1 in zip(plain.w_, sharded.w_):
        assert np.allclose(w0, w1, atol=atol)

    dplain = DetSRM(n_iter=5, features=3, rand_seed=0).fit(X)
    dsharded = DetSRM(n_iter=5, features=3, rand_seed=0,
                      mesh=mesh).fit(X)
    assert np.allclose(dplain.s_, dsharded.s_, atol=atol)
    for w0, w1 in zip(dplain.w_, dsharded.w_):
        assert np.allclose(w0, w1, atol=atol)


def test_fcma_distla_path_matches_replicated(seeded_rng):
    """VoxelSelector's sharded-data2 (distla) path reproduces the
    replicated XLA path, including an uneven voxel count that pads
    data2 to the mesh axis."""
    from brainiak_tpu.fcma.voxelselector import VoxelSelector

    def epoch(cols):
        mat = seeded_rng.rand(12, cols).astype(np.float32)
        return (mat - mat.mean(0)) / (mat.std(0) * np.sqrt(12))

    data = [epoch(21) for _ in range(8)]  # 21 % 8 != 0 -> padded
    labels = [0, 1] * 4
    plain = sorted(VoxelSelector(
        labels, 4, 2, data, voxel_unit=7,
        use_pallas=False, use_distla=False).run('svm'))
    mesh = make_mesh(("voxel",), (8,))
    vs = VoxelSelector(labels, 4, 2, data, voxel_unit=7, mesh=mesh,
                       use_pallas=False, use_distla=True)
    sharded = sorted(vs.run('svm'))
    for (v0, a0), (v1, a1) in zip(plain, sharded):
        assert v0 == v1
        assert np.isclose(a0, a1, atol=1e-4)
    # the EXPLICITLY-requested distla path serves the on-device SVM
    # only
    with pytest.raises(ValueError, match="on-device SVM"):
        vs.run(object())
    # explicit opt-in without a mesh is a loud error
    with pytest.raises(ValueError, match="mesh"):
        VoxelSelector(labels, 4, 2, data, use_distla=True)


def test_fcma_distla_auto_falls_back_for_host_cv(seeded_rng, caplog):
    """A budget-triggered AUTO engagement must not turn a host-CV
    run() into an error: that call degrades to the replicated layout
    (with a warning) and the sharded path is restored afterwards."""
    import logging

    from sklearn import svm

    from brainiak_tpu.fcma.voxelselector import VoxelSelector

    def epoch():
        mat = seeded_rng.rand(12, 16).astype(np.float32)
        return (mat - mat.mean(0)) / (mat.std(0) * np.sqrt(12))

    data = [epoch() for _ in range(8)]
    labels = [0, 1] * 4
    mesh = make_mesh(("voxel",), (8,))
    # a 1-byte budget auto-engages distla for any data
    vs = VoxelSelector(labels, 4, 2, data, voxel_unit=4, mesh=mesh,
                       use_pallas=False, replicated_budget_bytes=1)
    assert vs.use_distla and vs._distla_auto
    clf = svm.SVC(kernel='precomputed', shrinking=False, C=1)
    with caplog.at_level(logging.WARNING,
                         logger="brainiak_tpu.fcma.voxelselector"):
        host = sorted(vs.run(clf))
    assert any("falling back" in r.message for r in caplog.records)
    assert vs.use_distla  # restored after the call
    plain = sorted(VoxelSelector(
        labels, 4, 2, data, voxel_unit=4, use_pallas=False,
        use_distla=False).run(clf))
    for (v0, a0), (v1, a1) in zip(plain, host):
        assert v0 == v1
        assert np.isclose(a0, a1, atol=1e-4)
    # and the sharded on-device path still works on the same instance
    sharded = sorted(vs.run('svm'))
    assert [v for v, _ in sharded] == [v for v, _ in plain]


def test_distla_cost_records_join_spans_for_flops():
    """With profiling on, a distla run emits ``distla.*`` cost
    records whose span hints join the recorded span durations in
    ``obs report`` (achieved-FLOP/s populated), and repeat calls do
    not rebuild the program (one retrace per site)."""
    from brainiak_tpu.obs import metrics as obs_metrics
    from brainiak_tpu.obs import profile as obs_profile
    from brainiak_tpu.obs import report

    rng = np.random.RandomState(8)
    data = rng.randn(16, 64)
    mesh = make_mesh(("voxel",), (8,))
    distla._summa_program.cache_clear()
    retrace = obs_metrics.counter("retrace_total")
    before = retrace.value(site="distla.summa")

    mem = obs.add_sink(obs.MemorySink())
    try:
        with obs_profile.profiling("lowered"):
            for _ in range(2):
                np.asarray(distla.summa_gram(data, mesh))
    finally:
        obs.remove_sink(mem)

    assert retrace.value(site="distla.summa") - before == 1
    costs = [r for r in mem.records if r["kind"] == "cost"
             and r["site"] == "distla.summa"]
    assert costs and costs[0]["span"] == "distla.gram"
    assert costs[0].get("flops")
    summary = report.aggregate(mem.records)
    (row,) = [r for r in summary["cost"]
              if r["site"] == "distla.summa"]
    assert row["achieved_flops_per_s"] > 0
