"""Test harness configuration.

Tests run on a virtual 8-device CPU mesh so multi-chip sharding paths
(shard_map/pjit over a jax.sharding.Mesh) are exercised without TPU pod
hardware — the TPU-native analog of the reference's pytest-mpiexec
subprocess re-execution trick (reference tests/pytest_mpiexec_plugin.py).
The env vars must be set before jax is first imported.
"""

import os
import sys

# Force CPU even when the ambient environment points JAX at a TPU
# (JAX_PLATFORMS=axon, registered by a sitecustomize before this file runs):
# the unit-test mesh is 8 virtual CPU devices.  The env var alone is not
# enough because jax may already be imported, so also update jax.config.
os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
# Reference algorithms are float64 (NumPy defaults); tests mirror that.
# The TPU production path passes float32 data explicitly.  Set
# BRAINIAK_TPU_TEST_X64=0 to sweep the suite in fp32 (TPU-like numerics).
jax.config.update("jax_enable_x64",
                  os.environ.get("BRAINIAK_TPU_TEST_X64", "1") != "0")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402
import pytest  # noqa: E402


REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def mesh_atol():
    """Sharded-vs-single comparisons are bit-exact in f64 but only
    reduction-order-close in fp32 (the TPU-like sweep)."""
    import jax
    return 1e-8 if jax.config.jax_enable_x64 else 2e-4


@pytest.fixture
def seeded_rng():
    """Seed global RNGs for tests that use library-internal randomness."""
    import random
    random.seed(0)
    np.random.seed(0)
    return np.random.RandomState(0)
