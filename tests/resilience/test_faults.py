"""Fault injection: deterministic preempt / nan / io_error triggers."""

import importlib

import numpy as np
import pytest

from brainiak_tpu.resilience import faults
from brainiak_tpu.resilience.faults import (
    InjectedIOError,
    PreemptionError,
    inject,
)

retry_mod = importlib.import_module("brainiak_tpu.resilience.retry")


@pytest.fixture(autouse=True)
def _fast_retries(monkeypatch):
    monkeypatch.setattr(retry_mod, "_sleep", lambda _d: None)


def test_preempt_fires_at_step_crossing():
    with inject("preempt", at_step=3) as fault:
        faults.preempt_point(2)  # below threshold: no fire
        with pytest.raises(PreemptionError, match="step 4"):
            faults.preempt_point(4)
        assert fault.fired == 1
        faults.preempt_point(6)  # times=1 exhausted: inert


def test_nan_corrupts_first_float_leaf():
    state = {"count": np.arange(3), "w": np.ones(4), "b": np.ones(2)}
    with inject("nan", at_step=2) as fault:
        same = faults.corrupt_state(state, 1)
        assert same is state  # below at_step
        out = faults.corrupt_state(state, 2)
        assert np.isnan(out["w"]).any()
        assert not np.isnan(state["w"]).any()  # original untouched
        assert fault.fired == 1


def test_nan_targets_named_leaf():
    state = {"a": np.ones(2), "b": np.ones(2)}
    with inject("nan", at_step=0, leaf="b"):
        out = faults.corrupt_state(state, 5)
    assert np.isnan(out["b"]).any() and not np.isnan(out["a"]).any()


def test_io_error_lets_through_at_step_calls():
    with inject("io_error", at_step=2, times=1) as fault:
        faults.io_point("f1")
        faults.io_point("f2")
        with pytest.raises(InjectedIOError):
            faults.io_point("f3")
        faults.io_point("f4")  # exhausted
        assert fault.fired == 1


def test_unknown_kind_rejected():
    with pytest.raises(ValueError, match="unknown fault kind"):
        with inject("segfault"):
            pass


def test_io_error_consumed_by_nifti_retry(tmp_path):
    from brainiak_tpu import nifti

    img = nifti.NiftiImage(np.arange(24, dtype=np.float32)
                           .reshape(2, 3, 4))
    path = str(tmp_path / "vol.nii.gz")
    nifti.save(img, path)
    with inject("io_error", times=1) as fault:
        loaded = nifti.load(path)
    assert fault.fired == 1  # failed once, retried, succeeded
    assert np.allclose(loaded.get_fdata(), img.get_fdata())


def test_io_error_exhausts_nifti_retries(tmp_path):
    from brainiak_tpu import nifti

    img = nifti.NiftiImage(np.zeros((2, 2, 2), dtype=np.float32))
    path = str(tmp_path / "vol.nii")
    nifti.save(img, path)
    with inject("io_error", times=10):
        with pytest.raises(OSError):
            nifti.load(path)


def test_io_error_consumed_by_checkpoint_retry(tmp_path):
    from brainiak_tpu.utils.checkpoint import CheckpointManager

    mngr = CheckpointManager(str(tmp_path / "ck"))
    with inject("io_error", times=1) as fault:
        mngr.save(1, {"x": np.ones(3)})
    assert fault.fired == 1
    step, state = mngr.restore()
    assert step == 1 and np.allclose(np.asarray(state["x"]), 1.0)


def test_truncated_gzip_read_is_retriable(tmp_path):
    """A .nii.gz truncated mid-restage raises EOFError/zlib.error from
    gzip — classified retriable, so a concurrently-completed file is
    picked up on a later attempt."""
    from brainiak_tpu import nifti

    img = nifti.NiftiImage(np.zeros((2, 2, 2), dtype=np.float32))
    good = str(tmp_path / "vol.nii.gz")
    nifti.save(img, good)
    payload = open(good, "rb").read()
    flaky = str(tmp_path / "staging.nii.gz")
    with open(flaky, "wb") as f:
        f.write(payload[: len(payload) // 2])  # truncated

    calls = {"n": 0}
    orig = nifti.gzip.open

    def healing_open(path, mode="rb"):
        calls["n"] += 1
        if calls["n"] == 2:  # "re-stage" completes before retry 1
            with open(flaky, "wb") as f:
                f.write(payload)
        return orig(path, mode)

    nifti.gzip.open = healing_open
    try:
        loaded = nifti.load(flaky)
    finally:
        nifti.gzip.open = orig
    assert calls["n"] >= 2
    assert np.allclose(loaded.get_fdata(), img.get_fdata())


def test_env_var_fault(monkeypatch):
    monkeypatch.setattr(faults, "_env_fault", None)
    monkeypatch.setattr(faults, "_env_spec_seen", None)
    monkeypatch.setenv(faults.FAULT_ENV_VAR, "preempt@2")
    with pytest.raises(PreemptionError):
        faults.preempt_point(2)
    faults.preempt_point(5)  # fires once per process


def test_env_var_malformed_ignored(monkeypatch):
    monkeypatch.setattr(faults, "_env_fault", None)
    monkeypatch.setattr(faults, "_env_spec_seen", None)
    monkeypatch.setenv(faults.FAULT_ENV_VAR, "preempt@banana")
    faults.preempt_point(10)  # no raise
