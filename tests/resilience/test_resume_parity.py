"""Resume-parity and rollback tests for every resilient estimator.

The acceptance contract of the resilience subsystem: a fit killed by
injected preemption at iteration k and re-launched with the same
``checkpoint_dir`` matches the uninterrupted fit to numerical
tolerance; an injected-NaN fit rolls back and recovers (transient
fault) or aborts with :class:`DivergenceError` naming the bad leaf
(persistent divergence).  All driven by ``resilience.faults`` — no
sleeps or real preemption.
"""

import numpy as np
import pytest

from brainiak_tpu.resilience.faults import PreemptionError, inject
from brainiak_tpu.resilience.guards import DivergenceError

ATOL = 1e-7


def _srm_data(n_subjects=3, voxels=14, samples=20, features=3, seed=0):
    rng = np.random.RandomState(seed)
    shared = rng.randn(features, samples)
    X = []
    for _ in range(n_subjects):
        q, _ = np.linalg.qr(rng.randn(voxels, features))
        X.append(q @ shared + 0.1 * rng.randn(voxels, samples))
    return X


def _interrupt_then_resume(make_model, fit, d, at_step):
    """Run fit under injected preemption at ``at_step``, then resume."""
    with inject("preempt", at_step=at_step) as fault:
        with pytest.raises(PreemptionError):
            fit(make_model(), d)
    assert fault.fired == 1
    return fit(make_model(), d)


def test_srm_preempt_resume_parity(tmp_path):
    from brainiak_tpu.funcalign.srm import SRM

    X = _srm_data()

    def make():
        return SRM(n_iter=8, features=3)

    def fit(model, d):
        return model.fit(X, checkpoint_dir=d, checkpoint_every=2)

    plain = make().fit(X)
    resumed = _interrupt_then_resume(make, fit,
                                     str(tmp_path / "ck"), at_step=4)
    for w0, w1 in zip(plain.w_, resumed.w_):
        assert np.allclose(w0, w1, atol=ATOL)
    assert np.allclose(plain.s_, resumed.s_, atol=ATOL)
    assert np.allclose(plain.logprob_, resumed.logprob_, atol=1e-5)


def test_srm_preempt_resume_parity_npz(tmp_path, monkeypatch):
    """Same parity through the npz fallback persistence path."""
    from brainiak_tpu.funcalign.srm import SRM
    from brainiak_tpu.utils.checkpoint import FORCE_NPZ_ENV_VAR

    monkeypatch.setenv(FORCE_NPZ_ENV_VAR, "1")
    X = _srm_data()

    def make():
        return SRM(n_iter=6, features=3)

    def fit(model, d):
        return model.fit(X, checkpoint_dir=d, checkpoint_every=2)

    plain = make().fit(X)
    d = str(tmp_path / "ck")
    resumed = _interrupt_then_resume(make, fit, d, at_step=2)
    # npz files (not orbax step dirs) actually backed the resume
    import os
    assert any(f.endswith(".npz") for f in os.listdir(d))
    for w0, w1 in zip(plain.w_, resumed.w_):
        assert np.allclose(w0, w1, atol=ATOL)
    assert np.allclose(plain.s_, resumed.s_, atol=ATOL)


def test_srm_nan_rollback_recovers(tmp_path):
    """A transient NaN is rolled back; the final fit matches plain."""
    from brainiak_tpu.funcalign.srm import SRM

    X = _srm_data()
    plain = SRM(n_iter=8, features=3).fit(X)
    with inject("nan", at_step=4) as fault:
        recovered = SRM(n_iter=8, features=3).fit(
            X, checkpoint_dir=str(tmp_path / "ck"), checkpoint_every=2)
    assert fault.fired == 1
    for w0, w1 in zip(plain.w_, recovered.w_):
        assert np.allclose(w0, w1, atol=ATOL)
    assert np.allclose(plain.s_, recovered.s_, atol=ATOL)


def test_srm_persistent_nan_aborts_naming_leaf(tmp_path):
    from brainiak_tpu.funcalign.srm import SRM

    X = _srm_data()
    with inject("nan", at_step=2, times=10, leaf="sigma_s"):
        with pytest.raises(DivergenceError) as exc:
            SRM(n_iter=8, features=3).fit(
                X, checkpoint_dir=str(tmp_path / "ck"),
                checkpoint_every=2)
    assert "sigma_s" in exc.value.leaves


def test_detsrm_preempt_resume_parity(tmp_path):
    from brainiak_tpu.funcalign.srm import DetSRM

    X = _srm_data()

    def make():
        return DetSRM(n_iter=8, features=3)

    def fit(model, d):
        return model.fit(X, checkpoint_dir=d, checkpoint_every=2)

    plain = make().fit(X)
    checkpointed = make().fit(X, checkpoint_dir=str(tmp_path / "full"),
                              checkpoint_every=3)
    assert np.allclose(plain.s_, checkpointed.s_, atol=ATOL)
    resumed = _interrupt_then_resume(make, fit,
                                     str(tmp_path / "ck"), at_step=4)
    for w0, w1 in zip(plain.w_, resumed.w_):
        assert np.allclose(w0, w1, atol=ATOL)
    assert np.allclose(plain.s_, resumed.s_, atol=ATOL)
    assert np.allclose(plain.objective_, resumed.objective_, rtol=1e-6)


def test_rsrm_preempt_resume_parity(tmp_path):
    from brainiak_tpu.funcalign.rsrm import RSRM

    X = _srm_data()

    def make():
        return RSRM(n_iter=8, features=3, gamma=1.0)

    def fit(model, d):
        return model.fit(X, checkpoint_dir=d, checkpoint_every=2)

    plain = make().fit(X)
    resumed = _interrupt_then_resume(make, fit,
                                     str(tmp_path / "ck"), at_step=4)
    for w0, w1 in zip(plain.w_, resumed.w_):
        assert np.allclose(w0, w1, atol=ATOL)
    for s0, s1 in zip(plain.s_, resumed.s_):
        assert np.allclose(s0, s1, atol=ATOL)
    assert np.allclose(plain.r_, resumed.r_, atol=ATOL)


def test_fastsrm_preempt_resume_parity(tmp_path):
    from brainiak_tpu.funcalign.fastsrm import FastSRM

    rng = np.random.RandomState(1)
    shared = rng.randn(4, 30)
    imgs = [np.linalg.qr(rng.randn(25, 4))[0] @ shared
            + 0.05 * rng.randn(25, 30) for _ in range(3)]

    def make():
        return FastSRM(n_components=3, n_iter=10, aggregate=None)

    def fit(model, d):
        return model.fit(imgs, checkpoint_dir=d, checkpoint_every=3)

    plain = make().fit(imgs)
    resumed = _interrupt_then_resume(make, fit,
                                     str(tmp_path / "ck"), at_step=6)
    for b0, b1 in zip(plain.basis_list, resumed.basis_list):
        assert np.allclose(b0, b1, atol=ATOL)


def _tfa_problem(seed=3):
    rng = np.random.RandomState(seed)
    R = rng.uniform(-10, 10, (60, 3))
    X = rng.randn(60, 25)
    return X, R


def test_tfa_preempt_resume_parity(tmp_path):
    from brainiak_tpu.factoranalysis.tfa import TFA

    X, R = _tfa_problem()

    def make():
        # tiny threshold: keep iterating so preemption lands mid-fit
        return TFA(K=3, max_iter=6, threshold=1e-12, max_num_voxel=40,
                   max_num_tr=20, seed=10, lbfgs_iters=15)

    def fit(model, d):
        return model.fit(X, R, checkpoint_dir=d, checkpoint_every=2)

    plain = make().fit(X, R)
    resumed = _interrupt_then_resume(make, fit,
                                     str(tmp_path / "ck"), at_step=2)
    assert np.allclose(plain.local_posterior_, resumed.local_posterior_,
                       atol=ATOL)
    assert np.allclose(plain.F_, resumed.F_, atol=ATOL)
    assert np.allclose(plain.W_, resumed.W_, atol=1e-5)


def test_htfa_preempt_resume_parity(tmp_path):
    from brainiak_tpu.factoranalysis.htfa import HTFA

    rng = np.random.RandomState(5)
    X = [rng.randn(40, 12) for _ in range(2)]
    R = [rng.uniform(-8, 8, (40, 3)) for _ in range(2)]

    def make():
        return HTFA(K=2, n_subj=2, max_global_iter=4, max_local_iter=2,
                    threshold=1e-12, max_voxel=30, max_tr=10,
                    voxel_ratio=1.0, tr_ratio=1.0, lbfgs_iters=10)

    def fit(model, d):
        # the template init draws from the global RNG; pin it so the
        # interrupted and uninterrupted fits start identically
        np.random.seed(0)
        return model.fit(X, R, checkpoint_dir=d, checkpoint_every=1)

    np.random.seed(0)
    plain = make().fit(X, R)
    resumed = _interrupt_then_resume(make, fit,
                                     str(tmp_path / "ck"), at_step=2)
    assert np.allclose(plain.local_posterior_, resumed.local_posterior_,
                       atol=ATOL)
    assert np.allclose(plain.global_prior_, resumed.global_prior_,
                       atol=ATOL)
    assert np.allclose(plain.local_weights_, resumed.local_weights_,
                       atol=1e-5)


def test_brsa_preempt_resume_parity(tmp_path):
    from brainiak_tpu.reprsimil.brsa import BRSA

    rng = np.random.RandomState(7)
    n_t, n_v, n_c = 40, 6, 3
    design = rng.randn(n_t, n_c)
    beta = rng.randn(n_c, n_v)
    X = design @ beta + 0.5 * rng.randn(n_t, n_v) + 10.0

    def make():
        return BRSA(n_iter=3, rank=2, n_nureg=1, lbfgs_iters=40,
                    random_state=0)

    def fit(model, d):
        return model.fit(X, design, checkpoint_dir=d,
                         checkpoint_every=1)

    plain = make().fit(X, design)
    resumed = _interrupt_then_resume(make, fit,
                                     str(tmp_path / "ck"), at_step=1)
    assert np.allclose(plain.U_, resumed.U_, atol=1e-6)
    assert np.allclose(plain.rho_, resumed.rho_, atol=1e-6)
    assert np.allclose(plain.beta_, resumed.beta_, atol=1e-6)


def test_eventsegment_preempt_resume_parity(tmp_path):
    from brainiak_tpu.eventseg.event import EventSegment

    rng = np.random.RandomState(11)
    n_events, t, v = 4, 60, 12
    pattern = rng.randn(n_events, v)
    bounds = np.sort(rng.choice(np.arange(1, t), n_events - 1,
                                replace=False))
    labels = np.searchsorted(bounds, np.arange(t), side="right")
    data = pattern[labels] + 0.5 * rng.randn(t, v)

    def make():
        return EventSegment(n_events=n_events, n_iter=20)

    def fit(model, d):
        return model.fit(data, checkpoint_dir=d, checkpoint_every=5)

    plain = make().fit(data)
    resumed = _interrupt_then_resume(make, fit,
                                     str(tmp_path / "ck"), at_step=10)
    assert np.allclose(plain.event_pat_, resumed.event_pat_, atol=ATOL)
    assert plain.ll_.shape == resumed.ll_.shape
    assert np.allclose(plain.ll_, resumed.ll_, atol=1e-6)
    for s0, s1 in zip(plain.segments_, resumed.segments_):
        assert np.allclose(s0, s1, atol=ATOL)


def test_eventsegment_nan_rollback_recovers(tmp_path):
    from brainiak_tpu.eventseg.event import EventSegment

    rng = np.random.RandomState(13)
    data = rng.randn(50, 10)

    plain = EventSegment(n_events=3, n_iter=12).fit(data)
    with inject("nan", at_step=8, leaf="best_pat") as fault:
        recovered = EventSegment(n_events=3, n_iter=12).fit(
            data, checkpoint_dir=str(tmp_path / "ck"),
            checkpoint_every=4)
    assert fault.fired == 1
    assert np.allclose(plain.event_pat_, recovered.event_pat_,
                       atol=ATOL)


def test_tfa_nan_rollback_recovers(tmp_path):
    from brainiak_tpu.factoranalysis.tfa import TFA

    X, R = _tfa_problem(seed=4)
    make = lambda: TFA(K=3, max_iter=4, threshold=1e-12,  # noqa: E731
                       max_num_voxel=40, max_num_tr=20, seed=10,
                       lbfgs_iters=10)
    plain = make().fit(X, R)
    with inject("nan", at_step=2, leaf="posterior") as fault:
        recovered = make().fit(
            X, R, checkpoint_dir=str(tmp_path / "ck"),
            checkpoint_every=2)
    assert fault.fired == 1
    assert np.allclose(plain.local_posterior_,
                       recovered.local_posterior_, atol=ATOL)
