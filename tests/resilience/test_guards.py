"""Non-finite guard + resilient loop driver unit tests."""

import numpy as np
import pytest

from brainiak_tpu.resilience.faults import PreemptionError, inject
from brainiak_tpu.resilience.guards import (
    DivergenceError,
    FitParked,
    check_state,
    pack_rng_state,
    park_scope,
    run_resilient_loop,
    unpack_rng_state,
)


def test_check_state_passes_finite():
    check_state({"a": np.ones(3), "ints": np.arange(2)})


def test_check_state_names_bad_leaves_and_iteration():
    state = {"good": np.ones(2), "bad": np.array([1.0, np.nan]),
             "worse": np.array([np.inf])}
    with pytest.raises(DivergenceError) as exc:
        check_state(state, iteration=7, where="unit")
    assert exc.value.leaves == ["bad", "worse"]
    assert exc.value.iteration == 7
    assert "bad" in str(exc.value) and "iteration 7" in str(exc.value)


def test_check_state_skip_and_nan_only():
    state = {"hist": np.array([np.nan]), "ll": np.array([-np.inf])}
    check_state(state, skip=("hist",), nan_only=True)
    with pytest.raises(DivergenceError):
        check_state(state, skip=("hist",), nan_only=False)


def test_array_digest_distinguishes_zscored_data():
    """Plain sums are ~0 for z-scored data; the digest must not be."""
    from scipy import stats

    from brainiak_tpu.resilience.guards import array_digest

    rng = np.random.RandomState(0)
    a = stats.zscore(rng.randn(20, 30), axis=1, ddof=1)
    b = stats.zscore(rng.randn(20, 30), axis=1, ddof=1)
    da, db = array_digest(a), array_digest(b)
    assert abs(da - db) > 1e-6 * max(abs(da), abs(db))
    assert array_digest(a) == da  # deterministic


def test_eventsegment_rejects_checkpoint_from_other_data(tmp_path):
    """Same-shape different data must not resume (the z-score trap)."""
    import pytest as _pytest

    from brainiak_tpu.eventseg.event import EventSegment

    rng = np.random.RandomState(2)
    d = str(tmp_path / "ck")
    EventSegment(n_events=3, n_iter=8).fit(
        rng.randn(30, 8), checkpoint_dir=d, checkpoint_every=4)
    with _pytest.raises(ValueError, match="different data"):
        EventSegment(n_events=3, n_iter=8).fit(
            rng.randn(30, 8), checkpoint_dir=d, checkpoint_every=4)


def test_srm_rejects_checkpoint_from_other_zscored_data(tmp_path):
    """SRM's fingerprint must distinguish z-scored datasets whose
    sum-of-squares (trace) is identical by construction."""
    import pytest as _pytest
    from scipy import stats

    from brainiak_tpu.funcalign.srm import SRM

    rng = np.random.RandomState(6)

    def zscored_subjects(seed):
        r = np.random.RandomState(seed)
        return [stats.zscore(r.randn(12, 20), axis=1, ddof=1)
                for _ in range(3)]

    d = str(tmp_path / "ck")
    SRM(n_iter=4, features=3).fit(zscored_subjects(1),
                                  checkpoint_dir=d)
    with _pytest.raises(ValueError, match="different data"):
        SRM(n_iter=6, features=3).fit(zscored_subjects(2),
                                      checkpoint_dir=d)


def test_rng_state_roundtrip():
    rng = np.random.RandomState(42)
    rng.randn(17)
    keys, meta = pack_rng_state(rng)
    expected = rng.randn(5)
    rng2 = unpack_rng_state(np.random.RandomState(0), keys, meta)
    assert np.allclose(rng2.randn(5), expected)


def _counting_chunk(state, step, n_steps):
    return {"x": np.asarray(state["x"]) + n_steps}, False


def test_loop_advances_in_chunks(tmp_path):
    state, step = run_resilient_loop(
        _counting_chunk, {"x": np.zeros(1)}, 7, checkpoint_every=3)
    assert step == 7 and state["x"][0] == 7.0


def test_loop_checkpoints_and_resumes(tmp_path):
    d = str(tmp_path / "ck")
    with inject("preempt", at_step=4):
        with pytest.raises(PreemptionError):
            run_resilient_loop(_counting_chunk, {"x": np.zeros(1)}, 10,
                               checkpoint_dir=d, checkpoint_every=2)
    # killed at step 4 with the checkpoint on disk; a fresh call
    # resumes there rather than restarting
    steps_run = []

    def tracked(state, step, n_steps):
        steps_run.append((step, n_steps))
        return _counting_chunk(state, step, n_steps)

    state, step = run_resilient_loop(
        tracked, {"x": np.zeros(1)}, 10, checkpoint_dir=d,
        checkpoint_every=2)
    assert steps_run[0][0] == 4
    assert step == 10 and state["x"][0] == 10.0


def test_loop_rejects_nonpositive_checkpoint_every():
    with pytest.raises(ValueError, match="checkpoint_every"):
        run_resilient_loop(_counting_chunk, {"x": np.zeros(1)}, 4,
                           checkpoint_every=0)


def test_loop_fingerprint_mismatch_rejected(tmp_path):
    d = str(tmp_path / "ck")
    run_resilient_loop(_counting_chunk, {"x": np.zeros(1)}, 2,
                       checkpoint_dir=d, fingerprint=np.array([1.0]))
    with pytest.raises(ValueError, match="different data"):
        run_resilient_loop(_counting_chunk, {"x": np.zeros(1)}, 4,
                           checkpoint_dir=d,
                           fingerprint=np.array([2.0]))


def test_loop_lower_budget_than_checkpoint_rejected(tmp_path):
    d = str(tmp_path / "ck")
    run_resilient_loop(_counting_chunk, {"x": np.zeros(1)}, 6,
                       checkpoint_dir=d, checkpoint_every=3)
    with pytest.raises(ValueError, match="iteration"):
        run_resilient_loop(_counting_chunk, {"x": np.zeros(1)}, 2,
                           checkpoint_dir=d)


def test_loop_rollback_recovers_from_transient_nan(tmp_path):
    with inject("nan", at_step=4) as fault:
        state, step = run_resilient_loop(
            _counting_chunk, {"x": np.zeros(1)}, 6, checkpoint_every=2)
    assert fault.fired == 1
    # the corrupted chunk was re-run from the last good state
    assert step == 6 and state["x"][0] == 6.0


def test_loop_aborts_after_consecutive_rollbacks():
    def diverging(state, step, n_steps):
        return {"x": np.full(1, np.nan)}, False

    calls = []

    def counted(state, step, n_steps):
        calls.append(step)
        return diverging(state, step, n_steps)

    with pytest.raises(DivergenceError) as exc:
        run_resilient_loop(counted, {"x": np.zeros(1)}, 4,
                           checkpoint_every=2, max_rollbacks=2,
                           name="unit")
    assert exc.value.leaves == ["x"]
    # initial attempt + 2 rollback re-runs, all from step 0
    assert calls == [0, 0, 0]


def test_loop_done_flag_short_circuits():
    def converge_at_3(state, step, n_steps):
        x = float(np.asarray(state["x"])[0])
        for i in range(n_steps):
            x += 1
            if x >= 3:
                return {"x": np.array([x]),
                        "done": np.array(1.0)}, True
        return {"x": np.array([x]), "done": np.array(0.0)}, False

    state, step = run_resilient_loop(
        converge_at_3, {"x": np.zeros(1), "done": np.zeros(1)}, 10,
        checkpoint_every=2)
    assert state["x"][0] == 3.0
    assert step < 10


def test_loop_resume_of_done_state_skips(tmp_path):
    d = str(tmp_path / "ck")

    def instantly_done(state, step, n_steps):
        return {"x": np.asarray(state["x"]) + 1,
                "done": np.array(1.0)}, True

    run_resilient_loop(instantly_done,
                       {"x": np.zeros(1), "done": np.zeros(1)}, 10,
                       checkpoint_dir=d, checkpoint_every=2)

    def must_not_run(state, step, n_steps):  # pragma: no cover
        raise AssertionError("resumed-done loop must not re-run")

    state, _ = run_resilient_loop(
        must_not_run, {"x": np.zeros(1), "done": np.zeros(1)}, 10,
        checkpoint_dir=d, checkpoint_every=2)
    assert state["x"][0] == 1.0


def test_preempt_fires_only_after_save(tmp_path):
    d = str(tmp_path / "ck")
    with inject("preempt", at_step=2):
        with pytest.raises(PreemptionError):
            run_resilient_loop(_counting_chunk, {"x": np.zeros(1)}, 6,
                               checkpoint_dir=d, checkpoint_every=2)
    from brainiak_tpu.utils.checkpoint import CheckpointManager
    step, state = CheckpointManager(d).restore()
    assert step == 2 and np.asarray(state["x"])[0] == 2.0


def test_fit_id_and_wall_survive_preempt_resume(tmp_path):
    """PR 19: the loop mints one fit_id, persists it (plus the
    cumulative wall accounting) in the checkpoint, and a resumed
    fit continues the same id with monotone chunk indices — while
    the meta leaves never leak into the user's state dict."""
    from brainiak_tpu.obs import sink as obs_sink

    d = str(tmp_path / "ck")
    mem = obs_sink.add_sink(obs_sink.MemorySink())
    try:
        with inject("preempt", at_step=4):
            with pytest.raises(PreemptionError):
                run_resilient_loop(
                    _counting_chunk, {"x": np.zeros(1)}, 10,
                    checkpoint_dir=d, checkpoint_every=2)
        state, step = run_resilient_loop(
            _counting_chunk, {"x": np.zeros(1)}, 10,
            checkpoint_dir=d, checkpoint_every=2)
    finally:
        obs_sink.remove_sink(mem)
    assert step == 10 and state["x"][0] == 10.0
    assert set(state) == {"x"}  # no fit_id/fit_wall meta leaves
    progress = [r for r in mem.records if r["kind"] == "progress"]
    assert len({r["fit_id"] for r in progress}) == 1
    assert [r["chunk"] for r in progress] == [1, 2, 3, 4, 5]
    walls = [r["fit_wall_s"] for r in progress]
    assert all(b > a for a, b in zip(walls, walls[1:]))
    resumes = [r for r in mem.records if r["kind"] == "event"
               and r["name"] == "rollback" or r["name"] == "resume"]
    assert any(r.get("fit_id") == progress[0]["fit_id"]
               for r in resumes)


def test_fresh_checkpoint_dir_mints_fresh_fit_id(tmp_path):
    from brainiak_tpu.obs import sink as obs_sink

    mem = obs_sink.add_sink(obs_sink.MemorySink())
    try:
        run_resilient_loop(_counting_chunk, {"x": np.zeros(1)}, 4,
                           checkpoint_dir=str(tmp_path / "a"),
                           checkpoint_every=2)
        run_resilient_loop(_counting_chunk, {"x": np.zeros(1)}, 4,
                           checkpoint_dir=str(tmp_path / "b"),
                           checkpoint_every=2)
    finally:
        obs_sink.remove_sink(mem)
    ids = {r["fit_id"] for r in mem.records
           if r["kind"] == "progress"}
    assert len(ids) == 2


def test_divergence_abort_reports_fit_id_and_diverged_status():
    from brainiak_tpu.obs import progress as obs_progress
    from brainiak_tpu.obs import sink as obs_sink

    obs_progress.clear_registry()
    mem = obs_sink.add_sink(obs_sink.MemorySink())
    try:
        with inject("nan", at_step=2, times=10):
            with pytest.raises(DivergenceError):
                run_resilient_loop(
                    _counting_chunk, {"x": np.zeros(1)}, 6,
                    checkpoint_every=2, max_rollbacks=1)
    finally:
        obs_sink.remove_sink(mem)
    (abort,) = [r for r in mem.records if r["kind"] == "event"
                and r["name"] == "divergence_abort"]
    assert abort["fit_id"]
    (snap,) = [s for s in obs_progress.active_fits()
               if s["fit_id"] == abort["fit_id"]]
    assert snap["status"] == "diverged"
    assert snap["rollbacks"] == 2  # the aborting failure counts too


def test_replicate_identity_cached():
    """The fetch_replicated fallback compiles once per mesh."""
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec

    from brainiak_tpu.parallel.mesh import (_replicate_identity,
                                            make_mesh)

    mesh = make_mesh(("subject",), (8,))
    fn = _replicate_identity(mesh)
    assert _replicate_identity(mesh) is fn
    x = jnp.arange(16.0).reshape(8, 2)
    placed = __import__("jax").device_put(
        x, NamedSharding(mesh, PartitionSpec("subject", None)))
    out = fn(placed)
    assert out.sharding.is_fully_replicated
    assert np.allclose(np.asarray(out), np.asarray(x))


# -- ISSUE 20: park_scope (the scheduler's preemption primitive) ------

def test_park_scope_parks_after_grant_and_resumes_bitexact(
        tmp_path):
    d = str(tmp_path / "ck")
    chunks = {"n": 0}

    def two_chunk_grant():
        chunks["n"] += 1
        return chunks["n"] >= 2

    with park_scope(two_chunk_grant):
        with pytest.raises(FitParked) as excinfo:
            run_resilient_loop(
                _counting_chunk, {"x": np.zeros(1)}, 10,
                checkpoint_dir=d, checkpoint_every=2)
    parked = excinfo.value
    # the predicate fired once per PERSISTED chunk: parked at the
    # second checkpoint with the state durably on disk
    assert parked.step == 4
    assert parked.fit_id is not None
    # re-running the same loop with the same checkpoint_dir resumes
    # under the SAME fit_id and completes to the exact final state
    state, step = run_resilient_loop(
        _counting_chunk, {"x": np.zeros(1)}, 10,
        checkpoint_dir=d, checkpoint_every=2)
    assert step == 10 and state["x"][0] == 10.0


def test_park_scope_ignored_without_checkpoint_dir():
    # parking without a checkpoint would discard work: the predicate
    # must never fire on an unpersisted loop
    with park_scope(lambda: True):
        state, step = run_resilient_loop(
            _counting_chunk, {"x": np.zeros(1)}, 4,
            checkpoint_every=2)
    assert step == 4 and state["x"][0] == 4.0


def test_park_scope_nests_and_restores(tmp_path):
    d = str(tmp_path / "ck")
    with park_scope(lambda: True):
        with park_scope(lambda: False):  # innermost predicate wins
            state, step = run_resilient_loop(
                _counting_chunk, {"x": np.zeros(1)}, 4,
                checkpoint_dir=d, checkpoint_every=2)
        assert step == 4
        with pytest.raises(FitParked):  # outer scope restored
            run_resilient_loop(
                _counting_chunk, {"x": np.zeros(1)}, 8,
                checkpoint_dir=d, checkpoint_every=2)


def test_park_scope_predicate_exceptions_are_swallowed(tmp_path):
    def broken():
        raise RuntimeError("scheduler bug")

    with park_scope(broken):
        state, step = run_resilient_loop(
            _counting_chunk, {"x": np.zeros(1)}, 4,
            checkpoint_dir=str(tmp_path / "ck"), checkpoint_every=2)
    assert step == 4 and state["x"][0] == 4.0
