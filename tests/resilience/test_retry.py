"""Unit tests for the exponential-backoff retry decorator."""

import importlib

import pytest

from brainiak_tpu.resilience.retry import retry

retry_mod = importlib.import_module("brainiak_tpu.resilience.retry")


@pytest.fixture(autouse=True)
def _no_sleep(monkeypatch):
    """Record requested delays instead of sleeping."""
    delays = []
    monkeypatch.setattr(retry_mod, "_sleep", delays.append)
    return delays


def test_retry_succeeds_after_transient_failures(_no_sleep):
    calls = {"n": 0}

    @retry(retries=3, backoff=0.5, jitter=0.0)
    def flaky():
        calls["n"] += 1
        if calls["n"] < 3:
            raise OSError("transient")
        return "ok"

    assert flaky() == "ok"
    assert calls["n"] == 3
    # exponential: 0.5, then 1.0
    assert _no_sleep == [0.5, 1.0]


def test_retry_exhausts_and_reraises(_no_sleep):
    @retry(retries=2, backoff=0.0, jitter=0.0)
    def always_fails():
        raise OSError("still down")

    with pytest.raises(OSError, match="still down"):
        always_fails()


def test_non_retriable_propagates_immediately(_no_sleep):
    calls = {"n": 0}

    @retry(retries=5, backoff=0.0)
    def typed_failure():
        calls["n"] += 1
        raise ValueError("not transient")

    with pytest.raises(ValueError):
        typed_failure()
    assert calls["n"] == 1
    assert _no_sleep == []


def test_bare_decorator_form(_no_sleep):
    @retry
    def fine(x):
        return x + 1

    assert fine(1) == 2


def test_inline_wrapper_form(_no_sleep):
    calls = {"n": 0}

    def flaky():
        calls["n"] += 1
        if calls["n"] == 1:
            raise ConnectionError("refused")
        return 7

    wrapped = retry(flaky, retries=1, backoff=0.0,
                    retriable=(ConnectionError,))
    assert wrapped() == 7


def test_retry_if_predicate_gates_broad_types(_no_sleep):
    calls = {"n": 0}

    @retry(retries=3, backoff=0.0, retriable=(RuntimeError,),
           retry_if=lambda e: "connect" in str(e))
    def deterministic_failure():
        calls["n"] += 1
        raise RuntimeError("already initialized")

    with pytest.raises(RuntimeError, match="already initialized"):
        deterministic_failure()
    assert calls["n"] == 1  # not retried: predicate said permanent

    attempts = {"n": 0}

    @retry(retries=3, backoff=0.0, retriable=(RuntimeError,),
           retry_if=lambda e: "connect" in str(e))
    def transient_failure():
        attempts["n"] += 1
        if attempts["n"] < 2:
            raise RuntimeError("failed to connect to coordinator")
        return "up"

    assert transient_failure() == "up"
    assert attempts["n"] == 2


def test_jitter_scales_delay(_no_sleep):
    @retry(retries=1, backoff=1.0, jitter=0.5)
    def flaky():
        if not _no_sleep:
            raise OSError("once")
        return True

    assert flaky()
    assert len(_no_sleep) == 1
    assert 1.0 <= _no_sleep[0] <= 1.5
