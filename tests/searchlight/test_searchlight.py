import numpy as np
import pytest

from brainiak_tpu.searchlight import Ball, Cube, Diamond, Searchlight


def test_shapes():
    c = Cube(1)
    assert c.mask_.shape == (3, 3, 3) and c.mask_.all()
    d = Diamond(1)
    assert d.mask_.sum() == 7  # center + 6 face neighbors
    assert d.mask_[1, 1, 1] and d.mask_[0, 1, 1] and not d.mask_[0, 0, 0]
    b = Ball(2)
    assert b.mask_[2, 2, 2] and b.mask_[0, 2, 2]
    assert not b.mask_[0, 0, 0]
    # Ball(r) contains Diamond(r) and is inside Cube(r)
    assert np.all(Ball(2).mask_ >= Diamond(2).mask_)


def test_run_searchlight_matches_oracle():
    rng = np.random.RandomState(0)
    dims = (6, 6, 6, 4)
    data = rng.randn(*dims)
    mask = np.ones(dims[:3], dtype=bool)
    rad = 1

    def voxel_fn(subjects, msk, myrad, bcast):
        return float(np.sum(subjects[0][msk]))

    sl = Searchlight(sl_rad=rad, shape=Cube, pool_size=1)
    sl.distribute([data], mask)
    sl.broadcast(None)
    out = sl.run_searchlight(voxel_fn)

    # border voxels skipped
    assert out[0, 0, 0] is None
    for (i, j, k) in [(1, 1, 1), (2, 3, 4), (4, 4, 4)]:
        expected = data[i - 1:i + 2, j - 1:j + 2, k - 1:k + 2].sum()
        assert np.isclose(out[i, j, k], expected)


def test_searchlight_min_active_proportion():
    dims = (5, 5, 5, 2)
    data = np.ones(dims)
    mask = np.zeros(dims[:3], dtype=bool)
    mask[2, 2, 2] = True  # single active voxel: 1/27 of Cube(1)

    def voxel_fn(subjects, msk, myrad, bcast):
        return 1.0

    sl = Searchlight(sl_rad=1, shape=Cube,
                     min_active_voxels_proportion=0.5, pool_size=1)
    sl.distribute([data], mask)
    out = sl.run_searchlight(voxel_fn)
    assert out[2, 2, 2] is None  # filtered by proportion

    sl2 = Searchlight(sl_rad=1, shape=Cube,
                      min_active_voxels_proportion=0, pool_size=1)
    sl2.distribute([data], mask)
    out2 = sl2.run_searchlight(voxel_fn)
    assert out2[2, 2, 2] == 1.0


def test_run_block_function():
    dims = (5, 5, 5, 3)
    data = np.arange(np.prod(dims), dtype=float).reshape(dims)
    mask = np.ones(dims[:3], dtype=bool)
    sl = Searchlight(sl_rad=1, pool_size=1)
    sl.distribute([data], mask)
    sl.broadcast(42)

    def block_fn(subjects, msk, rad, bcast, extra):
        assert bcast == 42 and extra == ('x',)
        inner = np.empty((3, 3, 3), dtype=object)
        inner[:] = 7.0
        return inner

    out = sl.run_block_function(block_fn, extra_block_fn_params=('x',))
    assert out[2, 2, 2] == 7.0
    assert out[0, 0, 0] is None


def test_traced_tier_matches_generic():
    import jax.numpy as jnp

    rng = np.random.RandomState(1)
    dims = (7, 6, 5, 8)
    subjects = [rng.randn(*dims) for _ in range(2)]
    mask = rng.rand(*dims[:3]) > 0.2

    # traced fn: mean over valid voxels of the correlation between the two
    # subjects' time series at each voxel
    def voxel_fn_jax(patches, mpatch, rad, bcast):
        x, y = patches[0], patches[1]
        xd = x - jnp.mean(x, axis=1, keepdims=True)
        yd = y - jnp.mean(y, axis=1, keepdims=True)
        r = jnp.sum(xd * yd, axis=1) / jnp.sqrt(
            jnp.sum(xd ** 2, axis=1) * jnp.sum(yd ** 2, axis=1))
        return jnp.sum(jnp.where(mpatch, r, 0.0)) / jnp.sum(mpatch)

    def voxel_fn_host(subj, msk, rad, bcast):
        vals = []
        flat0 = subj[0][msk]
        flat1 = subj[1][msk]
        for v in range(flat0.shape[0]):
            vals.append(np.corrcoef(flat0[v], flat1[v])[0, 1])
        return float(np.mean(vals))

    sl = Searchlight(sl_rad=1, shape=Diamond, pool_size=1)
    sl.distribute(subjects, mask)
    sl.broadcast(None)
    host_out = sl.run_searchlight(voxel_fn_host)
    jax_out = sl.run_searchlight_jax(voxel_fn_jax)

    centers = np.argwhere(mask[1:-1, 1:-1, 1:-1]) + 1
    checked = 0
    for (i, j, k) in centers:
        if host_out[i, j, k] is not None:
            assert np.isclose(jax_out[i, j, k], host_out[i, j, k],
                              atol=1e-6)
            checked += 1
    assert checked > 10
    # skipped voxels are NaN in the traced tier
    assert np.isnan(jax_out[0, 0, 0])


def test_traced_tier_mesh_matches_single():
    import jax.numpy as jnp

    from brainiak_tpu.parallel import make_mesh

    rng = np.random.RandomState(2)
    dims = (6, 6, 6, 5)
    subjects = [rng.randn(*dims)]
    mask = np.ones(dims[:3], dtype=bool)

    def voxel_fn_jax(patches, mpatch, rad, bcast):
        return jnp.sum(patches[0] * mpatch[:, None])

    sl = Searchlight(sl_rad=1, shape=Cube)
    sl.distribute(subjects, mask)
    single = sl.run_searchlight_jax(voxel_fn_jax)

    mesh = make_mesh(("subject", "voxel"), (1, 8))
    sl_m = Searchlight(sl_rad=1, shape=Cube, mesh=mesh)
    sl_m.distribute(subjects, mask)
    dist = sl_m.run_searchlight_jax(voxel_fn_jax)
    assert np.allclose(single, dist, equal_nan=True)


def test_searchlight_pool_tier_matches_serial(monkeypatch):
    """pool_size > 1 streams patches through a process Pool (the
    reference's per-node multiprocessing, searchlight.py L4); results
    must equal the serial tier exactly.

    This container's cpuset reports ONE usable CPU, which silently
    demotes any pool_size to the serial tier — so the CPU count is
    forced to 2 and the test asserts the Pool actually ran."""
    import brainiak_tpu.searchlight.searchlight as slmod

    rng = np.random.RandomState(2)
    dims = (6, 6, 6, 3)
    data = rng.randn(*dims)
    mask = np.ones(dims[:3], dtype=bool)

    serial = Searchlight(sl_rad=1, shape=Cube, pool_size=1)
    serial.distribute([data], mask)
    out_serial = serial.run_searchlight(_sum_patch)

    monkeypatch.setattr(slmod, "usable_cpu_count", lambda: 2)
    orig_pool = slmod.Pool
    pool_used = []
    monkeypatch.setattr(
        slmod, "Pool",
        lambda n: (pool_used.append(n), orig_pool(n))[1])
    pooled = Searchlight(sl_rad=1, shape=Cube, pool_size=2)
    pooled.distribute([data], mask)
    out_pool = pooled.run_searchlight(_sum_patch)
    assert pool_used == [2]

    for idx in np.ndindex(*dims[:3]):
        a, b = out_serial[idx], out_pool[idx]
        assert (a is None and b is None) or np.isclose(a, b)


def _sum_patch(subjects, msk, myrad, bcast):
    # top-level so the Pool tier can pickle it
    return float(np.sum(subjects[0][msk]))


def test_searchlight_rad_zero():
    """sl_rad=0: every in-mask voxel is its own neighborhood and no
    border is skipped."""
    rng = np.random.RandomState(3)
    dims = (4, 4, 4, 2)
    data = rng.randn(*dims)
    mask = np.ones(dims[:3], dtype=bool)
    sl = Searchlight(sl_rad=0, pool_size=1)
    sl.distribute([data], mask)
    out = sl.run_searchlight(_sum_patch)
    assert out[0, 0, 0] is not None
    for idx in np.ndindex(*dims[:3]):
        assert np.isclose(out[idx], data[idx].sum())


def test_traced_tier_edge_inputs():
    """Empty active set returns a fill_value volume; None subject
    placeholders are rejected (generic-tier-only feature)."""
    import jax.numpy as jnp

    dims = (4, 4, 4, 2)
    data = np.ones(dims)

    def jfn(patch, mpatch, rad, bcast):
        return jnp.sum(patch)

    sl = Searchlight(sl_rad=1, shape=Cube)
    sl.distribute([data], np.zeros(dims[:3], dtype=bool))
    out = sl.run_searchlight_jax(jfn, fill_value=-7.0)
    assert out.shape == dims[:3] and np.all(out == -7.0)

    sl2 = Searchlight(sl_rad=1, shape=Cube)
    sl2.distribute([None, data], np.ones(dims[:3], dtype=bool))
    with pytest.raises(ValueError, match="None"):
        sl2.run_searchlight_jax(jfn)


def test_searchlight_validation():
    sl = Searchlight(sl_rad=1)
    with pytest.raises(ValueError):
        sl.distribute([np.zeros((4, 4, 4, 2))], np.ones((5, 5, 5),
                                                        dtype=bool))
    with pytest.raises(AssertionError):
        Searchlight(sl_rad=-1)
    with pytest.raises(AssertionError):
        Searchlight(max_blk_edge=0)
