import numpy as np
import pytest

from brainiak_tpu.reconstruct.iem import (
    InvertedEncoding1D,
    InvertedEncoding2D,
)


def make_1d_data(n_per=12, n_voxels=30, noise=0.2, seed=0,
                 mode='halfcircular'):
    """Voxels with random tuning to the feature domain."""
    rng = np.random.RandomState(seed)
    span = 180.0 if mode == 'halfcircular' else 360.0
    features = np.repeat(np.linspace(0, span - span / 6, 6), n_per)
    prefs = rng.rand(n_voxels) * span
    factor = 2.0 if mode == 'halfcircular' else 1.0
    tuning = np.cos(np.deg2rad(factor * (features[:, None]
                                         - prefs[None, :]))) ** 2
    X = tuning + noise * rng.randn(len(features), n_voxels)
    return X, features


def test_iem1d_recovers_features():
    X, y = make_1d_data()
    model = InvertedEncoding1D(n_channels=6, channel_exp=5,
                               stimulus_mode='halfcircular')
    model.fit(X, y)
    pred = model.predict(X)
    err = np.abs(((pred - y) + 90) % 180 - 90)
    assert np.median(err) < 20
    score = model.score(X, y)
    assert score > 0.5


def test_iem1d_circular():
    X, y = make_1d_data(mode='circular')
    model = InvertedEncoding1D(n_channels=6, channel_exp=5,
                               stimulus_mode='circular',
                               range_stop=360.)
    model.fit(X, y)
    pred = model.predict(X)
    err = np.abs(((pred - y) + 180) % 360 - 180)
    assert np.median(err) < 40


def test_iem1d_validation():
    X, y = make_1d_data()
    with pytest.raises(ValueError):
        InvertedEncoding1D(range_start=100, range_stop=80)
    with pytest.raises(ValueError):
        InvertedEncoding1D(stimulus_mode='halfcircular', range_stop=90.)
    with pytest.raises(ValueError):
        InvertedEncoding1D(stimulus_mode='circular', range_stop=180.)
    with pytest.raises(ValueError):
        InvertedEncoding1D(n_channels=1)
    with pytest.raises(ValueError):
        InvertedEncoding1D(stimulus_mode='oval')
    model = InvertedEncoding1D()
    with pytest.raises(ValueError):
        model.fit(X[:3], y[:3])  # fewer trials than channels
    with pytest.raises(ValueError):
        model.fit(X, y[:-2])
    params = model.get_params()
    assert params["n_channels"] == 6
    model.set_params(n_channels=8)
    assert model.n_channels == 8


def test_iem1d_stimulus_resolution():
    """Coarser stimulus resolution than channel density expands the
    one-hot mask by repetition; a non-divisor is rejected (reference
    iem.py:212-253)."""
    X, y = make_1d_data()
    model = InvertedEncoding1D(n_channels=6, channel_exp=5,
                               stimulus_mode='halfcircular',
                               channel_density=180,
                               stimulus_resolution=90)
    model.fit(X, y)
    pred = model.predict(X)
    err = np.abs(((pred - y) + 90) % 180 - 90)
    assert np.median(err) < 20
    bad = InvertedEncoding1D(n_channels=6, channel_exp=5,
                             stimulus_mode='halfcircular',
                             channel_density=180,
                             stimulus_resolution=77)
    with pytest.raises(NotImplementedError):
        bad.fit(X, y)


def test_iem1d_rank_deficient_warns():
    """Repeating a single stimulus value gives a rank-deficient design;
    the reference warns instead of failing (iem.py:240-251)."""
    X, y = make_1d_data()
    y_const = np.zeros_like(y)  # every trial the same stimulus
    model = InvertedEncoding1D(n_channels=6, channel_exp=5,
                               stimulus_mode='halfcircular')
    with pytest.warns(RuntimeWarning, match="full rank"):
        try:
            model.fit(X, y_const)
        except ValueError:
            pass  # the near-singular W check may also fire; the
            # warning is the contract under test


def test_iem2d_recovers_positions():
    rng = np.random.RandomState(1)
    n_trials, n_voxels = 60, 20
    centers = rng.rand(n_trials, 2) * 8 + 1  # inside [1, 9]
    model = InvertedEncoding2D(stim_xlim=[0, 10], stim_ylim=[0, 10],
                               stimulus_resolution=20, stim_radius=1.5)
    channels, chan_centers = model.define_basis_functions_sqgrid(5)
    assert channels.shape[0] == 25
    # voxels = random linear combination of channel responses
    C = model._define_trial_activations(centers)
    W = rng.rand(n_voxels, 25)
    X = C @ W.T + 0.1 * rng.randn(n_trials, n_voxels)
    model.fit(X, centers)
    pred = model.predict(X)
    err = np.linalg.norm(pred - centers, axis=1)
    assert np.median(err) < 2.0
    scores = model.score(X, centers)
    assert np.mean(scores) > 0.0
    # reconstruction-space scoring
    recon = model.predict_feature_responses(X)
    d = model.score_against_reconstructed(X, recon[:, :1])
    assert d.shape == (n_trials,)


def test_iem2d_trigrid_and_validation():
    model = InvertedEncoding2D(stim_xlim=[0, 10], stim_ylim=[0, 10],
                               stimulus_resolution=15, stim_radius=1.0)
    channels, centers = model.define_basis_functions_trigrid(3)
    assert channels.shape[1] == 15 * 15
    assert centers.shape[1] == 2
    with pytest.raises(ValueError):
        InvertedEncoding2D(stim_xlim=[10, 0], stim_ylim=[0, 10],
                           stimulus_resolution=10)
    with pytest.raises(ValueError):
        InvertedEncoding2D(stim_xlim=5, stim_ylim=[0, 10],
                           stimulus_resolution=10)
    m2 = InvertedEncoding2D(stim_xlim=[0, 10], stim_ylim=[0, 10],
                            stimulus_resolution=10)
    with pytest.raises(ValueError):
        m2.fit(np.random.rand(20, 5), np.random.rand(20, 2))  # no channels
    with pytest.raises(ValueError):
        m3 = InvertedEncoding2D(stim_xlim=[0, 10], stim_ylim=[0, 10],
                                stimulus_resolution=10)
        m3.define_basis_functions_sqgrid(4)
        m3._define_trial_activations(np.random.rand(5, 2))  # no radius
