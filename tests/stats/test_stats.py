"""ISSUE 18: the resampling-statistics engine (brainiak_tpu.stats).

Pins the subsystem's contracts: the ``+1`` p-value convention (a
reference implementation, not a round-trip), accumulator counts
reproducing ``p_from_null`` bit-for-bit, exact pooling of disjoint
resample ranges through BOTH wire formats, chunk-size invariance,
the population-scale chunked run + resume proof at a resample count
whose materialized null cannot fit the configured budget, exact
sign-flip enumeration against an itertools brute force, and the
one-compile-per-family retrace contract.
"""

import itertools
import os

import numpy as np
import pytest

from brainiak_tpu.stats import (NullAccumulator, NullEngine,
                                compute_summary_statistic,
                                default_null_batch, p_from_null)
from brainiak_tpu.stats.pvalues import exceedance_counts, p_from_counts


def _p_reference(observed, distribution, side, exact):
    """The original brainiak convention, re-derived from scratch:
    exact tests divide raw counts by n; sampled tests add the
    observed value to both numerator and denominator (the ``+1``)."""
    observed = np.asarray(observed, dtype=np.float64)
    distribution = np.asarray(distribution, dtype=np.float64)
    n = distribution.shape[0]
    if side == 'right':
        numerator = np.sum(distribution >= observed, axis=0)
    elif side == 'left':
        numerator = np.sum(distribution <= observed, axis=0)
    else:
        numerator = np.sum(np.abs(distribution) >= np.abs(observed),
                           axis=0)
    if exact:
        return numerator / n
    return (numerator + 1) / (n + 1)


def test_p_from_null_pins_plus_one_convention():
    """p_from_null (now in stats.pvalues, the single source) matches
    the reference convention bitwise for every side x exact mode."""
    rng = np.random.RandomState(0)
    observed = rng.randn(7)
    distribution = rng.randn(100, 7)
    for side in ('right', 'left', 'two-sided'):
        for exact in (False, True):
            got = p_from_null(observed, distribution, side=side,
                              exact=exact, axis=0)
            want = _p_reference(observed, distribution, side, exact)
            assert np.array_equal(got, want), (side, exact)


def test_pvalue_shims_share_one_implementation():
    """The utils/isc re-export shims resolve to the stats.pvalues
    objects — one convention, no copies to drift."""
    import brainiak_tpu.isc as isc_mod
    import brainiak_tpu.stats.pvalues as pvalues
    import brainiak_tpu.utils.utils as utils_mod
    assert utils_mod.p_from_null is pvalues.p_from_null
    assert isc_mod.p_from_null is pvalues.p_from_null
    assert (isc_mod.compute_summary_statistic
            is pvalues.compute_summary_statistic)
    assert (compute_summary_statistic
            is pvalues.compute_summary_statistic)


def test_p_from_counts_matches_exceedance_counts():
    rng = np.random.RandomState(1)
    observed = rng.randn(5)
    distribution = rng.randn(64, 5)
    ge, le, abs_ge = exceedance_counts(observed, distribution)
    for side, numerator in (('right', ge), ('left', le),
                            ('two-sided', abs_ge)):
        for exact in (False, True):
            assert np.array_equal(
                p_from_counts(numerator, 64, exact=exact),
                _p_reference(observed, distribution, side, exact))


def test_accumulator_reproduces_p_from_null_bitwise():
    """Integer exceedance counts folded chunk-by-chunk (including a
    NaN column) reproduce p_from_null on the materialized null
    bit-for-bit."""
    rng = np.random.RandomState(2)
    observed = rng.randn(6)
    distribution = rng.randn(90, 6)
    distribution[13:40, 2] = np.nan
    acc = NullAccumulator(observed, 90, shape=(6,))
    for lo, hi in ((0, 17), (17, 64), (64, 90)):
        acc.update(distribution[lo:hi], (lo, hi))
    assert acc.complete
    for side in ('right', 'left', 'two-sided'):
        for exact in (False, True):
            assert np.array_equal(
                acc.p_values(side=side, exact=exact),
                p_from_null(observed, distribution, side=side,
                            exact=exact, axis=0)), (side, exact)


def test_accumulator_merge_exact_through_both_wire_formats(tmp_path):
    """Two half-range accumulators, one round-tripped through JSON
    hex-floats and one through npz, merge to EXACTLY the single-run
    verdicts: p-values, quantiles, CI bounds, FWER/FDR thresholds,
    moments."""
    rng = np.random.RandomState(3)
    observed = rng.randn(5)
    distribution = rng.randn(120, 5)
    full = NullAccumulator(observed, 120, shape=(5,))
    full.update(distribution, (0, 120))

    a = NullAccumulator(observed, 120, shape=(5,))
    a.update(distribution[:50], (0, 50))
    b = NullAccumulator(observed, 120, shape=(5,))
    b.update(distribution[50:], (50, 120))
    a = NullAccumulator.from_json(a.to_json())
    path = os.path.join(str(tmp_path), "half_b.npz")
    b.save(path)
    b = NullAccumulator.load(path)

    merged = a.merge(b)
    assert merged.complete
    for side in ('right', 'left', 'two-sided'):
        assert np.array_equal(merged.p_values(side=side),
                              full.p_values(side=side))
    for q in (0.025, 0.5, 0.975):
        assert np.array_equal(merged.quantile(q), full.quantile(q))
    assert merged.fwer_threshold() == full.fwer_threshold()
    assert merged.fdr_threshold() == full.fdr_threshold()
    # Moments are float sums: pooling adds two partial sums where the
    # full run sums 120 rows in one pass, so the last ulp can differ.
    # The count-based verdicts above are the EXACT contract.
    assert np.allclose(merged.mean(), full.mean(), rtol=1e-12)
    assert np.allclose(merged.variance(), full.variance(), rtol=1e-12)


def test_accumulator_rejects_overlap_and_config_mismatch():
    rng = np.random.RandomState(4)
    observed = rng.randn(3)
    a = NullAccumulator(observed, 20, shape=(3,))
    a.update(rng.randn(10, 3), (0, 10))
    b = NullAccumulator(observed, 20, shape=(3,))
    b.update(rng.randn(10, 3), (5, 15))
    with pytest.raises(ValueError, match="overlap"):
        a.merge(b)
    c = NullAccumulator(observed, 21, shape=(3,))
    with pytest.raises(ValueError, match="configurations"):
        a.merge(c)
    with pytest.raises(ValueError, match="already accumulated"):
        a.update(rng.randn(5, 3), (5, 10))


def test_engine_chunk_invariance_bitwise():
    """A starved budget (one dispatch lane per chunk) returns the
    bitwise-identical null and p-map to a one-chunk run — chunking
    is an execution detail, never a statistical one."""
    rng = np.random.RandomState(5)
    iscs = 0.2 + 0.1 * rng.randn(10, 4)
    kwargs = dict(statistic="median", side="two-sided", seed=11,
                  return_distribution=True)
    one = NullEngine(null_batch_size=16).run(
        iscs, "subject_bootstrap", 48, **kwargs)
    many = NullEngine(null_batch_size=16, budget_bytes=1).run(
        iscs, "subject_bootstrap", 48, **kwargs)
    assert np.array_equal(one.distribution, many.distribution,
                          equal_nan=True)
    assert np.array_equal(one.p_values(), many.p_values())


def test_engine_population_scale_chunked_run_and_resume(tmp_path):
    """The scale proof: 20,000 resamples under a 64 KiB budget — the
    materialized [N, V] null (1.25 MiB at f64) cannot exist under
    the budget, so the run MUST chunk (and does: ~40 chunks), and an
    injected preemption mid-run resumes from the checkpoint to a
    BIT-IDENTICAL p-map."""
    from brainiak_tpu.resilience import faults

    rng = np.random.RandomState(6)
    iscs = 0.2 + 0.1 * rng.randn(12, 8)
    n_resamples, budget = 20000, 64 * 1024
    assert n_resamples * iscs.shape[1] * 8 > budget  # no [N, V] fits
    kwargs = dict(statistic="median", side="right", seed=7)
    engine = NullEngine(null_batch_size=64, budget_bytes=budget)
    full = engine.run(iscs, "subject_bootstrap", n_resamples,
                      **kwargs)
    assert full.n == n_resamples

    ckpt = os.path.join(str(tmp_path), "ckpt")
    with pytest.raises(faults.PreemptionError):
        with faults.inject("preempt", at_step=3):
            engine.run(iscs, "subject_bootstrap", n_resamples,
                       checkpoint_dir=ckpt, **kwargs)
    resumed = engine.run(iscs, "subject_bootstrap", n_resamples,
                         checkpoint_dir=ckpt, **kwargs)
    assert np.array_equal(resumed.p_values(), full.p_values())
    assert np.array_equal(resumed.observed, full.observed)
    assert resumed.fwer_threshold() == full.fwer_threshold()


def test_engine_disjoint_ranges_pool_exactly():
    """The pooling proof: two engine runs over disjoint halves of
    the resample index space (same seed — ONE key schedule sliced
    per range) merge to EXACTLY the single full run, across the
    NullDistribution merge surface."""
    rng = np.random.RandomState(7)
    iscs = 0.2 + 0.1 * rng.randn(9, 5)
    kwargs = dict(statistic="median", side="two-sided", seed=13)
    engine = NullEngine(null_batch_size=16)
    full = engine.run(iscs, "subject_bootstrap", 64, **kwargs)
    lo = engine.run(iscs, "subject_bootstrap", 64,
                    index_range=(0, 32), **kwargs)
    hi = engine.run(iscs, "subject_bootstrap", 64,
                    index_range=(32, 64), **kwargs)
    assert lo.n == 32 and hi.n == 32 and not lo.complete
    pooled = lo.merge(hi)
    assert pooled.complete
    assert np.array_equal(pooled.p_values(), full.p_values())
    assert np.array_equal(pooled.ci(95)[0], full.ci(95)[0])
    assert pooled.fwer_threshold() == full.fwer_threshold()


def test_exact_sign_flip_matches_itertools_brute_force():
    """Exact sign-flip enumeration (n_resamples >= 2**n) carries the
    same multiset of null statistics as an itertools product over
    every sign pattern, and the exact-mode p-map (counts / n, no +1)
    matches the reference convention bitwise."""
    rng = np.random.RandomState(8)
    iscs = 0.2 + 0.3 * rng.randn(4, 3)
    engine = NullEngine(null_batch_size=16)
    res = engine.run(iscs, "sign_flip", 16, statistic="median",
                     side="two-sided", return_distribution=True)
    assert res.exact and res.n == 16
    brute = np.stack([
        np.median(np.asarray(signs)[:, None] * iscs, axis=0)
        for signs in itertools.product((1.0, -1.0), repeat=4)])
    assert np.allclose(np.sort(res.distribution, axis=0),
                       np.sort(brute, axis=0), atol=1e-6)
    want = _p_reference(res.observed, brute, 'two-sided', True)
    assert np.allclose(res.p_values(), want, atol=1e-12)


def test_engine_runs_every_family():
    """Each registered family completes end-to-end through the
    chunked engine and yields a valid p-map."""
    rng = np.random.RandomState(9)
    iscs = 0.2 + 0.1 * rng.randn(8, 4)
    data = rng.randn(24, 4, 6)
    group = [0] * 3 + [1] * 5
    engine = NullEngine(null_batch_size=16)
    runs = {
        "subject_bootstrap": (iscs, {}),
        "sign_flip": (iscs, {}),
        "group_shuffle": (iscs, {"group_assignment": group}),
        "circular_timeshift": (data, {}),
        "phase_randomize": (data, {}),
    }
    for family, (payload, extra) in runs.items():
        res = engine.run(payload, family, 24, statistic="median",
                         side="two-sided", seed=1, **extra)
        p = res.p_values()
        assert p.shape == (4,)
        assert np.all((p > 0.0) & (p <= 1.0)), family
        assert res.family == family


def test_repeat_runs_never_retrace():
    """The retrace contract: re-running a family at the same lane
    width reuses the compiled program — retrace_total{stats.*} gains
    nothing on the second pass."""
    from brainiak_tpu.obs import metrics as obs_metrics

    rng = np.random.RandomState(10)
    iscs = 0.2 + 0.1 * rng.randn(8, 4)
    engine = NullEngine(null_batch_size=16)
    kwargs = dict(statistic="median", side="right", seed=2)
    engine.run(iscs, "subject_bootstrap", 32, **kwargs)
    engine.run(iscs, "sign_flip", 32, **kwargs)
    counter = obs_metrics.counter("retrace_total")

    def stats_counts():
        return {labels.get("site"): value
                for labels, value in counter.samples()
                if labels.get("site", "").startswith("stats.")}

    before = stats_counts()
    engine.run(iscs, "subject_bootstrap", 64, **kwargs)
    engine.run(iscs, "sign_flip", 64, **kwargs)
    assert stats_counts() == before


def test_default_null_batch_unified():
    """The one shared default (satellite c): power-of-two lanes,
    clamped to [16, 64], monotone in the voxel count."""
    sizes = [default_null_batch(v)
             for v in (1, 64, 1024, 1 << 20)]
    for batch in sizes:
        assert batch in (16, 32, 64)
    assert sizes == sorted(sizes, reverse=True)


def test_stats_budget_env_override(monkeypatch):
    from brainiak_tpu.stats import stats_budget_bytes
    monkeypatch.setenv("BRAINIAK_TPU_STATS_BUDGET_BYTES", "12345")
    assert stats_budget_bytes() == 12345
    monkeypatch.delenv("BRAINIAK_TPU_STATS_BUDGET_BYTES")
    assert stats_budget_bytes() == (1 << 28)
