"""The four legacy ISC resampling wrappers now route through
``NullEngine``; these tests pin what the rewiring must preserve:
matched-seed determinism across calls, and the
``return_distribution=False`` accumulator path returning the BITWISE
same p-map as the materialized path (the null is counted, never
stored)."""

import numpy as np
import pytest

from brainiak_tpu.isc import (bootstrap_isc, permutation_isc,
                              phaseshift_isc, timeshift_isc)

SEED = 42


@pytest.fixture(scope="module")
def iscs():
    rng = np.random.RandomState(0)
    return 0.2 + 0.1 * rng.randn(10, 6)


@pytest.fixture(scope="module")
def data():
    rng = np.random.RandomState(1)
    return rng.randn(30, 4, 8)


def test_bootstrap_isc_matched_seed_and_counted_path(iscs):
    obs, ci, p, dist = bootstrap_isc(
        iscs, n_bootstraps=48, random_state=SEED)
    obs2, ci2, p2, dist2 = bootstrap_isc(
        iscs, n_bootstraps=48, random_state=SEED)
    assert np.array_equal(obs, obs2)
    assert np.array_equal(p, p2)
    assert np.array_equal(dist, dist2, equal_nan=True)
    assert dist.shape == (48, 6)

    obs3, ci3, p3, dist3 = bootstrap_isc(
        iscs, n_bootstraps=48, random_state=SEED,
        return_distribution=False)
    assert dist3 is None
    assert np.array_equal(obs3, obs)
    assert np.array_equal(p3, p)


def test_permutation_isc_matched_seed_and_counted_path(iscs):
    group = [0] * 4 + [1] * 6
    obs, p, dist = permutation_isc(
        iscs, group_assignment=group, n_permutations=48,
        random_state=SEED)
    obs2, p2, dist2 = permutation_isc(
        iscs, group_assignment=group, n_permutations=48,
        random_state=SEED)
    assert np.array_equal(p, p2)
    assert np.array_equal(dist, dist2, equal_nan=True)

    obs3, p3, dist3 = permutation_isc(
        iscs, group_assignment=group, n_permutations=48,
        random_state=SEED, return_distribution=False)
    assert dist3 is None
    assert np.array_equal(np.asarray(obs3), np.asarray(obs))
    assert np.array_equal(p3, p)


def test_permutation_isc_one_sample_counted_path(iscs):
    obs, p, dist = permutation_isc(
        iscs, n_permutations=32, random_state=SEED)
    obs3, p3, dist3 = permutation_isc(
        iscs, n_permutations=32, random_state=SEED,
        return_distribution=False)
    assert dist3 is None
    assert np.array_equal(p3, p)


def test_timeshift_isc_matched_seed_and_counted_path(data):
    obs, p, dist = timeshift_isc(
        data, n_shifts=32, random_state=SEED)
    obs2, p2, dist2 = timeshift_isc(
        data, n_shifts=32, random_state=SEED)
    assert np.array_equal(p, p2)
    assert np.array_equal(dist, dist2, equal_nan=True)

    obs3, p3, dist3 = timeshift_isc(
        data, n_shifts=32, random_state=SEED,
        return_distribution=False)
    assert dist3 is None
    assert np.array_equal(obs3, obs)
    assert np.array_equal(p3, p)


def test_phaseshift_isc_matched_seed_and_counted_path(data):
    obs, p, dist = phaseshift_isc(
        data, n_shifts=32, random_state=SEED)
    obs2, p2, dist2 = phaseshift_isc(
        data, n_shifts=32, random_state=SEED)
    assert np.array_equal(p, p2)
    assert np.array_equal(dist, dist2, equal_nan=True)

    obs3, p3, dist3 = phaseshift_isc(
        data, n_shifts=32, random_state=SEED,
        return_distribution=False)
    assert dist3 is None
    assert np.array_equal(obs3, obs)
    assert np.array_equal(p3, p)
