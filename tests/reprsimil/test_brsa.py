import numpy as np
import pytest

from brainiak_tpu.reprsimil.brsa import BRSA, GBRSA


def make_brsa_data(n_t=150, n_v=30, n_c=4, seed=0, snr_scale=1.0,
                   n_runs=2):
    """Synthetic data following the BRSA generative model."""
    rng = np.random.RandomState(seed)
    # smooth-ish design with known covariance structure between conditions
    design = np.zeros((n_t, n_c))
    for c in range(n_c):
        onsets = rng.choice(n_t - 12, size=6, replace=False)
        for o in onsets:
            design[o:o + 6, c] += 1.0
    from scipy.ndimage import gaussian_filter1d
    design = gaussian_filter1d(design, 2, axis=0)

    U = np.array([[1.0, 0.8, 0.0, 0.0],
                  [0.8, 1.0, 0.0, 0.0],
                  [0.0, 0.0, 1.0, 0.8],
                  [0.0, 0.0, 0.8, 1.0]])[:n_c, :n_c]
    L = np.linalg.cholesky(U + 1e-9 * np.eye(n_c))
    snr = np.exp(rng.randn(n_v) * 0.3) * snr_scale
    sigma = 1.0 + 0.2 * rng.rand(n_v)
    rho = 0.3 + 0.2 * rng.rand(n_v)

    onsets = np.arange(0, n_t, n_t // n_runs)[:n_runs]
    beta = (L @ rng.randn(n_c, n_v)) * snr * sigma
    noise = np.zeros((n_t, n_v))
    for v in range(n_v):
        e = rng.randn(n_t)
        for t in range(1, n_t):
            if t not in onsets:
                e[t] = rho[v] * e[t - 1] + \
                    np.sqrt(1 - rho[v] ** 2) * e[t]
        noise[:, v] = e * sigma[v]
    Y = design @ beta + noise
    return Y, design, U, snr, onsets


def test_brsa_recovers_structure():
    Y, design, U, snr, onsets = make_brsa_data(seed=1)
    model = BRSA(n_iter=1, auto_nuisance=False, lbfgs_iters=150,
                 random_state=0)
    model.fit(Y, design, scan_onsets=onsets)
    assert model.U_.shape == (4, 4)
    assert model.C_.shape == (4, 4)
    # recovered correlation structure: within-pair >> across-pair
    within = (model.C_[0, 1] + model.C_[2, 3]) / 2
    across = np.mean([abs(model.C_[0, 2]), abs(model.C_[0, 3]),
                      abs(model.C_[1, 2]), abs(model.C_[1, 3])])
    assert within > across + 0.2
    assert within > 0.4
    # SNR map correlates with the generative SNR
    c = np.corrcoef(np.log(model.nSNR_), np.log(snr))[0, 1]
    assert c > 0.3
    # noise parameters sensible
    assert np.all(model.sigma_ > 0)
    assert np.all(np.abs(model.rho_) < 1)
    assert model.beta_.shape == (4, Y.shape[1])


def test_brsa_auto_nuisance_and_transform():
    Y, design, U, snr, onsets = make_brsa_data(seed=2)
    model = BRSA(n_iter=2, auto_nuisance=True, n_nureg=3,
                 lbfgs_iters=100, random_state=0)
    model.fit(Y, design, scan_onsets=onsets)
    assert model.X0_.shape[1] >= 3
    ts, ts0 = model.transform(Y, scan_onsets=onsets)
    assert ts.shape == (Y.shape[0], 4)
    # decoded task time course correlates with the true design
    c = np.corrcoef(ts[:, 0], design[:, 0])[0, 1]
    assert c > 0.3


def test_brsa_score_prefers_true_model():
    Y, design, U, snr, onsets = make_brsa_data(seed=3)
    Y2, design2, _, _, _ = make_brsa_data(seed=30)
    model = BRSA(n_iter=1, auto_nuisance=False, lbfgs_iters=100,
                 random_state=0)
    model.fit(Y, design, scan_onsets=onsets)
    ll, ll_null = model.score(Y, design, scan_onsets=onsets)
    assert ll > ll_null  # removing the fitted response helps


def test_brsa_validation():
    Y, design, _, _, _ = make_brsa_data()
    model = BRSA()
    with pytest.raises(AssertionError):
        model.fit(Y[:, :5] * 0, design)  # constant voxels
    with pytest.raises(AssertionError):
        model.fit(Y[:-5], design)  # length mismatch
    with pytest.raises(AssertionError):
        bad_design = np.column_stack([design, design[:, 0]])
        model.fit(Y, bad_design)  # rank-deficient design
    with pytest.raises(AssertionError):
        BRSA(GP_inten=True, GP_space=False).fit(Y, design)


def test_brsa_gp_prior_runs():
    Y, design, _, _, onsets = make_brsa_data(n_v=20, seed=4)
    rng = np.random.RandomState(0)
    coords = rng.rand(20, 3) * 10
    model = BRSA(n_iter=1, auto_nuisance=False, GP_space=True,
                 lbfgs_iters=60, random_state=0)
    model.fit(Y, design, scan_onsets=onsets, coords=coords)
    assert np.all(np.isfinite(model.nSNR_))
    # learned GP hyperparameters are exposed like the reference's
    assert np.isfinite(model.lGPspace_) and model.lGPspace_ > 0
    assert np.isfinite(model.bGP_) and model.bGP_ > 0
    # with intensity: both scales learned
    inten = rng.rand(20) * 5
    model2 = BRSA(n_iter=1, auto_nuisance=False, GP_space=True,
                  GP_inten=True, lbfgs_iters=60, random_state=0)
    model2.fit(Y, design, scan_onsets=onsets, coords=coords, inten=inten)
    assert np.isfinite(model2.lGPinten_) and model2.lGPinten_ > 0
    # half-Cauchy variance prior: finite fit (its MAP tau2 is 0 at the
    # zero init, which must not poison the objective with NaN)
    from brainiak_tpu.reprsimil.brsa import prior_GP_var_half_cauchy
    model3 = BRSA(n_iter=1, auto_nuisance=False, GP_space=True,
                  lbfgs_iters=40, random_state=0,
                  tau2_prior=prior_GP_var_half_cauchy)
    model3.fit(Y, design, scan_onsets=onsets, coords=coords)
    assert np.isfinite(model3.lGPspace_) and np.isfinite(model3.bGP_)
    # a custom callable cannot be resolved to a jittable branch: clear
    # error instead of a silent prior mismatch
    import functools
    with pytest.raises(ValueError):
        BRSA(GP_space=True, tau2_prior=functools.partial(
            prior_GP_var_half_cauchy)).fit(
            Y, design, scan_onsets=onsets, coords=coords)


def test_brsa_gp_learns_smoothness():
    """Smoothly varying log-SNR over a 1-D voxel line: the learned GP
    prior should smooth the SNR map toward the generative profile better
    than the GP-free fit (the behavior the reference's learned
    length-scale machinery exists for, brsa.py:2425-2517)."""
    n_v = 30
    rng = np.random.RandomState(7)
    coords = np.column_stack([np.linspace(0, 20, n_v),
                              np.zeros(n_v), np.zeros(n_v)])
    # generative SNR: one smooth bump in the middle of the line
    log_snr_true = 1.2 * np.exp(-0.5 * (coords[:, 0] - 10.0) ** 2 / 9.0)
    Y, design, _, _, onsets = make_brsa_data(n_v=n_v, seed=8)
    # rebuild data with the spatially smooth SNR profile
    snr = np.exp(log_snr_true - log_snr_true.mean())
    U = np.array([[1.0, 0.8, 0.0, 0.0], [0.8, 1.0, 0.0, 0.0],
                  [0.0, 0.0, 1.0, 0.8], [0.0, 0.0, 0.8, 1.0]])
    L = np.linalg.cholesky(U + 1e-9 * np.eye(4))
    beta = (L @ rng.randn(4, n_v)) * snr
    Y = design @ beta + rng.randn(*Y.shape)

    gp = BRSA(n_iter=1, auto_nuisance=False, GP_space=True,
              lbfgs_iters=150, random_state=0)
    gp.fit(Y, design, scan_onsets=onsets, coords=coords)
    plain = BRSA(n_iter=1, auto_nuisance=False, lbfgs_iters=150,
                 random_state=0)
    plain.fit(Y, design, scan_onsets=onsets)

    c_gp = np.corrcoef(np.log(gp.nSNR_), log_snr_true)[0, 1]
    c_plain = np.corrcoef(np.log(plain.nSNR_), log_snr_true)[0, 1]
    assert c_gp > 0.4
    assert c_gp >= c_plain - 0.05
    # the optimizer must MOVE the scale well above its voxel-size init
    # (~0.7, the box's lower edge) — smoothing actually engaged
    assert gp.lGPspace_ > 2.0


def test_gbrsa_mesh_matches_single():
    """Voxel-sharding the GBRSA grid likelihood must not change the fit
    (padding is mask-weighted because a zero-data voxel's grid LL still
    depends on the parameters)."""
    from brainiak_tpu.parallel.mesh import make_mesh

    from tests.conftest import mesh_atol

    Y, design, _, _, onsets = make_brsa_data(n_v=21, seed=12)
    kw = dict(rank=None, lbfgs_iters=60, SNR_bins=4, rho_bins=4,
              auto_nuisance=False, random_state=0)
    single = GBRSA(**kw).fit([Y], [design], scan_onsets=onsets)
    # 21 voxels on 8 shards exercises the padding path
    mesh = make_mesh(("voxel",), (8,))
    sharded = GBRSA(mesh=mesh, **kw).fit([Y], [design],
                                         scan_onsets=onsets)
    import jax
    # U_ entries are O(30): under fp32 the sharded reduction order shifts
    # the L-BFGS trajectory at relative ~1e-5, so compare relatively
    rtol = 0.0 if jax.config.jax_enable_x64 else 1e-3
    np.testing.assert_allclose(sharded.U_, single.U_, atol=mesh_atol(),
                               rtol=rtol)
    np.testing.assert_allclose(sharded.nSNR_[0], single.nSNR_[0],
                               atol=mesh_atol(), rtol=rtol)
    # a plain int list is one shared onset vector, consistently across
    # fit/transform/score (fit already consumed it above)
    ts, ts0 = single.transform(Y, scan_onsets=list(onsets))
    assert np.all(np.isfinite(ts))
    ll, ll_null = single.score(Y, design, scan_onsets=list(onsets))
    assert np.all(np.isfinite(ll))


def test_gbrsa_multi_subject():
    datasets, designs = [], []
    for s in range(2):
        Y, design, U, _, onsets = make_brsa_data(n_v=20, seed=10 + s)
        datasets.append(Y)
        designs.append(design)
    # auto_nuisance off: with only 20 voxels, residual PCs absorb real
    # signal (the reference's Gavish-Donoho n_nureg selection addresses
    # this at realistic voxel counts)
    model = GBRSA(rank=None, lbfgs_iters=80, SNR_bins=5, rho_bins=5,
                  auto_nuisance=False, random_state=0)
    model.fit(datasets, designs)
    assert model.U_.shape == (4, 4)
    within = (model.C_[0, 1] + model.C_[2, 3]) / 2
    across = np.mean([abs(model.C_[0, 2]), abs(model.C_[0, 3]),
                      abs(model.C_[1, 2]), abs(model.C_[1, 3])])
    assert within > across
    assert len(model.nSNR_) == 2
    ll, ll_null = model.score(datasets, designs)
    assert len(ll) == 2
    ts, ts0 = model.transform(datasets)
    assert len(ts) == 2 and ts[0].shape == (datasets[0].shape[0], 4)
    # decoded time course genuinely correlates with the true design
    c = np.corrcoef(ts[0][:, 0], designs[0][:, 0])[0, 1]
    assert c > 0.3
    with pytest.raises(ValueError):
        model.transform([datasets[0]])  # subject count mismatch


def test_gbrsa_auto_nuisance_and_priors():
    Y, design, _, _, onsets = make_brsa_data(n_v=25, seed=20)
    model = GBRSA(lbfgs_iters=40, SNR_bins=4, rho_bins=4, n_nureg=2,
                  auto_nuisance=True, random_state=0)
    model.fit(Y, design)
    assert np.all(np.isfinite(model.U_))
    # per-subject scan_onsets list + nuisance array accepted
    nuis = np.random.RandomState(0).randn(Y.shape[0], 2)
    model2 = GBRSA(lbfgs_iters=30, SNR_bins=4, rho_bins=4,
                   auto_nuisance=False, SNR_prior='lognorm',
                   random_state=0)
    model2.fit([Y], [design], nuisance=[nuis],
               scan_onsets=[onsets])
    ll, ll_null = model2.score([Y], [design], scan_onsets=[onsets])
    # single-subject results are unwrapped to scalars
    assert np.isfinite(ll) and np.isfinite(ll_null)
    with pytest.raises(ValueError):
        GBRSA(SNR_prior='gaussian').fit(Y, design)


def test_ncomp_svht():
    from brainiak_tpu.reprsimil.brsa import Ncomp_SVHT_MG_DLD_approx

    rng = np.random.RandomState(0)
    # low-rank signal + noise: SVHT should find ~the true rank
    U = rng.randn(200, 3)
    V = rng.randn(3, 100)
    X = U @ V + 0.1 * rng.randn(200, 100)
    ncomp = Ncomp_SVHT_MG_DLD_approx(X, zscore=False)
    assert 2 <= ncomp <= 5
    # pure noise: very few components survive
    ncomp_noise = Ncomp_SVHT_MG_DLD_approx(rng.randn(200, 100),
                                           zscore=False)
    assert ncomp_noise <= ncomp
    # zscore=True normalizes internally (the reference's default
    # calling convention, reference brsa.py:733): scaling a column by
    # a large constant must not change the answer
    X_scaled = X.copy()
    X_scaled[:, 0] *= 1e6
    assert Ncomp_SVHT_MG_DLD_approx(X_scaled, zscore=True) \
        == Ncomp_SVHT_MG_DLD_approx(X, zscore=True)


def test_brsa_auto_n_nureg():
    Y, design, _, _, onsets = make_brsa_data(n_v=40, seed=5)
    model = BRSA(n_iter=2, auto_nuisance=True, n_nureg=None,
                 lbfgs_iters=60, random_state=0)
    model.fit(Y, design, scan_onsets=onsets)
    assert model.X0_.shape[1] >= 2  # DC components + selected PCs


def test_lgssm_smoother_matches_dense_oracle():
    """The block-tridiagonal state-space smoother behind transform/score
    (marginal likelihood AND posterior mean) equals a dense multivariate
    normal constructed independently from AR(1) covariance matrices."""
    import jax.numpy as jnp
    from scipy.stats import multivariate_normal
    from brainiak_tpu.reprsimil.brsa import _lgssm_segment

    rng = np.random.RandomState(0)
    T, V, K = 12, 4, 3
    W = rng.randn(K, V)
    sigma2_e = rng.rand(V) + 0.5
    rho_e = rng.uniform(-0.6, 0.6, V)
    rho_x = rng.uniform(-0.5, 0.9, K)
    sigma2_x = rng.rand(K) + 0.2
    Y = rng.randn(T, V)

    mu, log_p = _lgssm_segment(
        jnp.asarray(Y), jnp.asarray(W), jnp.asarray(sigma2_e),
        jnp.asarray(rho_e), jnp.asarray(rho_x), jnp.asarray(sigma2_x))
    mu, log_p = np.asarray(mu), float(log_p)

    def ar1_cov(n, rho, sig2):
        idx = np.arange(n)
        return sig2 / (1 - rho ** 2) * \
            rho ** np.abs(idx[:, None] - idx[None, :])

    cov = np.zeros((T * V, T * V))
    for k in range(K):
        cov += np.kron(ar1_cov(T, rho_x[k], sigma2_x[k]),
                       np.outer(W[k], W[k]))
    for v in range(V):
        iv = np.arange(T) * V + v
        cov[np.ix_(iv, iv)] += ar1_cov(T, rho_e[v], sigma2_e[v])
    log_p_dense = multivariate_normal(
        mean=np.zeros(T * V), cov=cov).logpdf(Y.reshape(-1))

    czy = np.zeros((T * K, T * V))
    for k in range(K):
        Kk = ar1_cov(T, rho_x[k], sigma2_x[k])
        ik = np.arange(T) * K + k
        for v in range(V):
            iv = np.arange(T) * V + v
            czy[np.ix_(ik, iv)] += Kk * W[k, v]
    mu_dense = (czy @ np.linalg.solve(cov, Y.reshape(-1))).reshape(T, K)

    import jax
    f64 = jax.config.jax_enable_x64
    assert abs(log_p - log_p_dense) < (1e-8 if f64 else 5e-2)
    assert np.abs(mu - mu_dense).max() < (1e-10 if f64 else 1e-3)

    # length-1 segment: precision is stationary prior + stationary-noise
    # emission only (regression: the T>=2 block construction aliased here)
    mu1, log_p1 = _lgssm_segment(
        jnp.asarray(Y[:1]), jnp.asarray(W), jnp.asarray(sigma2_e),
        jnp.asarray(rho_e), jnp.asarray(rho_x), jnp.asarray(sigma2_x))
    cov1 = np.zeros((V, V))
    for k in range(K):
        cov1 += sigma2_x[k] / (1 - rho_x[k] ** 2) * np.outer(W[k], W[k])
    cov1 += np.diag(sigma2_e / (1 - rho_e ** 2))
    log_p1_dense = multivariate_normal(
        mean=np.zeros(V), cov=cov1).logpdf(Y[0])
    czy1 = np.zeros((K, V))
    for k in range(K):
        czy1[k] = sigma2_x[k] / (1 - rho_x[k] ** 2) * W[k]
    mu1_dense = czy1 @ np.linalg.solve(cov1, Y[0])
    assert abs(float(log_p1) - log_p1_dense) < (1e-8 if f64 else 5e-2)
    assert np.abs(np.asarray(mu1)[0] - mu1_dense).max() < \
        (1e-10 if f64 else 1e-3)


def test_nureg_methods():
    """All four reference nuisance decompositions are accepted
    (reference brsa.py:546-558) and produce usable components; unknown
    names fail with the reference's message."""
    Y, design, _, _, onsets = make_brsa_data(n_v=25, seed=30)
    for method in ("PCA", "FA", "ICA", "SPCA"):
        model = BRSA(n_iter=2, auto_nuisance=True, n_nureg=2,
                     nureg_method=method, lbfgs_iters=20,
                     random_state=0)
        comps = model._nuisance_components(
            np.random.RandomState(0).randn(60, 25))
        assert comps.shape == (60, 2)
        assert np.all(np.isfinite(comps))
        np.testing.assert_allclose(comps.std(0), 1.0, atol=1e-6)
    with pytest.raises(ValueError, match="nureg_method"):
        BRSA(nureg_method="kmeans")
