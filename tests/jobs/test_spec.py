"""JobSpec validation, the lifecycle table, and the npz codec."""

import io
import json

import numpy as np
import pytest

from brainiak_tpu.jobs.spec import (
    CODEC_SCHEMA,
    KINDS,
    STATES,
    TERMINAL_STATES,
    JobSpec,
    can_transition,
    decode_jobs,
    encode_jobs,
    load_jobs,
    new_job_id,
    save_jobs,
)


def test_new_job_id_is_16_hex_and_unique():
    ids = {new_job_id() for _ in range(64)}
    assert len(ids) == 64
    for job_id in ids:
        assert len(job_id) == 16
        int(job_id, 16)


def test_spec_defaults_mint_a_job_id():
    a = JobSpec(tenant="t", kind="srm")
    b = JobSpec(tenant="t", kind="srm")
    assert a.job_id != b.job_id
    assert a.priority == 0 and a.checkpoint_every == 1


@pytest.mark.parametrize("bad", [
    dict(tenant="", kind="srm"),
    dict(tenant=None, kind="srm"),
    dict(tenant="t", kind="svm"),
    dict(tenant="t", kind="srm", n_iter=0),
    dict(tenant="t", kind="srm", checkpoint_every=0),
])
def test_spec_validation_rejects(bad):
    with pytest.raises(ValueError):
        JobSpec(**bad)


def test_lifecycle_table():
    assert TERMINAL_STATES == {"done", "failed", "cancelled"}
    assert set(STATES) >= TERMINAL_STATES
    assert can_transition("queued", "running")
    assert can_transition("running", "parked")
    assert can_transition("running", "queued")   # crash requeue
    assert can_transition("parked", "running")   # resume
    assert can_transition("parked", "cancelled")
    assert not can_transition("queued", "parked")
    assert not can_transition("parked", "done")
    for terminal in TERMINAL_STATES:
        for state in STATES:
            assert not can_transition(terminal, state)
    assert not can_transition("nonsense", "running")


def test_roundtrip_dict_rejects_unknown_keys():
    spec = JobSpec(tenant="t", kind="htfa", n_iter=4, seed=9)
    assert JobSpec.from_dict(spec.to_dict()) == spec
    with pytest.raises(ValueError, match="unknown JobSpec keys"):
        JobSpec.from_dict({**spec.to_dict(), "gpu_hours": 3})


def test_codec_roundtrip_without_pickle():
    specs = [JobSpec(tenant=f"t{i}", kind=KINDS[i % len(KINDS)],
                     priority=i, n_iter=2 + i, deadline_s=1.5 * i
                     if i else None)
             for i in range(4)]
    data = encode_jobs(specs)
    assert decode_jobs(data) == specs
    # the archive really is pickle-free npz
    with np.load(io.BytesIO(data), allow_pickle=False) as archive:
        assert int(archive["n_jobs"]) == 4
        assert int(archive["codec_schema"]) == CODEC_SCHEMA


def test_codec_rejects_non_spec_and_newer_schema():
    with pytest.raises(TypeError):
        encode_jobs([{"tenant": "t", "kind": "srm"}])
    buf = io.BytesIO()
    np.savez(buf, codec_schema=np.array(CODEC_SCHEMA + 1),
             n_jobs=np.array(1),
             **{"job.0": np.array(json.dumps(
                 JobSpec(tenant="t", kind="srm").to_dict()))})
    with pytest.raises(ValueError, match="codec_schema"):
        decode_jobs(buf.getvalue())


def test_save_load_file(tmp_path):
    specs = [JobSpec(tenant="a", kind="srm"),
             JobSpec(tenant="b", kind="ridge_encoding", n_iter=3)]
    path = save_jobs(str(tmp_path / "batch.npz"), specs)
    assert load_jobs(path) == specs


def test_cli_gen_writes_loadable_batch(tmp_path, capsys):
    from brainiak_tpu.jobs.__main__ import main

    out = str(tmp_path / "jobs.npz")
    rc = main(["gen", "--out", out, "--tenant", "hospital-a",
               "--kind", "srm", "--n", "3", "--n-iter", "2",
               "--seed", "5", "--priority", "1"])
    assert rc == 0
    verdict = json.loads(capsys.readouterr().out)
    specs = load_jobs(out)
    assert [s.job_id for s in specs] == verdict["job_ids"]
    assert [s.seed for s in specs] == [5, 6, 7]
    assert all(s.tenant == "hospital-a" and s.priority == 1
               for s in specs)
