"""Jobs test isolation: clean obs surfaces (the scheduler writes
events, metrics, progress and flight records) plus a leaked
finish-listener guard — a test that forgets to ``close()`` its
scheduler must not leave its hook observing later tests' fits."""

import pytest

from brainiak_tpu.obs import flight, metrics, progress, sink
from brainiak_tpu.jobs import scheduler as sched_mod


@pytest.fixture(autouse=True)
def _clean_obs(monkeypatch):
    monkeypatch.delenv(sink.OBS_DIR_ENV, raising=False)
    monkeypatch.delenv(sink.OBS_RANK_ENV, raising=False)
    monkeypatch.delenv(flight.FLIGHT_DIR_ENV, raising=False)
    monkeypatch.delenv(flight.FLIGHT_RECORDS_ENV, raising=False)
    sink.close_all()
    metrics.reset()
    flight.clear()
    progress.clear_registry()
    yield
    # close any scheduler a failing test left live (close() also
    # detaches its finish listener and the _active entry)
    with sched_mod._active_lock:
        leaked = list(sched_mod._active)
    for sched in leaked:
        sched.close()
    with progress._listeners_lock:
        del progress._finish_listeners[:]
    sink.close_all()
    metrics.reset()
    flight.clear()
    progress.clear_registry()
