"""TrafficGenerator fit-workload mode (the jobs soak/bench feed)."""

import numpy as np
import pytest

from brainiak_tpu.jobs.spec import JobSpec
from brainiak_tpu.serve.federation.traffic import TrafficGenerator


def _mix(specs):
    return [(s.tenant, s.kind, s.priority, s.seed) for s in specs]


def test_fit_jobs_deterministic_mix():
    a = TrafficGenerator(seed=3).fit_jobs(
        12, kinds=("srm", "ridge_encoding"), priorities=(0, 1))
    b = TrafficGenerator(seed=3).fit_jobs(
        12, kinds=("srm", "ridge_encoding"), priorities=(0, 1))
    assert _mix(a) == _mix(b)  # job_ids differ; the mix replays
    assert all(isinstance(s, JobSpec) for s in a)
    assert len({s.job_id for s in a}) == 12
    assert len({s.seed for s in a}) == 12  # per-job datasets


def test_fit_jobs_zipf_tenant_skew():
    specs = TrafficGenerator(seed=0).fit_jobs(
        300, tenants=("big", "mid", "small"))
    counts = [sum(1 for s in specs if s.tenant == t)
              for t in ("big", "mid", "small")]
    assert counts[0] > counts[1] > counts[2] > 0


def test_job_schedule_rate_and_order():
    schedule = TrafficGenerator(seed=1).job_schedule(
        40, target_jobs_per_s=8.0, n_iter=2)
    arrivals = [t for t, _ in schedule]
    assert arrivals == sorted(arrivals)
    # rescaled so the MEAN rate is exact: last arrival = n / rate
    assert arrivals[-1] == pytest.approx(40 / 8.0)
    gaps = np.diff(arrivals)
    assert gaps.max() > 3 * np.median(gaps)  # the tail stays heavy
    assert all(isinstance(s, JobSpec) for _, s in schedule)


def test_fit_only_generator_rejects_serving_requests():
    gen = TrafficGenerator(model=None, seed=2)
    assert gen.voxel_counts == []
    with pytest.raises(ValueError, match="fit-only"):
        gen.requests(3)
    with pytest.raises(ValueError, match="target_jobs_per_s"):
        gen.job_schedule(3, target_jobs_per_s=0.0)
