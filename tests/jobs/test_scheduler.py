"""Scheduler lifecycle: preempt-park-resume parity, fair share,
cancellation, crash containment, shed admission, and the control
plane — the ISSUE-20 scenarios the JOB001 gate mirrors in CI."""

import json
import time

import pytest

from brainiak_tpu.jobs.quota import FairShare
from brainiak_tpu.jobs.runners import run_job
from brainiak_tpu.jobs.scheduler import (
    Scheduler,
    SchedulerClosed,
    scheduler_state,
)
from brainiak_tpu.jobs.spec import JobSpec
from brainiak_tpu.obs import flight, metrics
from brainiak_tpu.resilience import faults
from brainiak_tpu.serve.federation.admission import (
    AdmissionController,
)

# tiny but real SRM fits: every chunk is one EM iteration persisted
# through the checkpoint contract
FIT = dict(kind="srm", features=2, checkpoint_every=1,
           n_subjects=2, voxels=8, samples=12)


def make_sched(tmp_path, **kwargs):
    kwargs.setdefault("max_slots", 1)
    kwargs.setdefault("serve_pressure_depth", 1 << 20)
    kwargs.setdefault("tick_interval_s", 0.01)
    return Scheduler(str(tmp_path / "jobs"), **kwargs)


def poll(sched, job_id, predicate, timeout=60.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        row = sched.job(job_id)
        if predicate(row):
            return row
        time.sleep(0.01)
    raise AssertionError(
        f"job {job_id} never satisfied predicate; last row: "
        f"{sched.job(job_id)}")


def test_single_job_runs_to_done(tmp_path):
    spec = JobSpec(tenant="hospital-a", n_iter=3, seed=1, **FIT)
    with make_sched(tmp_path) as sched:
        ticket = sched.submit(spec)
        record = ticket.result(timeout=120.0)
        assert record["state"] == "done"
        assert record["digest"] is not None
        assert record["fit_id"] is not None
        assert record["chunks"] == pytest.approx(3.0)
        summary = sched.summary()
        assert summary["counts"] == {"done": 1}
        assert summary["tenants"]["hospital-a"]["usage"] == \
            pytest.approx(3.0)
        # the module-level merged view feeds the /jobs payload
        merged = scheduler_state()
        assert merged is not None
        assert merged["counts"] == {"done": 1}
    assert scheduler_state() is None  # closed schedulers unregister


def test_preempt_park_resume_parity(tmp_path):
    low = JobSpec(tenant="hospital-a", priority=0, n_iter=10,
                  seed=7, **FIT)
    hi = JobSpec(tenant="hospital-b", priority=1, n_iter=5,
                 seed=11, **FIT)
    with make_sched(tmp_path, pressure_slots=1) as sched:
        low_ticket = sched.submit(low)
        mid = poll(sched, low.job_id,
                   lambda r: r["state"] == "running"
                   and r["chunks"] >= 1)
        fit_id = mid["fit_id"]
        assert fit_id is not None
        hi_ticket = sched.submit(hi)
        hi_rec = hi_ticket.result(timeout=120.0)
        low_rec = low_ticket.result(timeout=120.0)
    assert hi_rec["state"] == "done"
    assert low_rec["state"] == "done"
    # the high-priority arrival parked the running low fit...
    assert low_rec["n_preemptions"] >= 1
    assert hi_rec["n_preemptions"] == 0
    assert low_rec["grants"] >= 2
    # ...which resumed the SAME fit (same fit_id, same checkpoint
    # stream) and landed on bit-exact parameters: an uninterrupted
    # solo run of the same spec reaches the identical digest
    assert low_rec["fit_id"] == fit_id
    solo = run_job(
        JobSpec(tenant="solo", priority=0, n_iter=10, seed=7,
                **FIT),
        str(tmp_path / "solo"))
    assert low_rec["digest"] == solo["digest"]


def test_fair_share_bounds_light_tenant_makespan(tmp_path):
    heavy = [JobSpec(tenant="heavy", n_iter=6, seed=20 + i, **FIT)
             for i in range(2)]
    light = JobSpec(tenant="light", n_iter=2, seed=30, **FIT)
    with make_sched(tmp_path, grant_chunks=1) as sched:
        heavy_tickets = sched.submit_many(heavy)
        light_ticket = sched.submit(light)
        light_rec = light_ticket.result(timeout=120.0)
        heavy_recs = [t.result(timeout=120.0)
                      for t in heavy_tickets]
    assert light_rec["state"] == "done"
    assert all(r["state"] == "done" for r in heavy_recs)
    # chunk-granular grants interleave by virtual time: the light
    # tenant (2 chunks) finishes before EITHER heavy job (6 chunks
    # each) despite submitting last — it is never starved behind
    # the heavy tenant's backlog
    assert all(light_rec["finished_ts"] < r["finished_ts"]
               for r in heavy_recs)
    vt = {t: e["virtual_time"]
          for t, e in sched.summary()["tenants"].items()}
    assert vt["light"] < vt["heavy"]


def test_weighted_fair_share_is_respected(tmp_path):
    fair = FairShare(weights={"gold": 3.0, "bronze": 1.0})
    specs = [JobSpec(tenant=t, n_iter=3, seed=40 + i, **FIT)
             for i, t in enumerate(("gold", "bronze"))]
    with make_sched(tmp_path, grant_chunks=1,
                    fair_share=fair) as sched:
        for t in sched.submit_many(specs):
            assert t.result(timeout=120.0)["state"] == "done"
        tenants = sched.summary()["tenants"]
    assert tenants["gold"]["weight"] == 3.0
    assert tenants["gold"]["virtual_time"] == pytest.approx(1.0)
    assert tenants["bronze"]["virtual_time"] == pytest.approx(3.0)


def test_cancel_while_parked_and_while_queued(tmp_path):
    low = JobSpec(tenant="a", priority=0, n_iter=16, seed=3, **FIT)
    hi = JobSpec(tenant="b", priority=1, n_iter=6, seed=4, **FIT)
    queued = JobSpec(tenant="c", priority=0, n_iter=4, seed=5,
                     **FIT)
    with make_sched(tmp_path) as sched:
        low_ticket = sched.submit(low)
        poll(sched, low.job_id,
             lambda r: r["state"] == "running" and r["chunks"] >= 1)
        hi_ticket = sched.submit(hi)
        queued_ticket = sched.submit(queued)
        # the preemption parks low; the hi fit holds the only slot,
        # so low STAYS parked — cancel it there
        poll(sched, low.job_id, lambda r: r["state"] == "parked")
        assert sched.cancel(queued.job_id) is True
        assert sched.cancel(low.job_id) is True
        low_rec = low_ticket.result(timeout=30.0)
        queued_rec = queued_ticket.result(timeout=30.0)
        hi_rec = hi_ticket.result(timeout=120.0)
        # terminal jobs refuse a second cancel (exactly-one-terminal)
        assert sched.cancel(low.job_id) is False
        assert sched.cancel("no-such-job") is False
    assert low_rec["state"] == "cancelled"
    assert queued_rec["state"] == "cancelled"
    assert queued_rec["fit_id"] is None  # never ran
    assert hi_rec["state"] == "done"


def _terminal_count(tenant):
    total = 0.0
    for labels, value in metrics.counter(
            "jobs_terminal_total").samples():
        if dict(labels).get("tenant") == tenant:
            total += value
    return total


def test_replica_crash_requeues_then_done_exactly_once(tmp_path):
    spec = JobSpec(tenant="crashy", n_iter=3, seed=6, **FIT)
    with make_sched(tmp_path) as sched:
        with faults.inject("replica_crash", at_step=0, times=1,
                           target=spec.job_id) as fault:
            record = sched.submit(spec).result(timeout=120.0)
        assert fault.fired == 1
    # the crash requeued the job (checkpoint intact) and the retry
    # finished it: ONE terminal state, counted exactly once
    assert record["state"] == "done"
    assert record["crash_retries"] == 1
    assert record["grants"] == 2
    assert _terminal_count("crashy") == 1.0


def test_replica_crash_exhausts_retries_to_terminal_failed(
        tmp_path):
    spec = JobSpec(tenant="doomed", n_iter=3, seed=6, **FIT)
    with make_sched(tmp_path, max_crash_retries=1) as sched:
        with faults.inject("replica_crash", at_step=0, times=5,
                           target=spec.job_id) as fault:
            record = sched.submit(spec).result(timeout=120.0)
        assert fault.fired == 2  # initial grant + the single retry
    assert record["state"] == "failed"
    assert record["crash_retries"] == 2
    assert "replica_crash" in record["error"]
    assert _terminal_count("doomed") == 1.0


def test_shed_submission_fails_fast_with_verdict(tmp_path):
    admission = AdmissionController(
        max_depth=256, tenant_quotas={"noisy": 0})
    spec = JobSpec(tenant="noisy", n_iter=2, seed=8, **FIT)
    with make_sched(tmp_path, admission=admission) as sched:
        ticket = sched.submit(spec)
        assert ticket.done()  # resolved synchronously, no queueing
        record = ticket.result(timeout=1.0)
    assert record["state"] == "failed"
    assert record["error"] == "shed:tenant_quota"
    assert record["shed"]["reason"] == "tenant_quota"
    assert record["shed"]["retry_after_s"] > 0.0
    assert record["fit_id"] is None


def test_diverged_fit_fails_with_status_and_snapshot(
        tmp_path, monkeypatch):
    monkeypatch.setenv(flight.FLIGHT_DIR_ENV,
                       str(tmp_path / "incidents"))
    spec = JobSpec(tenant="nan-lab", n_iter=4, seed=9, **FIT)
    with make_sched(tmp_path) as sched:
        # times=10: outlive the resilient loop's rollback budget so
        # the divergence is terminal, not recovered
        with faults.inject("nan", at_step=1, times=10):
            record = sched.submit(spec).result(timeout=120.0)
    assert record["state"] == "failed"
    assert record["fit_status"] == "diverged"
    assert "DivergenceError" in record["error"]
    # the flight-recorder incident snapshot is attached, not lost
    assert record["snapshot_path"] is not None
    manifest = json.load(open(
        record["snapshot_path"] + "/manifest.json"))
    assert manifest["trigger"] == "divergence_abort"
    assert manifest["fit_id"] == record["fit_id"]


def test_serving_pressure_parks_excess_fits(tmp_path):
    specs = [JobSpec(tenant=t, n_iter=10, seed=50 + i, **FIT)
             for i, t in enumerate(("a", "b"))]
    with make_sched(tmp_path, max_slots=2, pressure_slots=1,
                    serve_pressure_depth=4) as sched:
        tickets = sched.submit_many(specs)
        for spec in specs:
            poll(sched, spec.job_id,
                 lambda r: r["state"] == "running")
        # a serving burst: the depth gauge the fleet supervisor
        # reads crosses the threshold -> slots shrink to 1
        depth = metrics.gauge("serve_service_queue_depth")
        depth.set(64.0, service="svc")
        deadline = time.monotonic() + 60.0
        while time.monotonic() < deadline:
            counts = sched.summary()["counts"]
            if counts.get("parked", 0) >= 1:
                break
            time.sleep(0.01)
        else:
            raise AssertionError(
                f"pressure never parked a fit: {counts}")
        assert sched.summary()["pressure"] is True
        depth.set(0.0, service="svc")  # burst over: resume
        records = [t.result(timeout=120.0) for t in tickets]
    assert all(r["state"] == "done" for r in records)
    assert sum(r["n_preemptions"] for r in records) >= 1


def test_deadline_overrun_marks_but_never_kills(tmp_path):
    spec = JobSpec(tenant="slo", n_iter=2, seed=10,
                   deadline_s=1e-9, **FIT)
    with make_sched(tmp_path) as sched:
        record = sched.submit(spec).result(timeout=120.0)
    assert record["state"] == "done"
    assert record["deadline_exceeded"] is True


def test_submit_rejects_duplicates_bad_types_and_closed(tmp_path):
    spec = JobSpec(tenant="t", n_iter=2, seed=11, **FIT)
    sched = make_sched(tmp_path)
    try:
        sched.submit(spec)
        with pytest.raises(ValueError, match="duplicate job_id"):
            sched.submit(spec)
        with pytest.raises(TypeError):
            sched.submit({"tenant": "t"})
        assert sched.drain(timeout=120.0) is True
    finally:
        sched.close()
    with pytest.raises(SchedulerClosed):
        sched.submit(JobSpec(tenant="t", n_iter=2, **FIT))


def test_http_control_plane_and_cli_roundtrip(tmp_path, capsys):
    from brainiak_tpu.jobs.__main__ import main

    batch = str(tmp_path / "batch.npz")
    rc = main(["gen", "--out", batch, "--tenant", "hospital-a",
               "--n", "2", "--n-iter", "2", "--seed", "12",
               "--voxels", "8", "--samples", "12",
               "--features", "2", "--subjects", "2"])
    assert rc == 0
    job_ids = json.loads(capsys.readouterr().out)["job_ids"]

    with make_sched(tmp_path, http_port=0) as sched:
        url = f"http://127.0.0.1:{sched.http.port}"
        assert main(["submit", batch, "--url", url]) == 0
        verdict = json.loads(capsys.readouterr().out)
        assert verdict == {"accepted": job_ids, "shed": []}
        assert sched.drain(timeout=120.0) is True
        # status renders the scheduler table from GET /jobs
        assert main(["status", "--url", url, "--json"]) == 0
        payload = json.loads(capsys.readouterr().out)
        assert payload["scheduler"]["counts"] == {"done": 2}
        states = {row["job_id"]: row["state"]
                  for row in payload["scheduler"]["jobs"]}
        assert states == {j: "done" for j in job_ids}
        # plain-text rendering exercises _render_status
        assert main(["status", "--url", url]) == 0
        text = capsys.readouterr().out
        assert "hospital-a" in text and "done=2" in text
        # cancelling a terminal job reports failure (rc 1)
        assert main(["cancel", job_ids[0], "--url", url]) == 1
        assert json.loads(
            capsys.readouterr().out)["cancelled"] is False
