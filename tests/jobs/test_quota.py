"""FairShare: the virtual-time ledger the scheduler picks by."""

import pytest

from brainiak_tpu.jobs.quota import FairShare


def test_weight_validation():
    with pytest.raises(ValueError):
        FairShare(default_weight=0.0)
    with pytest.raises(ValueError):
        FairShare(weights={"t": -1.0})


def test_charge_usage_virtual_time():
    fair = FairShare(weights={"heavy": 2.0})
    fair.charge("heavy", 4)
    fair.charge("light", 1)
    fair.charge("light", 1)
    assert fair.usage("heavy") == 4.0
    assert fair.usage("light") == 2.0
    # vt normalizes by weight: heavy ran twice the chunks but has
    # twice the weight, so the two tenants tie
    assert fair.virtual_time("heavy") == fair.virtual_time("light")
    with pytest.raises(ValueError):
        fair.charge("light", -1)


def test_pick_minimal_virtual_time_with_lexical_tiebreak():
    fair = FairShare()
    assert fair.pick([]) is None
    assert fair.pick(["b", "a"]) == "a"  # vt tie -> lexical
    fair.charge("a", 3)
    assert fair.pick(["a", "b"]) == "b"
    fair.charge("b", 5)
    assert fair.pick(["a", "b"]) == "a"


def test_deficits_entitlement_minus_consumption():
    fair = FairShare(weights={"big": 3.0})
    fair.charge("big", 4)
    fair.charge("small", 4)
    deficits = fair.deficits()
    # total 8 chunks, weights 3:1 -> big entitled to 6, small to 2
    assert deficits["big"] == pytest.approx(2.0)
    assert deficits["small"] == pytest.approx(-2.0)
    # widening includes a tenant that never consumed
    wide = fair.deficits(["big", "small", "idle"])
    assert wide["idle"] > 0.0
    assert fair.deficits() != {} and FairShare().deficits() == {}


def test_summary_is_json_shaped():
    fair = FairShare(weights={"a": 2.0})
    fair.charge("a", 6)
    fair.charge("b", 1)
    summary = fair.summary()
    assert sorted(summary) == ["a", "b"]
    assert summary["a"] == {"usage": 6.0, "weight": 2.0,
                            "virtual_time": 3.0,
                            "deficit": summary["a"]["deficit"]}
    assert summary["a"]["deficit"] == pytest.approx(-4.0 / 3.0)
