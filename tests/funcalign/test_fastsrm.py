import numpy as np
import pytest
from sklearn.exceptions import NotFittedError

from brainiak_tpu.funcalign.fastsrm import FastSRM


def make_fastsrm_data(n_subjects=4, voxels=60, components=3,
                      session_lengths=(30, 25), noise=0.05, seed=0):
    rng = np.random.RandomState(seed)
    shared = [rng.randn(components, t) for t in session_lengths]
    imgs, bases = [], []
    for i in range(n_subjects):
        q, _ = np.linalg.qr(rng.randn(voxels, components))
        bases.append(q)
        sessions = [q @ s + noise * rng.randn(voxels, s.shape[1])
                    for s in shared]
        imgs.append(sessions)
    return imgs, bases, shared


def test_fastsrm_fit_transform_inverse():
    imgs, _, shared = make_fastsrm_data()
    model = FastSRM(n_components=3, n_iter=20, aggregate="mean")
    model.fit(imgs)
    assert len(model.basis_list) == 4
    out = model.transform(imgs)
    assert len(out) == 2  # one per session
    assert out[0].shape == (3, 30) and out[1].shape == (3, 25)
    # shared response recovered up to rotation: correlations with truth
    c = np.abs(np.corrcoef(out[0].ravel(), (out[0]).ravel())[0, 1])
    assert np.isfinite(c)
    # inverse reconstructs data well
    recon = model.inverse_transform(out)
    rel = np.linalg.norm(recon[0][0] - imgs[0][0]) / \
        np.linalg.norm(imgs[0][0])
    assert rel < 0.2


def test_fastsrm_single_session_and_aggregate_none():
    imgs, _, _ = make_fastsrm_data(session_lengths=(40,))
    flat = [subj[0] for subj in imgs]  # list-of-arrays input
    model = FastSRM(n_components=3, n_iter=20, aggregate=None)
    out = model.fit_transform(flat)
    assert len(out) == 4  # per subject
    assert out[0].shape == (3, 40)
    with pytest.raises(ValueError):
        FastSRM(aggregate="median")


def test_fastsrm_deterministic_atlas():
    imgs, _, _ = make_fastsrm_data(voxels=60)
    atlas = np.repeat(np.arange(1, 11), 6)  # 10 parcels
    model = FastSRM(atlas=atlas, n_components=3, n_iter=20)
    model.fit(imgs)
    out = model.transform(imgs)
    assert out[0].shape == (3, 30)


def test_fastsrm_probabilistic_atlas():
    imgs, _, _ = make_fastsrm_data(voxels=60)
    rng = np.random.RandomState(1)
    atlas = np.abs(rng.randn(10, 60))  # probabilistic
    model = FastSRM(atlas=atlas, n_components=3, n_iter=20)
    model.fit(imgs)
    out = model.transform(imgs)
    assert out[0].shape == (3, 30)


def test_fastsrm_parallel_reduce_matches_serial(tmp_path):
    imgs, _, _ = make_fastsrm_data(n_subjects=3)
    serial = FastSRM(n_components=3, n_iter=15, n_jobs=1).fit(imgs)
    parallel = FastSRM(n_components=3, n_iter=15, n_jobs=3).fit(imgs)
    for b0, b1 in zip(serial.basis_list, parallel.basis_list):
        assert np.allclose(b0, b1, atol=1e-10)
    # threaded reduce combined with the disk-spill path
    spill = FastSRM(n_components=3, n_iter=15, n_jobs=3,
                    temp_dir=str(tmp_path), low_ram=True).fit(imgs)
    for b0, b1 in zip(serial.basis_list, spill.basis_list):
        assert np.allclose(b0, np.load(b1) if isinstance(b1, str) else b1,
                           atol=1e-10)
    spill.clean()


def test_fastsrm_paths_and_low_ram(tmp_path):
    imgs, _, _ = make_fastsrm_data(n_subjects=3)
    paths = np.empty((3, 2), dtype=object)
    for i, subj in enumerate(imgs):
        for j, sess in enumerate(subj):
            p = tmp_path / f"s{i}_{j}.npy"
            np.save(p, sess)
            paths[i, j] = str(p)
    model = FastSRM(n_components=3, n_iter=15,
                    temp_dir=str(tmp_path), low_ram=True)
    model.fit(paths)
    assert isinstance(model.basis_list[0], str)
    out = model.transform(paths)
    assert out[0].shape == (3, 30)
    model.clean()
    assert not any(p.name.startswith("fastsrm")
                   for p in tmp_path.iterdir())


def test_fastsrm_add_subjects():
    imgs, _, _ = make_fastsrm_data(n_subjects=5)
    model = FastSRM(n_components=3, n_iter=20)
    model.fit(imgs[:4])
    shared = model.transform(imgs[:4])
    model.add_subjects(imgs[4:], shared)
    assert len(model.basis_list) == 5
    # new subject's basis reconstructs its data
    recon = model.inverse_transform(shared, subjects_indexes=[4])
    rel = np.linalg.norm(recon[0][0] - imgs[4][0]) / \
        np.linalg.norm(imgs[4][0])
    assert rel < 0.25


def test_fastsrm_errors():
    imgs, _, _ = make_fastsrm_data()
    with pytest.raises(NotFittedError):
        FastSRM(n_components=3).transform(imgs)
    with pytest.raises(ValueError):
        FastSRM(n_components=3).fit(imgs[:1])
    with pytest.raises(ValueError):
        FastSRM(n_components=3).fit([imgs[0], imgs[1][:1]])


def test_fastsrm_input_validation():
    """Shape/atlas/index validation mirrors the reference's check layer
    (reference fastsrm.py:256-454): clear errors instead of deep matmul
    failures."""
    rng = np.random.RandomState(0)
    V, T, K = 60, 40, 4
    imgs = [rng.randn(V, T) for _ in range(3)]

    with pytest.raises(ValueError, match="voxels"):
        FastSRM(n_components=K).fit(
            [imgs[0], imgs[1], rng.randn(V + 5, T)])
    with pytest.raises(ValueError, match="timeframes"):
        FastSRM(n_components=K).fit(
            [[imgs[0]], [rng.randn(V, T - 3)], [imgs[2]]])
    with pytest.raises(ValueError, match="2 axes"):
        FastSRM(n_components=K).fit([rng.randn(V), imgs[1], imgs[2]])
    with pytest.raises(ValueError, match="shorter than"):
        FastSRM(n_components=50).fit(imgs)
    with pytest.raises(ValueError, match="Atlas has"):
        atlas = np.tile(np.arange(1, 11), 5)  # 50 voxels, data have 60
        FastSRM(atlas=atlas, n_components=K).fit(imgs)
    with pytest.raises(ValueError, match="regions"):
        atlas = np.tile(np.arange(1, 4), 20)  # 3 regions <= 4 components
        FastSRM(atlas=atlas, n_components=K).fit(imgs)

    model = FastSRM(n_components=K).fit(imgs)
    with pytest.raises(ValueError, match="out of range"):
        model.transform(imgs, subjects_indexes=[0, 1, 5])
    with pytest.raises(ValueError, match="must match"):
        model.transform(imgs[:2], subjects_indexes=[0, 1, 2])
    with pytest.raises(ValueError, match="out of range"):
        model.inverse_transform(rng.randn(K, T), subjects_indexes=[9])


# -- ISSUE 13: SubjectStore ingestion ---------------------------------

def test_fastsrm_store_matches_array_input(tmp_path):
    """A SubjectStore routes each subject through SubjectRef handles
    and the streamed voxel-chunked reduction, reproducing the eager
    array-input fit exactly."""
    from brainiak_tpu.data import write_store

    imgs, _, _ = make_fastsrm_data(session_lengths=(30,))
    flat = [subj[0] for subj in imgs]
    store = write_store(str(tmp_path / "st"), flat,
                        dtype=np.float64)
    rng = np.random.RandomState(1)
    atlas = rng.randint(0, 9, size=flat[0].shape[0])

    eager = FastSRM(atlas=atlas, n_components=3, n_iter=10,
                    seed=0).fit([[x] for x in flat])
    streamed = FastSRM(atlas=atlas, n_components=3, n_iter=10,
                       seed=0).fit(store)
    for a, b in zip(eager.basis_list, streamed.basis_list):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   atol=1e-10)
    out = streamed.transform(store)
    assert np.asarray(out).shape == (3, 30)


def test_reduce_one_streams_in_chunks():
    """The streamed reductions (deterministic label means and
    probabilistic pseudo-inverse projection) match the eager
    formulations at any chunking."""
    from brainiak_tpu.funcalign.fastsrm import _reduce_one

    rng = np.random.RandomState(0)
    data = rng.randn(100, 20)
    atlas = rng.randint(0, 6, size=100)
    values = np.unique(atlas)
    values = values[values != 0]
    eager = np.stack([data.T[:, atlas == c].mean(axis=1)
                      for c in values], axis=1)
    for chunk in (7, 33, 1000):
        np.testing.assert_allclose(
            _reduce_one(data, atlas, None, chunk_voxels=chunk),
            eager, atol=1e-12)

    prob = rng.rand(5, 100)
    inv = np.linalg.pinv(prob)
    eager_p = data.T @ inv
    for chunk in (7, 33, 1000):
        np.testing.assert_allclose(
            _reduce_one(data, None, inv, chunk_voxels=chunk),
            eager_p, atol=1e-12)


def test_reduce_one_memmap_path(tmp_path):
    """.npy-path ingestion reduces off the memmap without an eager
    full load (the finish-the-job satellite: shape probing already
    used mmap; now the reduction itself does)."""
    from brainiak_tpu.funcalign.fastsrm import _reduce_one

    rng = np.random.RandomState(0)
    data = rng.randn(64, 12)
    path = str(tmp_path / "subj.npy")
    np.save(path, data)
    atlas = rng.randint(0, 4, size=64)
    values = np.unique(atlas)
    values = values[values != 0]
    eager = np.stack([data.T[:, atlas == c].mean(axis=1)
                      for c in values], axis=1)
    np.testing.assert_allclose(
        _reduce_one(path, atlas, None, chunk_voxels=16), eager,
        atol=1e-12)


def test_store_fit_never_loads_a_subject_whole(tmp_path,
                                               monkeypatch):
    """Regression guard: the fit-path atlas reduction must go
    through the voxel-chunked readers, never a full SubjectRef.load
    (that was the whole point of the streamed ingestion)."""
    from brainiak_tpu.data import write_store
    from brainiak_tpu.data.store import SubjectRef
    from brainiak_tpu.funcalign.fastsrm import _reduce_one

    imgs, _, _ = make_fastsrm_data(session_lengths=(30,))
    flat = [subj[0] for subj in imgs]
    store = write_store(str(tmp_path / "st"), flat,
                        dtype=np.float64)
    rng = np.random.RandomState(1)
    atlas = rng.randint(0, 9, size=flat[0].shape[0])

    def no_full_loads(self):
        raise AssertionError(
            "streamed reduction loaded a subject whole")

    monkeypatch.setattr(SubjectRef, "load", no_full_loads)
    out = _reduce_one(store.ref(0), atlas, None)
    values = np.unique(atlas)
    values = values[values != 0]
    eager = np.stack([flat[0].T[:, atlas == c].mean(axis=1)
                      for c in values], axis=1)
    np.testing.assert_allclose(out, eager, atol=1e-12)
