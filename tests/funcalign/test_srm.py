import numpy as np
import pytest

from brainiak_tpu.funcalign.srm import SRM, DetSRM, load


def make_synthetic(n_subjects=4, voxels=30, samples=40, features=4,
                   noise=0.1, seed=0, ragged=False):
    """X_i = W_i S + noise with orthonormal W_i."""
    rng = np.random.RandomState(seed)
    S = rng.randn(features, samples)
    X, W = [], []
    for i in range(n_subjects):
        v = voxels + (i if ragged else 0)
        q, _ = np.linalg.qr(rng.randn(v, features))
        W.append(q)
        X.append(q @ S + noise * rng.randn(v, samples))
    return X, W, S


def shared_space_correlation(model, X):
    """Mean pairwise correlation of per-subject shared responses."""
    s = model.transform(X)
    corrs = []
    for i in range(len(s)):
        for j in range(i + 1, len(s)):
            corrs.append(np.corrcoef(s[i].ravel(), s[j].ravel())[0, 1])
    return np.mean(corrs)


@pytest.mark.parametrize("cls", [SRM, DetSRM])
def test_srm_recovers_shared_structure(cls):
    X, _, S = make_synthetic()
    model = cls(n_iter=10, features=4)
    model.fit(X)
    assert len(model.w_) == len(X)
    for i, w in enumerate(model.w_):
        assert w.shape == (X[i].shape[0], 4)
        # orthonormality
        assert np.allclose(w.T @ w, np.eye(4), atol=1e-5)
    assert model.s_.shape == (4, 40)
    # subjects agree in shared space
    assert shared_space_correlation(model, X) > 0.9


@pytest.mark.parametrize("cls", [SRM, DetSRM])
def test_srm_ragged_voxel_counts(cls):
    X, _, _ = make_synthetic(ragged=True)
    model = cls(n_iter=8, features=4)
    model.fit(X)
    for i, w in enumerate(model.w_):
        assert w.shape == (X[i].shape[0], 4)
        assert np.allclose(w.T @ w, np.eye(4), atol=1e-5)
    assert shared_space_correlation(model, X) > 0.9


def test_srm_attributes_and_logprob():
    X, _, _ = make_synthetic()
    model = SRM(n_iter=10, features=4)
    model.fit(X)
    assert model.sigma_s_.shape == (4, 4)
    assert model.rho2_.shape == (4,)
    assert np.all(model.rho2_ > 0)
    assert len(model.mu_) == 4
    assert np.isfinite(model.logprob_)
    # rho2 should approximate the injected noise variance (0.1^2)
    assert np.all(model.rho2_ < 0.1)


def test_srm_errors():
    X, _, _ = make_synthetic(n_subjects=2)
    with pytest.raises(ValueError):
        SRM(n_iter=2, features=4).fit([X[0]])
    with pytest.raises(ValueError):
        SRM(n_iter=2, features=4).fit([X[0], X[1][:, :-3]])
    with pytest.raises(ValueError):
        SRM(n_iter=2, features=100).fit(X)
    model = SRM(n_iter=2, features=4)
    from sklearn.exceptions import NotFittedError
    with pytest.raises(NotFittedError):
        model.transform(X)
    with pytest.raises(NotFittedError):
        model.transform_subject(X[0])
    model.fit(X)
    with pytest.raises(ValueError):
        model.transform([X[0]])
    with pytest.raises(ValueError):
        model.transform_subject(X[0][:, :-2])


def test_transform_subject_new():
    X, _, _ = make_synthetic(n_subjects=5)
    model = SRM(n_iter=10, features=4)
    model.fit(X[:4])
    w_new = model.transform_subject(X[4])
    assert w_new.shape == (X[4].shape[0], 4)
    assert np.allclose(w_new.T @ w_new, np.eye(4), atol=1e-5)
    # held-out subject maps into shared space consistently
    s_new = w_new.T @ X[4]
    s0 = model.w_[0].T @ X[0]
    assert np.corrcoef(s_new.ravel(), s0.ravel())[0, 1] > 0.8


def test_save_load_roundtrip(tmp_path):
    X, _, _ = make_synthetic()
    model = SRM(n_iter=5, features=4)
    model.fit(X)
    path = tmp_path / "model.npz"
    model.save(path)
    loaded = load(path)
    assert loaded.features == 4 and loaded.n_iter == 5
    for w0, w1 in zip(model.w_, loaded.w_):
        assert np.allclose(w0, w1)
    assert np.allclose(model.s_, loaded.s_)
    assert np.allclose(model.sigma_s_, loaded.sigma_s_)
    assert np.allclose(model.rho2_, loaded.rho2_)
    # loaded model is usable
    s = loaded.transform(X)
    assert s[0].shape == (4, 40)


def test_unfitted_save(tmp_path):
    from sklearn.exceptions import NotFittedError
    with pytest.raises(NotFittedError):
        SRM().save(tmp_path / "x.npz")


def test_save_load_ragged_voxel_counts(tmp_path):
    """Subjects with DIFFERENT voxel counts save through the
    object-array path (uniform counts use plain stacks so the file
    stays readable with pickle disabled, matching the reference's own
    save(); ragged counts cannot — reference srm.py:451-481)."""
    X, _, _ = make_synthetic(ragged=True)  # 30, 31, 32, 33 voxels
    model = SRM(n_iter=4, features=4)
    model.fit(X)
    path = tmp_path / "ragged.npz"
    model.save(path)
    loaded = load(path)
    for w0, w1 in zip(model.w_, loaded.w_):
        assert w0.shape == w1.shape and np.allclose(w0, w1)
    assert np.allclose(model.s_, loaded.s_)
    s = loaded.transform(X)
    assert s[0].shape == (4, X[0].shape[1])



from tests.conftest import mesh_atol as _mesh_atol

def test_srm_distributed_mesh_matches_single_device():
    """Sharding subjects over the 8-device CPU mesh must reproduce the
    single-device fit (the analog of the reference's MPI test
    tests/funcalign/test_srm_distributed.py)."""
    from brainiak_tpu.parallel import make_mesh

    X, _, _ = make_synthetic(n_subjects=8, voxels=20, samples=30, features=3)
    single = SRM(n_iter=6, features=3).fit(X)
    mesh = make_mesh(("subject",), (8,))
    dist = SRM(n_iter=6, features=3, mesh=mesh).fit(X)
    atol = _mesh_atol()
    for w0, w1 in zip(single.w_, dist.w_):
        assert np.allclose(w0, w1, atol=atol)
    assert np.allclose(single.s_, dist.s_, atol=atol)
    assert np.allclose(single.rho2_, dist.rho2_, atol=atol)


def test_detsrm_distributed_mesh_matches_single_device():
    from brainiak_tpu.parallel import make_mesh

    X, _, _ = make_synthetic(n_subjects=8, voxels=20, samples=30, features=3)
    single = DetSRM(n_iter=6, features=3).fit(X)
    mesh = make_mesh(("subject",), (8,))
    dist = DetSRM(n_iter=6, features=3, mesh=mesh).fit(X)
    atol = _mesh_atol()
    for w0, w1 in zip(single.w_, dist.w_):
        assert np.allclose(w0, w1, atol=atol)
    assert np.allclose(single.s_, dist.s_, atol=atol)


def test_srm_checkpoint_resume(tmp_path):
    """Checkpointed fit matches the plain fit, and an interrupted fit
    resumes from its checkpoint rather than starting over."""
    X, _, _ = make_synthetic(n_subjects=4, voxels=20, samples=30,
                             features=3)
    plain = SRM(n_iter=9, features=3).fit(X)
    ckpt = SRM(n_iter=9, features=3).fit(
        X, checkpoint_dir=str(tmp_path / "full"), checkpoint_every=4)
    for w0, w1 in zip(plain.w_, ckpt.w_):
        assert np.allclose(w0, w1, atol=1e-8)
    assert np.allclose(plain.s_, ckpt.s_, atol=1e-8)

    # simulate preemption: run 4 of 9 iterations, then resume to 9
    partial_dir = str(tmp_path / "partial")
    SRM(n_iter=4, features=3).fit(X, checkpoint_dir=partial_dir,
                                  checkpoint_every=4)
    resumed = SRM(n_iter=9, features=3).fit(X, checkpoint_dir=partial_dir,
                                            checkpoint_every=4)
    for w0, w1 in zip(plain.w_, resumed.w_):
        assert np.allclose(w0, w1, atol=1e-8)
    assert np.allclose(plain.s_, resumed.s_, atol=1e-8)


def test_srm_checkpoint_rejects_mismatched_data(tmp_path):
    X, _, _ = make_synthetic(n_subjects=4, voxels=20, samples=30,
                             features=3)
    d = str(tmp_path / "ck")
    SRM(n_iter=4, features=3).fit(X, checkpoint_dir=d)
    # different data of the same shape must be refused
    X2 = [x + 1.0 for x in X]
    with pytest.raises(ValueError, match="different data"):
        SRM(n_iter=8, features=3).fit(X2, checkpoint_dir=d)
    # lower n_iter than the checkpoint step must be refused
    with pytest.raises(ValueError, match="iteration"):
        SRM(n_iter=2, features=3).fit(X, checkpoint_dir=d)


def test_procrustes_polar_matches_svd_and_survives_rank_deficiency():
    """The tall-input Gram-eigh polar path must match U@Vt from the SVD
    and stay finite on rank-deficient input (RSRM passes
    perturbation=0)."""
    import jax.numpy as jnp

    from brainiak_tpu.funcalign.srm import _procrustes

    import jax

    rng = np.random.RandomState(0)
    a = rng.randn(600, 12)
    w = np.asarray(_procrustes(jnp.asarray(a)))
    u, _, vt = np.linalg.svd(a + 0.001 * np.eye(600, 12),
                             full_matrices=False)
    # fp32 sweep: the Gram path squares the condition number, so
    # proximity to the f64 SVD oracle degrades to ~eps*kappa^2
    x64 = bool(jax.config.jax_enable_x64)
    assert np.allclose(w, u @ vt, atol=1e-8 if x64 else 1e-4)
    assert np.allclose(w.T @ w, np.eye(12),
                       atol=1e-10 if x64 else 1e-5)

    # rank-1 input, no perturbation: finite, orthogonal columns where
    # defined (old absolute-tiny floor overflowed to Inf/NaN here)
    a1 = np.outer(rng.randn(600), np.ones(12))
    w1 = np.asarray(_procrustes(jnp.asarray(a1), perturbation=0.0))
    assert np.all(np.isfinite(w1))

    # all-zero input: finite (0 @ inf would be NaN without the guard)
    w0 = np.asarray(_procrustes(jnp.zeros((600, 12))))
    assert np.all(np.isfinite(w0))


def _conditioned_matrix(kappa, v=600, k=20):
    rng = np.random.RandomState(1)
    u, _ = np.linalg.qr(rng.randn(v, k))
    vv, _ = np.linalg.qr(rng.randn(k, k))
    return (u * np.logspace(0, -np.log10(kappa), k)) @ vv.T


def test_polar_ns_matches_svd():
    """``_polar_ns`` called DIRECTLY (the earlier version of this test
    went through ``_procrustes(perturbation=0.0)``, whose gate sent it
    down the eigh path — it never exercised Newton-Schulz at all).

    Accuracy is floored by working precision on the SQUARED condition
    number of the Gram, err ~ eps * kappa^2, independent of the
    iteration budget (measured: more iterations do not move the
    result).  f64 passes a tight tolerance through kappa=1e3; fp32 is
    asserted against the documented eps*kappa^2 floor model — at
    kappa >= 100 it is NOT a faithful polar factor (see the _polar_ns
    docstring), which this test pins rather than hides."""
    import jax.numpy as jnp

    import brainiak_tpu.funcalign.srm as srm_mod

    k = 20
    probe = np.asarray(jnp.zeros(())).dtype
    f64 = probe == np.float64
    kappas = [1.0, 100.0, 1000.0] if f64 else [1.0, 30.0, 100.0]
    for kappa in kappas:
        a = _conditioned_matrix(kappa, k=k)
        w = np.asarray(srm_mod._polar_ns(jnp.asarray(a)))
        uu, _, vt = np.linalg.svd(a, full_matrices=False)
        err = np.abs(w - uu @ vt).max()
        eps = np.finfo(w.dtype).eps
        # measured prefactor is ~6-10x eps*kappa^2 at 600x20; assert
        # within 30x so the bound is a real model, not a tautology
        bound = max(30.0 * eps * kappa ** 2, 50.0 * eps)
        assert err < bound, (kappa, err, bound)
    # tight absolute claim in the dtype where the path is exact
    if f64:
        a = _conditioned_matrix(1000.0, k=k)
        w = np.asarray(srm_mod._polar_ns(jnp.asarray(a)))
        uu, _, vt = np.linalg.svd(a, full_matrices=False)
        assert np.abs(w - uu @ vt).max() < 1e-6
        assert np.abs(w.T @ w - np.eye(k)).max() < 1e-6


def test_procrustes_ns_path_matches_eigh_path():
    """The gated production route: ``_procrustes`` with the reference's
    0.001 perturbation under POLAR_METHOD='ns' (the only call sites the
    gate lets through) must agree with the default eigh path in the
    regime the docstring claims valid for the working dtype."""
    import jax.numpy as jnp

    import brainiak_tpu.funcalign.srm as srm_mod

    probe = np.asarray(jnp.zeros(())).dtype
    f64 = probe == np.float64
    kappa = 1000.0 if f64 else 30.0
    a = jnp.asarray(_conditioned_matrix(kappa))
    w_eigh = np.asarray(srm_mod._procrustes(a, perturbation=0.001))
    try:
        srm_mod.POLAR_METHOD = "ns"
        w_ns = np.asarray(srm_mod._procrustes(a, perturbation=0.001))
    finally:
        srm_mod.POLAR_METHOD = "eigh"
    tol = 1e-6 if f64 else 3e-3
    assert np.abs(w_ns - w_eigh).max() < tol
    k = a.shape[1]
    assert np.abs(w_ns.T @ w_ns - np.eye(k)).max() < tol
