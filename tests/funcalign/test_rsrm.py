import numpy as np
import pytest
from sklearn.exceptions import NotFittedError

from brainiak_tpu.funcalign.rsrm import RSRM


def make_rsrm_data(n_subjects=4, voxels=40, features=3, trs=50,
                   noise=0.05, outlier_frac=0.02, seed=0):
    rng = np.random.RandomState(seed)
    R = rng.randn(features, trs)
    X, W, S = [], [], []
    for i in range(n_subjects):
        q, _ = np.linalg.qr(rng.randn(voxels, features))
        s = np.zeros((voxels, trs))
        idx = rng.rand(voxels, trs) < outlier_frac
        s[idx] = rng.randn(idx.sum()) * 5
        X.append(q @ R + s + noise * rng.randn(voxels, trs))
        W.append(q)
        S.append(s)
    return X, W, R, S


def test_rsrm_recovery():
    X, W, R, S = make_rsrm_data()
    model = RSRM(n_iter=15, features=3, gamma=0.5)
    model.fit(X)
    assert len(model.w_) == 4
    for w in model.w_:
        assert np.allclose(w.T @ w, np.eye(3), atol=1e-5)
    assert model.r_.shape == (3, 50)
    # shared space is consistent across subjects
    projections = [model.w_[i].T @ (X[i] - model.s_[i]) for i in range(4)]
    for i in range(1, 4):
        c = np.corrcoef(projections[0].ravel(), projections[i].ravel())[0, 1]
        assert c > 0.9
    # individual terms are sparse
    for s in model.s_:
        assert np.mean(np.abs(s) > 1e-8) < 0.2
    assert np.isfinite(model.objective_)


def test_rsrm_transform():
    X, _, _, _ = make_rsrm_data(n_subjects=3)
    model = RSRM(n_iter=10, features=3, gamma=0.5)
    model.fit(X)
    r, s = model.transform(X)
    assert len(r) == 3 and len(s) == 3
    assert r[0].shape == (3, 50)
    assert s[0].shape == (40, 50)
    # None entries pass through
    r2, s2 = model.transform([X[0], None, X[2]])
    assert r2[1] is None and s2[1] is None


def test_rsrm_transform_subject():
    X, _, _, _ = make_rsrm_data(n_subjects=4)
    model = RSRM(n_iter=10, features=3, gamma=0.5)
    model.fit(X[:3])
    w, s = model.transform_subject(X[3])
    assert w.shape == (40, 3)
    assert np.allclose(w.T @ w, np.eye(3), atol=1e-5)
    assert s.shape == (40, 50)
    with pytest.raises(ValueError):
        model.transform_subject(X[3][:, :-1])


def test_rsrm_errors():
    X, _, _, _ = make_rsrm_data(n_subjects=2)
    with pytest.raises(ValueError):
        RSRM(gamma=-1.0).fit(X)
    with pytest.raises(ValueError):
        RSRM(features=3).fit([X[0]])
    with pytest.raises(ValueError):
        RSRM(features=100).fit(X)
    with pytest.raises(ValueError):
        RSRM(features=3).fit([X[0], X[1][:, :-2]])
    with pytest.raises(NotFittedError):
        RSRM().transform(X)
    with pytest.raises(NotFittedError):
        RSRM().transform_subject(X[0])
    model = RSRM(n_iter=5, features=3, gamma=0.5).fit(X)
    with pytest.raises(ValueError):
        model.transform([X[0]])


def test_rsrm_mesh_matches_single_device():
    from brainiak_tpu.parallel import make_mesh

    X, _, _, _ = make_rsrm_data(n_subjects=8)
    single = RSRM(n_iter=8, features=3, gamma=0.5).fit(X)
    mesh = make_mesh(("subject",), (8,))
    dist = RSRM(n_iter=8, features=3, gamma=0.5, mesh=mesh).fit(X)
    from tests.conftest import mesh_atol
    atol = mesh_atol()
    for w0, w1 in zip(single.w_, dist.w_):
        assert np.allclose(w0, w1, atol=atol)
    assert np.allclose(single.r_, dist.r_, atol=atol)
