import numpy as np
import pytest
from sklearn.exceptions import NotFittedError

from brainiak_tpu.funcalign.sssrm import SSSRM


def make_sssrm_data(n_subjects=3, voxels=30, features=3, n_align=40,
                    n_sup=20, noise=0.1, seed=0):
    """Alignment data sharing a response; supervised data whose classes are
    separable in shared space."""
    rng = np.random.RandomState(seed)
    S_align = rng.randn(features, n_align)
    class_means = rng.randn(features, 2) * 3
    X, Z, y = [], [], []
    for i in range(n_subjects):
        q, _ = np.linalg.qr(rng.randn(voxels, features))
        X.append(q @ S_align + noise * rng.randn(voxels, n_align))
        labels = rng.randint(0, 2, n_sup)
        latent = class_means[:, labels] + 0.3 * rng.randn(features, n_sup)
        Z.append(q @ latent + noise * rng.randn(voxels, n_sup))
        y.append(labels + 5)  # arbitrary label values
    return X, y, Z


def test_sssrm_fit_and_predict():
    X, y, Z = make_sssrm_data()
    model = SSSRM(n_iter=4, features=3, gamma=1.0, alpha=0.5)
    model.fit(X, y, Z)
    assert len(model.w_) == 3
    for w in model.w_:
        assert np.allclose(w.T @ w, np.eye(3), atol=1e-5)
    assert model.s_.shape == (3, 40)
    assert set(model.classes_) == {5, 6}
    # predicts training supervised data well
    preds = model.predict(Z)
    acc = np.mean([np.mean(p == yy) for p, yy in zip(preds, y)])
    assert acc > 0.85
    # transform shapes
    s = model.transform(X)
    assert s[0].shape == (3, 40)


def test_sssrm_improves_alignment():
    X, y, Z = make_sssrm_data(noise=0.05)
    model = SSSRM(n_iter=4, features=3, gamma=1.0, alpha=0.3)
    model.fit(X, y, Z)
    proj = model.transform(X)
    for i in range(1, len(proj)):
        c = np.corrcoef(proj[0].ravel(), proj[i].ravel())[0, 1]
        assert c > 0.9


def test_sssrm_unsupervised_subject():
    """A subject with no labeled data (Z/y entries None) still joins
    alignment through the unsupervised Stiefel update (reference
    sssrm.py:133-202 allows missing supervised data per subject), and
    transform/predict return None for that subject's None inputs."""
    X, y, Z = make_sssrm_data(n_subjects=3)
    y[1], Z[1] = None, None
    model = SSSRM(n_iter=3, features=3, gamma=1.0, alpha=0.5)
    model.fit(X, y, Z)
    assert len(model.w_) == 3
    for w in model.w_:
        assert np.allclose(w.T @ w, np.eye(3), atol=1e-5)
    # the unlabeled subject still aligns to the shared response
    proj = model.transform(X)
    c = np.corrcoef(proj[0].ravel(), proj[1].ravel())[0, 1]
    assert c > 0.9
    preds = model.predict([Z[0], None, Z[2]])
    assert preds[1] is None
    acc = np.mean([np.mean(p == yy)
                   for p, yy in zip((preds[0], preds[2]),
                                    (y[0], y[2]))])
    assert acc > 0.85
    s = model.transform([X[0], None, X[2]])
    assert s[1] is None and s[0].shape == (3, 40)


def test_sssrm_errors():
    X, y, Z = make_sssrm_data(n_subjects=2)
    with pytest.raises(ValueError):
        SSSRM(alpha=1.5).fit(X, y, Z)
    with pytest.raises(ValueError):
        SSSRM(gamma=-1.0).fit(X, y, Z)
    with pytest.raises(ValueError):
        SSSRM().fit([X[0]], [y[0]], [Z[0]])
    with pytest.raises(ValueError):
        SSSRM().fit(X, y[:1], Z)
    with pytest.raises(ValueError):
        SSSRM(features=100).fit(X, y, Z)
    with pytest.raises(ValueError):
        SSSRM(features=3).fit([X[0], X[1][:, :-2]], y, Z)
    with pytest.raises(ValueError):
        SSSRM(features=3).fit(X, [y[0], y[1][:-3]], Z)
    with pytest.raises(NotFittedError):
        SSSRM().transform(X)
    with pytest.raises(NotFittedError):
        SSSRM().predict(Z)
