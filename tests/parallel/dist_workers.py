"""Worker functions for the multi-process distributed harness tests.

These run inside jax.distributed processes spawned by
brainiak_tpu.parallel.testing.run_distributed.
"""

import numpy as np


def psum_worker(process_id, num_processes):
    """Global psum across all processes' devices."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh, NamedSharding, PartitionSpec

    devices = np.array(jax.devices())
    mesh = Mesh(devices, ("subject",))
    n_global = len(devices)
    # each process contributes its local slice of a global array
    local = np.arange(jax.local_device_count(), dtype=float) + \
        process_id * jax.local_device_count()
    global_shape = (n_global,)
    arr = jax.make_array_from_process_local_data(
        NamedSharding(mesh, PartitionSpec("subject")), local, global_shape)
    total = jax.jit(lambda x: jnp.sum(x))(arr)
    return float(total), n_global


def srm_worker(process_id, num_processes):
    """Distributed DetSRM over a global mesh: subjects sharded across
    processes; returns the shared response computed with multi-process
    collectives."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh, NamedSharding, PartitionSpec

    from brainiak_tpu.funcalign.srm import _fit_det_srm

    rng = np.random.RandomState(0)
    n_subjects, voxels, samples, features = 4, 12, 16, 3
    S = rng.randn(features, samples)
    data = np.zeros((n_subjects, voxels, samples))
    for i in range(n_subjects):
        q, _ = np.linalg.qr(rng.randn(voxels, features))
        data[i] = q @ S + 0.01 * rng.randn(voxels, samples)

    devices = np.array(jax.devices())
    mesh = Mesh(devices, ("subject",))
    sharding = NamedSharding(mesh, PartitionSpec("subject", None, None))
    n_local = n_subjects // num_processes
    local = data[process_id * n_local:(process_id + 1) * n_local]
    arr = jax.make_array_from_process_local_data(sharding, local,
                                                 data.shape)
    voxel_counts = jnp.full((n_subjects,), voxels)
    key = jax.random.PRNGKey(0)
    fit = jax.jit(_fit_det_srm, static_argnames=("features", "n_iter"))
    w, shared, objective = fit(arr, voxel_counts, key, features=features,
                               n_iter=5)
    # shared response is replicated; fetch it on every process
    return np.asarray(shared), float(objective)


def failing_worker(process_id, num_processes):
    """Process 0 fails immediately; the peer genuinely blocks in a
    cross-process collective, so the harness must kill it."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh, NamedSharding, PartitionSpec

    if process_id == 0:
        raise RuntimeError("intentional worker failure")
    devices = np.array(jax.devices())
    mesh = Mesh(devices, ("subject",))
    local = np.ones(jax.local_device_count())
    arr = jax.make_array_from_process_local_data(
        NamedSharding(mesh, PartitionSpec("subject")), local,
        (len(devices),))
    # global reduction requires the dead peer -> blocks until killed
    total = jax.jit(lambda x: jnp.sum(x))(arr)
    return float(total)
