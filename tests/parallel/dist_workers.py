"""Worker functions for the multi-process distributed harness tests.

These run inside jax.distributed processes spawned by
brainiak_tpu.parallel.testing.run_distributed.
"""

import numpy as np


def make_fcma_data():
    """Shared FCMA dataset for the distributed-vs-single comparison —
    ONE definition so the two sides cannot silently diverge."""
    n_e, n_t, n_v = 8, 20, 32
    rng = np.random.RandomState(5)
    raw = []
    for _ in range(n_e):
        mat = rng.randn(n_t, n_v).astype(np.float64)
        mat = (mat - mat.mean(0)) / (mat.std(0) * np.sqrt(n_t))
        raw.append(mat)
    return raw, [0, 1] * (n_e // 2), n_e // 2


def make_isc_data():
    return np.random.RandomState(6).randn(30, 16, 6)


def make_htfa_data():
    rng = np.random.RandomState(7)
    n_subj = 3  # does not divide 4 devices: pad lanes cross processes
    R_coords = rng.rand(40, 3) * 10.0
    true_c = np.array([[2.0, 2.0, 2.0], [8.0, 8.0, 8.0]])
    F = np.exp(-((R_coords[:, None, :] - true_c[None]) ** 2).sum(-1)
               / 4.0)
    X = [np.asarray(F @ rng.randn(2, 12) + 0.05 * rng.randn(40, 12))
         for _ in range(n_subj)]
    return X, R_coords, n_subj


HTFA_PARAMS = dict(K=2, max_global_iter=2, max_local_iter=2,
                   voxel_ratio=1.0, tr_ratio=1.0, max_voxel=40,
                   max_tr=12)


def psum_worker(process_id, num_processes):
    """Global psum across all processes' devices."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh, NamedSharding, PartitionSpec

    devices = np.array(jax.devices())
    mesh = Mesh(devices, ("subject",))
    n_global = len(devices)
    # each process contributes its local slice of a global array
    local = np.arange(jax.local_device_count(), dtype=float) + \
        process_id * jax.local_device_count()
    global_shape = (n_global,)
    arr = jax.make_array_from_process_local_data(
        NamedSharding(mesh, PartitionSpec("subject")), local, global_shape)
    total = jax.jit(lambda x: jnp.sum(x))(arr)
    return float(total), n_global


def srm_worker(process_id, num_processes):
    """Distributed DetSRM over a global mesh: subjects sharded across
    processes; returns the shared response computed with multi-process
    collectives."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh, NamedSharding, PartitionSpec

    from brainiak_tpu.funcalign.srm import _fit_det_srm

    rng = np.random.RandomState(0)
    n_subjects, voxels, samples, features = 4, 12, 16, 3
    S = rng.randn(features, samples)
    data = np.zeros((n_subjects, voxels, samples))
    for i in range(n_subjects):
        q, _ = np.linalg.qr(rng.randn(voxels, features))
        data[i] = q @ S + 0.01 * rng.randn(voxels, samples)

    devices = np.array(jax.devices())
    mesh = Mesh(devices, ("subject",))
    sharding = NamedSharding(mesh, PartitionSpec("subject", None, None))
    n_local = n_subjects // num_processes
    local = data[process_id * n_local:(process_id + 1) * n_local]
    arr = jax.make_array_from_process_local_data(sharding, local,
                                                 data.shape)
    voxel_counts = jnp.full((n_subjects,), voxels)
    key = jax.random.PRNGKey(0)
    fit = jax.jit(_fit_det_srm, static_argnames=("features", "n_iter"))
    w, shared, objective = fit(arr, voxel_counts, key, features=features,
                               n_iter=5)
    # shared response is replicated; fetch it on every process
    return np.asarray(shared), float(objective)


def failing_worker(process_id, num_processes):
    """Process 0 fails immediately; the peer genuinely blocks in a
    cross-process collective, so the harness must kill it."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh, NamedSharding, PartitionSpec

    if process_id == 0:
        raise RuntimeError("intentional worker failure")
    devices = np.array(jax.devices())
    mesh = Mesh(devices, ("subject",))
    local = np.ones(jax.local_device_count())
    arr = jax.make_array_from_process_local_data(
        NamedSharding(mesh, PartitionSpec("subject")), local,
        (len(devices),))
    # global reduction requires the dead peer -> blocks until killed
    total = jax.jit(lambda x: jnp.sum(x))(arr)
    return float(total)


def voxelselector_worker(process_id, num_processes):
    """FCMA voxel selection with the voxel axis sharded across the
    2-process cluster (the analog of the reference's MPI voxel-block
    task farm, reference voxelselector.py:89-238)."""
    import jax
    from jax.sharding import Mesh

    from brainiak_tpu.fcma.voxelselector import VoxelSelector

    mesh = Mesh(np.array(jax.devices()), ("voxel",))
    raw, labels, epochs_per_subj = make_fcma_data()
    vs = VoxelSelector(labels, epochs_per_subj, 2, raw,
                       voxel_unit=8, mesh=mesh, use_pallas=False)
    return vs.run('svm')


def bootstrap_isc_worker(process_id, num_processes):
    """ISC + bootstrap null with voxels sharded across processes
    (the analog of distributing the reference's per-voxel resampling
    loops)."""
    import jax
    from jax.sharding import Mesh

    from brainiak_tpu.isc import bootstrap_isc, isc

    mesh = Mesh(np.array(jax.devices()), ("voxel",))
    ts = make_isc_data()
    iscs = isc(ts, mesh=mesh)
    observed, ci, p, distribution = bootstrap_isc(
        iscs, n_bootstraps=12, mesh=mesh, null_batch_size=4,
        random_state=0)
    return (np.asarray(iscs), np.asarray(observed), np.asarray(p),
            np.asarray(distribution))


def htfa_worker(process_id, num_processes):
    """HTFA with the subject axis sharded across processes (the analog
    of the reference's hierarchical MPI gather/broadcast,
    htfa.py:672-764)."""
    import jax
    from jax.sharding import Mesh

    from brainiak_tpu.factoranalysis.htfa import HTFA

    mesh = Mesh(np.array(jax.devices()), ("subject",))
    X, R_coords, n_subj = make_htfa_data()
    htfa = HTFA(n_subj=n_subj, mesh=mesh, **HTFA_PARAMS)
    htfa.fit(X, [R_coords] * n_subj)
    return np.asarray(htfa.global_posterior_)


def make_isfc_data():
    return np.random.RandomState(9).randn(24, 16, 4)


def isfc_ring_worker(process_id, num_processes):
    """ISFC via the ppermute ring with the voxel axis sharded AROUND
    the ring across processes — the long-context-style collective
    (ops/ring.py) crossing real process boundaries."""
    import jax
    from jax.sharding import Mesh

    from brainiak_tpu.isc import isfc

    mesh = Mesh(np.array(jax.devices()), ("voxel",))
    ts = make_isfc_data()
    isfcs, iscs = isfc(ts, mesh=mesh, vectorize_isfcs=True)
    return np.asarray(isfcs), np.asarray(iscs)


def make_searchlight_data():
    rng = np.random.RandomState(11)
    dim = 7
    data = [rng.randn(dim, dim, dim, 10).astype(np.float64)
            for _ in range(2)]
    mask = np.ones((dim, dim, dim), dtype=bool)
    return data, mask


def searchlight_worker(process_id, num_processes):
    """Traced-tier searchlight with the center sweep sharded across
    processes (the analog of the reference's MPI scatter/gather,
    searchlight.py:301-476)."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh

    from brainiak_tpu.searchlight.searchlight import Searchlight

    mesh = Mesh(np.array(jax.devices()), ("voxel",))
    data, mask = make_searchlight_data()
    sl = Searchlight(sl_rad=1, mesh=mesh)
    sl.distribute(data, mask)

    def voxel_fn(patches, mask_patch, rad, bcast):
        return jnp.mean(patches * mask_patch[None, :, None])

    vol = sl.run_searchlight_jax(voxel_fn, batch_size=64)
    return np.asarray(vol, dtype=float)


def make_srm_class_data():
    rng = np.random.RandomState(12)
    n_subjects, voxels, samples, features = 4, 12, 16, 3
    S = rng.randn(features, samples)
    X = []
    for _ in range(n_subjects):
        q, _ = np.linalg.qr(rng.randn(voxels, features))
        X.append(q @ S + 0.01 * rng.randn(voxels, samples))
    return X


def srm_class_worker(process_id, num_processes):
    """The PUBLIC SRM estimator API (fit/w_/s_) under a cross-process
    subject mesh — exercises the class-level fetches, not just the
    private jitted core."""
    import jax
    from jax.sharding import Mesh

    from brainiak_tpu.funcalign.srm import SRM

    mesh = Mesh(np.array(jax.devices()), ("subject",))
    X = make_srm_class_data()
    srm = SRM(n_iter=5, features=3, rand_seed=0, mesh=mesh)
    srm.fit(X)
    return ([np.asarray(w) for w in srm.w_], np.asarray(srm.s_),
            np.asarray(srm.rho2_))


def make_gbrsa_data():
    rng = np.random.RandomState(13)
    n_t, n_v, n_c = 40, 16, 2
    design = np.zeros((n_t, n_c))
    design[5:10, 0] = 1.0
    design[20:25, 1] = 1.0
    data = design @ rng.randn(n_c, n_v) + rng.randn(n_t, n_v)
    return data, design, np.array([0, n_t // 2])


def gbrsa_worker(process_id, num_processes):
    """GBRSA with each subject's voxel axis sharded across processes
    (grid-marginal likelihood is voxelwise independent)."""
    import jax
    from jax.sharding import Mesh

    from brainiak_tpu.reprsimil.brsa import GBRSA

    mesh = Mesh(np.array(jax.devices()), ("voxel",))
    data, design, onsets = make_gbrsa_data()
    gb = GBRSA(SNR_bins=3, rho_bins=3, lbfgs_iters=15,
               auto_nuisance=False, random_state=0, mesh=mesh)
    gb.fit([data], [design], scan_onsets=onsets)
    return np.asarray(gb.U_), np.asarray(gb.nSNR_)
