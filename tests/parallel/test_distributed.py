"""Multi-process jax.distributed tests — the analog of the reference's
MPI-marked tests (tests/funcalign/test_srm_distributed.py etc.), run as
OS processes forming a distributed JAX cluster on CPU."""

import numpy as np
import pytest

from brainiak_tpu.parallel.testing import run_distributed
from tests.conftest import REPO_ROOT, mesh_atol


def _x64():
    import jax
    return bool(jax.config.jax_enable_x64)


def test_distributed_psum():
    results = run_distributed("tests.parallel.dist_workers", "psum_worker",
                              n_procs=2, local_devices=2, x64=_x64(),
                              extra_path=REPO_ROOT)
    totals = [r[0] for r in results]
    n_global = results[0][1]
    assert n_global == 4
    assert all(t == totals[0] for t in totals)
    assert totals[0] == float(sum(range(4)))


def test_distributed_detsrm_matches_single_process():
    results = run_distributed("tests.parallel.dist_workers", "srm_worker",
                              n_procs=2, local_devices=2, x64=_x64(),
                              extra_path=REPO_ROOT)
    shared_0, obj_0 = results[0]
    shared_1, obj_1 = results[1]
    # both processes agree on the replicated shared response
    assert np.allclose(shared_0, shared_1, atol=1e-10)
    assert np.isclose(obj_0, obj_1)

    # and the distributed result matches a local single-process fit
    import jax
    import jax.numpy as jnp

    from brainiak_tpu.funcalign.srm import _fit_det_srm_jit

    rng = np.random.RandomState(0)
    n_subjects, voxels, samples, features = 4, 12, 16, 3
    S = rng.randn(features, samples)
    data = np.zeros((n_subjects, voxels, samples))
    for i in range(n_subjects):
        q, _ = np.linalg.qr(rng.randn(voxels, features))
        data[i] = q @ S + 0.01 * rng.randn(voxels, samples)
    w, shared, objective = _fit_det_srm_jit(
        jnp.asarray(data), jnp.full((n_subjects,), voxels),
        jax.random.PRNGKey(0), features=features, n_iter=5)
    atol = mesh_atol()
    assert np.allclose(np.asarray(shared), shared_0, atol=atol)


def test_distributed_fast_failure_reporting():
    """A worker that dies immediately is reported promptly with its real
    traceback, not a timeout."""
    import time

    t0 = time.time()
    with pytest.raises(RuntimeError, match="intentional worker failure"):
        run_distributed("tests.parallel.dist_workers", "failing_worker",
                        n_procs=2, local_devices=1, timeout=180,
                        extra_path=REPO_ROOT)
    assert time.time() - t0 < 60  # far less than the 180s timeout
