"""Multi-process jax.distributed tests — the analog of the reference's
MPI-marked tests (tests/funcalign/test_srm_distributed.py etc.), run as
OS processes forming a distributed JAX cluster on CPU."""

import numpy as np
import pytest

from brainiak_tpu.parallel.testing import run_distributed
from tests.conftest import REPO_ROOT, mesh_atol


def _x64():
    import jax
    return bool(jax.config.jax_enable_x64)


def test_distributed_psum():
    results = run_distributed("tests.parallel.dist_workers", "psum_worker",
                              n_procs=2, local_devices=2, x64=_x64(),
                              extra_path=REPO_ROOT)
    totals = [r[0] for r in results]
    n_global = results[0][1]
    assert n_global == 4
    assert all(t == totals[0] for t in totals)
    assert totals[0] == float(sum(range(4)))


def test_distributed_detsrm_matches_single_process():
    results = run_distributed("tests.parallel.dist_workers", "srm_worker",
                              n_procs=2, local_devices=2, x64=_x64(),
                              extra_path=REPO_ROOT)
    shared_0, obj_0 = results[0]
    shared_1, obj_1 = results[1]
    # both processes agree on the replicated shared response
    assert np.allclose(shared_0, shared_1, atol=1e-10)
    assert np.isclose(obj_0, obj_1)

    # and the distributed result matches a local single-process fit
    import jax
    import jax.numpy as jnp

    from brainiak_tpu.funcalign.srm import _fit_det_srm_jit

    rng = np.random.RandomState(0)
    n_subjects, voxels, samples, features = 4, 12, 16, 3
    S = rng.randn(features, samples)
    data = np.zeros((n_subjects, voxels, samples))
    for i in range(n_subjects):
        q, _ = np.linalg.qr(rng.randn(voxels, features))
        data[i] = q @ S + 0.01 * rng.randn(voxels, samples)
    w, shared, objective = _fit_det_srm_jit(
        jnp.asarray(data), jnp.full((n_subjects,), voxels),
        jax.random.PRNGKey(0), features=features, n_iter=5)
    atol = mesh_atol()
    assert np.allclose(np.asarray(shared), shared_0, atol=atol)


def test_distributed_fast_failure_reporting():
    """A worker that dies immediately is reported promptly with its real
    traceback, not a timeout."""
    import time

    t0 = time.time()
    with pytest.raises(RuntimeError, match="intentional worker failure"):
        run_distributed("tests.parallel.dist_workers", "failing_worker",
                        n_procs=2, local_devices=1, timeout=180,
                        extra_path=REPO_ROOT)
    assert time.time() - t0 < 60  # far less than the 180s timeout


def test_distributed_voxelselector_matches_single_process():
    """The sharded FCMA engine produces identical voxel rankings and
    accuracies across process boundaries (VERDICT r3 item 7 — the
    analog of the reference's mpiexec-marked FCMA tests)."""
    results = run_distributed("tests.parallel.dist_workers",
                              "voxelselector_worker",
                              n_procs=2, local_devices=2, x64=_x64(),
                              extra_path=REPO_ROOT)
    # both processes return the full gathered ranking and agree exactly
    assert results[0] == results[1]

    single = _single_process_voxelselector()
    dist = dict(results[0])
    assert set(dist) == set(single)
    for v, acc in single.items():
        assert abs(dist[v] - acc) <= 0.51 / 8, (v, dist[v], acc)


def _single_process_voxelselector():
    from brainiak_tpu.fcma.voxelselector import VoxelSelector
    from tests.parallel.dist_workers import make_fcma_data

    raw, labels, epochs_per_subj = make_fcma_data()
    vs = VoxelSelector(labels, epochs_per_subj, 2, raw,
                       voxel_unit=8, use_pallas=False)
    return dict(vs.run('svm'))


def test_distributed_bootstrap_isc_matches_single_process():
    results = run_distributed("tests.parallel.dist_workers",
                              "bootstrap_isc_worker",
                              n_procs=2, local_devices=2, x64=_x64(),
                              extra_path=REPO_ROOT)
    iscs_d, observed_d, p_d, dist_d = results[0]
    iscs_d1, observed_d1, p_d1, dist_d1 = results[1]
    np.testing.assert_array_equal(iscs_d, iscs_d1)
    np.testing.assert_array_equal(dist_d, dist_d1)

    from brainiak_tpu.isc import bootstrap_isc, isc
    from tests.parallel.dist_workers import make_isc_data

    ts = make_isc_data()
    iscs = isc(ts)
    observed, ci, p, distribution = bootstrap_isc(
        iscs, n_bootstraps=12, null_batch_size=4, random_state=0)
    atol = mesh_atol()
    np.testing.assert_allclose(iscs_d, np.asarray(iscs), atol=atol)
    np.testing.assert_allclose(observed_d, np.asarray(observed),
                               atol=atol)
    np.testing.assert_allclose(dist_d, np.asarray(distribution),
                               atol=atol)
    np.testing.assert_allclose(p_d, np.asarray(p), atol=atol)


def test_distributed_htfa_matches_single_process():
    results = run_distributed("tests.parallel.dist_workers",
                              "htfa_worker",
                              n_procs=2, local_devices=2, x64=_x64(),
                              timeout=480, extra_path=REPO_ROOT)
    np.testing.assert_allclose(results[0], results[1], atol=1e-12)

    from brainiak_tpu.factoranalysis.htfa import HTFA
    from tests.parallel.dist_workers import (HTFA_PARAMS,
                                             make_htfa_data)

    X, R_coords, n_subj = make_htfa_data()
    htfa = HTFA(n_subj=n_subj, **HTFA_PARAMS)
    htfa.fit(X, [R_coords] * n_subj)
    # distributed optimization follows the same trajectory up to
    # cross-shard reduction-order noise amplified by L-BFGS steps
    np.testing.assert_allclose(results[0],
                               np.asarray(htfa.global_posterior_),
                               atol=1e-3)


def test_distributed_isfc_ring_matches_single_process():
    """The ppermute ring computes V x V leave-one-out ISFC with voxels
    sharded around a ring that crosses process boundaries; results
    must match the replicated single-process einsum path."""
    results = run_distributed("tests.parallel.dist_workers",
                              "isfc_ring_worker",
                              n_procs=2, local_devices=2, x64=_x64(),
                              extra_path=REPO_ROOT)
    isfcs_0, iscs_0 = results[0]
    isfcs_1, iscs_1 = results[1]
    np.testing.assert_array_equal(isfcs_0, isfcs_1)
    np.testing.assert_array_equal(iscs_0, iscs_1)

    from brainiak_tpu.isc import isfc
    from tests.parallel.dist_workers import make_isfc_data

    isfcs_s, iscs_s = isfc(make_isfc_data(), vectorize_isfcs=True)
    atol = mesh_atol()
    np.testing.assert_allclose(isfcs_0, np.asarray(isfcs_s), atol=atol)
    np.testing.assert_allclose(iscs_0, np.asarray(iscs_s), atol=atol)


def test_distributed_searchlight_matches_single_process():
    results = run_distributed("tests.parallel.dist_workers",
                              "searchlight_worker",
                              n_procs=2, local_devices=2, x64=_x64(),
                              extra_path=REPO_ROOT)
    np.testing.assert_array_equal(results[0], results[1])

    import jax.numpy as jnp

    from brainiak_tpu.searchlight.searchlight import Searchlight
    from tests.parallel.dist_workers import make_searchlight_data

    data, mask = make_searchlight_data()
    sl = Searchlight(sl_rad=1)
    sl.distribute(data, mask)

    def voxel_fn(patches, mask_patch, rad, bcast):
        return jnp.mean(patches * mask_patch[None, :, None])

    vol = np.asarray(sl.run_searchlight_jax(voxel_fn, batch_size=64),
                     dtype=float)
    np.testing.assert_allclose(results[0], vol, atol=mesh_atol())


def test_distributed_srm_class_api_matches_single_process():
    """The public SRM estimator (not just the jitted core) works under
    a 2-process mesh: subject-sharded w_/rho2_ are gathered so every
    process holds the full model."""
    results = run_distributed("tests.parallel.dist_workers",
                              "srm_class_worker",
                              n_procs=2, local_devices=2, x64=_x64(),
                              extra_path=REPO_ROOT)
    w_d, s_d, rho2_d = results[0]
    w_d1, s_d1, rho2_d1 = results[1]
    for a, b in zip(w_d, w_d1):
        np.testing.assert_array_equal(a, b)
    np.testing.assert_array_equal(rho2_d, rho2_d1)

    from brainiak_tpu.funcalign.srm import SRM
    from tests.parallel.dist_workers import make_srm_class_data

    srm = SRM(n_iter=5, features=3, rand_seed=0)
    srm.fit(make_srm_class_data())
    atol = mesh_atol()
    for a, b in zip(w_d, srm.w_):
        np.testing.assert_allclose(a, b, atol=atol)
    np.testing.assert_allclose(s_d, srm.s_, atol=atol)
    np.testing.assert_allclose(rho2_d, srm.rho2_, atol=atol)


def test_distributed_gbrsa_matches_single_process():
    results = run_distributed("tests.parallel.dist_workers",
                              "gbrsa_worker",
                              n_procs=2, local_devices=2, x64=_x64(),
                              timeout=480, extra_path=REPO_ROOT)
    u_0, snr_0 = results[0]
    u_1, snr_1 = results[1]
    np.testing.assert_array_equal(u_0, u_1)
    np.testing.assert_array_equal(snr_0, snr_1)

    from brainiak_tpu.reprsimil.brsa import GBRSA
    from tests.parallel.dist_workers import make_gbrsa_data

    data, design, onsets = make_gbrsa_data()
    gb = GBRSA(SNR_bins=3, rho_bins=3, lbfgs_iters=15,
               auto_nuisance=False, random_state=0)
    gb.fit([data], [design], scan_onsets=onsets)
    # cross-shard reduction-order noise is amplified through L-BFGS
    # steps, so the bound is looser than the elementwise engines'
    np.testing.assert_allclose(u_0, np.asarray(gb.U_), atol=1e-3)
    np.testing.assert_allclose(snr_0, np.asarray(gb.nSNR_), atol=1e-3)
