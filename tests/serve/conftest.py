"""Serve test isolation: obs sinks/metrics reset around every test
(the engine's retrace/latency metrics and the program caches are
process-global), plus shared fitted-model fixtures — the estimator
fits dominate this directory's runtime, so they are session-scoped.
"""

import numpy as np
import pytest

from brainiak_tpu.obs import metrics, sink


@pytest.fixture(autouse=True)
def _clean_obs(monkeypatch):
    monkeypatch.delenv(sink.OBS_DIR_ENV, raising=False)
    monkeypatch.delenv(sink.OBS_RANK_ENV, raising=False)
    # AOTProgramCache would otherwise point jax's PROCESS-GLOBAL
    # persistent compilation cache at soon-deleted tmp dirs; the
    # subprocess tests (CLI, SRV002 gate) cover that layer for real
    monkeypatch.setenv("BRAINIAK_TPU_SERVE_XLA_CACHE", "0")
    sink.close_all()
    metrics.reset()
    yield
    sink.close_all()
    metrics.reset()


def make_srm_data(n_subjects=3, voxels=20, samples=30, features=4,
                  seed=0, ragged=True):
    rng = np.random.RandomState(seed)
    shared = rng.randn(features, samples)
    data = []
    for i in range(n_subjects):
        v = voxels + (i if ragged else 0)
        q, _ = np.linalg.qr(rng.randn(v, features))
        data.append(q @ shared + 0.1 * rng.randn(v, samples))
    return data


@pytest.fixture(scope="session")
def srm_model():
    from brainiak_tpu.funcalign.srm import SRM
    model = SRM(n_iter=3, features=4, rand_seed=0)
    model.fit(make_srm_data())
    return model


@pytest.fixture(scope="session")
def detsrm_model():
    from brainiak_tpu.funcalign.srm import DetSRM
    model = DetSRM(n_iter=3, features=4, rand_seed=0)
    model.fit(make_srm_data())
    return model


@pytest.fixture(scope="session")
def rsrm_model():
    from brainiak_tpu.funcalign.rsrm import RSRM
    model = RSRM(n_iter=3, features=4, gamma=1.0, rand_seed=0)
    model.fit(make_srm_data(ragged=False))
    return model


@pytest.fixture(scope="session")
def eventseg_model():
    from brainiak_tpu.eventseg.event import EventSegment
    rng = np.random.RandomState(0)
    # blocky event structure: [T, V] with 3 mean-shifted segments
    means = rng.randn(3, 10)
    data = np.vstack([means[i] + 0.2 * rng.randn(12, 10)
                      for i in range(3)])
    model = EventSegment(n_events=3, n_iter=30)
    model.fit(data)
    return model


@pytest.fixture(scope="session")
def iem1d_model():
    from brainiak_tpu.reconstruct.iem import InvertedEncoding1D
    rng = np.random.RandomState(0)
    model = InvertedEncoding1D(n_channels=6, channel_exp=5)
    feats = rng.uniform(0, 179, size=40)
    channels, centers = model._define_channels()
    model.channels_ = channels
    design = model._define_trial_activations(feats)
    voxels = 12
    w_true = rng.randn(6, voxels)
    X = design @ w_true + 0.05 * rng.randn(40, voxels)
    model.fit(X, feats)
    return model


@pytest.fixture(scope="session")
def fcma_models():
    """(full-features LogisticRegression model, single-portion
    precomputed-SVM model) plus held-out epoch pairs."""
    import math

    from scipy.stats.mstats import zscore
    from sklearn import svm
    from sklearn.linear_model import LogisticRegression

    from brainiak_tpu.fcma.classifier import Classifier

    rng = np.random.RandomState(42)

    def epoch(idx, num_voxels=5, row=12):
        mat = rng.rand(row, num_voxels).astype(np.float32)
        if idx % 2 == 0:
            mat = np.sort(mat, axis=0)
        mat = np.nan_to_num(zscore(mat, axis=0, ddof=0))
        return mat / math.sqrt(mat.shape[0])

    epochs = [epoch(i) for i in range(20)]
    labels = [0, 1] * 6
    train = list(zip(epochs[:12], epochs[:12]))
    test = list(zip(epochs[12:], epochs[12:]))

    logit = Classifier(LogisticRegression(solver="liblinear"),
                       epochs_per_subj=4)
    logit.fit(train, labels)

    precomp = Classifier(
        svm.SVC(kernel="precomputed", shrinking=False, C=1,
                gamma="auto"), epochs_per_subj=4)
    precomp.fit(train, labels)
    return logit, precomp, test


@pytest.fixture(scope="session")
def encoding_model():
    from brainiak_tpu.encoding import RidgeEncoder
    rng = np.random.RandomState(0)
    t, f, v = 60, 8, 16
    x = rng.randn(t, f).astype(np.float32)
    w = rng.randn(f, v).astype(np.float32)
    y = (x @ w + 0.5 * rng.randn(t, v)).astype(np.float32)
    return RidgeEncoder(lambdas=(1.0, 10.0, 100.0),
                        n_folds=3).fit(x, y)


@pytest.fixture(scope="session")
def banded_encoding_model():
    from brainiak_tpu.encoding import BandedRidgeEncoder
    rng = np.random.RandomState(1)
    t, f, v = 60, 8, 16
    x = rng.randn(t, f).astype(np.float32)
    w = rng.randn(f, v).astype(np.float32)
    y = (x @ w + 0.5 * rng.randn(t, v)).astype(np.float32)
    return BandedRidgeEncoder(np.repeat(np.arange(2), 4),
                              lambdas=(1.0, 100.0), n_folds=3,
                              candidate_block=2,
                              standardize=True).fit(x, y)
