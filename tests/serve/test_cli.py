"""``python -m brainiak_tpu.serve`` CLI: run + service + bench
subcommands (the SRV001/SRV002 gates' contracts) and the offline
results file."""

import json
import os
import subprocess
import sys

import numpy as np

from tests.conftest import REPO_ROOT

SUMMARY_KEYS = ("n_requests", "n_ok", "n_errors", "buckets",
                "retrace_total", "padding_waste",
                "requests_per_sec")


def _cli(*args):
    return subprocess.run(
        [sys.executable, "-m", "brainiak_tpu.serve", *args],
        capture_output=True, text=True, cwd=REPO_ROOT,
        env=dict(os.environ, JAX_PLATFORMS="cpu"))


def _fixture_paths(tmp_path, poison=False):
    from brainiak_tpu.serve import save_model, save_requests
    from brainiak_tpu.serve.__main__ import (build_demo_model,
                                             build_mixed_requests)
    model_path = str(tmp_path / "model.npz")
    req_path = str(tmp_path / "requests.npz")
    model = build_demo_model(n_subjects=3, voxels=10, samples=20,
                             features=3, n_iter=2, seed=1)
    save_model(model, model_path)
    reqs = build_mixed_requests(model, 6, seed=1,
                                tr_choices=(5, 9))
    payloads = [r.x for r in reqs]
    subjects = [r.subject for r in reqs]
    if poison:
        bad = np.full_like(payloads[0], np.nan)
        payloads.append(bad)
        subjects.append(0)
    save_requests(req_path, payloads, subjects=subjects)
    return model_path, req_path


def test_cli_run_json_summary(tmp_path):
    model_path, req_path = _fixture_paths(tmp_path)
    out_path = str(tmp_path / "results.npz")
    proc = _cli("run", "--model", model_path,
                "--requests", req_path, "--out", out_path,
                "--format=json")
    assert proc.returncode == 0, proc.stderr
    summary = json.loads(proc.stdout)
    for key in SUMMARY_KEYS:
        assert key in summary, key
    assert summary["n_errors"] == 0
    assert summary["n_ok"] == summary["n_requests"] == 6
    assert summary["retrace_total"] <= len(summary["buckets"])
    with np.load(out_path) as z:
        assert int(z["n"]) == 6
        assert z["result.0"].ndim == 2


def test_cli_run_poison_exits_nonzero(tmp_path):
    model_path, req_path = _fixture_paths(tmp_path, poison=True)
    proc = _cli("run", "--model", model_path,
                "--requests", req_path, "--format=json")
    assert proc.returncode == 1
    summary = json.loads(proc.stdout)
    assert summary["n_errors"] == 1
    assert summary["errors_by_code"] == {"non_finite_input": 1}
    # still one record per request
    assert summary["n_ok"] + summary["n_errors"] == \
        summary["n_requests"]


def test_cli_bench_emits_valid_bench_record(tmp_path):
    from brainiak_tpu.obs import validate_bench_record
    proc = _cli("bench", "--n-requests", "12",
                "--save-model", str(tmp_path / "demo.npz"))
    assert proc.returncode == 0, proc.stderr
    record = json.loads(proc.stdout.strip().splitlines()[-1])
    assert validate_bench_record(record) == []
    # CPU test backend -> the cpu_fallback serve tier
    assert record["tier"] == "serve_cpu_fallback"
    assert record["unit"] == "requests/sec"
    assert record["value"] > 0
    assert (tmp_path / "demo.npz").exists()


def test_cli_run_text_format(tmp_path):
    model_path, req_path = _fixture_paths(tmp_path)
    proc = _cli("run", "--model", model_path,
                "--requests", req_path)
    assert proc.returncode == 0, proc.stderr
    assert "6/6 ok" in proc.stdout


def test_cli_bench_rejects_unsupported_kind_naming_kinds(tmp_path):
    """`serve bench` with an artifact it has no request generator
    for fails rc=2 with an error that ENUMERATES the supported
    kinds (ISSUE 7 satellite), instead of a bare driver error."""
    from brainiak_tpu.eventseg.event import EventSegment
    from brainiak_tpu.serve import save_model

    model = EventSegment(n_events=2)
    model.event_pat_ = np.random.RandomState(0).randn(6, 2)
    model.event_var_ = 1.0
    path = str(tmp_path / "eventseg.npz")
    save_model(model, path)
    proc = _cli("bench", "--model", path, "--n-requests", "4")
    assert proc.returncode == 2
    for kind in ("srm", "detsrm", "rsrm", "ridge_encoding"):
        assert kind in proc.stderr
    assert "eventseg" in proc.stderr


def _two_model_request_file(tmp_path):
    """Two tiny artifacts + a request file whose model.<i> keys
    route between them (second half unrouted -> default model)."""
    from brainiak_tpu.serve import save_model, save_requests
    from brainiak_tpu.serve.__main__ import (build_demo_model,
                                             build_mixed_requests)
    a = build_demo_model(n_subjects=2, voxels=10, samples=20,
                         features=3, n_iter=2, seed=1)
    b = build_demo_model(n_subjects=2, voxels=14, samples=20,
                         features=3, n_iter=2, seed=2)
    a_path = str(tmp_path / "a.npz")
    b_path = str(tmp_path / "b.npz")
    save_model(a, a_path)
    save_model(b, b_path)
    reqs = (build_mixed_requests(a, 4, seed=1, tr_choices=(5, 9))
            + build_mixed_requests(b, 4, seed=2,
                                   tr_choices=(5, 9)))
    req_path = str(tmp_path / "requests.npz")
    save_requests(req_path, [r.x for r in reqs],
                  subjects=[r.subject for r in reqs],
                  models=["a", "a", None, None, "b", "b", "b", "b"])
    return a_path, b_path, req_path


def test_cli_service_multi_model_summary(tmp_path):
    """ISSUE 9 satellite: the `service` subcommand serves a routed
    multi-model request file and prints the JSON summary with the
    p50/p99 / padding / eviction / aot blocks."""
    a_path, b_path, req_path = _two_model_request_file(tmp_path)
    proc = _cli("service", "--model", f"a={a_path}",
                "--model", f"b={b_path}",
                "--requests", req_path, "--waves", "2")
    assert proc.returncode == 0, proc.stderr
    summary = json.loads(proc.stdout)
    assert summary["n_submitted"] == 8
    assert summary["n_ok"] == 8 and summary["n_errors"] == 0
    assert summary["p50_latency_s"] > 0
    assert summary["p99_latency_s"] >= summary["p50_latency_s"]
    assert 0.0 <= summary["padding_waste"] < 1.0
    assert summary["residency"]["evictions"] == 0
    assert set(summary["models"]) == {"a", "b"}
    # both routed halves landed on their named model
    assert summary["models"]["a"]["n_requests"] == 4
    assert summary["models"]["b"]["n_requests"] == 4
    assert "aot" not in summary   # no --aot-cache given
    assert summary["requests_per_sec"] > 0


def test_cli_service_aot_restart_zero_retraces(tmp_path):
    """The SRV002 contract end to end: a second CLI process over
    the same AOT cache reports aot hits and ZERO serve retraces."""
    a_path, b_path, req_path = _two_model_request_file(tmp_path)
    cache = str(tmp_path / "aot")
    args = ("service", "--model", f"a={a_path}",
            "--model", f"b={b_path}",
            "--requests", req_path, "--aot-cache", cache,
            "--waves", "1")
    first = _cli(*args)
    assert first.returncode == 0, first.stderr
    cold = json.loads(first.stdout)
    assert cold["aot"]["stores"] > 0
    second = _cli(*args)
    assert second.returncode == 0, second.stderr
    warm = json.loads(second.stdout)
    assert warm["n_errors"] == 0
    assert warm["aot"]["hits"] > 0
    assert warm["retrace_total"] == 0


def test_cli_service_no_drain_and_text_format(tmp_path):
    """--no-drain + --duration 0 fails queued work with `shutdown`
    records (rc=1) and the text renderer reports them."""
    a_path, _, req_path = _two_model_request_file(tmp_path)
    proc = _cli("service", "--model", f"a={a_path}",
                "--requests", req_path, "--no-drain",
                "--duration", "0.001", "--max-wait", "30",
                "--format=text")
    assert proc.returncode == 1, proc.stderr
    assert "shutdown" in proc.stdout


def test_cli_service_bad_model_spec_is_driver_error(tmp_path):
    a_path, _, req_path = _two_model_request_file(tmp_path)
    proc = _cli("service", "--model", f"a={a_path}",
                "--model", f"a={a_path}",
                "--requests", req_path)
    assert proc.returncode == 2
    assert "duplicate model name" in proc.stderr


def test_cli_bench_encoding_artifact_emits_valid_record(tmp_path,
                                                        capsys):
    """`serve bench` covers the new encoding read path: a
    ridge_encoding artifact drives the scoring generator and emits
    a schema-valid bench record (in-process `main` call — the
    subprocess surface is covered by the other CLI tests)."""
    from brainiak_tpu.obs import validate_bench_record
    from brainiak_tpu.serve import save_model
    from brainiak_tpu.serve.__main__ import build_encoding_model, main

    path = str(tmp_path / "enc.npz")
    save_model(build_encoding_model(voxels=24, features=6,
                                    samples=40), path)
    assert main(["bench", "--model", path, "--n-requests", "8"]) == 0
    record = json.loads(
        capsys.readouterr().out.strip().splitlines()[-1])
    assert validate_bench_record(record) == []
    assert record["metric"] == \
        "serve_ridge_encoding_score_requests_per_sec"
    assert record["tier"] in ("serve", "serve_cpu_fallback")
