"""AOT program persistence: the zero cold-start layer.

Contract under test (ISSUE 9 tentpole part 3): programs the engine
builds are exported + persisted; a fresh "process" (program caches
cleared, metrics reset) over the warm cache serves bit-identically
with ``retrace_total{site=serve.*} == 0``; every failure mode is a
counted miss that falls back to jit, never a lost answer.
"""

import glob
import os

import numpy as np
import pytest

from brainiak_tpu.obs import metrics
from brainiak_tpu.serve import aot as aot_mod
from brainiak_tpu.serve import engine as engine_mod
from brainiak_tpu.serve.aot import AOTProgramCache
from brainiak_tpu.serve.artifacts import model_digest
from brainiak_tpu.serve.batching import Request
from brainiak_tpu.serve.engine import InferenceEngine


def _requests(model, n, seed=0, tr_choices=(6, 20)):
    rng = np.random.RandomState(seed)
    counts = [w.shape[0] for w in model.w_]
    return [Request(request_id=f"r{i}",
                    x=rng.randn(counts[i % len(counts)],
                                tr_choices[i % len(tr_choices)])
                    .astype(np.float32),
                    subject=i % len(counts))
            for i in range(n)]


def _fresh_process():
    """Simulate a restart: module-level jit builder caches cleared,
    retrace counters reset (each engine's AOT lookups and the
    process-global serve program caches start cold)."""
    for builder in (engine_mod._srm_program,
                    engine_mod._rsrm_program,
                    engine_mod._eventseg_program,
                    engine_mod._encoding_program,
                    engine_mod._iem_program):
        builder.cache_clear()
    metrics.reset()


def serve_retraces(site="serve.srm"):
    return metrics.counter("retrace_total").value(site=site)


def test_restart_zero_compile_and_bit_parity(srm_model, tmp_path):
    """The tentpole acceptance (in-process form; the SRV002 gate
    proves the true-subprocess version): a warm AOT cache serves a
    fresh process's first requests with zero serve retraces and
    bit-identical results."""
    reqs = _requests(srm_model, 8)
    cache = AOTProgramCache(tmp_path)
    cold = InferenceEngine(srm_model, aot=cache)
    cold_recs = cold.run(reqs)
    assert all(r.ok for r in cold_recs)
    assert cold.summary()["retrace_total"] > 0      # cold compiles
    assert cache.stats()["stores"] == \
        len(cold.summary()["buckets"])
    assert sorted(glob.glob(os.path.join(tmp_path, "*.jaxprog")))

    _fresh_process()
    warm_cache = AOTProgramCache(tmp_path)
    warm = InferenceEngine(srm_model, aot=warm_cache)
    for req in reqs:
        req.submitted = None
    warm_recs = warm.run(reqs)
    assert all(r.ok for r in warm_recs)
    assert serve_retraces() == 0                     # no compiles
    assert warm_cache.stats()["hits"] == \
        len(warm.summary()["buckets"])
    for a, b in zip(cold_recs, warm_recs):
        np.testing.assert_array_equal(np.asarray(a.result),
                                      np.asarray(b.result))


def test_corrupt_entry_falls_back_to_jit(srm_model, tmp_path):
    reqs = _requests(srm_model, 4, tr_choices=(6,))
    cache = AOTProgramCache(tmp_path)
    InferenceEngine(srm_model, aot=cache).run(reqs)
    for path in glob.glob(os.path.join(tmp_path, "*.jaxprog")):
        with open(path, "wb") as fh:
            fh.write(b"not a serialized program")

    _fresh_process()
    cache2 = AOTProgramCache(tmp_path)
    engine = InferenceEngine(srm_model, aot=cache2)
    for req in reqs:
        req.submitted = None
    records = engine.run(reqs)
    assert all(r.ok for r in records)                # served anyway
    assert cache2.stats()["misses"] == {"deserialize_failed": 1}
    assert metrics.counter("serve_aot_miss_total").value(
        site="serve.srm", reason="deserialize_failed") == 1
    assert serve_retraces() == 1                     # jit fallback


def test_unsupported_jax_is_a_counted_miss(srm_model, tmp_path,
                                           monkeypatch):
    monkeypatch.setattr(aot_mod, "_export", None)
    cache = AOTProgramCache(tmp_path)
    engine = InferenceEngine(srm_model, aot=cache)
    records = engine.run(_requests(srm_model, 2, tr_choices=(6,)))
    assert all(r.ok for r in records)
    assert cache.stats()["hits"] == 0
    assert cache.stats()["misses"] == {"unsupported": 1}
    assert not glob.glob(os.path.join(tmp_path, "*.jaxprog"))


def test_key_covers_digest_args_and_environment(srm_model,
                                                detsrm_model,
                                                tmp_path):
    cache = AOTProgramCache(tmp_path)
    d1 = model_digest(srm_model)
    d2 = model_digest(detsrm_model)
    assert d1 != d2
    base = cache.key_for(d1, "serve.srm", (3, 14, 4, 16, 4))
    assert cache.key_for(d1, "serve.srm",
                         (3, 14, 4, 16, 4)) == base
    assert cache.key_for(d2, "serve.srm",
                         (3, 14, 4, 16, 4)) != base
    assert cache.key_for(d1, "serve.rsrm",
                         (3, 14, 4, 16, 4)) != base
    assert cache.key_for(d1, "serve.srm",
                         (3, 14, 4, 32, 4)) != base


def test_digest_survives_save_load_round_trip(srm_model, tmp_path):
    from brainiak_tpu.serve import load_model, save_model
    path = save_model(srm_model, str(tmp_path / "m.npz"))
    assert model_digest(load_model(path)) == model_digest(srm_model)


def test_store_failure_never_breaks_serving(srm_model, tmp_path,
                                            monkeypatch):
    def boom(path, blob):
        raise OSError("disk on fire")

    monkeypatch.setattr(aot_mod, "_atomic_write", boom)
    cache = AOTProgramCache(tmp_path)
    engine = InferenceEngine(srm_model, aot=cache)
    records = engine.run(_requests(srm_model, 2, tr_choices=(6,)))
    assert all(r.ok for r in records)
    assert cache.stats()["stores"] == 0


def test_put_is_idempotent(srm_model, tmp_path):
    reqs = _requests(srm_model, 2, tr_choices=(6,))
    cache = AOTProgramCache(tmp_path)
    InferenceEngine(srm_model, aot=cache).run(reqs)
    files = sorted(glob.glob(os.path.join(tmp_path, "*.jaxprog")))
    eng = InferenceEngine(srm_model, aot=cache)
    for req in reqs:
        req.submitted = None
    eng.run(reqs)
    assert sorted(glob.glob(
        os.path.join(tmp_path, "*.jaxprog"))) == files


def test_fcma_kind_bypasses_aot(fcma_models, tmp_path):
    logit, _, _ = fcma_models
    engine = InferenceEngine(logit, aot=AOTProgramCache(tmp_path))
    assert engine.aot is None


def test_xla_persistent_cache_opt_in(monkeypatch, srm_model,
                                     tmp_path):
    """With the env opt-out lifted, the cache points jax's
    persistent compilation cache at <dir>/xla so even the XLA
    executable survives a restart; the config is restored after."""
    import glob as _glob

    import jax

    monkeypatch.setenv(aot_mod.XLA_CACHE_ENV, "1")
    prev = jax.config.jax_compilation_cache_dir
    try:
        cache = AOTProgramCache(tmp_path)
        assert cache.xla_cache_dir == str(tmp_path / "xla")
        assert jax.config.jax_compilation_cache_dir == \
            cache.xla_cache_dir
        # a process-novel program (the odd shape + constant make
        # the jit cache miss for sure): its XLA compile must land
        # in the persistent cache directory
        fn = jax.jit(lambda x: x * 3.14159 + 2.71828)
        np.asarray(fn(np.arange(17.0, dtype=np.float32)))
        assert _glob.glob(os.path.join(cache.xla_cache_dir, "*"))
    finally:
        jax.config.update("jax_compilation_cache_dir", prev)
    # and the opt-out leaves jax config untouched
    monkeypatch.setenv(aot_mod.XLA_CACHE_ENV, "0")
    assert AOTProgramCache(tmp_path / "b").xla_cache_dir is None


@pytest.mark.parametrize("bad", ["", "0"])
def test_env_tag_changes_key(monkeypatch, bad, srm_model, tmp_path):
    """jax version/platform ride in the key: faking a different
    version makes every prior entry unreachable (absent miss)."""
    cache = AOTProgramCache(tmp_path)
    digest = model_digest(srm_model)
    key = cache.key_for(digest, "serve.srm", (1,))
    monkeypatch.setattr(aot_mod, "_environment_tag",
                        lambda: f"fake-{bad}|cpu")
    assert cache.key_for(digest, "serve.srm", (1,)) != key
