"""Pod-scale serving federation (ISSUE 14 tentpole): sharded-model
serving with per-device residency accounting, the multi-replica
router, load-shedding admission control, and the fmrisim traffic
generator.  The conftest forces 8 CPU devices, so the mesh paths
run multi-device in-process; the SRV003 gate adds true-subprocess
replica coverage."""

import time

import numpy as np
import pytest

from brainiak_tpu.obs import metrics
from brainiak_tpu.parallel.mesh import make_mesh
from brainiak_tpu.serve.artifacts import (SHARDED_KINDS,
                                          model_nbytes,
                                          model_shard_nbytes)
from brainiak_tpu.serve.batching import BucketPolicy, Request
from brainiak_tpu.serve.engine import InferenceEngine
from brainiak_tpu.serve.federation import (AdmissionController,
                                           LocalReplica, Router,
                                           TrafficGenerator,
                                           replay,
                                           scrape_replica_state)
from brainiak_tpu.serve.residency import (AdmissionError,
                                          ModelResidency)
from brainiak_tpu.serve.service import ServeService


def _policy():
    return BucketPolicy(max_batch=8, max_wait_s=0.01)


def _mesh():
    import jax
    return make_mesh(("voxel",), (len(jax.devices()),))


def _srm_requests(model, n, seed=0, tr_choices=(6, 20), prefix="r"):
    rng = np.random.RandomState(seed)
    counts = [w.shape[0] for w in model.w_]
    return [Request(request_id=f"{prefix}{i}",
                    x=rng.randn(counts[i % len(counts)],
                                tr_choices[i % len(tr_choices)])
                    .astype(np.float32),
                    subject=i % len(counts))
            for i in range(n)]


# -- sharded-model serving (tentpole part a) --------------------------

def test_model_shard_nbytes_layout(srm_model, encoding_model,
                                   eventseg_model):
    """Per-shard layouts: shardable bytes ceil-divide, the rest
    replicates, and the split reconstructs the packed total."""
    for model in (srm_model, encoding_model):
        total = model_nbytes(model)
        per_shard, replicated = model_shard_nbytes(model, 4)
        assert 0 < per_shard < total
        assert 0 < replicated < total
        # ceil division: 4 shards cover all shardable bytes
        assert 4 * per_shard + replicated >= total
        one, rep_one = model_shard_nbytes(model, 1)
        assert one + rep_one == total
    with pytest.raises(ValueError, match="no sharded serve"):
        model_shard_nbytes(eventseg_model, 2)  # no sharded program


def test_sharded_engine_parity_srm(srm_model):
    """A voxel-sharded SRM engine over the 8-device mesh answers
    bit-close to the replicated engine and the host reference."""
    mesh = _mesh()
    reqs = _srm_requests(srm_model, 6)
    sharded = InferenceEngine(srm_model, mesh=mesh,
                              policy=_policy())
    assert sharded.op.site == "serve.srm_sharded"
    recs = sharded.run(reqs)
    assert all(r.ok for r in recs)
    for req, rec in zip(reqs, recs):
        want = np.asarray(srm_model.w_[req.subject]).T \
            @ np.asarray(req.x)
        np.testing.assert_allclose(np.asarray(rec.result), want,
                                   atol=1e-4)


def test_sharded_engine_parity_encoding(encoding_model):
    """Voxel-sharded encoding scoring matches the replicated
    program (voxel-local math, no collective)."""
    mesh = _mesh()
    rng = np.random.RandomState(0)
    f, v = encoding_model.W_.shape
    reqs = []
    for i in range(4):
        feats = rng.randn(12 + i, f).astype(np.float32)
        resp = (encoding_model.predict(feats)
                + 0.5 * rng.randn(12 + i, v)).astype(np.float32)
        reqs.append(Request(request_id=f"e{i}", x=(feats, resp)))
    recs_s = InferenceEngine(encoding_model, mesh=mesh,
                             policy=_policy()).run(reqs)
    for req in reqs:
        req.submitted = None
    recs_u = InferenceEngine(encoding_model,
                             policy=_policy()).run(reqs)
    assert all(r.ok for r in recs_s)
    for a, b in zip(recs_s, recs_u):
        np.testing.assert_allclose(np.asarray(a.result),
                                   np.asarray(b.result), atol=1e-5)


def test_sharded_kinds_guard(eventseg_model):
    """Kinds without a sharded program refuse a mesh with a clear
    error instead of serving wrong answers."""
    assert "eventseg" not in SHARDED_KINDS
    with pytest.raises(ValueError, match="no sharded serve"):
        InferenceEngine(eventseg_model, mesh=_mesh())


def test_residency_auto_shards_over_budget_model(srm_model):
    """The acceptance scenario: a model whose nbytes exceed one
    device's budget admits SHARDED over the mesh, serves with
    parity, and charges every mesh device within budget."""
    mesh = _mesh()
    n_dev = int(mesh.devices.size)
    nbytes = model_nbytes(srm_model)
    per_shard, replicated = model_shard_nbytes(srm_model, n_dev)
    budget = max(int(nbytes * 0.6), per_shard + replicated + 1)
    assert budget < nbytes  # genuinely over one device's budget
    res = ModelResidency(budget_bytes=budget, policy=_policy(),
                         mesh=mesh)
    res.register("big", source=None, model=srm_model)
    reqs = _srm_requests(srm_model, 4)
    with ServeService(res, default_model="big") as svc:
        recs = [t.result(timeout=60)
                for t in svc.submit_many(reqs)]
        stats = res.stats()
    assert all(r.ok for r in recs)
    want = np.asarray(srm_model.w_[reqs[0].subject]).T \
        @ np.asarray(reqs[0].x)
    np.testing.assert_allclose(np.asarray(recs[0].result), want,
                               atol=1e-4)
    # per-device accounting: every mesh device charged, all within
    # the per-device budget
    assert stats["sharded"] == ["big"]
    assert len(stats["per_device"]) == n_dev
    assert all(0 < b <= budget
               for b in stats["per_device"].values())


def test_residency_explicit_sharded_registration(srm_model):
    """register(sharded=True) shards even under an ample budget;
    sharded=True without a mesh is refused at registration."""
    res = ModelResidency(budget_bytes=1 << 30, policy=_policy(),
                         mesh=_mesh())
    res.register("m", model=srm_model, sharded=True)
    entry = res.acquire("m")
    assert entry.sharded
    assert len(entry.device_nbytes) == int(_mesh().devices.size)
    no_mesh = ModelResidency(budget_bytes=1 << 30)
    with pytest.raises(ValueError, match="no mesh"):
        no_mesh.register("m", model=srm_model, sharded=True)


def test_per_device_placement_and_eviction(srm_model, detsrm_model,
                                           rsrm_model):
    """Unsharded models place least-loaded-first across device
    slots, and eviction victims come from the CONSTRAINED device
    (the survivor on the other device is untouched)."""
    sizes = {name: model_nbytes(m)
             for name, m in (("a", srm_model), ("b", detsrm_model),
                             ("c", rsrm_model))}
    budget = max(sizes.values()) + 16  # one model per device slot
    res = ModelResidency(budget_bytes=budget,
                         devices=["hbm0", "hbm1"])
    res.register("a", model=srm_model)
    res.register("b", model=detsrm_model)
    res.register("c", model=rsrm_model)
    res.acquire("a")
    res.acquire("b")
    per_dev = res.stats()["per_device"]
    # spread: one model per slot, no eviction yet
    assert sorted(per_dev.values()) == sorted(
        [sizes["a"], sizes["b"]])
    res.acquire("a")          # touch: "a" is now MRU
    res.acquire("c")          # must evict on ITS target device
    stats = res.stats()
    assert stats["evictions"] == 1
    assert "a" in stats["resident"] and "c" in stats["resident"]
    assert "b" not in stats["resident"]  # LRU on the target slot


def test_placement_avoids_pinned_full_device(srm_model):
    """An admissible model is never refused because the least-
    loaded device happens to be pinned-full: placement prefers a
    device where eviction CAN make room."""
    nbytes = model_nbytes(srm_model)
    res = ModelResidency(budget_bytes=nbytes + 16,
                         devices=["p0", "p1"])
    res.register("a", model=srm_model, pinned=True)
    res.register("b", model=srm_model)
    res.register("c", model=srm_model)
    res.acquire("a")              # pinned, lands p0
    res.acquire("b")              # lands p1
    res.acquire("c")              # must evict b on p1, NOT refuse
    stats = res.stats()
    assert sorted(stats["resident"]) == ["a", "c"]
    assert stats["evictions"] == 1


def test_admission_depth_excludes_ingress_gauge(srm_model):
    """The service's admission depth counts ingress LIVE and the
    engine-queue gauge only — a stale ingress gauge (which submit
    itself maintains at len(ingress)) must not double-count and
    halve the effective bound."""
    res = ModelResidency(budget_bytes=1 << 30, policy=_policy(),
                         devices=["hbm0"])
    res.register("m", model=srm_model)
    with ServeService(res, default_model="m", name="d1",
                      admission=AdmissionController(
                          max_depth=4)) as svc:
        # the state submit() leaves behind after 4 accepted
        # requests that the loop has not yet routed
        metrics.gauge("serve_service_ingress_depth").set(
            4, replica="d1")
        metrics.gauge("serve_service_queue_depth").set(
            3, model="m", replica="d1")
        assert svc.queued_depth() == 7          # router's view
        assert svc._engine_queue_depth() == 3   # admission's view
        # depth 3 (queue) + 1 staged < 4: the wave must admit
        # (double-counting the ingress gauge would shed it)
        rec = svc.submit_many(
            _srm_requests(srm_model, 1))[0].result(timeout=60)
    assert rec.ok


def test_budget_env_malformed_names_var(monkeypatch):
    """ISSUE 14 satellite: a malformed budget env var raises a
    clear error naming the variable and the value, not a bare
    int() ValueError."""
    from brainiak_tpu.serve.residency import (BUDGET_ENV,
                                              default_budget_bytes)
    monkeypatch.setenv(BUDGET_ENV, "8 gigabytes")
    with pytest.raises(ValueError) as excinfo:
        default_budget_bytes()
    msg = str(excinfo.value)
    assert BUDGET_ENV in msg
    assert "8 gigabytes" in msg
    monkeypatch.setenv(BUDGET_ENV, "1024")
    assert default_budget_bytes() == 1024


def test_oversized_unshardable_still_refuses(eventseg_model):
    """Per-device accounting keeps the typed refusal: an
    over-budget model with no sharded program (eventseg) refuses
    with AdmissionError even when a mesh is attached."""
    res = ModelResidency(
        budget_bytes=max(1, model_nbytes(eventseg_model) // 2),
        mesh=_mesh())
    res.register("ev", model=eventseg_model)
    with pytest.raises(AdmissionError):
        res.acquire("ev")


# -- admission control (tentpole part c) ------------------------------

def test_admission_controller_bounds_and_retry_growth():
    ctrl = AdmissionController(max_depth=4, retry_after_s=0.1)
    assert ctrl.evaluate(3) is None
    shed = ctrl.evaluate(4)
    assert shed is not None and shed.reason == "queue_full"
    assert shed.retry_after_s == pytest.approx(0.1)
    deeper = ctrl.evaluate(12)
    assert deeper.retry_after_s > shed.retry_after_s
    huge = ctrl.evaluate(10_000)
    assert huge.retry_after_s <= 0.1 * 8.0 + 1e-9  # clipped
    stats = ctrl.stats()
    assert stats["n_admitted"] == 1 and stats["n_shed"] == 3
    assert stats["shed_by_reason"] == {"queue_full": 3}


def test_admission_controller_slo_brownout():
    """A violating SLO tracker browns the bound out (requests shed
    earlier, reason slo_burn); recovery restores it.  The tracker
    poll is throttled by the injected clock."""

    class FakeTracker:
        def __init__(self):
            self.violating = False
            self.evaluations = 0

        def evaluate(self):
            self.evaluations += 1
            return {"objectives": {
                "p99": {"violating": self.violating}}}

    clock = [0.0]
    tracker = FakeTracker()
    ctrl = AdmissionController(max_depth=8, slo=tracker,
                               brownout_factor=0.5,
                               slo_poll_interval_s=1.0,
                               clock=lambda: clock[0])
    assert ctrl.evaluate(5) is None
    tracker.violating = True
    assert ctrl.evaluate(5) is None        # poll throttled: cached
    clock[0] = 2.0
    shed = ctrl.evaluate(5)                # bound now 4
    assert shed is not None and shed.reason == "slo_burn"
    assert shed.bound == 4
    tracker.violating = False
    clock[0] = 4.0
    assert ctrl.evaluate(5) is None        # recovered
    assert tracker.evaluations == 3        # throttle held


def test_service_shed_fires_before_dispatch(srm_model):
    """ISSUE 14 satellite (bounded ingress): a wave over the bound
    sheds its tail BEFORE enqueue — typed records with retry_after,
    every request resolves exactly one ticket, and the engine never
    saw the shed requests."""
    res = ModelResidency(budget_bytes=1 << 30, policy=_policy(),
                         devices=["hbm0"])
    res.register("m", model=srm_model)
    reqs = _srm_requests(srm_model, 10)
    with ServeService(res, default_model="m",
                      admission=AdmissionController(
                          max_depth=4, retry_after_s=0.02)) as svc:
        tickets = svc.submit_many(reqs)
        records = [t.result(timeout=60) for t in tickets]
        summary = svc.summary()
    assert len(records) == 10           # one ticket each, all kept
    sheds = [r for r in records if r.error == "shed_overload"]
    served = [r for r in records if r.ok]
    assert len(served) == 4             # the admitted head
    assert len(sheds) == 6              # the deterministic tail
    assert all(r.retry_after_s and r.retry_after_s > 0
               for r in sheds)
    assert all("retry after" in r.message for r in sheds)
    assert summary["n_shed"] == 6
    assert summary["n_submitted"] == 4  # sheds never enqueued
    assert summary["admission"]["n_shed"] == 6
    # the engine only ever dispatched the admitted 4
    assert summary["models"]["m"]["n_requests"] == 4
    assert metrics.counter("serve_shed_total").value(
        reason="queue_full") == 6


def test_service_shed_all_when_bound_zero(srm_model):
    """max_depth=0 sheds every submit() — the engine is never
    touched, and single submits resolve instantly too."""
    res = ModelResidency(budget_bytes=1 << 30, policy=_policy(),
                         devices=["hbm0"])
    res.register("m", model=srm_model)
    with ServeService(res, default_model="m",
                      admission=AdmissionController(
                          max_depth=0)) as svc:
        recs = [svc.submit(r).result(timeout=10)
                for r in _srm_requests(srm_model, 3)]
        summary = svc.summary()
    assert [r.error for r in recs] == ["shed_overload"] * 3
    assert summary["n_delivered"] == 0
    assert summary["models"] == {}      # nothing ever admitted


# -- the router (tentpole part b) -------------------------------------

def _replica(name, models, policy=None):
    res = ModelResidency(budget_bytes=1 << 30,
                         policy=policy or _policy(),
                         devices=["hbm0"])
    for model_name, model in models.items():
        res.register(model_name, model=model)
    return LocalReplica(ServeService(
        res, default_model=sorted(models)[0], name=name).start())


def test_router_requires_named_unique_replicas(srm_model):
    res = ModelResidency(budget_bytes=1 << 30, devices=["hbm0"])
    res.register("m", model=srm_model)
    svc = ServeService(res)  # unnamed
    with pytest.raises(ValueError, match="name"):
        LocalReplica(svc)
    r1 = _replica("dup", {"m": srm_model})
    r2 = _replica("dup", {"m": srm_model})
    try:
        with pytest.raises(ValueError, match="duplicate"):
            Router([r1, r2])
    finally:
        r1.service.shutdown()
        r2.service.shutdown()


def test_router_spreads_wave_by_depth(srm_model):
    """One atomic wave splits across equally-loaded replicas via
    the in-flight correction (no herding on stale gauges)."""
    r1 = _replica("r1", {"m": srm_model})
    r2 = _replica("r2", {"m": srm_model})
    router = Router([r1, r2])
    try:
        tickets = router.submit_many(
            _srm_requests(srm_model, 8), model="m")
        records = [t.result(timeout=60) for t in tickets]
    finally:
        r1.service.shutdown()
        r2.service.shutdown()
    assert all(r.ok for r in records)
    routed = router.summary()["routed"]
    assert routed == {"r1": 4, "r2": 4}


def test_router_places_by_registration_and_residency(
        srm_model, encoding_model):
    """Model-targeted placement: requests land only on replicas
    that REGISTER the model, preferring one where it is already
    RESIDENT."""
    r1 = _replica("r1", {"a": srm_model})
    r2 = _replica("r2", {"b": srm_model})
    router = Router([r1, r2])
    try:
        wave = _srm_requests(srm_model, 4, prefix="a")
        for req in wave:
            req.model = "a"
        wave2 = _srm_requests(srm_model, 4, prefix="b")
        for req in wave2:
            req.model = "b"
        records = [t.result(timeout=60)
                   for t in router.submit_many(wave + wave2)]
        assert all(r.ok for r in records)
        assert router.summary()["routed"] == {"r1": 4, "r2": 4}
        # residency preference: "a" resident ONLY on r1 now — an
        # untargeted placement over a shared registration would
        # pick it; here verify the pure decision surface
        assert router.place("a").name == "r1"
    finally:
        r1.service.shutdown()
        r2.service.shutdown()


def test_router_fleet_level_shed(srm_model):
    """The router sheds only when EVERY replica is at the bound:
    a 12-wave over 2 replicas with bound 2 admits 4, sheds 8 —
    all tickets resolved, shed records typed with retry_after."""
    r1 = _replica("s1", {"m": srm_model})
    r2 = _replica("s2", {"m": srm_model})
    router = Router([r1, r2],
                    admission=AdmissionController(
                        max_depth=2, retry_after_s=0.01))
    try:
        tickets = router.submit_many(
            _srm_requests(srm_model, 12), model="m")
        records = [t.result(timeout=60) for t in tickets]
    finally:
        r1.service.shutdown()
        r2.service.shutdown()
    assert len(records) == 12
    sheds = [r for r in records if r.error == "shed_overload"]
    assert len(sheds) == 8
    assert all(r.retry_after_s > 0 for r in sheds)
    assert sum(1 for r in records if r.ok) == 4
    summary = router.summary()
    assert summary["n_shed"] == 8
    assert summary["admission"]["n_shed"] == 8


def test_replica_gauges_are_labeled_and_scrapable(srm_model):
    """Named replicas publish replica-labeled gauges; the
    cross-process scraper reads the same series off /metrics."""
    res = ModelResidency(budget_bytes=1 << 30, policy=_policy(),
                         devices=["hbm0"])
    res.register("m", model=srm_model)
    with ServeService(res, default_model="m", name="rep1",
                      http_port=0) as svc:
        recs = [t.result(timeout=60) for t in svc.submit_many(
            _srm_requests(srm_model, 4))]
        assert all(r.ok for r in recs)
        port = svc.summary()["http_port"]
        state = scrape_replica_state(f"127.0.0.1:{port}")
    assert all(r.ok for r in recs)
    samples = metrics.gauge(
        "serve_service_queue_depth").samples()
    assert any(labels.get("replica") == "rep1"
               for labels, _ in samples)
    assert "rep1" in state["by_replica"]
    assert state["resident_bytes"] > 0
    assert state["queue_depth"] >= 0


# -- traffic generation (the soak surface) ----------------------------

def test_traffic_generator_deterministic_heavy_tail(srm_model):
    gen_a = TrafficGenerator(srm_model, model_name="m", seed=7)
    gen_b = TrafficGenerator(srm_model, model_name="m", seed=7)
    reqs_a = gen_a.requests(12)
    reqs_b = gen_b.requests(12)
    for a, b in zip(reqs_a, reqs_b):
        assert a.x.shape == b.x.shape
        np.testing.assert_array_equal(a.x, b.x)
    # heavy-tailed mix: more than one scan length, short dominates
    lengths = [r.x.shape[1] for r in reqs_a]
    assert len(set(lengths)) > 1
    assert sorted(lengths)[len(lengths) // 2] <= 64
    # payloads are valid SRM requests for their subject
    counts = [w.shape[0] for w in srm_model.w_]
    assert all(r.x.shape[0] == counts[r.subject] for r in reqs_a)
    with pytest.raises(ValueError, match="alpha"):
        TrafficGenerator(srm_model, alpha=1.0)


def test_traffic_schedule_rate_and_tail(srm_model):
    gen = TrafficGenerator(srm_model, model_name="m", seed=3)
    n, rps = 64, 500.0
    schedule = gen.schedule(n, target_rps=rps)
    arrivals = [t for t, _ in schedule]
    assert arrivals == sorted(arrivals)
    # rescaled so the schedule's mean rate IS the target
    assert arrivals[-1] == pytest.approx(n / rps)
    gaps = np.diff([0.0] + arrivals)
    # heavy tail: the max burst gap dwarfs the mean gap
    assert gaps.max() > 3.0 * gaps.mean()


def test_replay_drives_service_to_completion(srm_model):
    """A compressed heavy-tailed replay resolves every ticket ok
    through a live service (the soak loop the bench's overload
    phase builds on)."""
    res = ModelResidency(budget_bytes=1 << 30, policy=_policy(),
                         devices=["hbm0"])
    res.register("m", model=srm_model)
    gen = TrafficGenerator(srm_model, model_name="m", seed=1)
    schedule = gen.schedule(16, target_rps=4000.0)
    with ServeService(res, default_model="m") as svc:
        tickets = replay(schedule, svc.submit_many)
        records = [t.result(timeout=60) for t in tickets]
    assert len(records) == 16
    assert all(r.ok for r in records)


# -- in-flight correction parity (ISSUE 16 satellite) -----------------

def test_router_inflight_correction_drains_to_gauge_parity(
        srm_model):
    """The router's per-wave in-flight correction (depths bumped at
    placement time, ahead of the gauges) is transient: once a wave
    is delivered, the published depth gauges drain back to the true
    value (zero) — and a shed wave at the admission bound drains
    back the same way, because shed requests never touch a queue."""

    def settled_depth(replica, want=0.0, timeout=10.0):
        deadline = time.monotonic() + timeout
        while replica.queue_depth() != want:
            if time.monotonic() > deadline:
                break
            time.sleep(0.005)
        return replica.queue_depth()

    r1 = _replica("p1", {"m": srm_model})
    r2 = _replica("p2", {"m": srm_model})
    router = Router([r1, r2],
                    admission=AdmissionController(
                        max_depth=3, retry_after_s=0.01))
    try:
        # under the bound: all delivered, gauges back to zero
        records = [t.result(timeout=60) for t in
                   router.submit_many(
                       _srm_requests(srm_model, 4), model="m")]
        assert all(r.ok for r in records)
        assert settled_depth(r1) == 0.0
        assert settled_depth(r2) == 0.0

        # a wave AT the admission bound: the tail sheds, every
        # ticket resolves, and the gauges still drain to zero —
        # the correction never leaks shed requests into the depth
        records = [t.result(timeout=60) for t in
                   router.submit_many(
                       _srm_requests(srm_model, 16, prefix="s"),
                       model="m")]
        assert len(records) == 16
        sheds = [r for r in records if r.error == "shed_overload"]
        assert sheds and all(r.retry_after_s > 0 for r in sheds)
        assert sum(1 for r in records if r.ok) == 16 - len(sheds)
        assert settled_depth(r1) == 0.0
        assert settled_depth(r2) == 0.0
        # parity restored: the next wave's placement snapshot sees
        # clean depths and routes instead of shedding
        record = router.submit(
            _srm_requests(srm_model, 1, prefix="z")[0],
            model="m").result(timeout=60)
        assert record.ok
    finally:
        r1.service.shutdown()
        r2.service.shutdown()


def test_admission_brownout_recovers_after_violation_clears(
        srm_model):
    """ISSUE 16 satellite: once the SLO violation clears, the
    browned-out depth bound returns to max_depth on the next
    throttled poll — brownout is a temporary regime, not a ratchet
    (fake-clock harness, like the brownout test above)."""

    class FakeTracker:
        def __init__(self):
            self.violating = False

        def evaluate(self):
            return {"objectives": {
                "p99": {"violating": self.violating}}}

    clock = [0.0]
    tracker = FakeTracker()
    ctrl = AdmissionController(max_depth=8, slo=tracker,
                               brownout_factor=0.5,
                               slo_poll_interval_s=1.0,
                               clock=lambda: clock[0])
    assert ctrl.depth_bound() == 8
    assert ctrl.burning() is False
    tracker.violating = True
    clock[0] = 2.0
    assert ctrl.depth_bound() == 4            # browned out
    assert ctrl.burning() is True
    assert ctrl.stats()["depth_bound"] == 4
    tracker.violating = False
    assert ctrl.depth_bound() == 4            # poll throttled
    clock[0] = 4.0
    assert ctrl.depth_bound() == 8            # recovered
    assert ctrl.burning() is False
    assert ctrl.stats()["depth_bound"] == 8
