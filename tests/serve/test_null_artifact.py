"""The ``null_distribution`` serve surface (ISSUE 18): a finished
:class:`NullDistribution` persists through the pickle-free artifact
schema bit-for-bit (counts, thresholds, seed/statistic edge cases),
and ``InferenceEngine`` serves ``serve.null_threshold`` lookups —
p-values from the accumulated tail tables plus FWER significance
masks that match a host recompute of ``x >= threshold`` exactly."""

import numpy as np
import pytest

from brainiak_tpu.serve import (InferenceEngine, Request, detect_kind,
                                load_model, model_digest, save_model)
from brainiak_tpu.stats import NullEngine


def _null_run(side="right", seed=9, return_distribution=False):
    rng = np.random.RandomState(4)
    iscs = 0.2 + 0.1 * rng.randn(8, 6)
    return NullEngine(null_batch_size=16).run(
        iscs, "subject_bootstrap", 64, statistic="median", side=side,
        seed=seed, return_distribution=return_distribution)


def _roundtrip(model, tmp_path, name):
    path = str(tmp_path / f"{name}.npz")
    save_model(model, path)
    return load_model(path)


def test_null_distribution_roundtrip_bit_exact(tmp_path):
    result = _null_run()
    loaded = _roundtrip(result, tmp_path, "null")
    assert detect_kind(loaded) == "null_distribution"
    assert model_digest(loaded) == model_digest(result)
    assert (loaded.family, loaded.statistic, loaded.seed,
            loaded.side, loaded.exact) == (
        result.family, result.statistic, result.seed,
        result.side, result.exact)
    np.testing.assert_array_equal(loaded.observed, result.observed)
    assert loaded.thresholds == result.thresholds
    for side in ("right", "left", "two-sided"):
        np.testing.assert_array_equal(loaded.p_values(side=side),
                                      result.p_values(side=side))
    a, b = loaded.accumulator, result.accumulator
    for key, arr in b.to_state().items():
        np.testing.assert_array_equal(a.to_state()[key], arr,
                                      err_msg=key)


def test_null_distribution_roundtrip_none_seed_and_statistic(
        tmp_path):
    result = _null_run()
    result.seed = None
    result.statistic = None
    loaded = _roundtrip(result, tmp_path, "null_none")
    assert loaded.seed is None
    assert loaded.statistic is None


def test_null_distribution_artifact_drops_materialized_null(
        tmp_path):
    """The artifact is the SUMMARY: a materialized [N, V] null on
    the in-memory object is not serialized (that is what the
    accumulator replaces), and the loaded object still answers every
    p/threshold query identically."""
    result = _null_run(return_distribution=True)
    assert result.distribution is not None
    loaded = _roundtrip(result, tmp_path, "null_dist")
    assert loaded.distribution is None
    np.testing.assert_array_equal(loaded.p_values(),
                                  result.p_values())


def test_unfitted_null_distribution_refused():
    from brainiak_tpu.stats.engine import NullDistribution
    bare = NullDistribution("sign_flip", "median", 0, "right", False,
                            np.zeros(3), None)
    with pytest.raises(ValueError, match="not fitted"):
        save_model(bare, "/dev/null")


def _serve(result, queries, **engine_kwargs):
    engine = InferenceEngine(result, **engine_kwargs)
    reqs = [Request(request_id=f"q{i}", x=q)
            for i, q in enumerate(queries)]
    return engine, engine.run(reqs)


def test_engine_serves_threshold_lookups_right_side():
    result = _null_run(side="right")
    thr = result.thresholds["fwer_0.05"]
    v = result.observed.shape[0]
    lo = np.full(v, -10.0)
    hi = np.full(v, 10.0)
    engine, records = _serve(result, [result.observed, lo, hi])
    assert all(r.ok for r in records), [r.error for r in records]
    n = result.n
    for rec, q in zip(records, (result.observed, lo, hi)):
        p, sig = rec.result
        assert p.shape == sig.shape == (v,)
        assert np.all((p > 0.0) & (p <= 1.0))
        np.testing.assert_array_equal(sig, q >= thr)
    p_lo = records[1].result[0]
    p_hi = records[2].result[0]
    np.testing.assert_array_equal(p_lo, np.full(v, 1.0))
    np.testing.assert_array_equal(p_hi, np.full(v, 1.0 / (n + 1)))
    # the served p is EXACTLY the bucketed-tail convention: a host
    # recompute from the same ordered bucket histogram matches
    # bitwise, and the exact count-based p differs by at most the
    # mass of the single bucket the query lands in
    counts, values = result.accumulator._ordered_counts()
    counts = counts.reshape(len(values), -1)
    tail = np.concatenate(
        [np.cumsum(counts[::-1], axis=0)[::-1],
         np.zeros((1, v), dtype=counts.dtype)], axis=0)
    idx = np.searchsorted(values, result.observed, side="left")
    want = (np.take_along_axis(tail, idx[None], axis=0)[0]
            + 1.0) / (n + 1.0)
    p_obs = records[0].result[0]
    np.testing.assert_allclose(p_obs, want.astype(p_obs.dtype),
                               rtol=1e-6)
    bucket_bound = (counts.max() + 1.0) / (n + 1.0)
    assert np.all(np.abs(p_obs - result.p_values()) <= bucket_bound)


def test_engine_serves_left_and_two_sided_modes():
    for side in ("left", "two-sided"):
        result = _null_run(side=side)
        v = result.observed.shape[0]
        lo = np.full(v, -10.0)
        hi = np.full(v, 10.0)
        _, records = _serve(result, [lo, hi])
        assert all(r.ok for r in records)
        p_lo = records[0].result[0]
        p_hi = records[1].result[0]
        n = result.n
        if side == "left":
            # left tail: very negative is maximally significant
            np.testing.assert_array_equal(p_lo,
                                          np.full(v, 1.0 / (n + 1)))
            np.testing.assert_array_equal(p_hi, np.full(v, 1.0))
        else:
            # two-sided: both extremes are maximally significant
            np.testing.assert_array_equal(p_lo,
                                          np.full(v, 1.0 / (n + 1)))
            np.testing.assert_array_equal(p_hi,
                                          np.full(v, 1.0 / (n + 1)))


def test_engine_serves_reloaded_artifact_identically(tmp_path):
    result = _null_run()
    loaded = _roundtrip(result, tmp_path, "null_served")
    rng = np.random.RandomState(5)
    queries = [0.2 + 0.1 * rng.randn(6) for _ in range(4)]
    _, recs_a = _serve(result, queries)
    _, recs_b = _serve(loaded, queries)
    for ra, rb in zip(recs_a, recs_b):
        assert ra.ok and rb.ok
        np.testing.assert_array_equal(ra.result[0], rb.result[0])
        np.testing.assert_array_equal(ra.result[1], rb.result[1])


def test_engine_rejects_bad_null_queries():
    result = _null_run()
    engine = InferenceEngine(result)
    records = engine.run([
        Request(request_id="badshape", x=np.zeros(5)),
        Request(request_id="nonfinite",
                x=np.full(6, np.nan)),
        Request(request_id="good", x=np.zeros(6)),
    ])
    by_id = {r.request_id: r for r in records}
    assert not by_id["badshape"].ok
    assert not by_id["nonfinite"].ok
    assert by_id["good"].ok


def test_engine_serves_one_sample_observed_layout():
    """A ``sign_flip`` result's own observed map carries a leading
    length-1 axis (the one-sample permutation convention); serving
    it verbatim must work — the op flattens any layout of the
    artifact's voxel extent."""
    rng = np.random.RandomState(4)
    iscs = 0.2 + 0.1 * rng.randn(8, 6)
    result = NullEngine(null_batch_size=16).run(
        iscs, "sign_flip", 64, statistic="median", seed=9)
    assert result.observed.shape == (1, 6)
    engine = InferenceEngine(result)
    records = engine.run([
        Request(request_id="as-is", x=result.observed),
        Request(request_id="flat", x=result.observed.reshape(-1)),
    ])
    assert all(r.ok for r in records), [r.error for r in records]
    p0, sig0 = records[0].result
    p1, sig1 = records[1].result
    assert np.array_equal(p0, p1)
    assert np.array_equal(sig0, sig1)


def test_repeat_null_serving_reuses_one_program():
    result = _null_run()
    engine = InferenceEngine(result)
    queries = [Request(request_id=f"q{i}", x=np.zeros(6))
               for i in range(3)]
    engine.run(queries)
    first = engine.summary()["retrace_total"]
    engine.run(queries)
    assert engine.summary()["retrace_total"] == first
