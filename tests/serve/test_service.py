"""The always-on service loop (ISSUE 9 tentpole part 1) + the PR's
acceptance scenario: two resident models under a byte budget,
staggered waves, eviction + transparent re-admission, and the
restart-zero-compile contract over a warm AOT cache."""

import time

import numpy as np
import pytest

from brainiak_tpu.obs import metrics
from brainiak_tpu.serve import engine as engine_mod
from brainiak_tpu.serve.aot import AOTProgramCache
from brainiak_tpu.serve.artifacts import model_nbytes
from brainiak_tpu.serve.batching import BucketPolicy, Request
from brainiak_tpu.serve.residency import ModelResidency
from brainiak_tpu.serve.service import (ServeService, ServiceClosed,
                                        serve_retrace_total)


def _srm_requests(model, n, seed=0, tr_choices=(6, 20),
                  deadline_s=None, prefix="r"):
    rng = np.random.RandomState(seed)
    counts = [w.shape[0] for w in model.w_]
    return [Request(request_id=f"{prefix}{i}",
                    x=rng.randn(counts[i % len(counts)],
                                tr_choices[i % len(tr_choices)])
                    .astype(np.float32),
                    subject=i % len(counts),
                    deadline_s=deadline_s)
            for i in range(n)]


def _enc_requests(model, n, seed=0, tr_choices=(6, 20),
                  prefix="e"):
    rng = np.random.RandomState(seed)
    f, v = model.W_.shape
    out = []
    for i in range(n):
        trs = tr_choices[i % len(tr_choices)]
        feats = rng.randn(trs, f).astype(np.float32)
        resp = (model.predict(feats)
                + 0.5 * rng.randn(trs, v)).astype(np.float32)
        out.append(Request(request_id=f"{prefix}{i}",
                           x=(feats, resp)))
    return out


def _residency(models, budget=1 << 30, policy=None, aot=None):
    # one accounting slot: the per-device budget IS the old global
    # pool on one device, so the eviction/refusal scenarios here
    # keep their meaning under the forced-8-device test env
    # (multi-device placement is covered in test_federation.py)
    res = ModelResidency(
        budget_bytes=budget,
        policy=policy or BucketPolicy(max_batch=8,
                                      max_wait_s=0.02),
        aot=aot, devices=["hbm0"])
    for name, model in models.items():
        res.register(name, model=model)
    return res


def _fresh_process():
    """Simulate a restart: module-level jit builder caches cleared,
    metrics (retrace counters included) reset."""
    for builder in (engine_mod._srm_program,
                    engine_mod._rsrm_program,
                    engine_mod._eventseg_program,
                    engine_mod._encoding_program,
                    engine_mod._iem_program):
        builder.cache_clear()
    metrics.reset()


def test_single_model_roundtrip_with_parity(srm_model):
    reqs = _srm_requests(srm_model, 6)
    with ServeService(_residency({"m": srm_model})) as svc:
        tickets = [svc.submit(r) for r in reqs]
        records = [t.result(timeout=60) for t in tickets]
    assert all(r.ok for r in records)
    w = np.asarray(srm_model.w_[reqs[0].subject])
    np.testing.assert_allclose(
        np.asarray(records[0].result),
        w.T @ np.asarray(reqs[0].x), atol=1e-5)


def test_late_joiner_lands_in_next_batch_same_bucket(srm_model):
    """A request submitted after its bucket already dispatched rides
    the NEXT batch of the same bucket — never lost, deadline
    honored."""
    policy = BucketPolicy(max_batch=8, max_wait_s=0.05)
    res = _residency({"m": srm_model}, policy=policy)
    first, late = _srm_requests(srm_model, 2, tr_choices=(6,),
                                deadline_s=30.0)
    with ServeService(res) as svc:
        t1 = svc.submit(first)
        rec1 = t1.result(timeout=60)     # batch 1 dispatched
        t2 = svc.submit(late)            # joins the same bucket
        rec2 = t2.result(timeout=60)
        engine = res.acquire("m").engine
        summary = engine.summary()
    assert rec1.ok and rec2.ok
    assert rec2.latency_s <= 30.0        # deadline honored
    assert summary["n_batches"] == 2     # two dispatches...
    assert rec1.bucket == rec2.bucket    # ...of the SAME bucket


def test_deadline_counts_from_original_enqueue(srm_model):
    """A deadline shorter than max_wait expires while queued: the
    dispatch-time check reads the service-stamped enqueue clock."""
    policy = BucketPolicy(max_batch=64, max_wait_s=0.3)
    res = _residency({"m": srm_model}, policy=policy)
    req = _srm_requests(srm_model, 1, tr_choices=(6,),
                        deadline_s=0.01)[0]
    with ServeService(res) as svc:
        record = svc.submit(req).result(timeout=60)
    assert not record.ok
    assert record.error == "deadline_exceeded"
    assert record.latency_s >= 0.01


def test_shutdown_drain_serves_queued_work(srm_model):
    policy = BucketPolicy(max_batch=64, max_wait_s=60.0)
    res = _residency({"m": srm_model}, policy=policy)
    svc = ServeService(res).start()
    tickets = [svc.submit(r)
               for r in _srm_requests(srm_model, 5)]
    time.sleep(0.05)          # routed, but max_wait never fires
    svc.shutdown(drain=True)
    records = [t.result(timeout=1) for t in tickets]
    assert all(r.ok for r in records)


def test_shutdown_no_drain_fails_queued_with_status(srm_model):
    policy = BucketPolicy(max_batch=64, max_wait_s=60.0)
    res = _residency({"m": srm_model}, policy=policy)
    svc = ServeService(res).start()
    tickets = [svc.submit(r)
               for r in _srm_requests(srm_model, 5)]
    time.sleep(0.05)
    summary = svc.shutdown(drain=False)
    records = [t.result(timeout=1) for t in tickets]
    assert [r.error for r in records] == ["shutdown"] * 5
    assert summary["errors_by_code"] == {"shutdown": 5}
    with pytest.raises(ServiceClosed):
        svc.submit(_srm_requests(srm_model, 1)[0])


def test_unknown_model_is_typed_record(srm_model):
    with ServeService(_residency({"m": srm_model})) as svc:
        req = _srm_requests(srm_model, 1)[0]
        req.model = "ghost"
        record = svc.submit(req).result(timeout=60)
    assert not record.ok
    assert record.error == "unknown_model"


def test_admission_refused_is_typed_record(srm_model,
                                           encoding_model):
    """An over-budget second model fails its requests with
    admission_refused records — never an OOM, never a crash."""
    budget = model_nbytes(srm_model) + 16
    res = _residency({"big": srm_model}, budget=budget)
    res.register("over", model=encoding_model, pinned=True)
    # pin the resident one so the incoming pinned model cannot fit
    res._registry["big"].pinned = True
    with ServeService(res) as svc:
        ok_rec = svc.submit(
            _srm_requests(srm_model, 1)[0],
            model="big").result(timeout=60)
        req = _enc_requests(encoding_model, 1)[0]
        bad_rec = svc.submit(req, model="over").result(timeout=60)
    assert ok_rec.ok
    assert not bad_rec.ok
    assert bad_rec.error == "admission_refused"
    assert "budget" in bad_rec.message


def test_tick_spans_and_queue_gauges_emit(srm_model):
    """A drive under an obs sink leaves serve.service.tick spans
    (active ticks only, real durations) and the per-model queue
    gauge behind."""
    from brainiak_tpu.obs import sink
    mem = sink.add_sink(sink.MemorySink())
    try:
        with ServeService(_residency({"m": srm_model})) as svc:
            tickets = svc.submit_many(_srm_requests(srm_model, 4))
            for ticket in tickets:
                ticket.result(timeout=60)
    finally:
        sink.remove_sink(mem)
    ticks = [r for r in mem.records
             if r["kind"] == "span"
             and r["name"] == "serve.service.tick"]
    assert ticks
    assert all(r["dur_s"] >= 0 for r in ticks)
    assert sum((r.get("attrs") or {}).get("n_delivered", 0)
               for r in ticks) == 4
    assert metrics.gauge(
        "serve_service_queue_depth").value(model="m") == 0


def test_submit_many_is_deterministic_over_buckets(srm_model):
    """Two identical atomic waves produce identical (bucket, batch)
    shapes — the property the AOT restart contract rides on."""
    def drive():
        res = _residency({"m": srm_model})
        with ServeService(res) as svc:
            reqs = _srm_requests(srm_model, 7, tr_choices=(6, 20))
            for req in reqs:
                req.submitted = None
            tickets = svc.submit_many(reqs)
            records = [t.result(timeout=60) for t in tickets]
        return sorted({str(r.bucket) for r in records})

    assert drive() == drive()


# -- the PR acceptance scenario ---------------------------------------

def test_acceptance_two_models_waves_eviction_restart(
        srm_model, encoding_model, tmp_path):
    """ISSUE 9 acceptance: an SRM and a ridge_encoding model under a
    byte budget that fits ONE of them answer 128 mixed-shape
    requests in staggered model-alternating waves — zero lost
    requests, retraces bounded by the distinct bucket count, at
    least one eviction with transparent re-admission — and after a
    (simulated) process restart against the same AOT cache, the
    first requests serve with ``retrace_total{site=serve.*} == 0``.
    The SRV002 gate proves the true-subprocess restart."""
    budget = max(model_nbytes(srm_model),
                 model_nbytes(encoding_model)) + 64
    aot_dir = str(tmp_path / "aot")
    models = {"srm": srm_model, "enc": encoding_model}

    def drive(n_total, prefix):
        res = _residency(models, budget=budget,
                         aot=AOTProgramCache(aot_dir))
        delivered = []   # (kind, record)
        per_wave = 16
        with ServeService(res) as svc:
            waves = n_total // per_wave
            for w in range(waves):
                kind = "srm" if w % 2 == 0 else "enc"
                build = (_srm_requests if kind == "srm"
                         else _enc_requests)
                reqs = build(models[kind], per_wave, seed=w,
                             prefix=f"{prefix}{w}-")
                tickets = svc.submit_many(reqs, model=kind)
                delivered.extend(
                    (kind, t.result(timeout=120))
                    for t in tickets)
            summary = svc.summary()
        return delivered, summary, res

    out, summary, res = drive(128, "cold")
    records = [rec for _, rec in out]
    # zero lost requests: every one of the 128 resolved ok
    assert len(records) == 128
    assert all(r.ok for r in records), \
        {r.request_id: r.error for r in records if not r.ok}
    # retraces bounded by the distinct per-kind bucket count
    buckets = {(kind, str(rec.bucket)) for kind, rec in out}
    assert 0 < serve_retrace_total() <= len(buckets)
    # at least one eviction, and the evicted model was re-admitted
    stats = summary["residency"]
    assert stats["evictions"] >= 1
    assert max(stats["admissions"].values()) >= 2
    # padding waste covers the WHOLE drive: the evicted engines'
    # dispatched elements were accrued, not lost with the engine
    assert summary["padding_waste"] > 0

    # restart: fresh caches/metrics, same AOT dir -> first requests
    # serve without ANY serve compile
    _fresh_process()
    out2, summary2, _ = drive(32, "warm")
    assert all(rec.ok for _, rec in out2)
    assert serve_retrace_total() == 0
    assert summary2["aot"]["hits"] > 0


# -- ISSUE 15 satellite: the low-latency single-request fast path -----

def test_low_latency_submit_skips_the_batch_window(srm_model):
    """submit(low_latency=True) dispatches a singleton on the next
    tick: the round trip completes in a fraction of a max_wait_s
    deliberately set far beyond the test timeout (waiting out the
    window would time the ticket out)."""
    policy = BucketPolicy(max_batch=8, max_wait_s=30.0)
    res = _residency({"m": srm_model}, policy=policy)
    warm, measured = _srm_requests(srm_model, 2, tr_choices=(6,))
    with ServeService(res) as svc:
        svc.submit(warm, low_latency=True).result(timeout=60)
        t0 = time.monotonic()
        rec = svc.submit(measured,
                         low_latency=True).result(timeout=5.0)
        elapsed = time.monotonic() - t0
        engine = res.acquire("m").engine
        n_batches = engine.summary()["n_batches"]
    assert rec.ok
    assert elapsed < 5.0          # never waited out the 30 s window
    assert n_batches == 2         # one dispatch per expedited submit
    w = np.asarray(srm_model.w_[measured.subject])
    np.testing.assert_allclose(np.asarray(rec.result),
                               w.T @ np.asarray(measured.x),
                               atol=1e-5)


def test_low_latency_expedites_queued_bucket_mates(srm_model):
    """Requests already queued in the same bucket ride the expedited
    batch — the fast path never reorders or strands them."""
    policy = BucketPolicy(max_batch=8, max_wait_s=30.0)
    res = _residency({"m": srm_model}, policy=policy)
    reqs = _srm_requests(srm_model, 3, tr_choices=(6,))
    with ServeService(res) as svc:
        svc.submit(reqs[0], low_latency=True).result(timeout=60)
        slow = svc.submit(reqs[1])            # batched: would wait
        fast = svc.submit(reqs[2], low_latency=True)
        rec_fast = fast.result(timeout=5.0)
        rec_slow = slow.result(timeout=5.0)   # rode the same flush
    assert rec_fast.ok and rec_slow.ok
    assert rec_slow.bucket == rec_fast.bucket


def test_engine_expedite_flushes_the_request_bucket(srm_model):
    """Engine-level: expedite() flushes exactly the bucket holding
    the request, and reports False when nothing is queued."""
    from brainiak_tpu.serve.engine import InferenceEngine

    engine = InferenceEngine(
        srm_model, policy=BucketPolicy(max_batch=8,
                                       max_wait_s=30.0))
    req = _srm_requests(srm_model, 1, tr_choices=(6,))[0]
    assert engine.submit(req) is None
    assert engine.expedite(req) is True
    records = engine.drain()
    assert len(records) == 1 and records[0].ok
    assert engine.expedite(req) is False  # bucket already empty


def test_low_latency_flag_is_not_sticky_across_resubmits(srm_model):
    """A request submitted low_latency once and later resubmitted
    as batched traffic (submit or submit_many) must not keep the
    fast-path flag."""
    res = _residency({"m": srm_model})
    req = _srm_requests(srm_model, 1, tr_choices=(6,))[0]
    with ServeService(res) as svc:
        svc.submit(req, low_latency=True).result(timeout=60)
        assert req._low_latency is True
        req.submitted = None
        svc.submit(req).result(timeout=60)
        assert req._low_latency is False
        req.submitted = None
        svc.submit_many([req])[0].result(timeout=60)
        assert req._low_latency is False
