"""Artifact round-trips: ``load_model(save_model(m))`` must be
bit-identical on the inference surface for every adapter (ISSUE 5
satellite), the schema must be enforced, and loads must retry
transient I/O faults."""

import io

import numpy as np
import pytest

from brainiak_tpu.serve import (detect_kind, load_model, save_model,
                                save_model_bytes)


def _roundtrip(model, tmp_path, name):
    path = str(tmp_path / f"{name}.npz")
    save_model(model, path)
    return load_model(path)


def _exact(a, b):
    assert type(a) is type(b)
    if isinstance(a, (list, tuple)):
        assert len(a) == len(b)
        for x, y in zip(a, b):
            _exact(x, y)
        return
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_srm_roundtrip_mixed_voxel_counts(srm_model, tmp_path):
    """The mixed-voxel-count path: per-subject W's of different
    shapes survive pickle-free (the ad-hoc SRM.save used object
    arrays + allow_pickle here)."""
    assert len({w.shape for w in srm_model.w_}) > 1
    loaded = _roundtrip(srm_model, tmp_path, "srm")
    X = [np.random.RandomState(1).randn(w.shape[0], 9)
         for w in srm_model.w_]
    _exact(srm_model.transform(X), loaded.transform(X))
    for w0, w1 in zip(srm_model.w_, loaded.w_):
        np.testing.assert_array_equal(w0, w1)
    np.testing.assert_array_equal(srm_model.sigma_s_,
                                  loaded.sigma_s_)
    assert loaded.logprob_ == srm_model.logprob_
    assert detect_kind(loaded) == "srm"


def test_detsrm_roundtrip(detsrm_model, tmp_path):
    loaded = _roundtrip(detsrm_model, tmp_path, "detsrm")
    X = [np.random.RandomState(2).randn(w.shape[0], 7)
         for w in detsrm_model.w_]
    _exact(detsrm_model.transform(X), loaded.transform(X))
    assert detect_kind(loaded) == "detsrm"


def test_rsrm_roundtrip(rsrm_model, tmp_path):
    loaded = _roundtrip(rsrm_model, tmp_path, "rsrm")
    X = [np.asarray(np.random.RandomState(3).randn(w.shape[0], 8),
                    dtype=rsrm_model.w_[0].dtype)
         for w in rsrm_model.w_]
    r0, s0 = rsrm_model.transform(X)
    r1, s1 = loaded.transform(X)
    _exact(r0, r1)
    _exact(s0, s1)
    assert loaded.gamma == rsrm_model.gamma


def test_eventseg_roundtrip(eventseg_model, tmp_path):
    loaded = _roundtrip(eventseg_model, tmp_path, "eventseg")
    rng = np.random.RandomState(4)
    test_data = rng.randn(20, eventseg_model.event_pat_.shape[0])
    seg0, ll0 = eventseg_model.find_events(test_data)
    seg1, ll1 = loaded.find_events(test_data)
    np.testing.assert_array_equal(seg0, seg1)
    assert ll0 == ll1
    np.testing.assert_array_equal(eventseg_model.predict(test_data),
                                  loaded.predict(test_data))
    assert type(loaded.event_var_) is type(
        eventseg_model.event_var_)


def test_iem1d_roundtrip(iem1d_model, tmp_path):
    loaded = _roundtrip(iem1d_model, tmp_path, "iem1d")
    rng = np.random.RandomState(5)
    X = rng.randn(15, iem1d_model.W_.shape[0])
    np.testing.assert_array_equal(iem1d_model.predict(X),
                                  loaded.predict(X))
    np.testing.assert_array_equal(iem1d_model.channels_,
                                  loaded.channels_)


def test_iem2d_roundtrip(tmp_path):
    from brainiak_tpu.reconstruct.iem import InvertedEncoding2D
    rng = np.random.RandomState(6)
    model = InvertedEncoding2D([-6, 6], [-6, 6], 21, stim_radius=2)
    model.define_basis_functions_sqgrid(4)
    centers = rng.uniform(-4, 4, size=(30, 2))
    design = model._define_trial_activations(centers)
    X = design @ rng.randn(model.n_channels, 10) \
        + 0.05 * rng.randn(30, 10)
    model.fit(X, centers)
    loaded = _roundtrip(model, tmp_path, "iem2d")
    X_test = rng.randn(8, 10)
    np.testing.assert_array_equal(model.predict(X_test),
                                  loaded.predict(X_test))


@pytest.mark.parametrize("which", ["logit", "precomputed"])
def test_fcma_roundtrip(fcma_models, tmp_path, which):
    logit, precomp, test = fcma_models
    model = logit if which == "logit" else precomp
    loaded = _roundtrip(model, tmp_path, f"fcma_{which}")
    np.testing.assert_array_equal(model.predict(test),
                                  loaded.predict(test))
    if which == "precomputed":
        np.testing.assert_array_equal(model.training_data_,
                                      loaded.training_data_)


def test_bytes_roundtrip(srm_model):
    blob = save_model_bytes(srm_model)
    loaded = load_model(io.BytesIO(blob))
    for w0, w1 in zip(srm_model.w_, loaded.w_):
        np.testing.assert_array_equal(w0, w1)


def test_unfitted_model_rejected(tmp_path):
    from brainiak_tpu.funcalign.srm import SRM
    with pytest.raises(ValueError, match="not fitted"):
        save_model(SRM(), str(tmp_path / "x.npz"))


def test_unknown_model_type_rejected(tmp_path):
    with pytest.raises(TypeError, match="no serve adapter"):
        save_model(object(), str(tmp_path / "x.npz"))


def test_not_an_artifact_rejected(tmp_path):
    path = str(tmp_path / "plain.npz")
    np.savez(path, a=np.arange(3))
    with pytest.raises(ValueError, match="not a serve artifact"):
        load_model(path)


def test_newer_schema_rejected(srm_model, tmp_path):
    from brainiak_tpu.serve import artifacts
    path = str(tmp_path / "new.npz")
    save_model(srm_model, path)
    with np.load(path) as z:
        arrays = {k: z[k] for k in z.files}
    arrays[artifacts.VERSION_KEY] = np.asarray(
        artifacts.SCHEMA_VERSION + 1)
    np.savez(path, **arrays)
    with pytest.raises(ValueError, match="newer"):
        load_model(path)


def test_load_retries_transient_oserror(srm_model, tmp_path,
                                        monkeypatch):
    """load_model is wired through resilience.retry: a transient
    OSError on the npz read retries with backoff instead of
    propagating (ISSUE 5 tentpole wiring)."""
    import importlib
    retry_mod = importlib.import_module(
        "brainiak_tpu.resilience.retry")

    path = str(tmp_path / "flaky.npz")
    save_model(srm_model, path)
    monkeypatch.setattr(retry_mod, "_sleep", lambda s: None)
    real_load = np.load
    calls = {"n": 0}

    def flaky_load(*args, **kwargs):
        calls["n"] += 1
        if calls["n"] == 1:
            raise OSError("shared filesystem hiccup")
        return real_load(*args, **kwargs)

    monkeypatch.setattr(np, "load", flaky_load)
    loaded = load_model(path)
    assert calls["n"] == 2
    for w0, w1 in zip(srm_model.w_, loaded.w_):
        np.testing.assert_array_equal(w0, w1)


def test_load_retry_rewinds_file_like(srm_model, monkeypatch):
    """A retry on a file-like input must rewind the stream: the
    failed first attempt leaves the cursor mid-file, and resuming
    there would corrupt the read instead of retrying it."""
    import importlib

    from brainiak_tpu.serve import save_model_bytes
    retry_mod = importlib.import_module(
        "brainiak_tpu.resilience.retry")
    monkeypatch.setattr(retry_mod, "_sleep", lambda s: None)

    buf = io.BytesIO(save_model_bytes(srm_model))
    real_load = np.load
    calls = {"n": 0}

    def flaky_load(file, *args, **kwargs):
        calls["n"] += 1
        if calls["n"] == 1:
            file.read(16)  # consume part of the stream, then fail
            raise OSError("transient read fault")
        return real_load(file, *args, **kwargs)

    monkeypatch.setattr(np, "load", flaky_load)
    loaded = load_model(buf)
    assert calls["n"] == 2
    for w0, w1 in zip(srm_model.w_, loaded.w_):
        np.testing.assert_array_equal(w0, w1)


def test_load_missing_path_fails_fast(tmp_path, monkeypatch):
    """A mispointed --model path is deterministic, not transient:
    load_model must raise FileNotFoundError on the first attempt
    instead of burning the full retry/backoff schedule."""
    import importlib
    retry_mod = importlib.import_module(
        "brainiak_tpu.resilience.retry")
    sleeps = []
    monkeypatch.setattr(retry_mod, "_sleep", sleeps.append)

    with pytest.raises(FileNotFoundError):
        load_model(str(tmp_path / "typo.npz"))
    assert sleeps == []  # no retries scheduled


def test_save_model_extensionless_path_roundtrips(srm_model,
                                                  tmp_path):
    """np.savez_compressed appends ".npz" to extensionless paths;
    save_model must return the path actually written so the
    documented load_model(save_model(m, f)) chain works for any f."""
    written = save_model(srm_model, str(tmp_path / "m"))
    assert written.endswith(".npz")
    loaded = load_model(written)
    for w0, w1 in zip(srm_model.w_, loaded.w_):
        np.testing.assert_array_equal(w0, w1)


def test_ridge_encoding_roundtrip(encoding_model, tmp_path):
    """The encoding artifact round-trips bit-exact on the inference
    surface (ISSUE 7 acceptance) with allow_pickle=False."""
    loaded = _roundtrip(encoding_model, tmp_path, "enc")
    assert detect_kind(loaded) == "ridge_encoding"
    assert type(loaded) is type(encoding_model)
    x = np.random.RandomState(5).randn(
        20, encoding_model.W_.shape[0]).astype(np.float32)
    _exact(encoding_model.predict(x), loaded.predict(x))
    np.testing.assert_array_equal(loaded.W_, encoding_model.W_)
    np.testing.assert_array_equal(loaded.lambda_,
                                  encoding_model.lambda_)
    np.testing.assert_array_equal(loaded.lambdas_,
                                  encoding_model.lambdas_)
    assert loaded.n_folds == encoding_model.n_folds


def test_banded_ridge_encoding_roundtrip(banded_encoding_model,
                                         tmp_path):
    """The banded subclass shares the ridge_encoding kind (a
    ``banded`` flag selects the class on load) and keeps its bands,
    candidates and per-band selected lambdas."""
    model = banded_encoding_model
    loaded = _roundtrip(model, tmp_path, "banded_enc")
    assert detect_kind(loaded) == "ridge_encoding"
    assert type(loaded) is type(model)
    x = np.random.RandomState(6).randn(
        15, model.W_.shape[0]).astype(np.float32)
    _exact(model.predict(x), loaded.predict(x))
    np.testing.assert_array_equal(loaded.bands, model.bands)
    np.testing.assert_array_equal(loaded.candidates_,
                                  model.candidates_)
    assert loaded.lambda_.shape == model.lambda_.shape
    assert loaded.standardize is True


def test_future_schema_rejected_before_decode(tmp_path):
    """Registry-level version handling (ISSUE 7 satellite): an
    artifact stamped with a FUTURE serve_schema_version must raise
    the unsupported-schema-version error up front — never a
    KeyError from an adapter decoding payload keys it does not
    understand (this artifact has none at all)."""
    from brainiak_tpu.serve import artifacts

    path = str(tmp_path / "future.npz")
    np.savez(path, **{
        artifacts.KIND_KEY: np.asarray("ridge_encoding"),
        artifacts.VERSION_KEY:
            np.asarray(artifacts.SCHEMA_VERSION + 1)})
    with pytest.raises(ValueError,
                       match="unsupported schema version"):
        load_model(path)
