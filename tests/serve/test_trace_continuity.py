"""Cross-process trace continuity (ISSUE 12 satellite).

The multi-process replica story: a submitter process mints trace
ids and writes them into the npz request codec; a `serve service`
CLI replica (true subprocess) serves the file with an obs sink
live; the exported chrome-trace then contains ONE connected trace
per request — rooted at the submitter's span, spanning
submit→enqueue→dispatch→deliver inside the replica — and the flow
events bind each trace across the timeline."""

import json
import os
import subprocess
import sys

import pytest

from brainiak_tpu.obs import trace as obs_trace
from brainiak_tpu.obs.export import (chrome_trace,
                                     validate_chrome_trace)
from brainiak_tpu.obs.report import load_records
from tests.conftest import REPO_ROOT

N_REQUESTS = 5


@pytest.fixture(scope="module")
def traced_run(tmp_path_factory):
    """One traced `serve service` subprocess run over codec-injected
    trace ids; returns (injected traces, obs records, client spans).
    """
    from brainiak_tpu.serve import save_model, save_requests
    from brainiak_tpu.serve.__main__ import (build_demo_model,
                                             build_mixed_requests)

    tmp = tmp_path_factory.mktemp("trace-continuity")
    obs_dir = str(tmp / "obs")
    model_path = str(tmp / "model.npz")
    req_path = str(tmp / "requests.npz")
    model = build_demo_model(n_subjects=2, voxels=10, samples=20,
                             features=3, n_iter=2, seed=1)
    save_model(model, model_path)
    reqs = build_mixed_requests(model, N_REQUESTS, seed=1,
                                tr_choices=(5, 9))
    # the submitter process: one client span per request, its id
    # carried as the request's parent through the codec
    traces = [(obs_trace.new_trace_id(), obs_trace.new_span_id())
              for _ in reqs]
    save_requests(req_path, [r.x for r in reqs],
                  subjects=[r.subject for r in reqs],
                  ids=[r.request_id for r in reqs],
                  traces=traces)
    proc = subprocess.run(
        [sys.executable, "-m", "brainiak_tpu.serve", "service",
         "--model", f"m={model_path}", "--requests", req_path,
         "--waves", "1", "--format=json"],
        capture_output=True, text=True, cwd=REPO_ROOT,
        env=dict(os.environ, JAX_PLATFORMS="cpu",
                 BENCH_FORCE_CPU="1",
                 BRAINIAK_TPU_OBS_DIR=obs_dir),
        timeout=420)
    assert proc.returncode == 0, proc.stderr
    summary = json.loads(proc.stdout)
    assert summary["n_ok"] == N_REQUESTS
    files = [os.path.join(obs_dir, f)
             for f in sorted(os.listdir(obs_dir))
             if f.endswith(".jsonl")]
    records, errors = load_records(files)
    assert errors == []
    return traces, records


def test_one_connected_trace_per_request(traced_run):
    traces, records = traced_run
    chains = obs_trace.trace_chains(records)
    assert set(chains) == {tid for tid, _ in traces}
    for tid, client_span in traces:
        recs = chains[tid]
        names = [r["name"] for r in recs]
        # the full replica-side chain, in causal order
        assert names == ["serve.submit", "serve.enqueue",
                         "serve.dispatch", "serve.request"], names
        # rooted at the SUBMITTER's span: cross-process continuity
        assert recs[0]["parent_id"] == client_span
        assert obs_trace.trace_is_connected(recs)
        for parent, child in zip(recs, recs[1:]):
            assert child["parent_id"] == parent["span_id"]


def test_export_renders_request_flows(traced_run):
    traces, records = traced_run
    doc = chrome_trace(records)
    assert validate_chrome_trace(doc) == []
    flows = [e for e in doc["traceEvents"]
             if e["ph"] in ("s", "t", "f")]
    by_id = {}
    for ev in flows:
        by_id.setdefault(ev["id"], []).append(ev["ph"])
    assert set(by_id) == {tid for tid, _ in traces}
    for phases in by_id.values():
        # one start, one finish, steps between (4 spans = 2 steps)
        assert phases[0] == "s" and phases[-1] == "f"
        assert phases.count("t") == len(phases) - 2
    # traced X slices carry their ids for the viewer
    traced_slices = [e for e in doc["traceEvents"]
                     if e["ph"] == "X"
                     and e["args"].get("trace_id")]
    assert len(traced_slices) == 4 * N_REQUESTS
