"""The live telemetry plane through ServeService (ISSUE 12).

In-process acceptance: every delivered request is ONE connected
trace (submit→enqueue→dispatch→deliver with correct parentage), the
summary percentiles come off the mergeable sketch (parity vs the
exact sorted latencies within the documented bound), two replica
sketches merge to the pooled p99, SLO burn tracking rides the
delivery path, the exposition endpoint serves live state that
agrees with the summary, and the obs-disabled drive adds zero
records and mints zero ids."""

import json
import urllib.request

import pytest

from brainiak_tpu.obs import metrics
from brainiak_tpu.obs import sink as obs_sink
from brainiak_tpu.obs import trace as obs_trace
from brainiak_tpu.obs.sketch import (DEFAULT_RELATIVE_ACCURACY,
                                     QuantileSketch)
from brainiak_tpu.obs.slo import BurnRule, Objective
from brainiak_tpu.serve import BucketPolicy, ModelResidency
from brainiak_tpu.serve.__main__ import (build_demo_model,
                                         build_mixed_requests)
from brainiak_tpu.serve.service import ServeService


@pytest.fixture(scope="module")
def demo_model():
    return build_demo_model(n_subjects=2, voxels=24, samples=20,
                            features=4, n_iter=2)


def _residency(model, max_batch=8):
    residency = ModelResidency(
        budget_bytes=1 << 30,
        policy=BucketPolicy(max_batch=max_batch, max_wait_s=0.01))
    residency.register("demo", model=model)
    return residency


def _drive(model, n, **service_kwargs):
    requests = build_mixed_requests(model, n)
    svc = ServeService(_residency(model), default_model="demo",
                       **service_kwargs).start()
    tickets = svc.submit_many(requests)
    records = [t.result(timeout=120.0) for t in tickets]
    return svc, requests, records


def test_every_delivered_request_is_one_connected_trace(demo_model):
    mem = obs_sink.add_sink(obs_sink.MemorySink())
    svc, requests, records = _drive(demo_model, 10)
    svc.shutdown()
    assert all(r.ok for r in records)
    chains = obs_trace.trace_chains(mem.records)
    assert len(chains) == 10  # one trace per request
    assert {r.trace_id for r in requests} == set(chains)
    for tid, recs in chains.items():
        assert obs_trace.trace_is_connected(recs), \
            [(r["name"], r.get("span_id"), r.get("parent_id"))
             for r in recs]
        names = [r["name"] for r in recs]
        assert names == ["serve.submit", "serve.enqueue",
                         "serve.dispatch", "serve.request"]
        # correct parentage: each stage parents the previous one
        for parent, child in zip(recs, recs[1:]):
            assert child["parent_id"] == parent["span_id"]
        assert all(obs_sink.validate_record(r) == [] for r in recs)


def test_injected_trace_ids_are_adopted_not_replaced(demo_model):
    mem = obs_sink.add_sink(obs_sink.MemorySink())
    requests = build_mixed_requests(demo_model, 3)
    upstream = [obs_trace.new_trace_id() for _ in requests]
    for req, tid in zip(requests, upstream):
        req.trace_id = tid
        req.parent_id = "aabbccdd"  # the submitter's span
    svc = ServeService(_residency(demo_model),
                       default_model="demo").start()
    for t in svc.submit_many(requests):
        t.result(timeout=120.0)
    svc.shutdown()
    chains = obs_trace.trace_chains(mem.records)
    assert set(chains) == set(upstream)
    for recs in chains.values():
        # the chain roots at the upstream span id (one external
        # root = still connected)
        assert recs[0]["parent_id"] == "aabbccdd"
        assert obs_trace.trace_is_connected(recs)


def test_disabled_drive_zero_records_zero_ids(demo_model):
    svc, requests, records = _drive(demo_model, 6)
    summary = svc.shutdown()
    assert summary["n_ok"] == 6
    assert all(r.trace_id is None and r.parent_id is None
               for r in requests)
    assert not obs_sink.enabled()


def test_summary_percentiles_match_exact_within_sketch_bound(
        demo_model):
    """Satellite: the sketch replaces the sorted-deque percentile;
    parity against the exact sorted result within the documented
    relative error."""
    svc, requests, records = _drive(demo_model, 24)
    summary = svc.shutdown()
    latencies = sorted(r.latency_s for r in records if r.ok)
    assert len(latencies) == 24

    def exact(q):
        idx = min(len(latencies) - 1,
                  int(round(q * (len(latencies) - 1))))
        return latencies[idx]

    for key, q in (("p50_latency_s", 0.50), ("p99_latency_s", 0.99)):
        assert summary[key] == pytest.approx(
            exact(q), rel=DEFAULT_RELATIVE_ACCURACY)
    # SUMMARY keeps its keys: the service bench tier and SRV002
    # read these unchanged
    assert {"p50_latency_s", "p99_latency_s", "n_ok",
            "padding_waste", "retrace_total"} <= set(summary)


def test_replica_sketches_merge_to_pooled_p99(demo_model):
    """Acceptance: two replica sketches reproduce the pooled p99
    within the documented relative-error bound."""
    svc1, _, recs1 = _drive(demo_model, 16)
    svc2, _, recs2 = _drive(demo_model, 12)
    s1 = svc1.latency_sketch()
    s2 = svc2.latency_sketch()
    svc1.shutdown()
    svc2.shutdown()
    # the router move: merge through the JSON wire format
    merged = QuantileSketch.from_dict(
        json.loads(json.dumps(s1.to_dict())))
    merged.merge(QuantileSketch.from_dict(s2.to_dict()))
    pooled = sorted(r.latency_s for r in recs1 + recs2 if r.ok)
    assert merged.count == len(pooled) == 28
    idx = min(len(pooled) - 1, int(round(0.99 * (len(pooled) - 1))))
    assert merged.quantile(0.99) == pytest.approx(
        pooled[idx], rel=DEFAULT_RELATIVE_ACCURACY)


def test_slo_tracking_rides_delivery(demo_model):
    mem = obs_sink.add_sink(obs_sink.MemorySink())
    # an impossible latency target: every served request burns
    slos = [Objective.latency("p99", quantile=0.99,
                              threshold_s=1e-9),
            Objective.error_rate("avail", max_error_rate=0.01)]
    svc, _, records = _drive(
        demo_model, 12,
        slos=SLOTrackerFactory(slos))
    summary = svc.shutdown()
    slo = summary["slo"]["objectives"]
    assert slo["p99"]["violating"]
    assert slo["p99"]["error_budget_remaining"] == 0.0
    assert not slo["avail"]["violating"]  # all requests served ok
    assert slo["avail"]["error_budget_remaining"] == \
        pytest.approx(1.0)
    events = [r for r in mem.records
              if r["kind"] == "event"
              and r["name"] == "slo_violation"]
    assert len(events) == 1
    assert events[0]["attrs"]["slo"] == "p99"
    assert metrics.gauge("slo_burn_rate").value(
        slo="avail", window="10s") == 0.0


def SLOTrackerFactory(objectives):
    """A tracker whose tiny windows judge immediately in-test."""
    from brainiak_tpu.obs.slo import SLOTracker
    return SLOTracker(objectives,
                      burn_rules=(BurnRule(long_s=10.0, short_s=2.0,
                                           factor=2.0),),
                      min_window_count=5)


def test_http_exposition_agrees_with_summary(demo_model):
    obs_sink.add_sink(obs_sink.MemorySink())
    svc, _, records = _drive(demo_model, 8, http_port=0)
    port = svc.summary()["http_port"]
    assert port and port > 0
    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/metrics", timeout=10) as resp:
        text = resp.read().decode()
    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/readyz", timeout=10) as resp:
        ready = json.loads(resp.read().decode())
    summary = svc.shutdown()
    assert summary["http_port"] == port
    from brainiak_tpu.obs.http import parse_prometheus_text
    families, errors = parse_prometheus_text(text)
    assert errors == []
    scraped_ok = sum(
        v for name, labels, v in
        families["serve_requests_total"]["samples"]
        if labels.get("outcome") == "ok")
    assert int(scraped_ok) == summary["n_ok"] == 8
    assert ready["ready"] is True
    assert ready["n_resident"] == 1
    # the listener is down after shutdown
    with pytest.raises(Exception):
        urllib.request.urlopen(
            f"http://127.0.0.1:{port}/healthz", timeout=2)


def test_readiness_states(demo_model):
    residency = _residency(demo_model)
    svc = ServeService(residency, default_model="demo")
    ready, detail = svc.readiness()
    assert not ready and detail["state"] == "idle"
    svc.start()
    # registered but nothing resident, no AOT: not ready yet
    ready, detail = svc.readiness()
    assert not ready and detail["n_resident"] == 0
    ticket = svc.submit(build_mixed_requests(demo_model, 1)[0])
    ticket.result(timeout=120.0)
    ready, detail = svc.readiness()
    assert ready and detail["n_resident"] == 1
    svc.shutdown()
    ready, detail = svc.readiness()
    assert not ready
