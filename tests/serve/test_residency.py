"""Multi-model residency: byte-weighted LRU, pinning, typed
admission refusal, transparent re-admission (ISSUE 9 tentpole
part 2)."""

import numpy as np
import pytest

from brainiak_tpu.obs import metrics, sink
from brainiak_tpu.serve import save_model
from brainiak_tpu.serve.artifacts import model_nbytes
from brainiak_tpu.serve.batching import Request
from brainiak_tpu.serve.residency import (AdmissionError,
                                          BUDGET_ENV,
                                          DEFAULT_BUDGET_BYTES,
                                          ModelResidency,
                                          default_budget_bytes)


@pytest.fixture
def three_models(tmp_path):
    """Three same-size SRM artifacts on disk + their byte size."""
    from brainiak_tpu.serve.__main__ import build_demo_model
    paths = {}
    nbytes = None
    for i, name in enumerate(("a", "b", "c")):
        model = build_demo_model(n_subjects=2, voxels=10,
                                 samples=16, features=3, n_iter=2,
                                 seed=i, ragged=False)
        paths[name] = save_model(model,
                                 str(tmp_path / f"{name}.npz"))
        nbytes = model_nbytes(model)
    return paths, nbytes


def test_lru_eviction_under_pressure(three_models):
    """Admit N+1 models under a budget that fits two: the LEAST
    recently used one is evicted, with the counter/event trail."""
    paths, nbytes = three_models
    mem = sink.add_sink(sink.MemorySink())
    try:
        # one accounting slot: the per-device budget IS the old
        # global pool on a single device (multi-device placement
        # and eviction are covered in test_federation.py)
        res = ModelResidency(budget_bytes=2 * nbytes + 16,
                             devices=["hbm0"])
        for name, path in paths.items():
            res.register(name, source=path)
        res.acquire("a")
        res.acquire("b")
        res.acquire("a")          # b is now the LRU
        res.acquire("c")          # must evict b, not a
        assert res.resident_names() == ["a", "c"]
        assert res.stats()["evictions"] == 1
        assert metrics.counter("serve_evictions_total").value(
            model="b") == 1
        events = [r for r in mem.records
                  if r.get("name") == "eviction"]
        assert len(events) == 1
        assert events[0]["attrs"]["model"] == "b"
        assert "'c'" in events[0]["attrs"]["reason"]
    finally:
        sink.remove_sink(mem)


def test_transparent_readmission(three_models):
    paths, nbytes = three_models
    res = ModelResidency(budget_bytes=nbytes + 16,
                         devices=["hbm0"])
    res.register("a", source=paths["a"])
    res.register("b", source=paths["b"])
    first = res.acquire("a")
    res.acquire("b")              # evicts a
    assert res.resident_names() == ["b"]
    again = res.acquire("a")      # reloads from the registration
    assert res.resident_names() == ["a"]
    assert again is not first
    assert again.admissions == 2
    assert res.stats()["admissions"]["a"] == 2
    # the re-admitted engine serves (same artifact, fresh load)
    rng = np.random.RandomState(0)
    x = rng.randn(10, 6).astype(np.float32)
    rec = again.engine.run(
        [Request(request_id="r", x=x, subject=0)])[0]
    assert rec.ok


def test_pinned_model_never_evicted(three_models):
    paths, nbytes = three_models
    res = ModelResidency(budget_bytes=nbytes + 16,
                         devices=["hbm0"])
    res.register("a", source=paths["a"], pinned=True)
    res.register("b", source=paths["b"])
    res.acquire("a")
    with pytest.raises(AdmissionError) as excinfo:
        res.acquire("b")
    err = excinfo.value
    assert err.model == "b"
    assert err.needed_bytes == nbytes
    assert err.budget_bytes == nbytes + 16
    assert err.pinned_bytes == nbytes
    assert res.resident_names() == ["a"]   # pinned survived
    with pytest.raises(ValueError, match="pinned"):
        res.evict("a")


def test_oversized_model_is_typed_refusal(three_models):
    paths, nbytes = three_models
    res = ModelResidency(budget_bytes=nbytes // 2)
    res.register("a", source=paths["a"])
    with pytest.raises(AdmissionError):
        res.acquire("a")
    assert res.resident_names() == []
    # the size was learned on the first load: repeat acquires must
    # refuse WITHOUT re-reading the artifact from disk
    res._registry["a"].source = str(paths["a"]) + ".gone"
    with pytest.raises(AdmissionError):
        res.acquire("a")


def test_eviction_fails_queued_work_and_delivers(three_models):
    """Requests queued on the victim fail with `evicted` records
    routed through the on_evict_records hook, never dropped."""
    paths, nbytes = three_models
    res = ModelResidency(budget_bytes=nbytes + 16,
                         devices=["hbm0"])
    delivered = []
    res.on_evict_records = \
        lambda name, recs: delivered.append((name, recs))
    res.register("a", source=paths["a"])
    res.register("b", source=paths["b"])
    entry = res.acquire("a")
    rng = np.random.RandomState(0)
    x = rng.randn(10, 6).astype(np.float32)
    assert entry.engine.submit(
        Request(request_id="q", x=x, subject=0)) is None
    res.acquire("b")              # evicts a with work queued
    assert len(delivered) == 1
    name, records = delivered[0]
    assert name == "a"
    assert [r.error for r in records] == ["evicted"]


def test_register_validation(three_models):
    paths, _ = three_models
    res = ModelResidency(budget_bytes=1 << 20)
    res.register("a", source=paths["a"])
    with pytest.raises(ValueError, match="already registered"):
        res.register("a", source=paths["b"])
    with pytest.raises(ValueError, match="exactly one"):
        res.register("x")
    with pytest.raises(KeyError):
        res.acquire("nope")


def test_budget_env_override(monkeypatch):
    monkeypatch.setenv(BUDGET_ENV, "12345")
    assert default_budget_bytes() == 12345
    monkeypatch.delenv(BUDGET_ENV)
    # CPU backend exposes no memory stats -> constant fallback
    assert default_budget_bytes() == DEFAULT_BUDGET_BYTES


def test_resident_gauges_track_occupancy(three_models):
    paths, nbytes = three_models
    res = ModelResidency(budget_bytes=4 * nbytes)
    res.register("a", source=paths["a"])
    res.register("b", source=paths["b"])
    res.acquire("a")
    res.acquire("b")
    assert metrics.gauge("serve_resident_models").value() == 2
    assert metrics.gauge("serve_resident_bytes").value() \
        == 2 * nbytes
