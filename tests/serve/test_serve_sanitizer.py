"""Serve-side checkify sanitizer lane: an injected NaN inside a
bucket program (poisoned model weights — finite requests pass
validation) becomes a typed ``sanitizer`` obs event and a structured
per-request error; with the lane off the same dispatch runs
untouched and emits nothing (ISSUE 17 acceptance)."""

import copy

import numpy as np
import pytest

from brainiak_tpu.obs import MemorySink, add_sink, metrics
from brainiak_tpu.obs import sanitize
from brainiak_tpu.serve import InferenceEngine, Request


@pytest.fixture(autouse=True)
def _clean_sanitizer():
    sanitize.reset()
    yield
    sanitize.reset()


def _poisoned(srm_model):
    """A deep copy whose subject-0 weights carry one NaN: every
    finite subject-0 request then produces NaN INSIDE the transform
    program, past request validation."""
    model = copy.deepcopy(srm_model)
    model.w_[0] = np.array(model.w_[0])
    model.w_[0][0, 0] = np.nan
    return model


def _request(model, rid="r0", subject=0, trs=10):
    rng = np.random.RandomState(3)
    return Request(request_id=rid, subject=subject,
                   x=rng.randn(model.w_[subject].shape[0], trs))


def test_serve_program_nan_becomes_typed_event(srm_model,
                                               monkeypatch):
    monkeypatch.setenv("BRAINIAK_TPU_SANITIZE", "1")
    mem = add_sink(MemorySink())
    model = _poisoned(srm_model)
    engine = InferenceEngine(model)
    record, = engine.run([_request(model)])
    assert not record.ok
    assert record.error == "execution_failed"
    assert "sanitizer" in (record.message or "")
    events = [r for r in mem.records
              if r["kind"] == "event" and r["name"] == "sanitizer"]
    assert events, "serve trip must emit a typed sanitizer event"
    attrs = events[0]["attrs"]
    assert attrs["site"] == "serve.srm"
    assert attrs["scope"] == "serve"
    assert "JP301" in attrs["codes"]
    assert metrics.counter("sanitizer_errors_total").value(
        site="serve.srm", scope="serve") >= 1.0


def test_serve_lane_off_runs_untouched(srm_model, monkeypatch):
    monkeypatch.delenv("BRAINIAK_TPU_SANITIZE", raising=False)
    mem = add_sink(MemorySink())
    model = _poisoned(srm_model)
    engine = InferenceEngine(model)
    record, = engine.run([_request(model)])
    # the NaN flows through silently: the lane is off, the engine's
    # contract is untouched dispatch
    assert record.ok
    assert np.isnan(np.asarray(record.result)).any()
    assert not sanitize._checked
    assert [r for r in mem.records
            if r["kind"] == "event"
            and r["name"].startswith("sanitizer")] == []


def test_serve_clean_requests_pass_under_sanitizer(srm_model,
                                                   monkeypatch):
    """The lane must not perturb healthy serving: same results,
    no events."""
    monkeypatch.setenv("BRAINIAK_TPU_SANITIZE", "1")
    mem = add_sink(MemorySink())
    engine = InferenceEngine(srm_model)
    req = _request(srm_model, rid="ok0")
    record, = engine.run([req])
    assert record.ok, record.error
    expected = srm_model.w_[0].T @ req.x
    np.testing.assert_allclose(np.asarray(record.result), expected,
                               atol=1e-5)
    assert [r for r in mem.records
            if r["kind"] == "event"
            and r["name"] == "sanitizer"] == []
