"""Elastic fault-tolerant fleet (ISSUE 16 tentpole): supervisor
health hysteresis, failover re-placement with the exactly-one-
ticket invariant, typed ``replica_lost`` records, autoscaling, and
drain-and-handoff resharding.  The SRV004 gate adds the full chaos
soak at subprocess granularity; these tests pin each mechanism
deterministically (fake replicas for the state machine, injected
clocks for deadlines, targeted faults for real crashes)."""

import time

import pytest

from brainiak_tpu.resilience import faults
from brainiak_tpu.serve.batching import BucketPolicy, Request
from brainiak_tpu.serve.federation import (FleetSupervisor,
                                           LocalReplica, Router,
                                           TrafficGenerator,
                                           scrape_replica_state)
from brainiak_tpu.serve.residency import ModelResidency
from brainiak_tpu.serve.service import ServeService, ServiceTicket


def _policy():
    return BucketPolicy(max_batch=8, max_wait_s=0.01)


def _replica(name, model, aot=None):
    res = ModelResidency(budget_bytes=1 << 30, policy=_policy(),
                         devices=["hbm0"], aot=aot)
    res.register("m", model=model)
    return LocalReplica(ServeService(
        res, default_model="m", name=name).start())


# -- fakes for the supervision state machine --------------------------


class FakeService:
    def __init__(self):
        self.alive_flag = True
        self.iters = 0
        self.n_ingress = 0
        self.ready = True
        self.shutdowns = []
        self.work = []

    def heartbeat(self):
        return self.alive_flag, self.iters, self.n_ingress

    def readiness(self):
        return self.ready, {}

    def alive(self):
        return self.alive_flag

    def shutdown(self, drain=True, timeout=None):
        self.shutdowns.append(drain)
        self.alive_flag = False

    def unresolved_work(self):
        return list(self.work)


class FakeReplica:
    def __init__(self, name, depth=0):
        self.name = name
        self.depth = depth
        self.service = FakeService()
        self.submitted = []

    def queue_depth(self):
        return self.depth

    def resident_models(self):
        return {"m"}

    def registered_models(self):
        return {"m"}

    def submit_many(self, requests):
        self.submitted.extend(requests)
        out = []
        for request in requests:
            ticket = ServiceTicket(request.request_id, "m")
            out.append(ticket)
        return out


# -- health hysteresis ------------------------------------------------


def test_supervisor_hysteresis_walks_states():
    """healthy -> degraded needs degraded_after consecutive slow
    probes; degraded -> healthy needs healthy_after good ones; a
    single bad probe never flips anything (the hysteresis point)."""
    replica = FakeReplica("r1")
    sup = FleetSupervisor(Router([replica]), degraded_after=2,
                          dead_after=2, healthy_after=2)

    def tick(advance=True):
        if advance:
            replica.service.iters += 1
        return sup.poll()["states"]["r1"]

    assert tick() == "healthy"
    # loop frozen with work queued: slow probes
    replica.service.n_ingress = 3
    assert tick(advance=False) == "healthy"   # slow x1: held
    assert tick(advance=False) == "degraded"  # slow x2: degraded
    # recovery: progress resumes, queue drains
    replica.service.n_ingress = 0
    assert tick() == "degraded"               # good x1: held
    assert tick() == "healthy"                # good x2: healed
    # a frozen loop with NO work queued is just idle, not slow
    assert tick(advance=False) == "healthy"


def test_supervisor_declares_death_and_fails_over():
    """dead_after down-probes declare death: the replica leaves the
    router, its unresolved work is harvested and re-placed on the
    survivor, and the supervision ledger records the failover."""
    r1, r2 = FakeReplica("r1"), FakeReplica("r2")
    router = Router([r1, r2])
    sup = FleetSupervisor(router, dead_after=2)
    stranded = Request(request_id="q1", x=None, model="m")
    ticket = ServiceTicket("q1", "m")
    r1.service.work = [("m", stranded, ticket)]
    r1.service.alive_flag = False

    first = sup.poll()
    assert first["states"]["r1"] == "degraded"  # down x1: held
    assert not first["failed_over"]
    second = sup.poll()
    assert second["states"]["r1"] == "dead"
    assert second["failed_over"] == [
        {"replica": "r1", "n_replaced": 1, "n_lost": 0}]
    assert [r.name for r in router.replicas] == ["r2"]
    assert [r.request_id for r in r2.submitted] == ["q1"]
    # re-placement chained the original ticket to the new one
    assert not ticket.done()
    summary = sup.summary()
    assert summary["n_failovers"] == 1
    assert summary["states"]["r1"] == "dead"


# -- failover re-placement against real services ----------------------


def test_crash_failover_resolves_every_ticket(srm_model, tmp_path):
    """A targeted replica_crash strands a submitted wave in r1's
    ingress (the loop dies mid-stall, before routing); the
    supervisor declares death, the router re-places the wave on r2,
    and EVERY original ticket resolves ok — zero lost tickets."""
    aot = str(tmp_path / "aot")
    r1 = _replica("r1", srm_model, aot=aot)
    r2 = _replica("r2", srm_model, aot=aot)
    router = Router([r1, r2])
    sup = FleetSupervisor(router, dead_after=1)
    gen = TrafficGenerator(srm_model, model_name="m", seed=0,
                           tr_choices=(8, 16))
    try:
        with faults.inject("slow_replica", times=1, leaf=1.5,
                           target="r1") as stall, \
                faults.inject("replica_crash",
                              target="r1") as crash:
            deadline = time.monotonic() + 30.0
            while stall.fired == 0:
                assert time.monotonic() < deadline, "no stall"
                time.sleep(0.001)
            # lands in ingress during the stall; the crash fires
            # in the SAME iteration, before the ingress drain
            tickets = r1.service.submit_many(
                gen.requests(6, deadline_s=60.0))
            while r1.service.alive():
                assert time.monotonic() < deadline, "no crash"
                time.sleep(0.001)
        assert crash.fired == 1
        actions = sup.poll()
        assert actions["failed_over"][0]["n_replaced"] == 6
        assert actions["failed_over"][0]["n_lost"] == 0
        records = [t.result(timeout=60) for t in tickets]
    finally:
        for replica in (r1, r2):
            replica.service.shutdown(drain=False)
    assert all(r.ok for r in records)
    assert router.summary()["routed"]["r2"] >= 6
    assert router.summary()["n_failed_over"] == 6


def test_failover_past_deadline_resolves_replica_lost():
    """Work already past its deadline is NOT re-placed: it resolves
    as a typed replica_lost record (reason deadline), and with no
    survivors at all everything resolves replica_lost — never
    silence, never a surprise re-execution."""
    survivor = FakeReplica("r2")
    router = Router([survivor])
    expired = Request(request_id="old", x=None, model="m",
                      submitted=100.0, deadline_s=1.0)
    fresh = Request(request_id="new", x=None, model="m",
                    submitted=100.0, deadline_s=50.0)
    t_old, t_new = ServiceTicket("old", "m"), ServiceTicket(
        "new", "m")
    out = router.failover([("r1", expired, t_old),
                           ("r1", fresh, t_new)],
                          source="r1", now=110.0)
    assert out == {"n_replaced": 1, "n_lost": 1}
    rec = t_old.result(timeout=1)
    assert not rec.ok and rec.error == "replica_lost"
    assert "r1" in rec.message
    assert [r.request_id for r in survivor.submitted] == ["new"]

    # no survivors left: everything is lost, typed, immediately
    router.remove_replica("r2")
    t2 = ServiceTicket("n2", "m")
    out = router.failover(
        [("r1", Request(request_id="n2", x=None, model="m"),
          t2)], source="r1")
    assert out == {"n_replaced": 0, "n_lost": 1}
    assert t2.result(timeout=1).error == "replica_lost"


# -- autoscaling ------------------------------------------------------


def test_supervisor_scales_up_and_down():
    """Queue pressure grows the fleet through the factory (bounded
    by max_replicas); scale_down_after consecutive idle polls drain
    the most recent joiner away (never below min_replicas)."""
    base = FakeReplica("r1", depth=0)
    router = Router([base])
    spawned = []

    def factory(name):
        replica = FakeReplica(name)
        spawned.append(replica)
        return replica

    sup = FleetSupervisor(router, factory=factory, min_replicas=1,
                          max_replicas=2, scale_up_depth=4.0,
                          scale_down_depth=1.0, scale_down_after=2)
    base.depth = 10
    first = sup.poll()
    assert first["scaled_up"] == ["auto1"]
    assert {r.name for r in router.replicas} == {"r1", "auto1"}
    # at max_replicas: pressure no longer grows the fleet
    assert sup.poll()["scaled_up"] == []

    base.depth = 0
    for replica in spawned:
        replica.service.iters += 1
    assert sup.poll()["scaled_down"] == []    # idle x1: held
    for replica in spawned:
        replica.service.iters += 1
    down = sup.poll()["scaled_down"]
    assert down == ["auto1"]                  # idle x2: drained
    assert spawned[0].service.shutdowns == [True]
    assert [r.name for r in router.replicas] == ["r1"]
    # at min_replicas: idleness never empties the fleet
    assert sup.poll()["scaled_down"] == []
    assert sup.poll()["scaled_down"] == []
    summary = sup.summary()
    assert summary["scaled_up"] == ["auto1"]
    assert summary["scaled_down"] == ["auto1"]


def test_supervisor_scales_up_on_shed_and_burn():
    """The other two /metrics signals: a shed-count delta since the
    last poll, and a burning admission SLO, each trigger scale-up
    even with shallow queues."""

    class FakeAdmission:
        def __init__(self):
            self.burn = False

        def burning(self):
            return self.burn

        def stats(self):
            return {}

    admission = FakeAdmission()
    router = Router([FakeReplica("r1")], admission=admission)
    sup = FleetSupervisor(router, factory=FakeReplica,
                          max_replicas=3, scale_up_depth=1000.0)
    assert sup.poll()["scaled_up"] == []
    with router._lock:
        router._n_shed += 5        # a shed wave landed
    assert sup.poll()["scaled_up"] == ["auto1"]
    assert sup.poll()["scaled_up"] == []      # delta consumed
    admission.burn = True
    assert sup.poll()["scaled_up"] == ["auto2"]


# -- drain-and-handoff resharding -------------------------------------


def test_reshard_replica_drain_and_handoff(srm_model):
    """reshard_replica detaches the replica, waits out the drain,
    re-lays residency out over the new device set, and re-attaches:
    requests before AND after see a whole model, and the residency
    charges the new device count afterwards."""
    r1 = _replica("r1", srm_model)
    router = Router([r1])
    sup = FleetSupervisor(router)
    gen = TrafficGenerator(srm_model, model_name="m", seed=1,
                           tr_choices=(8,))
    try:
        before = [t.result(timeout=60) for t in
                  router.submit_many(gen.requests(4))]
        dropped = sup.reshard_replica(
            "r1", devices=["hbm0", "hbm1"])
        assert dropped == ["m"]
        assert [r.name for r in router.replicas] == ["r1"]
        after = [t.result(timeout=60) for t in
                 router.submit_many(gen.requests(4, prefix="b"))]
    finally:
        r1.service.shutdown()
    assert all(r.ok for r in before + after)
    stats = r1.service.residency.stats()
    assert set(stats["per_device"]) == {"hbm0", "hbm1"}


def test_reshard_refuses_while_work_pending(srm_model):
    """ServeService.reshard is drain-gated: with work still queued
    it refuses (RuntimeError) instead of dropping a model out from
    under a queued request."""
    r1 = _replica("r1", srm_model)
    try:
        with faults.inject("slow_replica", times=1, leaf=1.0,
                           target="r1") as stall:
            deadline = time.monotonic() + 30.0
            while stall.fired == 0:
                assert time.monotonic() < deadline
                time.sleep(0.001)
            gen = TrafficGenerator(srm_model, model_name="m",
                                   seed=2, tr_choices=(8,))
            tickets = r1.service.submit_many(gen.requests(2))
            with pytest.raises(RuntimeError, match="drain"):
                r1.service.reshard(devices=["hbm0", "hbm1"])
        records = [t.result(timeout=60) for t in tickets]
    finally:
        r1.service.shutdown()
    assert all(r.ok for r in records)


# -- the scrape's typed unreachable state -----------------------------


def test_scrape_replica_state_unreachable():
    """ISSUE 16 satellite: a dead endpoint exhausts the bounded
    retries and comes back as a TYPED unreachable state (zeroed
    placement signals), not an exception mid-supervision-round."""
    state = scrape_replica_state("127.0.0.1:9", timeout=0.2,
                                 retries=1, backoff=0.0)
    assert state["state"] == "unreachable"
    assert "error" in state
    assert state["queue_depth"] == 0.0
    assert state["by_replica"] == {}


def test_scrape_replica_state_ok_has_state_field(srm_model):
    """The live-scrape dict now carries state=ok so supervision
    code can branch on one field for both outcomes."""
    res = ModelResidency(budget_bytes=1 << 30, policy=_policy(),
                         devices=["hbm0"])
    res.register("m", model=srm_model)
    with ServeService(res, default_model="m", name="rep1",
                      http_port=0) as svc:
        gen = TrafficGenerator(srm_model, model_name="m", seed=3,
                               tr_choices=(8,))
        for ticket in svc.submit_many(gen.requests(2)):
            assert ticket.result(timeout=60).ok
        port = svc.summary()["http_port"]
        state = scrape_replica_state(f"127.0.0.1:{port}")
    assert state["state"] == "ok"
    assert "rep1" in state["by_replica"]
