"""InferenceEngine behavior: the ISSUE 5 acceptance integration
(>= 64 mixed-shape requests against a reloaded SRM, bounded
retraces, result-or-error for every request) plus per-kind parity
with the estimators' own inference methods, poison isolation, flush
policy, and telemetry."""

import numpy as np
import pytest

from brainiak_tpu.obs import MemorySink, add_sink, metrics, \
    remove_sink
from brainiak_tpu.serve import (BucketPolicy, InferenceEngine,
                                Request, load_model, save_model)
from brainiak_tpu.serve.engine import (_eventseg_program,
                                       _iem_program, _rsrm_program,
                                       _srm_program)


def _clear_program_caches():
    for prog in (_srm_program, _rsrm_program, _eventseg_program,
                 _iem_program):
        prog.cache_clear()


def _srm_requests(model, n, tr_choices=(10, 25, 40, 70), seed=0):
    rng = np.random.RandomState(seed)
    counts = [w.shape[0] for w in model.w_]
    reqs = []
    for i in range(n):
        subject = i % len(counts)
        trs = tr_choices[i % len(tr_choices)]
        reqs.append(Request(
            request_id=f"r{i}",
            x=rng.randn(counts[subject], trs),
            subject=subject))
    return reqs


def test_acceptance_mixed_requests_reloaded_srm(srm_model,
                                                tmp_path):
    """ISSUE 5 acceptance: >= 64 mixed-shape requests against a
    save/load-round-tripped SRM complete with retraces <= distinct
    buckets and a result or structured error for every request."""
    path = str(tmp_path / "model.npz")
    save_model(srm_model, path)
    model = load_model(path)

    good = _srm_requests(model, 64)
    poison = [
        Request(request_id="nan", subject=0,
                x=np.full((model.w_[0].shape[0], 25), np.nan)),
        Request(request_id="badshape", subject=1,
                x=np.zeros((3, 25))),
        Request(request_id="badsubj", subject=99,
                x=np.zeros((model.w_[0].shape[0], 25))),
        Request(request_id="late", subject=0,
                x=np.zeros((model.w_[0].shape[0], 25)),
                deadline_s=0.0),
    ]
    requests = good[:32] + poison + good[32:]

    _clear_program_caches()
    metrics.reset()
    engine = InferenceEngine(
        model, policy=BucketPolicy(max_batch=16))
    records = engine.run(requests)

    # every request answered, in submission order
    assert len(records) == len(requests)
    assert [r.request_id for r in records] == \
        [r.request_id for r in requests]
    by_id = {r.request_id: r for r in records}
    assert by_id["nan"].error == "non_finite_input"
    assert by_id["badshape"].error == "invalid_shape"
    assert by_id["badsubj"].error == "invalid_subject"
    assert by_id["late"].error == "deadline_exceeded"
    assert all(by_id[r.request_id].ok for r in good)

    # results match the estimator's own transform bit-for-bit in
    # intent (allclose: the batched einsum may reassociate)
    for req in good[:8]:
        expected = model.w_[req.subject].T @ req.x
        got = by_id[req.request_id].result
        assert got.shape == expected.shape
        np.testing.assert_allclose(got, expected, atol=1e-5)

    summary = engine.summary()
    assert summary["n_requests"] == len(requests)
    assert summary["n_ok"] == 64
    assert summary["n_errors"] == 4
    # the acceptance bound: compiles <= distinct dispatched buckets,
    # i.e. no per-request recompiles
    retraces = metrics.counter("retrace_total").value(
        site="serve.srm")
    assert 0 < retraces <= len(summary["buckets"])
    assert summary["retrace_total"] == retraces
    assert 0.0 <= summary["padding_waste"] < 1.0
    assert summary["p99_latency_s"] >= summary["p50_latency_s"]


def test_engine_requires_fitted_kind():
    with pytest.raises(TypeError):
        InferenceEngine(object())


def test_detsrm_engine_matches_transform(detsrm_model):
    engine = InferenceEngine(detsrm_model)
    reqs = _srm_requests(detsrm_model, 6, seed=1)
    records = engine.run(reqs)
    assert engine.kind == "detsrm"
    for req, rec in zip(reqs, records):
        assert rec.ok
        np.testing.assert_allclose(
            rec.result, detsrm_model.w_[req.subject].T @ req.x,
            atol=1e-5)


def test_rsrm_engine_matches_transform(rsrm_model):
    engine = InferenceEngine(rsrm_model)
    rng = np.random.RandomState(2)
    counts = [w.shape[0] for w in rsrm_model.w_]
    reqs = [Request(request_id=f"r{i}",
                    x=rng.randn(counts[i % len(counts)], 12),
                    subject=i % len(counts))
            for i in range(5)]
    records = engine.run(reqs)
    X = [None] * len(rsrm_model.w_)
    for req, rec in zip(reqs, records):
        assert rec.ok
        r_got, s_got = rec.result
        X = [None] * len(rsrm_model.w_)
        X[req.subject] = req.x
        r_exp, s_exp = rsrm_model.transform(X)
        np.testing.assert_allclose(r_got, r_exp[req.subject],
                                   atol=1e-4)
        np.testing.assert_allclose(s_got, s_exp[req.subject],
                                   atol=1e-4)


def test_eventseg_engine_matches_find_events(eventseg_model):
    engine = InferenceEngine(eventseg_model)
    rng = np.random.RandomState(3)
    n_vox = eventseg_model.event_pat_.shape[0]
    # two T-groups -> two (exact-T) buckets, batched within a group
    reqs = [Request(request_id=f"r{i}",
                    x=rng.randn(20 if i % 2 else 28, n_vox))
            for i in range(6)]
    records = engine.run(reqs)
    for req, rec in zip(reqs, records):
        assert rec.ok
        seg_got, ll_got = rec.result
        seg_exp, ll_exp = eventseg_model.find_events(req.x)
        np.testing.assert_allclose(seg_got, seg_exp, atol=1e-5)
        assert abs(ll_got - ll_exp) < 1e-5 * max(1.0, abs(ll_exp))


def test_iem_engine_matches_predict(iem1d_model):
    engine = InferenceEngine(iem1d_model)
    rng = np.random.RandomState(4)
    n_vox = iem1d_model.W_.shape[0]
    reqs = [Request(request_id=f"r{i}",
                    x=rng.randn(5 + 3 * i, n_vox))
            for i in range(4)]
    records = engine.run(reqs)
    for req, rec in zip(reqs, records):
        assert rec.ok
        np.testing.assert_array_equal(rec.result,
                                      iem1d_model.predict(req.x))


def test_fcma_engine_matches_predict(fcma_models):
    logit, precomp, test_pairs = fcma_models
    for model in (logit, precomp):
        engine = InferenceEngine(model)
        reqs = [Request(request_id=f"r{i}", x=pair)
                for i, pair in enumerate(test_pairs)]
        records = engine.run(reqs)
        expected = model.predict(test_pairs)
        got = np.asarray([r.result for r in records])
        np.testing.assert_array_equal(got, expected)


def test_fcma_portioned_artifact_refused(fcma_models):
    """A precomputed-SVM model whose training features were
    discarded (portion-by-portion Gram) cannot serve predict; the
    engine refuses at construction with a clear error."""
    _, precomp, _ = fcma_models
    import copy
    crippled = copy.copy(precomp)
    crippled.training_data_ = None
    with pytest.raises(ValueError, match="cannot serve"):
        InferenceEngine(crippled, kind="fcma")


def test_poison_batch_isolated(srm_model, monkeypatch):
    """A batch whose dispatch raises falls back to per-request
    execution: the poison request alone gets an execution_failed
    record, its batchmates still get results."""
    engine = InferenceEngine(srm_model)
    op = engine.op
    real_dispatch = op.dispatch

    def sabotaged(reqs, key, b_pad):
        if any(r.request_id == "posion-like" for r in reqs) \
                and len(reqs) > 1:
            raise RuntimeError("batch-level explosion")
        if reqs[0].request_id == "posion-like" and len(reqs) == 1:
            raise RuntimeError("still poisoned alone")
        return real_dispatch(reqs, key, b_pad)

    monkeypatch.setattr(op, "dispatch", sabotaged)
    reqs = _srm_requests(srm_model, 4, tr_choices=(20,), seed=5)
    reqs.insert(2, Request(
        request_id="posion-like", subject=0,
        x=np.zeros((srm_model.w_[0].shape[0], 20))))
    mem = add_sink(MemorySink())
    try:
        records = engine.run(reqs)
    finally:
        remove_sink(mem)
    by_id = {r.request_id: r for r in records}
    assert by_id["posion-like"].error == "execution_failed"
    assert "still poisoned" in by_id["posion-like"].message
    assert sum(r.ok for r in records) == 4
    # the singleton re-dispatches carry the same serve.batch
    # span/histogram contract as the normal path: a poison-recovery
    # trace must show its isolated batches, not a telemetry hole
    isolated = [r for r in mem.records
                if r["kind"] == "span" and r["name"] == "serve.batch"
                and (r.get("attrs") or {}).get("isolated")]
    # 4 survivors + the poison retry (its span emits on the way out)
    assert len(isolated) == 5


def test_poison_isolation_adds_no_program_shapes(srm_model,
                                                 monkeypatch):
    """ISSUE 9 satellite: the singleton fallback re-pads to the
    FAILED dispatch's batch extent (the smallest admissible bucket
    this flush already resolved), so poison recovery mints ZERO new
    program shapes — `retrace_total{site=serve.srm}` stays at the
    distinct-bucket count instead of growing a fresh singleton
    shape per poisoned bucket."""
    engine = InferenceEngine(srm_model)
    op = engine.op
    real_dispatch = op.dispatch
    calls = []

    def sabotaged(reqs, key, b_pad):
        calls.append((key, b_pad, len(reqs)))
        if len(reqs) > 1:
            raise RuntimeError("batch-level explosion")
        return real_dispatch(reqs, key, b_pad)

    monkeypatch.setattr(op, "dispatch", sabotaged)
    reqs = _srm_requests(srm_model, 5, tr_choices=(20,), seed=5)
    records = engine.run(reqs)
    assert all(r.ok for r in records)
    # every singleton re-ran at the failed batch's extent (8 for a
    # 5-request flush), never a fresh b_pad=1 shape
    failed_key, failed_b_pad, _ = calls[0]
    assert failed_b_pad == 8
    assert all(b == failed_b_pad for _, b, n in calls[1:])
    assert {str(r.bucket) for r in records} \
        == {str(failed_key + (failed_b_pad,))}
    # at most one program shape for the whole poisoned round (0
    # when an earlier test already compiled this bucket: builder
    # caches are process-global)
    assert engine.summary()["retrace_total"] <= 1


def test_fail_pending_delivers_structured_records(srm_model):
    """fail_pending (the no-drain shutdown path) fails every queued
    request with the given status and empties the queues."""
    policy = BucketPolicy(max_batch=64, max_wait_s=60.0)
    engine = InferenceEngine(srm_model, policy=policy)
    reqs = _srm_requests(srm_model, 3, tr_choices=(20,))
    for req in reqs:
        assert engine.submit(req) is None
    assert engine.fail_pending("shutdown") == 3
    records = engine.drain()
    assert [r.error for r in records] == ["shutdown"] * 3
    assert engine.fail_pending() == 0  # queues are empty now



def test_flush_policy_max_batch_and_poll(srm_model):
    """A bucket flushes as soon as max_batch accumulates; poll()
    flushes an under-full bucket once its oldest request exceeds
    max_wait_s."""
    policy = BucketPolicy(max_batch=4, max_wait_s=10.0)
    engine = InferenceEngine(srm_model, policy=policy)
    reqs = _srm_requests(srm_model, 6, tr_choices=(20,), seed=6)
    for req in reqs[:3]:
        assert engine.submit(req) is None
    assert len(engine.records) == 0      # under-full, still queued
    engine.submit(reqs[3])
    assert len(engine.records) == 4      # max_batch flushed
    engine.submit(reqs[4])
    engine.poll()                        # not yet past max_wait
    assert len(engine.records) == 4
    engine.poll(now=reqs[4].submitted + 11.0)
    assert len(engine.records) == 5


def test_engine_emits_serve_telemetry(srm_model):
    """With an obs sink active, a drive emits serve.batch spans,
    serve.request span records, and the serve metrics."""
    mem = add_sink(MemorySink())
    try:
        engine = InferenceEngine(srm_model)
        engine.run(_srm_requests(srm_model, 5, tr_choices=(20, 40),
                                 seed=7))
    finally:
        remove_sink(mem)
    names = {(r["kind"], r["name"]) for r in mem.records}
    assert ("span", "serve.batch") in names
    assert ("span", "serve.request") in names
    metric_names = {r["name"] for r in mem.records
                    if r["kind"] == "metric"}
    assert {"serve_queue_depth", "serve_request_seconds",
            "serve_batch_seconds",
            "serve_padding_waste_ratio",
            "serve_requests_total"} <= metric_names
    # every record in the trace validates against the obs schema
    from brainiak_tpu.obs import validate_record
    for rec in mem.records:
        assert validate_record(rec) == [], rec


def test_drain_releases_records(srm_model):
    """Online mode: drain() hands back completed records and drops
    the engine's references, so a long-lived server's memory is the
    queued work, not the history."""
    engine = InferenceEngine(srm_model)
    reqs = _srm_requests(srm_model, 3, tr_choices=(20,), seed=8)
    engine.run(reqs)
    drained = engine.drain()
    assert [r.request_id for r in drained] == \
        [r.request_id for r in reqs]
    assert engine.records == []
    assert engine.drain() == []
    # the engine keeps serving after a drain
    more = engine.run(_srm_requests(srm_model, 2,
                                    tr_choices=(20,), seed=9))
    assert len(more) == 2 and all(r.ok for r in more)


def test_run_excludes_earlier_queued_submits(srm_model):
    """run()'s flush may complete requests queued by earlier
    submit() calls, but its return covers exactly the passed
    requests — the earlier work stays in records for drain()."""
    policy = BucketPolicy(max_batch=8, max_wait_s=60.0)
    engine = InferenceEngine(srm_model, policy=policy)
    early = _srm_requests(srm_model, 1, tr_choices=(20,), seed=20)[0]
    early.request_id = "early"
    assert engine.submit(early) is None   # under-full, queued
    later = _srm_requests(srm_model, 2, tr_choices=(20,), seed=21)
    records = engine.run(later)
    assert [r.request_id for r in records] == \
        [r.request_id for r in later]
    # the earlier submit's record is delivered via drain, once
    drained = engine.drain()
    assert "early" in {r.request_id for r in drained}


def test_submit_rejection_delivered_exactly_once(srm_model):
    """A submit-time rejection is returned synchronously and must
    NOT be re-delivered by drain(); it still counts in summary()."""
    engine = InferenceEngine(srm_model)
    rec = engine.submit(Request(request_id="bad", subject=0,
                                x=np.zeros((3, 10))))
    assert rec is not None and rec.error == "invalid_shape"
    assert engine.records == []
    assert engine.drain() == []
    summ = engine.summary()
    assert summ["n_requests"] == 1
    assert summ["n_errors"] == 1
    assert summ["errors_by_code"] == {"invalid_shape": 1}


def test_fcma_poison_batch_fails_as_unit(fcma_models, monkeypatch):
    """FCMA predictions are batch-composition-dependent, so a failed
    batch must NOT fall back to singleton re-runs (that would
    silently change the survivors' answers): the whole batch gets
    execution_failed records."""
    logit, _, test_pairs = fcma_models
    engine = InferenceEngine(logit)

    def boom(reqs, key, b_pad):
        raise RuntimeError("clf exploded")

    monkeypatch.setattr(engine.op, "dispatch", boom)
    reqs = [Request(request_id=f"r{i}", x=pair)
            for i, pair in enumerate(test_pairs[:4])]
    records = engine.run(reqs)
    assert len(records) == 4
    assert all(not r.ok and r.error == "execution_failed"
               for r in records)
    assert "batch fails as a unit" in records[0].message


def test_fcma_rejects_wrong_region_geometry(fcma_models):
    """Per-region voxel counts are validated (order-insensitive),
    not just their product: a (T,1)x(T,25) pair against a (5,5)
    model has matching feature count but alien geometry."""
    logit, _, test_pairs = fcma_models
    engine = InferenceEngine(logit)
    t = test_pairs[0][0].shape[0]
    n_feat = logit.num_features_
    rec = engine.run([Request(
        request_id="alien",
        x=(np.zeros((t, 1), np.float32),
           np.zeros((t, n_feat), np.float32)))])[0]
    assert not rec.ok and rec.error == "invalid_shape"
    # swapped order of a VALID pair is accepted (mirrors
    # _stack_pairs' orientation swap)
    x1, x2 = test_pairs[0]
    ok = engine.run([Request(request_id="swap", x=(x2, x1))])[0]
    assert ok.ok


def test_fcma_mixed_pair_order_in_one_batch():
    """validate() accepts either region order, so one batch can mix
    (small, large) and (large, small) pairs; dispatch canonicalizes
    per pair (larger region first, like _stack_pairs on a lone
    request) instead of letting np.stack fail the batch as a unit."""
    import math

    from scipy.stats.mstats import zscore
    from sklearn.linear_model import LogisticRegression

    from brainiak_tpu.fcma.classifier import Classifier

    rng = np.random.RandomState(7)

    def region(idx, num_voxels, rows=12):
        mat = rng.rand(rows, num_voxels).astype(np.float32)
        if idx % 2 == 0:
            mat = np.sort(mat, axis=0)
        mat = np.nan_to_num(zscore(mat, axis=0, ddof=0))
        return mat / math.sqrt(mat.shape[0])

    train = [(region(i, 7), region(i, 5)) for i in range(12)]
    model = Classifier(LogisticRegression(solver="liblinear"),
                       epochs_per_subj=4)
    model.fit(train, [0, 1] * 6)

    test = [(region(i, 7), region(i, 5)) for i in range(12, 18)]
    reqs = [Request(request_id=f"r{i}",
                    x=pair if i % 2 == 0 else (pair[1], pair[0]))
            for i, pair in enumerate(test)]
    records = InferenceEngine(model).run(reqs)
    assert all(r.ok for r in records), \
        [(r.request_id, r.error, r.message) for r in records]
    np.testing.assert_array_equal(
        np.asarray([r.result for r in records]),
        model.predict(test))


def test_malformed_payload_yields_invalid_payload_record(srm_model):
    """A payload weird enough to crash validation itself (ragged
    nested list, non-int subject) still yields exactly one
    structured record instead of crashing the engine."""
    engine = InferenceEngine(srm_model)
    records = engine.run([
        Request(request_id="ragged", x=[[1.0, 2.0], [3.0]],
                subject=0),
        Request(request_id="strsubj",
                x=np.zeros((srm_model.w_[0].shape[0], 20)),
                subject="zero"),
    ])
    assert [r.error for r in records] == \
        ["invalid_payload"] * 2
    assert engine.summary()["n_requests"] == 2
    assert engine.summary()["n_errors"] == 2


def test_duplicate_request_ids_keep_submission_order(srm_model):
    """Results are ordered by the per-submission index, not the
    user-supplied id, so duplicate ids cannot misorder records."""
    v = srm_model.w_[0].shape[0]
    reqs = [Request(request_id="dup", subject=0,
                    x=np.full((v, 20), float(i)))
            for i in range(3)]
    records = InferenceEngine(srm_model).run(reqs)
    assert [r.request_id for r in records] == ["dup"] * 3
    assert [r.seq for r in records] == [0, 1, 2]
    for i, rec in enumerate(records):
        expected = srm_model.w_[0].T @ reqs[i].x
        np.testing.assert_allclose(rec.result, expected, atol=1e-5)


def test_acceptance_mixed_scoring_reloaded_encoding(encoding_model):
    """ISSUE 7 acceptance: 64 mixed-TR held-out-scan scoring
    requests against a reloaded ``ridge_encoding`` artifact — every
    request answered, retraces bounded by the bucket count, and
    per-request per-voxel correlations matching the estimator's own
    host scoring (TR padding masked before the reduction)."""
    import io

    from brainiak_tpu.serve import save_model_bytes

    model = load_model(io.BytesIO(save_model_bytes(encoding_model)))
    rng = np.random.RandomState(0)
    f, v = model.W_.shape
    reqs, host = [], []
    for i in range(64):
        trs = (18, 30, 50, 70)[i % 4]
        x = rng.randn(trs, f).astype(np.float32)
        y = (model.predict(x)
             + rng.randn(trs, v)).astype(np.float32)
        reqs.append(Request(request_id=f"r{i}", x=(x, y)))
        host.append(model.score(x, y))
    engine = InferenceEngine(model)
    records = engine.run(reqs)
    assert len(records) == 64 and all(r.ok for r in records)
    summary = engine.summary()
    assert summary["kind"] == "ridge_encoding"
    assert summary["n_ok"] == 64
    # the acceptance bound: compiles <= distinct dispatched buckets
    assert summary["retrace_total"] <= len(summary["buckets"])
    for rec, expect in zip(records, host):
        np.testing.assert_allclose(rec.result, expect, rtol=1e-4,
                                   atol=1e-5)


def test_encoding_engine_banded_and_validation(
        banded_encoding_model):
    """The banded subclass serves through the same op (its predict
    surface is the same affine map), and malformed scoring payloads
    produce structured error records, not crashes."""
    model = banded_encoding_model
    rng = np.random.RandomState(3)
    f, v = model.W_.shape
    x = rng.randn(20, f).astype(np.float32)
    y = (model.predict(x) + rng.randn(20, v)).astype(np.float32)
    engine = InferenceEngine(model)
    ok = engine.run([Request(request_id="good", x=(x, y))])[0]
    assert ok.ok
    np.testing.assert_allclose(ok.result, model.score(x, y),
                               rtol=1e-4, atol=1e-5)
    bad = [
        Request(request_id="notpair", x=x),
        Request(request_id="badf", x=(x[:, :-1], y)),
        Request(request_id="badv", x=(x, y[:, :-1])),
        Request(request_id="short", x=(x[:1], y[:1])),
        Request(request_id="nan",
                x=(np.full_like(x, np.nan), y)),
    ]
    records = engine.run(bad)
    assert [r.ok for r in records] == [False] * 5
    assert {r.error for r in records} == {"invalid_shape",
                                          "non_finite_input"}
