"""Bucketing policy + request codec unit tests."""

import numpy as np
import pytest

from brainiak_tpu.serve import (BucketPolicy, Request, bucket_length,
                                load_requests, pad_axis,
                                save_requests)


def test_bucket_length_powers_of_two():
    assert bucket_length(1) == 16      # floor
    assert bucket_length(16) == 16
    assert bucket_length(17) == 32
    assert bucket_length(100) == 128
    assert bucket_length(128) == 128
    assert bucket_length(3, floor=1) == 4
    assert bucket_length(1, floor=1) == 1


def test_pad_axis():
    x = np.arange(6.0).reshape(2, 3)
    padded = pad_axis(x, 1, 8)
    assert padded.shape == (2, 8)
    np.testing.assert_array_equal(padded[:, :3], x)
    assert not padded[:, 3:].any()
    assert pad_axis(x, 0, 2) is not None  # no-op path
    with pytest.raises(ValueError):
        pad_axis(x, 1, 2)


def test_policy_batch_bucket():
    policy = BucketPolicy(max_batch=64)
    assert policy.batch_bucket(1) == 1
    assert policy.batch_bucket(3) == 4
    assert policy.batch_bucket(64) == 64
    # never beyond the max-batch power of two
    assert policy.batch_bucket(70) == 64


def test_request_deadline_expiry():
    req = Request(request_id="r", x=np.zeros((2, 2)),
                  deadline_s=0.5, submitted=100.0)
    assert not req.expired(now=100.4)
    assert req.expired(now=100.6)
    # no deadline / not yet submitted: never expired
    assert not Request(request_id="r", x=None).expired()


def test_request_codec_roundtrip(tmp_path):
    path = str(tmp_path / "reqs.npz")
    payloads = [np.random.randn(4, 7), np.random.randn(4, 9),
                (np.random.randn(5, 3), np.random.randn(5, 4))]
    save_requests(path, payloads, subjects=[1, None, None],
                  deadlines=[None, 0.25, None],
                  ids=["a", "b", "c"])
    back = load_requests(path)
    assert [r.request_id for r in back] == ["a", "b", "c"]
    np.testing.assert_array_equal(back[0].x, payloads[0])
    assert back[0].subject == 1 and back[0].deadline_s is None
    assert back[1].subject is None and back[1].deadline_s == 0.25
    assert isinstance(back[2].x, tuple) and len(back[2].x) == 2
    np.testing.assert_array_equal(back[2].x[1], payloads[2][1])
    # no models= passed: the routing field stays unset
    assert [r.model for r in back] == [None, None, None]


def test_request_codec_carries_model_routing(tmp_path):
    """ISSUE 9: per-request model names (the multi-model `service`
    routing key) round-trip through the npz codec, None omitted."""
    path = str(tmp_path / "reqs.npz")
    payloads = [np.random.randn(4, 7), np.random.randn(4, 9)]
    save_requests(path, payloads, ids=["a", "b"],
                  models=["subj01", None])
    back = load_requests(path)
    assert back[0].model == "subj01"
    assert back[1].model is None
