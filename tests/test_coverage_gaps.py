"""Direct tests for public surface that was only exercised indirectly:
conditional matrix-normal likelihoods, masked Kronecker solves, mesh
helpers, the profiler context, and the condition-spec containers
(reference behaviors: matnormal_likelihoods.py:318-429,
kronecker_solvers.py:150-330, image.py:51-105)."""

import os

import jax
import jax.numpy as jnp
import numpy as np
from scipy.stats import multivariate_normal

from brainiak_tpu.matnormal.covs import (CovIdentity,
                                         CovUnconstrainedCholesky)
from brainiak_tpu.matnormal.matnormal_likelihoods import (
    matnorm_logp, matnorm_logp_conditional_col,
    matnorm_logp_conditional_row)
from brainiak_tpu.parallel.mesh import (make_mesh, shard_along,
                                        subject_voxel_mesh)
from brainiak_tpu.utils.kronecker_solvers import (
    kron_mult, solve_lower_triangular_masked_kron,
    solve_upper_triangular_masked_kron)

RNG = np.random.RandomState(0)


def _spd(n):
    a = RNG.randn(n, n)
    return a @ a.T + n * np.eye(n)


def _dense_logp(x, row_sigma, col_sigma):
    """Oracle: vec(X) ~ N(0, col_sigma ⊗ row_sigma)."""
    full = np.kron(np.asarray(col_sigma), np.asarray(row_sigma))
    return multivariate_normal.logpdf(
        np.asarray(x).flatten(order='F'), mean=None, cov=full)


def test_conditional_row_logp_matches_dense_oracle():
    """Row covariance Σ − A Q⁻¹ Aᵀ via the inversion/determinant lemmas
    must equal the dense conditional covariance density."""
    n, m, p = 5, 3, 2
    sigma_full = _spd(n + p)
    sigma = sigma_full[:n, :n]
    a = sigma_full[:n, n:]
    q = sigma_full[n:, n:]
    col = _spd(m)
    x = RNG.randn(n, m)

    row_cov = CovUnconstrainedCholesky(Sigma=sigma)
    row_params = row_cov.init_params()
    col_cov = CovUnconstrainedCholesky(Sigma=col)
    col_params = col_cov.init_params()
    q_cov = CovUnconstrainedCholesky(Sigma=q)
    q_params = q_cov.init_params()

    got = float(matnorm_logp_conditional_row(
        jnp.asarray(x), row_cov, row_params, col_cov, col_params,
        jnp.asarray(a), q_cov, q_params))
    cond_sigma = sigma - a @ np.linalg.solve(q, a.T)
    want = _dense_logp(x, cond_sigma, col)
    assert np.isclose(got, want, rtol=1e-6)


def test_conditional_col_logp_matches_dense_oracle():
    n, m, p = 3, 5, 2
    col_full = _spd(m + p)
    col = col_full[:m, :m]
    a = col_full[:m, m:]
    q = col_full[m:, m:]
    row = _spd(n)
    x = RNG.randn(n, m)

    row_cov = CovUnconstrainedCholesky(Sigma=row)
    row_params = row_cov.init_params()
    col_cov = CovUnconstrainedCholesky(Sigma=col)
    col_params = col_cov.init_params()
    q_cov = CovUnconstrainedCholesky(Sigma=q)
    q_params = q_cov.init_params()

    got = float(matnorm_logp_conditional_col(
        jnp.asarray(x), row_cov, row_params, col_cov, col_params,
        jnp.asarray(a), q_cov, q_params))
    cond_col = col - a @ np.linalg.solve(q, a.T)
    want = _dense_logp(x, row, cond_col)
    assert np.isclose(got, want, rtol=1e-6)


def test_unconditional_logp_identity_cov():
    n, m = 4, 3
    x = RNG.randn(n, m)
    row_cov = CovIdentity(size=n)
    col_cov = CovIdentity(size=m)
    got = float(matnorm_logp(jnp.asarray(x), row_cov,
                             row_cov.init_params(),
                             col_cov, col_cov.init_params()))
    want = _dense_logp(x, np.eye(n), np.eye(m))
    assert np.isclose(got, want, rtol=1e-6)


def test_masked_kron_solves_match_dense():
    """Masked Kronecker triangular solves equal the dense solve on the
    unmasked principal submatrix, zero elsewhere (reference
    kronecker_solvers.py:150-269)."""
    l1 = np.linalg.cholesky(_spd(2))
    l2 = np.linalg.cholesky(_spd(3))
    ls = [jnp.asarray(l1), jnp.asarray(l2)]
    dense = np.kron(l1, l2)
    y = RNG.randn(6, 2)
    mask = np.array([1, 0, 1, 1, 0, 1])
    idx = np.where(mask)[0]

    got = np.asarray(solve_lower_triangular_masked_kron(ls,
                                                        jnp.asarray(y),
                                                        mask))
    want = np.zeros_like(y)
    want[idx] = np.linalg.solve(dense[np.ix_(idx, idx)], y[idx])
    assert np.allclose(got, want, atol=1e-8)

    got_u = np.asarray(solve_upper_triangular_masked_kron(
        ls, jnp.asarray(y), mask))
    want_u = np.zeros_like(y)
    want_u[idx] = np.linalg.solve(dense[np.ix_(idx, idx)].T, y[idx])
    assert np.allclose(got_u, want_u, atol=1e-8)

    # sanity on the unmasked primitive against the dense Kron product
    x = RNG.randn(6, 2)
    assert np.allclose(np.asarray(kron_mult(ls, jnp.asarray(x))),
                       dense @ x, atol=1e-8)


def test_subject_voxel_mesh_and_shard_along():
    mesh = subject_voxel_mesh(4, 2)
    assert mesh.axis_names == ('subject', 'voxel')
    assert mesh.devices.shape == (4, 2)
    arr = jnp.arange(8.0 * 6).reshape(8, 6)
    sharded = shard_along(arr, mesh, 'subject', 0)
    assert sharded.sharding.spec == jax.sharding.PartitionSpec(
        'subject', None)
    np.testing.assert_array_equal(np.asarray(sharded), np.asarray(arr))
    # default: all devices on the subject axis
    mesh1 = subject_voxel_mesh()
    assert mesh1.devices.size == len(jax.devices())

    mesh2 = make_mesh(('subject',), (len(jax.devices()),))
    assert mesh2.axis_names == ('subject',)


def test_device_trace_writes_profile(tmp_path):
    from brainiak_tpu.obs import device_trace

    log_dir = str(tmp_path / "trace")
    with device_trace(log_dir):
        x = jnp.ones((32, 32))
        (x @ x).block_until_ready()
    written = []
    for root, _, files in os.walk(log_dir):
        written.extend(files)
    assert written, "profiler trace produced no files"


def test_condition_spec_extract_labels():
    from brainiak_tpu.image import SingleConditionSpec

    spec = np.zeros((3, 4, 10))
    for epoch, cond in enumerate([2, 0, 1, 0]):
        spec[cond, epoch, 2:6] = 1
    labels = spec.view(SingleConditionSpec).extract_labels()
    np.testing.assert_array_equal(labels, [2, 0, 1, 0])


# ---- round-3 additions: paths the suite only reached via subprocesses

def test_nifti_qform_affine_roundtrip(tmp_path):
    """The qform quaternion branch of the own NIfTI codec: a header
    with qform_code>0 and sform_code=0 reconstructs the rotation from
    the stored quaternion (NIfTI-1 method 2)."""
    import gzip
    import struct

    from brainiak_tpu import nifti

    data = np.arange(24, dtype=np.float32).reshape(2, 3, 4)
    path = str(tmp_path / "q.nii")
    nifti.save(nifti.NiftiImage(data, np.eye(4)), path)
    raw = bytearray(open(path, "rb").read())
    # qform_code=1, sform_code=0; quaternion for a 90-degree rotation
    # about z: (a, b, c, d) = (cos45, 0, 0, sin45)
    struct.pack_into("<2h", raw, 252, 1, 0)
    struct.pack_into("<3f", raw, 256, 0.0, 0.0, np.sqrt(0.5))
    struct.pack_into("<3f", raw, 268, 7.0, 8.0, 9.0)
    qpath = str(tmp_path / "q2.nii.gz")
    with gzip.open(qpath, "wb") as f:
        f.write(bytes(raw))
    img = nifti.load(qpath)
    want_rot = np.array([[0.0, -1.0, 0.0],
                         [1.0, 0.0, 0.0],
                         [0.0, 0.0, 1.0]])
    np.testing.assert_allclose(img.affine[:3, :3], want_rot, atol=1e-6)
    np.testing.assert_allclose(img.affine[:3, 3], [7.0, 8.0, 9.0])
    np.testing.assert_array_equal(np.asarray(img.dataobj), data)


def test_realtime_generator_cli_main(tmp_path, monkeypatch):
    """The argparse entry point (the package's one CLI, reference
    fmrisim_real_time_generator.py:536-601) runs in-process."""
    import sys as _sys

    from brainiak_tpu.utils import fmrisim_real_time_generator as rtg

    out_dir = str(tmp_path / "rt")
    monkeypatch.setattr(_sys, "argv", [
        "fmrisim_real_time_generator", "-o", out_dir,
        "--numTRs", "12", "--event-duration", "4", "--isi", "2",
        "--burn-in", "2", "--trDuration", "2"])
    rtg.main()
    vols = [f for f in os.listdir(out_dir) if f.startswith("rt_")]
    assert len(vols) == 12
    labels = np.load(os.path.join(out_dir, "labels.npy"))
    assert labels.shape[0] == 12


def test_realtime_generator_dicom_requires_pydicom(tmp_path):
    """Without pydicom the save_dicom path must fail loudly, not write
    garbage."""
    import importlib.util

    import pytest as _pytest

    from brainiak_tpu.utils import fmrisim_real_time_generator as rtg

    if importlib.util.find_spec("pydicom") is not None:
        _pytest.skip("pydicom installed; error path not reachable")
    with _pytest.raises(ImportError, match="pydicom"):
        rtg._save_volume(np.zeros((4, 4, 4)),
                         str(tmp_path / "v.dcm"), save_dicom=True)


def test_fmrisim_temporal_noise_components():
    """physiological + task temporal components mix into the noise
    volume (reference fmrisim.py:1782-1906)."""
    from brainiak_tpu.utils import fmrisim as sim

    np.random.seed(0)
    dims = np.array([6, 6, 6])
    mask, template = sim.mask_brain(dims, mask_self=False)
    stim = np.zeros(20)
    stim[5:10] = 1.0
    nd = sim._noise_dict_update({
        "physiological_sigma": 1.0, "task_sigma": 1.0,
        "auto_reg_sigma": 1.0, "drift_sigma": 1.0})
    noise = sim._generate_noise_temporal(stim, 2.0, dims, template,
                                         mask, nd)
    assert noise.shape == (6, 6, 6, 20)
    assert np.isfinite(noise).all() and noise.std() > 0


def test_fmrisim_fit_temporal_iterates():
    """The SFNR fitting loop converges (or clamps) rather than running
    away (reference fmrisim.py:2613-2831)."""
    from brainiak_tpu.utils import fmrisim as sim

    np.random.seed(1)
    dims = np.array([8, 8, 8])
    mask, template = sim.mask_brain(dims, mask_self=False)
    trs = 15
    stim = np.zeros(trs)
    nd = sim._noise_dict_update({"sfnr": 50, "snr": 30, "matched": 1})
    noise = np.random.randn(8, 8, 8, trs) + \
        (template * nd["max_activity"])[..., None]
    drift = np.zeros((8, 8, 8, trs))
    fitted = sim._fit_temporal(
        noise, mask, template, stim, 2.0, spatial_sd=5.0,
        temporal_proportion=0.5, temporal_sd=10.0, drift_noise=drift,
        noise_dict=nd, fit_thresh=0.05, fit_delta=0.5, iterations=3)
    assert fitted.shape == noise.shape
    assert np.isfinite(fitted).all()


def test_fmrisim_rf_responses_direct():
    """generate_1d_rf_responses end-to-end in-process (the examples
    exercise it only in subprocesses)."""
    from brainiak_tpu.utils import fmrisim as sim

    np.random.seed(2)
    rfs, tuning = sim.generate_1d_gaussian_rfs(
        10, 180, (0, 179), rf_size=20, random_tuning=False)
    resp = sim.generate_1d_rf_responses(
        rfs, np.array([0.0, 45.0, 90.0]), 180, (0, 179),
        trial_noise=0.05)
    assert resp.shape == (10, 3)
    assert np.isfinite(resp).all()
    # evenly-spaced non-random tuning: each trial drives the voxel
    # tuned nearest to it hardest (up to the noise floor)
    for t, stim in enumerate([0.0, 45.0, 90.0]):
        best_voxel = int(np.argmax(resp[:, t]))
        assert abs(tuning[best_voxel] - stim) <= \
            np.min(np.abs(np.asarray(tuning) - stim)) + 18


def test_iem2d_param_validation_and_get_params():
    from brainiak_tpu.reconstruct.iem import InvertedEncoding2D

    model = InvertedEncoding2D(stim_xlim=[-5, 5], stim_ylim=[-5, 5],
                               stimulus_resolution=10)
    model.define_basis_functions_sqgrid(4)
    params = model.get_params()
    assert params["channels"] is not None
    assert params["xp"].shape == (10, 10)  # the pixel meshgrid
    # channel/pixel mismatch must fail loudly at fit time
    import pytest as _pytest

    model2 = InvertedEncoding2D(stim_xlim=[-5, 5], stim_ylim=[-5, 5],
                                stimulus_resolution=10, stim_radius=1.0)
    model2.define_basis_functions_sqgrid(4)
    model2.channels = model2.channels[:, :50]
    with _pytest.raises(ValueError, match="pixels"):
        model2.fit(np.random.randn(20, 8),
                   np.random.rand(20, 2) * 4 - 2)
