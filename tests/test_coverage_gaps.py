"""Direct tests for public surface that was only exercised indirectly:
conditional matrix-normal likelihoods, masked Kronecker solves, mesh
helpers, the profiler context, and the condition-spec containers
(reference behaviors: matnormal_likelihoods.py:318-429,
kronecker_solvers.py:150-330, image.py:51-105)."""

import os

import jax
import jax.numpy as jnp
import numpy as np
from scipy.stats import multivariate_normal

from brainiak_tpu.matnormal.covs import (CovIdentity,
                                         CovUnconstrainedCholesky)
from brainiak_tpu.matnormal.matnormal_likelihoods import (
    matnorm_logp, matnorm_logp_conditional_col,
    matnorm_logp_conditional_row)
from brainiak_tpu.parallel.mesh import (make_mesh, shard_along,
                                        subject_voxel_mesh)
from brainiak_tpu.utils.kronecker_solvers import (
    kron_mult, solve_lower_triangular_masked_kron,
    solve_upper_triangular_masked_kron)

RNG = np.random.RandomState(0)


def _spd(n):
    a = RNG.randn(n, n)
    return a @ a.T + n * np.eye(n)


def _dense_logp(x, row_sigma, col_sigma):
    """Oracle: vec(X) ~ N(0, col_sigma ⊗ row_sigma)."""
    full = np.kron(np.asarray(col_sigma), np.asarray(row_sigma))
    return multivariate_normal.logpdf(
        np.asarray(x).flatten(order='F'), mean=None, cov=full)


def test_conditional_row_logp_matches_dense_oracle():
    """Row covariance Σ − A Q⁻¹ Aᵀ via the inversion/determinant lemmas
    must equal the dense conditional covariance density."""
    n, m, p = 5, 3, 2
    sigma_full = _spd(n + p)
    sigma = sigma_full[:n, :n]
    a = sigma_full[:n, n:]
    q = sigma_full[n:, n:]
    col = _spd(m)
    x = RNG.randn(n, m)

    row_cov = CovUnconstrainedCholesky(Sigma=sigma)
    row_params = row_cov.init_params()
    col_cov = CovUnconstrainedCholesky(Sigma=col)
    col_params = col_cov.init_params()
    q_cov = CovUnconstrainedCholesky(Sigma=q)
    q_params = q_cov.init_params()

    got = float(matnorm_logp_conditional_row(
        jnp.asarray(x), row_cov, row_params, col_cov, col_params,
        jnp.asarray(a), q_cov, q_params))
    cond_sigma = sigma - a @ np.linalg.solve(q, a.T)
    want = _dense_logp(x, cond_sigma, col)
    assert np.isclose(got, want, rtol=1e-6)


def test_conditional_col_logp_matches_dense_oracle():
    n, m, p = 3, 5, 2
    col_full = _spd(m + p)
    col = col_full[:m, :m]
    a = col_full[:m, m:]
    q = col_full[m:, m:]
    row = _spd(n)
    x = RNG.randn(n, m)

    row_cov = CovUnconstrainedCholesky(Sigma=row)
    row_params = row_cov.init_params()
    col_cov = CovUnconstrainedCholesky(Sigma=col)
    col_params = col_cov.init_params()
    q_cov = CovUnconstrainedCholesky(Sigma=q)
    q_params = q_cov.init_params()

    got = float(matnorm_logp_conditional_col(
        jnp.asarray(x), row_cov, row_params, col_cov, col_params,
        jnp.asarray(a), q_cov, q_params))
    cond_col = col - a @ np.linalg.solve(q, a.T)
    want = _dense_logp(x, row, cond_col)
    assert np.isclose(got, want, rtol=1e-6)


def test_unconditional_logp_identity_cov():
    n, m = 4, 3
    x = RNG.randn(n, m)
    row_cov = CovIdentity(size=n)
    col_cov = CovIdentity(size=m)
    got = float(matnorm_logp(jnp.asarray(x), row_cov,
                             row_cov.init_params(),
                             col_cov, col_cov.init_params()))
    want = _dense_logp(x, np.eye(n), np.eye(m))
    assert np.isclose(got, want, rtol=1e-6)


def test_masked_kron_solves_match_dense():
    """Masked Kronecker triangular solves equal the dense solve on the
    unmasked principal submatrix, zero elsewhere (reference
    kronecker_solvers.py:150-269)."""
    l1 = np.linalg.cholesky(_spd(2))
    l2 = np.linalg.cholesky(_spd(3))
    ls = [jnp.asarray(l1), jnp.asarray(l2)]
    dense = np.kron(l1, l2)
    y = RNG.randn(6, 2)
    mask = np.array([1, 0, 1, 1, 0, 1])
    idx = np.where(mask)[0]

    got = np.asarray(solve_lower_triangular_masked_kron(ls,
                                                        jnp.asarray(y),
                                                        mask))
    want = np.zeros_like(y)
    want[idx] = np.linalg.solve(dense[np.ix_(idx, idx)], y[idx])
    assert np.allclose(got, want, atol=1e-8)

    got_u = np.asarray(solve_upper_triangular_masked_kron(
        ls, jnp.asarray(y), mask))
    want_u = np.zeros_like(y)
    want_u[idx] = np.linalg.solve(dense[np.ix_(idx, idx)].T, y[idx])
    assert np.allclose(got_u, want_u, atol=1e-8)

    # sanity on the unmasked primitive against the dense Kron product
    x = RNG.randn(6, 2)
    assert np.allclose(np.asarray(kron_mult(ls, jnp.asarray(x))),
                       dense @ x, atol=1e-8)


def test_subject_voxel_mesh_and_shard_along():
    mesh = subject_voxel_mesh(4, 2)
    assert mesh.axis_names == ('subject', 'voxel')
    assert mesh.devices.shape == (4, 2)
    arr = jnp.arange(8.0 * 6).reshape(8, 6)
    sharded = shard_along(arr, mesh, 'subject', 0)
    assert sharded.sharding.spec == jax.sharding.PartitionSpec(
        'subject', None)
    np.testing.assert_array_equal(np.asarray(sharded), np.asarray(arr))
    # default: all devices on the subject axis
    mesh1 = subject_voxel_mesh()
    assert mesh1.devices.size == len(jax.devices())

    mesh2 = make_mesh(('subject',), (len(jax.devices()),))
    assert mesh2.axis_names == ('subject',)


def test_device_trace_writes_profile(tmp_path):
    from brainiak_tpu.utils.profiling import device_trace

    log_dir = str(tmp_path / "trace")
    with device_trace(log_dir):
        x = jnp.ones((32, 32))
        (x @ x).block_until_ready()
    written = []
    for root, _, files in os.walk(log_dir):
        written.extend(files)
    assert written, "profiler trace produced no files"


def test_condition_spec_extract_labels():
    from brainiak_tpu.image import SingleConditionSpec

    spec = np.zeros((3, 4, 10))
    for epoch, cond in enumerate([2, 0, 1, 0]):
        spec[cond, epoch, 2:6] = 1
    labels = spec.view(SingleConditionSpec).extract_labels()
    np.testing.assert_array_equal(labels, [2, 0, 1, 0])
