import jax.numpy as jnp
import numpy as np
import pytest

from brainiak_tpu.matnormal.covs import (
    CovAR1,
    CovDiagonal,
    CovDiagonalGammaPrior,
    CovIdentity,
    CovIsotropic,
    CovKroneckerFactored,
    CovUnconstrainedCholesky,
    CovUnconstrainedCholeskyWishartReg,
    CovUnconstrainedInvCholesky,
)

SIZE = 6
RNG = np.random.RandomState(0)


def _dense_checks(cov, params, atol=1e-6, rtol=1e-5):
    """logdet and solve must agree with dense linear algebra."""
    prec = np.asarray(cov.prec(params))
    dense_cov = np.linalg.inv(prec)
    # logdet
    sign, logdet = np.linalg.slogdet(dense_cov)
    assert sign > 0
    assert np.isclose(float(cov.logdet(params)), logdet,
                      atol=atol, rtol=rtol)
    # solve
    X = RNG.randn(cov.size, 3)
    got = np.asarray(cov.solve(params, jnp.asarray(X)))
    assert np.allclose(got, np.linalg.solve(dense_cov, X),
                       atol=atol, rtol=rtol)


def test_cov_identity():
    cov = CovIdentity(SIZE)
    params = cov.init_params()
    assert float(cov.logdet(params)) == 0.0
    X = RNG.randn(SIZE, 2)
    assert np.allclose(cov.solve(params, X), X)
    assert np.allclose(cov.cov(params), np.eye(SIZE))


def test_cov_isotropic():
    cov = CovIsotropic(SIZE, var=2.5)
    _dense_checks(cov, cov.init_params())


def test_cov_diagonal():
    var = RNG.rand(SIZE) + 0.5
    cov = CovDiagonal(SIZE, diag_var=var)
    params = cov.init_params()
    _dense_checks(cov, params)
    assert np.allclose(np.diag(np.asarray(cov.prec(params))), 1 / var)


def test_cov_diagonal_gamma_prior():
    cov = CovDiagonalGammaPrior(SIZE, sigma=RNG.rand(SIZE) + 0.5)
    params = cov.init_params()
    _dense_checks(cov, params)
    assert np.isfinite(float(cov.logp(params)))


def test_cov_ar1():
    cov = CovAR1(SIZE, rho=0.4, sigma=1.3)
    params = cov.init_params()
    prec = np.asarray(cov.prec(params))
    # AR(1) precision is tridiagonal
    assert np.allclose(prec, np.triu(np.tril(prec, 1), -1))
    X = RNG.randn(SIZE, 2)
    assert np.allclose(cov.solve(params, X), prec @ X)
    # logdet of the AR(1) covariance: n*2*log(sigma) - log(1-rho^2)
    expected = SIZE * 2 * np.log(1.3) - np.log(1 - 0.4 ** 2)
    assert np.isclose(float(cov.logdet(params)), expected)


def test_cov_ar1_scan_onsets():
    cov = CovAR1(SIZE, rho=0.3, sigma=1.0, scan_onsets=[0, 3])
    params = cov.init_params()
    prec = np.asarray(cov.prec(params))
    # no coupling across the block boundary
    assert prec[2, 3] == 0 and prec[3, 2] == 0


def test_cov_unconstrained_cholesky():
    A = RNG.randn(SIZE, SIZE)
    Sigma = A @ A.T + SIZE * np.eye(SIZE)
    cov = CovUnconstrainedCholesky(Sigma=Sigma)
    params = cov.init_params()
    sign, logdet = np.linalg.slogdet(Sigma)
    assert np.isclose(float(cov.logdet(params)), logdet, atol=1e-8)
    X = RNG.randn(SIZE, 3)
    assert np.allclose(np.asarray(cov.solve(params, jnp.asarray(X))),
                       np.linalg.solve(Sigma, X), atol=1e-8)
    with pytest.raises(RuntimeError):
        CovUnconstrainedCholesky()
    with pytest.raises(RuntimeError):
        CovUnconstrainedCholesky(size=3, Sigma=Sigma)


def test_cov_unconstrained_inv_cholesky():
    A = RNG.randn(SIZE, SIZE)
    invSigma = A @ A.T + SIZE * np.eye(SIZE)
    cov = CovUnconstrainedInvCholesky(invSigma=invSigma)
    params = cov.init_params()
    # The precision LinvᵀLinv has the same determinant as invSigma (the
    # init is a reparameterized seed — same property as the reference).
    sign, logdet_prec = np.linalg.slogdet(invSigma)
    assert np.isclose(float(cov.logdet(params)), -logdet_prec, atol=1e-8)
    prec = np.asarray(cov.prec(params))
    assert np.all(np.linalg.eigvalsh(prec) > 0)
    # solve is consistent with its own precision
    X = RNG.randn(SIZE, 2)
    assert np.allclose(np.asarray(cov.solve(params, jnp.asarray(X))),
                       prec @ X, atol=1e-8)
    with pytest.raises(RuntimeError):
        CovUnconstrainedInvCholesky()


def test_cov_wishart_reg():
    cov = CovUnconstrainedCholeskyWishartReg(SIZE)
    params = cov.init_params()
    assert np.isfinite(float(cov.logp(params)))


def test_cov_kronecker():
    sizes = [2, 3]
    sigmas = []
    for n in sizes:
        A = RNG.randn(n, n)
        sigmas.append(A @ A.T + n * np.eye(n))
    cov = CovKroneckerFactored(sizes, Sigmas=sigmas)
    params = cov.init_params()
    dense = np.kron(sigmas[0], sigmas[1])
    sign, logdet = np.linalg.slogdet(dense)
    assert np.isclose(float(cov.logdet(params)), logdet, atol=1e-8)
    X = RNG.randn(6, 2)
    assert np.allclose(np.asarray(cov.solve(params, jnp.asarray(X))),
                       np.linalg.solve(dense, X), atol=1e-8)
    with pytest.raises(TypeError):
        CovKroneckerFactored((2, 3))


def test_cov_random_inits_and_base():
    """Random initialization (no values supplied) must yield usable,
    self-consistent covariances for every learnable family; the
    abstract base refuses logdet/solve."""
    from brainiak_tpu.matnormal.covs import CovBase

    base = CovBase(3)
    with pytest.raises(NotImplementedError):
        base.logdet({})
    with pytest.raises(NotImplementedError):
        base.solve({}, np.zeros((3, 1)))

    # relative tolerance carries the fp32 sweep: a random exp-diagonal
    # Cholesky can be ill-conditioned, putting dense-solve entries at
    # ~1e3 where float32 round-off is far above a 1e-6 absolute band
    import jax

    fp32 = not jax.config.read("jax_enable_x64")
    tol = dict(atol=1e-3, rtol=2e-3) if fp32 else {}
    for cov in (CovDiagonal(SIZE), CovUnconstrainedCholesky(size=SIZE),
                CovUnconstrainedInvCholesky(size=SIZE)):
        params = cov.init_params(seed=1)
        _dense_checks(cov, params, **tol)

    kron = CovKroneckerFactored([2, 3])
    params = kron.init_params(seed=2)
    Ls = kron.L(params)
    dense = np.kron(*[np.asarray(L) @ np.asarray(L).T for L in Ls])
    sign, logdet = np.linalg.slogdet(dense)
    assert sign > 0
    assert np.isclose(float(kron.logdet(params)), logdet,
                      atol=1e-6, **({"rtol": 2e-3} if fp32 else {}))
    X = RNG.randn(6, 2)
    assert np.allclose(np.asarray(kron.solve(params, jnp.asarray(X))),
                       np.linalg.solve(dense, X),
                       atol=1e-3 if fp32 else 1e-5,
                       rtol=2e-3 if fp32 else 1e-5)


def test_cov_kronecker_masked_logdet():
    """Masked Kronecker logdet: per-factor log-diagonals weighted by
    surviving index counts equal the dense masked-Cholesky logdet."""
    sizes = [2, 3]
    sigmas = []
    for n in sizes:
        A = RNG.randn(n, n)
        sigmas.append(A @ A.T + n * np.eye(n))
    mask = np.array([1, 0, 1, 1, 1, 0])
    cov = CovKroneckerFactored(sizes, Sigmas=sigmas, mask=mask)
    params = cov.init_params()
    L = np.linalg.cholesky(np.kron(sigmas[0], sigmas[1]))
    idx = np.where(mask)[0]
    sub_chol = L[np.ix_(idx, idx)]
    sign, expected = np.linalg.slogdet(sub_chol @ sub_chol.T)
    assert sign > 0
    assert np.isclose(float(cov.logdet(params)), expected, atol=1e-8)


def test_cov_kronecker_masked():
    sizes = [2, 3]
    sigmas = []
    for n in sizes:
        A = RNG.randn(n, n)
        sigmas.append(A @ A.T + n * np.eye(n))
    mask = np.array([1, 1, 0, 1, 1, 1])
    cov = CovKroneckerFactored(sizes, Sigmas=sigmas, mask=mask)
    params = cov.init_params()
    # solve restricted to valid indices matches dense sub-solve
    L = np.linalg.cholesky(np.kron(sigmas[0], sigmas[1]))
    idx = np.where(mask)[0]
    sub = (L @ L.T)[np.ix_(idx, idx)]
    # note: masked kron solve uses the masked CHOLESKY factor, i.e.
    # (L_masked L_maskedᵀ)⁻¹, matching the reference's recursion
    sub_chol = L[np.ix_(idx, idx)]
    dense_masked = sub_chol @ sub_chol.T
    X = RNG.randn(6, 2)
    got = np.asarray(cov.solve(params, jnp.asarray(X)))
    assert np.allclose(got[idx], np.linalg.solve(dense_masked, X[idx]),
                       atol=1e-8)
    assert np.allclose(got[mask == 0], 0.0)
