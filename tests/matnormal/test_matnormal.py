import jax
import jax.numpy as jnp
import numpy as np

from brainiak_tpu.matnormal.covs import (
    CovIdentity,
    CovIsotropic,
    CovUnconstrainedCholesky,
)
from brainiak_tpu.matnormal.matnormal_likelihoods import (
    matnorm_logp,
    matnorm_logp_marginal_col,
    matnorm_logp_marginal_row,
)
from brainiak_tpu.matnormal.mnrsa import MNRSA
from brainiak_tpu.matnormal.regression import MatnormalRegression
from brainiak_tpu.matnormal.utils import rmn
from brainiak_tpu.utils.kronecker_solvers import (
    kron_mult,
    solve_lower_triangular_kron,
    solve_upper_triangular_kron,
)

RNG = np.random.RandomState(0)


def _spd(n, rng):
    A = rng.randn(n, n)
    return A @ A.T + n * np.eye(n)


def _dense_mn_logp(X, R, C):
    """Direct dense matrix-normal log-density oracle."""
    n, m = X.shape
    sR, ldR = np.linalg.slogdet(R)
    sC, ldC = np.linalg.slogdet(C)
    tr = np.trace(np.linalg.solve(C, X.T) @ np.linalg.solve(R, X))
    return -0.5 * (n * m * np.log(2 * np.pi) + m * ldR + n * ldC + tr)


def test_kron_solvers_match_dense():
    Ls = [np.linalg.cholesky(_spd(n, RNG)) for n in (3, 4)]
    y = RNG.randn(12, 2)
    dense = np.kron(Ls[0], Ls[1])
    x_lower = np.asarray(solve_lower_triangular_kron(
        [jnp.asarray(m) for m in Ls], jnp.asarray(y)))
    assert np.allclose(x_lower, np.linalg.solve(dense, y), atol=1e-8)
    x_upper = np.asarray(solve_upper_triangular_kron(
        [jnp.asarray(m) for m in Ls], jnp.asarray(y)))
    assert np.allclose(x_upper, np.linalg.solve(dense.T, y), atol=1e-8)
    prod = np.asarray(kron_mult([jnp.asarray(m) for m in Ls],
                                jnp.asarray(y)))
    assert np.allclose(prod, dense @ y, atol=1e-8)
    # 1-D input
    y1 = RNG.randn(12)
    assert np.allclose(
        np.asarray(kron_mult([jnp.asarray(m) for m in Ls],
                             jnp.asarray(y1))), dense @ y1, atol=1e-8)


def test_matnorm_logp_matches_dense_oracle():
    n_t, n_v = 5, 4
    R = _spd(n_t, RNG)
    C = _spd(n_v, RNG)
    X = rmn(R, C, random_state=1)
    row_cov = CovUnconstrainedCholesky(Sigma=R)
    col_cov = CovUnconstrainedCholesky(Sigma=C)
    got = float(matnorm_logp(jnp.asarray(X), row_cov,
                             row_cov.init_params(), col_cov,
                             col_cov.init_params()))
    assert np.isclose(got, _dense_mn_logp(X, R, C), atol=1e-6)


def test_matnorm_logp_marginal_row_matches_dense():
    n_t, n_v, k = 6, 4, 2
    R = _spd(n_t, RNG)
    C = _spd(n_v, RNG)
    A = RNG.randn(n_t, k)
    Q = _spd(k, RNG)
    X = RNG.randn(n_t, n_v)

    row_cov = CovUnconstrainedCholesky(Sigma=R)
    col_cov = CovUnconstrainedCholesky(Sigma=C)
    q_cov = CovUnconstrainedCholesky(Sigma=Q)
    got = float(matnorm_logp_marginal_row(
        jnp.asarray(X), row_cov, row_cov.init_params(),
        col_cov, col_cov.init_params(), jnp.asarray(A),
        q_cov, q_cov.init_params()))
    expected = _dense_mn_logp(X, R + A @ Q @ A.T, C)
    assert np.isclose(got, expected, atol=1e-6)


def test_matnorm_logp_marginal_col_matches_dense():
    n_t, n_v, k = 4, 6, 2
    R = _spd(n_t, RNG)
    C = _spd(n_v, RNG)
    A = RNG.randn(n_v, k)
    Q = _spd(k, RNG)
    X = RNG.randn(n_t, n_v)

    row_cov = CovUnconstrainedCholesky(Sigma=R)
    col_cov = CovUnconstrainedCholesky(Sigma=C)
    q_cov = CovUnconstrainedCholesky(Sigma=Q)
    got = float(matnorm_logp_marginal_col(
        jnp.asarray(X), row_cov, row_cov.init_params(),
        col_cov, col_cov.init_params(), jnp.asarray(A),
        q_cov, q_cov.init_params()))
    expected = _dense_mn_logp(X, R, C + A @ Q @ A.T)
    assert np.isclose(got, expected, atol=1e-6)


def test_matnormal_regression_recovers_beta():
    n_t, n_c, n_v = 120, 3, 8
    rng = np.random.RandomState(2)
    X = rng.randn(n_t, n_c)
    beta = rng.randn(n_c, n_v)
    Y = X @ beta + 0.1 * rng.randn(n_t, n_v)
    model = MatnormalRegression(time_cov=CovIdentity(n_t),
                                space_cov=CovIsotropic(n_v))
    model.fit(X, Y)
    assert np.allclose(model.beta_, beta, atol=0.1)
    pred = model.predict(X)
    assert np.corrcoef(pred.ravel(), Y.ravel())[0, 1] > 0.99
    # calibrate recovers the design direction
    X_hat = model.calibrate(Y)
    assert np.corrcoef(X_hat.ravel(), X.ravel())[0, 1] > 0.9


def test_mnrsa_recovers_rsa_structure():
    n_t, n_c, n_v = 150, 4, 12
    rng = np.random.RandomState(3)
    # ground-truth RSA covariance with block structure
    U = np.array([[1.0, 0.8, 0.0, 0.0],
                  [0.8, 1.0, 0.0, 0.0],
                  [0.0, 0.0, 1.0, 0.8],
                  [0.0, 0.0, 0.8, 1.0]])
    X = rng.randn(n_t, n_c)
    W = np.linalg.cholesky(U) @ rng.randn(n_c, n_v)
    Y = X @ W + 0.5 * rng.randn(n_t, n_v)
    model = MNRSA(time_cov=CovIdentity(n_t), space_cov=CovIsotropic(n_v),
                  n_nureg=2)
    model.fit(Y, X)
    assert model.U_.shape == (n_c, n_c)
    # recovered correlation structure matches the generative one
    c = np.corrcoef(model.C_[np.triu_indices(n_c, 1)],
                    U[np.triu_indices(n_c, 1)])[0, 1]
    assert c > 0.7
    assert np.isfinite(model.final_loss_)


def test_parity_helpers():
    from brainiak_tpu.matnormal.utils import scaled_I, x_tx, xx_t
    from brainiak_tpu.utils.kronecker_solvers import \
        masked_triangular_solve

    x = jnp.asarray(RNG.randn(4, 3))
    assert np.allclose(np.asarray(xx_t(x)), np.asarray(x) @ np.asarray(x).T)
    assert np.allclose(np.asarray(x_tx(x)), np.asarray(x).T @ np.asarray(x))
    assert np.allclose(np.asarray(scaled_I(2.5, 3)), 2.5 * np.eye(3))

    L = np.linalg.cholesky(_spd(5, RNG))
    y = RNG.randn(5, 2)
    mask = np.array([1, 0, 1, 1, 0])
    got = np.asarray(masked_triangular_solve(jnp.asarray(L),
                                             jnp.asarray(y), mask))
    idx = np.where(mask)[0]
    expected = np.zeros_like(y)
    expected[idx] = np.linalg.solve(L[np.ix_(idx, idx)], y[idx])
    assert np.allclose(got, expected)
    # adjoint solve
    got_a = np.asarray(masked_triangular_solve(
        jnp.asarray(L), jnp.asarray(y), mask, adjoint=True))
    expected_a = np.zeros_like(y)
    expected_a[idx] = np.linalg.solve(L[np.ix_(idx, idx)].T, y[idx])
    assert np.allclose(got_a, expected_a)


def test_make_val_and_grad_scipy_bridge():
    """The scipy bridge (reference matnormal/utils.py:107-124 analog)
    must drive scipy.optimize.minimize with jac=True to the optimum."""
    from scipy.optimize import minimize

    from brainiak_tpu.matnormal.utils import make_val_and_grad

    a = jnp.asarray(_spd(4, RNG))
    b = jnp.asarray(RNG.randn(4))

    def loss(x):
        return 0.5 * x @ a @ x - b @ x

    vg = make_val_and_grad(loss)
    val, grad = vg(np.zeros(4))
    assert isinstance(val, float)
    assert grad.dtype == np.float64
    assert np.allclose(grad, -np.asarray(b), atol=1e-6)
    res = minimize(vg, np.zeros(4), jac=True, method='L-BFGS-B')
    # fp32 gradients limit L-BFGS-B convergence to ~1e-4
    atol = 1e-5 if jax.config.jax_enable_x64 else 5e-4
    assert np.allclose(res.x, np.linalg.solve(np.asarray(a),
                                              np.asarray(b)), atol=atol)


def test_gp_var_priors():
    from brainiak_tpu.reprsimil.brsa import (
        prior_GP_var_half_cauchy,
        prior_GP_var_inv_gamma,
    )

    tau2, logp = prior_GP_var_inv_gamma(5.0, 20, 1.0)
    assert tau2 > 0 and np.isfinite(logp)
    tau2_hc, logp_hc = prior_GP_var_half_cauchy(5.0, 20, 1.0)
    assert tau2_hc > 0 and np.isfinite(logp_hc)
