"""Span semantics: nesting/paths, disabled no-ops, sync handling,
decorator form, legacy stage_timer aliases, thread safety."""

import threading

import numpy as np

from brainiak_tpu import obs
from brainiak_tpu.obs import sink as obs_sink


def _mem():
    return obs_sink.add_sink(obs.MemorySink())


def _spans(mem):
    return [r for r in mem.records if r["kind"] == "span"]


def test_span_disabled_is_noop_and_emits_nothing():
    assert not obs.enabled()
    with obs.span("outer") as frame:
        # the null frame accepts attrs AND the documented late-sync
        # assignment without effect (and without raising)
        frame.set("k", 1)
        frame.sync = [1, 2, 3]
        assert frame.sync is None  # discarded, not pinned
        assert obs.current_span() == ""
    # nothing to assert against a sink: there is none — enabled()
    # stays false and no record was buffered anywhere
    assert not obs.enabled()


def test_span_nesting_paths_and_attrs():
    mem = _mem()
    with obs.span("outer", attrs={"estimator": "SRM"}):
        assert obs.current_span() == "outer"
        with obs.span("inner") as frame:
            frame.set("step", 3)
            assert obs.current_span() == "outer/inner"
    recs = _spans(mem)
    assert [r["path"] for r in recs] == ["outer/inner", "outer"]
    assert recs[0]["attrs"] == {"step": 3}
    assert recs[1]["attrs"] == {"estimator": "SRM"}
    for rec in recs:
        assert obs.validate_record(rec) == []
        assert rec["dur_s"] >= 0


def test_span_sync_blocks_on_device_result():
    import jax.numpy as jnp

    mem = _mem()
    x = jnp.ones((16, 16))
    with obs.span("matmul", sync=x @ x):
        pass
    with obs.span("late") as frame:
        frame.sync = x + 1
    assert len(_spans(mem)) == 2


def test_failing_sync_propagates_but_stack_stays_clean(monkeypatch):
    """A sync target whose computation failed re-raises out of the
    span, but the thread-local stack must be unwound — a caller that
    catches and continues (the resilient-loop rollback path) must
    not see corrupted paths on later spans."""
    from brainiak_tpu.obs import spans

    mem = _mem()

    def boom(target):
        raise FloatingPointError("async computation failed")

    monkeypatch.setattr(spans, "_block_until_ready", boom)
    try:
        with obs.span("doomed", sync=object()):
            pass
    except FloatingPointError:
        pass
    assert obs.current_span() == ""
    monkeypatch.undo()
    with obs.span("after"):
        pass
    recs = _spans(mem)
    # the doomed span recorded nothing (its time would be bogus);
    # the next span has an uncorrupted root path
    assert [r["path"] for r in recs] == ["after"]


def test_span_exception_still_recorded():
    mem = _mem()
    try:
        with obs.span("boom"):
            raise RuntimeError("x")
    except RuntimeError:
        pass
    recs = _spans(mem)
    assert len(recs) == 1 and recs[0]["name"] == "boom"
    # the stack unwound — no leaked active span
    assert obs.current_span() == ""


def test_traced_decorator_forms():
    mem = _mem()

    @obs.traced
    def bare():
        return 1

    @obs.traced("labeled", sync_result=True)
    def labeled():
        import jax.numpy as jnp
        return jnp.zeros(3)

    assert bare() == 1
    np.testing.assert_array_equal(np.asarray(labeled()), 0.0)
    names = [r["name"] for r in _spans(mem)]
    assert "bare" in names[0]  # qualified name of the function
    assert names[1] == "labeled"


def test_stage_timer_records_without_sink():
    obs.reset_stage_times()
    with obs.stage_timer("stage_a"):
        pass
    with obs.stage_timer("stage_a"):
        pass
    times = obs.stage_times()
    assert len(times["stage_a"]) == 2
    obs.reset_stage_times()
    assert obs.stage_times() == {}


def test_stage_timer_emits_span_when_enabled():
    mem = _mem()
    obs.reset_stage_times()
    with obs.span("outer"):
        with obs.stage_timer("legacy"):
            pass
    paths = [r["path"] for r in _spans(mem)]
    assert "outer/legacy" in paths
    assert "legacy" in obs.stage_times()
    obs.reset_stage_times()


def test_profiling_shim_reexports():
    from brainiak_tpu.utils import profiling

    assert profiling.stage_timer is obs.stage_timer
    assert profiling.stage_times is obs.stage_times
    assert profiling.reset_stage_times is obs.reset_stage_times


def test_stage_registry_thread_safe():
    obs.reset_stage_times()

    def work():
        for _ in range(200):
            with obs.stage_timer("shared"):
                pass

    threads = [threading.Thread(target=work) for _ in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert len(obs.stage_times()["shared"]) == 800
    obs.reset_stage_times()


def test_span_stacks_are_thread_local():
    mem = _mem()
    seen = {}

    def work(tag):
        with obs.span(tag):
            seen[tag] = obs.current_span()

    threads = [threading.Thread(target=work, args=(f"t{i}",))
               for i in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    # no cross-thread nesting: every span is its own root
    assert seen == {f"t{i}": f"t{i}" for i in range(4)}
    assert all(r["path"] == r["name"] for r in _spans(mem))
