"""SLO burn-rate tracking (ISSUE 12 tentpole part 4).

Deterministic fake-clock coverage: burn-rate math, the multi-window
AND rule, violation events on transitions (with de-dup while a
violation persists), budget gauges, and the min-count guard."""

import pytest

from brainiak_tpu.obs import metrics
from brainiak_tpu.obs import sink as obs_sink
from brainiak_tpu.obs.slo import (DEFAULT_BURN_RULES, BurnRule,
                                  Objective, SLOTracker)


class FakeClock:
    def __init__(self):
        self.now = 1000.0

    def __call__(self):
        return self.now

    def advance(self, seconds):
        self.now += seconds


def _tracker(objective, rule=BurnRule(long_s=60.0, short_s=10.0,
                                      factor=2.0),
             min_window_count=10):
    clock = FakeClock()
    return SLOTracker([objective], burn_rules=(rule,), clock=clock,
                      min_window_count=min_window_count), clock


def test_objective_declarations():
    lat = Objective.latency("p99", quantile=0.99, threshold_s=0.5)
    assert lat.target == 0.99
    assert lat.is_bad(True, 0.6) and not lat.is_bad(True, 0.4)
    assert lat.is_bad(False, 0.1)  # an error is always bad
    err = Objective.error_rate("avail", max_error_rate=0.001)
    assert err.target == pytest.approx(0.999)
    assert not err.is_bad(True, 99.0)  # no latency threshold
    with pytest.raises(ValueError, match="target"):
        Objective("bad", target=1.0)
    with pytest.raises(ValueError, match="duplicate"):
        SLOTracker([err, err])
    with pytest.raises(ValueError, match="objective"):
        SLOTracker([])
    with pytest.raises(ValueError, match="burn rule"):
        SLOTracker([err], burn_rules=())


def test_healthy_traffic_full_budget_no_violation():
    tracker, clock = _tracker(
        Objective.error_rate("avail", max_error_rate=0.01))
    for _ in range(200):
        tracker.record(True, latency_s=0.01)
        clock.advance(0.1)
    out = tracker.evaluate()
    state = out["objectives"]["avail"]
    assert not state["violating"]
    assert state["error_budget_remaining"] == pytest.approx(1.0)
    assert out["n_violations"] == 0
    for wstate in state["windows"].values():
        assert wstate["burn_rate"] == 0.0


def test_burn_rate_math():
    """5% bad against a 1% budget burns at exactly 5.0."""
    tracker, clock = _tracker(
        Objective.error_rate("avail", max_error_rate=0.01),
        rule=BurnRule(long_s=60.0, short_s=10.0, factor=100.0))
    for i in range(100):
        tracker.record(i % 20 != 0)  # 5% errors
        clock.advance(0.05)
    state = tracker.evaluate()["objectives"]["avail"]
    for wstate in state["windows"].values():
        assert wstate["burn_rate"] == pytest.approx(5.0)
    assert state["error_budget_remaining"] == 0.0
    assert not state["violating"]  # factor 100 not reached


def test_multi_window_and_rule():
    """A past burst that has left the SHORT window no longer
    violates (long still burning, short recovered) — the workbook
    property that alerts stop once the problem stops."""
    mem = obs_sink.add_sink(obs_sink.MemorySink())
    tracker, clock = _tracker(
        Objective.error_rate("avail", max_error_rate=0.01))
    # burst: everything fails for 5 s
    for _ in range(50):
        tracker.record(False)
        clock.advance(0.1)
    out = tracker.evaluate()
    assert out["objectives"]["avail"]["violating"]
    assert out["n_violations"] == 1
    events = [r for r in mem.records
              if r["kind"] == "event" and r["name"] == "slo_violation"]
    assert len(events) == 1
    assert events[0]["attrs"]["slo"] == "avail"
    assert obs_sink.validate_record(events[0]) == []
    # still violating on re-evaluate: NO duplicate event
    tracker.evaluate()
    assert len([r for r in mem.records
                if r["name"] == "slo_violation"]) == 1
    # 15 s of clean traffic: the short window recovers, long still
    # holds the burst -> no longer violating (AND rule)
    for _ in range(150):
        tracker.record(True, latency_s=0.01)
        clock.advance(0.1)
    out = tracker.evaluate()
    state = out["objectives"]["avail"]
    assert state["windows"]["60s"]["burn_rate"] > 2.0
    assert state["windows"]["10s"]["burn_rate"] == 0.0
    assert not state["violating"]
    # a SECOND burst is a new transition: a second event fires
    for _ in range(50):
        tracker.record(False)
        clock.advance(0.1)
    tracker.evaluate()
    assert len([r for r in mem.records
                if r["name"] == "slo_violation"]) == 2
    assert metrics.counter("slo_violations_total").value(
        slo="avail") == 2


def test_latency_objective_burns_on_slow_ok_requests():
    tracker, clock = _tracker(
        Objective.latency("p99", quantile=0.99, threshold_s=0.1))
    for _ in range(100):
        tracker.record(True, latency_s=0.5)  # ok but slow = bad
        clock.advance(0.05)
    state = tracker.evaluate()["objectives"]["p99"]
    assert state["violating"]
    assert state["error_budget_remaining"] == 0.0


def test_min_window_count_guard():
    """Two requests, one failed, must not page at the first error."""
    tracker, clock = _tracker(
        Objective.error_rate("avail", max_error_rate=0.01),
        min_window_count=10)
    tracker.record(True)
    tracker.record(False)
    state = tracker.evaluate()["objectives"]["avail"]
    assert not state["violating"]


def test_gauges_land_in_registry_for_exposition():
    tracker, clock = _tracker(
        Objective.error_rate("avail", max_error_rate=0.01))
    for _ in range(20):
        tracker.record(True)
        clock.advance(0.1)
    tracker.evaluate()
    assert metrics.gauge("slo_error_budget_remaining").value(
        slo="avail") == pytest.approx(1.0)
    assert metrics.gauge("slo_burn_rate").value(
        slo="avail", window="60s") == 0.0
    assert metrics.gauge("slo_burn_rate").value(
        slo="avail", window="10s") == 0.0


def test_old_slices_are_pruned():
    tracker, clock = _tracker(
        Objective.error_rate("avail", max_error_rate=0.01))
    for _ in range(100):
        tracker.record(False)
        clock.advance(1.0)
    clock.advance(3600.0)  # far past the longest window
    state = tracker.evaluate()["objectives"]["avail"]
    assert state["n_requests"] == 0
    assert state["error_budget_remaining"] == pytest.approx(1.0)
    counts = tracker._counts["avail"]
    assert len(counts.slices) == 0


def test_default_burn_rules_are_workbook_shaped():
    (fast, slow) = DEFAULT_BURN_RULES
    assert fast.factor > slow.factor
    assert fast.long_s < slow.long_s
    assert fast.short_s < fast.long_s
