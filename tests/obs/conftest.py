"""Obs test isolation: every test starts with no sinks, no env dir,
and a clean default metric registry."""

import pytest

from brainiak_tpu.obs import metrics, sink


@pytest.fixture(autouse=True)
def _clean_obs(monkeypatch):
    monkeypatch.delenv(sink.OBS_DIR_ENV, raising=False)
    monkeypatch.delenv(sink.OBS_RANK_ENV, raising=False)
    sink.close_all()
    metrics.reset()
    yield
    sink.close_all()
    metrics.reset()
