"""Obs test isolation: every test starts with no sinks, no env dir,
a clean default metric registry, an empty flight-recorder ring, and
an empty fit-progress registry."""

import pytest

from brainiak_tpu.obs import flight, metrics, progress, sink


@pytest.fixture(autouse=True)
def _clean_obs(monkeypatch):
    monkeypatch.delenv(sink.OBS_DIR_ENV, raising=False)
    monkeypatch.delenv(sink.OBS_RANK_ENV, raising=False)
    monkeypatch.delenv(flight.FLIGHT_DIR_ENV, raising=False)
    monkeypatch.delenv(flight.FLIGHT_RECORDS_ENV, raising=False)
    sink.close_all()
    metrics.reset()
    flight.clear()
    progress.clear_registry()
    yield
    sink.close_all()
    metrics.reset()
    flight.clear()
    progress.clear_registry()
