"""Runtime collectors: counted_cache, topology, device memory."""

from brainiak_tpu import obs
from brainiak_tpu.obs import sink as obs_sink


def test_counted_cache_counts_misses_only():
    calls = []

    @obs.counted_cache("test.site")
    def build(key):
        calls.append(key)
        return key * 2

    assert build(1) == 2
    assert build(1) == 2
    assert build(2) == 4
    assert calls == [1, 2]
    c = obs.counter("retrace_total")
    assert c.value(site="test.site") == 2
    info = build.cache_info()
    assert info.misses == 2 and info.hits == 1
    build.cache_clear()
    assert build(1) == 2
    assert c.value(site="test.site") == 3


def test_mesh_builders_report_retraces():
    from brainiak_tpu.parallel import mesh as pmesh

    pmesh._replicate_identity.cache_clear()
    m = pmesh.subject_voxel_mesh(2, 1)
    pmesh._replicate_identity(m)
    pmesh._replicate_identity(m)
    assert obs.counter("retrace_total").value(
        site="parallel.replicate_identity") == 1


def test_make_mesh_emits_topology_event():
    from brainiak_tpu.parallel import mesh as pmesh

    mem = obs_sink.add_sink(obs.MemorySink())
    pmesh.subject_voxel_mesh(2, 2)
    (rec,) = [r for r in mem.records if r["name"] == "topology"]
    assert rec["attrs"]["mesh_axes"] == {"subject": 2, "voxel": 2}
    assert rec["attrs"]["backend"] == "cpu"
    assert rec["attrs"]["device_count"] == 8
    assert obs.validate_record(rec) == []


def test_topology_event_disabled_returns_none():
    assert obs.topology_event() is None


def test_device_memory_snapshot_never_raises():
    # CPU devices may expose no memory_stats; the call must stay a
    # safe no-op returning a (possibly empty) list either way
    mem = obs_sink.add_sink(obs.MemorySink())
    out = obs.device_memory_snapshot()
    assert isinstance(out, list)
    for rec in mem.records:
        assert obs.validate_record(rec) == []


def test_install_compile_listener_best_effort_idempotent():
    # jax is imported by conftest, so this either installs (True) or
    # reports the capability missing (False) — and never raises; a
    # second call is a no-op
    first = obs.install_compile_listener()
    assert first in (True, False)
    assert obs.install_compile_listener() == first


def test_fetch_replicated_fallback_counter(monkeypatch):
    import jax
    import numpy as np
    from brainiak_tpu.parallel import mesh as pmesh

    m = pmesh.subject_voxel_mesh(2, 1)
    x = pmesh.shard_along(np.ones((4, 3)), m, "subject")

    # single-process short-circuits before device_put; force the
    # multi-process branch and make device_put reject, so the cached
    # jitted-identity fallback engages
    monkeypatch.setattr(jax, "process_count", lambda: 2)

    def boom(*args, **kwargs):
        raise NotImplementedError("no cross-process reshard")

    monkeypatch.setattr(jax, "device_put", boom)
    out = pmesh.fetch_replicated(x, m)
    assert out.shape == (4, 3)
    assert obs.counter("fetch_replicated_fallback_total").value(
        reason="NotImplementedError") == 1
