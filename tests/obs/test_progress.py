"""FitProgress tracker: records, convergence telemetry, registry."""

import json
import math

import numpy as np
import pytest

from brainiak_tpu.obs import flight, progress
from brainiak_tpu.obs import sink as obs_sink
from brainiak_tpu.obs.progress import FitProgress


def _observe_n(fp, n, objective=None, start=0, n_steps=2,
               chunk_s=0.1):
    recs = []
    for i in range(n):
        value = objective(i) if callable(objective) else objective
        state = {} if value is None else \
            {"obj": np.full(3, float(value))}
        recs.append(fp.observe(state, start + (i + 1) * n_steps,
                               n_steps, chunk_s))
    return recs


def test_new_fit_id_is_trace_shaped():
    fid = progress.new_fit_id()
    assert len(fid) == 16
    int(fid, 16)  # hex or bust
    assert fid != progress.new_fit_id()


def test_progress_records_validate_and_carry_telemetry():
    mem = obs_sink.add_sink(obs_sink.MemorySink())
    try:
        fp = FitProgress("SRM.fit", 10, objective="obj",
                         n_chunks=5)
        recs = _observe_n(fp, 5, objective=lambda i: 100.0 - i)
    finally:
        obs_sink.remove_sink(mem)
    assert [r["chunk"] for r in recs] == [1, 2, 3, 4, 5]
    for rec in recs:
        assert obs_sink.validate_record(rec) == []
        assert rec["v"] == obs_sink.SCHEMA_VERSION
        assert rec["fit_id"] == fp.fit_id
        assert rec["estimator"] == "SRM.fit"
    assert recs[-1]["ratio"] == 1.0
    assert recs[-1]["objective"] == 96.0
    assert recs[1]["delta"] == -1.0
    assert recs[-1]["rate"] > 0
    assert recs[-1]["eta_s"] == 0.0
    # the sink saw the same stream
    assert [r for r in mem.records if r["kind"] == "progress"] \
        == recs


def test_disabled_obs_emits_no_sink_records():
    """The zero-overhead lane: no sink -> no records anywhere but
    the flight ring and the /jobs registry."""
    assert not obs_sink.enabled()
    fp = FitProgress("fit", 4, objective="obj")
    _observe_n(fp, 2, objective=lambda i: 1.0)
    fp.finish("completed")
    # flight ring and registry still fed (the always-on lane)
    kinds = {r["kind"] for r in flight.records()}
    assert kinds == {"progress", "event"}
    (snap,) = progress.active_fits()
    assert snap["fit_id"] == fp.fit_id
    assert snap["status"] == "completed"


def test_enabled_obs_taps_flight_ring_exactly_once():
    """sink.emit mirrors into the flight ring itself; the tracker
    must not ALSO tap it directly, or incident snapshots carry
    every progress record twice."""
    mem = obs_sink.add_sink(obs_sink.MemorySink())
    try:
        fp = FitProgress("fit", 4, objective="obj")
        _observe_n(fp, 2, objective=lambda i: 1.0)
        fp.finish("completed")
    finally:
        obs_sink.remove_sink(mem)
    chunks = [r["chunk"] for r in flight.records()
              if r["kind"] == "progress"]
    assert chunks == [1, 2]
    finished = [r for r in flight.records()
                if r["kind"] == "event"
                and r["name"] == "fit_finished"]
    assert len(finished) == 1


def test_divergence_precursor_on_non_finite_objective():
    mem = obs_sink.add_sink(obs_sink.MemorySink())
    try:
        fp = FitProgress("fit", 10, objective="obj")
        _observe_n(fp, 2, objective=lambda i: 5.0)
        fp.observe({"obj": np.array([1.0, np.nan, 2.0])}, 6, 2, 0.1)
    finally:
        obs_sink.remove_sink(mem)
    events = [r for r in mem.records if r["kind"] == "event"
              and r["name"] == "divergence_precursor"]
    assert len(events) == 1
    assert events[0]["fit_id"] == fp.fit_id
    assert events[0]["attrs"]["reason"] == "non_finite_objective"
    assert fp.precursor_fired
    # the NaN objective is omitted, never serialized: every record
    # in the stream stays strict JSON (no bare NaN tokens)
    assert events[0]["attrs"]["objective"] is None
    progress_recs = [r for r in mem.records
                     if r["kind"] == "progress"]
    assert progress_recs[-1].get("objective") is None
    json.dumps(mem.records, allow_nan=False)


def test_divergence_precursor_on_worsening_trend():
    mem = obs_sink.add_sink(obs_sink.MemorySink())
    try:
        fp = FitProgress("fit", 20, objective="obj",
                         direction="min")
        # steadily worsening (growing) objective under "min"
        _observe_n(fp, 6, objective=lambda i: 10.0 + 3.0 * i)
    finally:
        obs_sink.remove_sink(mem)
    events = [r for r in mem.records if r["kind"] == "event"
              and r["name"] == "divergence_precursor"]
    assert len(events) == 1  # fires once, not per chunk
    assert events[0]["attrs"]["reason"] == "worsening_trend"
    assert events[0]["attrs"]["ewma_worsening"] > 0


def test_improving_objective_fires_no_precursor():
    fp = FitProgress("fit", 20, objective="obj", direction="min")
    _observe_n(fp, 8, objective=lambda i: 10.0 - i)
    assert not fp.precursor_fired
    fp = FitProgress("fit", 20, objective="obj", direction="max")
    _observe_n(fp, 8, objective=lambda i: 10.0 + i)
    assert not fp.precursor_fired


def test_plateau_detection():
    mem = obs_sink.add_sink(obs_sink.MemorySink())
    try:
        fp = FitProgress("fit", 40, objective="obj")
        _observe_n(fp, 2, objective=lambda i: 50.0 - 10 * i)
        # then flat within PLATEAU_RTOL for PLATEAU_CHUNKS chunks
        _observe_n(fp, progress.PLATEAU_CHUNKS,
                   objective=lambda i: 40.0, start=4)
    finally:
        obs_sink.remove_sink(mem)
    assert fp.plateaued
    events = [r for r in mem.records if r["kind"] == "event"
              and r["name"] == "plateau"]
    assert len(events) == 1
    last = [r for r in mem.records if r["kind"] == "progress"][-1]
    assert last["plateaued"] is True


def test_callable_objective_and_swallowed_errors():
    calls = []

    def objective(state):
        calls.append(1)
        if len(calls) > 1:
            raise RuntimeError("flaky telemetry")
        return 7.0

    fp = FitProgress("fit", 4, objective=objective)
    rec = fp.observe({}, 2, 2, 0.1)
    assert rec["objective"] == 7.0
    rec = fp.observe({}, 4, 2, 0.1)  # extractor raises -> None
    assert rec.get("objective") is None
    # missing leaf names are swallowed too
    fp = FitProgress("fit", 4, objective="nope")
    rec = fp.observe({"obj": np.ones(2)}, 2, 2, 0.1)
    assert rec.get("objective") is None


def test_eta_uses_ewma_rate():
    fp = FitProgress("fit", 100, objective=None)
    fp.observe({}, 10, 10, 1.0)   # 10 it/s
    assert fp.eta_s == pytest.approx(9.0)
    fp.observe({}, 20, 10, 1.0)
    assert fp.rate == pytest.approx(10.0)
    assert fp.eta_s == pytest.approx(8.0)


def test_resume_carries_wall_and_chunks():
    fp = FitProgress("fit", 10, fit_id="ab" * 8, wall0=3.0,
                     chunks0=2)
    rec = fp.observe({}, 6, 2, 0.5)
    assert rec["fit_id"] == "ab" * 8
    assert rec["chunk"] == 3
    assert rec["fit_wall_s"] == pytest.approx(3.5)


def test_registry_eviction_keeps_running_fits():
    running = FitProgress("fit", 4)
    running.observe({}, 2, 2, 0.1)
    finished = []
    for _ in range(progress._MAX_FINISHED + 5):
        fp = FitProgress("fit", 2)
        fp.observe({}, 2, 2, 0.1)
        fp.finish("completed")
        finished.append(fp.fit_id)
    snaps = progress.active_fits()
    ids = [s["fit_id"] for s in snaps]
    assert running.fit_id in ids
    assert len(ids) == progress._MAX_FINISHED + 1
    # evicted oldest-first
    assert finished[0] not in ids
    assert finished[-1] in ids


def test_direction_validated():
    with pytest.raises(ValueError):
        FitProgress("fit", 4, direction="sideways")


def test_gauges_exposed_when_enabled():
    mem = obs_sink.add_sink(obs_sink.MemorySink())
    try:
        fp = FitProgress("SRM.fit", 10)
        fp.observe({}, 5, 5, 0.5)
        rows = {(m["name"],
                 tuple(sorted((m.get("labels") or {}).items())))
                for m in mem.records if m["kind"] == "metric"}
    finally:
        obs_sink.remove_sink(mem)
    labels = (("estimator", "SRM.fit"), ("fit_id", fp.fit_id))
    assert ("fit_progress_ratio", labels) in rows
    assert ("fit_eta_seconds", labels) in rows


def test_objective_ring_is_bounded():
    fp = FitProgress("fit", 10_000, objective="obj")
    _observe_n(fp, progress.OBJECTIVE_RING + 20,
               objective=lambda i: float(i) * -1.0)
    assert len(fp.objectives) == progress.OBJECTIVE_RING
    assert math.isfinite(fp.objectives[-1][1])


# -- ISSUE 20: fit context + finish listeners -------------------------

def test_fit_context_nests_drops_none_and_restores():
    assert progress.current_context() == {}
    with progress.fit_context(job_id="j1", tenant="a",
                              trace_id=None):
        assert progress.current_context() == {"job_id": "j1",
                                              "tenant": "a"}
        with progress.fit_context(tenant="b"):
            assert progress.current_context() == {"job_id": "j1",
                                                  "tenant": "b"}
        assert progress.current_context()["tenant"] == "a"
    assert progress.current_context() == {}


def test_fit_context_attrs_ride_records_and_registry():
    mem = obs_sink.add_sink(obs_sink.MemorySink())
    try:
        with progress.fit_context(job_id="job-1",
                                  tenant="hospital-a"):
            fp = FitProgress("SRM.fit", 4, n_chunks=2)
            _observe_n(fp, 2)
            fp.finish("completed")
    finally:
        obs_sink.remove_sink(mem)
    recs = [r for r in mem.records if r["kind"] == "progress"]
    assert all(r["attrs"]["job_id"] == "job-1" for r in recs)
    assert all(r["attrs"]["tenant"] == "hospital-a" for r in recs)
    snap = progress.active_fits()[-1]
    assert snap["fit_id"] == fp.fit_id
    assert snap["job_id"] == "job-1"
    assert snap["tenant"] == "hospital-a"


def test_finish_listener_sees_terminal_snapshot_once():
    seen = []
    progress.add_finish_listener(seen.append)
    progress.add_finish_listener(seen.append)  # dedup: once only
    try:
        with progress.fit_context(job_id="job-2"):
            fp = FitProgress("SRM.fit", 4, n_chunks=2)
            _observe_n(fp, 2)
            fp.finish("converged")
    finally:
        progress.remove_finish_listener(seen.append)
    assert len(seen) == 1
    assert seen[0]["status"] == "converged"
    assert seen[0]["fit_id"] == fp.fit_id
    assert seen[0]["job_id"] == "job-2"
    # removed listeners stay silent
    fp2 = FitProgress("SRM.fit", 2, n_chunks=1)
    fp2.finish("completed")
    assert len(seen) == 1


def test_finish_listener_exceptions_are_swallowed():
    def boom(snapshot):
        raise RuntimeError("telemetry must never break the fit")

    calls = []
    progress.add_finish_listener(boom)
    progress.add_finish_listener(calls.append)
    try:
        fp = FitProgress("SRM.fit", 2, n_chunks=1)
        fp.finish("completed")  # must not raise
    finally:
        progress.remove_finish_listener(boom)
        progress.remove_finish_listener(calls.append)
    assert len(calls) == 1
