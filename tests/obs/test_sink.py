"""Sink mechanics: env activation, JSONL files, schema validation."""

import json
import os
import threading

from brainiak_tpu import obs
from brainiak_tpu.obs import sink as obs_sink


def test_disabled_by_default():
    assert not obs.enabled()
    assert obs_sink.all_sinks() == []


def test_env_dir_enables_and_writes_rank_file(tmp_path,
                                              monkeypatch):
    d = str(tmp_path / "trace")
    monkeypatch.setenv(obs.OBS_DIR_ENV, d)
    assert obs.enabled()
    obs.emit(obs.make_record("event", "hello", attrs={"a": 1}))
    obs_sink.close_all()
    path = os.path.join(d, "obs-0.jsonl")
    assert os.path.exists(path)
    (rec,) = [json.loads(line) for line in open(path)]
    assert rec["name"] == "hello"
    assert rec["kind"] == "event"
    assert rec["v"] == obs.SCHEMA_VERSION
    assert obs.validate_record(rec) == []


def test_rank_env_override(tmp_path, monkeypatch):
    monkeypatch.setenv(obs_sink.OBS_RANK_ENV, "3")
    monkeypatch.setenv(obs.OBS_DIR_ENV, str(tmp_path))
    obs.emit(obs.make_record("event", "x"))
    obs_sink.close_all()
    assert os.path.exists(str(tmp_path / "obs-3.jsonl"))


def test_event_helper_noop_when_disabled(tmp_path, monkeypatch):
    assert obs_sink.event("nothing") is None
    monkeypatch.setenv(obs.OBS_DIR_ENV, str(tmp_path))
    rec = obs_sink.event("something", k="v")
    assert rec["attrs"] == {"k": "v"}


def test_memory_sink_add_remove():
    mem = obs_sink.add_sink(obs.MemorySink())
    assert obs.enabled()
    obs_sink.event("ping")
    obs_sink.remove_sink(mem)
    assert not obs.enabled()
    assert [r["name"] for r in mem.records] == ["ping"]


def test_numpy_attrs_serialize(tmp_path, monkeypatch):
    import numpy as np

    monkeypatch.setenv(obs.OBS_DIR_ENV, str(tmp_path))
    obs_sink.event("np", value=np.float32(1.5),
                   arr=np.arange(3))
    obs_sink.close_all()
    (rec,) = [json.loads(line)
              for line in open(str(tmp_path / "obs-0.jsonl"))]
    assert rec["attrs"]["value"] == 1.5
    assert rec["attrs"]["arr"] == [0, 1, 2]


def test_validate_record_rejects_bad_shapes():
    assert obs.validate_record([]) != []
    assert obs.validate_record({"v": 99}) != []
    good = obs.make_record("span", "s", path="s", dur_s=0.1)
    assert obs.validate_record(good) == []
    bad = dict(good)
    bad["dur_s"] = "fast"
    assert any("dur_s" in e for e in obs.validate_record(bad))
    bad = dict(good)
    bad["extra"] = 1
    assert any("unknown" in e for e in obs.validate_record(bad))
    bad = obs.make_record("metric", "m", mtype="timer", value=1.0)
    assert any("mtype" in e for e in obs.validate_record(bad))


def test_unwritable_dir_disables_sink_without_raising(tmp_path,
                                                      monkeypatch,
                                                      caplog):
    # point the obs dir at a path whose parent is a FILE: makedirs
    # fails on first write; the instrumented caller must not see it
    blocker = tmp_path / "blocker"
    blocker.write_text("")
    monkeypatch.setenv(obs.OBS_DIR_ENV, str(blocker / "trace"))
    import logging
    assert obs.enabled()
    with caplog.at_level(logging.WARNING,
                         logger="brainiak_tpu.obs.sink"):
        obs_sink.event("survives")       # must not raise
        obs_sink.event("also survives")  # sink already disabled
    assert "disabling" in caplog.text
    # the broken env sink turns enabled() back off: hot loops stop
    # paying for records nobody can receive
    assert not obs.enabled()
    # a DIFFERENT dir gets a fresh chance
    monkeypatch.setenv(obs.OBS_DIR_ENV, str(tmp_path / "ok"))
    assert obs.enabled()
    obs_sink.event("works now")
    obs_sink.close_all()
    assert (tmp_path / "ok" / "obs-0.jsonl").exists()


def test_rank_resolution_never_initializes_backend(monkeypatch):
    # simulate "jax imported, backend not initialized": the rank
    # probe must fall back to 0 without calling process_index (which
    # would initialize — and on a wedged tunnel, hang — the backend)
    import sys as _sys
    bridge = _sys.modules.get("jax._src.xla_bridge")
    if bridge is not None:
        monkeypatch.setattr(bridge, "_backends", {}, raising=False)
    import jax

    def boom():
        raise AssertionError("process_index would init the backend")

    monkeypatch.setattr(jax, "process_index", boom)
    assert obs_sink.process_rank() == 0


def test_jsonl_sink_reopens_when_rank_changes(tmp_path,
                                              monkeypatch):
    sink = obs.JsonlSink(str(tmp_path))
    monkeypatch.setenv(obs_sink.OBS_RANK_ENV, "0")
    sink.write(obs.make_record("event", "early"))
    monkeypatch.setenv(obs_sink.OBS_RANK_ENV, "2")
    sink.write(obs.make_record("event", "late"))
    sink.close()
    early = open(str(tmp_path / "obs-0.jsonl")).read()
    late = open(str(tmp_path / "obs-2.jsonl")).read()
    assert "early" in early and "late" in late


def test_jsonl_sink_concurrent_writes(tmp_path):
    sink = obs.JsonlSink(str(tmp_path), rank=0)

    def work(tag):
        for i in range(100):
            sink.write(obs.make_record("event", f"{tag}-{i}"))

    threads = [threading.Thread(target=work, args=(f"t{j}",))
               for j in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    sink.close()
    lines = open(str(tmp_path / "obs-0.jsonl")).read().splitlines()
    assert len(lines) == 400
    for line in lines:  # no interleaved/torn writes
        assert obs.validate_record(json.loads(line)) == []


# -- PR 4: schema v2 (cost kind) and the disk-usage cap ---------------

def test_v1_records_still_validate():
    rec = {"v": 1, "kind": "span", "ts": 1.0, "rank": 0,
           "name": "s", "path": "s", "dur_s": 0.1}
    assert obs.validate_record(rec) == []


def test_cost_records_require_v2():
    rec = {"v": 2, "kind": "cost", "ts": 1.0, "rank": 0,
           "name": "site", "site": "site", "flops": 1.0,
           "unavailable": "x"}
    assert obs.validate_record(rec) == []
    rec["v"] = 1
    assert any("require schema v>=2" in e
               for e in obs.validate_record(rec))
    rec["v"] = 3  # v3 (trace fields) accepts cost records too
    assert obs.validate_record(rec) == []
    rec["v"] = 4  # v4 (progress/fit_id) accepts cost records too
    assert obs.validate_record(rec) == []
    rec["v"] = 5  # future versions still rejected
    assert any("v=5" in e for e in obs.validate_record(rec))


def test_cost_record_unknown_key_rejected():
    rec = {"v": 2, "kind": "cost", "ts": 1.0, "rank": 0,
           "name": "s", "site": "s", "flopz": 1.0}
    assert any("unknown key" in e for e in obs.validate_record(rec))


def test_max_mb_cap_truncates_with_one_marker(tmp_path,
                                              monkeypatch):
    monkeypatch.setenv(obs_sink.OBS_MAX_MB_ENV, "0.001")  # ~1 KB
    sink = obs.JsonlSink(str(tmp_path), rank=0)
    for i in range(100):
        sink.write(obs.make_record("event", f"e{i}",
                                   attrs={"pad": "x" * 64}))
    sink.close()
    lines = open(str(tmp_path / "obs-0.jsonl")).read().splitlines()
    # far fewer than 100 lines made it; the truncation marker comes
    # last-but-one, the close-time drop count last (ISSUE 12
    # satellite: dropped records are counted, not silent)
    assert len(lines) < 50
    marker = json.loads(lines[-2])
    assert marker["name"] == "obs_truncated"
    assert abs(marker["attrs"]["limit_mb"] - 0.001) < 1e-5
    dropped = json.loads(lines[-1])
    assert dropped["name"] == "obs_dropped"
    # every record past the cap was counted: written events plus
    # dropped count account for all 100 writes (the marker and the
    # stamp are the sink's own two lines)
    n_written_events = len(lines) - 2
    assert dropped["attrs"]["dropped_total"] == \
        100 - n_written_events
    assert all(json.loads(line)["name"] != "obs_truncated"
               for line in lines[:-2])
    # the cap bounds the file size (markers included)
    assert os.path.getsize(str(tmp_path / "obs-0.jsonl")) \
        < 2 * 1024


def test_max_mb_env_activated_sink_truncates(tmp_path, monkeypatch):
    monkeypatch.setenv(obs.OBS_DIR_ENV, str(tmp_path))
    monkeypatch.setenv(obs_sink.OBS_MAX_MB_ENV, "0.0005")
    for i in range(50):
        obs_sink.event("spam", pad="y" * 64)
    obs_sink.close_all()
    lines = open(str(tmp_path / "obs-0.jsonl")).read().splitlines()
    assert json.loads(lines[-2])["name"] == "obs_truncated"
    assert json.loads(lines[-1])["name"] == "obs_dropped"
    assert len(lines) < 50


def test_bad_max_mb_env_is_ignored(tmp_path, monkeypatch):
    monkeypatch.setenv(obs_sink.OBS_MAX_MB_ENV, "lots")
    sink = obs.JsonlSink(str(tmp_path), rank=0)
    assert sink.max_bytes is None
    sink.write(obs.make_record("event", "ok"))
    sink.close()


def test_truncate_close_round_trip_renders_in_report(tmp_path,
                                                     monkeypatch):
    """ISSUE 12 satellite acceptance: cap -> drop -> close stamps
    dropped_total; the report CLI surfaces it as the incompleteness
    headline, and the record round-trips the schema."""
    from brainiak_tpu.obs.report import (aggregate, load_records,
                                         render_text)
    monkeypatch.setenv(obs_sink.OBS_MAX_MB_ENV, "0.0005")
    sink = obs.JsonlSink(str(tmp_path), rank=0)
    for i in range(80):
        sink.write(obs.make_record("event", f"e{i}",
                                   attrs={"pad": "z" * 48}))
    assert sink.dropped_total > 0
    n_dropped = sink.dropped_total
    sink.close()
    # repeated close() must not stamp twice
    sink.close()
    records, errors = load_records(
        [str(tmp_path / "obs-0.jsonl")])
    assert errors == []  # the stamp validates against the schema
    assert sum(1 for r in records
               if r["name"] == "obs_dropped") == 1
    summary = aggregate(records)
    assert summary["dropped_records"] == n_dropped
    text = render_text(summary)
    assert "incomplete" in text and str(n_dropped) in text
    # written + dropped account for every write
    n_events = sum(1 for r in records
                   if r["kind"] == "event"
                   and r["name"].startswith("e"))
    assert n_events + n_dropped == 80


def test_dropped_total_zero_below_cap(tmp_path):
    sink = obs.JsonlSink(str(tmp_path), rank=0, max_mb=10)
    sink.write(obs.make_record("event", "fine"))
    assert sink.dropped_total == 0
    sink.close()
    lines = open(str(tmp_path / "obs-0.jsonl")).read().splitlines()
    # no markers on a healthy close
    assert [json.loads(line)["name"] for line in lines] == ["fine"]


def test_suspended_disables_and_restores(tmp_path, monkeypatch):
    monkeypatch.setenv(obs.OBS_DIR_ENV, str(tmp_path))
    assert obs.enabled()
    with obs_sink.suspended():
        assert not obs.enabled()
        assert obs_sink.all_sinks() == []
        obs_sink.event("invisible")
        with obs_sink.suspended():  # nests
            assert not obs.enabled()
        assert not obs.enabled()
    assert obs.enabled()
    obs_sink.event("visible")
    obs_sink.close_all()
    lines = open(str(tmp_path / "obs-0.jsonl")).read().splitlines()
    assert [json.loads(line)["name"] for line in lines] == \
        ["visible"]


def test_trace_fields_validate_as_v3():
    rec = obs.make_record("span", "serve.submit",
                          path="serve.submit", dur_s=0.001,
                          trace_id="a" * 16, span_id="b" * 8,
                          parent_id="c" * 8)
    assert rec["v"] == obs_sink.SCHEMA_VERSION
    assert obs.validate_record(rec) == []
    # wrong types are rejected
    bad = dict(rec, trace_id=123)
    assert obs.validate_record(bad)
    # v1 spans without trace fields still validate (back-compat)
    old = dict(rec)
    old["v"] = 1
    for key in ("trace_id", "span_id", "parent_id"):
        old.pop(key)
    assert obs.validate_record(old) == []
