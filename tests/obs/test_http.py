"""Live exposition endpoint (ISSUE 12 tentpole part 3).

The stdlib daemon-thread server: /metrics renders the registry as
valid Prometheus text (validated by the same minimal in-repo parser
the OBS002 gate uses), /healthz answers liveness, /readyz delegates
to the injected readiness callback with a JSON detail body."""

import json
import urllib.error
import urllib.request

import pytest

from brainiak_tpu.obs import metrics
from brainiak_tpu.obs.http import (TelemetryServer,
                                   maybe_start_from_env,
                                   parse_prometheus_text,
                                   render_prometheus)


def _get(port, path):
    try:
        with urllib.request.urlopen(
                f"http://127.0.0.1:{port}{path}",
                timeout=10) as resp:
            return resp.status, resp.read().decode("utf-8"), \
                resp.headers.get("Content-Type", "")
    except urllib.error.HTTPError as exc:
        return exc.code, exc.read().decode("utf-8"), \
            exc.headers.get("Content-Type", "")


@pytest.fixture
def server():
    srv = TelemetryServer(port=0, host="127.0.0.1").start()
    yield srv
    srv.stop()


def _seed_metrics():
    metrics.counter("serve_requests_total",
                    help="by outcome").inc(5, kind="srm",
                                           outcome="ok")
    metrics.gauge("serve_queue_depth").set(3, kind="srm")
    hist = metrics.histogram("serve_request_seconds", unit="s")
    for v in (0.01, 0.02, 0.03, 0.5):
        hist.observe(v, kind="srm")


def test_render_parses_clean_and_carries_quantiles():
    _seed_metrics()
    text = render_prometheus()
    families, errors = parse_prometheus_text(text)
    assert errors == []
    assert families["serve_requests_total"]["type"] == "counter"
    assert families["serve_queue_depth"]["type"] == "gauge"
    summary = families["serve_request_seconds"]
    assert summary["type"] == "summary"
    quantiles = {labels["quantile"]: value
                 for name, labels, value in summary["samples"]
                 if "quantile" in labels}
    assert set(quantiles) == {"0.5", "0.9", "0.99"}
    assert quantiles["0.99"] == pytest.approx(0.5, rel=0.02)
    names = {name for name, _, _ in summary["samples"]}
    assert {"serve_request_seconds_sum",
            "serve_request_seconds_count"} <= names


def test_label_escaping_round_trips():
    # the backslash-n value is the order-sensitive case: escaped as
    # \\n it must come back as backslash + literal n, NOT newline
    # (sequential str.replace unescaping got this wrong)
    for value in ('a"b\\c', "tail\\n", "nl\nmid", "\\\\double"):
        metrics.reset()
        metrics.gauge("weird_gauge").set(1.0, path=value)
        families, errors = parse_prometheus_text(
            render_prometheus())
        assert errors == []
        (_, labels, _), = families["weird_gauge"]["samples"]
        assert labels["path"] == value, (value, labels)


def test_parser_flags_malformations():
    _, errors = parse_prometheus_text(
        "# TYPE broken widget\n"
        "orphan_series 1.0\n"
        "# TYPE declared counter\n"
        "declared not-a-number\n")
    assert any("unknown metric type" in e for e in errors)
    assert any("no TYPE/HELP family" in e for e in errors)
    assert any("non-numeric" in e for e in errors)
    assert any("declared but has no samples" in e for e in errors)


def test_metrics_endpoint_live_scrape(server):
    _seed_metrics()
    status, body, ctype = _get(server.port, "/metrics")
    assert status == 200
    assert ctype.startswith("text/plain")
    families, errors = parse_prometheus_text(body)
    assert errors == []
    assert "serve_requests_total" in families


def test_healthz(server):
    status, body, _ = _get(server.port, "/healthz")
    assert status == 200
    assert body.strip() == "ok"


def test_unknown_path_404(server):
    status, body, _ = _get(server.port, "/nope")
    assert status == 404
    assert "/metrics" in body
    assert "/jobs" in body


def test_jobs_endpoint_serves_fit_registry(server):
    from brainiak_tpu.obs.progress import FitProgress

    status, body, ctype = _get(server.port, "/jobs")
    assert status == 200
    assert "json" in ctype
    assert json.loads(body) == {"fits": []}

    fp = FitProgress("SRM.fit", 10, n_chunks=5)
    fp.observe({}, 4, 2, 0.25)
    status, body, _ = _get(server.port, "/jobs")
    assert status == 200
    (fit,) = json.loads(body)["fits"]
    assert fit["fit_id"] == fp.fit_id
    assert fit["estimator"] == "SRM.fit"
    assert fit["status"] == "running"
    assert fit["step"] == 4 and fit["n_iter"] == 10
    assert fit["ratio"] == pytest.approx(0.4)
    fp.finish("completed")
    status, body, _ = _get(server.port, "/jobs")
    (fit,) = json.loads(body)["fits"]
    assert fit["status"] == "completed"


def test_readyz_reflects_callback():
    state = {"ok": False}
    srv = TelemetryServer(
        port=0, host="127.0.0.1",
        readiness=lambda: (state["ok"], {"detail": "warming"}))
    with srv:
        status, body, ctype = _get(srv.port, "/readyz")
        assert status == 503
        payload = json.loads(body)
        assert payload["ready"] is False
        assert payload["detail"] == "warming"
        assert ctype.startswith("application/json")
        state["ok"] = True
        status, body, _ = _get(srv.port, "/readyz")
        assert status == 200
        assert json.loads(body)["ready"] is True
    # without a callback readiness mirrors liveness
    with TelemetryServer(port=0, host="127.0.0.1") as bare:
        status, body, _ = _get(bare.port, "/readyz")
        assert status == 200


def test_start_stop_idempotent_and_ephemeral_port():
    srv = TelemetryServer(port=0, host="127.0.0.1")
    assert srv.port is None
    srv.start()
    port = srv.port
    assert port and port > 0
    assert srv.start() is srv          # idempotent
    assert srv.port == port
    srv.stop()
    srv.stop()                         # idempotent
    assert srv.port is None


def test_maybe_start_from_env(monkeypatch):
    monkeypatch.delenv("BRAINIAK_TPU_OBS_HTTP_PORT", raising=False)
    assert maybe_start_from_env() is None
    monkeypatch.setenv("BRAINIAK_TPU_OBS_HTTP_PORT", "not-a-port")
    assert maybe_start_from_env() is None
    monkeypatch.setenv("BRAINIAK_TPU_OBS_HTTP_PORT", "0")
    srv = maybe_start_from_env()
    try:
        assert srv is not None and srv.port > 0
        status, _, _ = _get(srv.port, "/healthz")
        assert status == 200
    finally:
        srv.stop()
