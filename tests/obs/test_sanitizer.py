"""Checkify sanitizer lane (BRAINIAK_TPU_SANITIZE=1): typed
``sanitizer`` events cross-referencing the JP3xx static rules, the
unsanitizable-chunk fallback, and the off-by-default zero-cost
contract (ISSUE 17 acceptance)."""

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from brainiak_tpu import obs  # noqa: E402
from brainiak_tpu.obs import metrics, sanitize  # noqa: E402
from brainiak_tpu.obs import sink as obs_sink  # noqa: E402
from brainiak_tpu.resilience.guards import (  # noqa: E402
    DivergenceError, run_resilient_loop)


@pytest.fixture(autouse=True)
def _clean_sanitizer():
    sanitize.reset()
    yield
    sanitize.reset()


def _mem():
    return obs_sink.add_sink(obs.MemorySink())


def _events(mem, name):
    return [r for r in mem.records
            if r["kind"] == "event" and r["name"] == name]


def _nan_chunk(state, step, n_steps):
    # sqrt of a negative produces NaN INSIDE the program — the
    # float_checks lane must catch it at the generating primitive
    return {"x": jnp.sqrt(jnp.asarray(state["x"]) - 10.0)}, False


def _host_chunk(state, step, n_steps):
    # np.asarray on a tracer fails: the classic host-side chunk
    # driver run_resilient_loop explicitly supports
    return {"x": np.asarray(state["x"]) + n_steps}, False


def test_call_checked_reports_nan_with_jp_codes():
    mem = _mem()

    @jax.jit
    def prog(x):
        return jnp.log(x)

    error, out = sanitize.call_checked(
        prog, (jnp.asarray([-1.0]),), site="t.site", scope="test")
    assert error is not None and "nan" in error.lower()
    events = _events(mem, "sanitizer")
    assert len(events) == 1
    attrs = events[0]["attrs"]
    assert attrs["site"] == "t.site"
    assert attrs["scope"] == "test"
    assert attrs["codes"] == ["JP301", "JP305"]
    assert metrics.counter("sanitizer_errors_total").value(
        site="t.site", scope="test") == 1.0


def test_call_checked_clean_program_passes_through():
    mem = _mem()

    @jax.jit
    def prog(x):
        return x * 2.0

    error, out = sanitize.call_checked(
        prog, (jnp.asarray([2.0]),), site="t.clean", scope="test")
    assert error is None
    np.testing.assert_allclose(np.asarray(out), [4.0])
    assert _events(mem, "sanitizer") == []


def test_resilient_loop_nan_chunk_becomes_typed_event(monkeypatch):
    """Acceptance: an injected NaN inside a resilient-loop chunk
    surfaces as a typed ``sanitizer`` event AND fails the fit
    through the normal divergence machinery, with the sanitizer —
    not the post-hoc state guard — naming the leaf."""
    monkeypatch.setenv("BRAINIAK_TPU_SANITIZE", "1")
    mem = _mem()
    with pytest.raises(DivergenceError) as exc:
        run_resilient_loop(_nan_chunk, {"x": np.zeros(3)}, 4,
                           checkpoint_every=2, name="sanfit")
    assert exc.value.leaves[0].startswith("sanitizer:")
    events = _events(mem, "sanitizer")
    assert events, "the trip must emit a typed sanitizer event"
    attrs = events[0]["attrs"]
    assert attrs["site"] == "sanfit"
    assert attrs["scope"] == "resilient_loop"
    assert "JP301" in attrs["codes"]
    assert metrics.counter("sanitizer_errors_total").value(
        site="sanfit", scope="resilient_loop") >= 1.0


def test_resilient_loop_host_chunk_skips_once_and_completes(
        monkeypatch):
    """A host-side chunk driver cannot checkify-trace: ONE
    sanitizer_skip event, then the loop runs it unwrapped to the
    same result the lane-off path produces."""
    monkeypatch.setenv("BRAINIAK_TPU_SANITIZE", "1")
    mem = _mem()
    state, step = run_resilient_loop(
        _host_chunk, {"x": np.zeros(1)}, 6, checkpoint_every=2,
        name="hostfit")
    assert step == 6 and state["x"][0] == 6.0
    skips = _events(mem, "sanitizer_skip")
    assert len(skips) == 1
    assert skips[0]["attrs"]["site"] == "hostfit"
    assert _events(mem, "sanitizer") == []


def test_sanitizer_off_is_zero_cost(monkeypatch):
    """Acceptance: with the env var unset the lane adds NOTHING —
    no checked-program builds, no events, no counter series."""
    monkeypatch.delenv("BRAINIAK_TPU_SANITIZE", raising=False)
    assert not sanitize.enabled()
    mem = _mem()
    state, step = run_resilient_loop(
        _host_chunk, {"x": np.zeros(1)}, 4, checkpoint_every=2,
        name="offfit")
    assert step == 4
    assert not sanitize._checked, \
        "no checkify wrapper may be built while the lane is off"
    assert _events(mem, "sanitizer") == []
    assert _events(mem, "sanitizer_skip") == []
    assert metrics.counter("sanitizer_errors_total").value(
        site="offfit", scope="resilient_loop") == 0.0


def test_sanitizer_events_silent_without_sink(monkeypatch):
    """Even tripped checks emit no records when obs is disabled —
    the error return path still works."""
    monkeypatch.setenv("BRAINIAK_TPU_SANITIZE", "1")
    assert not obs_sink.enabled()

    @jax.jit
    def prog(x):
        return jnp.log(x)

    error, _ = sanitize.call_checked(
        prog, (jnp.asarray([-1.0]),), site="t.nosink", scope="test")
    assert error is not None
