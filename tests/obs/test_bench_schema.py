"""Bench record schema (satellite: BENCH_*.json drift fails CI).

Runs the tiny bench tier in-process on CPU and validates the emitted
record against the obs bench schema — metric/value/unit/vs_baseline/
tier keys plus the per-stage time breakdown."""

import bench

from brainiak_tpu import obs


def test_tiny_tier_record_matches_obs_schema(monkeypatch):
    monkeypatch.setenv("BENCH_MID_VOXELS", "64")
    out = bench.measure_tier("mid")
    assert out["voxels_per_sec"] > 0
    stages = out["stages"]
    assert set(bench.STAGE_KEYS) <= set(stages)
    assert all(stages[k] >= 0 for k in bench.STAGE_KEYS)
    # warm (upload+compile) and steady (compute) both actually ran
    assert stages["warm_s"] > 0 and stages["steady_s"] > 0

    rec = bench._result_record(
        "mid_V8192", out["voxels_per_sec"], cpu_vps=100.0,
        config={"n_voxels": 64, "n_epochs": bench.N_EPOCHS,
                "n_trs": bench.N_TRS},
        stages=stages)
    assert obs.validate_bench_record(rec) == []
    assert rec["unit"] == "voxels/sec"
    assert rec["tier"] == "mid_V8192"


def test_cpu_fallback_record_matches_obs_schema():
    rec = bench._result_record(
        "cpu_fallback", 100.0, cpu_vps=50.0,
        stages={"data_gen_s": 0.1, "warm_s": 0.2, "steady_s": 0.3})
    assert obs.validate_bench_record(rec) == []
    assert rec["metric"].endswith("_CPU_FALLBACK_tpu_unresponsive")
    assert rec["vs_baseline"] == 2.0


def test_stage_seconds_fills_missing_stages():
    recs = [{"kind": "span", "name": "bench.steady", "dur_s": 1.5},
            {"kind": "span", "name": "bench.steady", "dur_s": 0.5},
            {"kind": "metric", "name": "bench.warm", "value": 9}]
    stages = bench._stage_seconds(recs)
    assert stages == {"data_gen_s": 0.0, "warm_s": 0.0,
                      "steady_s": 2.0}


# -- PR 4: provenance stamps (schema_version + git commit) ------------

def test_result_record_carries_provenance_stamps():
    from brainiak_tpu.obs.report import BENCH_SCHEMA_VERSION

    rec = bench._result_record(
        "cpu_fallback", 100.0, cpu_vps=50.0,
        stages={"data_gen_s": 0.1, "warm_s": 0.2, "steady_s": 0.3})
    assert rec["schema_version"] == BENCH_SCHEMA_VERSION
    # this test runs inside the repo's git checkout
    assert rec["git_commit"] == bench._git_commit()
    assert obs.validate_bench_record(rec) == []


def test_validator_rejects_bad_stamps():
    base = bench._result_record("cpu_fallback", 100.0, cpu_vps=50.0)
    bad_version = dict(base, schema_version="two")
    assert any("schema_version" in e
               for e in obs.validate_bench_record(bad_version))
    futuristic = dict(base, schema_version=99)
    assert any("newer than supported" in e
               for e in obs.validate_bench_record(futuristic))
    bad_commit = dict(base, git_commit="")
    assert any("git_commit" in e
               for e in obs.validate_bench_record(bad_commit))


# -- PR 5: serve tier -------------------------------------------------

def test_serve_tier_record_matches_obs_schema(monkeypatch):
    """The serve tier (bench.py satellite): a tiny in-process run
    emits a schema-valid bench record with tier="serve" and the
    stage breakdown, so `obs regress` gates serving throughput
    alongside fit throughput."""
    monkeypatch.setenv("BENCH_SERVE_REQUESTS", "12")
    out = bench.measure_tier("serve")
    assert out["requests_per_sec"] > 0
    assert out["baseline_rps"] > 0
    assert 0.0 <= out["padding_waste"] < 1.0
    assert out["retrace_total"] <= out["n_buckets"]
    stages = out["stages"]
    assert set(bench.STAGE_KEYS) <= set(stages)
    assert stages["steady_s"] > 0

    rec = bench._serve_result_record(out, n_requests=12)
    assert obs.validate_bench_record(rec) == []
    # in-process run on the CPU test backend -> the fallback tier
    # (tier separation mirrors the fcma tiers)
    assert rec["tier"] == "serve_cpu_fallback"
    assert rec["config"]["backend"] == "cpu"
    assert rec["unit"] == "requests/sec"
    assert rec["metric"] == "serve_srm_transform_requests_per_sec"
    assert rec["config"]["n_buckets"] == out["n_buckets"]


def test_distla_tier_record_matches_obs_schema(monkeypatch):
    """The distla tier (ISSUE 6 satellite): a tiny in-process run
    emits a schema-valid bench record with the backend-split tier,
    so `obs regress --only distla` gates SUMMA-Gram throughput
    alongside fit and serving throughput."""
    monkeypatch.setenv("BENCH_DISTLA_VOXELS", "256")
    out = bench.measure_tier("distla")
    assert out["voxels_per_sec"] > 0
    assert out["n_voxels"] == 256
    assert out["n_shards"] >= 1
    stages = out["stages"]
    assert set(bench.STAGE_KEYS) <= set(stages)
    assert stages["steady_s"] > 0

    rec = bench._distla_result_record(out)
    assert obs.validate_bench_record(rec) == []
    # in-process run on the CPU test backend -> the fallback tier
    # (tier separation mirrors the fcma/serve tiers)
    assert rec["tier"] == "distla_cpu_fallback"
    assert rec["unit"] == "voxels/sec"
    assert rec["metric"] == "distla_summa_gram_voxels_per_sec"
    assert rec["config"]["n_voxels"] == 256
    assert rec["config"]["n_shards"] == out["n_shards"]
    assert rec["vs_baseline"] > 0


def test_encoding_tier_record_matches_obs_schema(monkeypatch):
    """The encoding tier (ISSUE 7): a tiny in-process run emits a
    schema-valid bench record with the backend-split tier, so
    `obs regress --only encoding` gates ridge-CV throughput
    alongside fit, serving, and SUMMA-Gram throughput."""
    monkeypatch.setenv("BENCH_ENCODING_VOXELS", "128")
    out = bench.measure_tier("encoding")
    assert out["voxels_lambdas_per_sec"] > 0
    assert out["n_voxels"] == 128
    assert out["n_lambdas"] == bench.ENCODING_N_LAMBDAS
    stages = out["stages"]
    assert set(bench.STAGE_KEYS) <= set(stages)
    assert stages["steady_s"] > 0

    rec = bench._encoding_result_record(out)
    assert obs.validate_bench_record(rec) == []
    # in-process run on the CPU test backend -> the fallback tier
    assert rec["tier"] == "encoding_cpu_fallback"
    assert rec["unit"] == "voxels*lambdas/sec"
    assert rec["metric"] == \
        "encoding_ridge_cv_voxels_lambdas_per_sec"
    assert rec["config"]["n_voxels"] == 128
    assert rec["config"]["n_folds"] == bench.ENCODING_FOLDS
    assert rec["vs_baseline"] > 0


# -- ISSUE 9: service tier --------------------------------------------

def test_service_tier_records_match_obs_schema(monkeypatch):
    """The service tier emits FOUR schema-valid records per round —
    steady-state requests/s plus p99 latency, padding waste, and
    (ISSUE 12) the telemetry overhead ratio (full tracing + SLO +
    exposition vs obs suspended), the latter three stamped
    direction="lower_is_better" so `obs regress --only service`
    gates them mirrored."""
    monkeypatch.setenv("BENCH_SERVICE_REQUESTS", "16")
    out = bench.measure_tier("service")
    assert out["requests_per_sec"] > 0
    assert out["p99_latency_s"] > 0
    assert 0.0 <= out["padding_waste"] < 1.0
    assert out["baseline_rps"] > 0
    # the overhead lane ran: a real positive ratio (obs-on work
    # can only add time, but timer jitter at toy sizes keeps this
    # a sanity bound, not >= 1.0)
    assert out["obs_overhead_ratio"] > 0
    stages = out["stages"]
    assert set(bench.STAGE_KEYS) <= set(stages)
    assert stages["steady_s"] > 0

    recs = bench._service_result_records(out, n_requests=16)
    assert [r["metric"] for r in recs] == [
        "service_mixed_requests_per_sec",
        "service_p99_latency_seconds",
        "service_padding_waste_ratio",
        "service_obs_overhead_ratio"]
    for rec in recs:
        assert obs.validate_bench_record(rec) == []
        # in-process CPU test backend -> the fallback tier
        assert rec["tier"] == "service_cpu_fallback"
        assert rec["config"]["n_requests"] == 16
    assert "direction" not in recs[0]
    assert recs[1]["direction"] == "lower_is_better"
    assert recs[2]["direction"] == "lower_is_better"
    assert recs[3]["direction"] == "lower_is_better"
    assert recs[3]["value"] > 0


def test_kernels_tier_records_match_obs_schema(monkeypatch):
    """The kernels tier (ISSUE 11): a tiny in-process run emits TWO
    schema-valid bench records (fused forward-backward TRs/s, fused
    ring step GB/s) whose ``vs_baseline`` is the measured
    fused-vs-unfused ratio, with the backend-split tier, so
    ``obs regress --only kernels`` gates the fused kernels
    alongside the other tiers."""
    monkeypatch.setenv("BENCH_KERNELS_TRS", "64")
    monkeypatch.setenv("BENCH_KERNELS_VOXELS", "256")
    out = bench.measure_tier("kernels")
    assert out["fb_trs_per_sec"] > 0
    assert out["fb_reference_trs_per_sec"] > 0
    assert out["ring_gb_per_sec"] > 0
    stages = out["stages"]
    assert set(bench.STAGE_KEYS) <= set(stages)
    assert stages["steady_s"] > 0

    recs = bench._kernels_result_records(out)
    assert [r["metric"] for r in recs] == [
        "kernels_eventseg_fb_trs_per_sec",
        "kernels_summa_ring_gb_per_sec"]
    for rec in recs:
        assert obs.validate_bench_record(rec) == []
        assert rec["tier"] == "kernels_cpu_fallback"
        assert rec["vs_baseline"] > 0
    assert recs[0]["config"]["n_trs"] == 64
    assert recs[1]["config"]["n_voxels"] == 256


# -- ISSUE 13: streaming tier -----------------------------------------

def test_streaming_tier_records_match_obs_schema(monkeypatch):
    """The streaming tier (ISSUE 13): a tiny in-process run emits
    TWO schema-valid records — streamed subjects/s (vs_baseline =
    ratio over the in-memory stacked fit) and the prefetch stall
    ratio stamped direction="lower_is_better" — so `obs regress
    --only streaming` gates the out-of-core data plane from day
    one."""
    monkeypatch.setenv("BENCH_STREAMING_SUBJECTS", "8")
    out = bench.measure_tier("streaming")
    assert out["subjects_per_sec"] > 0
    assert out["n_subjects"] == 8
    assert out["stack_bytes"] > 0
    assert 0.0 <= out["stall_ratio"]
    stages = out["stages"]
    assert set(bench.STAGE_KEYS) <= set(stages)
    assert stages["steady_s"] > 0

    recs = bench._streaming_result_records(out)
    assert [r["metric"] for r in recs] == [
        "streaming_srm_subjects_per_sec",
        "streaming_prefetch_stall_ratio"]
    for rec in recs:
        assert obs.validate_bench_record(rec) == []
        # in-process run on the CPU test backend -> fallback tier
        assert rec["tier"] == "streaming_cpu_fallback"
        assert rec["config"]["n_subjects"] == 8
        assert rec["config"]["stack_bytes"] == out["stack_bytes"]
    assert recs[0]["vs_baseline"] > 0
    assert recs[1]["direction"] == "lower_is_better"


# -- ISSUE 14: federation tier ----------------------------------------

def test_federation_tier_records_match_obs_schema(monkeypatch):
    """The federation tier (ISSUE 14): a tiny in-process run emits
    THREE schema-valid records — routed requests/s across 2
    replicas (vs_baseline = the win over one replica on the same
    workload), accepted-request p99 under the 2x-capacity overload
    burst and the shed ratio (both direction="lower_is_better") —
    so `obs regress --only federation` gates the federation plane
    from day one."""
    monkeypatch.setenv("BENCH_FEDERATION_REQUESTS", "16")
    out = bench.measure_tier("federation")
    assert out["routed_requests_per_sec"] > 0
    assert out["single_replica_rps"] > 0
    assert out["overload_p99_s"] > 0
    assert out["n_replicas"] == 2
    # the atomic overload burst admits exactly the fleet bound and
    # sheds the deterministic rest (2x fleet capacity -> ratio 0.5)
    assert out["shed_ratio"] == 0.5
    assert out["overload_burst"] == 4 * out["shed_bound"]
    assert all(v > 0 for v in out["routed"].values())
    stages = out["stages"]
    assert set(bench.STAGE_KEYS) <= set(stages)
    assert stages["steady_s"] > 0

    recs = bench._federation_result_records(out)
    assert [r["metric"] for r in recs] == [
        "federation_routed_requests_per_sec",
        "federation_overload_p99_seconds",
        "federation_shed_ratio"]
    for rec in recs:
        assert obs.validate_bench_record(rec) == []
        # in-process run on the CPU test backend -> fallback tier
        assert rec["tier"] == "federation_cpu_fallback"
        assert rec["config"]["n_requests"] == 16
        assert rec["config"]["n_replicas"] == 2
    assert recs[0]["vs_baseline"] > 0
    assert "direction" not in recs[0]
    assert recs[1]["direction"] == "lower_is_better"
    assert recs[2]["direction"] == "lower_is_better"


# -- ISSUE 15: realtime tier ------------------------------------------

def test_realtime_tier_records_match_obs_schema(monkeypatch):
    """The realtime tier (ISSUE 15): a short in-process closed-loop
    scan off the seeded fmrisim source emits TWO schema-valid
    records — per-TR p99 latency and the deadline-miss ratio, BOTH
    direction="lower_is_better" (the tier is latency-bound) — so
    `obs regress --only realtime` gates the closed-loop SLO from
    day one."""
    monkeypatch.setenv("BENCH_REALTIME_TRS", "30")
    out = bench.measure_tier("realtime")
    assert out["n_trs"] == 30
    assert out["p99_latency_s"] > 0
    assert 0.0 <= out["miss_ratio"] <= 1.0
    assert out["n_voxels"] > 0
    stages = out["stages"]
    assert set(bench.STAGE_KEYS) <= set(stages)
    assert stages["steady_s"] > 0

    recs = bench._realtime_result_records(out)
    assert [r["metric"] for r in recs] == [
        "realtime_tr_p99_latency_seconds",
        "realtime_deadline_miss_ratio"]
    for rec in recs:
        assert obs.validate_bench_record(rec) == []
        # in-process run on the CPU test backend -> fallback tier
        assert rec["tier"] == "realtime_cpu_fallback"
        assert rec["config"]["n_trs"] == 30
        assert rec["config"]["deadline_s"] == \
            bench.REALTIME_DEADLINE_S
        assert rec["direction"] == "lower_is_better"


# -- ISSUE 20: jobs tier ----------------------------------------------

def test_jobs_tier_records_match_obs_schema(monkeypatch):
    """The jobs tier (ISSUE 20): a short in-process scheduled-fit
    round co-scheduled with serving waves emits THREE schema-valid
    records — scheduled jobs/s (vs_baseline = scheduled/solo rate),
    co-scheduled serving p99 and jobs_lost (both lower_is_better,
    jobs_lost against a zero baseline) — so `obs regress --only
    jobs` gates control-plane throughput from day one."""
    monkeypatch.setenv("BENCH_JOBS_COUNT", "2")
    out = bench.measure_tier("jobs")
    assert out["n_jobs"] == 2
    assert out["jobs_per_sec"] > 0
    assert out["solo_jobs_per_sec"] > 0
    assert out["jobs_lost"] == 0 and out["lost"] == []
    assert out["n_serve_requests"] > 0
    stages = out["stages"]
    assert set(bench.STAGE_KEYS) <= set(stages)
    assert stages["warm_s"] > 0 and stages["steady_s"] > 0

    recs = bench._jobs_result_records(out)
    assert [r["metric"] for r in recs] == [
        "jobs_scheduled_jobs_per_sec",
        "jobs_coserve_p99_latency_seconds",
        "jobs_lost"]
    for rec in recs:
        assert obs.validate_bench_record(rec) == []
        # in-process run on the CPU test backend -> fallback tier
        assert rec["tier"] == "jobs_cpu_fallback"
        assert rec["config"]["n_jobs"] == 2
        assert rec["config"]["n_tenants"] == 2
        assert rec["config"]["max_slots"] == bench.JOBS_MAX_SLOTS
    assert recs[0]["vs_baseline"] > 0
    assert "direction" not in recs[0]
    assert recs[1]["direction"] == "lower_is_better"
    assert recs[2]["direction"] == "lower_is_better"
