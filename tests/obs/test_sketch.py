"""Mergeable quantile sketches (ISSUE 12 tentpole part 2).

The guarantees the serving plane rides on: bounded relative error
against exact rank statistics, EXACT merge (pooled replica
quantiles keep the single-sketch bound — the acceptance criterion),
bounded memory, and a JSON round-trip for cross-process travel."""

import json

import numpy as np
import pytest

from brainiak_tpu.obs.sketch import (DEFAULT_RELATIVE_ACCURACY,
                                     QuantileSketch)


def _exact(values, q):
    """Nearest-rank percentile, the sketch's documented convention
    (and the serve summary's historical one)."""
    ordered = sorted(values)
    idx = min(len(ordered) - 1,
              int(round(q * (len(ordered) - 1))))
    return ordered[idx]


def test_relative_error_bound_lognormal():
    rng = np.random.RandomState(0)
    values = rng.lognormal(mean=-3.0, sigma=1.5, size=20000)
    sketch = QuantileSketch()
    for v in values:
        sketch.observe(float(v))
    for q in (0.01, 0.1, 0.5, 0.9, 0.99, 0.999):
        true = _exact(values, q)
        est = sketch.quantile(q)
        assert abs(est - true) <= \
            DEFAULT_RELATIVE_ACCURACY * true + 1e-12, (q, est, true)


def test_relative_error_bound_across_scales():
    """The log-bucket bound holds from microseconds to hours with no
    prior scale hint."""
    rng = np.random.RandomState(1)
    values = np.concatenate([
        rng.uniform(1e-6, 1e-5, 500),
        rng.uniform(0.01, 0.1, 500),
        rng.uniform(100.0, 5000.0, 500)])
    rng.shuffle(values)
    sketch = QuantileSketch()
    for v in values:
        sketch.observe(float(v))
    for q in (0.1, 0.5, 0.9):
        true = _exact(values, q)
        assert abs(sketch.quantile(q) - true) <= \
            DEFAULT_RELATIVE_ACCURACY * true + 1e-15


def test_merge_is_exact():
    """merge() is bucket-wise addition: indistinguishable from
    observing both streams into one sketch."""
    rng = np.random.RandomState(2)
    a_vals = rng.exponential(0.05, 5000)
    b_vals = rng.exponential(0.5, 3000)  # a slower replica
    pooled = QuantileSketch()
    a = QuantileSketch()
    b = QuantileSketch()
    for v in a_vals:
        a.observe(float(v))
        pooled.observe(float(v))
    for v in b_vals:
        b.observe(float(v))
        pooled.observe(float(v))
    a.merge(b)
    assert a.count == pooled.count == 8000
    assert a.sum == pytest.approx(pooled.sum)
    for q in (0.05, 0.5, 0.95, 0.99):
        assert a.quantile(q) == pooled.quantile(q)


def test_merged_pooled_p99_within_documented_bound():
    """The ISSUE 12 acceptance: two replica sketches, merged,
    reproduce the pooled p99 within the documented relative-error
    bound."""
    rng = np.random.RandomState(3)
    rep1 = rng.lognormal(-2.5, 1.0, 4000)
    rep2 = rng.lognormal(-1.5, 0.7, 6000)
    s1 = QuantileSketch()
    s2 = QuantileSketch()
    for v in rep1:
        s1.observe(float(v))
    for v in rep2:
        s2.observe(float(v))
    merged = QuantileSketch.from_dict(s1.to_dict()).merge(s2)
    true_p99 = _exact(np.concatenate([rep1, rep2]), 0.99)
    assert abs(merged.quantile(0.99) - true_p99) <= \
        DEFAULT_RELATIVE_ACCURACY * true_p99


def test_merge_rejects_mismatched_accuracy_and_type():
    a = QuantileSketch(relative_accuracy=0.01)
    b = QuantileSketch(relative_accuracy=0.05)
    with pytest.raises(ValueError, match="relative"):
        a.merge(b)
    with pytest.raises(TypeError):
        a.merge([1, 2, 3])


def test_json_round_trip():
    sketch = QuantileSketch()
    for v in (0.0, 1e-6, 0.25, 0.25, 7.5, -3.0):
        sketch.observe(v)
    wire = json.loads(json.dumps(sketch.to_dict()))
    back = QuantileSketch.from_dict(wire)
    assert back.count == sketch.count
    assert back.sum == pytest.approx(sketch.sum)
    assert back.min == sketch.min
    assert back.max == sketch.max
    for q in (0.0, 0.25, 0.5, 0.99, 1.0):
        assert back.quantile(q) == sketch.quantile(q)


def test_zero_negative_and_edge_quantiles():
    sketch = QuantileSketch()
    for v in (-2.0, -1.0, 0.0, 0.0, 1.0, 2.0):
        sketch.observe(v)
    assert sketch.quantile(0.0) == pytest.approx(-2.0, rel=0.02)
    assert sketch.quantile(0.5) == 0.0
    assert sketch.quantile(1.0) == pytest.approx(2.0, rel=0.02)
    assert sketch.min == -2.0
    assert sketch.max == 2.0


def test_empty_and_invalid_inputs():
    sketch = QuantileSketch()
    assert sketch.quantile(0.5) is None
    assert sketch.quantiles((0.5, 0.99)) == [None, None]
    with pytest.raises(ValueError):
        sketch.observe(float("nan"))
    with pytest.raises(ValueError):
        sketch.observe(float("inf"))
    with pytest.raises(ValueError):
        sketch.quantile(1.5)
    with pytest.raises(ValueError):
        QuantileSketch(relative_accuracy=0.0)
    with pytest.raises(ValueError):
        QuantileSketch(max_buckets=1)


def test_memory_bound_collapses_low_buckets():
    """max_buckets bounds the store; quantiles ABOVE the collapse
    boundary keep their error bound (the tail is the product — the
    collapsed low end degrades toward the boundary, by design)."""
    rng = np.random.RandomState(4)
    values = rng.uniform(1.0, 100.0, 30000)
    sketch = QuantileSketch(max_buckets=64)
    for v in values:
        sketch.observe(float(v))
    assert len(sketch._buckets) <= 64
    boundary = sketch._bucket_value(min(sketch._buckets))
    for q in (0.8, 0.9, 0.99):
        true = _exact(values, q)
        assert true > boundary  # the tail stayed un-collapsed
        assert abs(sketch.quantile(q) - true) <= \
            DEFAULT_RELATIVE_ACCURACY * true
    # the collapsed low end reports at most the boundary region —
    # bounded memory, degraded-but-sane low quantiles
    assert sketch.quantile(0.01) <= boundary * (1.02)


def test_observe_is_o1_state():
    """count/sum/min/max track exactly regardless of bucketing."""
    sketch = QuantileSketch()
    values = [0.003, 0.5, 0.0021, 12.0, 0.5]
    for v in values:
        sketch.observe(v)
    assert sketch.count == 5
    assert sketch.sum == pytest.approx(sum(values))
    assert sketch.min == 0.0021
    assert sketch.max == 12.0
