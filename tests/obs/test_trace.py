"""Request-scoped tracing (ISSUE 12 tentpole part 1).

Unit coverage for :mod:`brainiak_tpu.obs.trace`: id minting, chain
advancement, npz inject/extract, connectivity reconstruction, the
obs-disabled zero-overhead contract, and schema-v3 record validity.
The end-to-end in-process service chain lives in
``tests/serve/test_telemetry.py``; the cross-process CLI continuity
acceptance in ``tests/serve/test_trace_continuity.py``."""

import numpy as np

from brainiak_tpu.obs import sink as obs_sink
from brainiak_tpu.obs import trace as obs_trace
from brainiak_tpu.serve.batching import (Request, load_requests,
                                         save_requests)


def _req(**kwargs):
    return Request(request_id="r0", x=np.zeros((4, 4)), **kwargs)


def test_ids_are_fresh_and_well_formed():
    tids = {obs_trace.new_trace_id() for _ in range(64)}
    sids = {obs_trace.new_span_id() for _ in range(64)}
    assert len(tids) == 64 and len(sids) == 64
    assert all(len(t) == 16 and int(t, 16) >= 0 for t in tids)
    assert all(len(s) == 8 and int(s, 16) >= 0 for s in sids)


def test_start_trace_disabled_mints_nothing():
    req = _req()
    assert obs_trace.start_trace(req) is None
    assert req.trace_id is None
    # a pre-assigned id survives untouched even while disabled
    req2 = _req(trace_id="deadbeefdeadbeef")
    assert obs_trace.start_trace(req2) == "deadbeefdeadbeef"


def test_traced_span_advances_chain_and_validates():
    mem = obs_sink.add_sink(obs_sink.MemorySink())
    req = _req(parent_id="11112222")  # an upstream process's span
    tid = obs_trace.start_trace(req)
    assert tid is not None
    s1 = obs_trace.traced_span("stage.one", 0.01, req,
                               attrs={"k": 1})
    s2 = obs_trace.traced_span("stage.two", 0.02, req)
    assert req.parent_id == s2 != s1
    recs = mem.records
    assert [r["name"] for r in recs] == ["stage.one", "stage.two"]
    assert recs[0]["parent_id"] == "11112222"
    assert recs[1]["parent_id"] == s1
    assert all(r["trace_id"] == tid for r in recs)
    assert all(obs_sink.validate_record(r) == [] for r in recs)
    assert all(r["v"] == obs_sink.SCHEMA_VERSION for r in recs)


def test_traced_span_noop_disabled_or_untraced():
    # disabled: nothing emitted even for a traced request
    req = _req(trace_id="deadbeefdeadbeef")
    assert obs_trace.traced_span("s", 0.0, req) is None
    # enabled but untraced request: still a no-op
    mem = obs_sink.add_sink(obs_sink.MemorySink())
    req2 = _req()
    assert obs_trace.traced_span("s", 0.0, req2) is None
    assert mem.records == []


def test_npz_inject_extract_round_trip(tmp_path):
    path = str(tmp_path / "reqs.npz")
    tid, pid = obs_trace.new_trace_id(), obs_trace.new_span_id()
    save_requests(path, [np.zeros((4, 4)), np.ones((2, 2))],
                  ids=["a", "b"],
                  traces=[(tid, pid), None])
    back = load_requests(path)
    assert back[0].trace_id == tid
    assert back[0].parent_id == pid
    assert back[1].trace_id is None and back[1].parent_id is None


def test_npz_bare_trace_id_string():
    """A bare string in traces= means (trace_id, no parent)."""
    store = {}
    obs_trace.inject_npz(store, 0, "feedfacefeedface")
    assert "trace.0" in store and "trace_parent.0" not in store


def test_trace_chains_and_connectivity():
    recs = [
        {"kind": "span", "ts": 2.0, "trace_id": "t1",
         "span_id": "b", "parent_id": "a", "name": "mid"},
        {"kind": "span", "ts": 1.0, "trace_id": "t1",
         "span_id": "a", "parent_id": None, "name": "root"},
        {"kind": "span", "ts": 3.0, "trace_id": "t1",
         "span_id": "c", "parent_id": "b", "name": "leaf"},
        {"kind": "span", "ts": 1.5, "trace_id": "t2",
         "span_id": "x", "parent_id": None, "name": "root"},
        {"kind": "span", "ts": 9.0, "name": "untraced",
         "dur_s": 0.0},
    ]
    chains = obs_trace.trace_chains(recs)
    assert set(chains) == {"t1", "t2"}
    assert [r["name"] for r in chains["t1"]] == \
        ["root", "mid", "leaf"]
    assert obs_trace.trace_is_connected(chains["t1"])
    assert obs_trace.trace_is_connected(chains["t2"])
    # two roots = NOT one connected trace
    broken = chains["t1"] + chains["t2"]
    assert not obs_trace.trace_is_connected(broken)
    # an orphan parent that is not a member counts as a root: one
    # external root is fine (cross-process continuation) ...
    ext = [{"kind": "span", "ts": 1.0, "trace_id": "t3",
            "span_id": "m", "parent_id": "upstream", "name": "n"}]
    assert obs_trace.trace_is_connected(ext)
    # ... two distinct orphan parents are a broken chain
    ext.append({"kind": "span", "ts": 2.0, "trace_id": "t3",
                "span_id": "n", "parent_id": "elsewhere",
                "name": "n2"})
    assert not obs_trace.trace_is_connected(ext)
