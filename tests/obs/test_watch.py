"""The watch CLI: /jobs scrape, JSONL-dir reconstruction, frames."""

import json

import pytest

from brainiak_tpu.obs import watch
from brainiak_tpu.obs.http import TelemetryServer
from brainiak_tpu.obs.progress import FitProgress


def _progress_line(fit_id, chunk, step, ts, status=None, **extra):
    rec = {"v": 4, "kind": "progress", "name": "fit_progress",
           "ts": ts, "rank": 0, "fit_id": fit_id,
           "estimator": "SRM.fit", "chunk": chunk, "step": step,
           "n_iter": 10, "ratio": step / 10.0}
    rec.update(extra)
    return json.dumps(rec)


def test_fits_from_url_scrapes_jobs():
    srv = TelemetryServer(port=0, host="127.0.0.1").start()
    try:
        fp = FitProgress("SRM.fit", 10)
        fp.observe({}, 2, 2, 0.1)
        for url in (f"http://127.0.0.1:{srv.port}",
                    f"http://127.0.0.1:{srv.port}/jobs"):
            (fit,) = watch.fits_from_url(url)
            assert fit["fit_id"] == fp.fit_id
    finally:
        srv.stop()


def test_fits_from_dir_last_record_wins(tmp_path):
    a, b = "a" * 16, "b" * 16
    (tmp_path / "obs-0.jsonl").write_text("\n".join([
        _progress_line(a, 1, 2, ts=10.0),
        _progress_line(a, 2, 4, ts=11.0, objective=5.0),
        _progress_line(b, 1, 2, ts=12.0),
        json.dumps({"v": 4, "kind": "event", "ts": 13.0, "rank": 0,
                    "name": "fit_finished", "fit_id": b,
                    "attrs": {"status": "diverged"}}),
    ]) + "\n")
    fits = watch.fits_from_dir(str(tmp_path))
    assert [f["fit_id"] for f in fits] == [a, b]
    assert fits[0]["chunk"] == 2
    assert fits[0]["objective"] == 5.0
    assert fits[1]["status"] == "diverged"


def test_render_frame_table_and_incidents(tmp_path):
    incident = tmp_path / "incidents" / "incident-x"
    incident.mkdir(parents=True)
    (incident / "manifest.json").write_text(json.dumps(
        {"trigger": "divergence_abort", "ts": 1000.0,
         "fit_id": "c" * 16}))
    fits = [{"fit_id": "a" * 16, "estimator": "SRM.fit",
             "chunk": 2, "step": 4, "n_iter": 10, "ratio": 0.4,
             "objective": 3.25, "eta_s": 90.0, "rollbacks": 1,
             "status": "running"}]
    incidents = watch.recent_incidents(str(tmp_path))
    frame = watch.render_frame(fits, incidents, now=2000.0)
    assert "SRM.fit" in frame
    assert "a" * 16 in frame
    assert "4/10" in frame
    assert "3.25" in frame
    assert "1.5m" in frame  # eta formatting
    assert "divergence_abort" in frame
    assert "c" * 16 in frame
    # empty table renders a placeholder, not a crash
    assert "no fits reported" in watch.render_frame([], [],
                                                    now=2000.0)


def test_recent_incidents_empty_and_missing(tmp_path):
    assert watch.recent_incidents("") == []
    assert watch.recent_incidents(str(tmp_path)) == []


def test_watch_cli_once(tmp_path, capsys):
    (tmp_path / "obs-0.jsonl").write_text(
        _progress_line("d" * 16, 3, 6, ts=5.0) + "\n")
    assert watch.main(["--dir", str(tmp_path), "--once"]) == 0
    out = capsys.readouterr().out
    assert "d" * 16 in out
    assert "6/10" in out


def test_watch_cli_url_unreachable_once(capsys):
    assert watch.main(["--url", "http://127.0.0.1:9/",
                       "--once"]) == 1


def test_watch_cli_requires_a_source(monkeypatch):
    from brainiak_tpu.obs.sink import OBS_DIR_ENV
    monkeypatch.delenv(OBS_DIR_ENV, raising=False)
    with pytest.raises(SystemExit):
        watch.main(["--once"])


def test_watch_via_obs_main(tmp_path, capsys):
    from brainiak_tpu.obs.__main__ import main as obs_main
    (tmp_path / "obs-0.jsonl").write_text(
        _progress_line("e" * 16, 1, 2, ts=5.0) + "\n")
    assert obs_main(["watch", "--dir", str(tmp_path),
                     "--once"]) == 0
    assert "e" * 16 in capsys.readouterr().out


# -- ISSUE 20: scheduler view -----------------------------------------

def test_render_frame_scheduler_table_and_tenant_column():
    fits = [{"fit_id": "f" * 16, "estimator": "SRM", "chunk": 2,
             "step": 4, "n_iter": 8, "ratio": 0.5,
             "tenant": "hospital-a", "job_id": "j" * 16}]
    scheduler = {
        "slots": 2, "pressure": True,
        "counts": {"running": 1, "done": 2},
        "tenants": {"hospital-a": {"usage": 6.0, "weight": 1.0,
                                   "virtual_time": 6.0,
                                   "deficit": -1.25}},
        "jobs": [{"job_id": "j" * 16, "tenant": "hospital-a",
                  "kind": "srm", "priority": 1, "state": "running",
                  "chunks": 4.0, "n_preemptions": 2}],
    }
    out = watch.render_frame(fits, scheduler=scheduler, now=0.0)
    # the fit table grows a tenant column when jobs are attributed
    assert "tenant" in out
    assert "hospital-a" in out
    # the scheduler block: header, pressure flag, job row, deficit
    assert "slots=2" in out and "[serving pressure]" in out
    assert "done=2" in out and "running=1" in out
    assert "j" * 16 in out
    assert "srm" in out and "-1.25" in out


def test_render_frame_without_scheduler_has_no_job_table():
    fits = [{"fit_id": "a" * 16, "estimator": "SRM", "step": 1,
             "n_iter": 2, "ratio": 0.5}]
    out = watch.render_frame(fits, now=0.0)
    assert "scheduler" not in out
    assert "tenant" not in out  # no jobs context -> classic table
