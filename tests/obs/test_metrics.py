"""Metric registry: types, labels, records, conflicts, threads."""

import threading

import pytest

from brainiak_tpu import obs
from brainiak_tpu.obs import metrics, sink as obs_sink


def test_counter_accumulates_by_labelset():
    c = obs.counter("fit_steps_total")
    c.inc(5, estimator="SRM")
    c.inc(3, estimator="SRM")
    c.inc(2, estimator="TFA")
    assert c.value(estimator="SRM") == 8
    assert c.value(estimator="TFA") == 2
    assert c.value(estimator="HTFA") == 0.0
    with pytest.raises(ValueError):
        c.inc(-1)


def test_gauge_and_histogram():
    g = obs.gauge("g", unit="bytes")
    g.set(5)
    g.set(7)
    assert g.value() == 7
    h = obs.histogram("h", unit="s")
    for v in (0.1, 0.3, 0.2):
        h.observe(v)
    summary = h.summary()
    assert summary["count"] == 3
    assert summary["min"] == pytest.approx(0.1)
    assert summary["max"] == pytest.approx(0.3)
    assert summary["sum"] == pytest.approx(0.6)


def test_type_conflict_raises():
    obs.counter("conflicted")
    with pytest.raises(ValueError):
        obs.gauge("conflicted")


def test_get_or_create_returns_same_object():
    assert obs.counter("same") is obs.counter("same")


def test_collect_shape():
    obs.counter("a_total").inc(2, site="x")
    obs.gauge("b").set(1.5)
    obs.histogram("c_seconds", unit="s").observe(0.5)
    samples = obs.collect()
    by_name = {s["name"]: s for s in samples}
    assert by_name["a_total"]["value"] == 2
    assert by_name["a_total"]["labels"] == {"site": "x"}
    assert by_name["b"]["value"] == 1.5
    assert by_name["c_seconds"]["value"]["count"] == 1


def test_updates_emit_records_only_when_enabled():
    obs.counter("quiet_total").inc()  # disabled: in-memory only
    mem = obs_sink.add_sink(obs.MemorySink())
    obs.counter("loud_total").inc(2, estimator="SRM")
    obs.histogram("loud_seconds", unit="s").observe(0.25)
    recs = [r for r in mem.records if r["kind"] == "metric"]
    assert [r["name"] for r in recs] == ["loud_total",
                                         "loud_seconds"]
    assert recs[0]["value"] == 2.0
    assert recs[0]["labels"] == {"estimator": "SRM"}
    assert recs[1]["unit"] == "s"
    for rec in recs:
        assert obs.validate_record(rec) == []


def test_counter_thread_safe():
    c = obs.counter("threaded_total")

    def work():
        for _ in range(1000):
            c.inc(site="x")

    threads = [threading.Thread(target=work) for _ in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert c.value(site="x") == 4000


def test_registry_reset_isolates():
    obs.counter("ephemeral_total").inc()
    metrics.reset()
    assert obs.collect() == []


# -- ISSUE 12: sketch-backed histogram quantiles ----------------------

def test_histogram_quantiles_from_sketch():
    h = obs.histogram("q_seconds", unit="s")
    for i in range(1, 101):
        h.observe(i / 100.0, kind="x")
    summary = h.summary(kind="x")
    assert summary["count"] == 100
    # real quantiles, within the sketch's 1% relative error
    # (nearest-rank: p50 of 0.01..1.00 is the 0-based index
    # round(0.5 * 99) = 50 -> 0.51)
    assert summary["p50"] == pytest.approx(0.51, rel=0.02)
    assert summary["p90"] == pytest.approx(0.90, rel=0.02)
    assert summary["p99"] == pytest.approx(0.99, rel=0.02)
    assert h.quantile(0.99, kind="x") == summary["p99"]
    assert h.quantile(0.5, kind="nope") is None
    # collect() carries the quantile fields for the exposition
    (sample,) = [s for s in obs.collect()
                 if s["name"] == "q_seconds"]
    assert sample["value"]["p99"] == summary["p99"]


def test_histogram_sketch_copy_is_mergeable():
    h = obs.histogram("m_seconds", unit="s")
    for v in (0.1, 0.2, 0.3):
        h.observe(v, shard="a")
    for v in (1.0, 2.0):
        h.observe(v, shard="b")
    a = h.sketch(shard="a")
    b = h.sketch(shard="b")
    assert h.sketch(shard="zzz") is None
    a.merge(b)
    assert a.count == 5
    # the copy is detached: merging did not corrupt the live metric
    assert h.summary(shard="a")["count"] == 3
    assert a.quantile(1.0) == pytest.approx(2.0, rel=0.02)


def test_collect_carries_help_text():
    obs.counter("helped_total", help="the help line").inc()
    (sample,) = [s for s in obs.collect()
                 if s["name"] == "helped_total"]
    assert sample["help"] == "the help line"
