"""Metric registry: types, labels, records, conflicts, threads."""

import threading

import pytest

from brainiak_tpu import obs
from brainiak_tpu.obs import metrics, sink as obs_sink


def test_counter_accumulates_by_labelset():
    c = obs.counter("fit_steps_total")
    c.inc(5, estimator="SRM")
    c.inc(3, estimator="SRM")
    c.inc(2, estimator="TFA")
    assert c.value(estimator="SRM") == 8
    assert c.value(estimator="TFA") == 2
    assert c.value(estimator="HTFA") == 0.0
    with pytest.raises(ValueError):
        c.inc(-1)


def test_gauge_and_histogram():
    g = obs.gauge("g", unit="bytes")
    g.set(5)
    g.set(7)
    assert g.value() == 7
    h = obs.histogram("h", unit="s")
    for v in (0.1, 0.3, 0.2):
        h.observe(v)
    summary = h.summary()
    assert summary["count"] == 3
    assert summary["min"] == pytest.approx(0.1)
    assert summary["max"] == pytest.approx(0.3)
    assert summary["sum"] == pytest.approx(0.6)


def test_type_conflict_raises():
    obs.counter("conflicted")
    with pytest.raises(ValueError):
        obs.gauge("conflicted")


def test_get_or_create_returns_same_object():
    assert obs.counter("same") is obs.counter("same")


def test_collect_shape():
    obs.counter("a_total").inc(2, site="x")
    obs.gauge("b").set(1.5)
    obs.histogram("c_seconds", unit="s").observe(0.5)
    samples = obs.collect()
    by_name = {s["name"]: s for s in samples}
    assert by_name["a_total"]["value"] == 2
    assert by_name["a_total"]["labels"] == {"site": "x"}
    assert by_name["b"]["value"] == 1.5
    assert by_name["c_seconds"]["value"]["count"] == 1


def test_updates_emit_records_only_when_enabled():
    obs.counter("quiet_total").inc()  # disabled: in-memory only
    mem = obs_sink.add_sink(obs.MemorySink())
    obs.counter("loud_total").inc(2, estimator="SRM")
    obs.histogram("loud_seconds", unit="s").observe(0.25)
    recs = [r for r in mem.records if r["kind"] == "metric"]
    assert [r["name"] for r in recs] == ["loud_total",
                                         "loud_seconds"]
    assert recs[0]["value"] == 2.0
    assert recs[0]["labels"] == {"estimator": "SRM"}
    assert recs[1]["unit"] == "s"
    for rec in recs:
        assert obs.validate_record(rec) == []


def test_counter_thread_safe():
    c = obs.counter("threaded_total")

    def work():
        for _ in range(1000):
            c.inc(site="x")

    threads = [threading.Thread(target=work) for _ in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert c.value(site="x") == 4000


def test_registry_reset_isolates():
    obs.counter("ephemeral_total").inc()
    metrics.reset()
    assert obs.collect() == []
