"""Flight-recorder ring + incident snapshots + postmortem CLI."""

import json
import os
import threading

from brainiak_tpu.obs import flight, postmortem
from brainiak_tpu.obs import sink as obs_sink


def _rec(i, kind="event", **fields):
    rec = {"v": obs_sink.SCHEMA_VERSION, "kind": kind,
           "name": f"r{i}", "ts": float(i), "rank": 0}
    rec.update(fields)
    return rec


def test_ring_appends_and_snapshots():
    for i in range(5):
        flight.record(_rec(i))
    recs = flight.records()
    assert [r["name"] for r in recs] == [f"r{i}" for i in range(5)]
    # snapshot is a copy: mutating it leaves the ring alone
    recs.append(_rec(99))
    assert len(flight.records()) == 5


def test_ring_overwrites_oldest_at_capacity(monkeypatch):
    monkeypatch.setenv(flight.FLIGHT_RECORDS_ENV, "8")
    for i in range(20):
        flight.record(_rec(i))
    recs = flight.records()
    assert len(recs) == 8
    assert [r["name"] for r in recs] == \
        [f"r{i}" for i in range(12, 20)]


def test_ring_capacity_env_validation(monkeypatch):
    monkeypatch.setenv(flight.FLIGHT_RECORDS_ENV, "not-a-number")
    assert flight.capacity() == flight.DEFAULT_CAPACITY
    monkeypatch.setenv(flight.FLIGHT_RECORDS_ENV, "0")
    assert flight.capacity() == flight.DEFAULT_CAPACITY


def test_concurrent_appends_never_lose_the_lock(monkeypatch):
    monkeypatch.setenv(flight.FLIGHT_RECORDS_ENV, "64")
    n_threads, per_thread = 8, 200

    def spin(t):
        for i in range(per_thread):
            flight.record(_rec(i, thread=t))

    threads = [threading.Thread(target=spin, args=(t,))
               for t in range(n_threads)]
    for th in threads:
        th.start()
    for th in threads:
        th.join()
    recs = flight.records()
    assert len(recs) == 64  # full ring, no corruption
    assert all(isinstance(r, dict) and "name" in r for r in recs)


def test_sink_emit_taps_the_ring(tmp_path, monkeypatch):
    mem = obs_sink.add_sink(obs_sink.MemorySink())
    try:
        obs_sink.event("ping", x=1)
    finally:
        obs_sink.remove_sink(mem)
    names = [r["name"] for r in flight.records()]
    assert "ping" in names


def test_dump_writes_snapshot_and_manifest(tmp_path):
    for i in range(4):
        flight.record(_rec(i, kind="progress", fit_id="f" * 16,
                           estimator="SRM.fit", chunk=i + 1,
                           step=2 * i, n_iter=8, ratio=i / 4,
                           objective=10.0 - i))
    path = flight.dump("divergence_abort", fit_id="f" * 16,
                       state={"estimator": "SRM.fit", "step": 4},
                       directory=str(tmp_path))
    assert path and os.path.isdir(path)
    with open(os.path.join(path, "manifest.json")) as fh:
        manifest = json.load(fh)
    assert manifest["trigger"] == "divergence_abort"
    assert manifest["fit_id"] == "f" * 16
    assert manifest["n_records"] == 4
    assert manifest["state"]["estimator"] == "SRM.fit"
    with open(os.path.join(path, "records.jsonl")) as fh:
        lines = [json.loads(l) for l in fh if l.strip()]
    assert len(lines) == 4
    assert lines[-1]["chunk"] == 4


def test_dump_resolution_order(tmp_path, monkeypatch):
    flight.record(_rec(0))
    # no directory anywhere -> no snapshot, no crash
    assert flight.dump("trigger") is None
    # $BRAINIAK_TPU_OBS_DIR -> <dir>/incidents
    monkeypatch.setenv(obs_sink.OBS_DIR_ENV, str(tmp_path))
    path = flight.dump("trigger")
    assert path.startswith(str(tmp_path / "incidents"))
    # explicit flight dir wins over the obs dir
    override = tmp_path / "elsewhere"
    monkeypatch.setenv(flight.FLIGHT_DIR_ENV, str(override))
    path = flight.dump("trigger")
    assert path.startswith(str(override))


def test_dump_emits_flight_dump_event_when_enabled(tmp_path):
    flight.record(_rec(0))
    mem = obs_sink.add_sink(obs_sink.MemorySink())
    try:
        flight.dump("sanitizer", directory=str(tmp_path))
    finally:
        obs_sink.remove_sink(mem)
    events = [r for r in mem.records
              if r["name"] == "flight_dump"]
    assert len(events) == 1
    assert events[0]["attrs"]["trigger"] == "sanitizer"


# -- postmortem CLI ---------------------------------------------------

def _snapshot(tmp_path):
    fit = "9" * 16
    flight.record(_rec(0, kind="span", path="fit",
                       dur_s=0.5, fit_id=fit))
    for i in range(6):
        flight.record(_rec(i + 1, kind="progress", fit_id=fit,
                           estimator="SRM.fit", chunk=i + 1,
                           step=2 * (i + 1), n_iter=20,
                           ratio=(i + 1) / 10.0,
                           objective=100.0 - 5 * i, rollbacks=0))
    flight.record(_rec(7, fit_id=fit,
                       name="divergence_precursor",
                       attrs={"estimator": "SRM.fit",
                              "reason": "non_finite_objective"}))
    flight.record(_rec(8, fit_id=fit, name="divergence_abort",
                       attrs={"estimator": "SRM.fit", "step": 10}))
    return flight.dump("divergence_abort", fit_id=fit,
                       state={"estimator": "SRM.fit",
                              "failed_step": 12,
                              "leaves": ["rho2"]},
                       directory=str(tmp_path))


def test_postmortem_renders_snapshot(tmp_path, capsys):
    path = _snapshot(tmp_path)
    assert postmortem.main([path]) == 0
    out = capsys.readouterr().out
    assert "trigger: divergence_abort" in out
    assert "SRM.fit" in out
    assert "<-- implicated" in out
    assert "failed_step: 12" in out
    # the objective tail shows the last OBJECTIVE_TAIL values
    assert "objective tail:" in out
    assert "75@12" in out
    assert "divergence_precursor" in out


def test_postmortem_accepts_manifest_or_records_path(tmp_path):
    path = _snapshot(tmp_path)
    assert postmortem.main(
        [os.path.join(path, "manifest.json")]) == 0
    assert postmortem.main(
        [os.path.join(path, "records.jsonl")]) == 0


def test_postmortem_cli_errors_on_garbage(tmp_path, capsys):
    assert postmortem.main([str(tmp_path / "nope")]) == 1
    bad = tmp_path / "incident"
    bad.mkdir()
    (bad / "records.jsonl").write_text("{not json\n")
    assert postmortem.main([str(bad)]) == 1
    err = capsys.readouterr().err
    assert "bad JSON" in err


def test_postmortem_via_obs_main(tmp_path):
    from brainiak_tpu.obs.__main__ import main as obs_main
    path = _snapshot(tmp_path)
    assert obs_main(["postmortem", path]) == 0


def test_postmortem_names_the_implicated_job(tmp_path, capsys):
    """ISSUE 20: a scheduled fit's incident names the owning job
    (tenant + job_id from the fit_context attrs) in the header and
    in the per-fit section."""
    fit = "b" * 16
    for i in range(3):
        flight.record(_rec(i, kind="progress", fit_id=fit,
                           estimator="SRM.fit", chunk=i + 1,
                           step=i + 1, n_iter=6,
                           ratio=(i + 1) / 6.0, rollbacks=0,
                           attrs={"job_id": "j" * 16,
                                  "tenant": "hospital-a"}))
    flight.record(_rec(3, fit_id=fit, name="divergence_abort",
                       attrs={"estimator": "SRM.fit",
                              "job_id": "j" * 16,
                              "tenant": "hospital-a"}))
    path = flight.dump("divergence_abort", fit_id=fit,
                       directory=str(tmp_path))
    assert postmortem.main([path]) == 0
    out = capsys.readouterr().out
    assert "implicated job: tenant=hospital-a" in out
    assert "job_id=" + "j" * 16 in out
    assert "(job " + "j" * 16 in out


def test_postmortem_without_job_attrs_stays_plain(tmp_path, capsys):
    path = _snapshot(tmp_path)
    assert postmortem.main([path]) == 0
    assert "implicated job" not in capsys.readouterr().out
