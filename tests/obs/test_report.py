"""Report aggregation + CLI (`python -m brainiak_tpu.obs report`)."""

import json
import os

import pytest

from brainiak_tpu import obs
from brainiak_tpu.obs import report, sink as obs_sink

FIXTURE = os.path.join(os.path.dirname(__file__), "..", "..",
                       "tools", "obs_fixture.jsonl")


def _write_trace(tmp_path, monkeypatch):
    monkeypatch.setenv(obs.OBS_DIR_ENV, str(tmp_path))
    with obs.span("fit", attrs={"estimator": "SRM.fit"}):
        with obs.span("fit_chunk",
                      attrs={"estimator": "SRM.fit", "step": 0}):
            pass
    obs_sink.event("checkpoint", estimator="SRM.fit", step=5)
    obs.counter("fit_steps_total").inc(5, estimator="SRM.fit")
    obs.counter("fit_steps_total").inc(3, estimator="SRM.fit")
    obs.gauge("g").set(2.0)
    obs.histogram("h", unit="s").observe(0.5)
    obs.histogram("h", unit="s").observe(1.5)
    obs_sink.close_all()
    monkeypatch.delenv(obs.OBS_DIR_ENV)


def test_aggregate_semantics(tmp_path, monkeypatch):
    _write_trace(tmp_path, monkeypatch)
    records, errors = report.load_records([str(tmp_path)])
    assert errors == []
    summary = report.aggregate(records)
    spans = {(r["path"], r["estimator"]): r
             for r in summary["spans"]}
    assert spans[("fit", "SRM.fit")]["count"] == 1
    assert spans[("fit/fit_chunk", "SRM.fit")]["count"] == 1
    assert summary["events"] == [{"name": "checkpoint", "count": 1}]
    mets = {m["name"]: m for m in summary["metrics"]}
    assert mets["fit_steps_total"]["value"] == 8  # counter: sum
    assert mets["g"]["value"] == 2.0              # gauge: last
    hist = mets["h"]["value"]                     # histogram: stats
    assert hist == {"count": 2, "sum": 2.0, "min": 0.5,
                    "max": 1.5, "mean": 1.0}
    text = report.render_text(summary)
    assert "fit/fit_chunk" in text and "fit_steps_total" in text


def test_cli_text_and_json(tmp_path, monkeypatch, capsys):
    _write_trace(tmp_path, monkeypatch)
    assert report.main(["report", str(tmp_path)]) == 0
    out = capsys.readouterr().out
    assert "spans (by path):" in out
    assert report.main(["report", str(tmp_path),
                        "--format=json"]) == 0
    summary = json.loads(capsys.readouterr().out)
    assert summary["schema_errors"] == []
    assert summary["n_records"] > 0


def test_cli_defaults_to_env_dir(tmp_path, monkeypatch, capsys):
    _write_trace(tmp_path, monkeypatch)
    monkeypatch.setenv(obs.OBS_DIR_ENV, str(tmp_path))
    assert report.main(["report", "--format=json"]) == 0
    capsys.readouterr()


def test_cli_fails_on_schema_violation(tmp_path, capsys):
    bad = tmp_path / "obs-0.jsonl"
    bad.write_text('{"v": 1, "kind": "span", "name": "x"}\n'
                   "not json at all\n")
    assert report.main(["report", str(tmp_path),
                        "--format=json"]) == 1
    summary = json.loads(capsys.readouterr().out)
    assert len(summary["schema_errors"]) == 2


def test_cli_errors_without_paths_or_env(monkeypatch):
    monkeypatch.delenv(obs.OBS_DIR_ENV, raising=False)
    with pytest.raises(SystemExit):
        report.main(["report"])


def test_committed_fixture_is_schema_clean():
    records, errors = report.load_records([FIXTURE])
    assert errors == []
    assert len(records) >= 10
    summary = report.aggregate(records)
    assert summary["spans"] and summary["events"] \
        and summary["metrics"]


def test_gauge_last_is_by_timestamp_not_file_order(tmp_path):
    # rank files read in lexical order (obs-10 before obs-2); the
    # chronologically newest set must still win
    def rec(ts, value, rank):
        return {"v": 1, "kind": "metric", "ts": ts, "rank": rank,
                "name": "g", "mtype": "gauge", "value": value}

    (tmp_path / "obs-10.jsonl").write_text(
        json.dumps(rec(200.0, 42.0, 10)) + "\n")
    (tmp_path / "obs-2.jsonl").write_text(
        json.dumps(rec(100.0, 7.0, 2)) + "\n")
    records, errors = report.load_records([str(tmp_path)])
    assert errors == []
    (row,) = report.aggregate(records)["metrics"]
    assert row["value"] == 42.0


def test_validate_bench_record():
    good = {"metric": "m", "value": 1.0, "unit": "voxels/sec",
            "vs_baseline": 2.0, "tier": "mid_V8192",
            "stages": {"data_gen_s": 0.1, "warm_s": 0.2,
                       "steady_s": 0.3}}
    assert obs.validate_bench_record(good) == []
    assert obs.validate_bench_record({}) != []
    bad = dict(good, stages={"data_gen_s": 0.1})
    assert any("warm_s" in e
               for e in obs.validate_bench_record(bad))
    bad = dict(good, value="fast")
    assert any("value" in e
               for e in obs.validate_bench_record(bad))


# -- PR 4: cost rows, roofline join, --top ----------------------------

def _cost_rec(**fields):
    rec = {"v": 2, "kind": "cost", "ts": 1.0, "rank": 0,
           "name": fields.get("site", "s")}
    rec.update(fields)
    assert obs_sink.validate_record(rec) == []
    return rec


def _span_rec(path, dur_s, ts=1.0, estimator=None):
    attrs = {"estimator": estimator} if estimator else None
    rec = {"v": 1, "kind": "span", "ts": ts, "rank": 0,
           "name": path.split("/")[-1], "path": path,
           "dur_s": dur_s}
    if attrs:
        rec["attrs"] = attrs
    return rec


def test_cost_rows_join_spans_for_roofline():
    records = [
        _cost_rec(site="fcma.sharded_gram", flops=2e9,
                  span="fcma.block", peak_flops=2e12),
        _span_rec("fcma.voxel_selection/fcma.block", 0.5),
        _span_rec("fcma.voxel_selection/fcma.block", 0.5),
    ]
    summary = report.aggregate(records)
    (row,) = summary["cost"]
    # 2 executions x 2e9 FLOPs / 1.0 s = 4e9 FLOP/s
    assert row["achieved_flops_per_s"] == pytest.approx(4e9)
    assert row["roofline_ratio"] == pytest.approx(4e9 / 2e12)
    text = report.render_text(summary)
    assert "cost profiles:" in text and "roofline" in text


def test_cost_estimator_hint_restricts_the_join():
    records = [
        _cost_rec(site="srm.em_chunk", flops=1e6,
                  span="fit_chunk", estimator="SRM.fit"),
        _span_rec("fit/fit_chunk", 1.0, estimator="SRM.fit"),
        _span_rec("fit/fit_chunk", 9.0, estimator="TFA.fit"),
    ]
    (row,) = report.aggregate(records)["cost"]
    # only the SRM.fit second counts: 1e6 FLOPs / 1.0 s
    assert row["achieved_flops_per_s"] == pytest.approx(1e6)


def test_cost_unavailable_row_stays_unannotated():
    records = [_cost_rec(site="x", unavailable="cost_analysis",
                         span="fit_chunk"),
               _span_rec("fit/fit_chunk", 1.0)]
    (row,) = report.aggregate(records)["cost"]
    assert "achieved_flops_per_s" not in row
    assert "unavailable=cost_analysis" in \
        report.render_text(report.aggregate(records))


def test_top_spans_per_estimator():
    records = [
        _span_rec("fit/fit_chunk", 0.1, ts=1.0, estimator="SRM.fit"),
        _span_rec("fit/fit_chunk", 0.9, ts=2.0, estimator="SRM.fit"),
        _span_rec("fit/fit_chunk", 0.5, ts=3.0, estimator="SRM.fit"),
        _span_rec("fcma.block", 0.3, ts=4.0),
    ]
    groups = report.top_spans(records, 2)
    assert [g["estimator"] for g in groups] == ["SRM.fit", None]
    assert [s["dur_s"] for s in groups[0]["spans"]] == [0.9, 0.5]
    assert [s["dur_s"] for s in groups[1]["spans"]] == [0.3]


def test_cli_top_flag(tmp_path, monkeypatch, capsys):
    _write_trace(tmp_path, monkeypatch)
    assert report.main(["report", str(tmp_path), "--top", "3",
                        "--format=json"]) == 0
    summary = json.loads(capsys.readouterr().out)
    assert summary["top_n"] == 3
    ests = {g["estimator"] for g in summary["top_spans"]}
    assert "SRM.fit" in ests
    assert report.main(["report", str(tmp_path), "--top", "1"]) == 0
    assert "slowest spans" in capsys.readouterr().out


def _progress_rec(fit_id, chunk, step, ts, **fields):
    rec = {"v": 4, "kind": "progress", "ts": ts, "rank": 0,
           "name": "fit_progress", "fit_id": fit_id,
           "estimator": "SRM.fit", "chunk": chunk, "step": step,
           "n_iter": 10, "ratio": step / 10.0}
    rec.update(fields)
    assert obs_sink.validate_record(rec) == []
    return rec


def _fit_event(name, fit_id, ts, **attrs):
    return {"v": 4, "kind": "event", "ts": ts, "rank": 0,
            "name": name, "fit_id": fit_id,
            "attrs": attrs or None}


def test_fits_section_verdicts():
    """PR 19: per-fit report rows with a convergence verdict —
    finished fits report their terminal status, an aborted fit is
    diverged, a precursor without completion is diverging, and a
    trailing-off fit is interrupted."""
    done, diverged, diverging, cut = ("d" * 16, "e" * 16,
                                      "f" * 16, "a" * 16)
    records = [
        _progress_rec(done, 1, 5, 1.0, objective=9.0),
        _progress_rec(done, 2, 10, 2.0, objective=4.0,
                      eta_s=0.0),
        _fit_event("fit_finished", done, 2.1, status="converged"),
        _progress_rec(diverged, 1, 5, 3.0, objective=2.0,
                      rollbacks=2),
        _fit_event("divergence_abort", diverged, 3.5,
                   step=4, leaves=["rho2"]),
        _progress_rec(diverging, 1, 5, 4.0, objective=50.0),
        _fit_event("divergence_precursor", diverging, 4.5,
                   reason="worsening_trend"),
        _progress_rec(cut, 1, 5, 5.0),
    ]
    rows = {r["fit_id"]: r
            for r in report.aggregate(records)["fits"]}
    assert rows[done]["verdict"] == "converged"
    assert rows[done]["chunks"] == 2
    assert rows[done]["objective"] == 4.0
    assert rows[diverged]["verdict"] == "diverged"
    assert rows[diverged]["rollbacks"] == 2
    assert rows[diverging]["verdict"] == "diverging"
    assert rows[cut]["verdict"] == "interrupted"
    text = report.render_text(report.aggregate(records))
    assert "fits:" in text
    assert "-> diverged" in text and "-> converged" in text


def test_fits_last_fields_follow_timestamp_not_order():
    fit = "9" * 16
    records = [
        _progress_rec(fit, 2, 8, 20.0, objective=1.5),
        _progress_rec(fit, 1, 4, 10.0, objective=3.0),
    ]
    (row,) = report.aggregate(records)["fits"]
    assert row["step"] == 8
    assert row["objective"] == 1.5
    assert row["chunks"] == 2


def test_roofline_skips_ambiguous_multi_signature_sites():
    """Two programs of one site sharing fit_chunk spans (full +
    remainder chunk) cannot be apportioned — neither row may claim
    the joined throughput (code-review fix)."""
    records = [
        _cost_rec(site="srm.em_chunk", flops=10e6,
                  span="fit_chunk", estimator="SRM.fit"),
        _cost_rec(site="srm.em_chunk", flops=5e6,
                  span="fit_chunk", estimator="SRM.fit"),
        _span_rec("fit/fit_chunk", 1.0, estimator="SRM.fit"),
        _span_rec("fit/fit_chunk", 1.0, estimator="SRM.fit"),
        _span_rec("fit/fit_chunk", 0.5, estimator="SRM.fit"),
    ]
    rows = report.aggregate(records)["cost"]
    assert len(rows) == 2
    assert all("achieved_flops_per_s" not in r for r in rows)
