"""Cost profiling: schema-v2 ``cost`` records for the jitted sites.

Acceptance (ISSUE 4): cost records are captured for at least 3
distinct jitted sites — FCMA gram, ISC slab, and a funcalign fit
program — under the in-memory sink, with FLOPs/bytes populated when
the backend provides ``cost_analysis()`` and a graceful
``unavailable`` marker when it does not.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from brainiak_tpu import obs
from brainiak_tpu.obs import profile as obs_profile


@pytest.fixture
def mem():
    sink = obs.add_sink(obs.MemorySink())
    yield sink
    obs.remove_sink(sink)


def _costs(mem, site=None):
    return [r for r in mem.records if r["kind"] == "cost"
            and (site is None or r["site"] == site)]


def test_profile_off_by_default(mem):
    prog = obs_profile.profile_program(
        jax.jit(lambda x: x * 2), "t.prog")
    prog(jnp.ones(4))
    assert _costs(mem) == []


def test_lowered_level_captures_cost_fields(mem):
    prog = obs_profile.profile_program(
        jax.jit(lambda a, b: a @ b), "t.matmul")
    with obs_profile.profiling("lowered"):
        prog(jnp.ones((16, 16)), jnp.ones((16, 16)))
    (rec,) = _costs(mem, "t.matmul")
    assert obs.validate_record(rec) == []
    assert rec["v"] == obs.SCHEMA_VERSION
    assert rec["level"] == "lowered"
    # XLA:CPU provides cost_analysis: 2*16^3 FLOPs for the matmul
    assert rec["flops"] == pytest.approx(2 * 16 ** 3, rel=0.5)
    assert rec["bytes_accessed"] > 0
    assert rec["hlo_bytes"] > 0
    assert "compile_s" not in rec  # lowered level never compiles


def test_compiled_level_times_the_compile(mem):
    prog = obs_profile.profile_program(
        jax.jit(lambda a: jnp.tanh(a).sum()), "t.compiled")
    with obs_profile.profiling("compiled"):
        prog(jnp.ones((8, 8)))
    (rec,) = _costs(mem, "t.compiled")
    assert rec["level"] == "compiled"
    assert rec["compile_s"] > 0
    # memory analysis rides along as attrs
    assert rec["attrs"]["argument_bytes"] > 0


def test_one_record_per_signature(mem):
    prog = obs_profile.profile_program(
        jax.jit(lambda x: x + 1), "t.dedup")
    with obs_profile.profiling("lowered"):
        prog(jnp.ones(4))
        prog(jnp.ones(4))          # same shape: no second record
        prog(jnp.ones(8))          # new shape: second record
    assert len(_costs(mem, "t.dedup")) == 2


def test_tracer_args_bypass(mem):
    inner = obs_profile.profile_program(
        jax.jit(lambda x: x * 3), "t.inner")

    @jax.jit
    def outer(x):
        return inner(x) + 1

    with obs_profile.profiling("lowered"):
        out = outer(jnp.ones(4))
    np.testing.assert_allclose(np.asarray(out), 4.0)
    assert _costs(mem, "t.inner") == []  # never lowered under trace


def test_unavailable_marker_when_backend_lacks_cost_analysis(
        mem, monkeypatch):
    jitted = jax.jit(lambda x: x - 1)
    prog = obs_profile.profile_program(jitted, "t.nocost")

    real_lower = jitted.lower

    class _NoCost:
        def __init__(self, lowered):
            self._lowered = lowered

        def as_text(self):
            return self._lowered.as_text()

        def cost_analysis(self):
            raise NotImplementedError("backend has no cost model")

    monkeypatch.setattr(
        prog, "_fn",
        type("F", (), {
            "lower": staticmethod(
                lambda *a, **k: _NoCost(real_lower(*a, **k))),
            "__call__": staticmethod(jitted),
        })())
    with obs_profile.profiling("lowered"):
        prog(jnp.ones(4))
    (rec,) = _costs(mem, "t.nocost")
    assert rec["unavailable"] == "cost_analysis"
    assert "flops" not in rec
    assert obs.validate_record(rec) == []


def test_not_lowerable_callable_marks_unavailable(mem):
    prog = obs_profile.profile_program(lambda x: x, "t.plain")
    with obs_profile.profiling("lowered"):
        prog(np.ones(4))
    (rec,) = _costs(mem, "t.plain")
    assert rec["unavailable"] == "not-lowerable"


def test_env_var_levels(monkeypatch):
    monkeypatch.delenv(obs_profile.PROFILE_ENV, raising=False)
    assert obs_profile.profile_level() is None
    monkeypatch.setenv(obs_profile.PROFILE_ENV, "1")
    assert obs_profile.profile_level() == "lowered"
    monkeypatch.setenv(obs_profile.PROFILE_ENV, "compiled")
    assert obs_profile.profile_level() == "compiled"
    monkeypatch.setenv(obs_profile.PROFILE_ENV, "0")
    assert obs_profile.profile_level() is None
    with obs_profile.profiling("compiled"):
        assert obs_profile.profile_level() == "compiled"
    with obs_profile.profiling(None):
        monkeypatch.setenv(obs_profile.PROFILE_ENV, "1")
        assert obs_profile.profile_level() is None


# -- the three acceptance sites ---------------------------------------

def test_fcma_gram_site_captured(mem):
    from brainiak_tpu.fcma.voxelselector import VoxelSelector

    rng = np.random.RandomState(0)
    data = [rng.randn(10, 32).astype(np.float32) for _ in range(4)]
    vs = VoxelSelector([0, 1, 0, 1], 2, 2, data, voxel_unit=16,
                       use_pallas=False)
    with obs_profile.profiling("lowered"):
        results = vs.run('svm')
    assert len(results) == 32
    (rec,) = _costs(mem, "fcma.block_gram")
    assert rec["flops"] > 0
    assert rec["span"] == "fcma.block"


def test_isc_slab_site_captured(mem):
    from brainiak_tpu.isc import _slab_program
    from brainiak_tpu.parallel.mesh import DEFAULT_VOXEL_AXIS, \
        make_mesh

    mesh = make_mesh((DEFAULT_VOXEL_AXIS,), (-1,))
    prog = _slab_program(mesh, 4)
    with obs_profile.profiling("lowered"):
        out = prog(jnp.arange(64.0).reshape(8, 8), jnp.asarray(0))
    assert out.shape == (4, 8)
    (rec,) = _costs(mem, "isc.slab")
    assert rec["span"] == "isc.ring_slab"
    assert rec["bytes_accessed"] > 0


def test_funcalign_fit_site_captured(mem):
    from brainiak_tpu.funcalign.srm import SRM

    rng = np.random.RandomState(1)
    X = [rng.randn(30, 20).astype(np.float32) for _ in range(3)]
    with obs_profile.profiling("lowered"):
        SRM(n_iter=2, features=4).fit(X)
    (rec,) = _costs(mem, "srm.fit_prob")
    assert rec["flops"] > 0
    assert rec["backend"] == "cpu"


# -- memory watermarks ------------------------------------------------

def test_memory_watermark_sets_host_gauge(mem):
    snap = obs_profile.memory_watermark()
    assert snap["host_rss"] > 0
    obs_profile.memory_watermark(estimator="T.fit", before=snap)
    gauges = [r for r in mem.records
              if r["kind"] == "metric"
              and r["name"] == "host_peak_rss_bytes"]
    assert gauges and gauges[0]["labels"] == {"estimator": "T.fit"}
    # CPU backend exposes no memory_stats: no HBM gauge, no crash
    assert not any(r["name"] == "hbm_peak_bytes"
                   for r in mem.records if r["kind"] == "metric")


def test_resilient_loop_emits_watermarks(mem):
    from brainiak_tpu.resilience.guards import run_resilient_loop

    def chunk(state, step, n):
        return {"x": state["x"] + n}, False

    run_resilient_loop(chunk, {"x": np.zeros(2)}, 4,
                       checkpoint_every=2, name="WM.fit")
    names = {r["name"] for r in mem.records if r["kind"] == "metric"}
    assert "host_peak_rss_bytes" in names


def test_float_scalar_args_share_one_signature(mem):
    """Dynamic float hyperparameters must not retrigger capture per
    value (jit keys weak scalars by dtype); static-style ints still
    select distinct programs (code-review fix)."""
    prog = obs_profile.profile_program(
        jax.jit(lambda x, g: x * g), "t.scalar")
    with obs_profile.profiling("lowered"):
        prog(jnp.ones(4), 0.5)
        prog(jnp.ones(4), 0.7)   # same signature: floats key by type
    assert len(_costs(mem, "t.scalar")) == 1

    chunk = obs_profile.profile_program(
        jax.jit(lambda x, n: x * n, static_argnames=("n",)),
        "t.static")
    with obs_profile.profiling("lowered"):
        chunk(jnp.ones(4), n=2)
        chunk(jnp.ones(4), n=3)  # different static: new program
    assert len(_costs(mem, "t.static")) == 2


def test_memory_watermark_never_first_device_touch(mem,
                                                   monkeypatch):
    """With jax imported but no backend initialized, the watermark
    must not call local_devices() (the blocking first device touch
    on a wedged tunnel) — code-review fix."""
    import sys as _sys
    monkeypatch.setitem(_sys.modules, "jax._src.xla_bridge",
                        type("B", (), {"_backends": {}})())

    def boom():
        raise AssertionError("local_devices would init the backend")

    monkeypatch.setattr(jax, "local_devices", boom)
    snap = obs_profile.memory_watermark()
    assert snap["hbm_peak"] is None


def test_compiled_fallback_to_lowered_cost_is_marked(mem,
                                                     monkeypatch):
    """A record that says level=compiled must never silently carry
    pre-optimization numbers (code-review fix)."""
    monkeypatch.setattr(
        obs_profile, "_cost_analysis_dict",
        lambda stage: None if hasattr(stage, "__call__")
        else {"flops": 1.0})
    # compiled objects are callable, Lowered is not — the lambda
    # above fails the compiled stage and answers for the lowered one
    prog = obs_profile.profile_program(
        jax.jit(lambda x: x + 2), "t.fallback")
    with obs_profile.profiling("compiled"):
        prog(jnp.ones(4))
    (rec,) = _costs(mem, "t.fallback")
    assert rec["level"] == "compiled"
    assert rec["unavailable"] == "compiled-cost-analysis"
    assert rec["flops"] == 1.0  # the lowered estimate, marked as such


def test_empty_cost_analysis_degrades_to_marker(mem):
    """A program whose cost analysis yields nothing attributable
    (Pallas/Mosaic-lowered programs do this) still emits a cost
    record, marked ``cost-analysis-empty`` (ISSUE 11 satellite)."""

    class EmptyCostLowered:
        def as_text(self):
            return "module {}"

        def cost_analysis(self):
            return {"utilization": 1.0}  # nothing attributable

    class Prog:
        def lower(self, *a, **k):
            return EmptyCostLowered()

        def __call__(self, x):
            return x

    prog = obs_profile.profile_program(Prog(), "t.pallas",
                                       span="t.span")
    with obs_profile.profiling("lowered"):
        prog(jnp.ones(3))
    (rec,) = _costs(mem, "t.pallas")
    assert rec["unavailable"] == "cost-analysis-empty"
    assert "flops" not in rec
    assert rec["span"] == "t.span"


def test_analysis_stage_raise_degrades_not_raises(mem):
    """A lowering stage that raises outside the per-step guards
    degrades to a marked record instead of losing the site."""

    class Prog:
        @property
        def lower(self):
            # raises on ATTRIBUTE ACCESS — outside every per-step
            # guard (getattr's default only swallows AttributeError)
            raise RuntimeError("mosaic said no")

        def __call__(self, x):
            return x

    prog = obs_profile.profile_program(Prog(), "t.explode")
    with obs_profile.profiling("lowered"):
        prog(jnp.ones(3))
    (rec,) = _costs(mem, "t.explode")
    assert rec["unavailable"].startswith("profile-failed:")


def test_report_renders_span_only_timing_for_unavailable_site():
    """obs report's cost-profiles section attaches span-only timing
    to a degraded (unavailable) cost row instead of dropping it."""
    from brainiak_tpu.obs import report

    records = [
        {"v": 1, "kind": "span", "ts": 1.0, "rank": 0,
         "name": "distla.gram", "path": "distla.gram",
         "dur_s": 0.25},
        {"v": 2, "kind": "cost", "ts": 1.1, "rank": 0,
         "name": "distla.summa", "site": "distla.summa",
         "level": "lowered", "span": "distla.gram",
         "unavailable": "cost-analysis-empty"},
    ]
    summary = report.aggregate(records)
    (row,) = summary["cost"]
    assert row["span_total_s"] == 0.25
    assert row["span_count"] == 1
    assert "achieved_flops_per_s" not in row
    text = report.render_text(summary)
    assert "span=0.2500s/1x" in text
    assert "unavailable=cost-analysis-empty" in text


def test_span_timing_not_attached_to_ambiguous_join_groups():
    """Review fix: several cost rows of one site sharing a join
    target (full + remainder chunk programs) stay unannotated —
    for span-only timing exactly as for FLOP/s — because the shared
    span total cannot be apportioned between them."""
    from brainiak_tpu.obs import report

    span = {"v": 1, "kind": "span", "ts": 1.0, "rank": 0,
            "name": "fit_chunk", "path": "fit_chunk", "dur_s": 0.5,
            "attrs": {"estimator": "X.fit"}}
    cost = {"v": 2, "kind": "cost", "ts": 1.1, "rank": 0,
            "name": "x.chunk", "site": "x.chunk",
            "level": "lowered", "span": "fit_chunk",
            "estimator": "X.fit",
            "unavailable": "cost-analysis-empty"}
    summary = report.aggregate([span, cost, dict(cost, ts=1.2)])
    for row in summary["cost"]:
        assert "span_total_s" not in row
        assert "achieved_flops_per_s" not in row
