"""Bench regression detector: tiers, thresholds, history policy.

Acceptance (ISSUE 4): the gate passes on the committed fixture
history and fails (non-zero exit, named metric) when the newest
record is degraded 2x; tier separation is proven by a test where a
``cpu_fallback`` record is NOT flagged against an on-chip baseline.
"""

import json
import os
import shutil

import pytest

from brainiak_tpu.obs import regress

FIXTURE_DIR = os.path.join(os.path.dirname(__file__), "..", "..",
                           "tools", "bench_fixture")

ONCHIP = {"metric": "fcma_voxel_selection_voxels_per_sec_chip",
          "unit": "voxels/sec", "vs_baseline": 300.0,
          "tier": "whole_brain"}
LEGACY_CPU = {"metric": "fcma_voxel_selection_voxels_per_sec_chip"
                        "_CPU_FALLBACK_tpu_unresponsive",
              "unit": "voxels/sec", "vs_baseline": 10.0}


def _rec(base, value, order, **extra):
    rec = dict(base, value=value, order=order, source=f"r{order}")
    rec.update(extra)
    return rec


def test_tier_inference():
    assert regress.tier_of({"tier": "whole_brain"}) == "whole_brain"
    assert regress.tier_of(dict(LEGACY_CPU)) == "cpu_fallback"
    assert regress.tier_of({"metric": "x"}) == "unknown"


def test_fixture_history_passes_and_gates():
    records, skipped = regress.load_bench_records([FIXTURE_DIR])
    # the real r01-r05 fcma trajectory + the serve_r01-r03 tier
    # (PR 5) + the distla_r01-r03 tier (ISSUE 6) + the
    # encoding_r01-r03 tier (ISSUE 7) + the service_r01-r03 tier
    # (ISSUE 9, refreshed by ISSUE 12: 3 rounds x 4 metrics —
    # requests/s, p99, padding, obs overhead) + the kernels_r01-r03
    # tier (ISSUE 11: 3 rounds x 2 metrics — fused forward-backward
    # TRs/s, fused ring GB/s) + the streaming_r01-r03 tier
    # (ISSUE 13: 3 rounds x 2 metrics — streamed subjects/s,
    # prefetch stall ratio) + the federation_r01-r03 tier
    # (ISSUE 14: 3 rounds x 3 metrics — routed requests/s, overload
    # p99, shed ratio) + the realtime_r01-r03 tier (ISSUE 15:
    # 3 rounds x 2 metrics — per-TR p99 latency, deadline-miss
    # ratio, both lower-is-better) + the elastic_r01-r03 tier
    # (ISSUE 16: 3 rounds x 3 metrics — chaos-soak requests/s,
    # post-failure p99, lost-ticket count held at zero) + the
    # stats_r01-r03 tier (ISSUE 18: 3 rounds x 1 metric — engine
    # surrogates/s vs a host loop) + the jobs_r01-r03 tier
    # (ISSUE 20: 3 rounds x 3 metrics — scheduled jobs/s,
    # co-scheduled serving p99, jobs lost held at zero), all
    # measured host-side -> *_cpu_fallback: twelve tiers gating
    # independently from one directory
    assert len(records) == 74
    assert skipped == []
    # legacy rounds (no tier field) were normalized, not dropped
    tiers = {regress.tier_of(r) for r in records}
    assert tiers == {"cpu_fallback", "serve_cpu_fallback",
                     "service_cpu_fallback",
                     "distla_cpu_fallback",
                     "encoding_cpu_fallback",
                     "kernels_cpu_fallback",
                     "streaming_cpu_fallback",
                     "federation_cpu_fallback",
                     "realtime_cpu_fallback",
                     "elastic_cpu_fallback",
                     "stats_cpu_fallback",
                     "jobs_cpu_fallback"}
    result = regress.evaluate(records)
    assert result["verdict"] == "pass"
    multi = ("service_cpu_fallback", "kernels_cpu_fallback",
             "streaming_cpu_fallback", "federation_cpu_fallback",
             "realtime_cpu_fallback", "elastic_cpu_fallback",
             "jobs_cpu_fallback")
    by_tier = {c["tier"]: c for c in result["checks"]
               if c["tier"] not in multi}
    by_metric = {c["metric"]: c for c in result["checks"]
                 if c["tier"] in multi}
    assert set(by_tier) == {"cpu_fallback", "serve_cpu_fallback",
                            "distla_cpu_fallback",
                            "encoding_cpu_fallback",
                            "stats_cpu_fallback"}
    # the service tier gates four metrics (three flipped, incl. the
    # ISSUE 12 telemetry-overhead ratio) and the kernels tier gates
    # two fused sites
    assert set(by_metric) == {"service_mixed_requests_per_sec",
                              "service_p99_latency_seconds",
                              "service_padding_waste_ratio",
                              "service_obs_overhead_ratio",
                              "kernels_eventseg_fb_trs_per_sec",
                              "kernels_summa_ring_gb_per_sec",
                              "streaming_srm_subjects_per_sec",
                              "streaming_prefetch_stall_ratio",
                              "federation_routed_requests_per_sec",
                              "federation_overload_p99_seconds",
                              "federation_shed_ratio",
                              "realtime_tr_p99_latency_seconds",
                              "realtime_deadline_miss_ratio",
                              "elastic_soak_requests_per_sec",
                              "elastic_post_failure_p99_seconds",
                              "elastic_lost_tickets",
                              "jobs_scheduled_jobs_per_sec",
                              "jobs_coserve_p99_latency_seconds",
                              "jobs_lost"}
    assert by_metric["service_obs_overhead_ratio"][
        "direction"] == "lower_is_better"
    # the ISSUE 13 streaming tier gates overlap the right way round
    assert by_metric["streaming_prefetch_stall_ratio"][
        "direction"] == "lower_is_better"
    assert by_metric["service_p99_latency_seconds"][
        "direction"] == "lower_is_better"
    # the ISSUE 14 federation tier gates overload behavior mirrored
    assert by_metric["federation_overload_p99_seconds"][
        "direction"] == "lower_is_better"
    # the ISSUE 15 realtime tier gates the latency SLO, not a rate
    assert by_metric["realtime_tr_p99_latency_seconds"][
        "direction"] == "lower_is_better"
    assert by_metric["realtime_deadline_miss_ratio"][
        "direction"] == "lower_is_better"
    assert by_metric["federation_shed_ratio"][
        "direction"] == "lower_is_better"
    # the ISSUE 16 elastic tier holds the lost-ticket count at
    # ZERO: any growth is an infinite-ratio regression
    assert by_metric["elastic_lost_tickets"][
        "direction"] == "lower_is_better"
    assert by_metric["elastic_lost_tickets"]["value"] == 0.0
    assert by_metric["elastic_post_failure_p99_seconds"][
        "direction"] == "lower_is_better"
    # the ISSUE 20 jobs tier gates co-scheduled serving latency and
    # holds the lost-job count at ZERO
    assert by_metric["jobs_coserve_p99_latency_seconds"][
        "direction"] == "lower_is_better"
    assert by_metric["jobs_lost"]["direction"] == "lower_is_better"
    assert by_metric["jobs_lost"]["value"] == 0.0
    assert all(c["status"] == "ok" for c in by_metric.values())
    assert by_tier["cpu_fallback"]["status"] == "ok"
    assert by_tier["cpu_fallback"]["n_history"] == 4
    assert by_tier["serve_cpu_fallback"]["status"] == "ok"
    assert by_tier["serve_cpu_fallback"]["n_history"] == 2
    assert by_tier["serve_cpu_fallback"]["metric"] == \
        "serve_srm_transform_requests_per_sec"
    assert by_tier["distla_cpu_fallback"]["status"] == "ok"
    assert by_tier["distla_cpu_fallback"]["n_history"] == 2
    assert by_tier["distla_cpu_fallback"]["metric"] == \
        "distla_summa_gram_voxels_per_sec"
    assert by_tier["encoding_cpu_fallback"]["status"] == "ok"
    assert by_tier["encoding_cpu_fallback"]["n_history"] == 2
    assert by_tier["encoding_cpu_fallback"]["metric"] == \
        "encoding_ridge_cv_voxels_lambdas_per_sec"
    # the ISSUE 18 stats tier gates the null-engine surrogate rate
    assert by_tier["stats_cpu_fallback"]["status"] == "ok"
    assert by_tier["stats_cpu_fallback"]["n_history"] == 2
    assert by_tier["stats_cpu_fallback"]["metric"] == \
        "stats_surrogates_per_sec"


def test_only_selects_tier_family():
    """--only gates just the named tier family — exact tier or its
    ``_``-separated backend variants, never an unrelated tier that
    happens to share a prefix string."""
    assert regress.tier_selected("distla", ["distla"])
    assert regress.tier_selected("distla_cpu_fallback", ["distla"])
    assert not regress.tier_selected("distlaish", ["distla"])
    assert not regress.tier_selected("serve_cpu_fallback", ["distla"])
    assert regress.tier_selected("anything", None)

    records, _ = regress.load_bench_records([FIXTURE_DIR])
    result = regress.evaluate(records, only=["distla"])
    assert result["verdict"] == "pass"
    assert [c["tier"] for c in result["checks"]] == \
        ["distla_cpu_fallback"]


def test_cli_only_flag(capsys):
    """``obs regress --only distla`` gates the distla family alone
    (ISSUE 6 acceptance) and an empty selection exits 2, not a
    silent pass."""
    assert regress.main(["--history", FIXTURE_DIR,
                         "--only", "distla",
                         "--format=json"]) == 0
    verdict = json.loads(capsys.readouterr().out)
    assert verdict["verdict"] == "pass"
    assert [c["tier"] for c in verdict["checks"]] == \
        ["distla_cpu_fallback"]
    assert regress.main(["--history", FIXTURE_DIR,
                         "--only", "nope"]) == 2


def test_two_x_degradation_fails_with_named_metric(tmp_path,
                                                   capsys):
    for name in os.listdir(FIXTURE_DIR):
        shutil.copy(os.path.join(FIXTURE_DIR, name), str(tmp_path))
    with open(os.path.join(FIXTURE_DIR, "r05.json")) as fh:
        degraded = json.load(fh)
    degraded["value"] = degraded["value"] / 2.0
    (tmp_path / "r06.json").write_text(json.dumps(degraded))
    rc = regress.main(["--history", str(tmp_path)])
    captured = capsys.readouterr()
    assert rc == 1
    assert "regression" in captured.err
    assert "fcma_voxel_selection_voxels_per_sec_chip" in captured.err


def test_cpu_fallback_never_compared_to_onchip_baseline():
    # an on-chip history an order of magnitude above the fresh
    # cpu_fallback number: tier separation must keep them apart
    history = [_rec(ONCHIP, 10000.0 + i, i) for i in range(4)]
    fresh = [_rec(LEGACY_CPU, 1000.0, 99, tier="cpu_fallback")]
    result = regress.evaluate(history, fresh)
    # the cpu_fallback sample has NO cpu history: insufficient, not
    # a regression — and the on-chip tier is not re-gated at all
    (check,) = result["checks"]
    assert check["tier"] == "cpu_fallback"
    assert check["status"] == "insufficient_history"
    assert result["verdict"] == "pass"


def test_median_baseline_resists_outlier_round():
    values = [1000.0, 1010.0, 990.0, 5000.0]  # one outlier round
    history = [_rec(ONCHIP, v, i) for i, v in enumerate(values)]
    fresh = [_rec(ONCHIP, 950.0, 99)]
    result = regress.evaluate(history, fresh)
    (check,) = result["checks"]
    assert check["status"] == "ok"
    assert check["baseline_median"] == 1005.0


def test_threshold_is_configurable():
    history = [_rec(ONCHIP, 1000.0, i) for i in range(3)]
    fresh = [_rec(ONCHIP, 800.0, 99)]
    assert regress.evaluate(history, fresh)["verdict"] == "pass"
    strict = regress.evaluate(history, fresh, threshold=0.9)
    assert strict["verdict"] == "fail"
    (check,) = strict["checks"]
    assert check["ratio"] == pytest.approx(0.8)


def test_min_history_gate():
    history = [_rec(ONCHIP, 1000.0, 0)]
    fresh = [_rec(ONCHIP, 100.0, 99)]
    result = regress.evaluate(history, fresh)
    assert result["checks"][0]["status"] == "insufficient_history"
    assert result["verdict"] == "pass"
    gated = regress.evaluate(history, fresh, min_history=1)
    assert gated["verdict"] == "fail"


def test_loader_understands_wrappers_and_jsonl(tmp_path):
    # a round wrapper (the BENCH_r* shape), a bare record, and JSONL
    (tmp_path / "a.json").write_text(json.dumps(
        {"n": 1, "cmd": "python bench.py", "rc": 0,
         "parsed": dict(ONCHIP, value=1.0)}))
    (tmp_path / "b.json").write_text(json.dumps(
        dict(ONCHIP, value=2.0)))
    (tmp_path / "c.jsonl").write_text(
        json.dumps(dict(ONCHIP, value=3.0)) + "\n"
        + json.dumps({"not": "a bench record"}) + "\n")
    records, skipped = regress.load_bench_records([str(tmp_path)])
    assert [r["value"] for r in records] == [1.0, 2.0, 3.0]
    assert len(skipped) == 1 and "c.jsonl" in skipped[0]


def test_schema_version_trust(tmp_path):
    futuristic = dict(ONCHIP, value=1.0, schema_version=99)
    (tmp_path / "f.json").write_text(json.dumps(futuristic))
    records, skipped = regress.load_bench_records([str(tmp_path)])
    assert records == []
    assert "schema_version=99" in skipped[0]


def test_cli_fresh_mode_and_exit_codes(tmp_path, capsys):
    hist = tmp_path / "hist"
    hist.mkdir()
    for i in range(3):
        (hist / f"r{i}.json").write_text(
            json.dumps(dict(ONCHIP, value=1000.0 + i)))
    good = tmp_path / "fresh.json"
    good.write_text(json.dumps(dict(ONCHIP, value=980.0)))
    assert regress.main(["--history", str(hist),
                         "--fresh", str(good)]) == 0
    capsys.readouterr()
    bad = tmp_path / "bad.json"
    bad.write_text(json.dumps(dict(ONCHIP, value=400.0)))
    assert regress.main(["--history", str(hist), "--fresh",
                         str(bad), "--format=json"]) == 1
    verdict = json.loads(capsys.readouterr().out)
    assert verdict["verdict"] == "fail"
    (check,) = verdict["checks"]
    assert check["status"] == "regression"
    assert check["metric"] == ONCHIP["metric"]
    # no usable records at all
    assert regress.main(["--history", str(tmp_path / "none")]) == 2


def test_stdin_fresh_normalizes_legacy_records(tmp_path,
                                               monkeypatch, capsys):
    """A pre-tier bench line piped via --fresh - must get the same
    legacy tier backfill the file path applies (code-review fix)."""
    import io
    hist = tmp_path / "hist"
    hist.mkdir()
    for i in range(3):
        (hist / f"r{i}.json").write_text(json.dumps(
            _rec(LEGACY_CPU, 1000.0 + i, i, tier="cpu_fallback")))
    legacy_line = json.dumps(dict(LEGACY_CPU, value=990.0))
    monkeypatch.setattr("sys.stdin", io.StringIO(legacy_line))
    assert regress.main(["--history", str(hist), "--fresh", "-",
                         "--format=json"]) == 0
    verdict = json.loads(capsys.readouterr().out)
    (check,) = verdict["checks"]
    assert check["tier"] == "cpu_fallback"
    assert check["status"] == "ok"


# -- ISSUE 9: per-metric direction (lower_is_better) ------------------

P99 = {"metric": "service_p99_latency_seconds", "unit": "s",
       "vs_baseline": 0.0, "tier": "service_cpu_fallback",
       "direction": "lower_is_better"}


def test_lower_is_better_flips_the_bar():
    """A latency metric gates mirrored: growth past baseline /
    threshold is the regression, shrinkage never is."""
    history = [_rec(P99, 0.050 + 0.001 * i, i) for i in range(3)]
    # halved latency: a big IMPROVEMENT, must pass
    good = [_rec(P99, 0.025, 99)]
    assert regress.evaluate(history, good)["verdict"] == "pass"
    # doubled latency: ratio 2.0 > 1/0.7 -> regression
    bad = [_rec(P99, 0.102, 99)]
    result = regress.evaluate(history, bad)
    assert result["verdict"] == "fail"
    (check,) = result["checks"]
    assert check["status"] == "regression"
    assert check["direction"] == "lower_is_better"
    # the same doubled value on a higher-is-better metric passes
    up = dict(P99)
    del up["direction"]
    history_up = [_rec(up, 0.050 + 0.001 * i, i) for i in range(3)]
    assert regress.evaluate(
        history_up, [_rec(up, 0.102, 99)])["verdict"] == "pass"


def test_acceptance_doubled_fixture_p99_exits_1(tmp_path, capsys):
    """ISSUE 9 acceptance: `obs regress --only service` passes on
    the committed fixture rounds and demonstrably fails (exit 1)
    when a fixture p99 is doubled."""
    assert regress.main(["--history", FIXTURE_DIR,
                         "--only", "service"]) == 0
    capsys.readouterr()
    hist = tmp_path / "hist"
    hist.mkdir()
    for name in os.listdir(FIXTURE_DIR):
        if name.startswith("service_"):
            shutil.copy(os.path.join(FIXTURE_DIR, name),
                        str(hist))
    # double the newest round's p99 line in place
    newest = hist / "service_r03.json"
    lines = []
    for line in newest.read_text().splitlines():
        rec = json.loads(line)
        if rec["metric"] == "service_p99_latency_seconds":
            rec["value"] *= 2.0
        lines.append(json.dumps(rec))
    newest.write_text("\n".join(lines) + "\n")
    rc = regress.main(["--history", str(hist),
                       "--only", "service"])
    captured = capsys.readouterr()
    assert rc == 1
    assert "service_p99_latency_seconds" in captured.err
    assert "lower is better" in captured.out


def test_zero_baseline_gates_by_direction():
    """A tier whose history is legitimately 0.0 (e.g. padding waste
    on a uniform workload) must not fail forever: staying at 0.0
    passes either direction; growing off 0.0 regresses only
    lower-is-better."""
    history = [_rec(P99, 0.0, i) for i in range(3)]
    flat = regress.evaluate(history, [_rec(P99, 0.0, 99)])
    assert flat["verdict"] == "pass"
    assert flat["checks"][0]["ratio"] == 1.0
    grown = regress.evaluate(history, [_rec(P99, 0.05, 99)])
    assert grown["verdict"] == "fail"
    up = {k: v for k, v in P99.items() if k != "direction"}
    history_up = [_rec(up, 0.0, i) for i in range(3)]
    assert regress.evaluate(
        history_up, [_rec(up, 0.05, 99)])["verdict"] == "pass"


def test_validator_rejects_unknown_direction():
    from brainiak_tpu.obs.report import validate_bench_record
    rec = dict(P99, value=0.05)
    assert validate_bench_record(rec) == []
    assert any("direction" in e for e in validate_bench_record(
        dict(rec, direction="sideways")))


def test_only_kernels_gates_committed_fixture():
    """ISSUE 11 acceptance: `obs regress --only kernels` passes on
    the committed kernels fixture rounds (both fused-site metrics
    gated, cpu_fallback tier)."""
    records, _ = regress.load_bench_records([FIXTURE_DIR])
    result = regress.evaluate(records, only=["kernels"])
    assert result["verdict"] == "pass"
    assert sorted(c["metric"] for c in result["checks"]) == [
        "kernels_eventseg_fb_trs_per_sec",
        "kernels_summa_ring_gb_per_sec"]
    assert all(c["status"] == "ok" for c in result["checks"])


def test_kernels_two_x_degradation_exits_one(tmp_path, capsys):
    """ISSUE 11 acceptance: a synthetic 2x degradation of the
    newest kernels round exits 1 with the metric named."""
    for name in os.listdir(FIXTURE_DIR):
        if name.startswith("kernels_"):
            shutil.copy(os.path.join(FIXTURE_DIR, name),
                        str(tmp_path))
    lines = []
    with open(os.path.join(FIXTURE_DIR, "kernels_r03.json")) as fh:
        for line in fh:
            rec = json.loads(line)
            rec["value"] = rec["value"] / 2.0
            lines.append(json.dumps(rec))
    (tmp_path / "kernels_r04.json").write_text("\n".join(lines))
    rc = regress.main(["--history", str(tmp_path),
                       "--only", "kernels"])
    captured = capsys.readouterr()
    assert rc == 1
    assert "regression" in captured.err
    assert "kernels_" in captured.err
