"""Acceptance: a resilient SRM fit under injected faults produces a
full JSONL trace (fit-step spans + checkpoint/rollback/resume/fault
events) that the report CLI renders; disabled, the instrumentation is
inert (zero records, zero telemetry-added host syncs)."""

import json
import os

import numpy as np
import pytest

from brainiak_tpu import obs
from brainiak_tpu.obs import report, sink as obs_sink, spans
from brainiak_tpu.resilience.faults import PreemptionError, inject


def _srm_data(n_subjects=3, voxels=14, samples=20, features=3):
    rng = np.random.RandomState(0)
    shared = rng.randn(features, samples)
    X = []
    for _ in range(n_subjects):
        q, _ = np.linalg.qr(rng.randn(voxels, features))
        X.append(q @ shared + 0.1 * rng.randn(voxels, samples))
    return X


def _load_trace(trace_dir):
    recs = []
    for name in sorted(os.listdir(trace_dir)):
        if not name.endswith(".jsonl"):
            continue  # e.g. the incidents/ snapshot directory
        with open(os.path.join(trace_dir, name)) as fh:
            recs.extend(json.loads(line) for line in fh)
    return recs


def test_faulted_srm_fit_produces_renderable_trace(
        tmp_path, monkeypatch):
    from brainiak_tpu.funcalign.srm import SRM

    trace_dir = str(tmp_path / "trace")
    ckpt = str(tmp_path / "ckpt")
    monkeypatch.setenv(obs.OBS_DIR_ENV, trace_dir)
    X = _srm_data()

    # preempt at step 4: the fit dies after checkpointing, then a
    # second call resumes from the checkpoint
    with inject("preempt", at_step=4) as fault:
        with pytest.raises(PreemptionError):
            SRM(n_iter=8, features=3).fit(
                X, checkpoint_dir=ckpt, checkpoint_every=2)
    assert fault.fired == 1
    # inject a NaN on resume: one rollback + re-run, then completion
    with inject("nan", at_step=6) as fault:
        SRM(n_iter=8, features=3).fit(
            X, checkpoint_dir=ckpt, checkpoint_every=2)
    assert fault.fired == 1

    obs_sink.close_all()
    monkeypatch.delenv(obs.OBS_DIR_ENV)
    records = _load_trace(trace_dir)
    for rec in records:
        assert obs.validate_record(rec) == []
    kinds = {}
    for rec in records:
        kinds.setdefault((rec["kind"], rec["name"]), []).append(rec)

    chunks = kinds[("span", "fit_chunk")]
    assert len(chunks) >= 4  # fit-step spans from both fits
    assert all(c["attrs"]["estimator"] == "SRM.fit" for c in chunks)
    assert ("event", "checkpoint") in kinds
    assert ("event", "resume") in kinds
    assert ("event", "rollback") in kinds
    fault_events = kinds[("event", "fault")]
    assert {e["attrs"]["kind"] for e in fault_events} == \
        {"preempt", "nan"}
    mets = {rec["name"] for rec in records
            if rec["kind"] == "metric"}
    assert {"fit_steps_total", "rollback_total", "resume_total",
            "checkpoint_seconds"} <= mets

    # the report CLI renders it
    summary = report.aggregate(records)
    text = report.render_text(summary)
    assert "fit_chunk" in text
    assert "rollback" in text


def test_disabled_fit_emits_nothing_and_never_syncs(
        tmp_path, monkeypatch):
    from brainiak_tpu.funcalign.srm import SRM

    calls = []
    real = spans._block_until_ready
    monkeypatch.setattr(spans, "_block_until_ready",
                        lambda target: calls.append(target))
    assert not obs.enabled()
    SRM(n_iter=4, features=3).fit(_srm_data())
    # no obs dir, no sink: the spans in run_resilient_loop (and any
    # other instrumented loop) must not have synced or recorded
    assert calls == []
    assert obs_sink.all_sinks() == []
    assert not os.listdir(str(tmp_path))

    # sanity check the seam: an enabled span WITH a sync target does
    # route through _block_until_ready
    monkeypatch.setattr(spans, "_block_until_ready", real)
    mem = obs_sink.add_sink(obs.MemorySink())
    import jax.numpy as jnp
    with obs.span("synced", sync=jnp.ones(3) * 2):
        pass
    # filter to spans: the best-effort jax.monitoring compile
    # listener (installed once per process by other obs tests/bench)
    # may interleave jax_compile_seconds metric records here
    assert [r["name"] for r in mem.records
            if r["kind"] == "span"] == ["synced"]


def test_streamed_srm_incident_telemetry_end_to_end(
        tmp_path, monkeypatch, capsys):
    """PR 19 acceptance: a streamed SRM fit preempted and resumed
    reports one fit_id with monotone chunk indices spanning the
    resume; a second fit driven into NaN divergence fires the
    precursor before the guard's rollback and auto-dumps a
    flight-recorder snapshot whose postmortem names the estimator,
    failing chunk, and objective tail."""
    from brainiak_tpu.data import write_store
    from brainiak_tpu.funcalign.srm import SRM
    from brainiak_tpu.obs import flight, postmortem
    from brainiak_tpu.resilience.guards import DivergenceError

    trace_dir = str(tmp_path / "trace")
    ckpt = str(tmp_path / "ckpt")
    monkeypatch.setenv(obs.OBS_DIR_ENV, trace_dir)
    store = write_store(str(tmp_path / "store"), _srm_data())
    flight.clear()

    # -- phase 1: preempt mid-fit, resume, finish -----------------
    with inject("preempt", at_step=4) as fault:
        with pytest.raises(PreemptionError):
            SRM(n_iter=8, features=3, shard_subjects=2).fit(
                store, checkpoint_dir=ckpt, checkpoint_every=2)
    assert fault.fired == 1
    SRM(n_iter=8, features=3, shard_subjects=2).fit(
        store, checkpoint_dir=ckpt, checkpoint_every=2)

    # -- phase 2: persistent NaN in the objective leaf -> abort ---
    with inject("nan", at_step=4, times=10, leaf="rho2"):
        with pytest.raises(DivergenceError):
            SRM(n_iter=8, features=3, shard_subjects=2).fit(store)

    obs_sink.close_all()
    monkeypatch.delenv(obs.OBS_DIR_ENV)
    records = _load_trace(os.path.join(trace_dir))
    for rec in records:
        assert obs.validate_record(rec) == []

    progress = [r for r in records if r["kind"] == "progress"]
    assert all(r["estimator"] == "SRM.fit_stream"
               for r in progress)
    by_fit = {}
    for rec in progress:
        by_fit.setdefault(rec["fit_id"], []).append(rec)
    resumed_id = next(
        fid for fid, recs in by_fit.items()
        if recs[-1]["step"] == 8 and recs[-1]["ratio"] == 1.0)
    chunks = [r["chunk"] for r in by_fit[resumed_id]]
    # ONE fit_id spans pre- and post-resume: all 4 planned chunks
    # observed, strictly monotone, despite two processes' worth of
    # records (the preempted run contributed chunks 1-2)
    assert chunks == [1, 2, 3, 4]
    walls = [r["fit_wall_s"] for r in by_fit[resumed_id]]
    assert all(b > a for a, b in zip(walls, walls[1:]))
    resume_events = [r for r in records if r["kind"] == "event"
                     and r["name"] == "resume"]
    assert any(e["attrs"].get("step") == 4 for e in resume_events)
    assert any(e.get("fit_id") == resumed_id
               for e in resume_events)

    # precursor strictly before the guard's rollback
    precursor = [r for r in records if r["kind"] == "event"
                 and r["name"] == "divergence_precursor"]
    rollbacks = [r for r in records if r["kind"] == "event"
                 and r["name"] == "rollback"]
    aborts = [r for r in records if r["kind"] == "event"
              and r["name"] == "divergence_abort"]
    assert precursor and rollbacks and aborts
    assert precursor[0]["attrs"]["reason"] == \
        "non_finite_objective"
    assert precursor[0]["ts"] <= rollbacks[0]["ts"]
    diverged_id = aborts[0]["fit_id"]
    assert diverged_id and diverged_id != resumed_id

    # the abort auto-dumped one snapshot naming the diverged fit
    incidents = os.path.join(trace_dir, "incidents")
    (snap,) = sorted(os.listdir(incidents))
    snap = os.path.join(incidents, snap)
    with open(os.path.join(snap, "manifest.json")) as fh:
        manifest = json.load(fh)
    assert manifest["trigger"] == "divergence_abort"
    assert manifest["fit_id"] == diverged_id
    assert manifest["state"]["estimator"] == "SRM.fit_stream"
    assert "rho2" in manifest["state"]["leaves"]

    # ... and the postmortem CLI renders it: estimator, failing
    # chunk, objective tail
    assert postmortem.main([snap]) == 0
    out = capsys.readouterr().out
    assert "trigger: divergence_abort" in out
    assert "SRM.fit_stream" in out
    assert "<-- implicated" in out
    assert "last chunk:" in out
    assert "objective tail:" in out


def test_fcma_selection_trace(monkeypatch):
    """Per-chunk FCMA spans land in the trace with the block loop
    still emitting one span per voxel block."""
    from brainiak_tpu.fcma.voxelselector import VoxelSelector

    rng = np.random.RandomState(0)
    n_epochs, n_trs, n_voxels = 8, 12, 32
    data = [rng.randn(n_trs, n_voxels).astype(np.float32)
            for _ in range(n_epochs)]
    labels = [0, 1] * (n_epochs // 2)
    mem = obs_sink.add_sink(obs.MemorySink())
    vs = VoxelSelector(labels, 2, 2, data, voxel_unit=16)
    results = vs.run('svm')
    assert len(results) == n_voxels
    names = [r["name"] for r in mem.records
             if r["kind"] == "span"]
    assert names.count("fcma.block") == 2  # 32 voxels / unit 16
    assert "fcma.svm_cv" in names
    assert "fcma.voxel_selection" in names
    top = [r for r in mem.records
           if r.get("name") == "fcma.voxel_selection"]
    assert top[0]["attrs"] == {"clf": "svm", "n_voxels": n_voxels}
