"""Chrome-trace export: cross-rank merge, schema, CLI.

Acceptance (ISSUE 4): two synthetic rank sinks with skewed clocks
must export to one monotonic timeline, and the exported chrome-trace
JSON must validate (required ``ph``/``ts``/``pid`` keys).
"""

import json

import pytest

from brainiak_tpu.obs import export, sink as obs_sink
from brainiak_tpu.obs.report import load_records

#: rank 1's wall clock runs 100 s ahead of rank 0's; both ranks emit
#: their topology event at the same TRUE instant (the collective
#: make_mesh), which is the merge anchor.
SKEW = 100.0
BASE = 1753900000.0


def _rec(kind, name, ts, rank, **fields):
    rec = {"v": obs_sink.SCHEMA_VERSION, "kind": kind, "ts": ts,
           "rank": rank, "name": name}
    rec.update(fields)
    assert obs_sink.validate_record(rec) == []
    return rec


def _two_rank_trace():
    r0 = [
        _rec("event", "topology", BASE + 1.0, 0,
             attrs={"backend": "cpu", "process_count": 2}),
        _rec("span", "fit", BASE + 5.0, 0, path="fit", dur_s=3.5),
        _rec("span", "fit_chunk", BASE + 3.0, 0,
             path="fit/fit_chunk", dur_s=1.0,
             attrs={"estimator": "SRM.fit"}),
        _rec("metric", "fit_steps_total", BASE + 3.1, 0,
             mtype="counter", value=5.0),
        _rec("metric", "fit_steps_total", BASE + 4.1, 0,
             mtype="counter", value=3.0),
    ]
    r1 = [
        _rec("event", "topology", BASE + 1.0 + SKEW, 1,
             attrs={"backend": "cpu", "process_count": 2}),
        _rec("span", "fit", BASE + 5.2 + SKEW, 1, path="fit",
             dur_s=3.6),
        _rec("cost", "isc.slab", BASE + 2.0 + SKEW, 1,
             site="isc.slab", flops=100.0),
    ]
    return r0, r1


def _write_sinks(tmp_path, r0, r1):
    for rank, recs in ((0, r0), (1, r1)):
        path = tmp_path / f"obs-{rank}.jsonl"
        path.write_text(
            "".join(json.dumps(r) + "\n" for r in recs))
    return str(tmp_path)


def test_rank_offsets_anchor_on_topology():
    r0, r1 = _two_rank_trace()
    offsets = export.rank_offsets(r0 + r1)
    assert offsets[0] == 0.0
    assert offsets[1] == pytest.approx(SKEW)


def test_skewed_ranks_merge_to_one_monotonic_timeline(tmp_path):
    r0, r1 = _two_rank_trace()
    records, errors = load_records(
        [_write_sinks(tmp_path, r0, r1)])
    assert errors == []
    doc = export.chrome_trace(records)
    assert export.validate_chrome_trace(doc) == []
    timed = [e for e in doc["traceEvents"] if e["ph"] != "M"]
    # monotonic export order, starting at 0
    ts = [e["ts"] for e in timed]
    assert ts == sorted(ts)
    assert min(ts) == 0.0
    # WITHOUT the merge, rank 1's events sit ~100 s away; with it the
    # two ranks' anchored topology instants coincide and every event
    # lands inside the ~9 s true extent of the trace
    assert max(ts) < 15e6
    # the two "fit" span lanes overlap in merged time (they truly ran
    # concurrently), proving rank 1 was shifted back
    fits = {e["pid"]: e for e in timed
            if e["ph"] == "X" and e["name"] == "fit"}
    s0, e0 = fits[0]["ts"], fits[0]["ts"] + fits[0]["dur"]
    s1, e1 = fits[1]["ts"], fits[1]["ts"] + fits[1]["dur"]
    assert s0 < e1 and s1 < e0


def test_span_nesting_and_counter_running_sum(tmp_path):
    r0, r1 = _two_rank_trace()
    doc = export.chrome_trace(r0 + r1)
    by_name = {}
    for ev in doc["traceEvents"]:
        by_name.setdefault(ev["name"], []).append(ev)
    chunk = by_name["fit_chunk"][0]
    fit = [e for e in by_name["fit"] if e["pid"] == 0][0]
    # the chunk nests inside its parent span on the same lane
    assert fit["ts"] <= chunk["ts"]
    assert chunk["ts"] + chunk["dur"] <= fit["ts"] + fit["dur"]
    assert chunk["args"]["path"] == "fit/fit_chunk"
    # counters plot their running sum (5 then 8), not the increments
    counters = sorted(by_name["fit_steps_total"],
                      key=lambda e: e["ts"])
    assert [c["args"]["value"] for c in counters] == [5.0, 8.0]
    # cost records ride along as instant events with their fields
    (cost,) = by_name["isc.slab"]
    assert cost["ph"] == "i"
    assert cost["args"]["flops"] == 100.0


def test_ranks_without_anchor_pass_through():
    recs = [_rec("span", "s", BASE + 1.0, 0, path="s", dur_s=0.5)]
    assert export.rank_offsets(recs) == {}
    doc = export.chrome_trace(recs)
    assert export.validate_chrome_trace(doc) == []


def test_validate_chrome_trace_catches_violations():
    assert export.validate_chrome_trace([]) \
        == ["document is not an object"]
    assert export.validate_chrome_trace({}) \
        == ["traceEvents missing or not a list"]
    bad = {"traceEvents": [
        {"ph": "Q", "name": "x", "pid": 0, "ts": 1},
        {"ph": "X", "pid": 0, "ts": 1, "dur": 1},
        {"ph": "X", "name": "x", "pid": 0, "ts": -5, "dur": 1},
        {"ph": "X", "name": "x", "pid": 0, "ts": 1},
    ]}
    errors = export.validate_chrome_trace(bad)
    assert len(errors) == 4
    assert any("ph=" in e for e in errors)
    assert any("missing 'name'" in e for e in errors)
    assert any("ts=-5" in e for e in errors)
    assert any("dur=None" in e for e in errors)


def test_cli_writes_loadable_file(tmp_path, capsys):
    r0, r1 = _two_rank_trace()
    trace_subdir = tmp_path / "t"
    trace_subdir.mkdir()
    trace_dir = _write_sinks(trace_subdir, r0, r1)
    out = tmp_path / "trace.json"
    assert export.main([trace_dir, "-o", str(out)]) == 0
    doc = json.loads(out.read_text())
    assert export.validate_chrome_trace(doc) == []
    assert doc["otherData"]["clock_offsets_s"]["1"] \
        == pytest.approx(SKEW)


def test_cli_rejects_empty_and_schema_violations(tmp_path, capsys):
    assert export.main([str(tmp_path)]) == 1
    bad = tmp_path / "obs-0.jsonl"
    bad.write_text('{"v": 99, "kind": "span"}\n')
    assert export.main([str(bad)]) == 1


# -- ISSUE 12: traced-request flow rendering --------------------------

def test_traced_spans_render_flow_events():
    """Schema-v3 traced spans become s/t/f flow events binding the
    request's chain; untraced spans draw no flows; a single-span
    trace draws none (no arrow to draw)."""
    t0 = BASE
    recs = [
        _rec("span", "serve.submit", t0 + 0.1, 0,
             path="serve.submit", dur_s=0.01, trace_id="t" * 16,
             span_id="aaaa0001"),
        _rec("span", "serve.dispatch", t0 + 0.3, 1,
             path="serve.dispatch", dur_s=0.05,
             trace_id="t" * 16, span_id="aaaa0002",
             parent_id="aaaa0001"),
        _rec("span", "serve.request", t0 + 0.4, 1,
             path="serve.request", dur_s=0.3, trace_id="t" * 16,
             span_id="aaaa0003", parent_id="aaaa0002"),
        _rec("span", "lonely", t0 + 0.5, 0, path="lonely",
             dur_s=0.01, trace_id="u" * 16, span_id="bbbb0001"),
        _rec("span", "untraced", t0 + 0.6, 0, path="untraced",
             dur_s=0.01),
    ]
    doc = export.chrome_trace(recs)
    assert export.validate_chrome_trace(doc) == []
    flows = [e for e in doc["traceEvents"]
             if e["ph"] in ("s", "t", "f")]
    assert [e["ph"] for e in flows] == ["s", "t", "f"]
    assert all(e["id"] == "t" * 16 for e in flows)
    assert flows[-1]["bp"] == "e"
    # flow steps name the span they bind to, in causal order, and
    # land in the pid lane of the rank that emitted the span
    assert [e["args"]["step"] for e in flows] == \
        ["serve.submit", "serve.dispatch", "serve.request"]
    assert [e["pid"] for e in flows] == [0, 1, 1]
    # traced X slices carry the ids for the viewer's args pane
    traced = [e for e in doc["traceEvents"]
              if e["ph"] == "X" and "trace_id" in e["args"]]
    assert len(traced) == 4
    dispatch = next(e for e in traced
                    if e["name"] == "serve.dispatch")
    assert dispatch["args"]["parent_id"] == "aaaa0001"


def test_progress_records_render_counter_tracks():
    """PR 19: each fit's progress becomes a ratio counter track (+
    an objective track when reported) in that rank's lane; a
    non-finite objective sample is skipped, not exported."""
    fit = "f" * 16
    recs = [
        _rec("progress", "fit_progress", BASE + 1.0, 0,
             fit_id=fit, estimator="SRM.fit", chunk=1, step=2,
             n_iter=8, ratio=0.25, objective=10.0),
        _rec("progress", "fit_progress", BASE + 2.0, 0,
             fit_id=fit, estimator="SRM.fit", chunk=2, step=4,
             n_iter=8, ratio=0.5, objective=float("nan")),
        _rec("progress", "fit_progress", BASE + 3.0, 0,
             fit_id=fit, estimator="SRM.fit", chunk=3, step=6,
             n_iter=8, ratio=0.75),
    ]
    doc = export.chrome_trace(recs)
    assert export.validate_chrome_trace(doc) == []
    counters = [e for e in doc["traceEvents"] if e["ph"] == "C"]
    track = f"SRM.fit:{fit}"
    ratios = [e for e in counters
              if e["name"] == f"fit_progress {track}"]
    assert [e["args"]["ratio"] for e in ratios] == \
        [0.25, 0.5, 0.75]
    assert all(e["pid"] == 0 for e in ratios)
    objectives = [e for e in counters
                  if e["name"] == f"fit_objective {track}"]
    # the NaN sample is dropped; the finite one survives
    assert [e["args"]["objective"] for e in objectives] == [10.0]
    # round-trips as strict JSON (no NaN tokens)
    json.loads(json.dumps(doc, allow_nan=False))


def test_validator_rejects_flow_event_without_id():
    doc = {"traceEvents": [
        {"ph": "s", "name": "trace", "pid": 0, "ts": 1.0}]}
    errors = export.validate_chrome_trace(doc)
    assert any("flow event" in e and "id" in e for e in errors)
