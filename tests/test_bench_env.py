"""Bench env-override validation (ADVICE round 5: odd BENCH_WB_EPOCHS
must not produce a label/epoch mismatch in VoxelSelector)."""

import bench


def test_even_epochs_env_rounds_up_odd(monkeypatch):
    monkeypatch.setenv("BENCH_WB_EPOCHS", "7")
    assert bench._even_epochs_env("BENCH_WB_EPOCHS", 32) == 8
    monkeypatch.setenv("BENCH_WB_EPOCHS", "8")
    assert bench._even_epochs_env("BENCH_WB_EPOCHS", 32) == 8
    monkeypatch.delenv("BENCH_WB_EPOCHS")
    assert bench._even_epochs_env("BENCH_WB_EPOCHS", 32) == 32


def test_make_data_labels_match_even_epochs():
    data, labels = bench.make_data(n_voxels=4, n_trs=6, n_epochs=8)
    assert len(data) == len(labels) == 8
