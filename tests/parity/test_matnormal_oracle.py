"""Live-oracle parity for matnormal (round-3 verdict item 5).

TensorFlow IS installed in this environment, so the reference
``brainiak.matnormal`` runs live — only its four tensorflow_probability
entry points are shimmed (conftest.py; textbook definitions, the
oracle's likelihoods/solvers/optimizers are its own TF code).

Covariance strategy classes are compared EXACTLY (same explicit
parameters -> same logdet / same solve, float64).  Fitted estimators
(MNRSA, MatnormalRegression) are compared as estimators: same data ->
same recovered structure within tolerance, since the two sides optimize
with different backends (TF scipy-L-BFGS vs jax L-BFGS) from different
nuisance inits.
"""

import numpy as np
import pytest

from brainiak_tpu.matnormal.covs import (CovAR1 as OurCovAR1,
                                         CovDiagonal as OurCovDiagonal,
                                         CovIdentity as OurCovIdentity,
                                         CovUnconstrainedCholesky
                                         as OurCovChol)
from brainiak_tpu.matnormal.mnrsa import MNRSA as OurMNRSA
from brainiak_tpu.matnormal.regression import (MatnormalRegression
                                               as OurRegression)

tf = pytest.importorskip("tensorflow")

# The covariance rows assert float64 bit-parity against the live TF
# reference; the fp32 sweep (BRAINIAK_TPU_TEST_X64=0) changes OUR
# working precision but not TF's, so that contract is f64-only.  The
# estimator rows (MNRSA/regression) compare within tolerance and run
# in both modes.
requires_x64 = pytest.mark.skipif(
    __import__("jax").config.jax_enable_x64 is False,
    reason="bit-parity vs the f64 TF oracle requires x64")


@pytest.fixture(scope="module")
def ref_matnormal(reference):
    import importlib
    ns = {}
    ns["covs"] = importlib.import_module("brainiak.matnormal.covs")
    ns["mnrsa"] = importlib.import_module("brainiak.matnormal.mnrsa")
    ns["regression"] = importlib.import_module(
        "brainiak.matnormal.regression")
    return ns


@requires_x64
def test_cov_ar1_logdet_solve_parity(ref_matnormal):
    """CovAR1 with explicit (rho, sigma) and scan-onset blocks: the
    precision recipe (I - rho D + rho^2 F)/sigma^2 must match the
    reference bit-for-bit at float64 (reference covs.py:127-231)."""
    size, rho, sigma = 24, 0.4, 1.3
    onsets = np.array([0, 10])
    ref = ref_matnormal["covs"].CovAR1(size=size, rho=rho, sigma=sigma,
                                       scan_onsets=onsets)
    ours = OurCovAR1(size=size, rho=rho, sigma=sigma,
                     scan_onsets=onsets)
    params = ours.init_params()

    np.testing.assert_allclose(float(ours.logdet(params)),
                               float(ref.logdet), rtol=1e-10)
    x = np.random.RandomState(0).randn(size, 7)
    ref_solve = ref.solve(tf.constant(x)).numpy()
    our_solve = np.asarray(ours.solve(params, x))
    np.testing.assert_allclose(our_solve, ref_solve,
                               rtol=1e-6, atol=1e-8)


@requires_x64
def test_cov_unconstrained_cholesky_parity(ref_matnormal):
    """CovUnconstrainedCholesky built from the same SPD Sigma
    (reference covs.py:343-404)."""
    rng = np.random.RandomState(1)
    a = rng.randn(6, 6)
    sigma_mat = a @ a.T + 6 * np.eye(6)
    ref = ref_matnormal["covs"].CovUnconstrainedCholesky(Sigma=sigma_mat)
    ours = OurCovChol(Sigma=sigma_mat)
    params = ours.init_params()

    expected_logdet = float(np.linalg.slogdet(sigma_mat)[1])
    assert abs(float(ref.logdet) - expected_logdet) < 1e-8
    assert abs(float(ours.logdet(params)) - expected_logdet) < 1e-8

    x = rng.randn(6, 4)
    ref_solve = ref.solve(tf.constant(x)).numpy()
    our_solve = np.asarray(ours.solve(params, x))
    np.testing.assert_allclose(our_solve, ref_solve,
                               rtol=1e-8, atol=1e-10)


@requires_x64
def test_cov_diagonal_parity(ref_matnormal):
    """CovDiagonal with explicit variances (reference covs.py:279-325)."""
    var = np.array([0.5, 1.0, 2.0, 4.0, 0.25])
    ref = ref_matnormal["covs"].CovDiagonal(size=5, diag_var=var)
    ours = OurCovDiagonal(size=5, diag_var=var)
    params = ours.init_params()

    np.testing.assert_allclose(float(ours.logdet(params)),
                               float(ref.logdet), rtol=1e-12)
    x = np.random.RandomState(2).randn(5, 3)
    np.testing.assert_allclose(np.asarray(ours.solve(params, x)),
                               ref.solve(tf.constant(x)).numpy(),
                               rtol=1e-10)


def _rsa_data(seed=3, n_t=60, n_v=16, n_c=4):
    """Design + data with a known condition covariance U."""
    rng = np.random.RandomState(seed)
    design = rng.randn(n_t, n_c)
    u_true = np.array([[1.0, 0.8, 0.0, 0.0],
                       [0.8, 1.0, 0.0, 0.0],
                       [0.0, 0.0, 1.0, -0.6],
                       [0.0, 0.0, -0.6, 1.0]])
    beta = np.linalg.cholesky(u_true) @ rng.randn(n_c, n_v)
    data = design @ beta + 0.7 * rng.randn(n_t, n_v)
    return design, data, u_true


def test_mnrsa_fit_parity(ref_matnormal):
    """MNRSA (reference mnrsa.py:21-175): both implementations must
    recover the same condition-correlation structure from the same
    data.  Tolerances are estimator-level: the nuisance X_0 starts from
    different random draws on each side."""
    design, data, u_true = _rsa_data()
    n_t, n_v = data.shape

    tf.random.set_seed(0)
    ref = ref_matnormal["mnrsa"].MNRSA(
        time_cov=ref_matnormal["covs"].CovIdentity(size=n_t),
        space_cov=ref_matnormal["covs"].CovIdentity(size=n_v),
        n_nureg=2)
    ref.fit(data, design)

    ours = OurMNRSA(time_cov=OurCovIdentity(size=n_t),
                    space_cov=OurCovIdentity(size=n_v), n_nureg=2)
    ours.fit(data, design)

    ref_c = np.asarray(ref.C_)
    our_c = np.asarray(ours.C_)
    assert ref_c.shape == our_c.shape == (4, 4)
    # both detect the dominant positive coupling
    for c in (ref_c, our_c):
        assert c[0, 1] > 0.4
    # the two implementations land on the SAME optimum here: measured
    # maxdiff 0.002 at this regime (at larger sizes the marginal
    # likelihood is multimodal and the reference itself flips between
    # optima across data draws — mutual agreement, not truth recovery,
    # is the parity contract)
    np.testing.assert_allclose(our_c, ref_c, atol=0.05)
    triu = np.triu_indices(4, k=1)
    corr = np.corrcoef(our_c[triu], ref_c[triu])[0, 1]
    assert corr > 0.98, corr


def test_matnormal_regression_parity(ref_matnormal):
    """MatnormalRegression (reference regression.py:15-120): the
    fitted coefficient maps must agree."""
    rng = np.random.RandomState(5)
    n_t, n_v, n_c = 50, 10, 3
    design = rng.randn(n_t, n_c)
    beta_true = rng.randn(n_c, n_v)
    data = design @ beta_true + 0.5 * rng.randn(n_t, n_v)

    tf.random.set_seed(0)
    ref = ref_matnormal["regression"].MatnormalRegression(
        time_cov=ref_matnormal["covs"].CovAR1(size=n_t),
        space_cov=ref_matnormal["covs"].CovIdentity(size=n_v))
    ref.fit(design, data)

    ours = OurRegression(time_cov=OurCovAR1(size=n_t),
                         space_cov=OurCovIdentity(size=n_v))
    ours.fit(design, data)

    ref_beta = np.asarray(ref.beta_)
    our_beta = np.asarray(ours.beta_)
    np.testing.assert_allclose(our_beta, ref_beta, atol=0.05)
    np.testing.assert_allclose(our_beta, beta_true, atol=0.4)
