"""Live-oracle parity for the funcalign family (round-3 verdict item 4).

The reference ``brainiak.funcalign`` modules run LIVE from
/root/reference/src through the single-rank mpi4py stand-in in
conftest.py (every collective is the identity at size 1, so the
oracle's numerics are exactly its own).  SSSRM is covered separately
in test_sssrm_oracle.py through the pymanopt stand-in (substitute
Riemannian CG — see _pymanopt_shim.py for the caveat).

Both implementations start from different random W inits (the repo
draws via jax PRNG, the reference via numpy RandomState), so tests
compare what the algorithms CONTRACT to produce — recovery of the
generating shared timecourse up to an orthogonal rotation, residual
levels, noise estimates — rather than bitwise iterates, plus exact
array round-trips through each other's .npz files
(reference srm.py:110-142, :451-481).
"""

import numpy as np

from brainiak_tpu.funcalign.fastsrm import FastSRM as OurFastSRM
from brainiak_tpu.funcalign.rsrm import RSRM as OurRSRM
from brainiak_tpu.funcalign.srm import (DetSRM as OurDetSRM, SRM as OurSRM,
                                        load as our_load)


def _spiral(samples, turns=4.0):
    """The reference test-suite's 3-D spiral shared response
    (reference tests/funcalign/test_srm.py:34-41)."""
    theta = np.linspace(-turns * np.pi, turns * np.pi, samples)
    z = np.linspace(-2, 2, samples)
    r = z ** 2 + 1
    return np.vstack((r * np.sin(theta), r * np.cos(theta), z))


def _spiral_data(seed, subjects=4, voxels=60, samples=150, features=3,
                 noise=0.1):
    """Spiral shared response mapped through per-subject orthonormal
    bases plus white noise (reference tests/funcalign/test_srm.py:34-63)."""
    rng = np.random.RandomState(seed)
    shared = _spiral(samples)
    data, bases = [], []
    for _ in range(subjects):
        q, _ = np.linalg.qr(rng.randn(voxels, features))
        bases.append(q)
        data.append(q @ shared + noise * rng.randn(voxels, samples))
    return data, bases, shared


def _aligned_corr(est, truth):
    """Mean per-component |correlation| after the best orthogonal
    (procrustes) alignment of ``est`` onto ``truth`` — SRM identifies
    the shared space only up to rotation."""
    u, _, vt = np.linalg.svd(truth @ est.T)
    rot = u @ vt
    est_a = rot @ est
    return float(np.mean([abs(np.corrcoef(est_a[k], truth[k])[0, 1])
                          for k in range(truth.shape[0])]))


def _recon_err(data, w_list, shared):
    return float(np.mean([np.linalg.norm(x - w @ shared, 'fro')
                          / np.linalg.norm(x, 'fro')
                          for x, w in zip(data, w_list)]))


def test_srm_recovery_parity(reference):
    """Probabilistic SRM: both implementations must recover the
    generating shared response (reference srm.py:483-624) to the same
    quality on identical data."""
    data, _, shared = _spiral_data(0)
    ref = reference.srm.SRM(n_iter=10, features=3, rand_seed=0)
    ref.fit(data)
    ours = OurSRM(n_iter=10, features=3, rand_seed=0)
    ours.fit(data)

    ref_corr = _aligned_corr(np.asarray(ref.s_), shared)
    our_corr = _aligned_corr(np.asarray(ours.s_), shared)
    assert ref_corr > 0.9 and our_corr > 0.9, (ref_corr, our_corr)
    assert abs(ref_corr - our_corr) < 0.05

    ref_err = _recon_err(data, ref.w_, ref.s_)
    our_err = _recon_err(data, ours.w_, ours.s_)
    assert our_err < max(1.1 * ref_err, ref_err + 0.02), (our_err, ref_err)

    # noise level estimates agree to the same order
    ref_rho = np.sort(np.asarray(ref.rho2_))
    our_rho = np.sort(np.asarray(ours.rho2_))
    np.testing.assert_allclose(our_rho, ref_rho, rtol=0.5, atol=1e-3)


def test_detsrm_recovery_parity(reference):
    """Deterministic SRM (reference srm.py:626-918): same contract."""
    data, _, shared = _spiral_data(1)
    ref = reference.srm.DetSRM(n_iter=10, features=3, rand_seed=0)
    ref.fit(data)
    ours = OurDetSRM(n_iter=10, features=3, rand_seed=0)
    ours.fit(data)

    ref_corr = _aligned_corr(np.asarray(ref.s_), shared)
    our_corr = _aligned_corr(np.asarray(ours.s_), shared)
    assert ref_corr > 0.9 and our_corr > 0.9, (ref_corr, our_corr)
    assert abs(ref_corr - our_corr) < 0.05

    ref_err = _recon_err(data, ref.w_, ref.s_)
    our_err = _recon_err(data, ours.w_, ours.s_)
    assert our_err < max(1.1 * ref_err, ref_err + 0.02), (our_err, ref_err)


def test_srm_npz_cross_load(reference, tmp_path):
    """Each implementation's .npz save loads EXACTLY in the other
    (reference srm.py:110-142 reads with pickle disabled, so uniform
    voxel counts must be saved as plain stacked arrays)."""
    data, _, _ = _spiral_data(2, subjects=3, voxels=40, samples=80)

    # reference save -> our load
    ref = reference.srm.SRM(n_iter=5, features=3, rand_seed=0)
    ref.fit(data)
    ref_path = tmp_path / "ref_model.npz"
    ref.save(str(ref_path))
    ours_loaded = our_load(str(ref_path))
    for w_ref, w_load in zip(ref.w_, ours_loaded.w_):
        np.testing.assert_array_equal(np.asarray(w_ref), w_load)
    np.testing.assert_array_equal(np.asarray(ref.s_), ours_loaded.s_)
    np.testing.assert_array_equal(np.asarray(ref.rho2_), ours_loaded.rho2_)
    # the loaded model transforms (reference transform contract)
    projected = ours_loaded.transform(data)
    assert len(projected) == len(data)
    assert projected[0].shape == (3, 80)

    # our save -> reference load
    ours = OurSRM(n_iter=5, features=3, rand_seed=0)
    ours.fit(data)
    our_path = tmp_path / "our_model"
    ours.save(str(our_path))
    ref_loaded = reference.srm.load(str(our_path) + ".npz")
    for w_ours, w_load in zip(ours.w_, ref_loaded.w_):
        np.testing.assert_array_equal(w_ours, np.asarray(w_load))
    np.testing.assert_array_equal(ours.s_, np.asarray(ref_loaded.s_))
    ref_projected = ref_loaded.transform(data)
    assert len(ref_projected) == len(data)
    assert ref_projected[0].shape == (3, 80)


def test_rsrm_agreement(reference):
    """Robust SRM (reference rsrm.py:114-260): on data with sparse
    subject-specific outliers both implementations must recover the
    shared response AND localize the outliers the same way.

    gamma=0.5 keeps the problem in the regime where BCD converges from
    any init; at gamma>=1 the reference's own recovery varies 0.70-0.93
    across its rand_seeds (init-dependent local optima — measured here
    r4), so no cross-implementation comparison is meaningful there."""
    rng = np.random.RandomState(3)
    data, _, shared = _spiral_data(3, subjects=3, voxels=50, samples=100)
    # sparse corruption: a few hot voxels per subject
    supports = []
    for x in data:
        idx = rng.choice(x.shape[0], size=4, replace=False)
        x[idx] += 3.0 * rng.randn(4, x.shape[1])
        supports.append(set(idx.tolist()))

    ref = reference.rsrm.RSRM(n_iter=10, features=3, gamma=0.5,
                              rand_seed=0)
    ref.fit(data)
    ours = OurRSRM(n_iter=10, features=3, gamma=0.5, rand_seed=0)
    ours.fit(data)

    ref_corr = _aligned_corr(np.asarray(ref.r_), shared)
    our_corr = _aligned_corr(np.asarray(ours.r_), shared)
    assert ref_corr > 0.9 and our_corr > 0.9, (ref_corr, our_corr)
    assert abs(ref_corr - our_corr) < 0.05

    # the sparse terms concentrate energy on the corrupted voxels
    for s_ref, s_our, hot in zip(ref.s_, ours.s_, supports):
        for s_term in (np.asarray(s_ref), np.asarray(s_our)):
            energy = (s_term ** 2).sum(axis=1)
            top = set(np.argsort(energy)[-4:].tolist())
            assert len(top & hot) >= 3, (top, hot)


def test_fastsrm_agreement(reference):
    """FastSRM (reference fastsrm.py:1327-1466): deterministic given
    arrays in memory, so the two implementations' shared responses must
    agree up to rotation, and cross-projection must reconstruct."""
    data, _, shared = _spiral_data(4, subjects=3, voxels=48,
                                   samples=90)
    arrays = [x.astype(np.float64) for x in data]

    ref = reference.fastsrm.FastSRM(n_components=3, n_iter=10, seed=0,
                                    aggregate="mean", verbose=False)
    ref_shared = ref.fit_transform(arrays)
    ours = OurFastSRM(n_components=3, n_iter=10, seed=0,
                      aggregate="mean", verbose=False)
    our_shared = ours.fit_transform(arrays)

    ref_corr = _aligned_corr(np.asarray(ref_shared), shared)
    our_corr = _aligned_corr(np.asarray(our_shared), shared)
    assert ref_corr > 0.9 and our_corr > 0.9, (ref_corr, our_corr)
    assert abs(ref_corr - our_corr) < 0.05

    # mutual agreement, not just truth recovery: align ours onto the
    # reference's and require near-identity correspondence
    u, _, vt = np.linalg.svd(np.asarray(ref_shared)
                             @ np.asarray(our_shared).T)
    aligned = (u @ vt) @ np.asarray(our_shared)
    for k in range(3):
        c = np.corrcoef(aligned[k], np.asarray(ref_shared)[k])[0, 1]
        assert c > 0.95, (k, c)


def test_fastsrm_atlas_and_sessions_agreement(reference):
    """FastSRM's deterministic-atlas reduction and multi-session input
    (reference fastsrm.py:678-788, :1383-1466) against the repo's on
    identical data: shared responses agree per session up to rotation."""
    rng = np.random.RandomState(6)
    subjects, voxels, features = 3, 48, 3
    session_lens = (60, 45)
    # one spiral per session, same per-subject bases
    sessions_shared = [_spiral(n_t, turns=3.0)
                       for n_t in session_lens]
    imgs = []
    for _ in range(subjects):
        q, _ = np.linalg.qr(rng.randn(voxels, features))
        imgs.append([q @ s + 0.1 * rng.randn(voxels, s.shape[1])
                     for s in sessions_shared])
    # deterministic atlas: contiguous parcels
    atlas = np.repeat(np.arange(1, 13), voxels // 12)

    ref = reference.fastsrm.FastSRM(atlas=atlas, n_components=3,
                                    n_iter=10, seed=0,
                                    aggregate="mean", verbose=False)
    ref_shared = ref.fit_transform(imgs)
    ours = OurFastSRM(atlas=atlas, n_components=3, n_iter=10, seed=0,
                      aggregate="mean", verbose=False)
    our_shared = ours.fit_transform(imgs)

    assert len(ref_shared) == len(our_shared) == len(session_lens)
    for sess, (r_s, o_s, truth) in enumerate(
            zip(ref_shared, our_shared, sessions_shared)):
        r_s, o_s = np.asarray(r_s), np.asarray(o_s)
        assert r_s.shape == o_s.shape == truth.shape
        assert _aligned_corr(r_s, truth) > 0.9, sess
        assert _aligned_corr(o_s, truth) > 0.9, sess
        u, _, vt = np.linalg.svd(r_s @ o_s.T)
        aligned = (u @ vt) @ o_s
        for k in range(features):
            c = np.corrcoef(aligned[k], r_s[k])[0, 1]
            assert c > 0.95, (sess, k, c)
