"""Live-oracle parity for SSSRM (semi-supervised SRM).

The reference runs through the mini-pymanopt stand-in
(tests/parity/_pymanopt_shim.py — reference objectives and alternating
loop, substitute Riemannian CG) with its TF costs on the installed
TensorFlow.  The repo side replaced TF+pymanopt with a JAX Stiefel CG
(funcalign/sssrm.py), so the comparison is estimator-level: both must
classify held-out labeled data to comparable accuracy and recover the
shared spiral to comparable alignment on identical data.
"""

import numpy as np
import pytest

from brainiak_tpu.funcalign.sssrm import SSSRM as OurSSSRM

pytest.importorskip("tensorflow")


def _semi_supervised_data(seed=0, subjects=3, voxels=30, n_align=60,
                          features=3, n_labeled=30, classes=2,
                          noise=0.1):
    """Spiral shared response for alignment + class-clustered labeled
    samples mapped through the same per-subject orthonormal bases."""
    rng = np.random.RandomState(seed)
    theta = np.linspace(-4 * np.pi, 4 * np.pi, n_align)
    z = np.linspace(-2, 2, n_align)
    r = z ** 2 + 1
    shared = np.vstack((r * np.sin(theta), r * np.cos(theta), z))
    class_means = rng.randn(features, classes) * 3

    x_align, z_sup, labels, bases = [], [], [], []
    for _ in range(subjects):
        q, _ = np.linalg.qr(rng.randn(voxels, features))
        bases.append(q)
        x_align.append(q @ shared + noise * rng.randn(voxels, n_align))
        y = rng.randint(0, classes, n_labeled)
        zs = class_means[:, y] + 0.3 * rng.randn(features, n_labeled)
        z_sup.append(q @ zs + noise * rng.randn(voxels, n_labeled))
        labels.append(y)
    return x_align, z_sup, labels, shared, class_means, bases


def _heldout(rng, bases, class_means, n_test=40, noise=0.1):
    outs, ys = [], []
    for q in bases:
        y = rng.randint(0, class_means.shape[1], n_test)
        zs = class_means[:, y] + 0.3 * rng.randn(class_means.shape[0],
                                                 n_test)
        outs.append(q @ zs + noise * rng.randn(q.shape[0], n_test))
        ys.append(y)
    return outs, ys


def _aligned_corr(est, truth):
    u, _, vt = np.linalg.svd(truth @ est.T)
    est_a = (u @ vt) @ est
    return float(np.mean([abs(np.corrcoef(est_a[k], truth[k])[0, 1])
                          for k in range(truth.shape[0])]))


def test_sssrm_parity(reference):
    """Reference sssrm.py:47-560 vs the JAX reimplementation on
    identical semi-supervised data: held-out classification accuracy
    and shared-response recovery must be comparable."""
    import importlib
    ref_mod = importlib.import_module("brainiak.funcalign.sssrm")

    x_align, z_sup, labels, shared, class_means, bases = \
        _semi_supervised_data()
    test_rng = np.random.RandomState(99)
    z_test, y_test = _heldout(test_rng, bases, class_means)

    ref = ref_mod.SSSRM(n_iter=3, features=3, gamma=1.0, alpha=0.5,
                        rand_seed=0)
    ref.fit(x_align, labels, z_sup)
    ref_pred = ref.predict(z_test)
    ref_acc = float(np.mean([np.mean(p == y)
                             for p, y in zip(ref_pred, y_test)]))
    ref_corr = _aligned_corr(np.asarray(ref.s_), shared)

    ours = OurSSSRM(n_iter=3, features=3, gamma=1.0, alpha=0.5,
                    rand_seed=0)
    ours.fit(x_align, labels, z_sup)
    our_pred = ours.predict(z_test)
    our_acc = float(np.mean([np.mean(p == y)
                             for p, y in zip(our_pred, y_test)]))
    our_corr = _aligned_corr(np.asarray(ours.s_), shared)

    # strong signal: both should classify held-out data well and
    # recover the spiral
    assert ref_acc > 0.85, ref_acc
    assert our_acc > 0.85, our_acc
    assert abs(ref_acc - our_acc) < 0.1, (ref_acc, our_acc)
    assert ref_corr > 0.9, ref_corr
    assert our_corr > 0.9, our_corr
    assert abs(ref_corr - our_corr) < 0.05, (ref_corr, our_corr)

    # the two MLR decision rules agree on most held-out samples
    agree = float(np.mean([np.mean(p == q)
                           for p, q in zip(ref_pred, our_pred)]))
    assert agree > 0.85, agree

    # --- convergence-insensitive check ------------------------------
    # The assertions above could in principle hinge on the stand-in CG
    # reaching the same basin as our optimizer.  This one cannot: the
    # reference's own numpy objective (_objective_function,
    # sssrm.py:585-638) evaluates BOTH implementations' parameters at
    # 1 and 3 alternating iterations on identical data — each must
    # DECREASE its value of the shared objective, whatever path its
    # optimizer took.
    def ref_obj(model):
        return float(ref._objective_function(
            x_align, z_sup, labels,
            [np.asarray(w) for w in model.w_], np.asarray(model.s_),
            np.asarray(model.theta_), np.asarray(model.bias_)))

    ref_short = ref_mod.SSSRM(n_iter=1, features=3, gamma=1.0,
                              alpha=0.5, rand_seed=0)
    ref_short.fit(x_align, labels, z_sup)
    ours_short = OurSSSRM(n_iter=1, features=3, gamma=1.0, alpha=0.5,
                          rand_seed=0)
    ours_short.fit(x_align, labels, z_sup)

    ref_1, ref_3 = ref_obj(ref_short), ref_obj(ref)
    our_1, our_3 = ref_obj(ours_short), ref_obj(ours)
    assert ref_3 <= ref_1 + 1e-9, (ref_1, ref_3)
    assert our_3 <= our_1 + 1e-9, (our_1, our_3)
    # and both optimizers end in the same objective regime
    assert abs(ref_3 - our_3) / max(abs(ref_3), abs(our_3)) < 0.25, \
        (ref_3, our_3)
