"""Live-oracle parity for the data plane (io.py / image.py).

The reference runs through the nibabel stand-in in conftest.py, which
routes file access through this repo's own NIfTI codec — so the codec
is common to both sides here (it is itself pinned against nibabel's
on-disk format by the real ``.nii.gz`` fixtures below, written by FSL
tooling).  What these tests pin is the reference's surrounding logic:
directory iteration order, mask thresholding, masked multi-subject
assembly, and condition-spec parsing, against this repo's
reimplementations, on the reference's own test data
(/root/reference/tests/io/data)."""

import os

import numpy as np
import pytest

import brainiak_tpu.image as our_image
import brainiak_tpu.io as our_io

DATA_DIR = "/root/reference/tests/io/data"


@pytest.fixture(scope="module")
def ref_io(reference):
    import importlib
    ns = {}
    ns["io"] = importlib.import_module("brainiak.io")
    ns["image"] = importlib.import_module("brainiak.image")
    return ns


def test_load_images_parity(ref_io):
    paths = [os.path.join(DATA_DIR, f"subject{i}_bet.nii.gz")
             for i in (1, 2)]
    ref_imgs = list(ref_io["io"].load_images(paths))
    our_imgs = list(our_io.load_images(paths))
    assert len(ref_imgs) == len(our_imgs) == 2
    for r, o in zip(ref_imgs, our_imgs):
        np.testing.assert_array_equal(o.get_fdata(), r.get_fdata())


def test_load_images_from_dir_parity(ref_io):
    ref_imgs = list(ref_io["io"].load_images_from_dir(
        DATA_DIR, suffix="bet.nii.gz"))
    our_imgs = list(our_io.load_images_from_dir(
        DATA_DIR, suffix="bet.nii.gz"))
    assert len(ref_imgs) == len(our_imgs) == 2
    for r, o in zip(ref_imgs, our_imgs):
        np.testing.assert_array_equal(o.get_fdata(), r.get_fdata())


def test_load_boolean_mask_parity(ref_io):
    path = os.path.join(DATA_DIR, "mask.nii.gz")
    ref_mask = ref_io["io"].load_boolean_mask(path)
    our_mask = our_io.load_boolean_mask(path)
    assert ref_mask.dtype == our_mask.dtype == bool
    np.testing.assert_array_equal(our_mask, ref_mask)
    # predicate variant
    ref_m2 = ref_io["io"].load_boolean_mask(path, lambda x: x > 0.5)
    our_m2 = our_io.load_boolean_mask(path, lambda x: x > 0.5)
    np.testing.assert_array_equal(our_m2, ref_m2)


def test_mask_images_and_assembly_parity(ref_io):
    paths = [os.path.join(DATA_DIR, f"subject{i}_bet.nii.gz")
             for i in (1, 2)]
    mask_path = os.path.join(DATA_DIR, "mask.nii.gz")

    ref_mask = ref_io["io"].load_boolean_mask(mask_path)
    ref_masked = list(ref_io["image"].mask_images(
        ref_io["io"].load_images(paths), ref_mask, np.float32))
    our_mask = our_io.load_boolean_mask(mask_path)
    our_masked = list(our_image.mask_images(
        our_io.load_images(paths), our_mask, np.float32))
    for r, o in zip(ref_masked, our_masked):
        np.testing.assert_array_equal(o, r)

    ref_data = ref_io["image"].MaskedMultiSubjectData \
        .from_masked_images(iter(ref_masked), 2)
    our_data = our_image.MaskedMultiSubjectData \
        .from_masked_images(iter(our_masked), 2)
    assert ref_data.shape == our_data.shape
    np.testing.assert_array_equal(np.asarray(our_data),
                                  np.asarray(ref_data))


def test_load_labels_parity(ref_io):
    path = os.path.join(DATA_DIR, "epoch_labels.npy")
    ref_labels = ref_io["io"].load_labels(path)
    our_labels = our_io.load_labels(path)
    assert len(ref_labels) == len(our_labels)
    for r, o in zip(ref_labels, our_labels):
        np.testing.assert_array_equal(np.asarray(o), np.asarray(r))
        ref_ex = r.extract_labels()
        our_ex = o.extract_labels()
        np.testing.assert_array_equal(our_ex, ref_ex)


def test_save_as_nifti_roundtrip_parity(ref_io, tmp_path):
    """Under the nibabel stand-in BOTH sides save through this repo's
    codec, so the ref-vs-ours equality below is vacuous then (it only
    gains teeth when a real nibabel is installed, where it pins our
    writer against nibabel's).  The assertion that carries signal in
    every environment is the final data-fidelity check: the reference
    io path must round-trip values and affine exactly."""
    rng = np.random.RandomState(0)
    data = rng.rand(4, 5, 6).astype(np.float32)
    affine = np.diag([2.0, 2.0, 3.0, 1.0])

    ref_path = str(tmp_path / "ref_out.nii")
    our_path = str(tmp_path / "our_out.nii")
    ref_io["io"].save_as_nifti_file(data, affine, ref_path)
    our_io.save_as_nifti_file(data, affine, our_path)

    from brainiak_tpu import nifti
    ref_back = nifti.load(ref_path)
    our_back = nifti.load(our_path)
    np.testing.assert_array_equal(our_back.get_fdata(),
                                  ref_back.get_fdata())
    np.testing.assert_array_equal(our_back.affine, ref_back.affine)
    np.testing.assert_allclose(ref_back.get_fdata(), data)
