"""Live-oracle parity for FCMA: VoxelSelector stage 1, Classifier,
and MVPAVoxelSelector.

The reference runs live through NumPy stand-ins for its two native
modules (conftest.py): ``cython_blas`` (sgemm/ssyrk wrappers) and
``fcma_extension`` (clamped Fisher-z + within-subject z-scoring).
``VoxelSelector.run`` cannot execute single-process — its MPI loop is
a blocking master/worker protocol (reference voxelselector.py:89-238)
— so stage-1 parity drives its comm-free compute core
``_voxel_scoring`` directly (see test_voxelselector_scoring_parity).
"""

import math

import numpy as np
from numpy.random import RandomState
from scipy.stats.mstats import zscore
from sklearn import svm

from brainiak_tpu.fcma.classifier import Classifier as OurClassifier
from brainiak_tpu.fcma.mvpa_voxelselector import (MVPAVoxelSelector
                                                  as OurMVPA)
from brainiak_tpu.searchlight.searchlight import (Ball as OurBall,
                                                  Searchlight
                                                  as OurSearchlight)


def _make_epochs(num_epochs=20, num_voxels=5, seed=1234567890):
    """The reference test-suite's generating process (reference
    tests/fcma/test_classification.py:31-46): sorted-pattern even
    epochs, z-scored and scaled."""
    prng = RandomState(seed)
    out = []
    for idx in range(num_epochs):
        mat = prng.rand(12, num_voxels).astype(np.float32)
        if idx % 2 == 0:
            mat = np.sort(mat, axis=0)
        mat = np.nan_to_num(zscore(mat, axis=0, ddof=0))
        out.append(mat / math.sqrt(mat.shape[0]))
    return out


def test_classifier_decision_parity(reference):
    """Same training epochs -> same decisions and close decision
    values from both classifiers (reference classifier.py:37-690)."""
    import importlib
    ref_clf_mod = importlib.import_module("brainiak.fcma.classifier")

    raw = _make_epochs()
    labels = [0, 1] * 10
    epochs_per_subj = 4
    train = list(zip(raw[:12], raw[:12]))
    test = list(zip(raw[12:], raw[12:]))

    ref = ref_clf_mod.Classifier(
        svm.SVC(kernel='precomputed', shrinking=False, C=1,
                gamma='auto'),
        epochs_per_subj=epochs_per_subj)
    ref.fit(train, labels[:12])
    ref_dec = np.asarray(ref.decision_function(test))
    ref_pred = np.asarray(ref.predict(test))

    ours = OurClassifier(
        svm.SVC(kernel='precomputed', shrinking=False, C=1,
                gamma='auto'),
        epochs_per_subj=epochs_per_subj)
    ours.fit(train, labels[:12])
    our_dec = np.asarray(ours.decision_function(test))
    our_pred = np.asarray(ours.predict(test))

    np.testing.assert_array_equal(our_pred, ref_pred)
    np.testing.assert_allclose(our_dec, ref_dec, atol=5e-3)

    # portioned-Gram path (test samples predeclared via
    # num_training_samples, same contract as the reference).  Compare
    # portioned-to-portioned: in BOTH implementations this path's
    # decision values sit ~0.1 from the unportioned ones (fp32 Gram
    # accumulated in a different order through the digit shrink), so
    # the oracle is the reference's portioned path, not ref_dec.
    everything = list(zip(raw, raw))
    ref_p = ref_clf_mod.Classifier(
        svm.SVC(kernel='precomputed', shrinking=False, C=1,
                gamma='auto'),
        num_processed_voxels=2, epochs_per_subj=epochs_per_subj)
    ref_p.fit(everything, labels, num_training_samples=12)
    ours_p = OurClassifier(
        svm.SVC(kernel='precomputed', shrinking=False, C=1,
                gamma='auto'),
        num_processed_voxels=2, epochs_per_subj=epochs_per_subj)
    ours_p.fit(everything, labels, num_training_samples=12)
    np.testing.assert_allclose(
        np.asarray(ours_p.decision_function()),
        np.asarray(ref_p.decision_function()), atol=2e-2)
    np.testing.assert_array_equal(np.asarray(ours_p.predict()),
                                  np.asarray(ref_p.predict()))


def test_mvpa_voxelselector_parity(reference):
    """Searchlight-based activity MVPA selection returns the same
    per-voxel CV accuracies (reference mvpa_voxelselector.py:27-137)."""
    import importlib
    ref_mvpa_mod = importlib.import_module(
        "brainiak.fcma.mvpa_voxelselector")
    ref_sl_mod = importlib.import_module(
        "brainiak.searchlight.searchlight")

    dim, n_t = 5, 24
    rng = np.random.RandomState(8)
    data = rng.randn(dim, dim, dim, n_t).astype(np.float32)
    # plant signal in half the epochs for a couple of voxels
    labels = np.array([0, 1] * (n_t // 2))
    data[2, 2, 2, labels == 1] += 1.5
    data[1, 2, 2, labels == 1] += 1.0
    mask = np.ones((dim, dim, dim), dtype=bool)

    clf = svm.SVC(kernel='linear', shrinking=False, C=1)
    ref_sl = ref_sl_mod.Searchlight(sl_rad=1, shape=ref_sl_mod.Ball)
    ref_sel = ref_mvpa_mod.MVPAVoxelSelector(
        data, mask, labels, 4, ref_sl)
    ref_vol, ref_results = ref_sel.run(clf)

    our_sl = OurSearchlight(sl_rad=1, shape=OurBall)
    our_sel = OurMVPA(data, mask, labels, 4, our_sl)
    our_vol, our_results = our_sel.run(clf)

    np.testing.assert_allclose(
        np.asarray(our_vol, dtype=float),
        np.asarray(ref_vol, dtype=float), atol=1e-12)
    assert [v for v, _ in our_results] == [v for v, _ in ref_results]
    np.testing.assert_allclose([a for _, a in our_results],
                               [a for _, a in ref_results], atol=1e-12)


def test_voxelselector_scoring_parity(reference, monkeypatch):
    """FCMA stage-1 (correlation-based voxel selection) against the
    live reference.

    ``VoxelSelector.run`` cannot execute single-process — its MPI loop
    is a blocking master/worker protocol (reference
    voxelselector.py:89-238) — but the entire per-voxel compute
    pipeline lives in ``_voxel_scoring`` (reference
    voxelselector.py:467-516): correlation -> within-subject
    normalization -> Gram -> per-voxel CV, a plain method needing no
    communication.  Driving it directly over ALL voxels in one task is
    exactly what the master/worker protocol distributes, so per-voxel
    accuracy parity here pins the stage-1 numbers end to end.

    The constructor's size>1 guard (reference voxelselector.py:137-139)
    is bypassed by reporting a 2-rank world during construction only;
    nothing else touches the communicator except a rank lookup in a
    log line.
    """
    import importlib
    ref_vs_mod = importlib.import_module("brainiak.fcma.voxelselector")
    from brainiak_tpu.fcma.voxelselector import (VoxelSelector
                                                 as OurVoxelSelector)

    n_voxels, n_epochs, epochs_per_subj, n_folds = 16, 12, 4, 3
    raw = _make_epochs(num_epochs=n_epochs, num_voxels=n_voxels)
    labels = [0, 1] * (n_epochs // 2)

    monkeypatch.setattr(ref_vs_mod.MPI.COMM_WORLD.__class__,
                        "Get_size", lambda self: 2)
    ref_sel = ref_vs_mod.VoxelSelector(
        labels, epochs_per_subj, n_folds, raw,
        process_num=0)  # serial CV: fork pool adds nothing at this size

    def ref_accuracies(clf):
        res = ref_sel._voxel_scoring((0, n_voxels), clf)
        accs = np.empty(n_voxels)
        for vid, acc in res:
            accs[vid] = acc
        return accs

    def our_accuracies(clf):
        ours = OurVoxelSelector(labels, epochs_per_subj, n_folds, raw)
        accs = np.empty(n_voxels)
        for vid, acc in ours.run(clf):
            accs[vid] = acc
        return accs

    # host-CV path, precomputed-kernel SVC: identical sklearn CV over
    # Grams that differ only by fp32 summation order
    svc = svm.SVC(kernel='precomputed', shrinking=False, C=1,
                  gamma='auto')
    ref_svc = ref_accuracies(svc)
    np.testing.assert_allclose(our_accuracies(svc), ref_svc, atol=1e-12)

    # host-CV path, non-precomputed classifier: exercises the
    # raw-correlation-vector branch of _prepare_for_cross_validation
    from sklearn.linear_model import LogisticRegression
    np.testing.assert_allclose(
        our_accuracies(LogisticRegression()),
        ref_accuracies(LogisticRegression()), atol=1e-12)

    # on-device batched-SMO path vs the live reference: the flagship
    # stage-1 numbers.  fp32 duals can flip single near-boundary test
    # samples, so allow at most one epoch per voxel and demand exact
    # agreement on the vast majority
    our_svm = our_accuracies('svm')
    assert np.max(np.abs(our_svm - ref_svc)) <= 1.0 / n_epochs + 1e-12
    assert np.mean(np.abs(our_svm - ref_svc) < 1e-12) >= 0.75
