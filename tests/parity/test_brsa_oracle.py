"""Live-oracle parity for BRSA / GBRSA (round-3 verdict item 6).

The reference ``brainiak.reprsimil.brsa`` (its largest module, 4.2k
LoC of hand-derived gradients) runs LIVE through the ~15-line
Yule-Walker nitime stand-in in conftest.py — everything else it needs
is installed here.

The two implementations optimize different surfaces with different
budgets (reference: n_iter alternating fitU/fitV coordinate rounds;
repo: outer auto-nuisance rounds around a joint L-BFGS — see
docs/migration.md), so the comparison is estimator-level on shared
synthetic data with known structure: recovered condition similarity
C_/U_ and the voxelwise pseudo-SNR ordering must agree between
implementations and with the ground truth.
"""

import numpy as np
import pytest
from scipy.ndimage import gaussian_filter1d
from scipy.stats import spearmanr

from brainiak_tpu.reprsimil.brsa import BRSA as OurBRSA, GBRSA as OurGBRSA


@pytest.fixture(scope="module")
def ref_brsa_mod(reference):
    import importlib
    return importlib.import_module("brainiak.reprsimil.brsa")


def _brsa_data(seed=0, n_t=120, n_v=30, n_c=4, snr_lo=0.3, snr_hi=1.5):
    """Event design smoothed to an HRF-ish shape, betas drawn with a
    known condition covariance, AR(1) noise, and a voxelwise SNR ramp."""
    rng = np.random.RandomState(seed)
    design = np.zeros((n_t, n_c))
    for c in range(n_c):
        onsets = np.arange(6 + 3 * c, n_t - 8, 29)
        for o in onsets:
            design[o:o + 4, c] = 1.0
    design = gaussian_filter1d(design, 2.0, axis=0)

    u_true = np.array([[1.0, 0.7, 0.0, 0.0],
                       [0.7, 1.0, 0.0, 0.0],
                       [0.0, 0.0, 1.0, 0.5],
                       [0.0, 0.0, 0.5, 1.0]])
    beta = np.linalg.cholesky(u_true) @ rng.randn(n_c, n_v)
    snr = np.linspace(snr_lo, snr_hi, n_v)
    rng.shuffle(snr)

    noise = np.zeros((n_t, n_v))
    e = rng.randn(n_t, n_v)
    for t in range(1, n_t):
        noise[t] = 0.3 * noise[t - 1] + e[t]
    data = design @ (beta * snr) + noise
    coords = rng.rand(n_v, 3) * 10
    return data, design, coords, u_true, snr


def _offdiag_corr(a, b):
    triu = np.triu_indices(a.shape[0], k=1)
    return float(np.corrcoef(a[triu], b[triu])[0, 1])


def test_brsa_recovery_parity(ref_brsa_mod):
    """Recovered condition-similarity C_ and pseudo-SNR ordering agree
    between the reference's alternating optimizer and the repo's joint
    L-BFGS at comparable budgets (reference brsa.py:518-780)."""
    data, design, coords, u_true, snr = _brsa_data()
    onsets = np.array([0, 60])

    ref = ref_brsa_mod.BRSA(n_iter=15, auto_nuisance=True,
                            random_state=0)
    ref.fit(data, design, coords=coords, scan_onsets=onsets)

    ours = OurBRSA(n_iter=2, auto_nuisance=True, random_state=0)
    ours.fit(data, design, coords=coords, scan_onsets=onsets)

    ref_c = np.asarray(ref.C_)
    our_c = np.asarray(ours.C_)
    true_c = u_true  # unit diagonal already

    # both recover the generating similarity structure...
    assert _offdiag_corr(ref_c, true_c) > 0.8
    assert _offdiag_corr(our_c, true_c) > 0.8
    # ...and agree with each other
    assert _offdiag_corr(our_c, ref_c) > 0.85
    np.testing.assert_allclose(our_c, ref_c, atol=0.25)

    # pseudo-SNR: scale is not identified (reference normalizes by the
    # geometric mean), so compare orderings
    rho_ref, _ = spearmanr(np.asarray(ref.nSNR_), snr)
    rho_our, _ = spearmanr(np.asarray(ours.nSNR_), snr)
    assert rho_ref > 0.6 and rho_our > 0.6, (rho_ref, rho_our)
    rho_cross, _ = spearmanr(np.asarray(ours.nSNR_),
                             np.asarray(ref.nSNR_))
    assert rho_cross > 0.7, rho_cross

    # noise AR(1) estimates center near the generating 0.3 on both
    assert abs(np.median(np.asarray(ref.rho_)) - 0.3) < 0.2
    assert abs(np.median(np.asarray(ours.rho_)) - 0.3) < 0.2


def test_gbrsa_recovery_parity(ref_brsa_mod):
    """GBRSA grid-marginalized path (reference brsa.py:2696-3390):
    three subjects (it is a group model), matched grids.  The tight
    atol here is load-bearing: it pinned down a real r4 bug where the
    repo projected X0 out of the data but not the design, biasing
    across-block C_ to -0.8 (now within 0.06 of the oracle)."""
    datas, designs = [], []
    u_true = None
    for s in range(3):
        data, design, _, u_true, _ = _brsa_data(seed=10 + s)
        datas.append(data)
        designs.append(design)
    onsets = np.array([0, 60])

    ref = ref_brsa_mod.GBRSA(n_iter=10, auto_nuisance=True,
                             random_state=0, SNR_bins=11, rho_bins=10)
    ref.fit(datas, designs, scan_onsets=onsets)

    ours = OurGBRSA(n_iter=2, auto_nuisance=True, random_state=0,
                    SNR_bins=11, rho_bins=10)
    ours.fit(datas, designs, scan_onsets=onsets)

    ref_c = np.asarray(ref.C_)
    our_c = np.asarray(ours.C_)
    assert _offdiag_corr(ref_c, u_true) > 0.8
    assert _offdiag_corr(our_c, u_true) > 0.8
    assert _offdiag_corr(our_c, ref_c) > 0.9
    np.testing.assert_allclose(our_c, ref_c, atol=0.15)

    for s in range(3):
        rho_cross, _ = spearmanr(np.asarray(ours.nSNR_[s]).ravel(),
                                 np.asarray(ref.nSNR_[s]).ravel())
        assert rho_cross > 0.7, (s, rho_cross)
