"""Tolerance-based agreement with the LIVE reference implementation.

Every test here runs the reference's own pure-Python code (imported from
/root/reference/src via the shims in conftest.py) side by side with this
repo's implementation on identical inputs.  Unlike the golden-fixture
tests elsewhere in the suite, a deviation introduced symmetrically in
both a generator and its estimator cannot cancel here — the oracle is
the other implementation, not this repo.
"""

import numpy as np
import pytest

import brainiak_tpu.utils.fmrisim as our_sim
from brainiak_tpu.eventseg.event import EventSegment as OurEventSegment
from brainiak_tpu.hyperparamopt.hpo import fmin as our_fmin
from brainiak_tpu.isc import isc as our_isc, isfc as our_isfc
from brainiak_tpu.reconstruct.iem import (
    InvertedEncoding1D as OurIEM1D,
)
from brainiak_tpu.utils.utils import (
    p_from_null as our_p_from_null,
    phase_randomize as our_phase_randomize,
)


# ---------------------------------------------------------------- utils

def test_phase_randomize_bit_parity(reference):
    """Same data + same random_state -> identical surrogates (the FFT
    phase-scramble chain is deterministic given the RandomState;
    reference utils.py:720-800)."""
    rng = np.random.RandomState(0)
    data = rng.randn(40, 5, 6)
    for voxelwise in (False, True):
        ours = np.asarray(our_phase_randomize(
            data, voxelwise=voxelwise, random_state=7))
        refs = reference.utils.phase_randomize(
            data, voxelwise=voxelwise, random_state=7)
        np.testing.assert_allclose(ours, refs, atol=1e-12)
    # surrogates preserve each series' amplitude spectrum exactly
    sur = np.asarray(our_phase_randomize(data, random_state=1))
    np.testing.assert_allclose(np.abs(np.fft.fft(sur, axis=0)),
                               np.abs(np.fft.fft(data, axis=0)),
                               rtol=1e-8)


def test_p_from_null_exact_parity(reference):
    """p-values agree exactly for every side x exact combination
    (reference utils.py:803-872)."""
    rng = np.random.RandomState(3)
    observed = rng.randn(5)
    distribution = rng.randn(400, 5)
    for side in ("two-sided", "left", "right"):
        for exact in (False, True):
            ours = np.asarray(our_p_from_null(
                observed, distribution, side=side, exact=exact))
            refs = reference.utils.p_from_null(
                observed, distribution, side=side, exact=exact)
            np.testing.assert_allclose(ours, refs, atol=0.0)


# ------------------------------------------------------------------ isc

def test_isc_value_parity(reference):
    """ISC values (pairwise and leave-one-out) match the reference's
    np.corrcoef / array_correlation paths (reference isc.py:81-208)."""
    rng = np.random.RandomState(5)
    signal = rng.randn(50, 8)
    data = np.dstack([signal[:, :, None] + 0.8 * rng.randn(50, 8, 1)
                      for _ in range(5)]).reshape(50, 8, 5)
    for pairwise in (False, True):
        ours = np.asarray(our_isc(data, pairwise=pairwise))
        refs = reference.isc.isc(data, pairwise=pairwise)
        np.testing.assert_allclose(ours, refs, atol=1e-5)


def test_isfc_value_parity(reference):
    """ISFC (through the reference's fcma.util.compute_correlation fp32
    GEMM path, here the shimmed NumPy matmul) agrees within fp32
    tolerance (reference isc.py:211-480)."""
    rng = np.random.RandomState(6)
    data = rng.randn(40, 6, 5)
    ours_isfcs, ours_iscs = our_isfc(data, vectorize_isfcs=True)
    refs_isfcs, refs_iscs = reference.isc.isfc(data, vectorize_isfcs=True)
    np.testing.assert_allclose(np.asarray(ours_isfcs), refs_isfcs,
                               atol=2e-4)
    np.testing.assert_allclose(np.asarray(ours_iscs), refs_iscs,
                               atol=2e-4)


# ------------------------------------------------------------- eventseg

def test_eventseg_parity(reference):
    """Event boundaries and segment posteriors from the reference HMM
    (forward-backward with its Cython masked_log shimmed) match this
    repo's lax.scan implementation on identical data (reference
    event.py:64-405)."""
    rng = np.random.RandomState(8)
    n_events, t_per, v = 5, 12, 20
    event_patterns = rng.randn(n_events, v)
    data = np.vstack([
        np.tile(p, (t_per, 1)) + 0.5 * rng.randn(t_per, v)
        for p in event_patterns])
    ref_model = reference.event.EventSegment(n_events)
    ref_model.fit(data.copy())
    our_model = OurEventSegment(n_events)
    our_model.fit(data.copy())
    ref_bounds = np.argmax(ref_model.segments_[0], axis=1)
    our_bounds = np.argmax(np.asarray(our_model.segments_[0]), axis=1)
    # identical recovered event sequences on well-separated data
    np.testing.assert_array_equal(our_bounds, ref_bounds)
    # posteriors agree to optimizer tolerance
    np.testing.assert_allclose(np.asarray(our_model.segments_[0]),
                               ref_model.segments_[0], atol=1e-2)
    ll_ours = float(np.ravel(our_model.ll_)[-1])
    ll_ref = float(np.ravel(ref_model.ll_)[-1])
    assert abs(ll_ours - ll_ref) / abs(ll_ref) < 1e-3


# ------------------------------------------------------------------ hpo

def test_hpo_fmin_parity(reference):
    """Both TPE-style optimizers minimize the same multimodal 1-D
    objective to the same basin given the same budget (reference
    hpo.py:282-374)."""
    import scipy.stats as st

    def loss(kwargs):
        x = kwargs["x"]
        return float((x - 1.7) ** 2 * (x + 2.0) ** 2 + 0.3 * x)

    results = {}
    for name, fmin in (("ref", reference.hpo.fmin), ("ours", our_fmin)):
        np.random.seed(31)
        trials = []
        space = {"x": {"dist": st.uniform(-4.0, 8.0),
                       "lo": -4.0, "hi": 4.0}}
        best = fmin(loss, space, max_evals=60, trials=trials,
                    init_random_evals=20)
        results[name] = (best["x"], best["loss"])
        assert len(trials) == 60
    # the objective's global basin is near x = -2 (value ~ -0.6);
    # both must land there
    for name, (x, val) in results.items():
        assert val < 0.0, (name, x, val)
        assert abs(x - (-2.0)) < 0.5 or abs(x - 1.7) < 0.5, (name, x)
    assert abs(results["ref"][1] - results["ours"][1]) < 0.5


# ------------------------------------------------------------------ iem

def test_iem_recovery_parity(reference):
    """Both 1-D inverted encoding models recover held-out stimulus
    features from the same synthetic voxel responses with matching
    accuracy, and their predictions agree (reference iem.py:67-462)."""
    rng = np.random.RandomState(11)
    n_train, n_test, n_vox, n_chan = 120, 30, 40, 6

    # build stimulus-driven responses through idealized cosine channels
    feats_train = rng.uniform(0, 180, n_train)
    feats_test = rng.uniform(10, 170, n_test)
    centers = np.linspace(0, np.pi, n_chan, endpoint=False)

    def channel_resp(feats):
        th = np.deg2rad(feats)[:, None]
        return np.maximum(0, np.cos(th - centers[None])) ** 5

    W = rng.randn(n_chan, n_vox)
    B_train = channel_resp(feats_train) @ W \
        + 0.3 * rng.randn(n_train, n_vox)
    B_test = channel_resp(feats_test) @ W \
        + 0.3 * rng.randn(n_test, n_vox)

    preds = {}
    for name, cls in (("ref", reference.iem.InvertedEncoding1D),
                      ("ours", OurIEM1D)):
        model = cls(n_channels=n_chan, channel_exp=5,
                    stimulus_mode="halfcircular",
                    range_start=0.0, range_stop=180.0)
        model.fit(B_train, feats_train)
        p = np.asarray(model.predict(B_test), dtype=np.float64)
        err = np.abs(p - feats_test)
        err = np.minimum(err, 180.0 - err)  # circular distance
        assert np.mean(err) < 15.0, (name, np.mean(err))
        preds[name] = p
    d = np.abs(preds["ref"] - preds["ours"])
    d = np.minimum(d, 180.0 - d)
    assert np.mean(d) < 5.0
    assert np.max(d) < 25.0


# -------------------------------------------------------------- fmrisim

@pytest.mark.slow
def test_fmrisim_cross_oracle_noise(reference):
    """The decisive simulator-fidelity check the self-referential
    round-trip test cannot provide: the REFERENCE's calc_noise measures
    this repo's generate_noise output (and vice versa), so a deviation
    planted symmetrically in this repo's generator+estimator pair would
    be caught here (reference fmrisim.py:1291, 2833)."""
    np.random.seed(13)
    dims = np.array([12, 12, 12])
    trs = 100
    stimfunction = our_sim.generate_stimfunction(
        onsets=[], event_durations=[1], total_time=trs)
    stimfunction_tr = stimfunction[::100]
    mask, template = our_sim.mask_brain(dims, mask_self=False)
    target = {"sfnr": 60.0, "snr": 40.0, "matched": 0}

    # our generator -> reference estimator
    gen_dict = our_sim._noise_dict_update(dict(target))
    noise = our_sim.generate_noise(
        dimensions=dims, stimfunction_tr=stimfunction_tr,
        tr_duration=1.5, template=template, mask=mask,
        noise_dict=gen_dict, iterations=[5, 5])
    ref_est = reference.fmrisim.calc_noise(noise, mask, template)
    assert 0.4 * target["sfnr"] < ref_est["sfnr"] < 2.5 * target["sfnr"]
    assert 0.4 * target["snr"] < ref_est["snr"] < 2.5 * target["snr"]
    assert -0.9 < ref_est["auto_reg_rho"][0] < 0.9
    assert ref_est["fwhm"] > 0

    # reference generator -> our estimator
    np.random.seed(14)
    ref_dict = reference.fmrisim._noise_dict_update(dict(target))
    ref_noise = reference.fmrisim.generate_noise(
        dimensions=dims, stimfunction_tr=stimfunction_tr,
        tr_duration=1.5, template=template, mask=mask,
        noise_dict=ref_dict, iterations=[5, 5])
    our_est = our_sim.calc_noise(ref_noise, mask, template)
    assert 0.4 * target["sfnr"] < our_est["sfnr"] < 2.5 * target["sfnr"]
    assert 0.4 * target["snr"] < our_est["snr"] < 2.5 * target["snr"]

    # and the two estimators agree on the SAME volume
    ref_on_ours = reference.fmrisim.calc_noise(noise, mask, template)
    our_on_ours = our_sim.calc_noise(noise, mask, template)
    for key in ("snr", "sfnr"):
        ratio = our_on_ours[key] / ref_on_ours[key]
        assert 0.5 < ratio < 2.0, (key, ratio)


def test_arima_stand_in_rejects_high_order(reference):
    """The statsmodels ARIMA stand-in fills every AR/MA lag with
    rho[0]/theta[0], which is only meaningful for order (1, d, 1);
    anything higher must fail loudly rather than silently handing the
    reference wrong parameters (ADVICE r3)."""
    import statsmodels.tsa.arima.model as arima_model

    ARIMA = arima_model.ARIMA
    if ARIMA.__module__.startswith("statsmodels"):
        pytest.skip("real statsmodels installed; stand-in not in use")
    series = np.random.RandomState(0).randn(80)
    with pytest.raises(ValueError, match="order"):
        ARIMA(series, order=(2, 0, 0)).fit()
    fit = ARIMA(series, order=(1, 0, 1)).fit()
    assert fit.params.shape == (4,)
