"""Live-oracle parity for TFA / HTFA and the searchlight engine.

The reference ``factoranalysis`` runs live through the NumPy
``tfa_extension`` stand-in (its C++ RBF kernels re-stated in ~10 lines
of array math, conftest.py) and the single-rank mpi4py stand-in; the
reference searchlight through the mpi4py stand-in alone.

The two TFA implementations use different optimizers (reference: scipy
trust-region NLLS; repo: jitted bounded L-BFGS) from K-means inits, so
factor-center recovery — the quantity the model exists to estimate —
is the comparison, Hungarian-matched to the generating centers.
"""

import numpy as np
from scipy.optimize import linear_sum_assignment

from brainiak_tpu.factoranalysis.htfa import HTFA as OurHTFA
from brainiak_tpu.factoranalysis.tfa import TFA as OurTFA
from brainiak_tpu.searchlight.searchlight import (Ball as OurBall,
                                                  Searchlight
                                                  as OurSearchlight)


def _tfa_data(seed=0, n_v=150, n_t=25, K=2, spread=12.0, width=6.0,
              noise=0.05):
    rng = np.random.RandomState(seed)
    coords = (rng.rand(n_v, 3) * spread).astype(float)
    true_c = np.array([[3.0, 3.0, 3.0], [9.0, 9.0, 9.0]])[:K]
    factors = np.exp(-((coords[:, None, :] - true_c[None]) ** 2).sum(-1)
                     / width)
    data = factors @ rng.randn(K, n_t) + noise * rng.randn(n_v, n_t)
    return data, coords, true_c


def _matched_center_err(centers, true_c):
    cost = np.linalg.norm(centers[:, None, :] - true_c[None], axis=-1)
    r, c = linear_sum_assignment(cost)
    return float(cost[r, c].mean())


def test_tfa_center_recovery_parity(reference):
    """TFA (reference tfa.py:46-1035): both implementations must place
    the factor centers on the generating hotspots to comparable
    accuracy from the same data."""
    import importlib
    ref_tfa_mod = importlib.import_module("brainiak.factoranalysis.tfa")

    data, coords, true_c = _tfa_data()
    n_v, n_t = data.shape

    np.random.seed(100)
    ref = ref_tfa_mod.TFA(K=2, max_iter=8, max_num_voxel=n_v,
                          max_num_tr=n_t, verbose=False)
    ref.fit(data, coords)
    ref_centers = ref.get_centers(ref.local_posterior_)

    np.random.seed(100)
    ours = OurTFA(K=2, max_iter=8, max_num_voxel=n_v, max_num_tr=n_t,
                  verbose=False)
    ours.fit(data, coords)
    our_centers = ours.get_centers(ours.local_posterior_)

    ref_err = _matched_center_err(np.asarray(ref_centers), true_c)
    our_err = _matched_center_err(np.asarray(our_centers), true_c)
    # hotspots are ~6 apart; both must land within a fraction of that
    assert ref_err < 1.5, ref_err
    assert our_err < 1.5, our_err
    assert our_err < ref_err + 0.75, (our_err, ref_err)


def test_htfa_global_template_parity(reference):
    """HTFA (reference htfa.py:56-850): the MAP global template centers
    from multi-subject data must agree with the reference's."""
    import importlib
    ref_htfa_mod = importlib.import_module(
        "brainiak.factoranalysis.htfa")

    n_subj = 3
    datas, coords_list = [], []
    true_c = None
    for s in range(n_subj):
        data, coords, true_c = _tfa_data(seed=10 + s)
        datas.append(data)
        coords_list.append(coords)

    np.random.seed(100)
    ref = ref_htfa_mod.HTFA(K=2, n_subj=n_subj, max_global_iter=3,
                            max_local_iter=3, voxel_ratio=1.0,
                            tr_ratio=1.0, max_voxel=150, max_tr=25,
                            verbose=False)
    ref.fit(datas, coords_list)
    ref_centers = ref.get_centers(ref.global_posterior_)

    # reseed: both inits draw from the global numpy RNG, and the MAP
    # problem is multimodal — a shifted stream lands in another mode
    np.random.seed(100)
    ours = OurHTFA(K=2, n_subj=n_subj, max_global_iter=3,
                   max_local_iter=3, voxel_ratio=1.0, tr_ratio=1.0,
                   max_voxel=150, max_tr=25)
    ours.fit(datas, coords_list)
    our_centers = ours.get_centers(ours.global_posterior_)

    # On this data BOTH implementations converge to the same merged
    # template (measured r4: centers agree to 0.01 while sitting ~5
    # from the generating hotspots — the MAP template problem is
    # multimodal and they land in the SAME mode).  Mutual agreement is
    # the parity contract; truth recovery is bounded only loosely.
    cross = _matched_center_err(np.asarray(our_centers),
                                np.asarray(ref_centers))
    assert cross < 0.2, cross
    ref_err = _matched_center_err(np.asarray(ref_centers), true_c)
    our_err = _matched_center_err(np.asarray(our_centers), true_c)
    assert abs(ref_err - our_err) < 0.1, (ref_err, our_err)
    assert ref_err < 8 and our_err < 8


def test_searchlight_parity(reference):
    """Searchlight scatter/gather (reference searchlight.py:24-281):
    identical voxel function on identical data must produce an
    identical output volume, including the masked/edge handling."""
    import importlib
    ref_sl_mod = importlib.import_module(
        "brainiak.searchlight.searchlight")

    dim, n_t = 9, 8
    rng = np.random.RandomState(3)
    data = [rng.randn(dim, dim, dim, n_t) for _ in range(2)]
    mask = rng.rand(dim, dim, dim) > 0.2

    def voxel_fn(subjects, sl_mask, rad, bcast_var):
        return float(sum(np.sum(s[sl_mask]) for s in subjects)
                     + bcast_var)

    ref = ref_sl_mod.Searchlight(sl_rad=1, shape=ref_sl_mod.Ball)
    ref.distribute([d.copy() for d in data], mask.copy())
    ref.broadcast(2.5)
    ref_out = ref.run_searchlight(voxel_fn, pool_size=1)

    ours = OurSearchlight(sl_rad=1, shape=OurBall)
    ours.distribute([d.copy() for d in data], mask.copy())
    ours.broadcast(2.5)
    our_out = ours.run_searchlight(voxel_fn, pool_size=1)

    assert ref_out.shape == our_out.shape
    ref_vals = np.where(ref_out == None, np.nan,  # noqa: E711
                        ref_out).astype(float)
    our_vals = np.where(our_out == None, np.nan,  # noqa: E711
                        our_out).astype(float)
    np.testing.assert_allclose(our_vals, ref_vals, equal_nan=True,
                               rtol=1e-12)


def test_searchlight_shapes_and_threshold_parity(reference):
    """Cube/Diamond masks and min_active_voxels_proportion gating
    match the reference exactly (reference searchlight.py:30-120)."""
    import importlib
    ref_sl_mod = importlib.import_module(
        "brainiak.searchlight.searchlight")
    from brainiak_tpu.searchlight.searchlight import Cube as OurCube
    from brainiak_tpu.searchlight.searchlight import Diamond as OurDiamond

    dim, n_t = 7, 5
    rng = np.random.RandomState(4)
    data = [rng.randn(dim, dim, dim, n_t)]
    mask = rng.rand(dim, dim, dim) > 0.4

    def count_fn(subjects, sl_mask, rad, bcast_var):
        return float(np.sum(sl_mask))

    for ref_shape, our_shape in ((ref_sl_mod.Cube, OurCube),
                                 (ref_sl_mod.Diamond, OurDiamond)):
        for prop in (0.0, 0.7):
            ref = ref_sl_mod.Searchlight(
                sl_rad=1, shape=ref_shape,
                min_active_voxels_proportion=prop)
            ref.distribute([d.copy() for d in data], mask.copy())
            ref_out = ref.run_searchlight(count_fn, pool_size=1)

            ours = OurSearchlight(
                sl_rad=1, shape=our_shape,
                min_active_voxels_proportion=prop)
            ours.distribute([d.copy() for d in data], mask.copy())
            our_out = ours.run_searchlight(count_fn, pool_size=1)

            ref_vals = np.where(ref_out == None, np.nan,  # noqa: E711
                                ref_out).astype(float)
            our_vals = np.where(our_out == None, np.nan,  # noqa: E711
                                our_out).astype(float)
            np.testing.assert_allclose(
                our_vals, ref_vals, equal_nan=True,
                err_msg=f"{ref_shape.__name__} prop={prop}")
