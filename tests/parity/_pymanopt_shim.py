"""Minimal pymanopt stand-in for running reference SSSRM live.

Implements exactly the surface reference funcalign/sssrm.py touches
(sssrm.py:37-41, :428-552): ``manifolds.Stiefel/Euclidean/Product``,
``function.tensorflow`` (cost + Euclidean gradient via
``tf.GradientTape``), ``Problem``, and ``optimizers.ConjugateGradient``
— a Riemannian Fletcher–Reeves CG with QR retraction,
projection-based vector transport, and Armijo backtracking.

Caveat (same class as the statsmodels/nibabel stand-ins, see
conftest.py): the ORACLE here is the reference's objective functions
and alternating optimization structure; the generic manifold optimizer
underneath is this stand-in's, not pymanopt's, so optimizer-level
quirks of real pymanopt are not reproduced.  Tolerance-based
estimator-level comparisons only.
"""

import numpy as np

__all__ = ["install"]


class _Manifold:
    def projection(self, point, vector):
        raise NotImplementedError

    def retraction(self, point, vector):
        raise NotImplementedError

    def norm(self, point, vector):
        return float(np.sqrt(self.inner(point, vector, vector)))

    def inner(self, point, u, v):
        raise NotImplementedError


class Euclidean(_Manifold):
    def __init__(self, *shape):
        self.shape = shape

    def projection(self, point, vector):
        return vector

    def retraction(self, point, vector):
        return point + vector

    def inner(self, point, u, v):
        return float(np.sum(u * v))


class Stiefel(_Manifold):
    """n x p matrices with orthonormal columns."""

    def __init__(self, n, p):
        self.n, self.p = n, p

    def projection(self, point, vector):
        xtv = point.T @ vector
        sym = 0.5 * (xtv + xtv.T)
        return vector - point @ sym

    def retraction(self, point, vector):
        q, r = np.linalg.qr(point + vector)
        # qf retraction: fix the sign so diag(R) > 0 (unique QR)
        signs = np.sign(np.diag(r))
        signs[signs == 0] = 1.0
        return q * signs[np.newaxis, :]

    def inner(self, point, u, v):
        return float(np.sum(u * v))


class Product(_Manifold):
    def __init__(self, manifolds):
        self.manifolds = list(manifolds)

    def projection(self, point, vector):
        return [m.projection(x, g) for m, x, g in
                zip(self.manifolds, point, vector)]

    def retraction(self, point, vector):
        return [m.retraction(x, g) for m, x, g in
                zip(self.manifolds, point, vector)]

    def inner(self, point, u, v):
        return float(sum(m.inner(x, a, b) for m, x, a, b in
                         zip(self.manifolds, point, u, v)))


def _tensorflow(manifold):
    """``@function.tensorflow(manifold)`` — wraps a TF cost into an
    object exposing ``cost(point)`` and ``euclidean_gradient(point)``.
    """
    import tensorflow as tf

    def decorator(fn):
        class _Function:
            def cost(self, point):
                args = point if isinstance(point, list) else [point]
                return float(fn(*[tf.constant(a, dtype=tf.float64)
                                  for a in args]))

            def euclidean_gradient(self, point):
                single = not isinstance(point, list)
                args = [point] if single else point
                variables = [tf.Variable(a, dtype=tf.float64)
                             for a in args]
                with tf.GradientTape() as tape:
                    value = fn(*variables)
                grads = tape.gradient(value, variables)
                out = [np.zeros_like(a) if g is None else g.numpy()
                       for g, a in zip(grads, args)]
                return out[0] if single else out

        return _Function()

    return decorator


class Problem:
    def __init__(self, manifold, cost):
        self.manifold = manifold
        self._function = cost

    def cost(self, point):
        return self._function.cost(point)

    def riemannian_gradient(self, point):
        egrad = self._function.euclidean_gradient(point)
        return self.manifold.projection(point, egrad)


def _scale(manifold, vector, alpha):
    if isinstance(vector, list):
        return [alpha * v for v in vector]
    return alpha * vector


def _add(vector, other):
    if isinstance(vector, list):
        return [a + b for a, b in zip(vector, other)]
    return vector + other


class ConjugateGradient:
    """Riemannian Fletcher–Reeves CG with Armijo backtracking and
    projection-based vector transport."""

    def __init__(self, min_gradient_norm=1e-6, min_step_size=1e-10,
                 max_iterations=200, verbosity=0):
        self.min_gradient_norm = min_gradient_norm
        self.min_step_size = min_step_size
        self.max_iterations = max_iterations

    def run(self, problem, initial_point):
        man = problem.manifold
        x = initial_point
        cost = problem.cost(x)
        grad = problem.riemannian_gradient(x)
        direction = _scale(man, grad, -1.0)
        gg = man.inner(x, grad, grad)
        step = 1.0
        for _ in range(self.max_iterations):
            gnorm = np.sqrt(gg)
            if gnorm < self.min_gradient_norm:
                break
            # Armijo backtracking along the retracted direction;
            # start from a slightly grown previous step (pymanopt's
            # adaptive-initial-step heuristic)
            slope = man.inner(x, grad, direction)
            if slope >= 0:  # not a descent direction: restart on -grad
                direction = _scale(man, grad, -1.0)
                slope = -gg
            t = min(step * 2.0, 1.0)
            accepted = False
            while t >= 1e-12:
                candidate = man.retraction(x, _scale(man, direction, t))
                new_cost = problem.cost(candidate)
                if new_cost <= cost + 1e-4 * t * slope:
                    accepted = True
                    break
                t *= 0.5
            if not accepted:
                break
            step = t
            x = candidate
            cost = new_cost
            # pymanopt semantics: min_step_size is an OUTER stopping
            # criterion on the accepted step, not a bound on the line
            # search — the small step is still taken before stopping
            if t < self.min_step_size:
                grad = problem.riemannian_gradient(x)
                break
            new_grad = problem.riemannian_gradient(x)
            new_gg = man.inner(x, new_grad, new_grad)
            beta = new_gg / gg if gg > 0 else 0.0
            # transport the previous direction by projection to the
            # new tangent space
            transported = man.projection(x, direction)
            direction = _add(_scale(man, new_grad, -1.0),
                             _scale(man, transported, beta))
            grad, gg = new_grad, new_gg

        class _Result:
            pass

        result = _Result()
        result.point = x
        result.cost = cost
        return result


def install(sys_modules):
    """Register the stand-in under the pymanopt names."""
    import types

    pymanopt = types.ModuleType("pymanopt")
    manifolds = types.ModuleType("pymanopt.manifolds")
    optimizers = types.ModuleType("pymanopt.optimizers")
    function = types.ModuleType("pymanopt.function")

    manifolds.Stiefel = Stiefel
    manifolds.Euclidean = Euclidean
    manifolds.Product = Product
    optimizers.ConjugateGradient = ConjugateGradient
    function.tensorflow = _tensorflow
    pymanopt.Problem = Problem
    pymanopt.function = function
    pymanopt.manifolds = manifolds
    pymanopt.optimizers = optimizers

    sys_modules["pymanopt"] = pymanopt
    sys_modules["pymanopt.manifolds"] = manifolds
    sys_modules["pymanopt.optimizers"] = optimizers
    sys_modules["pymanopt.function"] = function
