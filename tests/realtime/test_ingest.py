"""TR sources: delivery, seek/resume, arrival-jitter metrics, and
the directory watcher's half-written-file tolerance (ISSUE 15)."""

import os
import threading
import time

import numpy as np
import pytest

from brainiak_tpu.obs import metrics as obs_metrics
from brainiak_tpu.realtime import (DirectoryWatcher, MemoryFeed,
                                   StoreReplay)

T, V = 10, 7


@pytest.fixture
def rows():
    return np.random.RandomState(0).randn(T, V)


def test_memory_feed_delivers_rows_with_indices(rows):
    feed = MemoryFeed(rows)
    assert len(feed) == T
    samples = list(feed)
    assert [s.index for s in samples] == list(range(T))
    for s in samples:
        assert np.array_equal(s.volume, rows[s.index])
        assert s.t_arrival > 0


def test_memory_feed_seek_and_mask(rows):
    mask = np.zeros(V)
    mask[:3] = 1
    feed = MemoryFeed(rows, mask=mask)
    feed.seek(7)
    samples = list(feed)
    assert [s.index for s in samples] == [7, 8, 9]
    assert samples[0].volume.shape == (3,)
    assert np.array_equal(samples[0].volume, rows[7, :3])


def test_memory_feed_flattens_realtime_stream():
    class FakeStream:  # duck-typed RealtimeStream
        brain = np.arange(2 * 2 * 1 * 4, dtype=float).reshape(
            2, 2, 1, 4)
        mask = np.array([[[1], [0]], [[1], [1]]])

    feed = MemoryFeed(FakeStream())
    sample = feed.next()
    assert sample.volume.shape == (3,)  # 3 in-mask voxels
    assert len(feed) == 4


def test_paced_feed_records_jitter(rows):
    feed = MemoryFeed(rows[:4], tr_s=0.01)
    list(feed)
    hist = obs_metrics.histogram(
        "realtime_arrival_jitter_seconds").summary(source="memory")
    assert hist is not None and hist["count"] == 3  # T-1 intervals
    assert obs_metrics.counter("realtime_trs_total").value(
        source="memory") == 4.0


def test_directory_watcher_reads_generator_layout(tmp_path, rows):
    mask = np.ones(V)
    mask[0] = 0
    np.save(tmp_path / "mask.npy", mask)
    for t in range(T):
        np.save(tmp_path / f"rt_{t:0>3}.npy", rows[t])
    watcher = DirectoryWatcher(tmp_path, n_trs=T, timeout_s=5.0)
    samples = list(watcher)
    assert len(samples) == T
    assert samples[3].volume.shape == (V - 1,)
    assert np.array_equal(samples[3].volume, rows[3, 1:])


def test_directory_watcher_retries_half_written_file(tmp_path,
                                                     rows):
    np.save(tmp_path / "rt_000.npy", rows[0])
    # a half-written volume: invalid npy bytes the producer will
    # finish shortly after the watcher first sees the file
    bad = tmp_path / "rt_001.npy"
    bad.write_bytes(b"\x93NUMPY")

    def finish_write():
        time.sleep(0.15)
        np.save(bad, rows[1])

    writer = threading.Thread(target=finish_write)
    writer.start()
    try:
        watcher = DirectoryWatcher(tmp_path, n_trs=2,
                                   timeout_s=10.0)
        samples = list(watcher)
    finally:
        writer.join()
    assert len(samples) == 2
    assert np.array_equal(samples[1].volume, rows[1])
    assert obs_metrics.counter(
        "realtime_ingest_retries_total").value(
            source="directory") >= 1.0


def test_directory_watcher_timeout_semantics(tmp_path, rows):
    np.save(tmp_path / "rt_000.npy", rows[0])
    # bounded scan that goes quiet mid-way: an error, not silence
    watcher = DirectoryWatcher(tmp_path, n_trs=3, timeout_s=0.1,
                               poll_s=0.01)
    assert watcher.next().index == 0
    with pytest.raises(TimeoutError, match="TR 1"):
        watcher.next()
    # open-ended scan: quiet means the scan is over
    watcher = DirectoryWatcher(tmp_path, timeout_s=0.1,
                               poll_s=0.01)
    assert [s.index for s in watcher] == [0]


def test_store_replay_and_seek(tmp_path, rows):
    from brainiak_tpu.data import write_store

    store = write_store(os.path.join(tmp_path, "store"),
                        [rows.T, rows.T * 2])
    replay = StoreReplay(store, subject=1)
    assert len(replay) == T
    samples = list(replay)
    assert np.allclose(samples[4].volume, rows[4] * 2, atol=1e-6)
    replay.seek(8)
    assert [s.index for s in replay] == [8, 9]


def test_directory_watcher_picks_up_late_mask(tmp_path, rows):
    """A watcher started before the producer wrote its metadata
    resolves mask.npy lazily at the first volume read (the
    generator writes mask.npy before any rt_*.npy), instead of
    silently locking in unmasked full volumes."""
    watcher = DirectoryWatcher(tmp_path, n_trs=2, timeout_s=10.0,
                               poll_s=0.01)  # empty dir so far
    mask = np.zeros(V)
    mask[:4] = 1

    def produce():
        time.sleep(0.1)
        np.save(tmp_path / "mask.npy", mask)
        for t in range(2):
            np.save(tmp_path / f"rt_{t:0>3}.npy", rows[t])

    producer = threading.Thread(target=produce)
    producer.start()
    try:
        samples = list(watcher)
    finally:
        producer.join()
    assert len(samples) == 2
    assert samples[0].volume.shape == (4,)
    assert np.array_equal(samples[1].volume, rows[1, :4])
