"""Realtime test isolation: obs sinks/metrics reset around every
test (the step-program retrace counters and the latency histograms
are process-global)."""

import pytest

from brainiak_tpu.obs import metrics, sink


@pytest.fixture(autouse=True)
def _clean_obs():
    sink.close_all()
    metrics.reset()
    yield
    sink.close_all()
    metrics.reset()
