"""Online estimators: O(1)-per-TR state, online == batch at every
prefix, retraces <= 1 per estimator (ISSUE 15 tentpole)."""

import numpy as np
import pytest
from scipy import stats

from brainiak_tpu.eventseg.event import EventSegment
from brainiak_tpu.obs import metrics as obs_metrics
from brainiak_tpu.realtime import (IncrementalEventSegment, OnlineISC,
                                   OnlineZScore)

T, V, R, K = 24, 13, 3, 4


@pytest.fixture
def scan():
    rng = np.random.RandomState(7)
    return rng.randn(T, V), rng.randn(T, V, R)


def _drive(est, rows):
    state = est.init_state()
    outs = []
    for t in range(rows.shape[0]):
        state, out = est.step(state, rows[t])
        outs.append({k: np.asarray(v) for k, v in out.items()})
    return state, outs


def test_online_zscore_matches_batch_prefix(scan):
    subj, _ = scan
    _, outs = _drive(OnlineZScore(V), subj)
    assert np.allclose(outs[0]["z"], 0.0)  # 1-sample std undefined
    for t in range(1, T):
        ref = stats.zscore(subj[:t + 1], axis=0, ddof=1)[t]
        assert np.max(np.abs(outs[t]["z"] - ref)) < 1e-9


def test_online_isc_loo_matches_batch_isc(scan):
    from brainiak_tpu.isc import isc
    subj, refs = scan
    _, outs = _drive(OnlineISC(refs), subj)
    for t in range(2, T, 5):
        stacked = np.concatenate(
            [subj[:t + 1, :, None], refs[:t + 1]], axis=2)
        batch = isc(stacked)  # [S, V]; row 0 = subj vs mean-refs
        err = np.nanmax(np.abs(outs[t]["isc"] - batch[0]))
        assert err < 1e-6, (t, err)


def test_online_isc_pairwise_and_windowed(scan):
    from brainiak_tpu.isc import isc
    subj, refs = scan
    window = 8
    _, outs = _drive(OnlineISC(refs, pairwise=True, window=window),
                     subj)
    for t in range(3, T, 5):
        stacked = np.concatenate(
            [subj[:t + 1, :, None], refs[:t + 1]], axis=2)
        # first R condensed rows are the (subject, ref_j) pairs
        batch = isc(stacked, pairwise=True)
        err = np.nanmax(np.abs(outs[t]["isc"].T - batch[:R]))
        assert err < 1e-6, (t, err)
        lo = max(0, t + 1 - window)
        stacked_w = np.concatenate(
            [subj[lo:t + 1, :, None], refs[lo:t + 1]], axis=2)
        batch_w = isc(stacked_w, pairwise=True)
        err_w = np.nanmax(np.abs(
            outs[t]["isc_windowed"].T - batch_w[:R]))
        assert err_w < 1e-6, (t, err_w)


def test_online_isc_validates_input(scan):
    subj, refs = scan
    with pytest.raises(ValueError, match=r"\[T, V, R\]"):
        OnlineISC(np.zeros(5))
    with pytest.raises(ValueError, match="window"):
        OnlineISC(refs, window=-1)
    est = OnlineISC(refs)
    state = est.init_state()
    for t in range(T):
        state, _ = est.step(state, subj[t])
    with pytest.raises(ValueError, match="past the end"):
        est.step(state, subj[0])


def test_incremental_eventseg_matches_batch_forward(scan):
    import jax.numpy as jnp

    from brainiak_tpu.eventseg.event import (_forward_pass,
                                             _logprob_obs_core)
    subj, _ = scan
    rng = np.random.RandomState(1)
    pat = rng.randn(V, K)
    model = EventSegment(n_events=K)
    model.set_event_patterns(pat)
    log_P, log_p_start, _ = model._build_transitions(T)
    logprob = np.asarray(_logprob_obs_core(
        jnp.asarray(subj.T), jnp.asarray(pat),
        jnp.asarray(np.full(K, 2.0))))
    lp_ext = np.hstack([logprob, np.full((T, 1), -np.inf)])
    batch_alpha = np.asarray(_forward_pass(
        jnp.asarray(lp_ext), jnp.asarray(log_P),
        jnp.asarray(log_p_start))[0])

    _, outs = _drive(IncrementalEventSegment(model, n_trs=T,
                                             var=2.0), subj)
    for t in range(T):
        row, ref = outs[t]["log_alpha"], batch_alpha[t]
        finite = np.isfinite(ref)
        assert np.array_equal(np.isfinite(row), finite)
        assert np.max(np.abs(row[finite] - ref[finite])) < 1e-8
        # the emitted posterior is exp(scaled alpha): a probability
        # row over the K events + the sink state
        post = outs[t]["posterior"]
        assert abs(post.sum() - 1.0) < 1e-8


def test_incremental_eventseg_requires_patterns_and_var():
    model = EventSegment(n_events=K)
    with pytest.raises(ValueError, match="event patterns"):
        IncrementalEventSegment(model, n_trs=T)
    model.set_event_patterns(np.random.RandomState(0).randn(V, K))
    with pytest.raises(ValueError, match="var="):
        IncrementalEventSegment(model, n_trs=T)
    # var from fit()-style attribute works too
    model.event_var_ = 3.0
    est = IncrementalEventSegment(model, n_trs=T)
    assert est.n_events == K


def test_estimators_report_state_size(scan):
    _, refs = scan
    assert OnlineZScore(V).state_nbytes > 0
    small = OnlineISC(refs).state_nbytes
    windowed = OnlineISC(refs, window=8).state_nbytes
    assert windowed > small  # the ring buffer costs W x V


def test_full_scan_retraces_at_most_one_per_estimator(scan):
    subj, refs = scan
    model = EventSegment(n_events=K)
    model.set_event_patterns(np.random.RandomState(2).randn(V, K))
    for est in (OnlineZScore(V), OnlineISC(refs, window=6),
                IncrementalEventSegment(model, n_trs=T, var=2.0)):
        _drive(est, subj)
    sites = {}
    for labels, value in obs_metrics.counter(
            "retrace_total").samples():
        if str(labels.get("site", "")).startswith("realtime."):
            sites[labels["site"]] = value
    assert all(count <= 1.0 for count in sites.values()), sites


def test_online_isc_is_stable_on_raw_fp32_intensities():
    """Raw fMRI intensities (mean >> std) in float32: the anchored
    sufficient statistics must stay parity-close to the batch
    isc(), where naive raw moments would cancel catastrophically
    (the TPU-path configuration streams fp32)."""
    from brainiak_tpu.isc import isc
    rng = np.random.RandomState(11)
    t_len = 60
    subj = (1000.0 + 10.0 * rng.randn(t_len, V)).astype(np.float32)
    refs = (1000.0 + 10.0 * rng.randn(t_len, V, R)).astype(
        np.float32)
    est = OnlineISC(refs, window=16, dtype=np.float32)
    state = est.init_state()
    for t in range(t_len):
        state, out = est.step(state, subj[t])
    stacked = np.concatenate(
        [subj[:, :, None], refs], axis=2).astype(np.float64)
    batch = isc(stacked)  # float64 reference
    err = np.nanmax(np.abs(np.asarray(out["isc"],
                                      dtype=np.float64) - batch[0]))
    assert np.isfinite(np.asarray(out["isc"])).all()
    assert err < 1e-3, err
    # windowed half too: last-16-TR window vs the float64 batch
    stacked_w = stacked[t_len - 16:]
    batch_w = isc(stacked_w)
    err_w = np.nanmax(np.abs(np.asarray(
        out["isc_windowed"], dtype=np.float64) - batch_w[0]))
    assert err_w < 1e-3, err_w
