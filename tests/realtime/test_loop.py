"""RealtimeSession: the closed-loop driver — deadline accounting,
checkpoint/resume mid-scan, and the warm low-latency serve hop
(ISSUE 15 tentpole)."""

import os

import numpy as np
import pytest

from brainiak_tpu.eventseg.event import EventSegment
from brainiak_tpu.obs import metrics as obs_metrics
from brainiak_tpu.obs import sink as obs_sink
from brainiak_tpu.realtime import (IncrementalEventSegment,
                                   MemoryFeed, OnlineISC,
                                   OnlineZScore, RealtimeSession)
from brainiak_tpu.resilience import faults

T, V, R, K = 20, 11, 2, 4


@pytest.fixture
def scan():
    rng = np.random.RandomState(3)
    return rng.randn(T, V), rng.randn(T, V, R)


def _session(scan, deadline_s=30.0, **kwargs):
    subj, refs = scan
    model = EventSegment(n_events=K)
    model.set_event_patterns(
        np.random.RandomState(5).randn(V, K))
    return RealtimeSession(
        MemoryFeed(subj),
        {"isc": OnlineISC(refs),
         "evseg": IncrementalEventSegment(model, n_trs=T, var=2.0)},
        preprocess=OnlineZScore(V), deadline_s=deadline_s,
        name="rt-loop-test", **kwargs)


def test_session_processes_whole_scan(scan):
    session = _session(scan)
    summary = session.run()
    assert summary["n_trs"] == T
    assert summary["n_deadline_misses"] == 0
    assert summary["deadline_miss_ratio"] == 0.0
    assert summary["p99_latency_s"] > 0
    # one output per TR, with both estimators' results fetched
    assert [o["tr"] for o in session.outputs] == list(range(T))
    out = session.outputs[-1]
    assert out["isc"]["isc"].shape == (V,)
    assert out["evseg"]["posterior"].shape == (K + 1,)
    assert not out["deadline_miss"]
    # per-stage latency sketches cover every stage + the total
    assert {"preprocess", "isc", "evseg", "total"} <= set(
        summary["stages"])
    assert all(count <= 1.0
               for count in summary["retraces"].values())


def test_deadline_misses_are_recorded_not_fatal(scan):
    mem = obs_sink.MemorySink()
    obs_sink.add_sink(mem)
    try:
        session = _session(scan, deadline_s=0.0)  # every TR misses
        summary = session.run()
    finally:
        obs_sink.remove_sink(mem)
    assert summary["n_trs"] == T  # the scan still completed
    assert summary["n_deadline_misses"] == T
    assert summary["deadline_miss_ratio"] == 1.0
    assert obs_metrics.counter(
        "realtime_deadline_miss_total").value(
            session="rt-loop-test") == float(T)
    events = [r for r in mem.records
              if r.get("name") == "deadline_exceeded"]
    assert len(events) == T
    attrs = events[0]["attrs"]
    assert attrs["deadline_s"] == 0.0
    assert "preprocess" in attrs["stages"]


def test_resume_mid_scan_matches_uninterrupted(scan, tmp_path):
    base = _session(scan)
    base.run()
    ckpt = os.path.join(tmp_path, "ckpt")
    with pytest.raises(faults.PreemptionError):
        with faults.inject("preempt", at_step=10):
            _session(scan).run(checkpoint_dir=ckpt,
                               checkpoint_every=5)
    resumed = _session(scan)
    resumed.run(checkpoint_dir=ckpt, checkpoint_every=5)
    # the resumed process holds only the TRs after the checkpoint
    assert resumed.outputs[0]["tr"] == 10
    assert resumed.outputs[-1]["tr"] == T - 1
    for est in ("isc", "evseg"):
        a_state = base.estimator_state(est)
        b_state = resumed.estimator_state(est)
        for leaf, a in a_state.items():
            b = b_state[leaf]
            finite = np.isfinite(a)
            assert np.array_equal(np.isfinite(b), finite)
            if finite.any():
                assert np.max(np.abs(a[finite] - b[finite])) < 1e-10


def test_resume_refuses_mismatched_configuration(scan, tmp_path):
    subj, refs = scan
    ckpt = os.path.join(tmp_path, "ckpt")
    with pytest.raises(faults.PreemptionError):
        with faults.inject("preempt", at_step=10):
            _session(scan).run(checkpoint_dir=ckpt,
                               checkpoint_every=5)
    other = RealtimeSession(
        MemoryFeed(subj), {"only_isc": OnlineISC(refs)},
        name="rt-loop-test")
    with pytest.raises(ValueError, match="different data"):
        other.run(checkpoint_dir=ckpt, checkpoint_every=5)


def test_estimator_names_cannot_collide_with_state_keys(scan):
    subj, refs = scan
    with pytest.raises(ValueError, match="must not contain"):
        RealtimeSession(MemoryFeed(subj),
                        {"a.b": OnlineISC(refs)})


def test_session_scores_through_low_latency_service(scan):
    from brainiak_tpu.serve import BucketPolicy, ModelResidency
    from brainiak_tpu.serve.__main__ import build_demo_model
    from brainiak_tpu.serve.service import ServeService

    subj, _ = scan
    srm = build_demo_model(n_subjects=2, voxels=V, samples=16,
                           features=3, n_iter=2, seed=0)
    residency = ModelResidency(
        budget_bytes=1 << 30,
        policy=BucketPolicy(max_batch=16, max_wait_s=5.0))
    residency.register("m", model=srm)
    with ServeService(residency, default_model="m") as service:
        session = RealtimeSession(
            MemoryFeed(subj), {"zs": OnlineZScore(V)},
            deadline_s=30.0, service=service, service_model="m",
            name="rt-serve-test")
        summary = session.run()
    assert summary["n_trs"] == T
    # every TR got a scored result back (shared response [k, 1]),
    # well inside a deadline far smaller than the 5 s batch window
    # it would have waited without the low-latency path
    for out in session.outputs:
        assert out["serve"] is not None
        assert out["serve"].shape == (3, 1)
    assert "serve" in summary["stages"]
    assert summary["n_deadline_misses"] == 0


def test_guard_rollback_does_not_double_count_slo(scan, tmp_path):
    """A NaN-guard rollback re-runs the chunk; the replayed TRs
    must not inflate n_trs / miss ratio / the latency sketches
    (the CI-gated SLO numbers)."""
    session = _session(scan)
    with faults.inject("nan", at_step=10):
        summary = session.run(checkpoint_dir=str(tmp_path / "ck"),
                              checkpoint_every=5)
    assert obs_metrics.counter("rollback_total").value(
        estimator="rt-loop-test") == 1.0
    assert summary["n_trs"] == T
    assert summary["stages"]["total"]["count"] == T
    assert [o["tr"] for o in session.outputs] == list(range(T))
    # and the replay converged to the same states as a clean run
    clean = _session(scan)
    clean.run()
    for leaf, a in clean.estimator_state("isc").items():
        b = session.estimator_state("isc")[leaf]
        assert np.max(np.abs(a - b)) < 1e-10


def test_retraces_are_per_session_deltas():
    """A second session over the same shapes reuses every cached
    step program: its retrace report is 0, not the process total
    (the InferenceEngine delta idiom).  A fresh voxel count forces
    the first session to build (the step caches are process-global
    and may be warm from earlier tests)."""
    v = V + 17  # unique shape -> guaranteed builds in session 1
    rows = np.random.RandomState(9).randn(T, v)

    def make():
        return RealtimeSession(MemoryFeed(rows),
                               {"zs": OnlineZScore(v)},
                               name="rt-delta-test")

    first = make()
    first.run()
    assert first.retraces().get("realtime.zscore_step") == 1.0
    second = make()
    summary = second.run()
    assert summary["retraces"]["realtime.zscore_step"] == 0.0


def test_resume_refuses_changed_estimator_config(scan, tmp_path):
    """Same estimator names and shapes but DIFFERENT parameters (a
    new reference group) must refuse the checkpoint — resuming
    would silently mix two groups' sufficient statistics."""
    subj, refs = scan
    ckpt = os.path.join(tmp_path, "ckpt")
    with pytest.raises(faults.PreemptionError):
        with faults.inject("preempt", at_step=10):
            RealtimeSession(
                MemoryFeed(subj), {"isc": OnlineISC(refs)},
                name="rt-loop-test").run(checkpoint_dir=ckpt,
                                         checkpoint_every=5)
    other_refs = refs + 1.0  # same shape, different content
    session = RealtimeSession(
        MemoryFeed(subj), {"isc": OnlineISC(other_refs)},
        name="rt-loop-test")
    with pytest.raises(ValueError, match="different data"):
        session.run(checkpoint_dir=ckpt, checkpoint_every=5)


def test_keep_outputs_bounds_retention(scan):
    subj, refs = scan
    with pytest.raises(ValueError, match="keep_outputs"):
        RealtimeSession(MemoryFeed(subj),
                        {"isc": OnlineISC(refs)}, keep_outputs=0)
    session = RealtimeSession(MemoryFeed(subj),
                              {"isc": OnlineISC(refs)},
                              keep_outputs=5, name="rt-keep")
    summary = session.run()
    assert summary["n_trs"] == T  # aggregates cover the whole scan
    assert [o["tr"] for o in session.outputs] == \
        list(range(T - 5, T))  # raw outputs: only the last 5


def test_reserved_stage_names_rejected(scan):
    subj, refs = scan
    for name in ("preprocess", "serve", "total"):
        with pytest.raises(ValueError, match="reserved"):
            RealtimeSession(MemoryFeed(subj),
                            {name: OnlineISC(refs)})
