"""Static-analysis gates, run with the suite (reference run-checks.sh).

The gate registry (tools/run_checks.py) shares one file walk between
the hermetic stdlib checks and the jaxlint TPU-correctness analyzer;
these tests enforce that the full gate — and the jaxlint gate alone —
run clean on the live tree, and that the machinery catches seeded
violations.
"""

import importlib.util
import json
import subprocess
import sys

from tests.conftest import REPO_ROOT


def _load_run_checks():
    spec = importlib.util.spec_from_file_location(
        "run_checks", f"{REPO_ROOT}/tools/run_checks.py")
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_static_checks_clean():
    r = subprocess.run(
        [sys.executable, f"{REPO_ROOT}/tools/run_checks.py"],
        capture_output=True, text=True)
    assert r.returncode == 0, f"static checks failed:\n{r.stdout}"


def test_run_checks_json_output():
    """--format=json emits one machine-readable object for CI,
    including per-gate wall time (ISSUE 10 satellite: gate-runtime
    creep must be visible as the registry grows)."""
    r = subprocess.run(
        [sys.executable, "-m", "tools.run_checks",
         "--format=json"],
        capture_output=True, text=True, cwd=REPO_ROOT)
    payload = json.loads(r.stdout)
    assert r.returncode == 0, r.stdout
    assert payload["ok"] is True
    assert payload["findings"] == []
    assert set(payload["gates"]) == {
        "external", "stdlib", "doc-defaults", "resilient-fits",
        "jaxlint", "jaxlint-deep", "jaxlint-ir", "obs", "obs-live",
        "obs-fit", "regress", "serve", "service", "federation",
        "fleet", "distla", "encoding", "kernels", "data",
        "realtime", "stats", "jobs"}
    assert payload["files"] > 100
    seconds = payload["gate_seconds"]
    assert set(seconds) == set(payload["gates"])
    assert all(isinstance(s, (int, float)) and s >= 0.0
               for s in seconds.values()), seconds
    # the analyzer gates (file rules + project-wide deep analysis)
    # must stay fast enough to run on every test invocation
    assert seconds["jaxlint"] + seconds["jaxlint-deep"] < 10.0, \
        seconds
    # the combined analyzer walk — AST families plus the traced-IR
    # audit child — must stay well under a minute (ISSUE 17
    # acceptance: asserted via gate_seconds, not wall-clock guesses)
    analyzer = (seconds["stdlib"] + seconds["jaxlint"]
                + seconds["jaxlint-deep"] + seconds["jaxlint-ir"])
    assert analyzer < 60.0, seconds


def test_jaxlint_gate_standalone():
    """`python -m tools.run_checks --only=jaxlint` runs the analyzer
    alone and exits clean on the live package (ISSUE 2 acceptance)."""
    r = subprocess.run(
        [sys.executable, "-m", "tools.run_checks",
         "--only=jaxlint"],
        capture_output=True, text=True, cwd=REPO_ROOT)
    assert r.returncode == 0, r.stdout


def test_jaxlint_clean_on_live_package():
    """In-process: every JX finding on the tree — file rules AND
    the project-wide deep families — is fixed or carries a
    justified baseline entry, and no baseline entry is stale."""
    from brainiak_tpu.analysis import cli as jaxlint_cli
    from brainiak_tpu.analysis.config import load_config
    config = load_config(
        str(REPO_ROOT), f"{REPO_ROOT}/pyproject.toml")
    deep = {r.code for r in jaxlint_cli.DEEP_RULES}
    assert deep & set(config.select), \
        "pyproject must select the deep rule families"
    findings, stale, n = jaxlint_cli.run(
        config.include_paths(), str(REPO_ROOT), config.select,
        baseline_path=config.baseline_path(),
        exclude=config.exclude)
    assert findings == [], [str(f) for f in findings]
    assert stale == [], f"stale baseline entries: {stale}"
    assert n > 50  # the walk actually covered the package


def test_jaxlint_deep_gate_standalone():
    """The jaxlint-deep gate runs the project rules alone over the
    configured scope and exits clean on the live tree (every
    JX010/JX1xx/JX2xx finding fixed or justified)."""
    rc = _load_run_checks()
    result = rc.run_gates(only=["jaxlint-deep"])
    assert result["ok"] is True, \
        [str(f) for f in result["findings"]]
    assert result["files"] > 50
    assert "jaxlint-deep" in result["gate_seconds"]


def test_gate_registry_selection():
    """run_gates honors --only and rejects unknown gates."""
    import pytest
    rc = _load_run_checks()
    result = rc.run_gates(only=["resilient-fits"])
    assert result["ok"] is True
    assert result["files"] == 0  # no file walk needed
    with pytest.raises(SystemExit, match="unknown gate"):
        rc.run_gates(only=["nope"])


def test_gate_rejects_unknown_select_code(monkeypatch):
    """A typo in [tool.jaxlint] select must fail the gate loudly,
    not silently disable the rule."""
    import pytest
    rc = _load_run_checks()
    real = rc.load_config

    def bad_config(*args, **kwargs):
        config = real(*args, **kwargs)
        config.select = ("JX001", "JX0099")
        return config

    monkeypatch.setattr(rc, "load_config", bad_config)
    with pytest.raises(SystemExit, match="JX0099"):
        rc.run_gates(only=["jaxlint"])


def test_resilience_gate_passes_on_repo():
    """Every public iterative fit accepts checkpoint_dir and runs
    under the resilience guard (run_resilient_loop / delegation)."""
    rc = _load_run_checks()
    findings = []
    rc.check_resilient_fits(findings)
    assert findings == []


def test_resilience_gate_catches_violations(tmp_path, monkeypatch):
    """The gate flags a fit without checkpoint_dir and a module that
    never touches the resilient-loop driver."""
    rc = _load_run_checks()
    bad = tmp_path / "bad_estimator.py"
    bad.write_text(
        "class Bad:\n"
        "    def fit(self, X):\n"
        "        return self\n")
    monkeypatch.setattr(rc, "REPO", str(tmp_path))
    monkeypatch.setattr(rc, "RESILIENT_FITS",
                        {"bad_estimator.py": ("Bad",)})
    findings = []
    rc.check_resilient_fits(findings)
    assert any("run_resilient_loop" in f.message for f in findings)
    assert any("checkpoint_dir" in f.message for f in findings)
    assert all(f.code == "CHK102" for f in findings)


def test_stdlib_gate_catches_seeded_violations(tmp_path):
    """One walk, shared context: line-length and unused-import rules
    both fire on a seeded file via the plugin registry."""
    rc = _load_run_checks()
    bad = tmp_path / "bad.py"
    bad.write_text(
        "import os\n"
        "X = '" + "x" * 90 + "'\n")
    from brainiak_tpu.analysis.core import analyze_file
    findings = analyze_file(
        str(bad), str(tmp_path),
        [rc.LineLength(), rc.UnusedImports()])
    codes = sorted(f.code for f in findings)
    assert codes == ["CHK002", "CHK003"]


def test_obs_gate_passes_on_repo():
    """The committed fixture trace renders clean through the report
    CLI smoke-run (ISSUE 3 satellite: obs gate)."""
    rc = _load_run_checks()
    findings = []
    rc.check_obs(findings)
    assert findings == [], [str(f) for f in findings]


def test_obs_gate_catches_schema_violations(tmp_path, monkeypatch):
    """A drifted/corrupt trace fixture fails the gate with OBS001."""
    rc = _load_run_checks()
    bad = tmp_path / "obs_fixture.jsonl"
    bad.write_text('{"v": 99, "kind": "span", "name": "x"}\n')
    monkeypatch.setattr(rc, "OBS_FIXTURE", str(bad))
    findings = []
    rc.check_obs(findings)
    assert findings and all(f.code == "OBS001" for f in findings)
    assert any("schema violation" in f.message for f in findings)


def test_obs_gate_catches_missing_fixture(tmp_path, monkeypatch):
    rc = _load_run_checks()
    monkeypatch.setattr(rc, "OBS_FIXTURE",
                        str(tmp_path / "nope.jsonl"))
    findings = []
    rc.check_obs(findings)
    assert [f.code for f in findings] == ["OBS001"]


def test_regress_gate_passes_on_committed_fixture():
    """The committed bench_fixture history (the repo's real BENCH_r*
    trajectory) gates clean (ISSUE 4 acceptance)."""
    rc = _load_run_checks()
    findings = []
    rc.check_regress(findings)
    assert findings == [], [str(f) for f in findings]


def test_regress_gate_fails_on_injected_2x_slowdown(tmp_path,
                                                    monkeypatch):
    """Degrading the fixture's newest record 2x flips the gate to a
    REG001 finding that names the metric (ISSUE 4 acceptance)."""
    import os
    import shutil
    rc = _load_run_checks()
    fixture = tmp_path / "bench_fixture"
    fixture.mkdir()
    for name in os.listdir(rc.BENCH_FIXTURE_DIR):
        shutil.copy(os.path.join(rc.BENCH_FIXTURE_DIR, name),
                    str(fixture))
    with open(str(fixture / "r05.json")) as fh:
        rec = json.load(fh)
    rec["value"] = rec["value"] / 2.0
    (fixture / "r06.json").write_text(json.dumps(rec))
    monkeypatch.setattr(rc, "BENCH_FIXTURE_DIR", str(fixture))
    findings = []
    rc.check_regress(findings)
    assert findings and all(f.code == "REG001" for f in findings)
    assert any("fcma_voxel_selection_voxels_per_sec_chip"
               in f.message for f in findings)


def test_regress_gate_catches_missing_fixture(tmp_path,
                                              monkeypatch):
    rc = _load_run_checks()
    monkeypatch.setattr(rc, "BENCH_FIXTURE_DIR",
                        str(tmp_path / "nope"))
    findings = []
    rc.check_regress(findings)
    assert [f.code for f in findings] == ["REG001"]


def test_stdlib_gate_honors_noqa(tmp_path):
    rc = _load_run_checks()
    bad = tmp_path / "bad.py"
    bad.write_text(
        "import os  # noqa\n"
        "X = '" + "x" * 90 + "'  # noqa\n")
    from brainiak_tpu.analysis.core import analyze_file
    findings = analyze_file(
        str(bad), str(tmp_path),
        [rc.LineLength(), rc.UnusedImports()])
    assert findings == []


def test_regress_gate_fails_when_fixture_cannot_gate(tmp_path,
                                                     monkeypatch):
    """A gutted fixture (every tier below min-history) must fail the
    gate instead of passing forever with zero coverage."""
    import os
    import shutil
    rc = _load_run_checks()
    fixture = tmp_path / "bench_fixture"
    fixture.mkdir()
    # keep only two records: newest becomes the sample, one prior
    # record is below the min-history bar
    for name in ("r01.json", "r02.json"):
        shutil.copy(os.path.join(rc.BENCH_FIXTURE_DIR, name),
                    str(fixture))
    monkeypatch.setattr(rc, "BENCH_FIXTURE_DIR", str(fixture))
    findings = []
    rc.check_regress(findings)
    assert [f.code for f in findings] == ["REG001"]
    assert "no gating" in findings[0].message


def test_serve_gate_passes_on_committed_fixture():
    """The serve gate (SRV001) smoke-runs the serving CLI on the
    committed tools/serve_fixture model + requests and passes on the
    live tree (ISSUE 5 satellite)."""
    rc = _load_run_checks()
    findings = []
    rc.check_serve(findings)
    assert findings == [], [str(f) for f in findings]


def test_serve_gate_catches_missing_fixture(tmp_path, monkeypatch):
    rc = _load_run_checks()
    monkeypatch.setattr(rc, "SERVE_FIXTURE_DIR",
                        str(tmp_path / "nope"))
    findings = []
    rc.check_serve(findings)
    assert [f.code for f in findings] == ["SRV001"]
    assert "missing" in findings[0].message


def test_serve_gate_catches_poison_fixture(tmp_path, monkeypatch):
    """A fixture whose requests produce error records fails the
    gate — the committed fixture must keep serving cleanly."""
    import os
    import shutil

    import numpy as np

    rc = _load_run_checks()
    fixture = tmp_path / "serve_fixture"
    fixture.mkdir()
    shutil.copy(os.path.join(rc.SERVE_FIXTURE_DIR, "model.npz"),
                str(fixture))
    from brainiak_tpu.serve import load_requests, save_requests
    reqs = load_requests(
        os.path.join(rc.SERVE_FIXTURE_DIR, "requests.npz"))
    payloads = [r.x for r in reqs]
    payloads[0] = np.full_like(payloads[0], np.nan)  # poison
    save_requests(str(fixture / "requests.npz"), payloads,
                  subjects=[r.subject for r in reqs])
    monkeypatch.setattr(rc, "SERVE_FIXTURE_DIR", str(fixture))
    findings = []
    rc.check_serve(findings)
    assert findings and all(f.code == "SRV001" for f in findings)
    assert any("error record" in f.message for f in findings)


def test_service_gate_passes_and_proves_restart_contract():
    """The service gate (SRV002, ISSUE 9 satellite): two `service`
    CLI runs over one temp AOT cache — the second must hit the
    cache and compile nothing.  Passing on the live tree IS the
    restart-zero-compile proof at true process granularity."""
    rc = _load_run_checks()
    findings = []
    rc.check_service(findings)
    assert findings == [], [str(f) for f in findings]


def test_service_gate_catches_missing_fixture(tmp_path,
                                              monkeypatch):
    rc = _load_run_checks()
    monkeypatch.setattr(rc, "SERVE_FIXTURE_DIR",
                        str(tmp_path / "nope"))
    findings = []
    rc.check_service(findings)
    assert [f.code for f in findings] == ["SRV002"]
    assert "missing" in findings[0].message


def test_federation_gate_catches_missing_fixture(tmp_path,
                                                 monkeypatch):
    rc = _load_run_checks()
    monkeypatch.setattr(rc, "SERVE_FIXTURE_DIR",
                        str(tmp_path / "nope"))
    findings = []
    rc.check_federation(findings)
    assert [f.code for f in findings] == ["SRV003"]
    assert "missing" in findings[0].message


def test_federation_gate_classifies_failures(monkeypatch):
    """SRV003 (ISSUE 14 satellite): warm-fleet retraces, a starved
    replica, lost tickets, missing sheds, per-device accounting,
    and sharded parity each classify distinctly.  The CLI half is
    stubbed with canned summaries so the classification paths run
    without 4 service subprocesses."""
    rc = _load_run_checks()

    def cli_stub(summary):
        return lambda aot_dir: (0, summary, "")

    def child(verdict):
        return ("import json, sys\n"
                f"print(json.dumps({verdict!r}))\n"
                "sys.exit(1)\n")

    ok_verdict = {"ok": True}
    warm = {"n_errors": 0, "retrace_total": 0.0,
            "aot": {"hits": 3},
            "federation": {"routed": {"r1": 5, "r2": 5}}}

    # warm fleet that recompiled -> retrace finding
    monkeypatch.setattr(rc, "_run_federation_cli", cli_stub(
        dict(warm, retrace_total=2.0)))
    monkeypatch.setattr(rc, "_FEDERATION_CHILD", child(ok_verdict))
    findings = []
    rc.check_federation(findings)
    assert [f.code for f in findings] == ["SRV003"]
    assert "zero serve retraces" in findings[0].message

    # router starved one replica
    monkeypatch.setattr(rc, "_run_federation_cli", cli_stub(
        dict(warm, federation={"routed": {"r1": 10, "r2": 0}})))
    findings = []
    rc.check_federation(findings)
    assert [f.code for f in findings] == ["SRV003"]
    assert "both replicas" in findings[0].message

    # selfcheck: lost tickets under overload
    monkeypatch.setattr(rc, "_run_federation_cli", cli_stub(warm))
    monkeypatch.setattr(rc, "_FEDERATION_CHILD", child(
        {"ok": False, "all_resolved": False}))
    findings = []
    rc.check_federation(findings)
    assert [f.code for f in findings] == ["SRV003"]
    assert "exactly one ticket" in findings[0].message

    # selfcheck: no sheds under overload
    monkeypatch.setattr(rc, "_FEDERATION_CHILD", child(
        {"ok": False, "all_resolved": True, "n_shed": 0,
         "retry_after_ok": False}))
    findings = []
    rc.check_federation(findings)
    assert [f.code for f in findings] == ["SRV003"]
    assert "shed" in findings[0].message

    # selfcheck: per-device accounting broke
    monkeypatch.setattr(rc, "_FEDERATION_CHILD", child(
        {"ok": False, "all_resolved": True, "n_shed": 4,
         "retry_after_ok": True, "routed": {"r1": 8, "r2": 8},
         "per_device_ok": False, "per_device": {"cpu0": 999}}))
    findings = []
    rc.check_federation(findings)
    assert [f.code for f in findings] == ["SRV003"]
    assert "per-device" in findings[0].message

    # selfcheck: sharded parity failure (the default classification)
    monkeypatch.setattr(rc, "_FEDERATION_CHILD", child(
        {"ok": False, "all_resolved": True, "n_shed": 4,
         "retry_after_ok": True, "per_device_ok": True,
         "max_err": 0.5, "tol": 1e-4, "n_devices": 8}))
    findings = []
    rc.check_federation(findings)
    assert [f.code for f in findings] == ["SRV003"]
    assert "parity" in findings[0].message


def test_fleet_gate_classifies_failures(monkeypatch):
    """SRV004 (ISSUE 16 satellite): a lost ticket, a missing
    failover, a missed degraded verdict, a missing scale-up, and
    scale-up retraces each classify distinctly.  The chaos-soak
    child is stubbed with canned verdicts so the classification
    paths run without a soak subprocess."""
    rc = _load_run_checks()

    def child(verdict):
        return ("import json, sys\n"
                f"print(json.dumps({verdict!r}))\n"
                "sys.exit(1)\n")

    # a request that never resolved: the invariant violation
    monkeypatch.setattr(rc, "_FLEET_CHILD", child(
        {"ok": False, "all_resolved": False, "n_unresolved": 3,
         "by_code": {"delivered": 45}}))
    findings = []
    rc.check_fleet(findings)
    assert [f.code for f in findings] == ["SRV004"]
    assert "LOST 3 ticket" in findings[0].message
    assert "exactly one ticket" in findings[0].message

    # the killed replica's work was not re-placed
    monkeypatch.setattr(rc, "_FLEET_CHILD", child(
        {"ok": False, "all_resolved": True, "failover_ok": False,
         "crash_fired": 1, "failover": {"n_replaced": 0},
         "routed": {"r2": 0}}))
    findings = []
    rc.check_fleet(findings)
    assert [f.code for f in findings] == ["SRV004"]
    assert "did not fail over" in findings[0].message

    # the stalled replica never went degraded
    monkeypatch.setattr(rc, "_FLEET_CHILD", child(
        {"ok": False, "all_resolved": True, "failover_ok": True,
         "survivor_routed_ok": True, "degraded_seen": False,
         "states": {"r1": "healthy"}}))
    findings = []
    rc.check_fleet(findings)
    assert [f.code for f in findings] == ["SRV004"]
    assert "degraded" in findings[0].message

    # the surge never scaled the fleet up
    monkeypatch.setattr(rc, "_FLEET_CHILD", child(
        {"ok": False, "all_resolved": True, "failover_ok": True,
         "survivor_routed_ok": True, "degraded_seen": True,
         "scale_up_ok": False, "scaled_replicas": [],
         "n_scaled_up_served": 0}))
    findings = []
    rc.check_fleet(findings)
    assert [f.code for f in findings] == ["SRV004"]
    assert "scale the fleet up" in findings[0].message

    # a scaled-up replica compiled: classified by the shared
    # retrace harness, identically to every selfcheck gate
    monkeypatch.setattr(rc, "_FLEET_CHILD", child(
        {"ok": False, "all_resolved": True, "failover_ok": True,
         "survivor_routed_ok": True, "degraded_seen": True,
         "scale_up_ok": True,
         "retraces": {"serve.fleet": 3.0}}))
    findings = []
    rc.check_fleet(findings)
    assert [f.code for f in findings] == ["SRV004"]
    assert "rebuilt" in findings[0].message


def test_distla_gate_passes_on_live_package():
    """The distla gate (DLA001) smoke-runs the pod-scale linear
    algebra selfcheck on the 8-device CPU mesh and passes on the
    live tree (ISSUE 6 satellite)."""
    rc = _load_run_checks()
    findings = []
    rc.check_distla(findings)
    assert findings == [], [str(f) for f in findings]


def test_distla_gate_classifies_failures(monkeypatch):
    """A failing selfcheck verdict is reported as DLA001, with
    retrace instability (program rebuilt on a repeat call) named
    separately from numerics parity."""
    rc = _load_run_checks()

    def fake_child(verdict):
        return ("import json, sys\n"
                f"print(json.dumps({verdict!r}))\n"
                "sys.exit(1)\n")

    monkeypatch.setattr(rc, "_DISTLA_CHILD", fake_child(
        {"ok": False, "max_err": 0.25, "tol": 5e-4, "n_shards": 8,
         "retraces": {"distla.summa": 1.0}}))
    findings = []
    rc.check_distla(findings)
    assert [f.code for f in findings] == ["DLA001"]
    assert "parity" in findings[0].message

    monkeypatch.setattr(rc, "_DISTLA_CHILD", fake_child(
        {"ok": False, "max_err": 0.0, "tol": 5e-4, "n_shards": 8,
         "retraces": {"distla.summa": 3.0, "distla.panel": 1.0}}))
    findings = []
    rc.check_distla(findings)
    assert [f.code for f in findings] == ["DLA001"]
    assert "rebuilt" in findings[0].message
    assert "distla.summa=3" in findings[0].message

    monkeypatch.setattr(rc, "_DISTLA_CHILD", "raise SystemExit(3)")
    findings = []
    rc.check_distla(findings)
    assert [f.code for f in findings] == ["DLA001"]
    assert "rc=3" in findings[0].message


def test_encoding_gate_classifies_failures(monkeypatch):
    """A failing encoding selfcheck is reported as ENC001, with
    retrace instability, a broken banded fit, and sklearn-parity
    failure each named distinctly."""
    rc = _load_run_checks()

    def fake_child(verdict):
        return ("import json, sys\n"
                f"print(json.dumps({verdict!r}))\n"
                "sys.exit(1)\n")

    monkeypatch.setattr(rc, "_ENCODING_CHILD", fake_child(
        {"ok": False, "max_err": 0.3, "tol": 1e-3,
         "banded_finite": True, "retraces": {}}))
    findings = []
    rc.check_encoding(findings)
    assert [f.code for f in findings] == ["ENC001"]
    assert "sklearn-parity" in findings[0].message

    monkeypatch.setattr(rc, "_ENCODING_CHILD", fake_child(
        {"ok": False, "max_err": 0.0, "tol": 1e-3,
         "banded_finite": True,
         "retraces": {"encoding.sweep": 4.0,
                      "encoding.refit": 1.0}}))
    findings = []
    rc.check_encoding(findings)
    assert [f.code for f in findings] == ["ENC001"]
    assert "rebuilt" in findings[0].message
    assert "encoding.sweep=4" in findings[0].message

    monkeypatch.setattr(rc, "_ENCODING_CHILD", fake_child(
        {"ok": False, "max_err": 0.0, "tol": 1e-3,
         "banded_finite": False, "retraces": {}}))
    findings = []
    rc.check_encoding(findings)
    assert [f.code for f in findings] == ["ENC001"]
    assert "non-finite" in findings[0].message

    # parity fine, retraces stable, but an expected site never
    # registered (builder no longer counted): named distinctly, not
    # misreported as a parity failure
    monkeypatch.setattr(rc, "_ENCODING_CHILD", fake_child(
        {"ok": False, "max_err": 1e-05, "tol": 1e-3,
         "banded_finite": True, "sites_present": False,
         "retraces": {"encoding.sweep": 1.0}}))
    findings = []
    rc.check_encoding(findings)
    assert [f.code for f in findings] == ["ENC001"]
    assert "missing expected" in findings[0].message
    assert "encoding.sweep" in findings[0].message

    monkeypatch.setattr(rc, "_ENCODING_CHILD",
                        "raise SystemExit(3)")
    findings = []
    rc.check_encoding(findings)
    assert [f.code for f in findings] == ["ENC001"]
    assert "rc=3" in findings[0].message


def test_kernels_gate_passes_on_live_package():
    """The kernels gate (KRN001, ISSUE 11 satellite) smoke-runs the
    fused-kernels parity selfcheck on the 8-device CPU mesh and
    passes on the live tree."""
    rc = _load_run_checks()
    findings = []
    rc.check_kernels(findings)
    assert findings == [], [str(f) for f in findings]


def test_kernels_gate_classifies_failures(monkeypatch):
    """A failing fused-kernels selfcheck is reported as KRN001, with
    retrace instability, a -inf/NaN mask mismatch, and numerics
    parity each named distinctly."""
    rc = _load_run_checks()

    def fake_child(verdict):
        return ("import json, sys\n"
                f"print(json.dumps({verdict!r}))\n"
                "sys.exit(1)\n")

    monkeypatch.setattr(rc, "_KERNELS_CHILD", fake_child(
        {"ok": False, "max_err": 0.2, "tol": 5e-4, "n_shards": 8,
         "mask_mismatch": [], "retraces": {}}))
    findings = []
    rc.check_kernels(findings)
    assert [f.code for f in findings] == ["KRN001"]
    assert "parity" in findings[0].message

    monkeypatch.setattr(rc, "_KERNELS_CHILD", fake_child(
        {"ok": False, "max_err": 0.0, "tol": 5e-4, "n_shards": 8,
         "mask_mismatch": ["fb_mask"],
         "retraces": {"eventseg.forward_backward": 1.0}}))
    findings = []
    rc.check_kernels(findings)
    assert [f.code for f in findings] == ["KRN001"]
    assert "mask" in findings[0].message
    assert "fb_mask" in findings[0].message

    monkeypatch.setattr(rc, "_KERNELS_CHILD", fake_child(
        {"ok": False, "max_err": 0.0, "tol": 5e-4, "n_shards": 8,
         "mask_mismatch": [],
         "retraces": {"distla.summa": 2.0,
                      "fcma.epoch_norm": 1.0}}))
    findings = []
    rc.check_kernels(findings)
    assert [f.code for f in findings] == ["KRN001"]
    assert "rebuilt" in findings[0].message
    assert "distla.summa=2" in findings[0].message

    monkeypatch.setattr(rc, "_KERNELS_CHILD", "raise SystemExit(3)")
    findings = []
    rc.check_kernels(findings)
    assert [f.code for f in findings] == ["KRN001"]
    assert "rc=3" in findings[0].message


# -- ISSUE 13: the data gate (DAT001) ---------------------------------

def test_data_gate_passes_on_live_package():
    """The data gate (DAT001) smoke-runs the streaming-data-plane
    selfcheck on the 8-device CPU mesh — streamed-vs-in-memory SRM
    parity over a real on-disk store, resume-at-shard-round after an
    injected preemption, retrace stability across repeat shard
    rounds — and passes on the live tree (ISSUE 13 satellite)."""
    rc = _load_run_checks()
    findings = []
    rc.check_data(findings)
    assert findings == [], [str(f) for f in findings]


def test_data_gate_classifies_failures(monkeypatch):
    """A failing data selfcheck is reported as DAT001, with retrace
    instability, a broken resume, and streamed-parity failure each
    named distinctly."""
    rc = _load_run_checks()

    def fake_child(verdict):
        return ("import json, sys\n"
                f"print(json.dumps({verdict!r}))\n"
                "sys.exit(1)\n")

    monkeypatch.setattr(rc, "_DATA_CHILD", fake_child(
        {"ok": False, "max_err": 0.2, "tol": 5e-4,
         "resume_ok": True, "retraces": {"srm.stream_init": 1.0}}))
    findings = []
    rc.check_data(findings)
    assert [f.code for f in findings] == ["DAT001"]
    assert "parity" in findings[0].message

    monkeypatch.setattr(rc, "_DATA_CHILD", fake_child(
        {"ok": False, "max_err": 0.0, "tol": 5e-4,
         "resume_ok": False, "retraces": {}}))
    findings = []
    rc.check_data(findings)
    assert [f.code for f in findings] == ["DAT001"]
    assert "resume" in findings[0].message

    monkeypatch.setattr(rc, "_DATA_CHILD", fake_child(
        {"ok": False, "max_err": 0.0, "tol": 5e-4,
         "resume_ok": True,
         "retraces": {"srm.stream_prob_shard": 3.0}}))
    findings = []
    rc.check_data(findings)
    assert [f.code for f in findings] == ["DAT001"]
    assert "rebuilt" in findings[0].message
    assert "srm.stream_prob_shard=3" in findings[0].message

    monkeypatch.setattr(rc, "_DATA_CHILD", "raise SystemExit(3)")
    findings = []
    rc.check_data(findings)
    assert [f.code for f in findings] == ["DAT001"]
    assert "rc=3" in findings[0].message


# -- ISSUE 15: the realtime gate (RT001) ------------------------------

def test_realtime_gate_passes_on_live_package():
    """The realtime gate (RT001) smoke-runs the closed-loop tier
    selfcheck — online-vs-batch parity at every prefix, resume-mid-
    scan parity after an injected preemption, retrace stability
    across repeat sessions with the warm low-latency serve hop —
    and passes on the live tree (ISSUE 15)."""
    rc = _load_run_checks()
    findings = []
    rc.check_realtime(findings)
    assert findings == [], [str(f) for f in findings]


def test_realtime_gate_classifies_failures(monkeypatch):
    """A failing realtime selfcheck is reported as RT001, with
    retrace instability, a broken resume, a failed serve hop, and
    online-vs-batch parity failure each named distinctly."""
    rc = _load_run_checks()

    def fake_child(verdict):
        return ("import json, sys\n"
                f"print(json.dumps({verdict!r}))\n"
                "sys.exit(1)\n")

    monkeypatch.setattr(rc, "_REALTIME_CHILD", fake_child(
        {"ok": False, "max_err": 0.2, "tol": 1e-6,
         "resume_ok": True, "serve_ok": True,
         "retraces": {"realtime.isc_step": 1.0}}))
    findings = []
    rc.check_realtime(findings)
    assert [f.code for f in findings] == ["RT001"]
    assert "parity" in findings[0].message

    monkeypatch.setattr(rc, "_REALTIME_CHILD", fake_child(
        {"ok": False, "max_err": 0.0, "tol": 1e-6,
         "resume_ok": False, "serve_ok": True, "retraces": {}}))
    findings = []
    rc.check_realtime(findings)
    assert [f.code for f in findings] == ["RT001"]
    assert "resume" in findings[0].message

    monkeypatch.setattr(rc, "_REALTIME_CHILD", fake_child(
        {"ok": False, "max_err": 0.0, "tol": 1e-6,
         "resume_ok": True, "serve_ok": False, "retraces": {}}))
    findings = []
    rc.check_realtime(findings)
    assert [f.code for f in findings] == ["RT001"]
    assert "serve" in findings[0].message.lower()

    monkeypatch.setattr(rc, "_REALTIME_CHILD", fake_child(
        {"ok": False, "max_err": 0.0, "tol": 1e-6,
         "resume_ok": True, "serve_ok": True,
         "retraces": {"realtime.evseg_step": 5.0}}))
    findings = []
    rc.check_realtime(findings)
    assert [f.code for f in findings] == ["RT001"]
    assert "rebuilt" in findings[0].message
    assert "realtime.evseg_step=5" in findings[0].message

    monkeypatch.setattr(rc, "_REALTIME_CHILD", "raise SystemExit(3)")
    findings = []
    rc.check_realtime(findings)
    assert [f.code for f in findings] == ["RT001"]
    assert "rc=3" in findings[0].message


# -- ISSUE 18: the stats gate (STA001) --------------------------------

def test_stats_gate_passes_on_live_package():
    """The stats gate (STA001) smoke-runs the resampling-statistics
    selfcheck — accumulator-vs-materialized p-value parity, chunk
    invariance under a starved budget, exact pooling through both
    wire formats, resume after an injected preemption, retrace
    stability — and passes on the live tree (ISSUE 18)."""
    rc = _load_run_checks()
    findings = []
    rc.check_stats(findings)
    assert findings == [], [str(f) for f in findings]


def test_stats_gate_classifies_failures(monkeypatch):
    """A failing stats selfcheck is reported as STA001, with broken
    pooling, a broken resume, retrace instability, and p-value
    parity failure each named distinctly."""
    rc = _load_run_checks()

    def fake_child(verdict):
        return ("import json, sys\n"
                f"print(json.dumps({verdict!r}))\n"
                "sys.exit(1)\n")

    monkeypatch.setattr(rc, "_STATS_CHILD", fake_child(
        {"ok": False, "max_err": 0.2, "tol": 0.0,
         "merge_ok": True, "resume_ok": True,
         "retraces": {"stats.sign_flip": 1.0}}))
    findings = []
    rc.check_stats(findings)
    assert [f.code for f in findings] == ["STA001"]
    assert "parity" in findings[0].message

    monkeypatch.setattr(rc, "_STATS_CHILD", fake_child(
        {"ok": False, "max_err": 0.0, "tol": 0.0,
         "merge_ok": False, "resume_ok": True, "retraces": {}}))
    findings = []
    rc.check_stats(findings)
    assert [f.code for f in findings] == ["STA001"]
    assert "merge" in findings[0].message

    monkeypatch.setattr(rc, "_STATS_CHILD", fake_child(
        {"ok": False, "max_err": 0.0, "tol": 0.0,
         "merge_ok": True, "resume_ok": False, "retraces": {}}))
    findings = []
    rc.check_stats(findings)
    assert [f.code for f in findings] == ["STA001"]
    assert "resume" in findings[0].message

    monkeypatch.setattr(rc, "_STATS_CHILD", fake_child(
        {"ok": False, "max_err": 0.0, "tol": 0.0,
         "merge_ok": True, "resume_ok": True,
         "retraces": {"stats.phase_randomize": 4.0}}))
    findings = []
    rc.check_stats(findings)
    assert [f.code for f in findings] == ["STA001"]
    assert "rebuilt" in findings[0].message
    assert "stats.phase_randomize=4" in findings[0].message

    monkeypatch.setattr(rc, "_STATS_CHILD", "raise SystemExit(3)")
    findings = []
    rc.check_stats(findings)
    assert [f.code for f in findings] == ["STA001"]
    assert "rc=3" in findings[0].message


def test_jobs_gate_passes_on_live_package():
    """The jobs gate (JOB001) smoke-runs the fit-scheduler
    selfcheck — two tenants' mixed-priority fits co-scheduled with
    warm serving, one injected priority preemption, zero lost jobs,
    park/resume parity, fair-share within tolerance, zero added
    serve retraces — and passes on the live tree (ISSUE 20)."""
    rc = _load_run_checks()
    findings = []
    rc.check_jobs(findings)
    assert findings == [], [str(f) for f in findings]


def test_jobs_gate_classifies_failures(monkeypatch):
    """A failing jobs selfcheck is reported as JOB001, with lost
    jobs, broken park/resume parity, a missing preemption,
    fair-share starvation, and serve-retrace regressions each named
    distinctly."""
    rc = _load_run_checks()

    def fake_child(verdict):
        return ("import json, sys\n"
                f"print(json.dumps({verdict!r}))\n"
                "sys.exit(1)\n")

    base = {"ok": False, "n_jobs": 2, "lost": [],
            "parity_ok": True, "preempt_ok": True,
            "n_preemptions": 1, "max_deficit": 0.0,
            "fair_tol": 1.0, "fairshare_ok": True,
            "serve_ok": True, "serve_retrace_delta": 0.0}

    monkeypatch.setattr(rc, "_JOBS_CHILD", fake_child(
        dict(base, lost=["deadbeef00000000"])))
    findings = []
    rc.check_jobs(findings)
    assert [f.code for f in findings] == ["JOB001"]
    assert "lost job" in findings[0].message
    assert "deadbeef00000000" in findings[0].message

    monkeypatch.setattr(rc, "_JOBS_CHILD", fake_child(
        dict(base, parity_ok=False)))
    findings = []
    rc.check_jobs(findings)
    assert [f.code for f in findings] == ["JOB001"]
    assert "parity" in findings[0].message

    monkeypatch.setattr(rc, "_JOBS_CHILD", fake_child(
        dict(base, preempt_ok=False, n_preemptions=0)))
    findings = []
    rc.check_jobs(findings)
    assert [f.code for f in findings] == ["JOB001"]
    assert "preemption never fired" in findings[0].message

    monkeypatch.setattr(rc, "_JOBS_CHILD", fake_child(
        dict(base, fairshare_ok=False, max_deficit=9.5)))
    findings = []
    rc.check_jobs(findings)
    assert [f.code for f in findings] == ["JOB001"]
    assert "starvation" in findings[0].message
    assert "9.5" in findings[0].message

    monkeypatch.setattr(rc, "_JOBS_CHILD", fake_child(
        dict(base, serve_retrace_delta=2.0)))
    findings = []
    rc.check_jobs(findings)
    assert [f.code for f in findings] == ["JOB001"]
    assert "retrace delta=2.0" in findings[0].message

    monkeypatch.setattr(rc, "_JOBS_CHILD", "raise SystemExit(3)")
    findings = []
    rc.check_jobs(findings)
    assert [f.code for f in findings] == ["JOB001"]
    assert "rc=3" in findings[0].message


def test_resilient_fits_method_entries(tmp_path, monkeypatch):
    """A RESILIENT_FITS entry may name the guarded method as
    "Class.method" (the realtime session's run()); a module whose
    named method lacks the contract is caught."""
    rc = _load_run_checks()
    bad = tmp_path / "loop.py"
    bad.write_text(
        "class RealtimeSession:\n"
        "    def run(self, n_trs=None):\n"
        "        pass\n")
    monkeypatch.setattr(
        rc, "RESILIENT_FITS",
        {str(bad.relative_to(tmp_path)): ("RealtimeSession.run",)})
    monkeypatch.setattr(rc, "REPO", str(tmp_path))
    findings = []
    rc.check_resilient_fits(findings)
    messages = [f.message for f in findings]
    assert any("RealtimeSession.run() does not accept "
               "checkpoint_dir=" in m for m in messages), messages
    assert any("run_resilient_loop" in m for m in messages)


# -- ISSUE 12: the obs-live gate (OBS002) -----------------------------

def test_obs_live_gate_passes_on_live_package():
    """The obs-live gate (OBS002): a child ServeService drive with
    SLO tracking + HTTP exposition, scraped and validated over real
    HTTP.  Passing on the live tree IS the live-telemetry
    acceptance at process granularity."""
    rc = _load_run_checks()
    findings = []
    rc.check_obs_live(findings)
    assert findings == [], [str(f) for f in findings]


def test_obs_live_gate_classifies_failures(monkeypatch):
    """A failing verdict is reported as OBS002 with the failure
    mode named: parse errors, missing series, summary/scrape
    disagreement, and hard child crashes each classify
    distinctly."""
    rc = _load_run_checks()

    def fake_child(verdict):
        return ("import json, sys\n"
                f"print(json.dumps({verdict!r}))\n"
                "sys.exit(1)\n")

    base = {"ok": False, "metrics_status": 200, "parse_errors": [],
            "missing": [], "healthz_ok": True,
            "readyz_ready": True, "counts_agree": True,
            "n_requested": 12, "n_ok": 12, "scraped_ok": 12.0}

    monkeypatch.setattr(rc, "_OBS_LIVE_CHILD", fake_child(
        dict(base, parse_errors=["line 3: unparseable sample"])))
    findings = []
    rc.check_obs_live(findings)
    assert [f.code for f in findings] == ["OBS002"]
    assert "not valid Prometheus text" in findings[0].message

    monkeypatch.setattr(rc, "_OBS_LIVE_CHILD", fake_child(
        dict(base, missing=["slo_burn_rate"])))
    findings = []
    rc.check_obs_live(findings)
    assert [f.code for f in findings] == ["OBS002"]
    assert "missing required series" in findings[0].message
    assert "slo_burn_rate" in findings[0].message

    monkeypatch.setattr(rc, "_OBS_LIVE_CHILD", fake_child(
        dict(base, counts_agree=False, scraped_ok=7.0)))
    findings = []
    rc.check_obs_live(findings)
    assert [f.code for f in findings] == ["OBS002"]
    assert "disagrees" in findings[0].message

    monkeypatch.setattr(rc, "_OBS_LIVE_CHILD", fake_child(
        dict(base, error="RuntimeError: boom")))
    findings = []
    rc.check_obs_live(findings)
    assert [f.code for f in findings] == ["OBS002"]
    assert "boom" in findings[0].message

    monkeypatch.setattr(rc, "_OBS_LIVE_CHILD",
                        "raise SystemExit(3)")
    findings = []
    rc.check_obs_live(findings)
    assert [f.code for f in findings] == ["OBS002"]
    assert "rc=3" in findings[0].message

    monkeypatch.setattr(rc, "_OBS_LIVE_CHILD", fake_child(
        dict(base, readyz_ready=False)))
    findings = []
    rc.check_obs_live(findings)
    assert [f.code for f in findings] == ["OBS002"]
    assert "readyz_ready=False" in findings[0].message


# -- ISSUE 19: the obs-fit gate (OBS003) ------------------------------

def test_obs_fit_gate_passes_on_live_package():
    """The obs-fit gate (OBS003): a child drives a chunked
    resilient fit through preempt/resume and a NaN-divergence
    incident, checking fit_id parity, precursor-before-guard
    ordering, the auto-dumped snapshot, and the postmortem render.
    Passing on the live tree IS the fit-telemetry acceptance at
    process granularity."""
    rc = _load_run_checks()
    findings = []
    rc.check_obs_fit(findings)
    assert findings == [], [str(f) for f in findings]


def test_obs_fit_gate_classifies_failures(monkeypatch):
    """A failing verdict is reported as OBS003 with the failure
    mode named: schema drift, resume-parity breaks, a late
    precursor, snapshot/postmortem failures, and hard child
    crashes each classify distinctly."""
    rc = _load_run_checks()

    def fake_child(verdict):
        return ("import json, sys\n"
                f"print(json.dumps({verdict!r}))\n"
                "sys.exit(1)\n")

    base = {"ok": False, "fit_id_stable": True,
            "chunks_monotone": True, "wall_cumulative": True,
            "chunks": [1, 2, 3, 4, 5], "aborted": True,
            "precursor_fired": True,
            "precursor_before_guard": True, "n_snapshots": 1,
            "snapshot_ok": True, "postmortem_rc": 0,
            "postmortem_ok": True, "schema_errors": []}

    monkeypatch.setattr(rc, "_OBS_FIT_CHILD", fake_child(
        dict(base, schema_errors=["progress: missing key ratio"])))
    findings = []
    rc.check_obs_fit(findings)
    assert [f.code for f in findings] == ["OBS003"]
    assert "not schema-clean" in findings[0].message

    monkeypatch.setattr(rc, "_OBS_FIT_CHILD", fake_child(
        dict(base, fit_id_stable=False)))
    findings = []
    rc.check_obs_fit(findings)
    assert [f.code for f in findings] == ["OBS003"]
    assert "resume parity broke" in findings[0].message

    monkeypatch.setattr(rc, "_OBS_FIT_CHILD", fake_child(
        dict(base, precursor_before_guard=False)))
    findings = []
    rc.check_obs_fit(findings)
    assert [f.code for f in findings] == ["OBS003"]
    assert "did not fire before the guard" in findings[0].message

    monkeypatch.setattr(rc, "_OBS_FIT_CHILD", fake_child(
        dict(base, n_snapshots=0, snapshot_ok=False)))
    findings = []
    rc.check_obs_fit(findings)
    assert [f.code for f in findings] == ["OBS003"]
    assert "n_snapshots=0" in findings[0].message

    monkeypatch.setattr(rc, "_OBS_FIT_CHILD", fake_child(
        dict(base, error="RuntimeError: boom")))
    findings = []
    rc.check_obs_fit(findings)
    assert [f.code for f in findings] == ["OBS003"]
    assert "boom" in findings[0].message

    monkeypatch.setattr(rc, "_OBS_FIT_CHILD",
                        "raise SystemExit(3)")
    findings = []
    rc.check_obs_fit(findings)
    assert [f.code for f in findings] == ["OBS003"]
    assert "rc=3" in findings[0].message


# -- jaxlint-ir gate --------------------------------------------------


def test_jaxlint_ir_gate_standalone():
    """`--only=jaxlint-ir` runs the traced-IR audit alone: the live
    tree traces every registered builder at its canonical signature
    with coverage >= 90%, and every JP3xx finding is fixed or
    carries a justified baseline entry (ISSUE 17 acceptance)."""
    rc = _load_run_checks()
    result = rc.run_gates(only=["jaxlint-ir"])
    assert result["ok"] is True, \
        [str(f) for f in result["findings"]]
    assert result["files"] == 0  # audit child owns the walk
    assert result["label"] == "jaxlint-ir"
    assert result["gate_seconds"]["jaxlint-ir"] > 0.0
    assert result["stale_baseline"] == []


def test_gate_list_includes_jaxlint_ir():
    """`--list` advertises the IR gate between the AST analyzer
    families and the runtime gates."""
    r = subprocess.run(
        [sys.executable, "-m", "tools.run_checks", "--list"],
        capture_output=True, text=True, cwd=REPO_ROOT)
    assert r.returncode == 0
    gates = r.stdout.split()
    assert gates.index("jaxlint") < gates.index("jaxlint-deep") \
        < gates.index("jaxlint-ir") < gates.index("obs")


def _fake_ir_child(monkeypatch, rc, verdict=None, stdout=None,
                   returncode=1, stderr="", timeout=False):
    def runner(cmd, **kwargs):
        assert "--ir" in cmd and "--format=json" in cmd
        env = kwargs.get("env") or {}
        assert env.get("JAX_PLATFORMS") == "cpu"
        assert "xla_force_host_platform_device_count" \
            in env.get("XLA_FLAGS", "")
        if timeout:
            raise rc.subprocess.TimeoutExpired(cmd, 420)
        out = stdout if stdout is not None else json.dumps(verdict)
        class Proc:
            pass
        proc = Proc()
        proc.stdout = out
        proc.stderr = stderr
        proc.returncode = returncode
        return proc
    monkeypatch.setattr(rc.subprocess, "run", runner)


def test_jaxlint_ir_gate_per_rule_classification(monkeypatch):
    """Audit findings keep their OWN JP codes in gate output — a
    dtype leak and a donation break stay distinguishable — and the
    child's JP-scoped stale-baseline entries join the report."""
    rc = _load_run_checks()
    _fake_ir_child(monkeypatch, rc, verdict={
        "coverage": 1.0,
        "findings": [
            {"path": "brainiak_tpu/a.py", "line": 3,
             "code": "JP301", "message": "float64 values appear",
             "snippet": "def build_a():"},
            {"path": "brainiak_tpu/b.py", "line": 7,
             "code": "JP302", "message": "declares no donation",
             "snippet": "def build_b():"},
        ],
        "skipped": [],
        "stale_baseline": [{"rule": "JP302", "path": "gone.py",
                            "snippet": "x", "reason": "old"}],
    })
    findings, stale = [], []
    rc.check_jaxlint_ir(findings, stale)
    assert [f.code for f in findings] == ["JP301", "JP302"]
    assert findings[0].path == "brainiak_tpu/a.py"
    assert findings[0].line == 3
    assert findings[1].snippet == "def build_b():"
    assert stale == [{"rule": "JP302", "path": "gone.py",
                      "snippet": "x", "reason": "old"}]


def test_jaxlint_ir_gate_coverage_contract(monkeypatch):
    """Builder coverage below 90% of the static census is a
    gate-level JPR001 naming every skipped site's reason."""
    rc = _load_run_checks()
    _fake_ir_child(monkeypatch, rc, verdict={
        "coverage": 0.5,
        "findings": [],
        "skipped": [
            {"site": "serve.srm",
             "reason": "signature factory failed: boom"},
            {"site": "isc.slab",
             "reason": "no canonical signature registered "
                       "(trace_signature missing)"},
        ],
        "stale_baseline": [],
    })
    findings, stale = [], []
    rc.check_jaxlint_ir(findings, stale)
    assert [f.code for f in findings] == ["JPR001"]
    msg = findings[0].message
    assert "50%" in msg and "90%" in msg
    assert "serve.srm" in msg and "isc.slab" in msg
    assert "signature factory failed" in msg


def test_jaxlint_ir_gate_child_failures(monkeypatch):
    """A crashed child (bad rc / no JSON) and a hung child each
    classify as gate-level JPR001, never as silence."""
    rc = _load_run_checks()
    _fake_ir_child(monkeypatch, rc, stdout="not json",
                   returncode=2, stderr="Traceback: boom")
    findings, stale = [], []
    rc.check_jaxlint_ir(findings, stale)
    assert [f.code for f in findings] == ["JPR001"]
    assert "rc=2" in findings[0].message
    assert "boom" in findings[0].message

    _fake_ir_child(monkeypatch, rc, timeout=True)
    findings, stale = [], []
    rc.check_jaxlint_ir(findings, stale)
    assert [f.code for f in findings] == ["JPR001"]
    assert "timed out" in findings[0].message


def test_run_checks_unified_sarif(monkeypatch, capsys):
    """--format=sarif merges every analyzer family into ONE log:
    JP3xx lint results stay level=warning, gate plumbing codes
    (JPR/CHK0 prefixes) map to level=error, and the driver carries
    rule metadata for the IR family."""
    rc = _load_run_checks()

    def fake_run_gates(only=None):
        return {
            "ok": False,
            "label": "test",
            "files": 2,
            "gates": ["stdlib", "jaxlint", "jaxlint-deep",
                      "jaxlint-ir"],
            "gate_seconds": {},
            "findings": [
                rc.Finding("a.py", 1, "CHK002", "line too long"),
                rc.Finding("b.py", 2, "JX001", "jit per call"),
                rc.Finding("c.py", 3, "JP301", "float64 leak"),
                rc.Finding("d.py", 4, "JPR001", "coverage 50%"),
            ],
            "stale_baseline": [],
        }

    monkeypatch.setattr(rc, "run_gates", fake_run_gates)
    assert rc.main(["--format", "sarif"]) == 1
    log = json.loads(capsys.readouterr().out)
    assert log["version"] == "2.1.0"
    run, = log["runs"]
    levels = {r["ruleId"]: r["level"] for r in run["results"]}
    assert levels == {"CHK002": "error", "JX001": "warning",
                      "JP301": "warning", "JPR001": "error"}
    rule_ids = {r["id"] for r in run["tool"]["driver"]["rules"]}
    # IR rules ship driver metadata alongside the AST families
    assert {"JP301", "JP302", "JP303", "JP304", "JP305",
            "JX001", "CHK002", "JPR001"} <= rule_ids
