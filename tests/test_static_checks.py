"""Static-analysis gate, run with the suite (reference run-checks.sh)."""

import subprocess
import sys

from tests.conftest import REPO_ROOT


def test_static_checks_clean():
    r = subprocess.run(
        [sys.executable, f"{REPO_ROOT}/tools/run_checks.py"],
        capture_output=True, text=True)
    assert r.returncode == 0, f"static checks failed:\n{r.stdout}"
