"""Static-analysis gate, run with the suite (reference run-checks.sh)."""

import importlib.util
import subprocess
import sys

from tests.conftest import REPO_ROOT


def _load_run_checks():
    spec = importlib.util.spec_from_file_location(
        "run_checks", f"{REPO_ROOT}/tools/run_checks.py")
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_static_checks_clean():
    r = subprocess.run(
        [sys.executable, f"{REPO_ROOT}/tools/run_checks.py"],
        capture_output=True, text=True)
    assert r.returncode == 0, f"static checks failed:\n{r.stdout}"


def test_resilience_gate_passes_on_repo():
    """Every public iterative fit accepts checkpoint_dir and runs
    under the resilience guard (run_resilient_loop / delegation)."""
    rc = _load_run_checks()
    findings = []
    rc.check_resilient_fits(findings)
    assert findings == []


def test_resilience_gate_catches_violations(tmp_path, monkeypatch):
    """The gate flags a fit without checkpoint_dir and a module that
    never touches the resilient-loop driver."""
    rc = _load_run_checks()
    bad = tmp_path / "bad_estimator.py"
    bad.write_text(
        "class Bad:\n"
        "    def fit(self, X):\n"
        "        return self\n")
    monkeypatch.setattr(rc, "REPO", str(tmp_path))
    monkeypatch.setattr(rc, "RESILIENT_FITS",
                        {"bad_estimator.py": ("Bad",)})
    findings = []
    rc.check_resilient_fits(findings)
    assert any("run_resilient_loop" in f for f in findings)
    assert any("checkpoint_dir" in f for f in findings)
