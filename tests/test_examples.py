"""Smoke tests: every example script runs end-to-end as a CLI.

The analog of the reference's notebook-execution tests
(tests/test_notebooks.py), but on the runnable example scripts with small
parameters.
"""

import os
import subprocess
import sys

import pytest

from tests.conftest import REPO_ROOT

EXAMPLES = os.path.join(REPO_ROOT, "examples")


def _run(script, *args, timeout=420):
    env = dict(os.environ)
    flags = env.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        env["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8").strip()
    r = subprocess.run(
        [sys.executable, os.path.join(EXAMPLES, script), "--backend",
         "cpu", *args],
        capture_output=True, timeout=timeout, env=env, cwd=REPO_ROOT)
    assert r.returncode == 0, r.stderr.decode()[-2000:]
    return r.stdout.decode()


def test_example_fcma():
    out = _run("fcma_voxel_selection_and_classification.py", "--top", "10")
    assert "classification accuracy" in out


def test_example_srm_with_mesh():
    out = _run("srm_image_reconstruction.py", "--subjects", "4",
               "--voxels", "120", "--trs", "80", "--features", "5",
               "--mesh")
    assert "shared-space correlation" in out


def test_example_isc():
    out = _run("isc_statistics.py", "--subjects", "8", "--trs", "120",
               "--n-resamples", "100")
    assert "bootstrap:" in out


@pytest.mark.slow
def test_example_htfa():
    out = _run("htfa_template.py", "--subjects", "2")
    assert "max center error" in out


def test_example_brsa():
    out = _run("brsa_representational_analysis.py", "--voxels", "20",
               "--trs", "200")
    assert "true-vs-BRSA correlation" in out


def test_example_eventseg():
    out = _run("eventseg_boundaries.py", "--events", "4",
               "--voxels", "12")
    assert "max boundary error" in out


def test_example_iem():
    out = _run("iem_orientation.py", "--voxels", "30", "--trials", "60")
    assert "median circular error" in out


def test_example_fcma_file_workflow(tmp_path):
    out = _run("fcma_file_workflow.py", "--subjects", "3",
               "--epochs-per-cond", "3", "--epoch-len", "12",
               "--dim", "6", "--top", "10", "--keep", str(tmp_path))
    assert "files on disk" in out
    assert "held-out-subject classification accuracy" in out
    # the dataset really was written in the reference layout
    files = sorted(p.name for p in tmp_path.iterdir())
    assert "epoch_labels.npy" in files and "mask.nii.gz" in files
    assert any(f.endswith("bet.nii.gz") for f in files)


def test_example_iem_synthetic_rf():
    out = _run("iem_synthetic_rf.py", "--voxels", "40", "--trials", "80")
    assert "channel peaks" in out
    assert "reconstruction-peak error" in out
    assert "R^2 by voxel count" in out


def test_example_matnormal():
    out = _run("matnormal_rsa.py", "--trs", "100", "--voxels", "20")
    assert "MNRSA similarity recovery" in out


def test_example_searchlight():
    out = _run("searchlight_decoding.py", "--dim", "12", "--ntr", "60")
    assert "traced tier: peak" in out
    assert "host tier" in out


def test_example_hpo():
    out = _run("hpo_branin.py", "--max-evals", "60")
    assert "hpo best" in out and "grid best" in out


def test_example_funcalign_variants():
    out = _run("funcalign_variants.py", "--subjects", "4", "--voxels",
               "100", "--trs", "80")
    assert "RSRM" in out and "SSSRM" in out and "FastSRM" in out


def test_example_fmrisim():
    out = _run("fmrisim_noise_simulation.py", "--trs", "40")
    assert "round-trip SFNR" in out


def test_example_realtime_decoding():
    out = _run("realtime_decoding.py", "--num-trs", "100")
    assert "incremental decoder accuracy" in out
    assert out.strip().endswith("OK")


def test_example_distributed_fcma():
    out = _run("distributed_fcma.py", "--processes", "2",
               "--devices-per-process", "2", "--top", "3")
    # every process prints the same gathered ranking; process output
    # order is racy, so assert each ranking line appears exactly twice
    # rather than comparing positional halves
    from collections import Counter
    assert out.count("top voxels:") == 2
    lines = [ln for ln in out.splitlines() if ln.startswith("  voxel ")]
    assert len(lines) == 6
    counts = Counter(lines)
    assert len(counts) == 3 and set(counts.values()) == {2}, counts
