import numpy as np
import pytest
from scipy.special import comb
from sklearn.exceptions import NotFittedError

from brainiak_tpu.eventseg.event import EventSegment


def test_fit_shapes():
    K, V, T = 5, 3, 10
    es = EventSegment(K, n_iter=2)
    rng = np.random.RandomState(0)
    es.fit(rng.rand(V, T).T)
    assert es.segments_[0].shape == (T, K)
    assert np.allclose(np.sum(es.segments_[0], axis=1), 1.0)

    T2 = 15
    test_segments, test_ll = es.find_events(rng.rand(V, T2).T)
    assert test_segments.shape == (T2, K)
    assert np.allclose(np.sum(test_segments, axis=1), 1.0)
    assert np.isfinite(test_ll)
    # scramble=True permutes the learned patterns (the reference's
    # null-model control for find_events)
    np.random.seed(4)
    scr_segments, scr_ll = es.find_events(rng.rand(V, T2).T,
                                          scramble=True)
    assert scr_segments.shape == (T2, K)
    assert np.allclose(np.sum(scr_segments, axis=1), 1.0)
    assert np.isfinite(scr_ll)

    with pytest.raises(ValueError):
        EventSegment(K).model_prior(K - 1)
    with pytest.raises(ValueError):
        EventSegment(K).set_event_patterns(np.zeros((V, K - 1)))


def test_simple_boundary():
    es = EventSegment(2)
    rng = np.random.RandomState(0)
    sample_data = np.array([[1, 1, 1, 0, 0, 0, 0],
                            [0, 0, 0, 1, 1, 1, 1]]) + rng.rand(2, 7) * 10
    es.fit(sample_data.T)
    events = np.argmax(es.segments_[0], axis=1)
    assert np.array_equal(events, [0, 0, 0, 1, 1, 1, 1])
    assert np.array_equal(es.predict(sample_data.T),
                          [0, 0, 0, 1, 1, 1, 1])


def test_event_transfer():
    es = EventSegment(2)
    sample_data = np.asarray([[1, 1, 1, 0, 0, 0, 0],
                              [0, 0, 0, 1, 1, 1, 1]], dtype=float)
    with pytest.raises(NotFittedError):
        es.find_events(sample_data.T)
    with pytest.raises(NotFittedError):
        es.find_events(sample_data.T, np.asarray([1, 1]))
    es.set_event_patterns(np.asarray([[1, 0], [0, 1]], dtype=float))
    seg = es.find_events(sample_data.T, np.asarray([1.0, 1.0]))[0]
    assert np.array_equal(np.argmax(seg, axis=1), [0, 0, 0, 1, 1, 1, 1])


def test_weighted_var():
    es = EventSegment(2)
    D = np.zeros((8, 4))
    for t in range(4):
        D[t, :] = (1 / np.sqrt(4 / 3)) * np.array([-1, -1, 1, 1])
    for t in range(4, 8):
        D[t, :] = (1 / np.sqrt(4 / 3)) * np.array([1, 1, -1, -1])
    mean_pat = D[[0, 4], :].T
    weights = np.zeros((8, 2))
    weights[:, 0] = [1, 1, 1, 1, 0, 0, 0, 0]
    weights[:, 1] = [0, 0, 0, 0, 1, 1, 1, 1]
    assert np.array_equal(
        es.calc_weighted_event_var(D, weights, mean_pat), [0, 0])
    weights[:, 0] = [1, 1, 1, 1, 0.5, 0.5, 0.5, 0.5]
    weights[:, 1] = [0.5, 0.5, 0.5, 0.5, 1, 1, 1, 1]
    true_var = (4 * 0.5 * 12) / (6 - 5 / 6) * np.ones(2) / 4
    assert np.allclose(
        es.calc_weighted_event_var(D, weights, mean_pat), true_var)


def test_sym():
    es = EventSegment(4)
    evpat = np.repeat(np.arange(10).reshape(-1, 1), 4, axis=1)
    es.set_event_patterns(evpat.astype(float))
    D = np.repeat(np.arange(10).reshape(1, -1), 20, axis=0).astype(float)
    ev = es.find_events(D, var=1)[0]
    assert np.allclose(ev[:, :2], np.fliplr(np.flipud(ev[:, 2:])))


def test_chains():
    es = EventSegment(5, event_chains=np.array(['A', 'A', 'B', 'B', 'B']))
    sample_data = np.array([[0, 0, 0], [1, 1, 1]], dtype=float)
    with pytest.raises(RuntimeError):
        es.fit(sample_data.T)
    es.set_event_patterns(np.array([[1, 1, 0, 0, 0],
                                    [0, 0, 1, 1, 1]], dtype=float))
    seg = es.find_events(sample_data.T, 0.1)[0]
    ev = np.nonzero(seg > 0.99)[1]
    assert np.array_equal(ev, [2, 3, 4])


def test_prior():
    K, T = 10, 100
    es = EventSegment(K)
    mp = es.model_prior(T)[0]

    p_bound = np.zeros((T, K - 1))
    norm = comb(T - 1, K - 1)
    for t in range(T - 1):
        for k in range(K - 1):
            p_bound[t + 1, k] = comb(t, k) * comb(T - t - 2, K - k - 2) \
                / norm
    p_bound = np.cumsum(p_bound, axis=0)

    mp_gt = np.zeros((T, K))
    for k in range(K):
        if k == 0:
            mp_gt[:, k] = 1 - p_bound[:, 0]
        elif k == K - 1:
            mp_gt[:, k] = p_bound[:, k - 1]
        else:
            mp_gt[:, k] = p_bound[:, k - 1] - p_bound[:, k]
    assert np.allclose(mp, mp_gt)


def test_split_merge():
    ev = np.array(
        [0, 0, 0, 1, 1, 1, 2, 2, 2, 3, 3, 3, 3, 3, 3, 3, 3, 3, 3, 3, 3, 3,
         3, 3, 3, 4, 4, 4, 4, 4, 4, 4, 4, 4, 4, 4, 4, 4, 4, 4, 4])
    rng = np.random.RandomState(0)
    ev_pat = rng.rand(5, 10)
    D = np.zeros((len(ev), 10))
    for t in range(len(ev)):
        D[t, :] = ev_pat[ev[t], :] + 0.1 * rng.rand(10)
    hmm_sm = EventSegment(5, split_merge=True, split_merge_proposals=2)
    hmm_sm.fit(D)
    assert np.array_equal(np.argmax(hmm_sm.segments_[0], axis=1), ev)

    # K=2 degenerate case: every (merge, split) pair collides with the
    # merge position, so the proposal list is empty and the split-merge
    # step must fall through cleanly rather than index into nothing
    rng2 = np.random.RandomState(1)
    pat2 = rng2.rand(2, 6)
    ev2 = np.array([0] * 8 + [1] * 8)
    D2 = pat2[ev2] + 0.05 * rng2.rand(16, 6)
    hmm2 = EventSegment(2, split_merge=True)
    hmm2.fit(D2)
    assert np.array_equal(np.argmax(hmm2.segments_[0], axis=1), ev2)


def test_subevent_patterns_degenerate_event():
    """An event whose soft-assignment mass crosses 1/2 at its first
    timepoint has an empty first half: its half-pattern must be zeros,
    not NaN."""
    es = EventSegment(2)
    t, v = 6, 4
    sp = np.zeros((t, 2))
    sp[0, 0] = 1.0                      # event 0: all mass at t=0
    sp[1:, 1] = 1.0 / (t - 1)           # event 1: uniform afterwards
    X = np.arange(v * t, dtype=float).reshape(v, t)
    first, second, pairs = es._subevent_patterns([X], [sp])
    assert np.all(np.isfinite(first)) and np.all(np.isfinite(second))
    assert np.allclose(first[:, 0], 0.0)
    assert np.all(np.isfinite(pairs))


def test_sym_ll():
    """Forward and time-reversed data give the same log-likelihood."""
    ev = np.array([0, 0, 0, 1, 1, 1, 1, 1, 1, 2, 2])
    rng = np.random.RandomState(0)
    ev_pat = rng.rand(3, 10)
    D_forward = np.zeros((len(ev), 10))
    for t in range(len(ev)):
        D_forward[t, :] = ev_pat[ev[t], :] + 0.1 * rng.rand(10)
    D_backward = np.flip(D_forward, axis=0)

    hmm_f = EventSegment(3)
    hmm_f.set_event_patterns(ev_pat.T)
    _, ll_forward = hmm_f.find_events(D_forward, var=1)

    hmm_b = EventSegment(3)
    hmm_b.set_event_patterns(np.flip(ev_pat.T, axis=1))
    _, ll_backward = hmm_b.find_events(D_backward, var=1)
    assert np.isclose(ll_forward, ll_backward)


def test_multiple_datasets_fit():
    rng = np.random.RandomState(1)
    base = np.array([[1, 1, 1, 0, 0, 0, 0], [0, 0, 0, 1, 1, 1, 1]],
                    dtype=float)
    X = [(base + rng.rand(2, 7)).T, (base + rng.rand(2, 7)).T]
    es = EventSegment(2).fit(X)
    assert len(es.segments_) == 2
    assert es.ll_.shape[1] == 2
    for seg in es.segments_:
        assert np.array_equal(np.argmax(seg, axis=1),
                              [0, 0, 0, 1, 1, 1, 1])


def test_fused_fit_matches_host_loop():
    """The one-dispatch while_loop fit must reproduce the host-driven
    annealing loop iterate for iterate (same LL history, patterns,
    segmentations, and stopping step).

    f64-only: the two loops fuse reductions differently, and in fp32
    the per-step rounding difference compounds chaotically through 60
    annealed EM steps — iterate-for-iterate equivalence is only a
    meaningful contract at f64 (the behavior both converge TO is pinned
    in fp32 by the recovery/boundary tests)."""
    import jax
    if not jax.config.jax_enable_x64:
        pytest.skip("iterate-level loop equivalence requires x64")
    rng = np.random.RandomState(7)
    n_vox, t, k = 12, 40, 4
    ev = np.linspace(0, t, k + 1).astype(int)
    pats = rng.rand(n_vox, k)
    d = np.zeros((t, n_vox))
    for e in range(k):
        d[ev[e]:ev[e + 1]] = pats[:, e] + 0.3 * rng.rand(
            ev[e + 1] - ev[e], n_vox)

    fused = EventSegment(k, n_iter=60).fit(d)
    host = EventSegment(k, n_iter=60)
    host._force_host_loop = True
    host.fit(d)

    assert fused.ll_.shape == host.ll_.shape
    # step 1's mean pattern is the z-scored data's row means (~0), so
    # z-scoring it amplifies fp rounding chaotically — both paths (and
    # the reference) share this; compare step 1 loosely, the rest tight
    assert np.allclose(fused.ll_[0], host.ll_[0], atol=5e-3)
    assert np.allclose(fused.ll_[1:], host.ll_[1:], rtol=1e-6)
    assert np.allclose(fused.event_pat_, host.event_pat_, rtol=1e-6)
    assert np.isclose(fused.event_var_, host.event_var_)
    assert np.allclose(fused.segments_[0], host.segments_[0], atol=1e-6)


def _fb_args(t, k, seed=0):
    import jax.numpy as jnp
    rng = np.random.RandomState(seed)
    es = EventSegment(k)
    log_P, log_p_start, log_p_end = es._build_transitions(t)
    lp = np.hstack([rng.randn(t, k), np.full((t, 1), -np.inf)])
    return (jnp.asarray(lp), jnp.asarray(log_P),
            jnp.asarray(log_p_start), jnp.asarray(log_p_end))


def _fb_compare(args):
    """(max diff treating mutual -inf/NaN as equal, mask mismatch)"""
    from brainiak_tpu.eventseg import event as ev
    g1, l1 = ev._fb_program()(*args)
    g2, l2 = ev._fb_reference_program()(*args)
    a, b = np.asarray(g1), np.asarray(g2)
    mismatch = (np.any(np.isneginf(a) != np.isneginf(b))
                or np.any(np.isnan(a) != np.isnan(b)))
    same = np.isneginf(a) & np.isneginf(b)
    with np.errstate(invalid="ignore"):
        d = np.abs(a - b)
    d[same | np.isnan(a)] = 0.0
    ll_ok = (float(l1) == float(l2)
             or np.isclose(float(l1), float(l2), rtol=1e-10))
    return float(np.max(d)), bool(mismatch), ll_ok


def test_fused_forward_backward_matches_two_scan_reference():
    """ISSUE 11 tentpole: the single-scan fused forward-backward
    (betas never materialized) reproduces the two-scan reference —
    gammas, lls, and -inf masks — across shapes."""
    for t, k in [(40, 5), (7, 2), (200, 16)]:
        d, mismatch, ll_ok = _fb_compare(_fb_args(t, k))
        assert d < 1e-9 and not mismatch and ll_ok, (t, k)


def test_fused_forward_backward_masked_log_edges():
    """Masked-log edge cases: an event column entirely -inf (an
    impossible state) and a huge-negative spike row yield identical
    gammas / NaN masks / lls on both paths."""
    import jax.numpy as jnp
    args = _fb_args(30, 4)
    lp = np.asarray(args[0])
    cases = [
        np.where(np.arange(5) == 1, -np.inf, lp),   # impossible event
        np.vstack([lp[:3], np.full((1, 5), -1e30), lp[4:]]),
    ]
    for case in cases:
        d, mismatch, ll_ok = _fb_compare(
            (jnp.asarray(case),) + args[1:])
        assert d < 1e-9 and not mismatch and ll_ok


def test_fused_sites_retrace_at_most_once():
    """Repeat fused fits/find_events add no program-builder cache
    misses (retrace_total{site=eventseg.*} <= 1 — ISSUE 11
    acceptance)."""
    from brainiak_tpu.obs import metrics as obs_metrics

    rng = np.random.RandomState(0)
    d = rng.rand(25, 6)
    es = EventSegment(3, n_iter=5).fit(d)
    es.find_events(d)
    retrace = obs_metrics.counter("retrace_total")
    before = {site: retrace.value(site=site)
              for site in ("eventseg.forward_backward",
                           "eventseg.fit_chunk")}
    assert before["eventseg.fit_chunk"] >= 1
    EventSegment(3, n_iter=5).fit(d)
    es.find_events(d)
    for site, count in before.items():
        assert retrace.value(site=site) == count, site
