from pathlib import Path

import numpy as np

from brainiak_tpu import io
from brainiak_tpu.fcma.preprocessing import (
    RandomType,
    generate_epochs_info,
    prepare_fcma_data,
    prepare_mvpa_data,
    prepare_searchlight_mvpa_data,
)

# Real data + golden outputs from the reference test suite (read-only).
DATA_DIR = Path("/root/reference/tests/io/data")
EXPECTED_DIR = Path("/root/reference/tests/fcma/data")
SUFFIX = "bet.nii.gz"
MASK_FILE = DATA_DIR / "mask.nii.gz"
EPOCH_FILE = DATA_DIR / "epoch_labels.npy"
EXPECTED_LABELS = np.array([0, 1, 0, 1])


def test_prepare_fcma_data_matches_reference_golden():
    images = io.load_images_from_dir(DATA_DIR, suffix=SUFFIX)
    mask = io.load_boolean_mask(MASK_FILE)
    conditions = io.load_labels(EPOCH_FILE)
    raw_data, raw_data2, labels = prepare_fcma_data(images, conditions, mask)
    expected_raw_data = np.load(EXPECTED_DIR / "expected_raw_data.npy")
    assert raw_data2 is None
    assert len(raw_data) == len(expected_raw_data)
    for idx in range(len(raw_data)):
        assert np.allclose(raw_data[idx], expected_raw_data[idx])
    assert np.array_equal(labels, EXPECTED_LABELS)


def test_prepare_fcma_data_randomized():
    mask = io.load_boolean_mask(MASK_FILE)
    conditions = io.load_labels(EPOCH_FILE)
    for random in (RandomType.REPRODUCIBLE, RandomType.UNREPRODUCIBLE):
        images = io.load_images_from_dir(DATA_DIR, suffix=SUFFIX)
        raw_data, _, labels = prepare_fcma_data(images, conditions, mask,
                                                random=random)
        assert len(raw_data) == 4
    # reproducible randomization is deterministic across runs
    images = io.load_images_from_dir(DATA_DIR, suffix=SUFFIX)
    r1, _, _ = prepare_fcma_data(images, conditions, mask,
                                 random=RandomType.REPRODUCIBLE)
    images = io.load_images_from_dir(DATA_DIR, suffix=SUFFIX)
    r2, _, _ = prepare_fcma_data(images, conditions, mask,
                                 random=RandomType.REPRODUCIBLE)
    for a, b in zip(r1, r2):
        assert np.array_equal(a, b)


def test_prepare_fcma_data_two_masks():
    images = io.load_images_from_dir(DATA_DIR, suffix=SUFFIX)
    mask = io.load_boolean_mask(MASK_FILE)
    conditions = io.load_labels(EPOCH_FILE)
    raw_data, raw_data2, labels = prepare_fcma_data(images, conditions,
                                                    mask, mask2=mask)
    assert raw_data2 is not None
    assert len(raw_data) == len(raw_data2) == 4
    for a, b in zip(raw_data, raw_data2):
        assert np.allclose(a, b)


def test_prepare_mvpa_data_matches_reference_golden():
    images = io.load_images_from_dir(DATA_DIR, suffix=SUFFIX)
    mask = io.load_boolean_mask(MASK_FILE)
    conditions = io.load_labels(EPOCH_FILE)
    processed_data, labels = prepare_mvpa_data(images, conditions, mask)
    expected = np.load(EXPECTED_DIR / "expected_processed_data.npy")
    assert processed_data.shape == expected.shape
    assert np.allclose(processed_data, expected)
    assert np.array_equal(labels, EXPECTED_LABELS)


def test_prepare_searchlight_mvpa_data_randomized():
    """Randomization permutes each subject's TRs before epoch
    averaging (reference preprocessing.py:328-414): labels and shape
    are unchanged, REPRODUCIBLE is deterministic across runs."""
    conditions = io.load_labels(EPOCH_FILE)
    images = io.load_images_from_dir(DATA_DIR, suffix=SUFFIX)
    base, base_labels = prepare_searchlight_mvpa_data(images, conditions)
    images = io.load_images_from_dir(DATA_DIR, suffix=SUFFIX)
    r1, labels1 = prepare_searchlight_mvpa_data(
        images, conditions, random=RandomType.REPRODUCIBLE)
    images = io.load_images_from_dir(DATA_DIR, suffix=SUFFIX)
    r2, _ = prepare_searchlight_mvpa_data(
        images, conditions, random=RandomType.REPRODUCIBLE)
    assert r1.shape == base.shape
    assert np.array_equal(labels1, base_labels)
    assert np.array_equal(r1, r2)
    assert not np.allclose(r1, base)


def test_prepare_searchlight_mvpa_data_matches_reference_golden():
    images = io.load_images_from_dir(DATA_DIR, suffix=SUFFIX)
    conditions = io.load_labels(EPOCH_FILE)
    processed_data, labels = prepare_searchlight_mvpa_data(images,
                                                           conditions)
    expected = np.load(
        EXPECTED_DIR / "expected_searchlight_processed_data.npy")
    assert processed_data.shape == expected.shape
    assert np.allclose(processed_data, expected)
    assert np.array_equal(labels, EXPECTED_LABELS)


def test_generate_epochs_info():
    conditions = io.load_labels(EPOCH_FILE)
    info = generate_epochs_info(conditions)
    assert len(info) == 4
    for cond, sid, start, end in info:
        assert cond in (0, 1)
        assert sid in (0, 1)
        assert end > start
