import numpy as np
from sklearn import svm

from brainiak_tpu.fcma.mvpa_voxelselector import MVPAVoxelSelector
from brainiak_tpu.searchlight import Cube, Searchlight


def test_mvpa_voxel_selection_finds_informative_region():
    rng = np.random.RandomState(0)
    dims = (5, 5, 5)
    n_epochs = 20
    labels = np.array([0, 1] * (n_epochs // 2))
    data = rng.randn(*dims, n_epochs).astype(np.float32)
    # informative corner: activity differs by condition
    data[:2, :2, :2, :] += labels[None, None, None, :] * 3.0
    mask = np.ones(dims, dtype=bool)

    sl = Searchlight(sl_rad=1, shape=Cube, pool_size=1)
    mvs = MVPAVoxelSelector(data, mask, labels, 2, sl)
    clf = svm.SVC(kernel='linear', shrinking=False, C=1)
    result_volume, results = mvs.run(clf)

    assert result_volume.shape == dims
    assert len(results) == mask.sum()
    # accuracies sorted descending
    accs = [r[1] for r in results]
    assert accs == sorted(accs, reverse=True)
    # a voxel inside the informative region classifies well
    assert result_volume[1, 1, 1] > 0.9
    # a distant noise voxel does not
    assert result_volume[3, 3, 3] < result_volume[1, 1, 1]


def test_mvpa_voxel_selection_empty_mask():
    import pytest

    data = np.zeros((4, 4, 4, 6), dtype=np.float32)
    mask = np.zeros((4, 4, 4), dtype=bool)
    sl = Searchlight(sl_rad=1)
    with pytest.raises(ValueError):
        MVPAVoxelSelector(data, mask, np.array([0, 1] * 3), 2, sl)
