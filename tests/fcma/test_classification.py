import math

import numpy as np
from numpy.random import RandomState
from scipy.spatial.distance import hamming
from scipy.stats.mstats import zscore
from sklearn import svm
from sklearn.linear_model import LogisticRegression

from brainiak_tpu.fcma.classifier import Classifier

# Same synthetic recipe as the reference fixture
# (reference tests/fcma/test_classification.py:25-40) so the golden
# predictions/confidences carry over.
prng = RandomState(1234567890)


def create_epoch(idx, num_voxels):
    row = 12
    mat = prng.rand(row, num_voxels).astype(np.float32)
    if idx % 2 == 0:
        mat = np.sort(mat, axis=0)
    mat = np.nan_to_num(zscore(mat, axis=0, ddof=0))
    return mat / math.sqrt(mat.shape[0])


def test_classification():
    fake_raw_data = [create_epoch(i, 5) for i in range(20)]
    labels = [0, 1] * 10
    epochs_per_subj = 4
    svm_clf = svm.SVC(kernel='precomputed', shrinking=False, C=1,
                      gamma='auto')
    training_data = fake_raw_data[0:12]
    clf = Classifier(svm_clf, epochs_per_subj=epochs_per_subj)
    clf.fit(list(zip(training_data, training_data)), labels[0:12])

    expected_confidence = np.array([-1.18234421, 0.97403604, -1.04005679,
                                    0.92403019, -0.95567738, 1.11746593,
                                    -0.83275891, 0.9486868])
    recomputed = clf.decision_function(
        list(zip(fake_raw_data[12:], fake_raw_data[12:])))
    # The reference's own assertion is sign agreement (hamming <= 1 of 8),
    # not exact values — its goldens aren't bit-reproducible from the
    # algorithm spec (an independent NumPy oracle agrees with our values).
    assert hamming(np.sign(expected_confidence),
                   np.sign(recomputed)) * 8 <= 1

    y_pred = clf.predict(list(zip(fake_raw_data[12:], fake_raw_data[12:])))
    expected_output = [0, 0, 0, 1, 0, 1, 0, 1]
    assert hamming(y_pred, expected_output) * 8 <= 1

    confidence = clf.decision_function(
        list(zip(fake_raw_data[12:], fake_raw_data[12:])))
    assert hamming(np.sign(expected_confidence),
                   np.sign(confidence)) * 8 <= 1

    y = [0, 1, 0, 1, 0, 1, 0, 1]
    score = clf.score(list(zip(fake_raw_data[12:], fake_raw_data[12:])), y)
    assert np.isclose(hamming(y_pred, y), 1 - score)


def test_classification_partial_similarity():
    fake_raw_data = [create_epoch(i, 5) for i in range(20)]
    labels = [0, 1] * 10
    svm_clf = svm.SVC(kernel='precomputed', shrinking=False, C=1,
                      gamma='auto')
    clf = Classifier(svm_clf, num_processed_voxels=2, epochs_per_subj=4)
    clf.fit(list(zip(fake_raw_data, fake_raw_data)), labels,
            num_training_samples=12)
    y_pred = clf.predict()
    expected_output = [0, 0, 0, 1, 0, 1, 0, 1]
    assert hamming(y_pred, expected_output) * 8 <= 1
    confidence = clf.decision_function()
    assert np.all(np.sign(confidence[np.asarray(expected_output) == 1]
                          ) >= 0)
    # score ignores X when the Gram was portioned
    score = clf.score(None, [0, 1, 0, 1, 0, 1, 0, 1])
    assert 0.5 <= score <= 1.0


def test_classification_pallas_sample_gram_matches():
    """The fused sample-Gram kernel (interpret mode) gives the same
    portioned-Gram classifier as the XLA accumulation path."""
    fake_raw_data = [create_epoch(i, 5) for i in range(20)]
    labels = [0, 1] * 10
    pairs = list(zip(fake_raw_data, fake_raw_data))

    def run(use_pallas):
        svm_clf = svm.SVC(kernel='precomputed', shrinking=False, C=1,
                          gamma='auto')
        clf = Classifier(svm_clf, num_processed_voxels=2,
                         epochs_per_subj=4, use_pallas=use_pallas)
        clf.fit(pairs, labels, num_training_samples=12)
        return clf

    ref = run(False)
    fused = run(True)
    assert np.allclose(fused.test_data_, ref.test_data_, atol=1e-4)
    assert np.array_equal(fused.predict(), ref.predict())
    # un-normalized feature path (epochs_per_subj=0) also agrees
    def run_raw(use_pallas):
        svm_clf = svm.SVC(kernel='precomputed', shrinking=False, C=1,
                          gamma='auto')
        clf = Classifier(svm_clf, num_processed_voxels=2,
                         epochs_per_subj=0, use_pallas=use_pallas)
        clf.fit(pairs, labels, num_training_samples=12)
        return clf

    assert np.allclose(run_raw(True).test_data_,
                       run_raw(False).test_data_, atol=1e-4)


def test_classification_logistic_regression():
    fake_raw_data = [create_epoch(i, 5) for i in range(20)]
    labels = [0, 1] * 10
    clf = Classifier(LogisticRegression(), epochs_per_subj=4)
    clf.fit(list(zip(fake_raw_data[0:12], fake_raw_data[0:12])),
            labels[0:12])
    y_pred = clf.predict(list(zip(fake_raw_data[12:], fake_raw_data[12:])))
    expected_output = [0, 0, 0, 1, 0, 1, 0, 1]
    assert hamming(y_pred, expected_output) * 8 <= 1


def test_classification_asymmetric_regions():
    """Region1 narrower than region2 triggers the internal swap (the
    correlation is symmetric, so predictions must not depend on pair
    order) — reference classifier.py:426-505 semantics."""
    fake_small = [create_epoch(i, 3) for i in range(20)]
    fake_large = [create_epoch(i, 7) for i in range(20)]
    labels = [0, 1] * 10
    svm_clf = svm.SVC(kernel='precomputed', shrinking=False, C=1,
                      gamma='auto')

    fwd = Classifier(svm_clf, epochs_per_subj=4)
    fwd.fit(list(zip(fake_small[:12], fake_large[:12])), labels[:12])
    pred_fwd = fwd.predict(list(zip(fake_small[12:], fake_large[12:])))

    svm_clf2 = svm.SVC(kernel='precomputed', shrinking=False, C=1,
                       gamma='auto')
    rev = Classifier(svm_clf2, epochs_per_subj=4)
    rev.fit(list(zip(fake_large[:12], fake_small[:12])), labels[:12])
    pred_rev = rev.predict(list(zip(fake_large[12:], fake_small[12:])))

    np.testing.assert_array_equal(pred_fwd, pred_rev)
    assert fwd.num_features_ == rev.num_features_ == 21


def test_classification_num_training_samples_warning(caplog):
    """num_training_samples with a non-precomputed classifier is
    ignored with a warning, not an error (reference
    classifier.py:426-470)."""
    import logging

    fake_raw_data = [create_epoch(i, 5) for i in range(12)]
    labels = [0, 1] * 6
    clf = Classifier(LogisticRegression(), epochs_per_subj=4)
    with caplog.at_level(logging.WARNING,
                         logger="brainiak_tpu.fcma.classifier"):
        clf.fit(list(zip(fake_raw_data, fake_raw_data)), labels,
                num_training_samples=8)
    assert any("num_training_samples" in r.message
               for r in caplog.records)
    preds = clf.predict(list(zip(fake_raw_data, fake_raw_data)))
    assert len(preds) == 12


def test_classification_errors():
    import pytest

    fake_raw_data = [create_epoch(i, 5) for i in range(8)]
    labels = [0, 1] * 4
    svm_clf = svm.SVC(kernel='precomputed', shrinking=False, C=1)
    clf = Classifier(svm_clf, num_processed_voxels=2, epochs_per_subj=2)
    with pytest.raises(RuntimeError):
        # portioned kernel requires num_training_samples
        clf.fit(list(zip(fake_raw_data, fake_raw_data)), labels)
    with pytest.raises(ValueError):
        clf.fit(list(zip(fake_raw_data, fake_raw_data)), labels,
                num_training_samples=8)


def test_predict_without_prepared_test_data_raises():
    """predict(X=None)/decision_function(X=None) without test data
    prepared during fit raise a clear ValueError instead of sklearn
    failing opaquely on None (PR 5 satellite)."""
    import pytest

    fake_raw_data = [create_epoch(i, 5) for i in range(8)]
    labels = [0, 1] * 4
    clf = Classifier(svm.SVC(kernel='precomputed', shrinking=False,
                             C=1, gamma='auto'), epochs_per_subj=2)
    clf.fit(list(zip(fake_raw_data, fake_raw_data)), labels)
    with pytest.raises(ValueError, match="predict"):
        clf.predict()
    with pytest.raises(ValueError, match="decision_function"):
        clf.decision_function()
    # passing X explicitly still works after the rejected call
    assert len(clf.predict(
        list(zip(fake_raw_data[:4], fake_raw_data[:4])))) == 4
