import math

import numpy as np
from numpy.random import RandomState
from scipy.stats.mstats import zscore
from sklearn import svm
from sklearn.linear_model import LogisticRegression

from brainiak_tpu.fcma.voxelselector import VoxelSelector
from brainiak_tpu.ops.fisherz import within_subject_normalization


def create_epoch(prng, col=5):
    """Same synthetic epoch recipe as the reference test fixture
    (reference tests/fcma/test_voxel_selection.py:27-36), so the golden
    accuracies below carry over."""
    row = 12
    mat = prng.rand(row, col).astype(np.float32)
    mat = np.nan_to_num(zscore(mat, axis=0, ddof=0))
    return mat / math.sqrt(mat.shape[0])


def test_within_subject_normalization_golden():
    """Reference golden values (tests/fcma/test_voxel_selection.py:58-66)."""
    prng = RandomState(1234567890)
    _ = [create_epoch(prng) for _ in range(8)]
    fake_corr = prng.rand(1, 4, 5).astype(np.float32)
    out = np.asarray(within_subject_normalization(fake_corr, 4))
    expected = [[[1.06988919, 0.51641309, -0.46790636, -1.31926763,
                  0.2270218],
                 [-1.22142744, -1.39881694, -1.2979387, 1.05702305,
                  -0.6525566],
                 [0.89795232, 1.27406132, 0.36460185, 0.87538344,
                  1.5227468],
                 [-0.74641371, -0.39165771, 1.40124381, -0.61313909,
                  -1.0972116]]]
    assert np.allclose(out, expected, atol=1e-4)


def _accuracy_counts(results, n_voxels, n_epochs=8):
    output = [None] * n_voxels
    for vid, acc in results:
        output[vid] = int(round(n_epochs * acc))
    return output


def test_voxel_selection_sklearn_parity():
    """Host-sklearn CV path reproduces the reference golden accuracies
    (tests/fcma/test_voxel_selection.py:68-90)."""
    prng = RandomState(1234567890)
    fake_raw_data = [create_epoch(prng) for _ in range(8)]
    labels = [0, 1, 0, 1, 0, 1, 0, 1]
    vs = VoxelSelector(labels, 4, 2, fake_raw_data, voxel_unit=1)

    clf = svm.SVC(kernel='precomputed', shrinking=False, C=1, gamma='auto')
    output = _accuracy_counts(vs.run(clf), 5)
    assert np.allclose(output, [7, 4, 6, 4, 4], atol=1)

    output = _accuracy_counts(vs.run(LogisticRegression()), 5)
    assert np.allclose(output, [6, 3, 6, 4, 4], atol=1)


def test_voxel_selection_on_device_svm():
    """The batched on-device SMO dual-SVM CV matches host sklearn SVC
    EXACTLY on identical kernels (the SMO solver honors the yᵀa=0
    equality constraint), and both sit within the reference's own
    tolerance band (atol=1 epoch) of its golden counts."""
    prng = RandomState(1234567890)
    fake_raw_data = [create_epoch(prng) for _ in range(8)]
    labels = [0, 1, 0, 1, 0, 1, 0, 1]
    vs = VoxelSelector(labels, 4, 2, fake_raw_data, voxel_unit=1)
    output = _accuracy_counts(vs.run('svm'), 5)
    clf = svm.SVC(kernel='precomputed', shrinking=False, C=1,
                  gamma='auto')
    host = _accuracy_counts(vs.run(clf), 5)
    assert output == host
    assert np.allclose(output, [7, 4, 6, 4, 4], atol=1)


def test_voxel_selection_two_masks():
    """Region x region golden accuracies
    (tests/fcma/test_voxel_selection.py:95-130)."""
    prng = RandomState(1234567890)
    fake_raw_data1 = [create_epoch(prng) for _ in range(8)]
    fake_raw_data2 = [create_epoch(prng) for _ in range(8)]
    labels = [0, 1, 0, 1, 0, 1, 0, 1]
    vs = VoxelSelector(labels, 4, 2, fake_raw_data1,
                       raw_data2=fake_raw_data2, voxel_unit=1)
    clf = svm.SVC(kernel='precomputed', shrinking=False, C=1, gamma='auto')
    output = _accuracy_counts(vs.run(clf), 5)
    assert np.allclose(output, [3, 3, 7, 5, 7], atol=1)

    output = _accuracy_counts(vs.run(LogisticRegression()), 5)
    assert np.allclose(output, [4, 3, 7, 4, 6], atol=1)

    output = _accuracy_counts(vs.run('svm'), 5)
    assert np.allclose(output, [3, 3, 7, 5, 7], atol=1)


def test_voxel_selection_block_sizes_agree():
    """Different voxel_unit values give identical results (the block
    decomposition is an implementation detail)."""
    prng = RandomState(1234567890)
    fake_raw_data = [create_epoch(prng, col=11) for _ in range(8)]
    labels = [0, 1, 0, 1, 0, 1, 0, 1]
    rs = []
    for unit in (3, 11, 64):
        vs = VoxelSelector(labels, 4, 2, fake_raw_data, voxel_unit=unit)
        rs.append(sorted(vs.run('svm')))
    for vid in range(11):
        assert np.isclose(rs[0][vid][1], rs[1][vid][1], atol=1e-5)
        assert np.isclose(rs[0][vid][1], rs[2][vid][1], atol=1e-5)


def test_voxel_selection_mesh():
    """Sharding blocks over the CPU mesh voxel axis reproduces the
    single-device result."""
    from brainiak_tpu.parallel import make_mesh

    prng = RandomState(1234567890)
    fake_raw_data = [create_epoch(prng, col=16) for _ in range(8)]
    labels = [0, 1, 0, 1, 0, 1, 0, 1]
    single = sorted(VoxelSelector(labels, 4, 2, fake_raw_data,
                                  voxel_unit=4).run('svm'))
    mesh = make_mesh(("subject", "voxel"), (1, 8))
    dist = sorted(VoxelSelector(labels, 4, 2, fake_raw_data, voxel_unit=2,
                                mesh=mesh).run('svm'))
    for (v0, a0), (v1, a1) in zip(single, dist):
        assert v0 == v1
        assert np.isclose(a0, a1, atol=1e-5)


def test_voxel_selection_errors():
    import pytest

    prng = RandomState(0)
    data = [create_epoch(prng) for _ in range(4)]
    with pytest.raises(ValueError):
        VoxelSelector([0, 1, 0, 1], 2, 2, data,
                      raw_data2=data[:-1])
    with pytest.raises(ValueError):
        VoxelSelector([0, 1, 0, 1], 2, 2,
                      [d[:, :0] for d in data])


def test_voxel_selection_pallas_path_matches_xla():
    """The fused Pallas kernel path (interpreter mode on CPU) gives the
    same rankings as the XLA path."""
    prng = RandomState(1234567890)
    fake_raw_data = [create_epoch(prng, col=12) for _ in range(8)]
    labels = [0, 1, 0, 1, 0, 1, 0, 1]
    xla = sorted(VoxelSelector(labels, 4, 2, fake_raw_data, voxel_unit=6,
                               use_pallas=False).run('svm'))
    pallas = sorted(VoxelSelector(labels, 4, 2, fake_raw_data, voxel_unit=6,
                                  use_pallas=True).run('svm'))
    for (v0, a0), (v1, a1) in zip(xla, pallas):
        assert v0 == v1
        assert np.isclose(a0, a1, atol=1e-4)


def test_voxel_selection_pallas_with_mesh():
    """mesh + use_pallas compose: the Gram kernel runs per shard under
    shard_map (GSPMD cannot partition a pallas_call) and matches the
    unsharded XLA path."""
    from brainiak_tpu.parallel import make_mesh

    prng = RandomState(1234567890)
    fake_raw_data = [create_epoch(prng, col=16) for _ in range(8)]
    labels = [0, 1, 0, 1, 0, 1, 0, 1]
    xla = sorted(VoxelSelector(labels, 4, 2, fake_raw_data, voxel_unit=16,
                               use_pallas=False).run('svm'))
    mesh = make_mesh(("voxel",), (8,))
    sharded = sorted(VoxelSelector(labels, 4, 2, fake_raw_data,
                                   voxel_unit=2, mesh=mesh,
                                   use_pallas=True).run('svm'))
    for (v0, a0), (v1, a1) in zip(xla, sharded):
        assert v0 == v1
        assert np.isclose(a0, a1, atol=1e-4)


def test_voxel_selection_pallas_host_cv_path():
    """use_pallas=True with an sklearn classifier takes the fused
    corr+normalize kernel into the host-CV pipeline; results equal the
    XLA host-CV path."""
    prng = RandomState(1234567890)
    fake_raw_data = [create_epoch(prng, col=12) for _ in range(8)]
    labels = [0, 1, 0, 1, 0, 1, 0, 1]
    clf = svm.SVC(kernel='precomputed', shrinking=False, C=1,
                  gamma='auto')
    xla = sorted(VoxelSelector(labels, 4, 2, fake_raw_data, voxel_unit=6,
                               use_pallas=False).run(clf))
    pallas = sorted(VoxelSelector(labels, 4, 2, fake_raw_data,
                                  voxel_unit=6, use_pallas=True).run(clf))
    for (v0, a0), (v1, a1) in zip(xla, pallas):
        assert v0 == v1
        assert np.isclose(a0, a1, atol=1e-4)


def test_pallas_block_helpers_vmem_fallback():
    """When the epoch x TR extent exceeds the VMEM tile budget the
    Pallas block helpers must fall back to the XLA path rather than
    fail (the whole-brain long-T regime)."""
    import jax.numpy as jnp

    from brainiak_tpu.fcma.voxelselector import (
        _block_gram_pallas,
        _block_gram_xla,
        _block_kernel_matrices,
        _block_kernel_matrices_pallas,
    )
    from brainiak_tpu.ops.pallas_kernels import pick_tiles

    E, T, B, V = 64, 4096, 8, 128
    assert not pick_tiles(E, T, B, V)[2]
    rng = RandomState(5)
    data = jnp.asarray(rng.randn(E, T, V).astype(np.float32) / T)
    blk = data[:, :, :B]

    g_pal = np.asarray(_block_gram_pallas(blk, data, 4))
    g_xla = np.asarray(_block_gram_xla(blk, data, 4))
    np.testing.assert_allclose(g_pal, g_xla, atol=1e-5)

    (k_pal, c_pal) = _block_kernel_matrices_pallas(blk, data, 4)
    (k_xla, c_xla) = _block_kernel_matrices(blk, data, 4)
    np.testing.assert_allclose(np.asarray(k_pal), np.asarray(k_xla),
                               atol=1e-5)
    np.testing.assert_allclose(np.asarray(c_pal), np.asarray(c_xla),
                               atol=1e-5)


def test_voxel_selection_kkt_gap_warning(caplog):
    """A starved SMO budget must warn loudly instead of silently
    degrading accuracies (voxelselector KKT-gap guard)."""
    import logging

    prng = RandomState(1234567890)
    fake_raw_data = [create_epoch(prng, col=8) for _ in range(8)]
    labels = [0, 1, 0, 1, 0, 1, 0, 1]
    vs = VoxelSelector(labels, 4, 2, fake_raw_data, voxel_unit=8,
                       svm_iters=0)
    with caplog.at_level(logging.WARNING,
                         logger="brainiak_tpu.fcma.voxelselector"):
        vs.run('svm')
    assert any("KKT" in r.message for r in caplog.records)


def test_voxel_selection_multiclass_on_device():
    """Three-condition voxel selection: the on-device one-vs-one SVM
    matches sklearn SVC's multiclass CV within the reference tolerance."""
    prng = RandomState(7)
    n_e = 12  # 2 subjects x 6 epochs, 3 conditions
    fake_raw_data = [create_epoch(prng, col=6) for _ in range(n_e)]
    labels = [0, 1, 2, 0, 1, 2, 0, 1, 2, 0, 1, 2]
    vs = VoxelSelector(labels, 6, 3, fake_raw_data, voxel_unit=3)
    clf = svm.SVC(kernel='precomputed', shrinking=False, C=1)
    skl = sorted(vs.run(clf))
    dev = sorted(vs.run('svm'))
    for (v0, a0), (v1, a1) in zip(skl, dev):
        assert v0 == v1
        assert abs(a0 - a1) * n_e <= 2  # within 2 epochs of SVC


def test_voxel_selection_precision_knob():
    """The matmul-precision knob ('high' = the TPU throughput lever) is
    accepted and is numerically identical on CPU (where XLA always runs
    fp32); bad values raise with the valid options named."""
    import pytest
    from brainiak_tpu.ops.correlation import resolve_precision

    prng = RandomState(1234567890)
    fake_raw_data = [create_epoch(prng) for _ in range(8)]
    labels = [0, 1, 0, 1, 0, 1, 0, 1]
    base = VoxelSelector(labels, 4, 2, fake_raw_data, voxel_unit=1)
    fast = VoxelSelector(labels, 4, 2, fake_raw_data, voxel_unit=1,
                         precision='high')
    base_counts = _accuracy_counts(base.run('svm'), 5)
    fast_counts = _accuracy_counts(fast.run('svm'), 5)
    import jax
    if jax.default_backend() != 'tpu':
        assert base_counts == fast_counts
    else:  # on TPU the precisions genuinely differ; band only
        assert np.allclose(base_counts, fast_counts, atol=1)
    with pytest.raises(ValueError, match="highest"):
        resolve_precision('hihgest')
