"""jaxlint-IR tier (JP301-JP305): per-rule fixtures over REAL traces.

Each rule gets a positive seed (the acceptance fixtures from ISSUE
17: an f32 builder with a hidden ``np.float64`` constant, a
donated-but-unaliased serve-style batch program, a ``psum`` over a
missing axis) and a negative twin, traced with the same
:func:`~brainiak_tpu.analysis.ir.trace.trace_spec` machinery the
audit child runs — plus end-to-end :func:`run_audit` coverage-report
and suppression tests over a throwaway fixture tree.
"""

import functools
import textwrap

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from brainiak_tpu.analysis.baseline import Baseline  # noqa: E402
from brainiak_tpu.analysis.ir import (  # noqa: E402
    DEFAULT_SELECT, IR_RULES, enumerate_static_sites, run_audit)
from brainiak_tpu.analysis.ir.rules import (  # noqa: E402
    CollectiveAxisMismatch, DegenerateDonation, DtypePromotionLeak,
    HostCallbackInProgram, RetraceSurface)
from brainiak_tpu.analysis.ir.trace import SiteTrace, trace_spec  # noqa: E402


def _record(site, fn, float_keys_ok=()):
    """A registry-shaped record without touching the global
    registry (trace_spec only reads these keys)."""
    return {"site": site,
            "wrapper": functools.lru_cache(maxsize=None)(fn),
            "fn": fn,
            "float_keys_ok": tuple(float_keys_ok)}


def _aval(*shape, dtype=jnp.float32):
    return jax.ShapeDtypeStruct(shape, dtype)


@pytest.fixture
def x64():
    """The audit's 64-bit tracing mode (restored afterwards)."""
    before = jax.config.jax_enable_x64
    jax.config.update("jax_enable_x64", True)
    yield
    jax.config.update("jax_enable_x64", before)


# -- JP301: dtype-promotion leak --------------------------------------


def test_jp301_flags_hidden_float64_constant(x64):
    """Acceptance seed: an f32 builder multiplying by a strongly
    typed np.float64 scalar promotes the whole chain under x64."""

    def build(n):
        hidden = np.float64(1.5)

        @jax.jit
        def prog(x):
            return x * hidden + jnp.sum(x)

        return prog

    trace = trace_spec(_record("irtest.leaky", build),
                       {"key": (4,), "args": (_aval(4),)})
    assert trace.jaxpr is not None
    assert trace.input_dtypes == ("float32",)
    assert trace.wide_eqns, "float64 must be visible in the IR"
    msgs = list(DtypePromotionLeak().check(trace))
    assert len(msgs) == 1
    assert "float64" in msgs[0] and "float32" in msgs[0]


def test_jp301_clean_on_weak_python_float(x64):
    """A Python float is weakly typed: the same program stays f32
    and must NOT be flagged."""

    def build(n):
        @jax.jit
        def prog(x):
            return x * 1.5 + jnp.sum(x)

        return prog

    trace = trace_spec(_record("irtest.weak", build),
                       {"key": (4,), "args": (_aval(4),)})
    assert trace.jaxpr is not None
    assert trace.wide_eqns == ()
    assert list(DtypePromotionLeak().check(trace)) == []


def test_jp301_silent_on_legitimate_f64_program(x64):
    """A program traced AT float64 inputs is legitimately 64-bit."""

    def build(n):
        @jax.jit
        def prog(x):
            return x * np.float64(1.5)

        return prog

    trace = trace_spec(
        _record("irtest.f64", build),
        {"key": (4,), "args": (_aval(4, dtype=jnp.float64),)})
    assert trace.jaxpr is not None
    assert list(DtypePromotionLeak().check(trace)) == []


# -- JP302: degenerate donation ---------------------------------------


def test_jp302_declared_but_unaliased():
    """Acceptance seed: a donated batch program none of whose
    outputs can reuse the donated buffer (shape mismatch) — XLA
    strips the donation, the executable aliases nothing."""

    def build(n):
        @functools.partial(jax.jit, donate_argnums=(0,))
        def prog(x):
            return jnp.sum(x)  # scalar out: (8,) donation unusable

        return prog

    trace = trace_spec(_record("irtest.donated", build),
                       {"key": (8,), "args": (_aval(8),)})
    assert trace.jaxpr is not None
    assert trace.donated_declared is True
    assert trace.aliased is False, \
        "XLA must have dropped the unusable donation"
    msgs = list(DegenerateDonation().check(trace))
    assert len(msgs) == 1
    assert "aliasing table is empty" in msgs[0]


def test_jp302_usable_donation_stays_clean():
    """The same declaration with a shape-matched output DOES alias
    (even on CPU) and must not be flagged — the rule keys off the
    executable's aliasing table, not the declaration."""

    def build(n):
        @functools.partial(jax.jit, donate_argnums=(0,))
        def prog(x):
            return x + 1.0

        return prog

    trace = trace_spec(_record("irtest.aliased", build),
                       {"key": (8,), "args": (_aval(8),)})
    assert trace.donated_declared is True
    assert trace.aliased is True
    assert list(DegenerateDonation().check(trace)) == []


def test_jp302_expected_but_not_declared():
    """A family that expects donation (spec['donate']) but builds a
    donation-free program is the other degenerate half."""

    def build(n):
        @jax.jit
        def prog(x):
            return x + 1.0

        return prog

    trace = trace_spec(
        _record("irtest.nodonate", build),
        {"key": (8,), "args": (_aval(8),), "donate": (0,)})
    assert trace.donated_declared is False
    assert trace.donate_expected == (0,)
    msgs = list(DegenerateDonation().check(trace))
    assert len(msgs) == 1
    assert "argnums 0" in msgs[0]
    assert "declares no donation" in msgs[0]


def test_jp302_clean_without_donation_anywhere():
    def build(n):
        @jax.jit
        def prog(x):
            return x + 1.0

        return prog

    trace = trace_spec(_record("irtest.plain", build),
                       {"key": (8,), "args": (_aval(8),)})
    assert trace.aliased is None  # donation not at stake: no compile
    assert list(DegenerateDonation().check(trace)) == []


def test_jp302_clean_when_aliasing_survives():
    """Synthetic: declared AND aliased (the TPU outcome) is the
    healthy state."""
    trace = SiteTrace(site="s", label="", key=(), spec={},
                      jaxpr=object(), donated_declared=True,
                      aliased=True)
    assert list(DegenerateDonation().check(trace)) == []


# -- JP303: host callback in a hot program ----------------------------


def test_jp303_flags_debug_callback():
    def build(n):
        @jax.jit
        def prog(x):
            jax.debug.callback(lambda v: None, x)
            return x * 2.0

        return prog

    trace = trace_spec(_record("irtest.cb", build),
                       {"key": (4,), "args": (_aval(4),)})
    assert trace.jaxpr is not None
    assert trace.callback_prims
    msgs = list(HostCallbackInProgram().check(trace))
    assert len(msgs) == 1
    assert "host round-trip" in msgs[0]


def test_jp303_clean_without_callbacks():
    def build(n):
        @jax.jit
        def prog(x):
            return x * 2.0

        return prog

    trace = trace_spec(_record("irtest.nocb", build),
                       {"key": (4,), "args": (_aval(4),)})
    assert trace.callback_prims == ()
    assert list(HostCallbackInProgram().check(trace)) == []


# -- JP304: collective-axis validation --------------------------------


def test_jp304_flags_psum_over_missing_axis():
    """Acceptance seed: a psum over an axis no enclosing mesh binds
    fails the trace with the unbound-axis signal — which IS the
    finding, and still counts as audited coverage."""

    def build(n):
        @jax.jit
        def prog(x):
            return jax.lax.psum(x, "missing")

        return prog

    trace = trace_spec(_record("irtest.axis", build),
                       {"key": (4,), "args": (_aval(4),)})
    assert trace.jaxpr is None
    assert trace.axis_error, trace.error
    assert trace.traced  # an axis error is auditable IR evidence
    msgs = list(CollectiveAxisMismatch().check(trace))
    assert len(msgs) == 1
    assert "collective axis" in msgs[0]


def test_jp304_clean_psum_over_real_mesh_axis():
    """The same collective under a shard_map over a real mesh axis
    resolves and passes."""
    from jax.sharding import Mesh, PartitionSpec
    from jax.experimental.shard_map import shard_map

    devs = np.array(jax.devices()[:1])
    mesh = Mesh(devs, ("voxel",))

    def build(n):
        @jax.jit
        @functools.partial(
            shard_map, mesh=mesh,
            in_specs=PartitionSpec("voxel"),
            out_specs=PartitionSpec())
        def prog(x):
            return jax.lax.psum(jnp.sum(x), "voxel")

        return prog

    trace = trace_spec(_record("irtest.goodaxis", build),
                       {"key": (4,), "args": (_aval(4),),
                        "mesh": mesh})
    assert trace.jaxpr is not None, trace.error
    assert ("psum", ("voxel",)) in trace.collectives \
        or any(p.startswith("psum") for p, _ in trace.collectives)
    assert trace.mesh_axes == ("voxel",)
    assert list(CollectiveAxisMismatch().check(trace)) == []


def test_jp304_mesh_mismatch_and_missing_mesh_branches():
    """Synthetic branch coverage: a collective over an axis the
    trace mesh doesn't bind, and a spec that provides no mesh at
    all for a collective program."""
    mismatch = SiteTrace(site="s", label="", key=(), spec={},
                         jaxpr=object(),
                         collectives=(("psum", ("voxel",)),),
                         mesh_axes=("subject",))
    msgs = list(CollectiveAxisMismatch().check(mismatch))
    assert len(msgs) == 1 and "not an axis" in msgs[0]

    meshless = SiteTrace(site="s", label="", key=(), spec={},
                         jaxpr=object(),
                         collectives=(("psum", ("voxel",)),),
                         mesh_axes=())
    msgs = list(CollectiveAxisMismatch().check(meshless))
    assert len(msgs) == 1 and "no trace mesh" in msgs[0]


# -- JP305: retrace surface -------------------------------------------


def test_jp305_flags_float_cache_key():
    def build(gamma, n):
        @jax.jit
        def prog(x):
            return x * gamma

        return prog

    trace = trace_spec(_record("irtest.floatkey", build),
                       {"key": (0.5, 4), "args": (_aval(4),)})
    assert trace.float_keys == ("gamma",)
    msgs = list(RetraceSurface().check(trace))
    assert len(msgs) == 1
    assert "'gamma'" in msgs[0] and "float" in msgs[0]


def test_jp305_float_keys_ok_declares_intent():
    """A site that declared the float a fixed per-model constant
    (float_keys_ok at registration) is NOT flagged."""

    def build(gamma, n):
        @jax.jit
        def prog(x):
            return x * gamma

        return prog

    trace = trace_spec(
        _record("irtest.okkey", build, float_keys_ok=("gamma",)),
        {"key": (0.5, 4), "args": (_aval(4),)})
    assert trace.float_keys == ()
    assert list(RetraceSurface().check(trace)) == []


def test_jp305_flags_array_cache_key():
    def build(weights, n):
        @jax.jit
        def prog(x):
            return x + 1.0

        return prog

    trace = trace_spec(
        _record("irtest.arrkey", build),
        {"key": (np.ones(3), 4), "args": (_aval(4),)})
    assert trace.array_keys == ("weights",)
    msgs = list(RetraceSurface().check(trace))
    assert len(msgs) == 1
    assert "'weights'" in msgs[0]


# -- end-to-end audit over a fixture tree -----------------------------

_FIXTURE_MOD = textwrap.dedent('''\
    """IR-audit fixture: one leaky, one pragma'd, one signature-less
    builder."""
    import numpy as np
    import jax
    import jax.numpy as jnp
    from brainiak_tpu.obs import runtime as obs_runtime


    @obs_runtime.counted_cache("{tag}.leaky")
    def _leaky(n):
        hidden = np.float64(1.5)

        @jax.jit
        def prog(x):
            return x * hidden

        return prog


    @obs_runtime.trace_signature("{tag}.leaky")
    def _leaky_sig():
        return [{{"key": (4,),
                 "args": (jax.ShapeDtypeStruct((4,), jnp.float32),)}}]


    @obs_runtime.counted_cache("{tag}.hushed")  # jaxlint: disable=JP301
    def _hushed(n):
        hidden = np.float64(1.5)

        @jax.jit
        def prog(x):
            return x * hidden

        return prog


    @obs_runtime.trace_signature("{tag}.hushed")
    def _hushed_sig():
        return [{{"key": (4,),
                 "args": (jax.ShapeDtypeStruct((4,), jnp.float32),)}}]


    @obs_runtime.counted_cache("{tag}.nosig")
    def _nosig(n):
        @jax.jit
        def prog(x):
            return x + 1

        return prog
''')


def _write_fixture(tmp_path, monkeypatch, name, tag):
    (tmp_path / f"{name}.py").write_text(
        _FIXTURE_MOD.format(tag=tag))
    monkeypatch.syspath_prepend(str(tmp_path))


def test_run_audit_coverage_report(tmp_path, monkeypatch):
    """The census is mechanical: every static site is traced or
    carries a reason, coverage is the traced fraction, findings
    anchor at the builder's def line, pragmas suppress."""
    _write_fixture(tmp_path, monkeypatch, "ir_fix_cov", "ircov")
    sites = enumerate_static_sites([str(tmp_path)], str(tmp_path))
    assert set(sites) == {"ircov.leaky", "ircov.hushed",
                          "ircov.nosig"}
    report = run_audit([str(tmp_path)], str(tmp_path))
    assert sorted(report.traced) == ["ircov.hushed", "ircov.leaky"]
    assert report.skipped == {
        "ircov.nosig": "no canonical signature registered "
                       "(trace_signature missing)"}
    assert report.coverage == pytest.approx(2 / 3)
    # the leaky builder is flagged at its def line; the pragma'd
    # twin (same IR) is suppressed
    assert [f.code for f in report.findings] == ["JP301"]
    finding = report.findings[0]
    assert finding.path == "ir_fix_cov.py"
    assert finding.snippet.startswith("def _leaky(")
    payload = report.to_dict()
    assert payload["sites"] == 3
    assert payload["coverage"] == pytest.approx(0.6667, abs=1e-3)
    assert payload["skipped"][0]["site"] == "ircov.nosig"
    assert payload["rules"] == list(DEFAULT_SELECT)


def test_run_audit_restores_x64(tmp_path, monkeypatch):
    _write_fixture(tmp_path, monkeypatch, "ir_fix_x64", "irx64")
    before = jax.config.jax_enable_x64
    run_audit([str(tmp_path)], str(tmp_path))
    assert jax.config.jax_enable_x64 == before


def test_run_audit_reports_import_failure(tmp_path, monkeypatch):
    """A census module that fails to import is skipped WITH the
    import error as its reason — never silently dropped."""
    (tmp_path / "ir_fix_broken.py").write_text(
        "from brainiak_tpu.obs import runtime as obs_runtime\n"
        "raise RuntimeError('deliberately broken')\n"
        "\n"
        "@obs_runtime.counted_cache('irbroken.site')\n"
        "def _b(n):\n"
        "    return None\n")
    monkeypatch.syspath_prepend(str(tmp_path))
    report = run_audit([str(tmp_path)], str(tmp_path))
    assert report.traced == []
    assert "irbroken.site" in report.skipped
    assert "deliberately broken" in report.skipped["irbroken.site"]
    assert report.coverage == 0.0


def test_run_audit_select_and_baseline_scoping(tmp_path, monkeypatch):
    """--select narrows the rule set; baseline entries suppress with
    justification and staleness is judged ONLY for selected JP
    rules (the shared baseline's JX entries are out of scope)."""
    _write_fixture(tmp_path, monkeypatch, "ir_fix_bl", "irbl")
    report = run_audit([str(tmp_path)], str(tmp_path),
                       select=("JP302",))
    assert report.findings == []  # the leak is a JP301 story

    bl = Baseline([
        {"rule": "JP301", "path": "ir_fix_bl.py",
         "snippet": "def _leaky(n):",
         "reason": "fixture: grandfathered"},
        {"rule": "JP301", "path": "gone.py",
         "snippet": "def vanished():", "reason": "stale one"},
        {"rule": "JX001", "path": "other.py",
         "snippet": "x = jax.jit(f)", "reason": "not ours"},
    ])
    report = run_audit([str(tmp_path)], str(tmp_path), baseline=bl)
    assert report.findings == []
    assert [e["path"] for e in report.stale] == ["gone.py"]


def test_ir_rules_registered_and_jax_free():
    """The rule layer imports without jax (gate hosts) and every
    JP3xx code is selectable from the CLI's --list surface."""
    import importlib
    import sys

    assert tuple(r.code for r in IR_RULES) == DEFAULT_SELECT == (
        "JP301", "JP302", "JP303", "JP304", "JP305")
    mod = importlib.import_module("brainiak_tpu.analysis.ir.rules")
    src = open(mod.__file__).read()
    assert "import jax" not in src
    assert "brainiak_tpu.analysis.ir.rules" in sys.modules
