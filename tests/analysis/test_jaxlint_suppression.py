"""jaxlint suppression mechanics: line pragma, baseline, config, CLI."""

import json
import textwrap

import pytest

from brainiak_tpu.analysis import cli
from brainiak_tpu.analysis.baseline import Baseline, BaselineError
from brainiak_tpu.analysis.config import load_config
from brainiak_tpu.analysis.core import analyze_file
from brainiak_tpu.analysis.rules import JAXLINT_RULES, JitPerCall

BAD = """
import jax
def make(fn):
    return jax.jit(fn)
"""


def _write(tmp_path, src, name="mod.py"):
    path = tmp_path / name
    path.write_text(textwrap.dedent(src))
    return path


def _lint(tmp_path, src, rules=(JitPerCall,), name="mod.py"):
    path = _write(tmp_path, src, name)
    return analyze_file(str(path), str(tmp_path),
                        [r() for r in rules])


# -- line pragma -----------------------------------------------------

def test_pragma_suppresses_matching_code(tmp_path):
    findings = _lint(tmp_path, """
        import jax
        def make(fn):
            return jax.jit(fn)  # jaxlint: disable=JX001
        """)
    assert findings == []


def test_pragma_with_code_list_and_all(tmp_path):
    for tag in ("JX005,JX001", "all"):
        findings = _lint(tmp_path, f"""
            import jax
            def make(fn):
                return jax.jit(fn)  # jaxlint: disable={tag}
            """)
        assert findings == []


def test_pragma_other_code_does_not_suppress(tmp_path):
    findings = _lint(tmp_path, """
        import jax
        def make(fn):
            return jax.jit(fn)  # jaxlint: disable=JX002
        """)
    assert [f.code for f in findings] == ["JX001"]


def test_blanket_noqa_does_not_suppress_jaxlint(tmp_path):
    """A bare ``# noqa`` must NOT silence TPU-correctness rules —
    grandfathered findings go to the baseline with a justification."""
    findings = _lint(tmp_path, """
        import jax
        def make(fn):
            return jax.jit(fn)  # noqa
        """)
    assert [f.code for f in findings] == ["JX001"]


def test_syntax_error_reported_as_chk001(tmp_path):
    findings = _lint(tmp_path, "def broken(:\n    pass\n")
    assert [f.code for f in findings] == ["CHK001"]


# -- pragma placement on multi-line statements and decorated defs ----
#
# Previously unspecified (ISSUE 10 satellite); the spec is:
# * a multi-line SIMPLE statement is one logical line — a pragma on
#   any of its physical lines suppresses a finding anchored to any
#   other (flake8 noqa semantics);
# * a function/class header (decorators + def line) is one unit —
#   a pragma on the decorator line suppresses a def-line finding
#   and vice versa;
# * a pragma on an unrelated BODY line does not leak upward.

def test_pragma_on_last_line_of_multiline_statement(tmp_path):
    findings = _lint(tmp_path, """
        import jax
        def make(fn):
            return jax.jit(
                fn)  # jaxlint: disable=JX001
        """)
    assert findings == []


def test_pragma_on_first_line_of_multiline_statement(tmp_path):
    findings = _lint(tmp_path, """
        import jax
        def make(fn):  # noqa will not work here
            out = jax.jit(  # jaxlint: disable=JX001
                fn)
            return out
        """)
    assert findings == []


def _lock_fixture(deco_comment="", def_comment=""):
    return f"""
        import threading


        class Svc:
            def __init__(self):
                self._lock = threading.Lock()

            @property{deco_comment}
            def thing(self):  # requires-lock: _nope{def_comment}
                return 1
        """


def test_pragma_on_decorator_line_suppresses_def_finding(tmp_path):
    from brainiak_tpu.analysis.lockrules import (
        UnknownLockAnnotation)
    from brainiak_tpu.analysis.core import analyze_paths
    path = _write(tmp_path, _lock_fixture(
        deco_comment="  # jaxlint: disable=JX205"))
    findings, _, _ = analyze_paths(
        [str(path)], str(tmp_path), [UnknownLockAnnotation])
    assert findings == []


def test_pragma_on_def_line_suppresses_decorator_finding(tmp_path):
    from brainiak_tpu.analysis.lockrules import (
        UnknownLockAnnotation)
    from brainiak_tpu.analysis.core import analyze_paths
    path = _write(tmp_path, _lock_fixture(
        def_comment="  # jaxlint: disable=JX205"))
    findings, _, _ = analyze_paths(
        [str(path)], str(tmp_path), [UnknownLockAnnotation])
    assert findings == []


def test_pragma_on_body_line_does_not_leak_to_header(tmp_path):
    from brainiak_tpu.analysis.lockrules import (
        UnknownLockAnnotation)
    from brainiak_tpu.analysis.core import analyze_paths
    src = _lock_fixture().replace(
        "return 1", "return 1  # jaxlint: disable=JX205")
    path = _write(tmp_path, src)
    findings, _, _ = analyze_paths(
        [str(path)], str(tmp_path), [UnknownLockAnnotation])
    assert [f.code for f in findings] == ["JX205"]


# -- baseline --------------------------------------------------------

def test_baseline_filters_matching_finding(tmp_path):
    findings = _lint(tmp_path, BAD)
    assert len(findings) == 1
    baseline = Baseline([{
        "rule": "JX001", "path": findings[0].path,
        "snippet": findings[0].snippet,
        "reason": "builder API: caller caches the result"}])
    kept, stale = baseline.filter(findings)
    assert kept == [] and stale == []


def test_baseline_reports_stale_entries(tmp_path):
    baseline = Baseline([{
        "rule": "JX001", "path": "gone.py",
        "snippet": "jax.jit(fn)", "reason": "was fixed"}])
    kept, stale = baseline.filter(_lint(tmp_path, BAD))
    assert len(kept) == 1
    assert [e["path"] for e in stale] == ["gone.py"]


def test_baseline_requires_written_justification():
    with pytest.raises(BaselineError, match="reason"):
        Baseline([{"rule": "JX001", "path": "a.py",
                   "snippet": "jax.jit(fn)", "reason": "  "}])


def test_baseline_load_rejects_bad_json(tmp_path):
    path = tmp_path / "baseline.json"
    path.write_text("not json")
    with pytest.raises(BaselineError, match="JSON"):
        Baseline.load(str(path))


def test_baseline_load_missing_file_is_empty(tmp_path):
    baseline = Baseline.load(str(tmp_path / "absent.json"))
    assert baseline.entries == []


def test_baseline_sections_flatten_and_require_reasons(tmp_path):
    """Entries may be grouped under named sections (the tools/bench
    walk keeps its justifications in its own section); sections are
    organizational only and flatten into one suppression set."""
    path = tmp_path / "bl.json"
    path.write_text(json.dumps({
        "version": 1,
        "entries": [],
        "sections": {"tools-and-bench": [
            {"rule": "JX001", "path": "mod.py",
             "snippet": "jax.jit(fn)",
             "reason": "bench harness builds one program per rep "
                       "on purpose"}]},
    }))
    baseline = Baseline.load(str(path))
    assert len(baseline.entries) == 1
    kept, stale = baseline.filter(_lint(tmp_path, BAD))
    assert len(kept) == 1   # different path: entry is unused
    assert len(stale) == 1
    bad = tmp_path / "bad_bl.json"
    bad.write_text(json.dumps({
        "version": 1,
        "sections": {"x": [{"rule": "JX001", "path": "a.py",
                            "snippet": "s", "reason": " "}]},
    }))
    with pytest.raises(BaselineError, match="reason"):
        Baseline.load(str(bad))


# -- [tool.jaxlint] config -------------------------------------------

def test_config_parses_tool_jaxlint_section(tmp_path):
    pyproject = tmp_path / "pyproject.toml"
    pyproject.write_text(textwrap.dedent("""
        [project]
        name = "x"

        [tool.jaxlint]
        select = [
            "JX001",
            "JX003",
        ]
        include = ["pkg"]
        exclude = ["pkg/vendored"]
        baseline = "tools/jaxlint_baseline.json"

        [tool.other]
        select = ["IGNORED"]
        """))
    config = load_config(str(tmp_path), str(pyproject))
    assert config.select == ("JX001", "JX003")
    assert config.include == ("pkg",)
    assert config.exclude == ("pkg/vendored",)
    assert config.baseline == "tools/jaxlint_baseline.json"
    assert config.baseline_path().endswith(
        "tools/jaxlint_baseline.json")


def test_config_defaults_without_section(tmp_path):
    pyproject = tmp_path / "pyproject.toml"
    pyproject.write_text("[project]\nname = 'x'\n")
    config = load_config(str(tmp_path), str(pyproject))
    assert config.select == tuple(r.code for r in JAXLINT_RULES)
    assert config.include == ("brainiak_tpu",)
    assert config.baseline is None


# -- CLI -------------------------------------------------------------

def _cli_repo(tmp_path, monkeypatch):
    pkg = tmp_path / "pkg"
    pkg.mkdir()
    _write(pkg, BAD, "bad.py")
    (tmp_path / "pyproject.toml").write_text(
        '[tool.jaxlint]\nselect = ["JX001"]\n'
        'include = ["pkg"]\n')
    monkeypatch.chdir(tmp_path)
    return pkg


def test_cli_exit_one_and_json_on_findings(tmp_path, monkeypatch,
                                           capsys):
    _cli_repo(tmp_path, monkeypatch)
    assert cli.main(["--format", "json"]) == 1
    payload = json.loads(capsys.readouterr().out)
    assert payload["ok"] is False
    assert [f["code"] for f in payload["findings"]] == ["JX001"]
    assert payload["findings"][0]["path"] == "pkg/bad.py"


def test_cli_write_then_enforce_baseline(tmp_path, monkeypatch,
                                         capsys):
    _cli_repo(tmp_path, monkeypatch)
    assert cli.main(["--write-baseline", "bl.json"]) == 0
    data = json.loads((tmp_path / "bl.json").read_text())
    assert len(data["entries"]) == 1
    assert cli.main(["--baseline", "bl.json"]) == 0
    out = capsys.readouterr().out
    assert "OK" in out


def test_cli_exit_zero_on_clean_tree(tmp_path, monkeypatch, capsys):
    pkg = _cli_repo(tmp_path, monkeypatch)
    _write(pkg, "import jax\n\n\n@jax.jit\ndef f(x):\n"
                "    return x\n", "bad.py")
    assert cli.main([]) == 0


def test_cli_rejects_unknown_rule(tmp_path, monkeypatch):
    _cli_repo(tmp_path, monkeypatch)
    with pytest.raises(SystemExit, match="JX999"):
        cli.main(["--select", "JX999"])
