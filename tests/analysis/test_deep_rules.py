"""jaxlint v2 project-rule fixtures (ISSUE 10 acceptance).

Each new rule family is proven on a seeded-bug fixture and its
known-good twin: a cross-module host-sync in a hot loop (JX010), a
``psum`` over an undeclared axis name (JX101), and an unguarded
write to a ``guarded-by`` field in a ``ServeService``-shaped class
(JX201), plus the satellite rules around them.
"""

import json
import textwrap

from brainiak_tpu.analysis.core import analyze_paths
from brainiak_tpu.analysis.interproc import (
    CrossFunctionKeyReuse,
    TransitiveHostSync,
    TransitiveJitInLoop,
)
from brainiak_tpu.analysis.lockrules import (
    BlockingCallUnderLock,
    LockOrderInversion,
    RequiresLockViolation,
    UnguardedAttribute,
    UnknownLockAnnotation,
)
from brainiak_tpu.analysis.meshrules import (
    CollectiveOutsideShardMap,
    UndeclaredCollectiveAxis,
    UndeclaredPartitionAxis,
)
from brainiak_tpu.analysis.sarif import to_sarif


def deep_lint(tmp_path, files, rules):
    for name, src in files.items():
        path = tmp_path / name
        path.parent.mkdir(parents=True, exist_ok=True)
        path.write_text(textwrap.dedent(src))
    findings, _stale, _n = analyze_paths(
        [str(tmp_path)], str(tmp_path), rules)
    assert not any(f.code == "CHK001" for f in findings), findings
    return findings


# -- JX010 transitive host sync --------------------------------------

HELPERS = """
    import jax
    import numpy as np


    def fetch_scalar(x):
        return float(np.asarray(x).sum())


    def definite(x):
        return x.block_until_ready()


    def guarded(x, debug=False):
        if debug:
            return x.block_until_ready()
        return x
"""


def test_jx010_cross_module_sync_in_hot_loop(tmp_path):
    """ISSUE 10 acceptance: a helper in ANOTHER module that syncs
    is flagged at its call site inside the hot loop."""
    findings = deep_lint(tmp_path, {
        "pkg/__init__.py": "",
        "pkg/helpers.py": HELPERS,
        "pkg/train.py": """
            from .helpers import definite


            def fit(step, state, n_iter):
                for epoch in range(n_iter):
                    state = step(state)
                    definite(state)
                return state
        """,
    }, [TransitiveHostSync])
    assert [f.code for f in findings] == ["JX010"]
    assert findings[0].path == "pkg/train.py"
    assert "definite" in findings[0].message
    assert "block_until_ready" in findings[0].message


def test_jx010_host_conv_one_level_and_while_loop(tmp_path):
    findings = deep_lint(tmp_path, {
        "pkg/__init__.py": "",
        "pkg/helpers.py": HELPERS,
        "pkg/train.py": """
            from .helpers import fetch_scalar


            def fit(step, state, n_iter):
                while n_iter > 0:
                    state = step(state)
                    fetch_scalar(state)
                    n_iter -= 1
                return state
        """,
    }, [TransitiveHostSync])
    assert [f.code for f in findings] == ["JX010"]
    assert "while-loop" in findings[0].message


def test_jx010_silent_on_conditional_sync_and_cold_code(tmp_path):
    """Must-execute analysis: a sync behind a debug flag does not
    taint the helper, and calls outside hot loops never fire."""
    findings = deep_lint(tmp_path, {
        "pkg/__init__.py": "",
        "pkg/helpers.py": HELPERS,
        "pkg/train.py": """
            from .helpers import definite, guarded


            def fit(step, state, n_iter):
                for epoch in range(n_iter):
                    state = guarded(step(state))
                return definite(state)
        """,
    }, [TransitiveHostSync])
    assert findings == []


def test_jx010_silent_in_jax_free_module(tmp_path):
    """np.asarray in a module that never imports jax is host
    bookkeeping, not a device sync."""
    findings = deep_lint(tmp_path, {
        "pkg/__init__.py": "",
        "pkg/hostmath.py": """
            import numpy as np


            def norm(x):
                return float(np.asarray(x).sum())
        """,
        "pkg/train.py": """
            from .hostmath import norm


            def fit(step, state, n_iter):
                for epoch in range(n_iter):
                    state = step(state)
                    norm([1.0])
                return state
        """,
    }, [TransitiveHostSync])
    assert findings == []


# -- JX011 transitive jit-in-loop ------------------------------------

def test_jx011_loop_call_to_jit_builder(tmp_path):
    findings = deep_lint(tmp_path, {
        "pkg/__init__.py": "",
        "pkg/builders.py": """
            import jax


            def build(fn):
                return jax.jit(fn)
        """,
        "pkg/drive.py": """
            from .builders import build


            def run(fns, x):
                out = []
                for fn in fns:
                    out.append(build(fn)(x))
                return out
        """,
    }, [TransitiveJitInLoop])
    assert [f.code for f in findings] == ["JX011"]
    assert findings[0].path == "pkg/drive.py"
    assert "build" in findings[0].message


def test_jx011_silent_on_cached_builder_and_loopless(tmp_path):
    findings = deep_lint(tmp_path, {
        "pkg/__init__.py": "",
        "pkg/builders.py": """
            import functools

            import jax


            @functools.lru_cache(maxsize=None)
            def cached(n):
                return jax.jit(lambda a: a + n)


            def build(fn):
                return jax.jit(fn)
        """,
        "pkg/drive.py": """
            from .builders import build, cached


            def run(fns, x):
                prog = build(lambda a: a)
                return [cached(i)(x) for i in range(3)]
        """,
    }, [TransitiveJitInLoop])
    assert findings == []


def test_jx011_silent_on_program_cache_builder(tmp_path):
    """ISSUE 17 regression: program_cache now lives in
    serve.batching; a builder decorated under the new spellings is a
    cached factory, so a loop calling it must stay clean while the
    uncached twin in the same project still fires."""
    findings = deep_lint(tmp_path, {
        "pkg/__init__.py": "",
        "pkg/builders.py": """
            import jax

            from brainiak_tpu.serve import batching
            from brainiak_tpu.serve.batching import program_cache


            @batching.program_cache("fixture.attr")
            def attr_cached(n, b):
                return jax.jit(lambda a: a + n)


            @program_cache("fixture.bare")
            def bare_cached(n, b):
                return jax.jit(lambda a: a * n)


            def uncached(n, b):
                return jax.jit(lambda a: a - n)
        """,
        "pkg/drive.py": """
            from .builders import attr_cached, bare_cached, uncached


            def run(xs):
                out = []
                for x in xs:
                    out.append(attr_cached(2, 8)(x))
                    out.append(bare_cached(3, 8)(x))
                    out.append(uncached(4, 8)(x))
                return out
        """,
    }, [TransitiveJitInLoop])
    assert [f.code for f in findings] == ["JX011"], \
        [f.message for f in findings]
    assert "uncached" in findings[0].message



# -- JX012 cross-function key reuse ----------------------------------

def test_jx012_key_reuse_through_helper(tmp_path):
    findings = deep_lint(tmp_path, {
        "mod.py": """
            import jax


            def sample(key, shape):
                return jax.random.normal(key, shape)


            def model(key):
                a = sample(key, (3,))
                b = jax.random.uniform(key, (3,))
                return a + b
        """,
    }, [CrossFunctionKeyReuse])
    assert [f.code for f in findings] == ["JX012"]
    assert "sample" in findings[0].message


def test_jx012_silent_after_split(tmp_path):
    findings = deep_lint(tmp_path, {
        "mod.py": """
            import jax


            def sample(key, shape):
                return jax.random.normal(key, shape)


            def model(key):
                k1, k2 = jax.random.split(key)
                a = sample(k1, (3,))
                b = jax.random.uniform(k2, (3,))
                return a + b
        """,
    }, [CrossFunctionKeyReuse])
    assert findings == []


# -- JX101/JX102/JX103 mesh + collectives ----------------------------

MESHMOD = """
    import jax
    from jax.sharding import Mesh, PartitionSpec

    from .compat import shard_map

    AXIS = "voxel"


    def build(devs):
        return Mesh(devs, ("voxel",))
"""

COMPAT = """
    try:
        from jax import shard_map
    except ImportError:
        from jax.experimental.shard_map import shard_map
"""


def test_jx101_psum_over_undeclared_axis(tmp_path):
    """ISSUE 10 acceptance: a psum over a misspelled axis name is
    reported with the right rule id."""
    findings = deep_lint(tmp_path, {
        "pkg/__init__.py": "",
        "pkg/compat.py": COMPAT,
        "pkg/meshes.py": MESHMOD,
        "pkg/ops.py": """
            import jax
            from jax.sharding import PartitionSpec

            from .compat import shard_map


            def body(x):
                return jax.lax.psum(x, "voxle")


            def run(x, mesh):
                return shard_map(
                    body, mesh,
                    in_specs=PartitionSpec("voxel"),
                    out_specs=PartitionSpec())(x)
        """,
    }, [UndeclaredCollectiveAxis])
    assert [f.code for f in findings] == ["JX101"]
    assert "'voxle'" in findings[0].message
    assert "voxel" in findings[0].message


def test_jx101_resolves_constants_and_defaults(tmp_path):
    """Axis names resolving through module constants and parameter
    defaults verify clean; unresolvable ones are skipped."""
    findings = deep_lint(tmp_path, {
        "pkg/__init__.py": "",
        "pkg/compat.py": COMPAT,
        "pkg/meshes.py": MESHMOD,
        "pkg/ops.py": """
            import jax

            from .compat import shard_map
            from .meshes import AXIS


            def body(x, axis_name=AXIS):
                opaque = x.aval.named_shape
                jax.lax.ppermute(x, opaque, [(0, 1)])
                return jax.lax.psum(x, axis_name)


            def run(x, mesh):
                return shard_map(body, mesh, in_specs=None,
                                 out_specs=None)(x)
        """,
    }, [UndeclaredCollectiveAxis])
    assert findings == []


def test_jx102_collective_outside_shard_map(tmp_path):
    findings = deep_lint(tmp_path, {
        "pkg/__init__.py": "",
        "pkg/compat.py": COMPAT,
        "pkg/meshes.py": MESHMOD,
        "pkg/loose.py": """
            import jax


            def reduce_all(x):
                return jax.lax.psum(x, "voxel")
        """,
    }, [CollectiveOutsideShardMap])
    assert [f.code for f in findings] == ["JX102"]
    assert findings[0].path == "pkg/loose.py"


def test_jx102_scope_follows_references(tmp_path):
    """A body handed to shard_map, and the nested step function it
    references through lax.scan, are both in scope."""
    findings = deep_lint(tmp_path, {
        "pkg/__init__.py": "",
        "pkg/compat.py": COMPAT,
        "pkg/meshes.py": MESHMOD,
        "pkg/ring.py": """
            import jax

            from .compat import shard_map


            def body(z):
                def step(rotating, _):
                    rotating = jax.lax.ppermute(
                        rotating, "voxel", [(0, 1)])
                    return rotating, rotating
                _, out = jax.lax.scan(step, z, None, length=2)
                return out


            def run(x, mesh):
                return shard_map(body, mesh, in_specs=None,
                                 out_specs=None)(x)
        """,
    }, [CollectiveOutsideShardMap])
    assert findings == []


def test_jx103_partition_spec_axis_no_mesh_declares(tmp_path):
    findings = deep_lint(tmp_path, {
        "pkg/__init__.py": "",
        "pkg/compat.py": COMPAT,
        "pkg/meshes.py": MESHMOD,
        "pkg/place.py": """
            from jax.sharding import PartitionSpec


            GOOD = PartitionSpec(None, "voxel")
            BAD = PartitionSpec("voxl", None)
        """,
    }, [UndeclaredPartitionAxis])
    assert [f.code for f in findings] == ["JX103"]
    assert "'voxl'" in findings[0].message


# -- JX201-JX205 lock discipline -------------------------------------

SERVICE = """
    import collections
    import threading


    class ServeService:
        def __init__(self):
            self._cond = threading.Condition()
            self._engine_lock = threading.Lock()
            self._ingress = collections.deque()  # guarded-by: _cond
            self._pending = {}   # guarded-by: _engine_lock

        def submit(self, seq, ticket):
            with self._cond:
                self._ingress.append((seq, ticket))
            self._pending[seq] = ticket

        def _tick(self):  # requires-lock: _engine_lock
            self._pending.clear()

        def _loop(self):
            with self._engine_lock:
                self._tick()
"""


def test_jx201_unguarded_write_in_serve_shaped_class(tmp_path):
    """ISSUE 10 acceptance: the unguarded ``_pending`` write in a
    ServeService-shaped fixture is reported as JX201; the
    requires-lock helper and the locked ingress write are not."""
    findings = deep_lint(tmp_path, {"service.py": SERVICE},
                         [UnguardedAttribute])
    assert [f.code for f in findings] == ["JX201"]
    assert "_pending" in findings[0].message
    assert "write" in findings[0].message
    assert "ServeService._engine_lock" in findings[0].message


def test_jx201_entry_lockset_propagates_through_callers(tmp_path):
    """A helper only ever called under the lock inherits it — no
    annotation needed (call-site intersection)."""
    findings = deep_lint(tmp_path, {"mod.py": """
        import threading


        class Sink:
            def __init__(self):
                self._lock = threading.Lock()
                self._buf = []   # guarded-by: _lock

            def write(self, rec):
                with self._lock:
                    self._push(rec)

            def _push(self, rec):
                self._buf.append(rec)
    """}, [UnguardedAttribute])
    assert findings == []


def test_jx201_escaped_callback_loses_lockset(tmp_path):
    """A method handed out as a callback can be entered from
    anywhere: its guarded accesses need requires-lock or a with."""
    findings = deep_lint(tmp_path, {"mod.py": """
        import threading


        class Svc:
            def __init__(self, residency):
                self._lock = threading.Lock()
                self._buf = []   # guarded-by: _lock
                residency.on_evict = self._deliver

            def _deliver(self, rec):
                self._buf.append(rec)
    """}, [UnguardedAttribute])
    assert [f.code for f in findings] == ["JX201"]
    assert "_buf" in findings[0].message


def test_jx202_lock_order_inversion(tmp_path):
    findings = deep_lint(tmp_path, {"mod.py": """
        import threading


        class TwoLocks:
            def __init__(self):
                self._a = threading.Lock()
                self._b = threading.Lock()

            def one(self):
                with self._a:
                    with self._b:
                        pass

            def two(self):
                with self._b:
                    with self._a:
                        pass
    """}, [LockOrderInversion])
    assert [f.code for f in findings] == ["JX202"]
    assert "inversion" in findings[0].message


def test_jx202_multi_item_with_counts_as_nesting(tmp_path):
    """`with self._a, self._b:` acquires left-to-right — the same
    order edge as nested with-blocks (review fix: the common
    single-statement spelling was a blind spot)."""
    findings = deep_lint(tmp_path, {"mod.py": """
        import threading


        class TwoLocks:
            def __init__(self):
                self._a = threading.Lock()
                self._b = threading.Lock()

            def one(self):
                with self._a, self._b:
                    pass

            def two(self):
                with self._b:
                    with self._a:
                        pass
    """}, [LockOrderInversion])
    assert [f.code for f in findings] == ["JX202"]


def test_jx202_self_deadlock_on_plain_lock_only(tmp_path):
    """Re-acquiring a Lock is a self-deadlock; an RLock is not."""
    findings = deep_lint(tmp_path, {"mod.py": """
        import threading


        class Re:
            def __init__(self):
                self._lock = threading.Lock()
                self._rlock = threading.RLock()

            def bad(self):
                with self._lock:
                    with self._lock:
                        pass

            def fine(self):
                with self._rlock:
                    with self._rlock:
                        pass
    """}, [LockOrderInversion])
    assert [f.code for f in findings] == ["JX202"]
    assert "re-acquisition" in findings[0].message


def test_jx203_blocking_call_under_lock(tmp_path):
    findings = deep_lint(tmp_path, {"mod.py": """
        import threading
        import time


        class Busy:
            def __init__(self):
                self._lock = threading.Lock()
                self._cond = threading.Condition()

            def slow(self, engine):
                with self._lock:
                    engine.poll()
                    time.sleep(0.1)

            def idiom(self):
                with self._cond:
                    self._cond.wait(0.1)

            def strings(self, parts):
                with self._lock:
                    return "; ".join(parts)
    """}, [BlockingCallUnderLock])
    codes = [f.code for f in findings]
    assert codes == ["JX203", "JX203"]
    labels = " ".join(f.message for f in findings)
    assert ".poll()" in labels and "time.sleep" in labels
    # waiting the held condition and str.join are NOT blocking


def test_jx204_requires_lock_checked_at_call_sites(tmp_path):
    findings = deep_lint(tmp_path, {"mod.py": """
        import threading


        class Svc:
            def __init__(self):
                self._lock = threading.Lock()

            def helper(self):  # requires-lock: _lock
                pass

            def good(self):
                with self._lock:
                    self.helper()

            def bad(self):
                self.helper()
    """}, [RequiresLockViolation])
    assert [f.code for f in findings] == ["JX204"]
    assert "helper" in findings[0].message


def test_jx205_unknown_lock_annotation(tmp_path):
    findings = deep_lint(tmp_path, {"mod.py": """
        import threading


        class Svc:
            def __init__(self):
                self._lock = threading.Lock()
                self._q = []   # guarded-by: _nope
    """}, [UnknownLockAnnotation])
    assert [f.code for f in findings] == ["JX205"]
    assert "_nope" in findings[0].message


# -- SARIF envelope ---------------------------------------------------

def test_sarif_envelope_from_findings(tmp_path):
    findings = deep_lint(tmp_path, {"service.py": SERVICE},
                         [UnguardedAttribute])
    from brainiak_tpu.analysis.lockrules import LOCK_RULES
    log = to_sarif(findings, {r.code: r for r in LOCK_RULES})
    blob = json.dumps(log)   # must be JSON-serializable
    assert json.loads(blob) == log
    assert log["version"] == "2.1.0"
    assert log["$schema"].endswith("sarif-schema-2.1.0.json")
    run = log["runs"][0]
    driver = run["tool"]["driver"]
    assert driver["name"] == "jaxlint"
    rule_ids = [r["id"] for r in driver["rules"]]
    assert "JX201" in rule_ids
    by_id = {r["id"]: r for r in driver["rules"]}
    assert by_id["JX201"]["shortDescription"]["text"]
    result = run["results"][0]
    assert result["ruleId"] == "JX201"
    assert result["level"] == "warning"
    assert result["message"]["text"]
    loc = result["locations"][0]["physicalLocation"]
    assert loc["artifactLocation"]["uri"] == "service.py"
    assert loc["region"]["startLine"] == findings[0].line


def test_sarif_cli_output(tmp_path, monkeypatch, capsys):
    from brainiak_tpu.analysis import cli
    pkg = tmp_path / "pkg"
    pkg.mkdir()
    (pkg / "bad.py").write_text(
        "import jax\n\n\ndef make(fn):\n    return jax.jit(fn)\n")
    (tmp_path / "pyproject.toml").write_text(
        '[tool.jaxlint]\nselect = ["JX001"]\ninclude = ["pkg"]\n')
    monkeypatch.chdir(tmp_path)
    assert cli.main(["--format", "sarif"]) == 1
    log = json.loads(capsys.readouterr().out)
    assert log["version"] == "2.1.0"
    results = log["runs"][0]["results"]
    assert [r["ruleId"] for r in results] == ["JX001"]
    uri = results[0]["locations"][0]["physicalLocation"][
        "artifactLocation"]["uri"]
    assert uri == "pkg/bad.py"
